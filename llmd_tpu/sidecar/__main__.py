"""CLI: python -m llmd_tpu.sidecar --port 8000 --vllm-port 8200 ...

Flag names mirror the reference sidecar's
(guides/recipes/modelserver/base/single-host/pd/vllm/patch-sidecar.yaml:9-16;
wide-ep-lws/modelserver/gpu/vllm/base/decode.yaml:29-39).
"""

from __future__ import annotations

import argparse
import asyncio
import logging

from llmd_tpu.sidecar.proxy import SidecarConfig, run_sidecar


def main() -> None:
    ap = argparse.ArgumentParser("llmd-tpu routing sidecar")
    ap.add_argument("--port", type=int, default=8000)
    ap.add_argument("--vllm-port", type=int, default=8200)
    ap.add_argument("--data-parallel-size", type=int, default=1)
    ap.add_argument(
        "--kv-connector", default="tpu",
        help="transfer protocol family: tpu/nixlv2 (two-phase kvship "
        "pull) or sglang (concurrent bootstrap rendezvous)",
    )
    ap.add_argument("--sglang-bootstrap-port", type=int, default=8998)
    ap.add_argument("--prefill-timeout", type=float, default=600.0)
    ap.add_argument("-v", "--verbose", action="store_true")
    ap.add_argument("--otlp-traces-endpoint", default=None)
    ap.add_argument("--trace-file", default=None)
    ap.add_argument("--trace-sample-ratio", type=float, default=0.1)
    args = ap.parse_args()

    if args.otlp_traces_endpoint or args.trace_file:
        from llmd_tpu.obs.tracing import configure_tracing

        configure_tracing(
            "llmd-sidecar",
            otlp_endpoint=args.otlp_traces_endpoint,
            trace_file=args.trace_file,
            sample_ratio=args.trace_sample_ratio,
        )

    logging.basicConfig(
        level=logging.DEBUG if args.verbose else logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s",
    )
    cfg = SidecarConfig(
        port=args.port,
        vllm_port=args.vllm_port,
        data_parallel_size=args.data_parallel_size,
        connector=args.kv_connector,
        sglang_bootstrap_port=args.sglang_bootstrap_port,
        prefill_timeout_s=args.prefill_timeout,
    )
    asyncio.run(run_sidecar(cfg))


if __name__ == "__main__":
    main()
