"""Routing sidecar: per-decode-pod proxy orchestrating the P/D two-phase flow.

Re-implements the reference's llm-d-router-disagg-sidecar behavior
(docs/architecture/advanced/disaggregation/README.md:104-131) for the
TPU-native stack.
"""

from llmd_tpu.sidecar.proxy import SidecarConfig, build_sidecar_app  # noqa: F401
