"""The P/D routing sidecar proxy.

Reference behavior (disaggregation/README.md:104-131; deployment shape
guides/recipes/modelserver/base/single-host/pd/vllm/patch-sidecar.yaml):
an init-container proxy on the decode pod's serving port. For each generate
request carrying the ``x-prefiller-host-port`` header it runs the two-phase
protocol:

  1. send the request to the prefiller with ``max_tokens=1``, stream off and
     ``kv_transfer_params: {"do_remote_decode": true}`` (the vLLM `nixlv2`
     protocol shape, README.md:33-46);
  2. capture ``kv_transfer_params`` from the prefill response and inject
     them into the original request;
  3. forward to the local engine; the consumer connector pulls the KV.

A prefill server error falls back to decoder-only execution on the local
engine (README.md:113-118). While the decode request is queued, the sidecar
heartbeats the producer lease (renew at 2/3 lease, operations-vllm.md:
155-160) so slow admission can't expire the transfer.

DP-awareness (wide-ep decode.yaml:29-39): with ``--data-parallel-size=N``
the sidecar listens on ``[port, port+N)`` and forwards rank ``i`` to local
engine port ``vllm_port + i``.
"""

from __future__ import annotations

import asyncio
import dataclasses
import json
import logging

import aiohttp
from aiohttp import web

from llmd_tpu import faults
from llmd_tpu.epp.types import HDR_EC_HOST, HDR_ENCODER, HDR_PREFILLER
from llmd_tpu.kvtransfer import shipper as shipper_mod
from llmd_tpu.obs.tracing import get_tracer

log = logging.getLogger(__name__)

GENERATE_PATHS = {"/v1/completions", "/v1/chat/completions"}

HOP_HEADERS = {
    "connection", "keep-alive", "transfer-encoding", "te", "upgrade",
    "proxy-authorization", "proxy-authenticate", "host", "content-length",
}


@dataclasses.dataclass
class SidecarConfig:
    port: int = 8000  # first listen port
    vllm_port: int = 8200  # first local engine port
    data_parallel_size: int = 1
    # Transfer protocol family the local model server speaks (reference
    # --kv-connector=nixlv2|sglang, wide-ep decode.yaml:29-39):
    #   "tpu" / "nixlv2": two-phase sequential — prefill with
    #     max_tokens=1, capture kv_transfer_params, inject into decode.
    #   "sglang": concurrent — inject bootstrap_host/port/room into BOTH
    #     requests, fire prefill asynchronously (never cancelled), send
    #     decode immediately; engines rendezvous out-of-band via the
    #     bootstrap room (disaggregation/README.md:104-131).
    connector: str = "tpu"
    sglang_bootstrap_port: int = 8998
    prefill_timeout_s: float = 600.0
    # lease renewal cadence; 2/3 of the reference's 30s default lease
    heartbeat_s: float = 10.0
    # P/D byte diet: probe the local decode engine's prefix cache before
    # phase 1 and tell the prefiller to skip staging the cached pages
    # (the reference decider's "how much of the prompt is cached on D?",
    # scheduling.md:113). Probe failure degrades to a full transfer.
    probe_prefix_cache: bool = True
    probe_timeout_s: float = 2.0


def _fwd_headers(headers) -> dict[str, str]:
    return {
        k: v for k, v in headers.items()
        if k.lower() not in HOP_HEADERS
        and k.lower() not in (HDR_PREFILLER, HDR_ENCODER, HDR_EC_HOST)
    }


def _strip_client_ec_parts(body: dict) -> None:
    """Drop client-supplied ec_embedding parts before phase 0.

    Only the sidecar may mint EC handles (it also vouches for their host
    via the x-llm-d-ec-host header); a client-forged part would otherwise
    make the engine issue a server-side GET to an attacker-chosen host."""
    for m in body.get("messages") or []:
        content = m.get("content") if isinstance(m, dict) else None
        if not isinstance(content, list):
            continue
        content[:] = [
            p for p in content
            if not (isinstance(p, dict) and p.get("type") == "ec_embedding")
        ]


class _LeaseHeartbeat:
    """Renews the producer-side lease until the decode request lands."""

    def __init__(self, params: dict, cadence_s: float) -> None:
        self.params = params
        self.cadence_s = cadence_s
        self._task: asyncio.Task | None = None

    async def _run(self) -> None:
        from llmd_tpu.kvtransfer.connector import transfer_keys

        host = self.params.get("remote_host")
        port = int(self.params.get("remote_port", 0))
        keys = transfer_keys(self.params)
        loop = asyncio.get_running_loop()
        while True:
            await asyncio.sleep(self.cadence_s)

            def renew_all() -> bool:
                # Chunked exports: every chunk key carries its own lease,
                # so EVERY key must be renewed each cycle (a list, not a
                # short-circuiting generator). Any still-alive entry keeps
                # the heartbeat going — a chunk may be registered only
                # after the first renew cycle.
                results = [shipper_mod.renew(host, port, k) for k in keys]
                return any(results)

            ok = await loop.run_in_executor(None, renew_all)
            if not ok:
                return  # entries gone (pulled+freed, or producer restarted)

    def start(self) -> None:
        if self.params.get("remote_host"):
            self._task = asyncio.get_running_loop().create_task(self._run())

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None


def build_sidecar_app(cfg: SidecarConfig, rank: int = 0) -> web.Application:
    """One sidecar app instance (one per DP rank listen port)."""

    local_base = f"http://127.0.0.1:{cfg.vllm_port + rank}"

    async def on_startup(app: web.Application) -> None:
        app["session"] = aiohttp.ClientSession(
            timeout=aiohttp.ClientTimeout(total=None, sock_connect=30)
        )

    async def on_cleanup(app: web.Application) -> None:
        # Detached sglang prefills must not die with 'Session is closed':
        # give in-flight ones a short grace, cancel stragglers, THEN
        # close the shared session.
        tasks = list(app["sglang_tasks"])
        if tasks:
            done, pending = await asyncio.wait(tasks, timeout=2.0)
            for t in pending:
                t.cancel()
            await asyncio.gather(*pending, return_exceptions=True)
        await app["session"].close()

    async def handle(request: web.Request) -> web.StreamResponse:
        session: aiohttp.ClientSession = request.app["session"]
        # The sidecar is the pod's outward-facing port; the engine's admin
        # surface (pause/drain/resume) must only be reachable by in-pod
        # peers (IRO, operator exec) that talk to the engine port directly.
        if request.path.startswith("/admin"):
            return web.json_response(
                {"error": {"message": "admin surface is not proxied",
                           "type": "forbidden"}},
                status=403,
            )
        prefiller = request.headers.get(HDR_PREFILLER)
        encoder = request.headers.get(HDR_ENCODER)
        if (
            request.method == "POST"
            and request.path in GENERATE_PATHS
            and (prefiller or encoder)
        ):
            try:
                body = json.loads(await request.read())
            except (json.JSONDecodeError, UnicodeDecodeError):
                return web.json_response(
                    {"error": {"message": "invalid JSON body",
                               "type": "invalid_request_error"}},
                    status=400,
                )
            if isinstance(body, dict):
                _strip_client_ec_parts(body)
            if encoder and isinstance(body, dict):
                body = await run_encode(session, encoder, body, request)
            if prefiller:
                if cfg.connector == "sglang":
                    return await sglang_concurrent(
                        request, session, prefiller, body
                    )
                return await two_phase(request, session, prefiller, body)
            # E-only (E/PD topology without a separate prefiller): forward
            # the embedding-substituted body to the local engine.
            headers = _fwd_headers(request.headers)
            if request.get("ec_host"):
                headers[HDR_EC_HOST] = request["ec_host"]
            async with session.post(
                local_base + request.path_qs,
                headers=headers,
                json=body,
            ) as upstream:
                return await _relay(request, upstream)
        return await passthrough(request, session)

    async def run_encode(
        session: aiohttp.ClientSession,
        encoder: str,
        body: dict,
        request: web.Request,
    ) -> dict:
        """Phase 0 (E tier): ship inline images to the encode worker and
        substitute EC embedding handles (multimodal-serving/README.md:41-46
        steps 2-4). Failure falls back to local processing: the original
        image parts are forwarded untouched."""
        images: list[dict] = []
        parts: list[dict] = []
        for m in body.get("messages") or []:
            content = m.get("content") if isinstance(m, dict) else None
            if not isinstance(content, list):
                continue
            for part in content:
                if isinstance(part, dict) and (
                    part.get("type") == "image_url" or "image_url" in part
                ):
                    url = part.get("image_url")
                    url = url.get("url", "") if isinstance(url, dict) else str(url)
                    # Encode workers only accept inline payloads; leave
                    # remote URLs for the engine so one of them cannot
                    # 400 the whole batch.
                    if not url.startswith("data:"):
                        continue
                    images.append({"url": url})
                    parts.append(part)
        if not images:
            return body
        span = get_tracer().start_span(
            "sidecar.encode",
            traceparent=request.headers.get("traceparent"),
        )
        span.set("llm_d.encode.worker", encoder)
        span.set("llm_d.encode.num_images", len(images))
        try:
            async with session.post(
                f"http://{encoder}/v1/encode", json={"images": images},
                timeout=aiohttp.ClientTimeout(total=cfg.prefill_timeout_s),
            ) as resp:
                if resp.status != 200:
                    log.warning(
                        "encode worker %s returned %d -- local fallback",
                        encoder, resp.status,
                    )
                    span.error(f"encode status {resp.status}")
                    return body
                items = (await resp.json()).get("items", [])
        except (aiohttp.ClientError, asyncio.TimeoutError) as e:
            log.warning("encode worker %s unreachable (%s) -- local fallback",
                        encoder, e)
            span.error(str(e))
            return body
        finally:
            span.end()
        if len(items) != len(parts):
            log.warning("encode worker returned %d items for %d images",
                        len(items), len(parts))
            return body
        for part, item in zip(parts, items):
            part.clear()
            part["type"] = "ec_embedding"
            part["ec_embedding"] = {"host": encoder, **item}
        # Vouch for the injected host: the engine only pulls EC handles
        # whose host matches the sidecar-set x-llm-d-ec-host header.
        request["ec_host"] = encoder
        return body

    async def passthrough(
        request: web.Request, session: aiohttp.ClientSession
    ) -> web.StreamResponse:
        body = await request.read()
        async with session.request(
            request.method,
            local_base + request.path_qs,
            headers=_fwd_headers(request.headers),
            data=body if body else None,
        ) as upstream:
            return await _relay(request, upstream)

    async def two_phase(
        request: web.Request,
        session: aiohttp.ClientSession,
        prefiller: str,
        body: dict,
    ) -> web.StreamResponse:
        # P/D decision intelligence spans (reference
        # proposals/distributed-tracing.md): one child span per phase so a
        # trace shows prefill time vs KV-pull+decode time per request.
        tracer = get_tracer()
        root = tracer.start_span(
            "sidecar.two_phase",
            traceparent=request.headers.get("traceparent"),
            kind="SPAN_KIND_SERVER",
        )
        root.set("llm_d.prefiller", prefiller)
        heartbeat = None
        dec_span = None
        try:
            skip_pages = 0
            if cfg.probe_prefix_cache:
                skip_pages = await probe_cached_pages(session, body)
                root.set("llm_d.decision.skip_pages", skip_pages)
            pre_span = tracer.start_span("sidecar.prefill", parent=root)
            try:
                params = await run_prefill(
                    session, prefiller, request.path, body,
                    ec_host=request.get("ec_host"),
                    skip_pages=skip_pages,
                )
                pre_span.set("llm_d.prefill.remote", params is not None)
            except BaseException as e:
                pre_span.error(str(e) or type(e).__name__)
                raise
            finally:
                pre_span.end()
            root.set("llm_d.decision.fallback_decoder_only", params is None)
            heartbeat = _LeaseHeartbeat(params or {}, cfg.heartbeat_s)
            if params is not None:
                body = dict(body)
                body["kv_transfer_params"] = params
                heartbeat.start()
            dec_span = tracer.start_span("sidecar.decode", parent=root)
            headers = _fwd_headers(request.headers)
            if request.get("ec_host"):
                headers[HDR_EC_HOST] = request["ec_host"]
            if dec_span.sampled:
                headers["traceparent"] = dec_span.traceparent
            async with session.post(
                local_base + request.path_qs,
                headers=headers,
                json=body,
            ) as upstream:
                heartbeat.stop()  # decode accepted; consumer owns the pull
                dec_span.set("http.status_code", upstream.status)
                return await _relay(request, upstream)
        except BaseException as e:
            root.error(str(e) or type(e).__name__)
            raise
        finally:
            if heartbeat is not None:
                heartbeat.stop()
            if dec_span is not None:
                dec_span.end()
            root.end()

    async def probe_cached_pages(
        session: aiohttp.ClientSession, body: dict
    ) -> int:
        """Byte-diet phase 0: ask the LOCAL decode engine how many leading
        full pages of this prompt it already caches; 0 on any failure
        (full transfer, never an error)."""
        try:
            async with session.post(
                local_base + "/v1/cache/probe", json=body,
                timeout=aiohttp.ClientTimeout(total=cfg.probe_timeout_s),
            ) as resp:
                if resp.status != 200:
                    return 0
                data = await resp.json()
                return max(int(data.get("cached_full_pages", 0) or 0), 0)
        except (aiohttp.ClientError, asyncio.TimeoutError, ValueError):
            return 0

    async def sglang_concurrent(
        request: web.Request,
        session: aiohttp.ClientSession,
        prefiller: str,
        body: dict,
    ) -> web.StreamResponse:
        """SGLang-protocol disaggregation: inject identical
        bootstrap_host/port/room into BOTH requests, fire the prefill
        asynchronously (detached — the reference runs it in a goroutine
        under context.WithoutCancel so a fast decode can't cancel it),
        and forward the decode immediately. The engines coordinate the
        KV transfer out-of-band via the bootstrap room
        (disaggregation/README.md:104-131)."""
        import random

        tracer = get_tracer()
        root = tracer.start_span(
            "sidecar.sglang_disagg",
            traceparent=request.headers.get("traceparent"),
            kind="SPAN_KIND_SERVER",
        )
        root.set("llm_d.prefiller", prefiller)
        boot = {
            "bootstrap_host": prefiller.rsplit(":", 1)[0],
            "bootstrap_port": cfg.sglang_bootstrap_port,
            "bootstrap_room": random.getrandbits(63),
        }
        root.set("llm_d.sglang.bootstrap_room", boot["bootstrap_room"])
        pre_body = dict(body)
        pre_body.update(boot)
        pre_body["stream"] = False
        dec_body = dict(body)
        dec_body.update(boot)

        # Encoder vouching survives the sglang path too: both legs carry
        # the sidecar-set x-llm-d-ec-host header (the engine only pulls
        # EC handles whose host matches it).
        ec_headers = (
            {HDR_EC_HOST: request["ec_host"]} if request.get("ec_host") else {}
        )

        async def fire_prefill() -> None:
            try:
                async with session.post(
                    f"http://{prefiller}{request.path}", json=pre_body,
                    headers=ec_headers or None,
                    timeout=aiohttp.ClientTimeout(total=cfg.prefill_timeout_s),
                ) as resp:
                    await resp.read()
                    if resp.status != 200:
                        log.warning(
                            "sglang prefill at %s returned %d",
                            prefiller, resp.status,
                        )
            except (aiohttp.ClientError, asyncio.TimeoutError,
                    RuntimeError) as e:
                # RuntimeError: session closed mid-flight at shutdown.
                log.warning("sglang prefill at %s failed: %s", prefiller, e)

        # Detached: deliberately not awaited before the decode leg.
        prefill_task = asyncio.get_running_loop().create_task(fire_prefill())
        # Keep a reference so the task isn't garbage-collected mid-flight
        # (set pre-created at app build — frozen apps refuse mutation).
        request.app["sglang_tasks"].add(prefill_task)
        prefill_task.add_done_callback(
            request.app["sglang_tasks"].discard
        )
        try:
            headers = _fwd_headers(request.headers)
            headers.update(ec_headers)
            async with session.post(
                local_base + request.path_qs, headers=headers, json=dec_body,
            ) as upstream:
                root.set("http.status_code", upstream.status)
                return await _relay(request, upstream)
        except BaseException as e:
            root.error(str(e) or type(e).__name__)
            raise
        finally:
            root.end()

    async def run_prefill(
        session: aiohttp.ClientSession, prefiller: str, path: str, body: dict,
        ec_host: str | None = None,
        skip_pages: int = 0,
    ) -> dict | None:
        """Phase 1. Returns kv_transfer_params, or None => decoder-only."""
        pre_body = dict(body)
        pre_body["max_tokens"] = 1
        pre_body.pop("max_completion_tokens", None)
        pre_body["stream"] = False
        pre_body["kv_transfer_params"] = {
            "do_remote_decode": True, "skip_pages": skip_pages,
        }
        url = f"http://{prefiller}{path}"
        headers = {HDR_EC_HOST: ec_host} if ec_host else None
        try:
            # Injection site: an unreachable prefiller degrades to the
            # decoder-only fallback below — same as production.
            if faults.fires("sidecar.prefill.fail", prefiller):
                raise aiohttp.ClientConnectionError(
                    f"injected sidecar.prefill.fail for {prefiller}"
                )
            async with session.post(
                url, json=pre_body, headers=headers,
                timeout=aiohttp.ClientTimeout(total=cfg.prefill_timeout_s),
            ) as resp:
                if resp.status != 200:
                    text = await resp.text()
                    log.warning(
                        "prefill at %s failed (%d): %.200s -- decoder-only fallback",
                        prefiller, resp.status, text,
                    )
                    return None
                payload = await resp.json()
        except (aiohttp.ClientError, asyncio.TimeoutError) as e:
            log.warning(
                "prefill at %s unreachable (%s) -- decoder-only fallback",
                prefiller, e,
            )
            return None
        params = payload.get("kv_transfer_params")
        if not params:
            log.warning(
                "prefill at %s returned no kv_transfer_params -- decoder-only",
                prefiller,
            )
        return params or None

    async def _relay(
        request: web.Request, upstream: aiohttp.ClientResponse
    ) -> web.StreamResponse:
        resp = web.StreamResponse(status=upstream.status)
        for k, v in upstream.headers.items():
            if k.lower() not in HOP_HEADERS:
                resp.headers[k] = v
        await resp.prepare(request)
        async for chunk in upstream.content.iter_any():
            await resp.write(chunk)
        await resp.write_eof()
        return resp

    app = web.Application(client_max_size=64 * 1024 * 1024)
    app["sglang_tasks"] = set()  # live detached prefill tasks (sglang mode)
    app.on_startup.append(on_startup)
    app.on_cleanup.append(on_cleanup)
    app.router.add_route("*", "/{tail:.*}", handle)
    return app


async def run_sidecar(cfg: SidecarConfig) -> None:
    """Serve all DP-rank listeners ([port, port+dp_size))."""
    runners = []
    for rank in range(cfg.data_parallel_size):
        app = build_sidecar_app(cfg, rank)
        runner = web.AppRunner(app)
        await runner.setup()
        site = web.TCPSite(runner, "0.0.0.0", cfg.port + rank)
        await site.start()
        runners.append(runner)
        log.info(
            "sidecar rank %d: :%d -> 127.0.0.1:%d",
            rank, cfg.port + rank, cfg.vllm_port + rank,
        )
    try:
        await asyncio.Event().wait()
    finally:
        for r in runners:
            await r.cleanup()
