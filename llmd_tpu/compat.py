"""Version-compatibility shims for the installed JAX.

The repo targets current JAX surface names; installs that predate a
rename still work because every internal importer routes through this
module (one place to delete when the floor version moves):

- ``shard_map`` graduated from ``jax.experimental.shard_map`` to a
  top-level ``jax.shard_map`` export, renaming ``check_rep`` ->
  ``check_vma`` on the way.
- Pallas-TPU ``TPUCompilerParams`` was renamed ``CompilerParams``.
"""

from __future__ import annotations

import functools

try:
    from jax import shard_map  # noqa: F401  (jax >= 0.6 top-level export)
except ImportError:  # pragma: no cover - exercised on older installs
    from jax.experimental.shard_map import shard_map as _shard_map

    @functools.wraps(_shard_map)
    def shard_map(f, *args, **kwargs):
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        return _shard_map(f, *args, **kwargs)


def pallas_tpu_compiler_params(**kwargs):
    """``pltpu.CompilerParams`` with fallback to the pre-rename
    ``TPUCompilerParams`` (identical fields)."""
    from jax.experimental.pallas import tpu as pltpu

    cls = getattr(pltpu, "CompilerParams", None)
    if cls is None:  # pragma: no cover - exercised on older installs
        cls = pltpu.TPUCompilerParams
    return cls(**kwargs)


__all__ = ["shard_map", "pallas_tpu_compiler_params"]
