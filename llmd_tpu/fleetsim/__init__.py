"""Fleet-scale chaos soak: a trace-driven closed-loop simulator that
drives the REAL routing/control stack (EPP filter/score/pick, flow
control, breaker, latency predictor, WVA autoscaler) through seeded
failures on a virtual-time event loop, and gates fleet-level recovery
invariants in CI. See docs/architecture/fleet-soak.md.

Entry points:

- ``python -m llmd_tpu.fleetsim --scenario replica_kill --out sb.json``
- :func:`llmd_tpu.fleetsim.scenarios.SCENARIOS` — the seeded matrix
- :class:`llmd_tpu.fleetsim.sim.FleetSim` — ad-hoc simulations
"""

from llmd_tpu.fleetsim.engines import ReplicaProfile, SimReplica  # noqa: F401
from llmd_tpu.fleetsim.sim import AutoscaleConfig, FleetConfig, FleetSim  # noqa: F401
from llmd_tpu.fleetsim.simloop import SimDeadlockError, SimEventLoop, run  # noqa: F401
from llmd_tpu.fleetsim.traces import TraceRequest, generate, load_jsonl, save_jsonl  # noqa: F401
