"""Seeded trace generation + JSONL replay for the fleet soak.

A trace is a list of :class:`TraceRequest` arrivals on the simulator's
virtual time axis (seconds from scenario start). Two sources:

- :func:`generate` — a seeded inhomogeneous-Poisson generator with
  three production-shaped rate curves (``steady``, ``burst``,
  ``diurnal``) and a weighted multi-tenant mix. The same (kind, qps,
  duration, seed, …) arguments always produce the identical trace —
  the first link in the byte-identical-scoreboard chain.
- :func:`load_jsonl` / :func:`save_jsonl` — the replay format: one JSON
  object per line, fields exactly the :class:`TraceRequest` fields with
  ``null`` for an absent SLO. Captured production traces (or hand-built
  regression traces) replay through the same pipeline as generated
  ones.

JSONL line schema (documented in docs/architecture/fleet-soak.md)::

    {"t": 0.0132, "request_id": "r000001", "tenant": "tenant-0",
     "prompt_tokens": 128, "output_tokens": 8, "priority": 0,
     "ttft_slo_ms": null, "prefix_group": "g001", "prefix_tokens": 128}

``prefix_group``/``prefix_tokens`` (optional, defaulting to no shared
prefix) mark the shared-prefix identity the kv_federation scenario
publishes and fetches through the simulated store; traces predating
the fields replay unchanged.
"""

from __future__ import annotations

import dataclasses
import json
import math
import pathlib
import random
from typing import Iterable, Sequence


@dataclasses.dataclass(frozen=True)
class TraceRequest:
    """One arrival in the replayed fleet workload."""

    t: float  # arrival, seconds of sim time from scenario start
    request_id: str
    tenant: str = "tenant-0"
    prompt_tokens: int = 128
    output_tokens: int = 8
    priority: int = 0
    ttft_slo_ms: float | None = None
    # Shared-prefix identity for the KV-federation scenario
    # (kv-federation.md): requests carrying the same group share their
    # first ``prefix_tokens`` tokens — the unit the simulated store
    # publishes and fetches. None = a fully unique prompt.
    prefix_group: str | None = None
    prefix_tokens: int = 0
    # Tenant adapter identity for the multi-LoRA scenario
    # (multi-tenant-lora.md): the LoRA adapter this request serves
    # under — the unit the replicas' paged adapter pools make resident
    # and the lora-affinity scorer routes on. None = base model.
    adapter: str | None = None
    # Dominant routed expert for the wide-EP MoE scenario
    # (docs/architecture/wide-ep.md): the logical expert this request's
    # decode tokens predominantly route to — the per-request stand-in
    # for the engine's per-token top-k draw, and the load the EPLB
    # placement balances across EP shards. None = dense / no MoE axis.
    expert: int | None = None

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


def save_jsonl(path: str | pathlib.Path, reqs: Iterable[TraceRequest]) -> None:
    with open(path, "w") as f:
        for r in reqs:
            f.write(json.dumps(r.to_dict(), sort_keys=True) + "\n")


def load_jsonl(path: str | pathlib.Path) -> list[TraceRequest]:
    out: list[TraceRequest] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            d = json.loads(line)
            out.append(TraceRequest(**d))
    out.sort(key=lambda r: r.t)
    return out


# ---- rate curves ------------------------------------------------------ #


def _rate(kind: str, qps: float, t: float, duration_s: float,
          burst_factor: float, diurnal_floor: float) -> float:
    if kind == "steady":
        return qps
    if kind == "burst":
        # A burst_factor x spike over the middle fifth of the window:
        # flow control must absorb the spike, fairness must hold inside it.
        lo, hi = 0.4 * duration_s, 0.6 * duration_s
        return qps * burst_factor if lo <= t < hi else qps
    if kind == "diurnal":
        # One full day-shaped cycle across the window, troughing near
        # the floor (scale-to-zero territory) and peaking at qps.
        phase = 0.5 * (1.0 - math.cos(2.0 * math.pi * t / duration_s))
        return qps * (diurnal_floor + (1.0 - diurnal_floor) * phase)
    raise ValueError(f"unknown trace kind {kind!r} (steady|burst|diurnal)")


def generate(
    kind: str = "steady",
    qps: float = 1000.0,
    duration_s: float = 2.0,
    seed: int = 0,
    tenants: Sequence[tuple[str, float]] = (("tenant-0", 1.0),),
    prompt_tokens: int = 128,
    output_tokens: int = 8,
    token_jitter: float = 0.25,
    burst_factor: float = 5.0,
    diurnal_floor: float = 0.02,
    ttft_slo_ms: float | None = None,
    prefix_groups: int = 0,
    prefix_frac: float = 0.5,
    adapters: int = 0,
    experts: int = 0,
) -> list[TraceRequest]:
    """Seeded inhomogeneous-Poisson arrivals with a weighted tenant mix.

    Arrivals are drawn by thinning: candidates at the curve's peak rate,
    each kept with probability ``rate(t)/peak`` — exact for an
    inhomogeneous Poisson process and correct through zero-rate troughs
    (a gap-sampler at the local rate would jump clean over them).
    Per-request token counts jitter uniformly within ``±token_jitter``
    of the means, so the fleet sees realistically ragged work, not a
    metronome.

    ``prefix_groups > 0`` gives every request a shared-prefix identity
    drawn Zipf-ish from that many groups (group k at weight 1/(k+1) —
    a few hot system prompts, a long warm tail), INDEPENDENT of the
    tenant draw, so the same prefix recurs across tenants — the
    overlapping-tenant workload whose fleet-wide recompute the KV
    federation exists to erase. ``prefix_frac`` of each prompt is the
    shared prefix.

    ``adapters > 0`` is the multi-tenant LoRA axis
    (multi-tenant-lora.md): each request serves under adapter ``k``
    drawn Zipf-ish (weight 1/(k+1) — a few hot tenants, a long warm
    tail) from that many tenant adapters, and the TENANT becomes the
    adapter's owner (``tenant-<k>``) — hundreds of tenants, one
    adapter each, exactly the fleet shape whose residency the paged
    adapter pool and the lora-affinity scorer manage. The ``tenants``
    mix is ignored in this mode.

    ``experts > 0`` is the wide-EP MoE axis (wide-ep.md): each request
    gets a dominant routed expert drawn Zipf-ish (weight 1/(k+1)) from
    that many logical experts — the skewed expert-popularity curve
    production routers actually see, under which a static contiguous
    expert layout piles the hot experts onto one EP shard while the
    EPLB placement spreads them. Independent of the tenant draw.
    """
    rng = random.Random(seed)
    names = [t for t, _ in tenants]
    weights = [w for _, w in tenants]
    peak = qps * (burst_factor if kind == "burst" else 1.0)
    out: list[TraceRequest] = []
    t = 0.0
    i = 0
    while True:
        t += rng.expovariate(max(peak, 1e-6))
        if t >= duration_s:
            break
        rate = _rate(kind, qps, t, duration_s, burst_factor, diurnal_floor)
        if rng.random() >= rate / peak:
            continue
        jit = 1.0 + token_jitter * (2.0 * rng.random() - 1.0)
        n_prompt = max(1, round(prompt_tokens * jit))
        group, n_prefix = None, 0
        if prefix_groups > 0:
            k = rng.choices(
                range(prefix_groups),
                weights=[1.0 / (j + 1) for j in range(prefix_groups)],
            )[0]
            group = f"g{k:03d}"
            n_prefix = min(n_prompt, max(1, round(prompt_tokens * prefix_frac)))
        adapter, tenant = None, None
        if adapters > 0:
            k = rng.choices(
                range(adapters),
                weights=[1.0 / (j + 1) for j in range(adapters)],
            )[0]
            adapter = f"a{k:03d}"
            tenant = f"tenant-{k:03d}"
        expert = None
        if experts > 0:
            expert = rng.choices(
                range(experts),
                weights=[1.0 / (j + 1) for j in range(experts)],
            )[0]
        out.append(TraceRequest(
            t=t,
            request_id=f"r{i:06d}",
            tenant=tenant or rng.choices(names, weights=weights, k=1)[0],
            prompt_tokens=n_prompt,
            output_tokens=max(1, round(output_tokens * jit)),
            ttft_slo_ms=ttft_slo_ms,
            prefix_group=group,
            prefix_tokens=n_prefix,
            adapter=adapter,
            expert=expert,
        ))
        i += 1
    return out
