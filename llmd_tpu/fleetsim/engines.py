"""Memory-speed engine-replica stubs parameterized from captured bench numbers.

A :class:`SimReplica` is the serving-side half of the fleet simulator:
it models one engine replica's queueing + continuous-batching dynamics
(waiting queue, batch slots, KV occupancy, load-dependent TPOT) with a
handful of floats, serves requests as virtual-time sleeps, and renders
a real Prometheus ``/metrics`` page so the EPP's production
``MetricsCollector``/``extract_attrs`` path scrapes it like any engine.

The service model, deliberately simple and fully deterministic:

- admission: FIFO wait for one of ``max_batch`` batch slots (the
  waiting count is what the queue-scorer and flow-control saturation
  see via scrape);
- prefill: ``prompt_tokens / prefill_tok_s`` seconds to first token
  (plus any armed ``replica.brownout`` delay, plus a recompute penalty
  when a ``kv.pull.drop`` fault fires — the production degradation
  contract for a dropped KV pull is local recompute, slower but
  correct);
- decode: per-token time is ``max(base_tpot, running / decode_tok_s)``
  — at saturation the batch shares the replica's aggregate decode
  throughput, under light load the single-sequence TPOT floor holds.

Failure surface (consulted through PR 7's seeded FaultPlan):

- ``replica.crash`` (fleet scope, fired by the simulator's chaos
  ticker): :meth:`SimReplica.kill` fails every in-flight wait with
  :class:`ReplicaDied` — mid-prefill requests look like a connection
  reset before first byte (retryable), mid-decode requests like a cut
  stream, which the driver RESUMES on a fresh replica with the
  delivered token history (the stream-continuation protocol,
  fault-tolerance.md) — exactly the split the router's retry/resume
  loop handles;
- ``replica.brownout``: per-request extra latency (``delay_ms``);
- new connections to a dead or draining replica raise
  :class:`ReplicaUnreachable` (the simulator's connection-refused).

Parameterization ties the stub to measured reality:
:meth:`ReplicaProfile.from_bench` reads a captured ``BENCH_r0N.json``
headline (output tok/s/chip — 4,914 in r4) for the decode rate and
scales by a chip count; prefill throughput defaults to 4x the decode
rate (prefill is the compute-bound, well-batched phase — an estimate,
labeled as such; override per scenario when a captured prefill figure
exists).
"""

from __future__ import annotations

import collections
import dataclasses
import json
import pathlib
import zlib

import asyncio

from llmd_tpu import faults


def stream_token(request_id: str, index: int) -> int:
    """The deterministic token at output position ``index`` of request
    ``request_id``: replicas are position-addressable generators (the
    sim's stand-in for the engine's per-(seed, output-index) PRNG
    derivation), so a resumed stream is byte-identical to an
    uninterrupted one EXACTLY when the continuation starts at the right
    position — the stitched-stream parity gate checks real content, not
    bookkeeping."""
    return zlib.crc32(f"{request_id}:{index}".encode()) & 0xFFFF


def expected_stream(request_id: str, output_tokens: int) -> list[int]:
    """The uninterrupted baseline a stitched client stream must equal."""
    return [stream_token(request_id, i) for i in range(output_tokens)]


class ReplicaUnreachable(ConnectionError):
    """Connection refused: the replica is dead or draining."""


@dataclasses.dataclass(frozen=True)
class StoreProfile:
    """The fleet-wide KV store's fetch-cost envelope
    (docs/architecture/kv-federation.md).

    ``fetch_tok_s`` is the peer-to-peer pull bandwidth in prefix tokens
    per second. The default derives from the same captured decode rate
    the replica profile uses (BENCH_r04): a store fetch moves bytes
    host-to-host over the kvship plane — wire-bandwidth-bound, faster
    than recomputing the prefix but slower than a local restore. 16x
    decode (4x the prefill estimate) is the labeled estimate; override
    per scenario when a captured fetch figure exists. ``fetch_rtt_s``
    is the per-pull fixed cost (locate at the master + connection
    setup)."""

    fetch_tok_s: float = 4914.0 * 16.0
    fetch_rtt_s: float = 0.002

    @classmethod
    def from_bench(
        cls, path: str | pathlib.Path | None = None, **overrides
    ) -> "StoreProfile":
        """Derive the fetch rate from the same captured bench headline
        ReplicaProfile.from_bench reads (falls back to the BENCH_r04
        default when the record is missing)."""
        decode = ReplicaProfile.from_bench(path).decode_tok_s
        fields = {"fetch_tok_s": decode * 16.0}
        fields.update(overrides)
        return cls(**fields)


class SimKVStore:
    """The fleet-wide prefix store, stubbed at the federation contract:
    membership (which prefix groups have a published copy) plus fetch
    cost (:class:`StoreProfile`). The real subsystem's master/segment/
    shipper mechanics are covered by tests/test_kvstore.py and
    tests/test_kv_federation.py; what the fleet simulation needs is the
    ROUTING-VISIBLE behavior — publish once, fetch from anywhere,
    degrade to recompute on a dropped pull — deterministically."""

    def __init__(self, profile: StoreProfile | None = None) -> None:
        self.profile = profile or StoreProfile()
        self._groups: set[str] = set()
        self.publishes = 0
        self.hits = 0
        self.misses = 0
        self.dropped_pulls = 0

    def has(self, group: str) -> bool:
        if group in self._groups:
            return True
        self.misses += 1
        return False

    def publish(self, group: str) -> None:
        """First copy wins (the master's dedup): a re-publish from a
        second replica is a no-op, exactly like a rejected put."""
        if group not in self._groups:
            self._groups.add(group)
            self.publishes += 1

    def fetch_s(self, tokens: int) -> float:
        """Virtual seconds one pull of ``tokens`` prefix tokens costs."""
        self.hits += 1
        return self.profile.fetch_rtt_s + tokens / self.profile.fetch_tok_s

    def stats(self) -> dict:
        return {
            "groups": len(self._groups),
            "publishes": self.publishes,
            "hits": self.hits,
            "misses": self.misses,
            "dropped_pulls": self.dropped_pulls,
        }


class ReplicaDied(ConnectionError):
    """The replica crashed while this request was in flight."""


@dataclasses.dataclass(frozen=True)
class PDTransferProfile:
    """Two-tier P→D disaggregation envelope (kv-cache.md
    "layer-streamed import"): decode replicas hand every prompt to a
    shared prefill tier and import the KV over a transfer leg with real
    latency + bandwidth.

    ``stage_tok_s`` is the producer's HBM→host staging rate,
    ``transfer_tok_s`` the wire rate, ``transfer_rtt_s`` the per-import
    fixed cost. ``stream_groups`` models the v3 group-framed wire: the
    stage and ship legs pipeline per layer group — import time drops
    from the additive stage+ship to first-group + max(stage, ship) of
    the remainder — and the decode side becomes schedulable at
    first-group-resident. ``stream_groups=1`` is the monolithic (v2)
    baseline. A seeded ``kv.pull.drop`` matching ``pd|...`` mid-stream
    degrades that import to a full local recompute on the decode
    replica — slower, never wrong."""

    prefill_replicas: int = 2
    prefill_tok_s: float = 4914.0 * 4.0
    stage_tok_s: float = 4914.0 * 24.0
    transfer_tok_s: float = 4914.0 * 16.0
    transfer_rtt_s: float = 0.01
    stream_groups: int = 4

    def import_s(self, tokens: int) -> float:
        """Virtual seconds one KV import occupies end to end: the
        stage/ship pipeline over ``stream_groups`` equal layer groups
        (G=1 degenerates to the additive serial path)."""
        stage = tokens / self.stage_tok_s
        ship = tokens / self.transfer_tok_s
        g = max(1, self.stream_groups)
        return self.transfer_rtt_s + (stage + ship) / g + (
            max(stage, ship) * (g - 1) / g
        )

    def first_group_s(self, tokens: int) -> float:
        """Seconds until group 0 is resident on the decode side — the
        admission gate the streamed import opens early."""
        stage = tokens / self.stage_tok_s
        ship = tokens / self.transfer_tok_s
        g = max(1, self.stream_groups)
        return self.transfer_rtt_s + (stage + ship) / g


class SimPrefillTier:
    """The shared P tier of a disaggregated fleet: FIFO prefill slots
    (one per prefill replica) each serving at the profile rate. Decode
    replicas hold a slot for the duration of their prompt's prefill;
    the tier itself never crashes (the scenario's failure surface is
    the TRANSFER leg — decode-replica kills are replica_kill's
    subject)."""

    def __init__(self, profile: PDTransferProfile) -> None:
        self.profile = profile
        self._free = max(1, profile.prefill_replicas)
        self._waiters: collections.deque[asyncio.Future] = (
            collections.deque()
        )
        self.prefills = 0
        self.prefill_tokens = 0

    async def acquire(self) -> None:
        if self._free > 0:
            self._free -= 1
            return
        fut = asyncio.get_event_loop().create_future()
        self._waiters.append(fut)
        await fut  # the releaser transfers its slot

    def release(self) -> None:
        while self._waiters:
            fut = self._waiters.popleft()
            if not fut.done():
                fut.set_result(None)
                return
        self._free += 1

    def stats(self) -> dict:
        return {
            "prefills": self.prefills,
            "prefill_tokens": self.prefill_tokens,
        }


@dataclasses.dataclass(frozen=True)
class LoraPoolProfile:
    """One replica's paged-adapter-pool envelope
    (docs/architecture/multi-tenant-lora.md).

    ``slots`` is the HBM residency bound (the engine's
    ``--lora-pool-slots``); ``load_s`` the cold-load cost — adapter
    store fetch + slot install + lockstep broadcast — a request pays
    when its adapter is not resident. Every adapter in the scenario's
    universe is REGISTERED (one fetch away in the adapter store) on
    every replica, which is what makes residency the routing-visible
    differentiator. ``wait_tick_s`` is the poll cadence a cold load
    parked behind a fully-pinned pool re-checks at (the sim analog of
    the engine's step-boundary loading queue)."""

    slots: int = 8
    load_s: float = 0.05
    wait_tick_s: float = 0.005


@dataclasses.dataclass(frozen=True)
class MoEProfile:
    """One replica's wide-EP MoE decode envelope
    (docs/architecture/wide-ep.md).

    Each request carries a dominant routed ``expert`` (the trace's
    Zipf popularity draw); the replica accumulates a decayed per-expert
    load window and its decode TPOT stretches by the EP dispatch skew —
    max/mean per-SHARD load under the current placement — because the
    synchronous all-to-all step is gated by the hottest shard's grouped
    GEMM. Tokens routed to an expert whose per-replica-slot load
    exceeds ``capacity_factor`` x the mean slot load overflow the
    GShard capacity ``C`` and are counted as DROPPED slots.

    Every ``eplb_interval_s`` of virtual time the replica's control
    loop runs the REAL :func:`llmd_tpu.parallel.eplb.compute_placement`
    (deterministic numpy — the same host-side balancer the engine's
    slow loop calls) over the observed window when EPLB is on; with
    EPLB off the identity placement (contiguous logical layout) is
    pinned for the whole run — the baseline leg the scenario's gates
    compare against."""

    num_experts: int = 32
    world: int = 8  # EP shards the experts are sharded over
    # Spare replica slots per shard: under the default Zipf popularity
    # the hottest expert carries ~25% of the flow, so equalizing slot
    # loads takes ~load/max_slot ≈ 8 replicas of it — two spares per
    # shard is the budget that lets the greedy balancer get the max
    # slot load down to ~1.7x the mean (where capacity_factor clears).
    redundancy: int = 2
    capacity_factor: float = 1.75  # GShard C as a multiple of mean slot load
    # The device path's minimum-capacity round-up (moe_ep.py sizes
    # C = max(ceil(t*k/W * factor), 8) rounded up to 8): an absolute
    # token floor under the per-slot cap, so a cold expert catching one
    # extra request doesn't register as overflow — only structural
    # overload (a hot expert pinned to too few slots) drops.
    capacity_floor: float = 8.0
    eplb_interval_s: float = 0.25  # control-loop cadence (virtual time)
    warmup_s: float = 0.02  # first control tick (the loop runs from step 0)


@dataclasses.dataclass(frozen=True)
class ReplicaProfile:
    """One replica's capacity envelope (all rates per replica)."""

    decode_tok_s: float = 4914.0  # BENCH_r04 headline, 1 chip
    prefill_tok_s: float = 4914.0 * 4.0  # estimate: 4x decode (see module doc)
    base_tpot_s: float = 0.005  # single-sequence TPOT floor
    max_batch: int = 256  # concurrent decode slots (headline B)
    kv_capacity_tokens: int = 2048 * 16  # pool pages x page size
    startup_s: float = 2.0  # autoscale provisioning delay (sim time)
    recompute_penalty: float = 1.0  # extra prefill fraction on kv.pull.drop
    # Million-token context tier (docs/architecture/long-context.md).
    # ``cp_degree > 1`` models context-parallel ring prefill: prompts at
    # or above ``long_prompt_tokens`` prefill with their chunks sharded
    # over the mesh sequence axis, so TTFT scales down ~cp_degree x (the
    # K/V ring rotation rides ICI and is not the bottleneck at these
    # chunk sizes). ``kv_window_tokens > 0`` models decode-time KV
    # paging: a sequence's resident HBM is bounded by the attention
    # window — everything colder spills to the host tier and is counted
    # in ``kv_paged_out_tokens`` — so a 1M-token document holds window
    # bytes, not context bytes, against ``kv_capacity_tokens``.
    cp_degree: int = 1
    long_prompt_tokens: int = 0  # 0 = no prompt rides the ring
    kv_window_tokens: int = 0  # 0 = full context resident (no pager)

    @classmethod
    def from_bench(
        cls, path: str | pathlib.Path | None = None, chips: int = 1, **overrides
    ) -> "ReplicaProfile":
        """Profile from a captured bench record's headline tok/s/chip.

        Falls back to the class defaults (themselves the BENCH_r04
        capture) when the record is missing/empty — CI must not depend
        on which bench artifacts a checkout carries.
        """
        decode = cls.decode_tok_s
        if path is not None:
            try:
                data = json.loads(pathlib.Path(path).read_text())
                parsed = data.get("parsed") or data
                value = float(parsed.get("value", 0.0))
                if value > 0 and "tok/s" in str(parsed.get("unit", "tok/s")):
                    decode = value
            except (OSError, ValueError, KeyError):
                pass
        fields = {
            "decode_tok_s": decode * chips,
            "prefill_tok_s": decode * chips * 4.0,
            "kv_capacity_tokens": cls.kv_capacity_tokens * chips,
        }
        fields.update(overrides)
        return cls(**fields)


class SimReplica:
    """One simulated engine replica on the virtual-time loop."""

    def __init__(
        self,
        address: str,
        profile: ReplicaProfile,
        variant: str = "sim",
        kv_store: SimKVStore | None = None,
        prefix_cache_groups: int = 8,
        lora: LoraPoolProfile | None = None,
        lora_universe: tuple = (),
        pd_tier: "SimPrefillTier | None" = None,
        moe: MoEProfile | None = None,
        moe_eplb: bool = True,
    ) -> None:
        self.address = address
        self.profile = profile
        self.variant = variant
        # Two-tier P→D serving (kv-cache.md): every prompt prefills on
        # the shared tier and imports KV over the transfer leg; seeded
        # mid-stream drops degrade that import to local recompute.
        self.pd_tier = pd_tier
        self.pd_imports = 0
        self.pd_drops = 0
        self.pd_recomputes = 0
        self.pd_import_s: list[float] = []
        self.pd_first_group_s: list[float] = []
        # Paged adapter pool (multi-tenant-lora.md): LRU residency over
        # `lora.slots` HBM slots with pin-while-referenced semantics —
        # the stub's whole-adapter stand-in for the engine's
        # AdapterPool. `lora_universe` is the registered set every
        # replica advertises as one-fetch-away.
        self.lora = lora
        self.lora_universe = tuple(lora_universe)
        self._lora_resident: collections.OrderedDict[str, None] = (
            collections.OrderedDict()
        )
        self._lora_refs: collections.Counter = collections.Counter()
        self._lora_ready_t: dict[str, float] = {}
        self.lora_hits = 0
        self.lora_cold_loads = 0
        self.lora_evictions = 0
        self.lora_pinned_evictions = 0  # MUST stay 0: the no-thrash gate
        self.lora_cold_stall_s: list[float] = []
        # Federation tier (kv-federation.md): the fleet-shared store and
        # a bounded local prefix cache (LRU over prefix groups — the
        # stub's whole-prefix stand-in for the page-granular device/host
        # tiers). Eviction from the bounded cache is what makes the
        # store earn its copies even on a single replica.
        self.kv_store = kv_store
        self.prefix_cache_groups = prefix_cache_groups
        self._prefix_cache: collections.OrderedDict[str, None] = (
            collections.OrderedDict()
        )
        self.prefix_local_hits = 0
        self.store_hits = 0
        self.store_published = 0
        self.recompute_avoided_tokens = 0
        # Wide-EP MoE (docs/architecture/wide-ep.md): decayed
        # per-expert load window, the current expert→shard placement
        # (identity until the EPLB control loop's first tick), and the
        # skew/drop counters the scoreboard's expert_skew section and
        # the EPLB-on-vs-off gates read.
        self.moe = moe
        self.moe_eplb = moe_eplb
        self.moe_routed_tokens = 0
        self.moe_dropped_slots = 0
        self.moe_rebalances = 0
        self.moe_peak_skew = 1.0
        self.moe_skew_sum = 0.0
        self.moe_skew_n = 0
        if moe is not None:
            from llmd_tpu.parallel.eplb import identity_placement

            self._moe_window = [0.0] * moe.num_experts
            self._moe_placement = identity_placement(
                moe.num_experts, moe.world
            )
            self._moe_next_tick: float | None = None
        # Million-token context tier (long-context.md): ring-prefill and
        # pager engagement counters for the scoreboard's long_context
        # section — documents that rode the cp ring, tokens whose KV was
        # paged out of HBM, and the replica's peak resident KV (the
        # bound the kv_peak gate holds against capacity).
        self.cp_ring_prefills = 0
        self.kv_paged_out_tokens = 0
        self.kv_peak_tokens = 0.0
        self.alive = True
        self.accepting = True  # False while draining out of the pool
        self.waiting = 0
        self.running = 0
        self.kv_used_tokens = 0.0
        self._free_slots = profile.max_batch
        self._slot_waiters: collections.deque[asyncio.Future] = (
            collections.deque()
        )
        # Every future an in-flight request is parked on; kill() fails
        # them all so crashes cut streams instantly, not at timer
        # expiry. Dict-as-ordered-set, NOT a set: kill() iterates this,
        # and set order follows object addresses — which would deliver
        # the crash in a different order every run and break the
        # byte-identical-scoreboard contract.
        self._inflight: dict[asyncio.Future, None] = {}
        # Counters for the WVA collector / scoreboard.
        self.arrived_total = 0
        self.served_total = 0
        self.prompt_tokens_total = 0
        self.output_tokens_total = 0
        self.recompute_fallbacks = 0
        # Batch serving tier (docs/architecture/batch-processing.md):
        # backfill rows ride their own accounting so interactive service
        # times never read batch state — the stub models the engine
        # contract (byte-identical interactive streams batch-on vs
        # batch-off) by construction. Batch rows DO hold KV and share
        # the decode rate, so scrapes and the EPP's saturation watermark
        # see them.
        self.batch_running = 0
        self.batch_served_total = 0
        self.batch_tokens_total = 0
        # KV held by in-flight batch rows: part of kv_used_tokens (the
        # scrape/EPP-visible pressure that gates watermark admission)
        # but SUBTRACTED from the WVA collector's utilization signal —
        # batch demand is deferrable and must never drive scale-up
        # (docs/architecture/batch-processing.md).
        self.batch_kv_held = 0.0

    # ---- failure controls -------------------------------------------- #

    def kill(self) -> None:
        """Crash: cut every in-flight request and refuse new ones."""
        self.alive = False
        self.accepting = False
        for fut in list(self._inflight):
            if not fut.done():
                fut.set_exception(ReplicaDied(self.address))

    def drain(self) -> None:
        """Scale-down: stop admitting; in-flight requests finish."""
        self.accepting = False

    # ---- internals --------------------------------------------------- #

    async def _hold(self, dt: float) -> None:
        """Virtual sleep that a kill() interrupts immediately.

        The alive checks on entry and resume close a same-iteration
        race: kill() can only fail futures that are not yet done, so a
        request whose timer fired (or whose slot was transferred) in
        the same event-loop iteration as the crash resumes normally —
        without the re-check it would sleep out its remaining
        prefill/decode and count as a completion served by a dead
        replica, masking the stream-interrupted outcome the
        replica-kill scenario measures."""
        if not self.alive:
            raise ReplicaDied(self.address)
        loop = asyncio.get_event_loop()
        fut = loop.create_future()
        handle = loop.call_later(
            max(dt, 0.0), lambda: fut.done() or fut.set_result(None)
        )
        self._inflight[fut] = None
        try:
            await fut
        finally:
            self._inflight.pop(fut, None)
            handle.cancel()
        if not self.alive:
            raise ReplicaDied(self.address)

    async def _acquire_slot(self) -> None:
        if not self.alive:
            raise ReplicaDied(self.address)
        if self._free_slots > 0:
            self._free_slots -= 1
            return
        fut = asyncio.get_event_loop().create_future()
        self._slot_waiters.append(fut)
        self._inflight[fut] = None
        try:
            await fut  # the releaser transfers its slot to us
        finally:
            self._inflight.pop(fut, None)
        if not self.alive:
            # The transferred slot dies with the replica — a crashed
            # stub's accounting is frozen, never reused.
            raise ReplicaDied(self.address)

    def _release_slot(self) -> None:
        while self._slot_waiters:
            fut = self._slot_waiters.popleft()
            if not fut.done():
                fut.set_result(None)
                return
        self._free_slots += 1

    # ---- the adapter pool (multi-tenant-lora.md) ---------------------- #

    async def _acquire_adapter(self, adapter: str) -> None:
        """Make ``adapter`` resident and pin it for this request.

        Resident hit: free. Cold: the request stalls for the load cost
        (fetch + slot install), evicting the LRU idle resident when no
        slot is free — NEVER a pinned one (a referenced slot's weights
        are read by the forward every step); with every slot pinned the
        load parks and re-checks each tick, the sim analog of the
        engine's step-boundary loading queue. A peer arriving during an
        install waits out the remaining install time only."""
        loop = asyncio.get_event_loop()
        assert self.lora is not None
        if adapter in self._lora_resident:
            self._lora_resident.move_to_end(adapter)
            self._lora_refs[adapter] += 1
            self.lora_hits += 1
            # Ride out a still-landing install (peer cold load).
            remaining = self._lora_ready_t.get(adapter, 0.0) - loop.time()
            if remaining > 0:
                await self._hold(remaining)
            return
        t0 = loop.time()
        self.lora_cold_loads += 1
        while True:
            if adapter in self._lora_resident:
                # A peer's install landed while this request waited.
                self._lora_resident.move_to_end(adapter)
                self._lora_refs[adapter] += 1
                remaining = (
                    self._lora_ready_t.get(adapter, 0.0) - loop.time()
                )
                if remaining > 0:
                    await self._hold(remaining)
                break
            if len(self._lora_resident) >= self.lora.slots:
                victim = next(
                    (
                        name for name in self._lora_resident
                        if self._lora_refs[name] == 0
                    ),
                    None,
                )
                if victim is None:
                    # Every slot pinned: park (backpressure, not thrash).
                    await self._hold(self.lora.wait_tick_s)
                    continue
                if self._lora_refs[victim] > 0:  # structurally unreachable
                    self.lora_pinned_evictions += 1
                del self._lora_resident[victim]
                self._lora_ready_t.pop(victim, None)
                self.lora_evictions += 1
            # Reserve the slot (pinned through the install), then pay
            # the load; peers see ready_t and wait out the remainder.
            self._lora_resident[adapter] = None
            self._lora_refs[adapter] += 1
            self._lora_ready_t[adapter] = loop.time() + self.lora.load_s
            await self._hold(self.lora.load_s)
            break
        self.lora_cold_stall_s.append(loop.time() - t0)

    # ---- wide-EP MoE dispatch (docs/architecture/wide-ep.md) ---------- #

    def _moe_dispatch(self, expert: int, tokens: int) -> float:
        """Account ``tokens`` routed to logical ``expert`` and return
        this request's decode-TPOT multiplier.

        The synchronous EP all-to-all step is gated by the hottest
        shard's grouped GEMM, so TPOT stretches by the max/mean
        per-shard load skew under the CURRENT placement. Tokens to an
        expert whose per-replica-slot load exceeds ``capacity_factor``
        x the mean slot load overflow the GShard capacity and the
        excess fraction is counted as dropped slots. The EPLB control
        loop ticks every ``eplb_interval_s`` of virtual time: real
        :func:`compute_placement` over the decayed window when EPLB is
        on, the pinned identity layout when off.
        """
        m = self.moe
        e = expert % m.num_experts
        w = self._moe_window
        w[e] += float(tokens)
        self.moe_routed_tokens += tokens
        now = asyncio.get_running_loop().time()
        if self._moe_next_tick is None:
            # Warmup tick: the first placement lands once a sliver of
            # traffic has been observed, then every eplb_interval_s.
            self._moe_next_tick = now + min(m.eplb_interval_s, m.warmup_s)
        elif now >= self._moe_next_tick:
            self._moe_next_tick = now + m.eplb_interval_s
            if self.moe_eplb and sum(w) > 0:
                from llmd_tpu.parallel.eplb import compute_placement

                self._moe_placement = compute_placement(
                    w, world=m.world, redundancy=m.redundancy,
                )
                self.moe_rebalances += 1
            # Decay, don't reset: the window tracks the recent expert
            # mix without the post-tick skew estimate restarting from a
            # single sample.
            for j in range(len(w)):
                w[j] *= 0.5
        pl = self._moe_placement
        shard = pl.shard_loads(w)
        mean = float(shard.mean())
        skew = float(shard.max()) / mean if mean > 0 else 1.0
        self.moe_skew_sum += skew
        self.moe_skew_n += 1
        if skew > self.moe_peak_skew:
            self.moe_peak_skew = skew
        # Capacity overflow: load on the expert's slot above C spills
        # the excess fraction of this request's routed tokens.
        mean_slot = sum(w) / pl.num_physical
        slot_load = w[e] / max(int(pl.n_replicas[e]), 1)
        cap = m.capacity_factor * mean_slot + m.capacity_floor
        if mean_slot > 0 and slot_load > cap:
            self.moe_dropped_slots += int(tokens * (1.0 - cap / slot_load))
        return skew

    def _release_adapter(self, adapter: str) -> None:
        if self._lora_refs[adapter] > 0:
            self._lora_refs[adapter] -= 1

    # ---- the serving path -------------------------------------------- #

    def _prefix_cache_put(self, group: str) -> None:
        self._prefix_cache.pop(group, None)
        self._prefix_cache[group] = None
        while len(self._prefix_cache) > self.prefix_cache_groups:
            self._prefix_cache.popitem(last=False)

    def _plan_prefill(
        self, request_id: str, prompt_tokens: int,
        prefix_group: str | None, prefix_tokens: int,
    ) -> tuple[float, str | None]:
        """Tri-state prefill cost (kv-federation.md): local prefix hit
        beats a store fetch beats recompute. Returns (prefill seconds,
        group to publish after the compute lands — None when no publish
        is due)."""
        p = self.profile
        full_s = prompt_tokens / p.prefill_tok_s
        if (
            self.kv_store is None
            or prefix_group is None
            or prefix_tokens <= 0
        ):
            return full_s, None
        rest_s = (prompt_tokens - prefix_tokens) / p.prefill_tok_s
        if prefix_group in self._prefix_cache:
            self._prefix_cache.move_to_end(prefix_group)
            self.prefix_local_hits += 1
            return rest_s, None
        if self.kv_store.has(prefix_group):
            # The store leg of the kv.pull.drop site: a dropped
            # federated pull degrades to recompute, exactly like a
            # dropped P/D pull (fault-tolerance.md).
            if faults.fires(
                "kv.pull.drop", f"store|{self.address}|{request_id}"
            ):
                self.kv_store.dropped_pulls += 1
                self.recompute_fallbacks += 1
                return full_s * (1.0 + p.recompute_penalty), None
            self.store_hits += 1
            self.recompute_avoided_tokens += prefix_tokens
            return self.kv_store.fetch_s(prefix_tokens) + rest_s, None
        # Neither tier holds it: recompute the whole prompt and publish
        # the prefix once the pages exist (the eager save policy —
        # deterministic, no hotness bookkeeping in the stub).
        return full_s, prefix_group

    async def _serve_pd_prefill(
        self, request_id: str, prompt_tokens: int
    ) -> None:
        """The disaggregated prefill leg: prompt prefills on the shared
        P tier, then the KV imports over the transfer leg (the
        group-streamed stage/ship pipeline — PDTransferProfile). A
        seeded ``kv.pull.drop`` matching ``pd|<addr>|<rid>|g<G>`` fired
        against ANY group mid-stream degrades the whole import to a
        full local recompute on this decode replica: slower, never
        wrong, never lost."""
        tier = self.pd_tier
        pd = tier.profile
        await tier.acquire()
        try:
            # The P tier's compute (FIFO slot per prefill replica).
            await self._hold(prompt_tokens / pd.prefill_tok_s)
            tier.prefills += 1
            tier.prefill_tokens += prompt_tokens
        finally:
            tier.release()
        dropped = any(
            faults.fires(
                "kv.pull.drop", f"pd|{self.address}|{request_id}|g{g}"
            )
            for g in range(max(1, pd.stream_groups))
        )
        if dropped:
            self.pd_drops += 1
            self.pd_recomputes += 1
            self.recompute_fallbacks += 1
            # Mid-stream failure: the decode side falls back to
            # prefilling the whole prompt itself at ITS prefill rate.
            await self._hold(
                prompt_tokens / self.profile.prefill_tok_s
            )
            return
        import_s = pd.import_s(prompt_tokens)
        self.pd_imports += 1
        self.pd_import_s.append(import_s)
        self.pd_first_group_s.append(pd.first_group_s(prompt_tokens))
        await self._hold(import_s)

    async def serve_batch(
        self, request_id: str, prompt_tokens: int, output_tokens: int
    ):
        """Serve one BATCH-band request (offline backfill): same
        yield-at-first-token generator shape as :meth:`serve`, but the
        row never takes an interactive batch slot and is metered at
        LEFTOVER capacity — prefill throughput scales with the
        interactive batch's idle fraction, decode TPOT shares the
        aggregate rate with everything running. Interactive rows never
        read batch state, so their latencies are independent of the
        backfill by construction (the engine-level byte-parity
        contract, docs/architecture/batch-processing.md). Crashes cut
        batch streams exactly like interactive ones."""
        if not self.alive or not self.accepting:
            raise ReplicaUnreachable(self.address)
        p = self.profile
        self.batch_running += 1
        held_tokens = prompt_tokens + output_tokens
        self.kv_used_tokens += held_tokens
        self.batch_kv_held += held_tokens
        try:
            # Backfill prefill: only the idle fraction of the step is
            # harvestable (snapshot at admission; 5% floor keeps a
            # saturated replica from stalling the row forever — the EPP
            # watermark should have kept it away anyway).
            headroom = max(0.05, 1.0 - self.running / p.max_batch)
            await self._hold(prompt_tokens / (p.prefill_tok_s * headroom))
            yield "first-token"
            if output_tokens > 1:
                tpot = max(
                    p.base_tpot_s,
                    (self.running + self.batch_running) / p.decode_tok_s,
                )
                await self._hold((output_tokens - 1) * tpot)
            self.batch_served_total += 1
            self.batch_tokens_total += output_tokens
        finally:
            self.batch_running -= 1
            self.kv_used_tokens -= held_tokens
            self.batch_kv_held -= held_tokens

    async def serve(
        self,
        request_id: str,
        prompt_tokens: int,
        output_tokens: int,
        prefix_group: str | None = None,
        prefix_tokens: int = 0,
        resume_tokens: int = 0,
        adapter: str | None = None,
        expert: int | None = None,
    ):
        """Serve one request; async generator yielding LISTS of token
        values (:func:`stream_token`) — the first list at first-token
        time, then decode chunks — and returning at completion (the
        transport measures TTFT and stream end from the yields, like SSE
        frames on a socket).

        ``resume_tokens`` is the mid-stream failover contract
        (fault-tolerance.md): the first ``resume_tokens`` output
        positions were already delivered by a dead replica; they are
        admitted as prefill of committed prefix — costed like prompt
        (the shared ``prefix_group`` still takes the store-fetch fast
        path) — and generation continues at position ``resume_tokens``.

        Raises :class:`ReplicaUnreachable` before any byte when the
        replica is down/draining, :class:`ReplicaDied` at whatever point
        a crash lands.
        """
        if not self.alive or not self.accepting:
            raise ReplicaUnreachable(self.address)
        p = self.profile
        self.arrived_total += 1
        self.waiting += 1
        try:
            await self._acquire_slot()
        finally:
            self.waiting -= 1
        self.running += 1
        held_tokens = prompt_tokens + output_tokens
        if 0 < p.kv_window_tokens < held_tokens:
            # Decode-time KV paging: only the attention window stays
            # resident; the cold remainder spills to the host tier.
            self.kv_paged_out_tokens += held_tokens - p.kv_window_tokens
            held_tokens = p.kv_window_tokens
        self.kv_used_tokens += held_tokens
        if self.kv_used_tokens > self.kv_peak_tokens:
            self.kv_peak_tokens = self.kv_used_tokens
        lora_acquired = False
        try:
            if adapter is not None and self.lora is not None:
                # Adapter residency before any token: a cold load's
                # fetch+install stall is a TTFT component, exactly like
                # the engine's parked loading queue. (A crash mid-
                # acquire leaves the dead replica's accounting frozen.)
                await self._acquire_adapter(adapter)
                lora_acquired = True
            # Degradations the production stack contracts for: a dropped
            # KV pull recomputes locally (slower prefill, correct
            # output); a brownout serves every request delay_ms late.
            # A resume leg prefills the delivered history too — that is
            # the replayed-prefix cost the store fetch keeps bounded.
            publish_group = None
            if self.pd_tier is not None:
                await self._serve_pd_prefill(
                    request_id, prompt_tokens + resume_tokens
                )
            else:
                prefill_s, publish_group = self._plan_prefill(
                    request_id, prompt_tokens + resume_tokens,
                    prefix_group, prefix_tokens,
                )
                if (
                    p.cp_degree > 1
                    and p.long_prompt_tokens > 0
                    and prompt_tokens + resume_tokens
                    >= p.long_prompt_tokens
                ):
                    # Context-parallel ring prefill: the document's
                    # chunks shard over the sequence axis, so time to
                    # first token divides by the cp degree.
                    prefill_s /= p.cp_degree
                    self.cp_ring_prefills += 1
                if faults.fires(
                    "kv.pull.drop", f"{self.address}|{request_id}"
                ):
                    self.recompute_fallbacks += 1
                    prefill_s *= 1.0 + p.recompute_penalty
                prefill_s += faults.delay_s(
                    "replica.brownout", self.address
                )
                await self._hold(prefill_s)
            if prefix_group is not None and self.kv_store is not None:
                # The prefix pages exist now: they enter the local cache,
                # and a freshly-computed group earns the fleet its first
                # store copy (publish-on-fill; the master dedups).
                self._prefix_cache_put(prefix_group)
                if publish_group is not None:
                    self.kv_store.publish(publish_group)
                    self.store_published += 1
            pos = resume_tokens
            yield [stream_token(request_id, pos)]
            pos += 1
            if pos < output_tokens:
                # Load-dependent TPOT, snapshotted at decode start: the
                # batch shares the aggregate decode rate at saturation.
                # Decode streams in chunks (not one whole-tail sleep) so
                # a crash lands MID-stream at a token position — the
                # delivered-prefix accounting the resume protocol rides.
                tpot = max(p.base_tpot_s, self.running / p.decode_tok_s)
                if self.moe is not None and expert is not None:
                    # Wide-EP dispatch skew: the step is gated by the
                    # hottest shard under the current placement.
                    tpot *= self._moe_dispatch(expert, output_tokens)
                chunk = max(1, output_tokens // 4)
                while pos < output_tokens:
                    n = min(chunk, output_tokens - pos)
                    await self._hold(n * tpot)
                    yield [
                        stream_token(request_id, i)
                        for i in range(pos, pos + n)
                    ]
                    pos += n
            self.served_total += 1
            self.prompt_tokens_total += prompt_tokens
            self.output_tokens_total += output_tokens - resume_tokens
        finally:
            if lora_acquired:
                self._release_adapter(adapter)
            self.running -= 1
            self.kv_used_tokens -= held_tokens
            self._release_slot()

    # ---- the scrape surface ------------------------------------------ #

    def metrics_text(self) -> str:
        """A real Prometheus page for the production MetricsCollector
        (llmd engine-family names — datalayer.METRIC_MAPPINGS)."""
        cap = max(self.profile.kv_capacity_tokens, 1)
        usage = min(self.kv_used_tokens / cap, 1.0)
        text = (
            f"llmd:num_requests_waiting {self.waiting}\n"
            f"llmd:num_requests_running {self.running}\n"
            f"llmd:gpu_cache_usage_perc {usage:.6f}\n"
            "llmd:prefix_cache_hit_rate 0.0\n"
            f'llmd:cache_config_info{{block_size="16",'
            f'num_gpu_blocks="{cap // 16}"}} 1\n'
        )
        if self.lora is not None:
            # The engine's adapter-residency surface, verbatim: the
            # production extract_attrs parses these labels into
            # ResidentAdapters/AvailableAdapters for the tri-state
            # lora-affinity scorer (multi-tenant-lora.md).
            resident = ",".join(self._lora_resident)
            available = ",".join(self.lora_universe)
            text += (
                "# TYPE vllm:lora_requests_info gauge\n"
                f'vllm:lora_requests_info{{max_lora="{self.lora.slots}",'
                'running_lora_adapters="",waiting_lora_adapters="",'
                f'available_lora_adapters="{available}",'
                f'resident_lora_adapters="{resident}"}} 1\n'
            )
        return text
