"""Fleet-soak CLI: run the seeded scenario matrix, emit scoreboards.

Usage::

    python -m llmd_tpu.fleetsim --list
    python -m llmd_tpu.fleetsim --scenario replica_kill --out sb.json
    python -m llmd_tpu.fleetsim --scenario all --out-dir soak/
    python -m llmd_tpu.fleetsim --scenario steady --emit-trace trace.jsonl
    python -m llmd_tpu.fleetsim --scenario steady --trace trace.jsonl

Exit status is nonzero when any invariant fails — the CI `soak` job's
hard gate. Scoreboard JSON is byte-deterministic for a given
(scenario, seed, qps-scale): CI runs a scenario twice and diffs the
bytes. Human-readable progress goes to stderr; stdout carries the
scoreboard JSON only when neither --out nor --out-dir is given.
"""

from __future__ import annotations

import argparse
import pathlib
import sys
import time

from llmd_tpu.fleetsim import traces
from llmd_tpu.fleetsim.scenarios import SCENARIOS
from llmd_tpu.fleetsim.scoreboard import to_canonical_json


def _summarize(board: dict) -> str:
    t = board["trace"]
    lat = board["latency_ms"]["ttft"]
    bad = [n for n, r in board["invariants"].items() if not r["ok"]]
    status = "OK" if board["ok"] else f"FAIL({', '.join(bad)})"
    return (
        f"{board['scenario']:<13} {t['requests']:>6} req @ "
        f"{t['offered_qps']:>7.0f} QPS  p50/p99 TTFT "
        f"{lat['p50']:.1f}/{lat['p99']:.1f} ms  hung={board['requests']['hung']} "
        f"lost={board['requests']['lost']}  {status}"
    )


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(prog="python -m llmd_tpu.fleetsim")
    ap.add_argument("--scenario", default="all",
                    help="scenario name or 'all' (default)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--qps-scale", type=float, default=1.0,
                    help="scale every scenario's offered rate (and fleet "
                         "size) — 1.0 is the CI soak scale")
    ap.add_argument("--out", help="write the scoreboard JSON here "
                                  "(single scenario)")
    ap.add_argument("--out-dir", help="write one <scenario>.json per "
                                      "scenario here")
    ap.add_argument("--trace", help="replay a JSONL trace instead of the "
                                    "scenario's generated one")
    ap.add_argument("--emit-trace", help="write the scenario's generated "
                                         "trace as JSONL and exit")
    ap.add_argument("--list", action="store_true",
                    help="list scenarios and exit")
    args = ap.parse_args(argv)

    if args.list:
        for name, sc in SCENARIOS.items():
            print(f"{name:<13} {sc.description}")
        return 0

    names = list(SCENARIOS) if args.scenario == "all" else [args.scenario]
    unknown = [n for n in names if n not in SCENARIOS]
    if unknown:
        print(f"unknown scenario(s): {unknown}; known: {list(SCENARIOS)}",
              file=sys.stderr)
        return 2
    if (args.emit_trace or args.trace) and len(names) > 1:
        # --emit-trace would silently write only the first scenario's
        # trace and exit 0; --trace would replay one trace into every
        # scenario's mismatched fleet/faults/invariants.
        print("--emit-trace/--trace need a single --scenario, not 'all'",
              file=sys.stderr)
        return 2

    ok = True
    boards: dict[str, dict] = {}
    for name in names:
        fleet = SCENARIOS[name].build(args.seed, args.qps_scale)
        if args.emit_trace:
            traces.save_jsonl(args.emit_trace, fleet.trace)
            print(f"wrote {len(fleet.trace)} arrivals to "
                  f"{args.emit_trace}", file=sys.stderr)
            return 0
        if args.trace:
            fleet.trace = traces.load_jsonl(args.trace)
            fleet._duration = max((r.t for r in fleet.trace), default=0.0)
        # llmd: allow(direct-clock) -- measuring real wall time of the run itself (stderr only, never in the scoreboard)
        t0 = time.monotonic()
        board = fleet.run()
        # llmd: allow(direct-clock) -- same wall-time measurement pair
        wall = time.monotonic() - t0
        boards[name] = board
        # Wall clock goes to stderr only — the scoreboard must stay
        # byte-identical across runs.
        print(f"{_summarize(board)}  [{wall:.1f}s wall]", file=sys.stderr)
        ok = ok and board["ok"]

    if args.out_dir:
        out = pathlib.Path(args.out_dir)
        out.mkdir(parents=True, exist_ok=True)
        for name, board in boards.items():
            (out / f"{name}.json").write_text(to_canonical_json(board))
    elif args.out:
        if len(boards) == 1:
            payload = next(iter(boards.values()))
        else:
            payload = boards
        pathlib.Path(args.out).write_text(to_canonical_json(payload))
    else:
        payload = next(iter(boards.values())) if len(boards) == 1 else boards
        sys.stdout.write(to_canonical_json(payload))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
