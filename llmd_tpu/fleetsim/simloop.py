"""Virtual-time asyncio event loop: minutes of fleet time in CI seconds.

The fleet simulator's core trick is that it drives the REAL control
stack — flow control's dispatch loop, the metrics collector's scrape
cadence, the WVA's 30 s pipeline, retry backoffs — through ordinary
``asyncio.sleep`` calls, on an event loop whose clock is simulated:

- :class:`SimEventLoop` overrides ``time()`` to return a virtual clock
  that starts at 0.0, and wraps its selector so that a positive select
  timeout (i.e. "nothing runnable until the next timer") ADVANCES the
  virtual clock to that timer instead of blocking the thread. Every
  scheduled callback still fires in exactly the order and at exactly
  the (virtual) times real asyncio would run them — the interleaving
  semantics are asyncio's own, only the waiting is erased.
- The control stack reads time through :mod:`llmd_tpu.clock`;
  :func:`run` installs ``loop.time`` there for the duration of the
  simulation, so breaker cooldowns, flow-control TTLs/EDF deadlines and
  scrape freshness all live on the same virtual axis as the sleeps.

Determinism: the ready queue is FIFO and the timer heap is keyed on
(virtual when, schedule order), both fully determined by the program —
no wall clock, no thread scheduling, no I/O readiness races in the
pure-simulation scenarios (they perform no real I/O), so the same
trace + seed replays to a byte-identical scoreboard, which CI asserts.
The router-soak scenario relaxes this: it runs REAL loopback sockets on
the loop (see :class:`_InstantSelector`), whose kernel-side readiness
ordering is outside the program — its gates are content invariants
(stream parity, zero visible failures), not byte-compared scoreboards.

Deadlock detection is free: real asyncio would block in ``select(None)``
forever when nothing is ready, nothing is scheduled and no I/O can
arrive. In a simulation that state means some coroutine is waiting on
an event nobody will ever set — a HUNG request, exactly the failure
class the soak exists to catch — so the loop raises
:class:`SimDeadlockError` instead of hanging CI.
"""

from __future__ import annotations

import asyncio
from typing import Any, Coroutine

from llmd_tpu import clock


class SimDeadlockError(RuntimeError):
    """The simulation has runnable future but no timer and no ready
    callback: some coroutine waits on an event that can never fire."""


class _InstantSelector:
    """Selector proxy: positive timeouts become virtual-clock advances.

    asyncio's ``_run_once`` computes ``timeout = next_timer_when -
    loop.time()`` and blocks in ``selector.select(timeout)``. With no
    real I/O registered beyond the loop's internal self-pipe, that block
    is pure waiting — so advance the virtual clock by ``timeout`` and
    poll (timeout 0) instead.

    Real loopback sockets (the router-soak scenario drives the ACTUAL
    aiohttp router in-process) extend the rule: socket I/O is
    *instantaneous in virtual time*. When external fds are registered, a
    short REAL grace poll lets in-flight loopback bytes land before the
    clock advances — data produced by this same loop's callbacks is
    almost always kernel-buffered by the next iteration, but "almost"
    is the kernel's call, not ours. The virtual clock never advances
    during a grace wait, so simulated latencies stay timer-driven."""

    # Real seconds one grace poll blocks for when loopback sockets are
    # live. Virtual time does not move during it.
    IO_GRACE_S = 0.001
    # timeout=None + external fds: poll this long per iteration, and
    # give up (deadlock) after this many consecutive empty polls.
    IO_IDLE_S = 0.01
    IO_IDLE_LIMIT = 3000  # ~30 s real

    def __init__(self, inner, loop: "SimEventLoop") -> None:
        self._inner = inner
        self._loop = loop
        # Fds present at install time (the loop's self-pipe): anything
        # beyond these is real I/O the simulation must not starve.
        self._base_fds = frozenset(inner.get_map())
        self._idle_polls = 0

    def _external_io(self) -> bool:
        return any(fd not in self._base_fds for fd in self._inner.get_map())

    def select(self, timeout=None):
        events = self._inner.select(0)
        if events:
            self._idle_polls = 0
            return events
        if timeout is not None and timeout <= 0:
            return events
        external = self._external_io()
        if external:
            events = self._inner.select(
                self.IO_GRACE_S if timeout is not None else self.IO_IDLE_S
            )
            if events:
                self._idle_polls = 0
                return events
        if timeout is None:
            if external:
                # Sockets are open but idle and no timer is scheduled:
                # bytes may still arrive from a transport teardown in
                # flight — spin with real waits, bounded.
                self._idle_polls += 1
                if self._idle_polls < self.IO_IDLE_LIMIT:
                    return []
            # No ready callbacks, no scheduled timers, not stopping:
            # real asyncio would block forever here.
            raise SimDeadlockError(
                "simulation deadlock: no runnable callback, no scheduled "
                "timer — a coroutine is awaiting an event that can never "
                "fire (a hung request or an un-cancelled waiter)"
            )
        self._idle_polls = 0
        self._loop.advance(timeout)
        return self._inner.select(0)

    def __getattr__(self, name):
        return getattr(self._inner, name)


class SimEventLoop(asyncio.SelectorEventLoop):
    """A selector event loop on simulated time (starts at 0.0)."""

    def __init__(self) -> None:
        super().__init__()
        self._sim_now = 0.0
        self._selector = _InstantSelector(self._selector, self)

    def time(self) -> float:
        return self._sim_now

    def advance(self, dt: float) -> None:
        """Jump the virtual clock forward by ``dt`` seconds."""
        if dt > 0:
            self._sim_now += dt


def run(main: Coroutine, install_clock: bool = True) -> Any:
    """``asyncio.run`` on a fresh :class:`SimEventLoop`.

    Installs the loop's virtual clock into :mod:`llmd_tpu.clock` for the
    duration (restored in a ``finally``), cancels leftover tasks on the
    way out, and returns the coroutine's result.
    """
    loop = SimEventLoop()
    try:
        if install_clock:
            clock.install(loop.time)
            # The wall seam rides the same virtual axis (epoch 0): batch
            # deadlines/timestamps then replay deterministically too.
            clock.install_wall(loop.time)
        asyncio.set_event_loop(loop)
        return loop.run_until_complete(main)
    finally:
        if install_clock:
            clock.reset()
        try:
            pending = asyncio.all_tasks(loop)
            for task in pending:
                task.cancel()
            if pending:
                loop.run_until_complete(
                    asyncio.gather(*pending, return_exceptions=True)
                )
        finally:
            asyncio.set_event_loop(None)
            loop.close()
