"""FleetSim: drive the REAL routing/control stack through a simulated fleet.

This is the closed loop the ROADMAP's "million-user fleet soak" item
names. Everything decision-making is the production code, not a model
of it:

- scheduling — ``epp.config.build_scheduler`` over a real
  EndpointPickerConfig dict: the same Filter→Score→Pick plugin chain,
  registry and profile handler the router process assembles;
- flow control — ``epp.config.build_flow_control``: real bands,
  fairness/ordering policies, TTL eviction and the saturation-gated
  dispatch loop, running as its own task on the virtual-time loop;
- health — the production ``MetricsCollector`` scrape loop; the only
  substitution is the HTTP leg (``_fetch`` returns the stub replica's
  Prometheus page), so consecutive-failure counting, ``extract_attrs``
  and the unhealthy window are the code under test;
- retry/breaker — ``epp.server.eligible_pods`` +
  ``epp.server.backoff_delay`` (decorrelated jitter) + the real
  ``EndpointCircuitBreaker`` on the shared clock seam;
- latency prediction — a real ``LatencyPredictor`` trained online from
  simulated completions via the production
  ``PredictedLatencyProducer``/``PredictorClient`` path (optional per
  scenario);
- autoscaling — the real ``WvaEngine`` pipeline (analyzer → optimizer →
  enforcer) plus its scale-from-zero fast path, fed by a snapshot
  collector over the stub fleet; its decisions materialize as replicas
  with a provisioning delay.

Only the engine replicas themselves and the proxy byte-shoveling are
stubs (:mod:`llmd_tpu.fleetsim.engines`) — those are the parts whose
behavior is captured by bench numbers, not routing logic.

The request driver mirrors ``Router._route_and_proxy`` semantics
exactly: tried-set exclusion, breaker gating with fail-open, re-pick on
connection-class errors before first byte, typed surfacing of a stream
cut after first byte, jittered backoff between attempts, breaker and
health feedback, completion notifications to scorers and the predictor.
"""

from __future__ import annotations

import asyncio
import copy
import dataclasses
import logging
import random

from llmd_tpu import clock, faults
from llmd_tpu.autoscale.engine import WvaEngine
from llmd_tpu.autoscale.types import PoolSnapshot, ReplicaMetrics, VariantSpec
from llmd_tpu.epp import config as epp_config
from llmd_tpu.epp import filters as filters_mod
from llmd_tpu.epp.breaker import EndpointCircuitBreaker
from llmd_tpu.epp.datalayer import EndpointStore, MetricsCollector
from llmd_tpu.epp.flow_control import Outcome
from llmd_tpu.epp.scheduler import NoEndpointsError
from llmd_tpu.epp.server import backoff_delay, eligible_pods
from llmd_tpu.epp.types import (
    BATCH_PRIORITY,
    KV_CACHE_USAGE,
    WAITING_QUEUE_SIZE,
    Endpoint,
    LLMRequest,
)
from llmd_tpu.fleetsim import simloop
from llmd_tpu.fleetsim.engines import (
    LoraPoolProfile,
    MoEProfile,
    PDTransferProfile,
    ReplicaDied,
    ReplicaProfile,
    ReplicaUnreachable,
    SimKVStore,
    SimPrefillTier,
    SimReplica,
    StoreProfile,
    expected_stream,
)
from llmd_tpu.fleetsim.scoreboard import Scoreboard
from llmd_tpu.fleetsim.traces import TraceRequest

log = logging.getLogger(__name__)


@dataclasses.dataclass
class AutoscaleConfig:
    """WVA wiring for a scenario (None = fixed fleet)."""

    analyzer: str = "saturation-percentage-based"
    interval_s: float = 2.0
    sfz_interval_s: float = 0.1
    scale_to_zero: bool = False
    retention_s: float = 10.0
    min_replicas: int = 0
    max_replicas: int = 64


@dataclasses.dataclass
class FleetConfig:
    """One scenario's fleet + control-stack knobs."""

    replicas: int = 4
    profile: ReplicaProfile = dataclasses.field(default_factory=ReplicaProfile)
    scheduler_config: dict | None = None  # EndpointPickerConfig dict
    use_predictor: bool = False
    flow_max_inflight: int = 4096
    flow_ttl_s: float = 30.0
    fairness: str = "round-robin"
    max_schedule_attempts: int = 3
    retry_backoff_s: float = 0.005
    retry_backoff_cap_s: float = 0.25
    breaker_threshold: int = 2
    breaker_cooldown_s: float = 1.0
    # Mid-stream failover budget (the router's max_resumes knob,
    # fault-tolerance.md): how many times one cut stream may continue
    # on a fresh replica before the failure is client-visible
    # ("stream-interrupted"). 0 = the pre-failover router.
    max_resumes: int = 2
    scrape_interval_s: float = 0.25
    unhealthy_after: int = 3
    chaos_tick_s: float = 0.05
    grace_s: float = 60.0  # drain window after the last arrival
    # KV federation (kv-federation.md): a StoreProfile arms the
    # fleet-wide prefix store — replicas publish freshly-computed
    # shared prefixes and fetch peers' copies instead of re-prefilling
    # (None = no store, the pre-federation fleet).
    kv_store: StoreProfile | None = None
    prefix_cache_groups: int = 8  # per-replica local prefix-cache LRU cap
    # Whether shared-prefix groups are VISIBLE to the router's
    # approximate prefix scorer (group-id-led prompt text). True is the
    # kv_federation scenario's subject — cache-affinity routing vs the
    # store tier. False keeps routing load-spread while replicas still
    # share prefixes through the store: the replica_kill shape, where
    # Zipf-hot group affinity at 10^4 QPS would drown the failover
    # signal in hot-replica queueing.
    prefix_affinity_text: bool = True
    # Simulated idle time appended AFTER the last request drains, with
    # the control loops still running — the window where scale-down /
    # scale-to-zero behavior is observable. Free: it is virtual time.
    idle_tail_s: float = 0.0
    autoscale: AutoscaleConfig | None = None
    model_id: str = "sim-model"
    # Batch serving tier (docs/architecture/batch-processing.md): a
    # standing queue of ``batch_jobs`` offline requests enqueued at
    # t≈0 at BATCH_PRIORITY. They ride the REAL pipeline — flow-control
    # band below every interactive priority, the production plugin
    # chain (whose batch-saturation-filter admits them only on replicas
    # below the watermark), the breaker — and are served by the
    # replicas' backfill path. A router 503 (no replica below the
    # watermark) re-offers the job after ``batch_retry_s``: batch work
    # WAITS for troughs, it never displaces.
    batch_jobs: int = 0
    batch_prompt_tokens: int = 64
    batch_output_tokens: int = 256
    batch_retry_s: float = 1.0
    # Per-job enqueue stagger: a standing queue drips in over
    # jobs x stagger seconds, so later arrivals observe the saturation
    # the earlier ones created and the watermark admission path is
    # actually exercised (retries > 0 in the scoreboard).
    batch_arrival_stagger_s: float = 0.1
    # Fleet-utilization sampling cadence (armed with batch_jobs > 0 or
    # sample_util): feeds the scoreboard's utilization/backlog series,
    # which the trough-utilization-floor and monotone-drain invariants
    # gate. ``sample_util`` arms the sampler without a batch queue —
    # the no-batch baseline leg the bench part compares against.
    util_sample_s: float = 0.5
    sample_util: bool = False
    # Multi-tenant LoRA (multi-tenant-lora.md): a LoraPoolProfile arms
    # every replica's paged adapter pool (trace requests carrying an
    # ``adapter`` stall on cold loads, LRU-evict idle residents, and
    # advertise residency on the scrape page); ``lora_affinity`` puts
    # the tri-state lora-affinity scorer in the plugin chain — False is
    # the adapter-blind baseline the hit-ratio lift is measured
    # against.
    lora: LoraPoolProfile | None = None
    lora_affinity: bool = True
    # Disaggregated P→D serving (kv-cache.md "layer-streamed import"):
    # a PDTransferProfile arms the two-tier shape — every decode
    # replica's prompts prefill on a shared P tier and the KV imports
    # over a transfer leg with real latency/bandwidth, group-streamed
    # per the profile; seeded kv.pull.drop (match "pd|") mid-stream
    # degrades that import to a full local recompute.
    pd: "PDTransferProfile | None" = None
    # Wide-EP MoE (docs/architecture/wide-ep.md): a MoEProfile arms
    # every replica's expert-dispatch model — trace requests carrying
    # an ``expert`` skew the per-shard load under the current
    # placement, stretching decode TPOT and overflowing the GShard
    # capacity into dropped slots; ``moe_eplb`` runs the real EPLB
    # balancer on each replica's control-loop tick — False pins the
    # identity layout, the hot-shard baseline the scenario's gates
    # compare against.
    moe: "MoEProfile | None" = None
    moe_eplb: bool = True


def default_sim_config(
    seed: int,
    max_inflight: int = 4096,
    ttl_s: float = 30.0,
    fairness: str = "round-robin",
    use_predictor: bool = False,
    lora_affinity: bool = False,
) -> dict:
    """The soak's EndpointPickerConfig: the production DEFAULT_CONFIG
    plugin set with a seeded picker (deterministic tie-breaks) and,
    optionally, the predicted-latency and/or lora-affinity scorers in
    the chain."""
    cfg = copy.deepcopy(epp_config.DEFAULT_CONFIG)
    for p in cfg["plugins"]:
        if p["type"] == "max-score-picker":
            p["parameters"] = {"seed": seed}
    if use_predictor:
        cfg["plugins"].append({"type": "latency-scorer", "name": "latency"})
        cfg["schedulingProfiles"][0]["plugins"].insert(
            -1, {"pluginRef": "latency", "weight": 2.0}
        )
    if lora_affinity:
        # The production tri-state residency scorer
        # (multi-tenant-lora.md), fed by the replicas' real scrape
        # pages through extract_attrs.
        cfg["plugins"].append(
            {"type": "lora-affinity-scorer", "name": "lora"}
        )
        cfg["schedulingProfiles"][0]["plugins"].insert(
            -1, {"pluginRef": "lora", "weight": 2.0}
        )
    cfg["flowControl"] = {
        "enabled": True,
        "maxInflight": max_inflight,
        "fairness": fairness,
        "bands": [{"priority": 0, "maxRequests": 65536,
                   "ttlSeconds": ttl_s}],
        "maxTotalRequests": 1 << 17,
    }
    return cfg


class _SimCollector(MetricsCollector):
    """Production scrape loop over the virtual transport: only the HTTP
    GET is replaced; health windows and attr extraction are the real
    datalayer code (including the epp.scrape.fail injection site)."""

    def __init__(self, fleet: "FleetSim", **kw) -> None:
        super().__init__(fleet.store, **kw)
        self.fleet = fleet

    async def _fetch(self, pod: Endpoint) -> str:
        replica = self.fleet.replicas.get(pod.address)
        if replica is None or not replica.alive:
            raise ConnectionRefusedError(pod.address)
        return replica.metrics_text()


class _SimWvaCollector:
    """PoolSnapshot source for the real WVA pipeline, mirroring
    RouterCollector's delta/retention accounting over the stub fleet."""

    def __init__(self, fleet: "FleetSim", retention_s: float) -> None:
        self.fleet = fleet
        self.retention_s = retention_s
        self._prev_served: dict[str, float] = {}
        self._last_t: float | None = None
        self._first_t: float | None = None
        self._history: list[tuple[float, float]] = []  # (t, completed delta)

    async def epp_queue_size(self) -> float:
        return float(self.fleet.flow.queue_depth())

    async def collect(self) -> PoolSnapshot:
        now = clock.monotonic()
        if self._first_t is None:
            self._first_t = now
        dt = (now - self._last_t) if self._last_t is not None else 0.0
        self._last_t = now
        snap = PoolSnapshot(model_id=self.fleet.cfg.model_id)
        snap.epp_queue_size = float(self.fleet.flow.queue_depth())
        # Batch backlog = deferrable demand: the WVA floors the fleet on
        # it instead of scaling to zero mid-drain, and never scales UP
        # for it (docs/architecture/batch-processing.md).
        snap.batch_backlog_upstream = float(self.fleet.batch_outstanding())
        cycle_delta = 0.0
        for pod in self.fleet.store.list():
            rep = self.fleet.replicas.get(pod.address)
            if rep is None:
                continue
            r = ReplicaMetrics(
                variant=rep.variant,
                address=rep.address,
                ready=pod.healthy and rep.alive,
                # Batch-held KV is excluded from the SCALING signal:
                # backfill pressure is deferrable demand (floor, never
                # scale-up) — the scrape/EPP surface still sees the
                # full usage for watermark admission.
                kv_usage=min(
                    max(0.0, rep.kv_used_tokens - rep.batch_kv_held)
                    / max(rep.profile.kv_capacity_tokens, 1),
                    1.0,
                ),
                batch_backlog=float(rep.batch_running),
                queue_len=float(rep.waiting),
                running=float(rep.running),
                block_size=16,
                num_blocks=max(rep.profile.kv_capacity_tokens, 1) // 16,
            )
            served = float(rep.served_total)
            d = served - self._prev_served.get(rep.address, served)
            self._prev_served[rep.address] = served
            cycle_delta += max(d, 0.0)
            if d > 0:
                r.avg_input_tokens = rep.prompt_tokens_total / max(
                    rep.served_total, 1
                )
                r.avg_output_tokens = rep.output_tokens_total / max(
                    rep.served_total, 1
                )
            if dt > 0:
                r.arrival_rate = max(d, 0.0) / dt
            if isinstance(pod.attrs.get("LastTTFT"), (int, float)):
                r.avg_ttft_s = float(pod.attrs["LastTTFT"])
            if isinstance(pod.attrs.get("LastTPOT"), (int, float)):
                r.avg_itl_s = float(pod.attrs["LastTPOT"])
            snap.replicas.append(r)
        self._history.append((now, cycle_delta))
        self._history = [
            (t, d) for t, d in self._history if now - t <= self.retention_s
        ]
        if now - self._first_t >= self.retention_s:
            snap.recent_request_count = sum(d for _, d in self._history)
        else:
            snap.recent_request_count = None
        return snap


class FleetSim:
    """One scenario run: trace + fault plan in, scoreboard dict out."""

    def __init__(
        self,
        cfg: FleetConfig,
        trace: list[TraceRequest],
        fault_plan: dict | None = None,
        seed: int = 0,
        scenario: str = "adhoc",
        invariants: list | None = None,
    ) -> None:
        self.cfg = cfg
        self.trace = sorted(trace, key=lambda r: (r.t, r.request_id))
        self.fault_plan = fault_plan
        self.seed = seed
        self.scenario = scenario
        self.invariants = invariants or []
        self.board = Scoreboard(scenario, seed)
        self.store = EndpointStore()
        self.replicas: dict[str, SimReplica] = {}
        self.kv_store = (
            SimKVStore(cfg.kv_store) if cfg.kv_store is not None else None
        )
        self.pd_tier = (
            SimPrefillTier(cfg.pd) if cfg.pd is not None else None
        )
        # Adapter universe: every adapter the trace names, registered
        # ("one fetch away") on every replica — residency is the only
        # routing differentiator, exactly the pool's contract.
        self.adapter_universe = tuple(sorted(
            {r.adapter for r in trace if r.adapter is not None}
        ))
        sched_cfg = cfg.scheduler_config or default_sim_config(
            seed,
            max_inflight=cfg.flow_max_inflight,
            ttl_s=cfg.flow_ttl_s,
            fairness=cfg.fairness,
            use_predictor=cfg.use_predictor,
            lora_affinity=cfg.lora is not None and cfg.lora_affinity,
        )
        self.scheduler = epp_config.build_scheduler(sched_cfg)
        self.flow = epp_config.build_flow_control(sched_cfg)
        self.breaker = EndpointCircuitBreaker(
            cfg.breaker_threshold, cfg.breaker_cooldown_s
        )
        self._retry_rng = random.Random(seed ^ 0x5EED)
        self.producers: list = []
        if cfg.use_predictor:
            from llmd_tpu.epp.predicted_latency import (
                PredictedLatencyProducer,
            )

            self.producers.append(PredictedLatencyProducer())
        self._next_replica = 0
        self._pending_spawns = 0
        self._tasks: list[tuple[asyncio.Task, TraceRequest]] = []
        self._duration = max((r.t for r in self.trace), default=0.0)
        self.wva: WvaEngine | None = None
        # Batch tier: the standing offline queue (separate from the
        # interactive trace so interactive accounting — zero_lost, QPS,
        # latency percentiles — stays untouched by offline work).
        self.batch_trace: list[TraceRequest] = [
            TraceRequest(
                t=i * cfg.batch_arrival_stagger_s,
                request_id=f"batch-{i:05d}",
                tenant="batch",
                prompt_tokens=cfg.batch_prompt_tokens,
                output_tokens=cfg.batch_output_tokens,
                priority=BATCH_PRIORITY,
            )
            for i in range(cfg.batch_jobs)
        ]
        self._batch_tasks: list[tuple[asyncio.Task, TraceRequest]] = []

    # ---- fleet membership -------------------------------------------- #

    def _add_replica(self) -> SimReplica:
        addr = f"10.0.0.{self._next_replica}:8000"
        self._next_replica += 1
        rep = SimReplica(
            addr, self.cfg.profile,
            kv_store=self.kv_store,
            prefix_cache_groups=self.cfg.prefix_cache_groups,
            lora=self.cfg.lora,
            lora_universe=self.adapter_universe,
            pd_tier=self.pd_tier,
            moe=self.cfg.moe,
            moe_eplb=self.cfg.moe_eplb,
        )
        self.replicas[addr] = rep
        self.store.upsert(Endpoint(
            address=addr,
            labels={
                "llm-d.ai/engine-type": "llmd",
                "llm-d.ai/variant": "sim",
            },
        ))
        self.board.replicas_started.append((clock.monotonic(), addr))
        return rep

    def _remove_replica(self, addr: str) -> None:
        rep = self.replicas.get(addr)
        if rep is not None:
            rep.drain()
        # Store removal fires scheduler.notify_endpoint_removed and
        # breaker.forget via the same on_remove hooks the router wires.
        self.store.remove(addr)
        self.board.replicas_removed.append((clock.monotonic(), addr))

    # ---- autoscale actuation ----------------------------------------- #

    def _apply_decisions(self, decisions) -> None:
        desired = sum(d.desired_replicas for d in decisions)
        self.board.record_autoscale(clock.monotonic(), desired)
        live = [
            a for a in self.store.list()
            if self.replicas.get(a.address) is not None
            and self.replicas[a.address].alive
        ]
        current = len(live) + self._pending_spawns
        loop = asyncio.get_event_loop()
        for _ in range(max(0, desired - current)):
            self._pending_spawns += 1

            def _spawn() -> None:
                self._pending_spawns -= 1
                self._add_replica()

            loop.call_later(self.cfg.profile.startup_s, _spawn)
        for _ in range(max(0, current - desired)):
            if not live:
                break
            pod = live.pop()  # newest registered goes first
            self._remove_replica(pod.address)

    # ---- chaos pump --------------------------------------------------- #

    async def _chaos_ticker(self) -> None:
        """Consults the fleet-scoped FaultPlan sites on a fixed virtual
        cadence: spec trigger counts (after/times) translate to
        deterministic simulated kill times."""
        while True:
            await asyncio.sleep(self.cfg.chaos_tick_s)
            for pod in self.store.list():
                rep = self.replicas.get(pod.address)
                if rep is None or not rep.alive:
                    continue
                if faults.fires("replica.crash", pod.address):
                    rep.kill()
                    self.board.record_kill(pod.address, clock.monotonic())

    # ---- the request path (mirrors Router._route_and_proxy) ----------- #

    def _prompt_text(self, treq: TraceRequest) -> str:
        """Unique prompt text: head identifies the request (so approx
        prefix hashing sees cold prompts, engaging no-hit-lru spread),
        padding makes approx_prompt_tokens track the trace's size.
        Shared-prefix requests instead lead with their group id padded
        to the prefix length, so the router's approximate prefix
        scorer sees EXACTLY the overlap the store tier models."""
        total = treq.prompt_tokens * 4
        if (
            treq.prefix_group
            and treq.prefix_tokens > 0
            and self.cfg.prefix_affinity_text
        ):
            head_len = min(total, treq.prefix_tokens * 4)
            head = (treq.prefix_group + ":") * (
                head_len // (len(treq.prefix_group) + 1) + 1
            )
            tail = f"{treq.tenant}:{treq.request_id}:"
            pad = max(0, total - head_len - len(tail))
            return head[:head_len] + tail + "x" * pad
        pad = max(0, total - len(treq.request_id) - 8)
        return f"{treq.tenant}:{treq.request_id}:" + "x" * pad

    async def _handle(self, treq: TraceRequest) -> None:
        req = LLMRequest(
            request_id=treq.request_id,
            model=self.cfg.model_id,
            prompt_text=self._prompt_text(treq),
            priority=treq.priority,
            fairness_id=treq.tenant,
            ttft_slo_ms=treq.ttft_slo_ms,
            # Adapter requests name their adapter as the model id (the
            # vLLM convention the lora-affinity scorer keys on).
            body=(
                {"model": treq.adapter} if treq.adapter is not None else {}
            ),
        )
        outcome = await self.flow.enqueue_and_wait(
            req, nbytes=treq.prompt_tokens
        )
        if outcome is not Outcome.DISPATCHED:
            self.board.record_outcome(treq.tenant, f"flow-{outcome.value}")
            return
        try:
            for producer in self.producers:
                await producer.produce(req, self.store.list())
            await self._route(req, treq)
        finally:
            self.flow.release()

    async def _route(self, req: LLMRequest, treq: TraceRequest) -> None:
        tried: set[str] = set()
        prev_backoff = self.cfg.retry_backoff_s
        first_fail_after_kill: float | None = None
        t_arrival = clock.monotonic()
        client_first: float | None = None  # first byte the CLIENT saw
        delivered: list[int] = []  # stitched client stream (all legs)
        pre_failures = 0
        resumes = 0
        resume_pending = False  # next first-byte measures the resume TTFT
        resume_cold_s = 0.0
        while True:
            pods = eligible_pods(self.store.list(), tried, self.breaker)
            try:
                result = self.scheduler.schedule(req, pods)
            except NoEndpointsError:
                self.board.record_outcome(
                    treq.tenant,
                    "stream-interrupted" if delivered else "no-endpoints",
                )
                return
            pod = result.primary
            tried.add(pod.address)
            if not self.breaker.take_probe(pod.address):
                # Same dispatch-time gate as the router's proxy leg:
                # a contested half-open probe means re-pick, not fail.
                continue
            replica = self.replicas.get(pod.address)
            pod.inflight += 1
            pod.inflight_tokens += req.approx_prompt_tokens
            t0 = clock.monotonic()
            first: float | None = None
            try:
                # The same injection site the router's proxy leg consults.
                if replica is None or faults.fires(
                    "epp.endpoint.refuse", pod.address
                ):
                    raise ReplicaUnreachable(pod.address)
                async for toks in replica.serve(
                    req.request_id, treq.prompt_tokens, treq.output_tokens,
                    prefix_group=treq.prefix_group,
                    prefix_tokens=treq.prefix_tokens,
                    resume_tokens=len(delivered),
                    adapter=treq.adapter,
                    expert=treq.expert,
                ):
                    if first is None:
                        first = clock.monotonic()
                        if client_first is None:
                            client_first = first
                        if resume_pending:
                            # Continuation TTFT measured from the leg's
                            # dispatch (the jittered backoff is protocol
                            # overhead both sides of the comparison
                            # would pay): store-fetch + tail prefill vs
                            # the deterministic full-recompute cost of
                            # prompt + delivered history.
                            self.board.record_resume_ttft(
                                first - t0, resume_cold_s
                            )
                            resume_pending = False
                    delivered.extend(toks)
                done = clock.monotonic()
                self.breaker.record_success(pod.address)
                ttft_s = (
                    client_first if client_first is not None else done
                ) - t_arrival
                tpot_ms = None
                if treq.output_tokens > 1 and client_first is not None:
                    tpot_ms = (
                        (done - client_first) * 1e3 / (treq.output_tokens - 1)
                    )
                pod.attrs["LastTTFT"] = (
                    first if first is not None else done
                ) - t0
                pod.attrs["LastE2E"] = done - t0
                if tpot_ms is not None:
                    pod.attrs["LastTPOT"] = tpot_ms / 1e3
                self.scheduler.notify_complete(req, pod)
                for producer in self.producers:
                    await producer.on_complete(
                        req, pod, ttft_s * 1e3, tpot_ms
                    )
                if first_fail_after_kill is not None and first is not None:
                    self.board.record_reroute(first - first_fail_after_kill)
                # Stitched-stream parity: the client's accumulated
                # tokens must equal the uninterrupted baseline — a
                # resume that restarted at the wrong position is
                # CORRUPTION, not recovery, and counts client-visible.
                if delivered != expected_stream(
                    req.request_id, treq.output_tokens
                ):
                    self.board.record_parity_failure(req.request_id)
                    self.board.record_outcome(treq.tenant, "stream-corrupt")
                    return
                self.board.record_completion(
                    treq.tenant, pod.address, ttft_s, tpot_ms,
                    pre_failures + resumes,
                )
                return
            except (ReplicaUnreachable, ReplicaDied):
                self.breaker.record_failure(pod.address)
                if pod.address in self.board.kills and self.breaker.is_open(
                    pod.address
                ):
                    self.board.record_breaker_open(
                        pod.address, clock.monotonic()
                    )
                if first is not None or delivered:
                    # Bytes already reached the client. The continuation
                    # protocol (fault-tolerance.md) replays the
                    # delivered history on a fresh replica — the client
                    # sees a pause, not an error — bounded by the
                    # max_resumes budget.
                    if first is not None:
                        self.board.record_mid_stream_failure()
                        if resumes >= self.cfg.max_resumes:
                            self.board.record_outcome(
                                treq.tenant, "stream-interrupted"
                            )
                            return
                        resumes += 1
                        self.board.record_resume(len(delivered))
                        tried = {pod.address}
                        resume_pending = True
                        resume_cold_s = (
                            treq.prompt_tokens + len(delivered)
                        ) / self.cfg.profile.prefill_tok_s
                    elif pre_failures + 1 >= self.cfg.max_schedule_attempts:
                        # A resume leg that failed before its first
                        # byte ran out of pre-stream budget.
                        self.board.record_outcome(
                            treq.tenant, "stream-interrupted"
                        )
                        return
                    else:
                        pre_failures += 1
                    pod.healthy = False
                    prev_backoff = backoff_delay(
                        prev_backoff,
                        self.cfg.retry_backoff_s,
                        self.cfg.retry_backoff_cap_s,
                        self._retry_rng,
                    )
                    await asyncio.sleep(prev_backoff)
                    continue
                # Nothing streamed: treat like a failed scrape and
                # re-pick (the production connection-error branch).
                pod.healthy = False
                if pod.address in self.board.kills and (
                    first_fail_after_kill is None
                ):
                    first_fail_after_kill = clock.monotonic()
                pre_failures += 1
                if pre_failures >= self.cfg.max_schedule_attempts:
                    break
                prev_backoff = backoff_delay(
                    prev_backoff,
                    self.cfg.retry_backoff_s,
                    self.cfg.retry_backoff_cap_s,
                    self._retry_rng,
                )
                await asyncio.sleep(prev_backoff)
            finally:
                pod.inflight = max(0, pod.inflight - 1)
                pod.inflight_tokens = max(
                    0, pod.inflight_tokens - req.approx_prompt_tokens
                )
        self.board.record_outcome(treq.tenant, "all-endpoints-failed")

    # ---- the batch tier (offline backfill) ---------------------------- #

    def batch_outstanding(self) -> int:
        """Jobs enqueued but not yet terminally completed/failed — the
        backlog the WVA counts as deferrable demand."""
        b = self.board
        return b.batch_enqueued - b.batch_completed - b.batch_failed

    async def _route_batch(self, req: LLMRequest, treq: TraceRequest) -> bool:
        """One offer of a batch job to the fleet through the REAL
        scheduler (the production chain's batch-saturation-filter gates
        it by watermark). False = nothing below the watermark / the pick
        failed — the caller re-offers after a backoff; offline jobs are
        idempotent, so a cut stream simply retries whole."""
        pods = eligible_pods(self.store.list(), set(), self.breaker)
        try:
            result = self.scheduler.schedule(req, pods)
        except NoEndpointsError:
            return False
        pod = result.primary
        if not self.breaker.take_probe(pod.address):
            return False
        replica = self.replicas.get(pod.address)
        pod.inflight += 1
        pod.inflight_tokens += req.approx_prompt_tokens
        try:
            if replica is None:
                raise ReplicaUnreachable(pod.address)
            async for _ in replica.serve_batch(
                req.request_id, treq.prompt_tokens, treq.output_tokens
            ):
                pass
            self.breaker.record_success(pod.address)
            self.board.record_batch_completion(
                pod.address, treq.output_tokens, clock.monotonic()
            )
            return True
        except (ReplicaUnreachable, ReplicaDied):
            self.breaker.record_failure(pod.address)
            return False
        finally:
            pod.inflight = max(0, pod.inflight - 1)
            pod.inflight_tokens = max(
                0, pod.inflight_tokens - req.approx_prompt_tokens
            )

    async def _handle_batch(self, treq: TraceRequest) -> None:
        self.board.record_batch_enqueued()
        attempts = 0
        while True:
            req = LLMRequest(
                request_id=f"{treq.request_id}-a{attempts}",
                model=self.cfg.model_id,
                prompt_text=self._prompt_text(treq),
                priority=treq.priority,
                fairness_id=treq.tenant,
            )
            outcome = await self.flow.enqueue_and_wait(
                req, nbytes=treq.prompt_tokens
            )
            if outcome is Outcome.DISPATCHED:
                try:
                    if await self._route_batch(req, treq):
                        return
                finally:
                    self.flow.release()
            elif outcome is Outcome.EVICTED_SHUTDOWN:
                self.board.record_batch_failed(outcome.value)
                return
            # capacity-rejected / TTL-evicted / above-watermark: the job
            # stays in the backlog and re-offers after the backoff.
            attempts += 1
            self.board.record_batch_retry()
            await asyncio.sleep(self.cfg.batch_retry_s)

    async def _pump_batch(self) -> None:
        loop = asyncio.get_event_loop()
        for treq in self.batch_trace:
            delay = treq.t - loop.time()
            if delay > 0:
                await asyncio.sleep(delay)
            self._batch_tasks.append(
                (asyncio.ensure_future(self._handle_batch(treq)), treq)
            )

    async def _util_ticker(self) -> None:
        """Samples fleet decode utilization (interactive + batch output
        tokens served per unit of live decode capacity) and the batch
        backlog — the series behind the trough-utilization-floor and
        monotone-drain invariants."""
        prev = 0.0
        while True:
            await asyncio.sleep(self.cfg.util_sample_s)
            reps = [r for r in self.replicas.values() if r.alive]
            served = sum(
                r.output_tokens_total + r.batch_tokens_total
                for r in self.replicas.values()
            )
            cap = (
                max(1, len(reps))
                * self.cfg.profile.decode_tok_s
                * self.cfg.util_sample_s
            )
            util = max(0.0, served - prev) / cap
            prev = served
            self.board.record_util_sample(
                clock.monotonic(), util, self.batch_outstanding(),
                len(reps),
            )

    # ---- the run ------------------------------------------------------ #

    async def _pump(self) -> None:
        loop = asyncio.get_event_loop()
        for treq in self.trace:
            delay = treq.t - loop.time()
            if delay > 0:
                await asyncio.sleep(delay)
            self.board.record_arrival(treq.tenant)
            self._tasks.append(
                (asyncio.ensure_future(self._handle(treq)), treq)
            )

    async def _run(self) -> dict:
        fail_open_base = filters_mod.fail_open_total()
        if self.fault_plan is not None:
            faults.arm(faults.FaultPlan(
                [faults.FaultSpec(**s) for s in
                 self.fault_plan.get("faults", [])],
                seed=int(self.fault_plan.get("seed", self.seed)),
            ))
        else:
            faults.disarm()
        collector = _SimCollector(
            self,
            interval_s=self.cfg.scrape_interval_s,
            unhealthy_after=self.cfg.unhealthy_after,
            engine_type_default="llmd",
        )
        wva_collector: _SimWvaCollector | None = None
        try:
            self.store.on_remove(self.scheduler.notify_endpoint_removed)
            self.store.on_remove(self.breaker.forget)
            for _ in range(self.cfg.replicas):
                self._add_replica()
            self.board.replicas_started.clear()  # the seed fleet is free
            if self.flow.saturation.pool_stats is None:
                self.flow.saturation.pool_stats = self._pool_stats
            await collector.scrape_once()
            collector.start()
            self.flow.start()
            chaos = asyncio.ensure_future(self._chaos_ticker())
            batch_pump = util_task = None
            if self.cfg.batch_jobs or self.cfg.sample_util:
                util_task = asyncio.ensure_future(self._util_ticker())
            if self.cfg.batch_jobs:
                batch_pump = asyncio.ensure_future(self._pump_batch())
            if self.cfg.autoscale is not None:
                asc = self.cfg.autoscale
                wva_collector = _SimWvaCollector(self, asc.retention_s)
                self.wva = WvaEngine(
                    wva_collector,
                    {self.cfg.model_id: [VariantSpec(
                        name="sim",
                        cost=1.0,
                        min_replicas=asc.min_replicas,
                        max_replicas=asc.max_replicas,
                        max_batched_tokens=2048,
                        max_num_seqs=self.cfg.profile.max_batch,
                    )]},
                    analyzer=asc.analyzer,
                    interval_s=asc.interval_s,
                    scale_from_zero_interval_s=asc.sfz_interval_s,
                    scale_to_zero=asc.scale_to_zero,
                    actuator=self._apply_decisions,
                )
                self.wva.start()
            await self._pump()
            if self._tasks:
                done, pending = await asyncio.wait(
                    [t for t, _ in self._tasks],
                    timeout=self.cfg.grace_s,
                )
                for task, treq in self._tasks:
                    if task in pending:
                        self.board.record_hung(treq.request_id)
                        task.cancel()
                    elif task.done() and not task.cancelled():
                        exc = task.exception()
                        if exc is not None:
                            raise exc
            if batch_pump is not None:
                await batch_pump
            if self._batch_tasks:
                done, pending = await asyncio.wait(
                    [t for t, _ in self._batch_tasks],
                    timeout=self.cfg.grace_s,
                )
                for task, treq in self._batch_tasks:
                    if task in pending:
                        self.board.record_batch_hung(treq.request_id)
                        task.cancel()
                    elif task.done() and not task.cancelled():
                        exc = task.exception()
                        if exc is not None:
                            raise exc
            if self.cfg.idle_tail_s > 0:
                await asyncio.sleep(self.cfg.idle_tail_s)
            chaos.cancel()
            if util_task is not None:
                util_task.cancel()
            if self.wva is not None:
                await self.wva.stop()
            await collector.stop()
            await self.flow.drain()
            injected = faults.injected_counts()
        finally:
            faults.disarm()
        recompute = sum(
            r.recompute_fallbacks for r in self.replicas.values()
        )
        extra = None
        if self.cfg.lora is not None:
            from llmd_tpu.fleetsim.scoreboard import percentile

            reps = list(self.replicas.values())
            hits = sum(r.lora_hits for r in reps)
            cold = sum(r.lora_cold_loads for r in reps)
            stalls = sorted(
                s for r in reps for s in r.lora_cold_stall_s
            )
            extra = {"lora": {
                "adapters": len(self.adapter_universe),
                "pool_slots": self.cfg.lora.slots,
                "resident_hits": hits,
                "cold_loads": cold,
                "evictions": sum(r.lora_evictions for r in reps),
                "pinned_evictions": sum(
                    r.lora_pinned_evictions for r in reps
                ),
                # THE affinity headline: the fraction of adapter
                # requests that found their adapter already resident.
                "hit_ratio": hits / max(hits + cold, 1),
                "cold_stall_p50_ms": percentile(stalls, 0.50) * 1e3,
                "cold_stall_p99_ms": percentile(stalls, 0.99) * 1e3,
            }}
        if self.pd_tier is not None:
            from llmd_tpu.fleetsim.scoreboard import percentile

            reps = list(self.replicas.values())
            extra = dict(extra or {})
            imports = sorted(
                s for r in reps for s in r.pd_import_s
            )
            firsts = sorted(
                s for r in reps for s in r.pd_first_group_s
            )
            extra["pd_transfer"] = {
                "prefill_tier": self.pd_tier.stats(),
                "imports": sum(r.pd_imports for r in reps),
                "drops": sum(r.pd_drops for r in reps),
                "recomputes": sum(r.pd_recomputes for r in reps),
                "stream_groups": self.cfg.pd.stream_groups,
                "import_p50_ms": percentile(imports, 0.50) * 1e3,
                # The admission gate the streamed wire opens early —
                # the serial TTFT leg, far under the full import.
                "first_group_p50_ms": percentile(firsts, 0.50) * 1e3,
            }
        if self.cfg.moe is not None:
            reps = list(self.replicas.values())
            extra = dict(extra or {})
            n = sum(r.moe_skew_n for r in reps)
            extra["expert_skew"] = {
                "experts": self.cfg.moe.num_experts,
                "ep_world": self.cfg.moe.world,
                "eplb": self.cfg.moe_eplb,
                "routed_tokens": sum(r.moe_routed_tokens for r in reps),
                # Capacity overflow under the run's placements — the
                # skew-proof-capacity headline the EPLB leg must beat
                # the identity-layout leg on.
                "dropped_slots": sum(r.moe_dropped_slots for r in reps),
                "rebalances": sum(r.moe_rebalances for r in reps),
                # max/mean per-shard load, sampled at every dispatch:
                # the peak includes the pre-first-rebalance window, the
                # mean is the run-long balance the gates bound.
                "peak_shard_skew": round(
                    max((r.moe_peak_skew for r in reps), default=1.0), 4
                ),
                "mean_shard_skew": round(
                    sum(r.moe_skew_sum for r in reps) / n, 4
                ) if n else 1.0,
            }
        if (
            self.cfg.profile.cp_degree > 1
            or self.cfg.profile.kv_window_tokens > 0
        ):
            reps = list(self.replicas.values())
            extra = dict(extra or {})
            extra["long_context"] = {
                "cp_degree": self.cfg.profile.cp_degree,
                "kv_window_tokens": self.cfg.profile.kv_window_tokens,
                "kv_capacity_tokens": self.cfg.profile.kv_capacity_tokens,
                "cp_ring_prefills": sum(r.cp_ring_prefills for r in reps),
                # Pager engagement + the residency headline: tokens whose
                # KV spilled to the host tier, and the worst any
                # replica's resident KV ever got (the kv_peak gate holds
                # this against capacity — window bytes, not context
                # bytes).
                "kv_paged_out_tokens": sum(
                    r.kv_paged_out_tokens for r in reps
                ),
                "peak_kv_tokens": max(
                    (r.kv_peak_tokens for r in reps), default=0.0
                ),
            }
        if self.kv_store is not None:
            reps = list(self.replicas.values())
            extra = dict(extra or {})
            extra["kv_federation"] = {
                "store": self.kv_store.stats(),
                "recompute_avoided_tokens": sum(
                    r.recompute_avoided_tokens for r in reps
                ),
                "store_hits": sum(r.store_hits for r in reps),
                "store_published": sum(r.store_published for r in reps),
                "local_prefix_hits": sum(r.prefix_local_hits for r in reps),
            }
        return self.board.finalize(
            duration_s=max(self._duration, 1e-9),
            invariants=self.invariants,
            fail_open_count=filters_mod.fail_open_total() - fail_open_base,
            breaker_trips=self.breaker.trips_total,
            breaker_opened=sorted(self.board.breaker_open_after_kill_s),
            faults_injected=injected,
            recompute_fallbacks=recompute,
            extra=extra,
        )

    def _pool_stats(self) -> tuple[float, float]:
        pods = self.store.list()
        if not pods:
            return 1.0, float("inf")  # empty pool counts as saturated
        kv = sum(p.attr(KV_CACHE_USAGE) for p in pods) / len(pods)
        q = sum(p.attr(WAITING_QUEUE_SIZE) for p in pods) / len(pods)
        return kv, q

    def run(self) -> dict:
        """Execute the scenario on a fresh virtual-time loop."""
        return simloop.run(self._run())
