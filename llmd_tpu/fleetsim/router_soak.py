"""Router soak: the REAL ``epp/server.py`` aiohttp router under chaos.

The pure-simulation scenarios (:mod:`llmd_tpu.fleetsim.sim`) MIRROR the
router's ``_route_and_proxy`` semantics; this scenario removes the
mirror. The production :class:`~llmd_tpu.epp.server.Router` — parser,
flow control, scheduler plugin chain, breaker, decorrelated-jitter
retry, the proxy byte loop, and the mid-stream resume protocol — serves
real HTTP over loopback sockets ON the virtual-time loop
(:class:`~llmd_tpu.fleetsim.simloop.SimEventLoop` treats socket I/O as
instantaneous in virtual time; pacing comes from virtual timers). The
production ``MetricsCollector`` scrapes the replicas' real ``/metrics``
pages over the same sockets. Only the engines are stubs:
:class:`StubReplicaServer` speaks the OpenAI SSE surface with
position-addressable token streams (:func:`~.engines.stream_token`),
honors the ``resume_token_ids`` replay contract and the
``x-llmd-stream-tokens`` annotation header, and can be killed
mid-stream — severing live transports exactly like a crashed engine.

Gates (content invariants, not byte-compared scoreboards — kernel-side
socket readiness ordering is outside the program): kills fired, ZERO
client-visible stream failures, resumes > 0 through the real proxy leg,
and every stitched client stream byte-identical to the uninterrupted
expectation.
"""

from __future__ import annotations

import asyncio
import json
import logging
import random

from aiohttp import web

from llmd_tpu import clock, faults
from llmd_tpu.epp import config as epp_config
from llmd_tpu.epp.datalayer import EndpointStore, MetricsCollector
from llmd_tpu.epp.server import Router
from llmd_tpu.epp.types import HDR_STREAM_TOKENS, Endpoint
from llmd_tpu.fleetsim import simloop
from llmd_tpu.fleetsim.engines import expected_stream, stream_token
from llmd_tpu.fleetsim.scoreboard import Scoreboard
from llmd_tpu.fleetsim.sim import default_sim_config
from llmd_tpu.fleetsim.traces import TraceRequest, generate

log = logging.getLogger(__name__)


class StubReplicaServer:
    """One engine replica as a real aiohttp server on a loopback port.

    Implements just enough of the model-server contract for the router
    path under test: ``POST /v1/completions`` (streaming SSE, token ids
    annotated under the :data:`HDR_STREAM_TOKENS` contract, the
    ``resume_token_ids`` replay admission) and ``GET /metrics`` (the
    llmd engine family the production collector parses). Token values
    are position-addressable (:func:`stream_token`), so a resume that
    continues at the wrong output position corrupts the stitched stream
    — which the driver's parity gate catches.
    """

    def __init__(self, name: str, tpot_s: float = 0.004,
                 prefill_s: float = 0.01) -> None:
        self.name = name
        self.tpot_s = tpot_s
        self.prefill_s = prefill_s
        self.alive = True
        self.running = 0
        self.served_total = 0
        self.resumes_served = 0
        self._transports: set = set()
        self._runner: web.AppRunner | None = None
        self.address = ""  # host:port once started

    async def start(self) -> None:
        app = web.Application()
        app.add_routes([
            web.post("/v1/completions", self.handle_completions),
            web.get("/metrics", self.handle_metrics),
        ])
        self._runner = web.AppRunner(app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, "127.0.0.1", 0)
        await site.start()
        port = site._server.sockets[0].getsockname()[1]
        self.address = f"127.0.0.1:{port}"

    async def stop(self) -> None:
        if self._runner is not None:
            await self._runner.cleanup()

    def kill(self) -> None:
        """Crash: sever every live stream's transport (no SSE
        terminator — the router's upstream read loop sees a truncated
        payload, the mid-stream failure shape) and refuse new work."""
        self.alive = False
        for tr in list(self._transports):
            tr.close()

    # ---- handlers ----------------------------------------------------- #

    async def handle_completions(self, request: web.Request) -> web.StreamResponse:
        if not self.alive:
            raise web.HTTPServiceUnavailable(text="replica dead")
        body = await request.json()
        rid = request.headers.get("x-request-id", "anon")
        max_tokens = int(body.get("max_tokens", 8))
        resume = list(body.get("resume_token_ids") or [])
        annotate = request.headers.get(HDR_STREAM_TOKENS, "") == "1"
        resp = web.StreamResponse(
            headers={"Content-Type": "text/event-stream",
                     "Cache-Control": "no-cache"}
        )
        await resp.prepare(request)
        if request.transport is not None:
            self._transports.add(request.transport)
        self.running += 1
        try:
            # Prefill pace (virtual time), then one token per frame.
            await asyncio.sleep(self.prefill_s)
            for i in range(len(resume), max_tokens):
                if i > len(resume):
                    await asyncio.sleep(self.tpot_s)
                if not self.alive:
                    # Crash landed between frames: stop emitting; the
                    # severed transport surfaces the cut downstream.
                    return resp
                tok = stream_token(rid, i)
                frame = {
                    "id": rid,
                    "object": "text_completion",
                    "choices": [{"index": 0, "text": f"{tok:04x} ",
                                 "finish_reason": None}],
                }
                if annotate:
                    frame["token_ids"] = [tok]
                await resp.write(
                    b"data: "
                    + json.dumps(frame, separators=(",", ":")).encode()
                    + b"\n\n"
                )
            final = {
                "id": rid,
                "object": "text_completion",
                "choices": [{"index": 0, "text": "",
                             "finish_reason": "length"}],
                "usage": {"prompt_tokens": 0,
                          "completion_tokens": max_tokens},
            }
            await resp.write(
                b"data: " + json.dumps(final, separators=(",", ":")).encode()
                + b"\n\n"
            )
            await resp.write(b"data: [DONE]\n\n")
            await resp.write_eof()
            self.served_total += 1
            if resume:
                self.resumes_served += 1
            return resp
        except (ConnectionResetError, RuntimeError):
            # Client (the router) went away or our transport was
            # severed by kill(): nothing further to write.
            return resp
        finally:
            self.running -= 1
            if request.transport is not None:
                self._transports.discard(request.transport)

    async def handle_metrics(self, request: web.Request) -> web.Response:
        return web.Response(
            text=(
                f"llmd:num_requests_waiting 0\n"
                f"llmd:num_requests_running {self.running}\n"
                f"llmd:gpu_cache_usage_perc 0.05\n"
                "llmd:prefix_cache_hit_rate 0.0\n"
                'llmd:cache_config_info{block_size="16",'
                'num_gpu_blocks="2048"} 1\n'
            ),
            content_type="text/plain",
        )


class RouterSoak:
    """One router-soak run: real Router + stub HTTP replicas + chaos."""

    def __init__(
        self,
        trace: list[TraceRequest],
        replicas: int = 3,
        kill_at_s: float = 0.5,
        kills: int = 1,
        max_resumes: int = 2,
        seed: int = 0,
        scenario: str = "router_soak",
        invariants: list | None = None,
        grace_s: float = 60.0,
    ) -> None:
        self.trace = sorted(trace, key=lambda r: (r.t, r.request_id))
        self.n_replicas = replicas
        self.kill_at_s = kill_at_s
        self.kills = kills
        self.max_resumes = max_resumes
        self.seed = seed
        self.invariants = invariants or []
        self.grace_s = grace_s
        self.board = Scoreboard(scenario, seed)
        self._duration = max((r.t for r in self.trace), default=0.0)

    async def _drive_request(self, session, base, treq: TraceRequest) -> None:
        body = {
            "model": "sim",
            "prompt": f"{treq.tenant}:{treq.request_id}:" + "x" * 64,
            "max_tokens": treq.output_tokens,
            "stream": True,
            "temperature": 0.0,
        }
        t0 = clock.monotonic()
        first: float | None = None
        tokens: list[int] = []
        err = None
        try:
            async with session.post(
                f"{base}/v1/completions", json=body,
                headers={"x-request-id": treq.request_id},
            ) as r:
                if r.status != 200:
                    self.board.record_outcome(
                        treq.tenant, f"http-{r.status}"
                    )
                    return
                carry = b""
                async for chunk in r.content.iter_any():
                    if first is None:
                        first = clock.monotonic()
                    lines = (carry + chunk).split(b"\n")
                    carry = lines.pop()
                    for ln in lines:
                        if not ln.startswith(b"data: ") or b"[DONE]" in ln:
                            continue
                        d = json.loads(ln[6:])
                        if "error" in d:
                            err = d["error"]
                            continue
                        assert "token_ids" not in d, (
                            "router leaked token annotations to the client"
                        )
                        text = (d.get("choices") or [{}])[0].get("text") or ""
                        tokens.extend(
                            int(t, 16) for t in text.split() if t
                        )
        except (OSError, asyncio.TimeoutError, json.JSONDecodeError) as e:
            self.board.record_outcome(treq.tenant, "client-error")
            log.warning("client leg failed for %s: %r", treq.request_id, e)
            return
        if err is not None:
            self.board.record_outcome(treq.tenant, "stream-interrupted")
            return
        if tokens != expected_stream(treq.request_id, treq.output_tokens):
            self.board.record_parity_failure(treq.request_id)
            self.board.record_outcome(treq.tenant, "stream-corrupt")
            return
        done = clock.monotonic()
        ttft = (first if first is not None else done) - t0
        tpot_ms = None
        if treq.output_tokens > 1 and first is not None:
            tpot_ms = (done - first) * 1e3 / (treq.output_tokens - 1)
        self.board.record_completion(treq.tenant, "router", ttft, tpot_ms, 0)

    async def _run(self) -> dict:
        import aiohttp

        faults.disarm()
        replicas = [
            StubReplicaServer(f"stub-{i}") for i in range(self.n_replicas)
        ]
        for rep in replicas:
            await rep.start()
        store = EndpointStore()
        for rep in replicas:
            store.upsert(Endpoint(
                address=rep.address,
                labels={"llm-d.ai/engine-type": "llmd"},
            ))
        cfg = default_sim_config(self.seed)
        router = Router(
            store=store,
            scheduler=epp_config.build_scheduler(cfg),
            flow_control=epp_config.build_flow_control(cfg),
            collector=MetricsCollector(store, interval_s=0.25),
            retry_backoff_s=0.005,
            retry_backoff_cap_s=0.25,
            retry_rng=random.Random(self.seed ^ 0x5EED),
            max_resumes=self.max_resumes,
        )
        runner = web.AppRunner(router.build_app())
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", 0)
        await site.start()
        port = site._server.sockets[0].getsockname()[1]
        base = f"http://127.0.0.1:{port}"
        session = aiohttp.ClientSession(
            timeout=aiohttp.ClientTimeout(total=300, sock_connect=30)
        )
        tasks: list[tuple[asyncio.Task, TraceRequest]] = []

        async def chaos() -> None:
            await asyncio.sleep(self.kill_at_s)
            for rep in replicas[: self.kills]:
                rep.kill()
                self.board.record_kill(rep.address, clock.monotonic())

        chaos_task = asyncio.ensure_future(chaos())
        try:
            loop = asyncio.get_event_loop()
            for treq in self.trace:
                delay = treq.t - loop.time()
                if delay > 0:
                    await asyncio.sleep(delay)
                self.board.record_arrival(treq.tenant)
                tasks.append((
                    asyncio.ensure_future(
                        self._drive_request(session, base, treq)
                    ),
                    treq,
                ))
            if tasks:
                done, pending = await asyncio.wait(
                    [t for t, _ in tasks], timeout=self.grace_s
                )
                for task, treq in tasks:
                    if task in pending:
                        self.board.record_hung(treq.request_id)
                        task.cancel()
                    elif task.done() and not task.cancelled():
                        exc = task.exception()
                        if exc is not None:
                            raise exc
        finally:
            chaos_task.cancel()
            await session.close()
            await runner.cleanup()
            for rep in replicas:
                await rep.stop()
        # The router's OWN counters are the soak's resume evidence: the
        # production proxy leg detected the cut, fed the breaker, and
        # replayed the history.
        m = router.metrics
        self.board.mid_stream_failures = m.mid_stream_failures
        self.board.stream_resumes = m.stream_resumes
        self.board.resume_replayed_tokens = m.resume_replayed_tokens
        for addr in self.board.kills:
            if router.breaker.is_open(addr) or addr in (
                router.breaker.open_endpoints()
            ):
                self.board.record_breaker_open(addr, clock.monotonic())
        return self.board.finalize(
            duration_s=max(self._duration, 1e-9),
            invariants=self.invariants,
            breaker_trips=router.breaker.trips_total,
            breaker_opened=sorted(router.breaker.open_endpoints()),
            extra={
                "router": {
                    "mid_stream_failures": m.mid_stream_failures,
                    "stream_resumes": m.stream_resumes,
                    "resume_replayed_tokens": m.resume_replayed_tokens,
                    "stream_resume_failures": m.stream_resume_failures,
                    "proxy_errors": m.proxy_errors,
                    "resumes_served_by_stubs": sum(
                        r.resumes_served for r in replicas
                    ),
                },
            },
        )

    def run(self) -> dict:
        return simloop.run(self._run())
