"""The chaos-soak scenario matrix: seeded scenarios with hard invariants.

Each scenario is a factory: (seed, qps_scale) -> a fully-wired
:class:`~llmd_tpu.fleetsim.sim.FleetSim` whose scoreboard carries
pass/fail invariant results. The matrix is the CI `soak` job's contract
(docs/architecture/fleet-soak.md carries the scenario -> invariant ->
bound table; docs/architecture/fault-tolerance.md the fleet-level
recovery contracts):

========== ==========================================================
steady      16 replicas, 10^4 QPS flat: SLO bands hold, zero lost,
            four equal tenants complete fairly.
burst       one tenant floods 5x over the middle of the window while
            three light tenants keep steady rates: flow-control
            fairness must keep the light tenants whole under pressure.
diurnal     day-shaped rate over the WVA autoscaler: scale-up reacts
            within bounded sim time, no decision oscillation, and the
            trough tail scales to zero.
replica_kill two replicas crash mid-stream at ~0.8 s under 10^4 QPS
            with the store tier armed: ZERO client-visible stream
            failures — cut streams RESUME on a fresh replica
            byte-identically (resumes > 0, stitched parity pinned),
            resume TTFT beats cold recompute (the store holds the
            prefix), breaker opens for the dead addresses within the
            scrape window, time-to-reroute bounded, nothing lost.
brownout    one replica serves every request 200 ms slow: the scorers
            steer load off it (its completed share falls well under
            fair share) and fleet p99 stays bounded.
all_flap    every scrape fails for the whole run: the healthy-filter
            FAILS OPEN rather than 503ing a healthy fleet — requests
            keep completing.
kv_federation overlapping-tenant shared prefixes over a fleet with the
            simulated store tier armed (kv-federation.md): fresh
            prefixes publish, peers fetch instead of re-prefilling,
            recompute_avoided_tokens > 0, seeded store-leg pull drops
            degrade to recompute, zero lost.
batch_backfill diurnal interactive traffic plus a standing offline
            batch queue (batch-processing.md): jobs admitted only
            below the saturation watermark, backlog monotonically
            drained through the troughs (WVA floors the fleet on the
            backlog instead of scaling to zero), trough utilization
            floor raised, interactive zero-lost and p99 TTFT held.
long_context steady chat traffic plus a wave of 1M-token document
            jobs (long-context.md): documents prefill through the
            context-parallel ring tier (TTFT / cp_degree) and decode
            under the KV pager (resident HBM bounded by the attention
            window) — chat-tenant p99 TTFT and fleet TPOT must hold
            THROUGH the wave, every document completes, the ring and
            the pager provably engaged, and no replica's resident KV
            ever exceeds its pool capacity.
router_soak the REAL epp/server.py aiohttp router over loopback
            sockets on the virtual loop (fleet-soak follow-up (a)):
            mid-stream kills of stub HTTP replicas resume through the
            production proxy/resume leg, stitched client streams
            byte-identical, zero visible failures. Real I/O — gated on
            content invariants, excluded from the byte-compare.
pd_transfer two-tier P→D fleet (fleet-soak follow-up (b)): prompts
            prefill on a shared P tier, KV imports over a transfer leg
            with real RTT/bandwidth, group-streamed so stage/ship
            pipeline and decode admits at first-group-resident; seeded
            kv.pull.drop mid-stream degrades each hit import to local
            recompute — never lost, never corrupt, byte-deterministic.
expert_skew wide-EP MoE under Zipf expert popularity (wide-ep.md):
            requests carry a dominant routed expert; hot experts pile
            onto one EP shard under the static layout, stretching
            decode TPOT by the shard skew and overflowing the GShard
            capacity into dropped slots. The real EPLB balancer runs
            on each replica's control loop and must hold the mean
            shard skew and dropped-slot fraction that the
            identity-placement off leg (``eplb=False``) provably
            cannot — CI and the bench part compare the two legs on
            the same seeded trace.
========== ==========================================================

Trace sizes are chosen so the full matrix runs in CI minutes while the
kill/steady scenarios still exercise >= 10^4 simulated QPS (the
acceptance bar); ``qps_scale`` lets tests and the bench part run the
same scenarios at reduced scale.
"""

from __future__ import annotations

import dataclasses
from typing import Callable

from llmd_tpu.fleetsim import scoreboard as sb
from llmd_tpu.fleetsim.engines import (
    LoraPoolProfile,
    MoEProfile,
    PDTransferProfile,
    ReplicaProfile,
    StoreProfile,
)
from llmd_tpu.fleetsim.sim import AutoscaleConfig, FleetConfig, FleetSim
from llmd_tpu.fleetsim.traces import TraceRequest, generate

# One simulated replica = one chip at the BENCH_r04 headline rate
# (4,914 out tok/s); short outputs keep event counts CI-sized while the
# arrival rate carries the 10^4 QPS bar.
_PROFILE = ReplicaProfile()

TENANTS_EQUAL = tuple((f"tenant-{i}", 1.0) for i in range(4))


@dataclasses.dataclass(frozen=True)
class Scenario:
    name: str
    build: Callable[[int, float], FleetSim]
    description: str = ""


def _kill_plan(addresses: list[str], tick_s: float, at_s: float) -> list[dict]:
    """FaultPlan specs that crash ``addresses`` at ~``at_s`` sim time:
    the chaos ticker consults replica.crash once per tick per replica,
    so `after` ticks = a deterministic simulated kill time."""
    after = max(0, round(at_s / tick_s) - 1)
    return [
        {"site": "replica.crash", "match": addr, "after": after, "times": 1}
        for addr in addresses
    ]


def build_steady(seed: int = 0, qps_scale: float = 1.0) -> FleetSim:
    # Offered rate targets >= 10^4 realized QPS (the acceptance bar);
    # the generator is Poisson, so aim 5% above and gate the floor.
    qps = 10_500.0 * qps_scale
    duration = 1.6
    trace = generate(
        "steady", qps=qps, duration_s=duration, seed=seed,
        tenants=TENANTS_EQUAL, prompt_tokens=128, output_tokens=8,
    )
    cfg = FleetConfig(replicas=max(2, round(20 * qps_scale)),
                      profile=_PROFILE)
    invariants = [
        ("zero_lost", sb.inv_zero_lost),
        ("all_completed", sb.inv_all_completed(1.0)),
        ("p99_ttft", sb.inv_p99_ttft_ms(500.0)),
        ("p99_tpot", sb.inv_p99_tpot_ms(120.0)),
        ("fairness", sb.inv_fairness_jain(0.95)),
        ("offered_qps", sb.inv_min_offered_qps(10_000.0 * qps_scale)),
    ]
    return FleetSim(cfg, trace, seed=seed, scenario="steady",
                    invariants=invariants)


def build_burst(seed: int = 0, qps_scale: float = 1.0) -> FleetSim:
    # One hog tenant at 4x the light tenants' rate, bursting 5x over
    # the middle fifth: the capacity is sized so the burst saturates
    # flow control and round-robin fairness has to defend the light
    # tenants' dispatch share.
    qps = 4_000.0 * qps_scale
    duration = 2.5
    tenants = (("hog", 4.0), ("light-0", 1.0), ("light-1", 1.0),
               ("light-2", 1.0))
    trace = generate(
        "burst", qps=qps, duration_s=duration, seed=seed, tenants=tenants,
        prompt_tokens=128, output_tokens=8, burst_factor=5.0,
    )
    cfg = FleetConfig(
        replicas=max(2, round(10 * qps_scale)),
        profile=_PROFILE,
        # Tight inflight cap: the burst must QUEUE (where fairness
        # policy acts), not fan straight out to idle replicas.
        flow_max_inflight=max(64, round(2048 * qps_scale)),
        flow_ttl_s=10.0,
        grace_s=90.0,
    )
    invariants = [
        ("zero_lost", sb.inv_zero_lost),
        ("light_tenants_whole",
         sb.inv_tenant_completion(["light-0", "light-1", "light-2"], 0.98)),
        ("p99_tpot", sb.inv_p99_tpot_ms(120.0)),
    ]
    return FleetSim(cfg, trace, seed=seed, scenario="burst",
                    invariants=invariants)


def build_diurnal(seed: int = 0, qps_scale: float = 1.0) -> FleetSim:
    # Low-rate day curve over the REAL WVA pipeline: peak demand needs
    # ~4 replicas, the trough needs zero. 40 s of fleet time.
    qps = 400.0 * qps_scale
    duration = 40.0
    trace = generate(
        "diurnal", qps=qps, duration_s=duration, seed=seed,
        tenants=TENANTS_EQUAL, prompt_tokens=128, output_tokens=8,
        diurnal_floor=0.0,
    )
    cfg = FleetConfig(
        replicas=1,
        profile=dataclasses.replace(
            _PROFILE,
            decode_tok_s=_PROFILE.decode_tok_s / 4.0,
            prefill_tok_s=_PROFILE.prefill_tok_s / 4.0,
            max_batch=64,
            startup_s=1.0,
        ),
        flow_ttl_s=20.0,
        grace_s=120.0,
        idle_tail_s=20.0,
        autoscale=AutoscaleConfig(
            interval_s=2.0,
            scale_to_zero=True,
            retention_s=8.0,
            max_replicas=8,
        ),
    )
    invariants = [
        ("zero_lost", sb.inv_zero_lost),
        ("scale_up_reacts", sb.inv_scale_up_within_s(10.0)),
        ("no_oscillation", sb.inv_no_oscillation(3)),
        ("scale_to_zero", sb.inv_scale_to_zero),
    ]
    return FleetSim(cfg, trace, seed=seed, scenario="diurnal",
                    invariants=invariants)


def build_replica_kill(seed: int = 0, qps_scale: float = 1.0) -> FleetSim:
    # Shared prefixes + the store tier armed: a mid-stream resume's
    # replayed prefix rides the federation fast path (store fetch), so
    # the tightened gate can assert resume TTFT < cold recompute — the
    # stream-continuation contract end to end (fault-tolerance.md).
    qps = 10_500.0 * qps_scale
    duration = 1.6
    n = max(3, round(20 * qps_scale))
    trace = generate(
        "steady", qps=qps, duration_s=duration, seed=seed,
        tenants=TENANTS_EQUAL, prompt_tokens=192, output_tokens=8,
        prefix_groups=64, prefix_frac=0.667,
    )
    cfg = FleetConfig(replicas=n, profile=_PROFILE, grace_s=90.0,
                      kv_store=StoreProfile.from_bench(),
                      # Affinity-led routing of Zipf-hot groups is the
                      # kv_federation scenario's subject; here it would
                      # drown the failover signal in hot-replica queues.
                      prefix_affinity_text=False,
                      max_resumes=2)
    killed = ["10.0.0.1:8000", "10.0.0.2:8000"]
    plan = {
        "seed": seed,
        "faults": _kill_plan(killed, cfg.chaos_tick_s, at_s=0.8),
    }
    invariants = [
        # THE acceptance bar, tightened from "zero lost" to "zero
        # CLIENT-VISIBLE stream failures": every cut stream resumes on
        # a fresh replica byte-identically, resume TTFT beats a cold
        # recompute (the store holds the prefix), and nothing is lost.
        ("zero_lost", sb.inv_zero_lost),
        ("kills_fired", sb.inv_faults_fired("replica.crash", 2)),
        ("breaker_opened", sb.inv_breaker_opened_for_kills),
        ("time_to_reroute", sb.inv_time_to_reroute_s(1.0)),
        ("stream_continuation", sb.inv_stream_continuation(1)),
        ("resume_beats_recompute", sb.inv_resume_ttft_vs_cold),
        ("p99_ttft", sb.inv_p99_ttft_ms(800.0)),
        ("offered_qps", sb.inv_min_offered_qps(10_000.0 * qps_scale)),
    ]
    return FleetSim(cfg, trace, fault_plan=plan, seed=seed,
                    scenario="replica_kill", invariants=invariants)


def build_brownout(seed: int = 0, qps_scale: float = 1.0) -> FleetSim:
    qps = 2_000.0 * qps_scale
    duration = 2.0
    n = max(3, round(6 * qps_scale))
    trace = generate(
        "steady", qps=qps, duration_s=duration, seed=seed,
        tenants=TENANTS_EQUAL, prompt_tokens=128, output_tokens=8,
    )
    slow = "10.0.0.1:8000"
    plan = {
        "seed": seed,
        "faults": [{
            "site": "replica.brownout", "match": slow,
            "times": None, "delay_ms": 200.0,
        }],
    }
    cfg = FleetConfig(replicas=n, profile=_PROFILE, use_predictor=True,
                      grace_s=90.0)
    invariants = [
        ("zero_lost", sb.inv_zero_lost),
        ("brownouts_fired", sb.inv_faults_fired("replica.brownout", 10)),
        # Fair share would be 1/n; the queue/latency scorers must push
        # the slow replica well under it.
        ("steered_off_slow", sb.inv_brownout_steered(slow, 0.6 / n)),
        ("p99_ttft", sb.inv_p99_ttft_ms(600.0)),
    ]
    return FleetSim(cfg, trace, fault_plan=plan, seed=seed,
                    scenario="brownout", invariants=invariants)


def build_all_flap(seed: int = 0, qps_scale: float = 1.0) -> FleetSim:
    qps = 2_000.0 * qps_scale
    duration = 2.0
    trace = generate(
        "steady", qps=qps, duration_s=duration, seed=seed,
        tenants=TENANTS_EQUAL, prompt_tokens=128, output_tokens=8,
    )
    # Every scrape of every replica fails for the whole run: health
    # DATA dies while the replicas stay fine — the telemetry-gap case
    # the healthy-filter's fail-open exists for.
    plan = {
        "seed": seed,
        "faults": [{"site": "epp.scrape.fail", "times": None, "p": 1.0}],
    }
    cfg = FleetConfig(replicas=max(2, round(5 * qps_scale)),
                      profile=_PROFILE, grace_s=90.0)
    invariants = [
        ("zero_lost", sb.inv_zero_lost),
        ("scrapes_flapped", sb.inv_faults_fired("epp.scrape.fail", 10)),
        ("fail_open_engaged", sb.inv_fail_open_engaged),
        ("all_completed", sb.inv_all_completed(0.99)),
    ]
    return FleetSim(cfg, trace, fault_plan=plan, seed=seed,
                    scenario="all_flap", invariants=invariants)


def build_kv_federation(
    seed: int = 0, qps_scale: float = 1.0, store: bool = True
) -> FleetSim:
    # Overlapping tenants: every tenant draws from the SAME Zipf-ish
    # pool of 16 shared prefixes (256-token system prompts over a
    # ragged unique tail), so identical prefixes land on different
    # replicas. The per-replica prefix cache holds only 2 groups —
    # eviction pressure is the point: a prefix computed (then evicted)
    # on replica A must come back through the store on replica B, not
    # through a fleet-wide re-prefill.
    qps = 2_000.0 * qps_scale
    duration = 2.0
    n = max(3, round(6 * qps_scale))
    trace = generate(
        "steady", qps=qps, duration_s=duration, seed=seed,
        tenants=TENANTS_EQUAL, prompt_tokens=256, output_tokens=8,
        prefix_groups=16, prefix_frac=0.5,
    )
    # A seeded drop on the STORE leg only (match="store|"): dropped
    # federated pulls must degrade to recompute — slower, never wrong,
    # never lost (fault-tolerance.md).
    plan = {
        "seed": seed,
        "faults": [{
            "site": "kv.pull.drop", "match": "store|", "p": 0.05,
            "times": None,
        }],
    } if store else None
    cfg = FleetConfig(
        replicas=n,
        profile=_PROFILE,
        kv_store=StoreProfile.from_bench() if store else None,
        prefix_cache_groups=2,
        grace_s=90.0,
    )
    invariants = [
        ("zero_lost", sb.inv_zero_lost),
        ("all_completed", sb.inv_all_completed(1.0)),
    ]
    if store:
        invariants += [
            # THE federation bar: fleet-wide reuse actually happened.
            ("recompute_avoided", sb.inv_recompute_avoided(1)),
            ("store_flow", sb.inv_store_flow(1, 1)),
            ("store_drops_fired", sb.inv_faults_fired("kv.pull.drop", 1)),
            ("p99_ttft", sb.inv_p99_ttft_ms(600.0)),
        ]
    return FleetSim(cfg, trace, fault_plan=plan, seed=seed,
                    scenario="kv_federation", invariants=invariants)


def build_batch_backfill(
    seed: int = 0, qps_scale: float = 1.0, batch: bool = True
) -> FleetSim:
    # The batch-tier acceptance scenario
    # (docs/architecture/batch-processing.md): the diurnal interactive
    # day-curve over the real WVA, PLUS a standing queue of offline
    # batch jobs enqueued at t≈0 at BATCH_PRIORITY. The jobs ride the
    # REAL pipeline — the flow-control band below every interactive
    # priority, the production plugin chain whose
    # batch-saturation-filter admits them only on replicas below the
    # watermark, and the replicas' backfill serving path — and the WVA
    # counts the backlog as deferrable demand (floor at one replica
    # through troughs, never scale-up). Gates: interactive zero-lost +
    # p99 TTFT band, backlog monotonically drained to zero, and the
    # trough-utilization floor raised (the no-batch leg of the bench
    # part measures the near-zero baseline). ``batch=False`` builds the
    # identical interactive run with no batch queue — the baseline the
    # CI summary compares interactive p99 against.
    qps = 400.0 * qps_scale
    duration = 40.0
    trace = generate(
        "diurnal", qps=qps, duration_s=duration, seed=seed,
        tenants=TENANTS_EQUAL, prompt_tokens=128, output_tokens=8,
        diurnal_floor=0.0,
    )
    cfg = FleetConfig(
        replicas=1,
        profile=dataclasses.replace(
            _PROFILE,
            decode_tok_s=_PROFILE.decode_tok_s / 4.0,
            prefill_tok_s=_PROFILE.prefill_tok_s / 4.0,
            max_batch=64,
            startup_s=1.0,
        ),
        flow_ttl_s=20.0,
        grace_s=150.0,
        idle_tail_s=30.0,
        autoscale=AutoscaleConfig(
            interval_s=2.0,
            scale_to_zero=True,
            retention_s=8.0,
            max_replicas=8,
        ),
        # Sized so the drain SPANS the diurnal peak into the evening
        # trough at every qps_scale (the per-replica capacity does not
        # scale with qps_scale, so the floor keeps the standing queue
        # from emptying before the trough window opens).
        batch_jobs=max(150, round(240 * qps_scale)) if batch else 0,
        batch_prompt_tokens=64,
        batch_output_tokens=256,
        batch_retry_s=1.0,
        sample_util=True,  # the baseline leg measures the trough floor too
    )
    invariants = [
        ("zero_lost", sb.inv_zero_lost),
        # Absolute band covering the diurnal cold-ramp shape (the ramp
        # out of a scaled-down trough queues until the WVA reacts, both
        # legs alike); the real batch-neutrality gate is the on/off p99
        # RATIO the bench part + CI summary assert.
        ("p99_ttft", sb.inv_p99_ttft_ms(6000.0)),
    ]
    if batch:
        invariants += [
            ("batch_drained", sb.inv_batch_drained),
            ("batch_harvest", sb.inv_batch_harvest(
                cfg.batch_jobs * cfg.batch_output_tokens
            )),
            # Above the measured no-batch baseline (~0.14 at scale 1.0)
            # and below the batch-armed floor (~0.40 at scale 1.0,
            # ~0.25 at the test scale 0.25): the gate fails if backfill
            # stops soaking the trough.
            ("util_floor", sb.inv_trough_util(0.20)),
        ]
    else:
        # The baseline still scales to zero in the idle tail (nothing
        # defers the trough) — pinning that the batch floor, not some
        # side effect, is what keeps the batch-armed fleet warm.
        invariants.append(("scale_to_zero", sb.inv_scale_to_zero))
    return FleetSim(cfg, trace, seed=seed, scenario="batch_backfill",
                    invariants=invariants)


def build_lora_tenant(
    seed: int = 0, qps_scale: float = 1.0, affinity: bool = True
) -> FleetSim:
    # The multi-tenant LoRA acceptance scenario
    # (docs/architecture/multi-tenant-lora.md): 192 tenants, one
    # adapter each, Zipf popularity (a few hot tenants, a long warm
    # tail), over replicas whose paged adapter pools hold 32 slots —
    # fleet-wide residency capacity far below the tenant count, so
    # WHERE a tenant's requests land decides whether they pay a cold
    # load. The tri-state lora-affinity scorer routes on the residency
    # the production MetricsCollector scrapes off the replicas'
    # lora_requests_info labels; gates: resident-hit ratio floor (the
    # blind baseline sits far lower — the bench part and CI compare
    # the two exactly), bounded cold-load stall, cold loads AND LRU
    # evictions provably engaged, and ZERO pinned-slot evictions.
    # ``affinity=False`` builds the identical fleet with the scorer
    # out of the chain — the adapter-blind baseline.
    qps = 1_500.0 * qps_scale
    duration = 2.0
    n = max(3, round(6 * qps_scale))
    trace = generate(
        "steady", qps=qps, duration_s=duration, seed=seed,
        prompt_tokens=128, output_tokens=8, adapters=192,
    )
    # Per-replica slots ~ universe / replicas: under affinity routing
    # each replica's tenant partition FITS its pool (near-full
    # residency); under blind routing every replica is reached by the
    # whole universe and LRU-churns. Slots stay far below the 192
    # tenants either way.
    cfg = FleetConfig(
        replicas=n,
        profile=_PROFILE,
        lora=LoraPoolProfile(slots=32, load_s=0.05),
        lora_affinity=affinity,
        grace_s=90.0,
    )
    invariants = [
        ("zero_lost", sb.inv_zero_lost),
        ("all_completed", sb.inv_all_completed(1.0)),
        ("lora_flow", sb.inv_lora_flow(1, 1)),
        ("no_pinned_eviction", sb.inv_no_pinned_eviction),
        ("cold_stall_bounded", sb.inv_lora_cold_stall_ms(250.0)),
        ("p99_ttft", sb.inv_p99_ttft_ms(800.0)),
    ]
    if affinity:
        # The blind baseline cannot hold this floor: residency-aware
        # routing is what keeps hot tenants resident somewhere.
        invariants.append(("hit_ratio", sb.inv_lora_hit_ratio(0.55)))
    return FleetSim(cfg, trace, seed=seed, scenario="lora_tenant",
                    invariants=invariants)


def build_pd_transfer(seed: int = 0, qps_scale: float = 1.0) -> FleetSim:
    # Disaggregated serving under soak (ROADMAP fleet-soak follow-up
    # (b); kv-cache.md "layer-streamed import"): a two-tier P→D fleet —
    # every decode replica's prompts prefill on a shared 4-slot P tier
    # and the KV imports over a transfer leg with real RTT + bandwidth,
    # group-streamed (stream_groups=4) so the stage/ship legs pipeline
    # and the decode side admits at first-group-resident. A seeded 1%-
    # per-group kv.pull.drop (~4% of imports; match "pd|" — the
    # transfer leg only)
    # lands mid-stream and MUST degrade each hit import to a full local
    # recompute: slower, never wrong, never lost. Gates: both pipeline
    # legs engaged (imports AND recomputes > 0, drops fired), the
    # streamed admission gate strictly ahead of the full import, p99
    # TTFT bounded, zero lost, byte-deterministic in the soak matrix.
    qps = 2_000.0 * qps_scale
    duration = 2.0
    n = max(3, round(6 * qps_scale))
    trace = generate(
        "steady", qps=qps, duration_s=duration, seed=seed,
        tenants=TENANTS_EQUAL, prompt_tokens=128, output_tokens=8,
    )
    # p = 1% per GROUP: with 4 groups ≈ 4% of imports hit a mid-stream
    # drop — enough to prove the degradation path at every scale
    # without recompute load dominating the latency gates.
    plan = {
        "seed": seed,
        "faults": [{
            "site": "kv.pull.drop", "match": "pd|", "p": 0.01,
            "times": None,
        }],
    }
    cfg = FleetConfig(
        replicas=n,
        profile=_PROFILE,
        pd=PDTransferProfile(
            # P-tier capacity tracks offered prefill demand (~80%
            # utilized at every qps_scale), mirroring the decode tier.
            prefill_replicas=max(2, round(16 * qps_scale)),
            prefill_tok_s=_PROFILE.prefill_tok_s,
            stream_groups=4,
        ),
        grace_s=90.0,
    )
    invariants = [
        ("zero_lost", sb.inv_zero_lost),
        ("all_completed", sb.inv_all_completed(1.0)),
        ("pd_flow", sb.inv_pd_transfer(1, 1)),
        ("drops_fired", sb.inv_faults_fired("kv.pull.drop", 1)),
        ("p99_ttft", sb.inv_p99_ttft_ms(800.0)),
    ]
    return FleetSim(cfg, trace, fault_plan=plan, seed=seed,
                    scenario="pd_transfer", invariants=invariants)


def build_expert_skew(
    seed: int = 0, qps_scale: float = 1.0, eplb: bool = True
) -> FleetSim:
    # The wide-EP MoE acceptance scenario
    # (docs/architecture/wide-ep.md): every request carries a dominant
    # routed expert drawn Zipf-ish from 32 logical experts — a few hot
    # experts, a long warm tail, the popularity curve production
    # routers actually see. Under the static contiguous layout the hot
    # experts all land on EP shard 0, so the synchronous all-to-all
    # step is gated by that shard's grouped GEMM (decode TPOT
    # stretches by the max/mean shard skew, ~4x here) and the hot
    # experts' slots overflow the GShard capacity into dropped slots.
    # The real EPLB balancer (parallel/eplb.py compute_placement, the
    # same host loop the engine calls) runs on each replica's control
    # tick, replicating the hot experts into the redundancy slots and
    # repacking — gates: mean shard skew and dropped-slot fraction
    # bounded (the identity baseline sits far outside both), the
    # balancer provably engaged, zero lost, p99 TTFT held.
    # ``eplb=False`` pins the identity layout for the whole run — the
    # hot-shard baseline CI and the bench part compare exactly: the
    # EPLB leg must be strictly better on tail TPOT AND dropped slots
    # under the same seeded trace.
    qps = 1_500.0 * qps_scale
    duration = 2.0
    n = max(3, round(6 * qps_scale))
    trace = generate(
        "steady", qps=qps, duration_s=duration, seed=seed,
        tenants=TENANTS_EQUAL, prompt_tokens=128, output_tokens=8,
        experts=32,
    )
    cfg = FleetConfig(
        replicas=n,
        profile=_PROFILE,
        moe=MoEProfile(),  # 32 experts over 8 EP shards, redundancy 1
        moe_eplb=eplb,
        grace_s=90.0,
    )
    invariants = [
        ("zero_lost", sb.inv_zero_lost),
        ("all_completed", sb.inv_all_completed(1.0)),
        ("p99_ttft", sb.inv_p99_ttft_ms(800.0)),
    ]
    if eplb:
        # The identity baseline sits near mean skew ~4.2 and a ~22%
        # dropped-slot fraction on this trace — the balanced bounds
        # here are unreachable without EPLB.
        invariants += [
            ("eplb_engaged", sb.inv_eplb_engaged(1)),
            ("expert_balance", sb.inv_expert_balance(1.8, 0.03)),
        ]
    return FleetSim(cfg, trace, seed=seed, scenario="expert_skew",
                    invariants=invariants)


def build_long_context(
    seed: int = 0, qps_scale: float = 1.0, cp: bool = True
) -> FleetSim:
    # The million-token-context acceptance scenario
    # (docs/architecture/long-context.md): four chat tenants at steady
    # rate PLUS a mid-window wave of 1M-token document jobs on a
    # long-context tier — replicas sized as an 8-chip slice whose
    # profile arms both tentpoles: ring prefill (cp_degree=8, so a
    # document's TTFT is its monolithic prefill / 8) and the decode-time
    # KV pager (kv_window_tokens bounds each sequence's resident HBM;
    # the ~15/16 of a document's KV beyond the window spills to the
    # host tier). Gates: chat-tenant p99 TTFT and fleet p99 TPOT hold
    # THROUGH the wave, every document completes, ring + pager provably
    # engaged, and peak resident KV never exceeds pool capacity — which
    # a windowless fleet (15 M resident tokens vs a 262 k pool) could
    # not hold. ``cp=False`` keeps the pager but pins the monolithic
    # prefill path — the TTFT baseline the bench part compares.
    qps = 2_000.0 * qps_scale
    duration = 2.0
    n = max(3, round(6 * qps_scale))
    chat = generate(
        "steady", qps=qps, duration_s=duration, seed=seed,
        tenants=TENANTS_EQUAL, prompt_tokens=128, output_tokens=8,
    )
    doc_tokens = 1_048_576
    docs = [
        TraceRequest(
            t=0.3 + 0.1 * i, request_id=f"doc-{i:03d}", tenant="docs",
            prompt_tokens=doc_tokens, output_tokens=16,
        )
        for i in range(6)
    ]
    profile = dataclasses.replace(
        _PROFILE,
        # An 8-chip long-context slice: rates and pool scale with chips.
        prefill_tok_s=_PROFILE.prefill_tok_s * 16.0,
        decode_tok_s=_PROFILE.decode_tok_s * 8.0,
        kv_capacity_tokens=_PROFILE.kv_capacity_tokens * 8,
        cp_degree=8 if cp else 1,
        long_prompt_tokens=32_768,
        kv_window_tokens=65_536,
    )
    cfg = FleetConfig(replicas=n, profile=profile, grace_s=90.0)
    chat_tenants = [t for t, _ in TENANTS_EQUAL]
    invariants = [
        ("zero_lost", sb.inv_zero_lost),
        ("all_completed", sb.inv_all_completed(1.0)),
        ("docs_completed", sb.inv_tenant_completion(["docs"], 1.0)),
        # Chat must not feel the document wave: per-tenant band, because
        # the global percentile legitimately carries the documents' long
        # (ring-compressed) prefills.
        ("chat_p99_ttft", sb.inv_tenant_p99_ttft_ms(chat_tenants, 600.0)),
        ("p99_tpot", sb.inv_p99_tpot_ms(120.0)),
        ("kv_paged_out", sb.inv_kv_paged_out(doc_tokens)),
        ("kv_peak_bounded", sb.inv_kv_peak_bounded),
    ]
    if cp:
        invariants.append(
            ("ring_engaged", sb.inv_cp_ring_engaged(len(docs)))
        )
    return FleetSim(cfg, chat + docs, seed=seed, scenario="long_context",
                    invariants=invariants)


def build_router_soak(seed: int = 0, qps_scale: float = 1.0):
    # The REAL epp/server.py aiohttp router in-process on the virtual
    # loop (fleetsim.router_soak): loopback sockets, production parser/
    # flow-control/scheduler/breaker/proxy/resume path, stub HTTP
    # replicas killed mid-stream. Gates are CONTENT invariants — this
    # scenario performs real I/O, so it is excluded from the two-process
    # byte-compare the pure-sim scenarios pin.
    from llmd_tpu.fleetsim.router_soak import RouterSoak

    qps = max(40.0, 150.0 * qps_scale)
    duration = 1.6
    trace = generate(
        "steady", qps=qps, duration_s=duration, seed=seed,
        tenants=TENANTS_EQUAL, prompt_tokens=64, output_tokens=16,
        token_jitter=0.0,
    )
    invariants = [
        ("zero_lost", sb.inv_zero_lost),
        ("all_completed", sb.inv_all_completed(1.0)),
        ("kills_fired", sb.inv_kills_recorded(1)),
        ("stream_continuation", sb.inv_stream_continuation(1)),
    ]
    return RouterSoak(
        trace, replicas=3, kill_at_s=0.5, kills=1, max_resumes=2,
        seed=seed, scenario="router_soak", invariants=invariants,
    )


SCENARIOS: dict[str, Scenario] = {
    s.name: s
    for s in [
        Scenario("steady", build_steady,
                 "flat 10^4 QPS, four tenants: SLO bands + fairness"),
        Scenario("burst", build_burst,
                 "hog tenant bursts 5x: flow-control fairness under "
                 "pressure"),
        Scenario("diurnal", build_diurnal,
                 "day-shaped rate over the real WVA: bounded reaction, "
                 "no oscillation, scale-to-zero"),
        Scenario("replica_kill", build_replica_kill,
                 "two crashes mid-stream at 10^4 QPS: zero lost, bounded "
                 "reroute, breaker visible"),
        Scenario("brownout", build_brownout,
                 "one 200 ms-slow replica: load steered off it"),
        Scenario("all_flap", build_all_flap,
                 "all scrapes fail: healthy-filter fail-open keeps "
                 "serving"),
        Scenario("kv_federation", build_kv_federation,
                 "shared prefixes through the store tier: publish + "
                 "fetch-on-miss avoid fleet-wide recompute, drops "
                 "degrade"),
        Scenario("batch_backfill", build_batch_backfill,
                 "diurnal interactive + standing batch queue: backlog "
                 "drains through troughs at watermark admission, "
                 "utilization floor raised, interactive p99 held"),
        Scenario("lora_tenant", build_lora_tenant,
                 "192 Zipf tenants over 32-slot adapter pools: "
                 "residency-affinity routing holds the hit-ratio floor, "
                 "cold loads bounded, pinned slots never evicted"),
        Scenario("pd_transfer", build_pd_transfer,
                 "two-tier P→D fleet with a real transfer leg: "
                 "group-streamed imports pipeline stage/ship, seeded "
                 "mid-stream drops degrade to recompute, first-group "
                 "admission strictly ahead of the full import"),
        Scenario("expert_skew", build_expert_skew,
                 "wide-EP MoE under Zipf expert popularity: the real "
                 "EPLB balancer holds shard skew and dropped slots "
                 "that the static identity layout provably cannot"),
        Scenario("long_context", build_long_context,
                 "steady chat + a 1M-token document wave: ring prefill "
                 "compresses document TTFT, the KV pager bounds "
                 "resident HBM by the attention window, chat p99 holds "
                 "through the wave"),
        Scenario("router_soak", build_router_soak,
                 "REAL aiohttp router over loopback on the virtual "
                 "loop: mid-stream kills resume through the production "
                 "proxy leg, stitched streams byte-identical"),
    ]
}
