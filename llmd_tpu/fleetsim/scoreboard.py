"""The soak scoreboard: deterministic per-scenario JSON + invariant gates.

Every simulated request ends in exactly one recorded outcome —
``completed``, a typed drop (flow-control outcome, ``no-endpoints``,
``all-endpoints-failed``, ``stream-interrupted``, ``stream-corrupt``)
or ``hung`` (still pending when the scenario's grace window closed). ``hung`` existing as
a category is the point: "zero requests lost to a killed replica" is
asserted as ``hung == 0`` plus every arrival accounted for, not assumed.

:meth:`Scoreboard.finalize` folds the per-request records plus the real
components' own counters (breaker trips, healthy-filter fail-opens,
``faults.injected_counts()``, WVA decision history) into one dict and
evaluates the scenario's invariants into an ``invariants`` section.
:func:`to_canonical_json` renders it byte-deterministically: floats
rounded to 6 places, keys sorted, no wall-clock anywhere — the same
trace + FaultPlan seed must produce the identical bytes across runs,
and CI diffs exactly that.

Latency percentiles are nearest-rank over the sorted sample list (no
interpolation — interpolation invites float-order sensitivity for zero
statistical benefit at soak sample counts).
"""

from __future__ import annotations

import json
from typing import Callable

Invariant = Callable[[dict], str | None]  # None = holds, str = violation


def percentile(sorted_vals: list[float], q: float) -> float:
    """Nearest-rank percentile over pre-sorted values (0 when empty)."""
    if not sorted_vals:
        return 0.0
    k = max(0, min(len(sorted_vals) - 1, int(q * len(sorted_vals) + 0.5) - 1))
    return sorted_vals[k]


def jain_index(values: list[float]) -> float:
    """Jain's fairness index: 1.0 = perfectly even, 1/n = one hog."""
    if not values:
        return 1.0
    s = sum(values)
    ss = sum(v * v for v in values)
    if ss <= 0:
        return 1.0
    return (s * s) / (len(values) * ss)


def _round(obj, places: int = 6):
    if isinstance(obj, float):
        return round(obj, places)
    if isinstance(obj, dict):
        return {k: _round(v, places) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_round(v, places) for v in obj]
    return obj


def to_canonical_json(board: dict) -> str:
    """Byte-deterministic rendering (rounded floats, sorted keys)."""
    return json.dumps(_round(board), sort_keys=True, indent=1) + "\n"


class Scoreboard:
    def __init__(self, scenario: str, seed: int) -> None:
        self.scenario = scenario
        self.seed = seed
        self.arrived: dict[str, int] = {}  # tenant -> count
        self.outcomes: dict[str, int] = {}
        self.completed_per_tenant: dict[str, int] = {}
        self.ttft_s: list[float] = []
        self.tpot_ms: list[float] = []
        self.ttft_per_tenant: dict[str, list[float]] = {}
        self.completed_per_replica: dict[str, int] = {}
        self.retries_total = 0
        self.hung: list[str] = []
        # chaos / recovery
        self.kills: dict[str, float] = {}  # address -> sim kill time
        self.breaker_open_after_kill_s: dict[str, float] = {}
        self.reroute_latencies_s: list[float] = []
        self.recompute_fallbacks = 0
        # Mid-stream failover (the stream-continuation contract,
        # docs/architecture/fault-tolerance.md): upstream streams cut
        # after first byte, successful resumes, tokens replayed as
        # committed prefix, stitched streams that did NOT match the
        # uninterrupted expectation (must stay 0), and per-resume TTFT
        # next to its deterministic cold-recompute estimate — the
        # store-fetch-bound-vs-recompute-bound gate.
        self.mid_stream_failures = 0
        self.stream_resumes = 0
        self.resume_replayed_tokens = 0
        self.stream_parity_failures = 0
        self.resume_ttft_s: list[float] = []
        self.resume_cold_ttft_s: list[float] = []
        # autoscale
        self.autoscale_history: list[tuple[float, int]] = []  # (t, desired)
        self.replicas_started: list[tuple[float, str]] = []
        self.replicas_removed: list[tuple[float, str]] = []
        # batch tier (docs/architecture/batch-processing.md) — separate
        # from the interactive records so offline work never distorts
        # the interactive QPS/latency/zero-lost accounting.
        self.batch_enqueued = 0
        self.batch_completed = 0
        self.batch_failed = 0
        self.batch_retries = 0
        self.batch_hung: list[str] = []
        self.batch_harvested_tokens = 0
        self.batch_completed_per_replica: dict[str, int] = {}
        self.batch_last_drain_t = 0.0
        # (t, fleet decode utilization, batch backlog, live replicas)
        self.util_series: list[tuple[float, float, int, int]] = []

    # ---- recording ---------------------------------------------------- #

    def record_arrival(self, tenant: str) -> None:
        self.arrived[tenant] = self.arrived.get(tenant, 0) + 1

    def record_outcome(self, tenant: str, outcome: str) -> None:
        self.outcomes[outcome] = self.outcomes.get(outcome, 0) + 1
        if outcome == "completed":
            self.completed_per_tenant[tenant] = (
                self.completed_per_tenant.get(tenant, 0) + 1
            )

    def record_completion(
        self,
        tenant: str,
        address: str,
        ttft_s: float,
        tpot_ms: float | None,
        retries: int,
    ) -> None:
        self.record_outcome(tenant, "completed")
        self.ttft_s.append(ttft_s)
        self.ttft_per_tenant.setdefault(tenant, []).append(ttft_s)
        if tpot_ms is not None:
            self.tpot_ms.append(tpot_ms)
        self.completed_per_replica[address] = (
            self.completed_per_replica.get(address, 0) + 1
        )
        self.retries_total += retries

    def record_hung(self, request_id: str) -> None:
        self.hung.append(request_id)
        self.outcomes["hung"] = self.outcomes.get("hung", 0) + 1

    def record_kill(self, address: str, t: float) -> None:
        self.kills.setdefault(address, t)

    def record_breaker_open(self, address: str, t: float) -> None:
        kill_t = self.kills.get(address)
        if kill_t is not None and address not in self.breaker_open_after_kill_s:
            self.breaker_open_after_kill_s[address] = t - kill_t

    def record_reroute(self, latency_s: float) -> None:
        self.reroute_latencies_s.append(latency_s)

    def record_mid_stream_failure(self) -> None:
        self.mid_stream_failures += 1

    def record_resume(self, replayed_tokens: int) -> None:
        self.stream_resumes += 1
        self.resume_replayed_tokens += replayed_tokens

    def record_resume_ttft(self, ttft_s: float, cold_estimate_s: float) -> None:
        """First token of a resumed leg (pause the client saw) next to
        what a full recompute of prompt + delivered history would have
        cost on the same profile."""
        self.resume_ttft_s.append(ttft_s)
        self.resume_cold_ttft_s.append(cold_estimate_s)

    def record_parity_failure(self, request_id: str) -> None:
        self.stream_parity_failures += 1

    def record_autoscale(self, t: float, desired_total: int) -> None:
        self.autoscale_history.append((t, desired_total))

    # ---- batch tier ---------------------------------------------------- #

    def record_batch_enqueued(self) -> None:
        self.batch_enqueued += 1

    def record_batch_completion(
        self, address: str, output_tokens: int, t: float
    ) -> None:
        self.batch_completed += 1
        self.batch_harvested_tokens += output_tokens
        self.batch_completed_per_replica[address] = (
            self.batch_completed_per_replica.get(address, 0) + 1
        )
        self.batch_last_drain_t = max(self.batch_last_drain_t, t)

    def record_batch_failed(self, reason: str) -> None:
        self.batch_failed += 1

    def record_batch_retry(self) -> None:
        self.batch_retries += 1

    def record_batch_hung(self, request_id: str) -> None:
        self.batch_hung.append(request_id)

    def record_util_sample(
        self, t: float, util: float, backlog: int, replicas: int
    ) -> None:
        self.util_series.append((t, util, backlog, replicas))

    # ---- finalize ----------------------------------------------------- #

    def _direction_flips(self) -> int:
        """Sign changes in the desired-replica delta series — the
        oscillation gauge (a healthy controller ramps, holds, ramps
        back; it does not saw-tooth)."""
        deltas = [
            b - a
            for (_, a), (_, b) in zip(
                self.autoscale_history, self.autoscale_history[1:]
            )
            if b != a
        ]
        flips = 0
        for prev, cur in zip(deltas, deltas[1:]):
            if (prev > 0) != (cur > 0):
                flips += 1
        return flips

    def finalize(
        self,
        duration_s: float,
        invariants: list[tuple[str, Invariant]],
        fail_open_count: int = 0,
        breaker_trips: int = 0,
        breaker_opened: list[str] | None = None,
        faults_injected: dict[str, int] | None = None,
        recompute_fallbacks: int = 0,
        extra: dict | None = None,
    ) -> dict:
        arrived_total = sum(self.arrived.values())
        ttft_sorted = sorted(self.ttft_s)
        tpot_sorted = sorted(self.tpot_ms)
        completed = self.outcomes.get("completed", 0)
        tenants = sorted(self.arrived)
        per_tenant = {
            t: {
                "arrived": self.arrived.get(t, 0),
                "completed": self.completed_per_tenant.get(t, 0),
                "completion_ratio": (
                    self.completed_per_tenant.get(t, 0)
                    / max(self.arrived.get(t, 0), 1)
                ),
                "p99_ttft_ms": percentile(
                    sorted(self.ttft_per_tenant.get(t, [])), 0.99
                ) * 1e3,
            }
            for t in tenants
        }
        board = {
            "scenario": self.scenario,
            "seed": self.seed,
            "trace": {
                "requests": arrived_total,
                "duration_s": duration_s,
                "offered_qps": arrived_total / max(duration_s, 1e-9),
            },
            "requests": {
                "outcomes": dict(sorted(self.outcomes.items())),
                "accounted": sum(self.outcomes.values()),
                "hung": len(self.hung),
                # hung arrivals carry a "hung" outcome and so count as
                # accounted; lost is strictly the UNaccounted remainder
                # (fleet-soak.md's definition) — the two categories
                # never overlap.
                "lost": arrived_total - sum(self.outcomes.values()),
                "retries_total": self.retries_total,
            },
            "latency_ms": {
                "ttft": {
                    "p50": percentile(ttft_sorted, 0.50) * 1e3,
                    "p90": percentile(ttft_sorted, 0.90) * 1e3,
                    "p99": percentile(ttft_sorted, 0.99) * 1e3,
                    "max": (ttft_sorted[-1] if ttft_sorted else 0.0) * 1e3,
                },
                "tpot": {
                    "p50": percentile(tpot_sorted, 0.50),
                    "p99": percentile(tpot_sorted, 0.99),
                },
            },
            "per_tenant": per_tenant,
            "fairness": {
                "jain_completed": jain_index(
                    [float(per_tenant[t]["completed"]) for t in tenants]
                ),
                "min_completion_ratio": min(
                    (per_tenant[t]["completion_ratio"] for t in tenants),
                    default=1.0,
                ),
            },
            "reroute": {
                "kills": dict(sorted(self.kills.items())),
                "breaker_open_after_kill_s": dict(
                    sorted(self.breaker_open_after_kill_s.items())
                ),
                "time_to_reroute_s": (
                    max(self.reroute_latencies_s)
                    if self.reroute_latencies_s
                    else 0.0
                ),
                "rerouted_requests": len(self.reroute_latencies_s),
            },
            "stream_continuation": {
                "mid_stream_failures": self.mid_stream_failures,
                "resumes": self.stream_resumes,
                "resume_replayed_tokens": self.resume_replayed_tokens,
                "parity_failures": self.stream_parity_failures,
                "interrupted": self.outcomes.get("stream-interrupted", 0),
                "resume_ttft_p50_ms": percentile(
                    sorted(self.resume_ttft_s), 0.50
                ) * 1e3,
                "cold_recompute_ttft_p50_ms": percentile(
                    sorted(self.resume_cold_ttft_s), 0.50
                ) * 1e3,
            },
            "breaker": {
                "trips_total": breaker_trips,
                "opened": sorted(breaker_opened or []),
            },
            "fail_open_total": fail_open_count,
            "faults_injected": dict(sorted((faults_injected or {}).items())),
            "recompute_fallbacks": recompute_fallbacks,
            "replicas": {
                "completed_per_replica": dict(
                    sorted(self.completed_per_replica.items())
                ),
            },
            "autoscale": {
                "history": [[t, n] for t, n in self.autoscale_history],
                "direction_flips": self._direction_flips(),
                "started": [[t, a] for t, a in self.replicas_started],
                "removed": [[t, a] for t, a in self.replicas_removed],
            },
        }
        if self.util_series:
            # Trough window: the diurnal rate curve troughs at the tail
            # of the window (cosine phase), so trough utilization is the
            # mean over samples past 70% of the trace — the capacity
            # interactive traffic abandons and backfill must soak. The
            # section exists on the no-batch baseline leg too
            # (FleetConfig.sample_util), which is what makes the
            # floor-raised comparison measurable.
            trough_t = 0.7 * duration_s
            trough = [
                u for t, u, _, _ in self.util_series
                if trough_t <= t <= duration_s
            ]
            board["utilization"] = {
                "trough_utilization": (
                    sum(trough) / len(trough) if trough else 0.0
                ),
                "series": [
                    [t, u, b, n] for t, u, b, n in self.util_series
                ],
            }
        if self.batch_enqueued:
            backlog_peak_i = 0
            backlogs = [b for _, _, b, _ in self.util_series]
            if backlogs:
                backlog_peak_i = backlogs.index(max(backlogs))
            monotone = all(
                a >= b
                for a, b in zip(
                    backlogs[backlog_peak_i:], backlogs[backlog_peak_i + 1:]
                )
            )
            board["batch"] = {
                "enqueued": self.batch_enqueued,
                "completed": self.batch_completed,
                "failed": self.batch_failed,
                "outstanding": (
                    self.batch_enqueued - self.batch_completed
                    - self.batch_failed
                ),
                "hung": len(self.batch_hung),
                "retries": self.batch_retries,
                "harvested_tokens": self.batch_harvested_tokens,
                "last_drain_t": self.batch_last_drain_t,
                "completed_per_replica": dict(
                    sorted(self.batch_completed_per_replica.items())
                ),
                "backlog_monotone_after_peak": monotone,
            }
        if extra:
            board.update(extra)
        results = {}
        for name, inv in invariants:
            violation = inv(board)
            results[name] = {
                "ok": violation is None,
                "detail": violation or "holds",
            }
        board["invariants"] = results
        board["ok"] = all(r["ok"] for r in results.values())
        return board


# ---- invariant library ------------------------------------------------ #
# Each factory returns a predicate over the finalized board dict; None
# means the invariant holds, a string describes the violation. The
# scenario matrix composes these (fleet-soak.md carries the contract
# table: scenario -> invariant -> simulated-time bound -> metric).


def inv_zero_lost(board: dict) -> str | None:
    r = board["requests"]
    if r["lost"] != 0 or r["hung"] != 0:
        return f"lost={r['lost']} hung={r['hung']} (must both be 0)"
    return None


def inv_all_completed(min_ratio: float = 1.0) -> Invariant:
    def check(board: dict) -> str | None:
        done = board["requests"]["outcomes"].get("completed", 0)
        total = board["trace"]["requests"]
        if total and done / total < min_ratio:
            return f"completed {done}/{total} < {min_ratio:.2f}"
        return None
    return check


def inv_p99_ttft_ms(bound_ms: float) -> Invariant:
    def check(board: dict) -> str | None:
        p99 = board["latency_ms"]["ttft"]["p99"]
        if p99 > bound_ms:
            return f"p99 TTFT {p99:.1f}ms > {bound_ms}ms"
        return None
    return check


def inv_p99_tpot_ms(bound_ms: float) -> Invariant:
    def check(board: dict) -> str | None:
        p99 = board["latency_ms"]["tpot"]["p99"]
        if p99 > bound_ms:
            return f"p99 TPOT {p99:.1f}ms > {bound_ms}ms"
        return None
    return check


def inv_time_to_reroute_s(bound_s: float) -> Invariant:
    def check(board: dict) -> str | None:
        ttr = board["reroute"]["time_to_reroute_s"]
        if ttr > bound_s:
            return f"time-to-reroute {ttr:.3f}s > {bound_s}s"
        if board["reroute"]["kills"] and not board["reroute"]["rerouted_requests"]:
            return "replicas were killed but no request was rerouted"
        return None
    return check


def inv_breaker_opened_for_kills(board: dict) -> str | None:
    missing = [
        a for a in board["reroute"]["kills"]
        if a not in board["reroute"]["breaker_open_after_kill_s"]
    ]
    if missing:
        return f"breaker never opened for killed replica(s): {missing}"
    return None


def inv_fail_open_engaged(board: dict) -> str | None:
    if board["fail_open_total"] <= 0:
        return "healthy-filter fail-open never engaged"
    return None


def inv_fairness_jain(min_index: float) -> Invariant:
    def check(board: dict) -> str | None:
        j = board["fairness"]["jain_completed"]
        if j < min_index:
            return f"Jain fairness {j:.3f} < {min_index}"
        return None
    return check


def inv_tenant_completion(tenants: list[str], min_ratio: float) -> Invariant:
    def check(board: dict) -> str | None:
        for t in tenants:
            pt = board["per_tenant"].get(t)
            if pt is None:
                return f"tenant {t} missing from scoreboard"
            if pt["completion_ratio"] < min_ratio:
                return (
                    f"tenant {t} completion {pt['completion_ratio']:.3f} "
                    f"< {min_ratio}"
                )
        return None
    return check


def inv_min_offered_qps(min_qps: float) -> Invariant:
    def check(board: dict) -> str | None:
        q = board["trace"]["offered_qps"]
        if q < min_qps:
            return f"offered {q:.0f} QPS < {min_qps:.0f}"
        return None
    return check


def inv_scale_up_within_s(bound_s: float, after_t: float = 0.0) -> Invariant:
    """Desired replicas must rise above the starting count within
    ``bound_s`` of ``after_t`` (burst onset)."""
    def check(board: dict) -> str | None:
        hist = board["autoscale"]["history"]
        if not hist:
            return "no autoscale decisions recorded"
        base = hist[0][1]
        for t, n in hist:
            if t >= after_t and n > base:
                if t - after_t <= bound_s:
                    return None
                return f"first scale-up at {t:.1f}s > {after_t}+{bound_s}s"
        return "never scaled up"
    return check


def inv_scale_to_zero(board: dict) -> str | None:
    hist = board["autoscale"]["history"]
    if not any(n == 0 for _, n in hist):
        return "never scaled to zero during the idle tail"
    return None


def inv_no_oscillation(max_flips: int) -> Invariant:
    def check(board: dict) -> str | None:
        flips = board["autoscale"]["direction_flips"]
        if flips > max_flips:
            return f"{flips} scale-direction flips > {max_flips}"
        return None
    return check


def inv_brownout_steered(address: str, max_share: float) -> Invariant:
    """Routing must shift load off the browned-out replica: its share of
    completions stays under ``max_share`` (fair share would be 1/N)."""
    def check(board: dict) -> str | None:
        per = board["replicas"]["completed_per_replica"]
        total = sum(per.values())
        share = per.get(address, 0) / max(total, 1)
        if share > max_share:
            return f"browned replica served {share:.3f} > {max_share}"
        return None
    return check


def inv_recompute_avoided(min_tokens: int = 1) -> Invariant:
    """The federation's headline (kv-federation.md): at least
    ``min_tokens`` prompt tokens were served by store fetches instead
    of fleet-wide re-prefill."""
    def check(board: dict) -> str | None:
        fed = board.get("kv_federation")
        if fed is None:
            return "scoreboard carries no kv_federation section"
        got = fed["recompute_avoided_tokens"]
        if got < min_tokens:
            return f"recompute_avoided_tokens {got} < {min_tokens}"
        return None
    return check


def inv_store_flow(min_published: int = 1, min_hits: int = 1) -> Invariant:
    """Both federation legs engaged: replicas published prefixes to the
    store AND peers fetched them back."""
    def check(board: dict) -> str | None:
        fed = board.get("kv_federation")
        if fed is None:
            return "scoreboard carries no kv_federation section"
        if fed["store_published"] < min_published:
            return f"store_published {fed['store_published']} < {min_published}"
        if fed["store_hits"] < min_hits:
            return f"store_hits {fed['store_hits']} < {min_hits}"
        return None
    return check


def inv_pd_transfer(
    min_imports: int = 1, min_recomputes: int = 1
) -> Invariant:
    """The two-tier P→D pipeline engaged end to end: prompts imported
    KV over the transfer leg AND seeded mid-stream drops provably
    degraded to local recompute (never a lost or corrupt stream — those
    are gated by zero_lost/parity alongside). Also pins the streamed
    admission gate: first-group p50 strictly below the full-import p50
    (with stream_groups > 1 the wire opens the gate early)."""
    def check(board: dict) -> str | None:
        pd = board.get("pd_transfer")
        if pd is None:
            return "scoreboard carries no pd_transfer section"
        if pd["imports"] < min_imports:
            return f"pd imports {pd['imports']} < {min_imports}"
        if pd["recomputes"] < min_recomputes:
            return f"pd recomputes {pd['recomputes']} < {min_recomputes}"
        if pd["stream_groups"] > 1 and not (
            pd["first_group_p50_ms"] < pd["import_p50_ms"]
        ):
            return (
                f"first-group p50 {pd['first_group_p50_ms']} ms not "
                f"below import p50 {pd['import_p50_ms']} ms"
            )
        return None
    return check


def inv_expert_balance(
    max_mean_skew: float, max_dropped_frac: float
) -> Invariant:
    """THE wide-EP bar (docs/architecture/wide-ep.md): under a Zipf
    expert-popularity trace the run-long mean per-shard load skew must
    stay under ``max_mean_skew`` and capacity-dropped slots under
    ``max_dropped_frac`` of all routed tokens. The identity-placement
    baseline leg blows through both (the scenario's off leg and the
    CI summary compare the two exactly) — only EPLB replication +
    repacking of the hot experts holds them."""
    def check(board: dict) -> str | None:
        es = board.get("expert_skew")
        if es is None:
            return "scoreboard carries no expert_skew section"
        if es["mean_shard_skew"] > max_mean_skew:
            return (
                f"mean shard skew {es['mean_shard_skew']:.3f} > "
                f"{max_mean_skew}"
            )
        frac = es["dropped_slots"] / max(es["routed_tokens"], 1)
        if frac > max_dropped_frac:
            return (
                f"dropped-slot fraction {frac:.4f} "
                f"({es['dropped_slots']}/{es['routed_tokens']}) > "
                f"{max_dropped_frac}"
            )
        return None
    return check


def inv_eplb_engaged(min_rebalances: int = 1) -> Invariant:
    """The balancer provably ran: at least ``min_rebalances`` EPLB
    placement recomputations across the fleet (a balance gate is
    vacuous if the control loop never ticked)."""
    def check(board: dict) -> str | None:
        es = board.get("expert_skew")
        if es is None:
            return "scoreboard carries no expert_skew section"
        if not es["eplb"]:
            return "EPLB is off in this leg"
        if es["rebalances"] < min_rebalances:
            return f"rebalances {es['rebalances']} < {min_rebalances}"
        return None
    return check


def inv_batch_drained(board: dict) -> str | None:
    """THE backfill bar (docs/architecture/batch-processing.md): every
    queued offline job completed through interactive troughs — nothing
    outstanding, nothing hung, and the backlog only fell once the
    standing queue was fully enqueued (monotone drain)."""
    b = board.get("batch")
    if b is None:
        return "scoreboard carries no batch section"
    if b["outstanding"] != 0 or b["hung"] != 0 or b["failed"] != 0:
        return (
            f"batch backlog not drained: outstanding={b['outstanding']} "
            f"hung={b['hung']} failed={b['failed']}"
        )
    if not b["backlog_monotone_after_peak"]:
        return "batch backlog rose after the standing queue was enqueued"
    return None


def inv_batch_harvest(min_tokens: int) -> Invariant:
    """Backfill actually harvested capacity: at least ``min_tokens``
    offline output tokens were generated."""
    def check(board: dict) -> str | None:
        b = board.get("batch")
        if b is None:
            return "scoreboard carries no batch section"
        if b["harvested_tokens"] < min_tokens:
            return (
                f"harvested {b['harvested_tokens']} batch tokens "
                f"< {min_tokens}"
            )
        return None
    return check


def inv_trough_util(min_util: float) -> Invariant:
    """The utilization-floor bar: mean fleet decode utilization over the
    trough window ([70%, 100%] of the trace span, where the diurnal
    curve bottoms out) stays at or above ``min_util`` — capacity
    interactive traffic abandoned that backfill soaked instead. The
    no-batch baseline sits near zero there (the bench part records
    both)."""
    def check(board: dict) -> str | None:
        u = board.get("utilization")
        if u is None:
            return "scoreboard carries no utilization section"
        v = u["trough_utilization"]
        if v < min_util:
            return f"trough utilization {v:.3f} < {min_util}"
        return None
    return check


def inv_stream_continuation(min_resumes: int = 1) -> Invariant:
    """THE failover bar (replica_kill's tightened gate): a mid-stream
    replica death is never client-visible — no ``stream-interrupted`` or
    ``stream-corrupt`` outcomes, no parity failures — AND at least
    ``min_resumes`` streams actually continued on a fresh replica (the
    zero-visible claim is vacuous if nothing was ever cut)."""
    def check(board: dict) -> str | None:
        sc = board.get("stream_continuation")
        if sc is None:
            return "scoreboard carries no stream_continuation section"
        visible = (
            sc["interrupted"]
            + board["requests"]["outcomes"].get("stream-corrupt", 0)
        )
        if visible:
            return f"{visible} client-visible stream failure(s)"
        if sc["parity_failures"]:
            return (
                f"{sc['parity_failures']} resumed stream(s) diverged from "
                "the uninterrupted expectation"
            )
        if sc["resumes"] < min_resumes:
            return f"resumes {sc['resumes']} < {min_resumes}"
        return None
    return check


def inv_resume_ttft_vs_cold(board: dict) -> str | None:
    """Resume must be store-fetch-bound, not recompute-bound: p50 TTFT
    of resumed legs beats the p50 deterministic cost of recomputing
    prompt + delivered history from scratch (kv-federation.md gives the
    fast path; requires the scenario to arm the store tier)."""
    sc = board.get("stream_continuation")
    if sc is None:
        return "scoreboard carries no stream_continuation section"
    if not sc["resumes"]:
        return "no resumes recorded to compare"
    if sc["resume_ttft_p50_ms"] >= sc["cold_recompute_ttft_p50_ms"]:
        return (
            f"resume p50 TTFT {sc['resume_ttft_p50_ms']:.2f}ms >= cold "
            f"recompute p50 {sc['cold_recompute_ttft_p50_ms']:.2f}ms"
        )
    return None


def inv_lora_hit_ratio(min_ratio: float) -> Invariant:
    """THE adapter-affinity bar (multi-tenant-lora.md): the fraction of
    adapter requests finding their adapter already resident must hold
    ``min_ratio`` — with pool capacity far below tenant count, only
    residency-aware routing keeps this high."""
    def check(board: dict) -> str | None:
        lo = board.get("lora")
        if lo is None:
            return "scoreboard carries no lora section"
        if lo["hit_ratio"] < min_ratio:
            return f"resident-hit ratio {lo['hit_ratio']:.3f} < {min_ratio}"
        return None
    return check


def inv_lora_flow(min_cold_loads: int = 1, min_evictions: int = 1) -> Invariant:
    """The pool's churn legs actually engaged: adapters cold-loaded into
    slots AND idle residents were LRU-evicted for incoming tenants (a
    registry smaller than the fleet's slot capacity would make the
    hit-ratio gate vacuous)."""
    def check(board: dict) -> str | None:
        lo = board.get("lora")
        if lo is None:
            return "scoreboard carries no lora section"
        if lo["cold_loads"] < min_cold_loads:
            return f"cold_loads {lo['cold_loads']} < {min_cold_loads}"
        if lo["evictions"] < min_evictions:
            return f"evictions {lo['evictions']} < {min_evictions}"
        return None
    return check


def inv_no_pinned_eviction(board: dict) -> str | None:
    """The no-thrash contract: a slot referenced by an in-flight row is
    NEVER evicted — displacing a referenced tenant would mix weight
    versions mid-stream."""
    lo = board.get("lora")
    if lo is None:
        return "scoreboard carries no lora section"
    if lo["pinned_evictions"] != 0:
        return f"{lo['pinned_evictions']} pinned slot(s) were evicted"
    return None


def inv_lora_cold_stall_ms(bound_p50_ms: float) -> Invariant:
    """Bounded cold-load TTFT: the p50 stall a cold-adapter request
    pays (fetch + install + any wait for an evictable slot) stays
    within ``bound_p50_ms`` — cold loads are a bounded tax, not a
    convoy."""
    def check(board: dict) -> str | None:
        lo = board.get("lora")
        if lo is None:
            return "scoreboard carries no lora section"
        if lo["cold_loads"] and lo["cold_stall_p50_ms"] > bound_p50_ms:
            return (
                f"cold-load stall p50 {lo['cold_stall_p50_ms']:.1f}ms "
                f"> {bound_p50_ms}ms"
            )
        return None
    return check


def inv_tenant_p99_ttft_ms(tenants: list[str], bound_ms: float) -> Invariant:
    """Per-tenant TTFT band: each named tenant's p99 TTFT stays under
    ``bound_ms`` — the long_context scenario's chat gate, where the
    GLOBAL percentile would be dominated by the document wave's
    legitimately long prefills."""
    def check(board: dict) -> str | None:
        for t in tenants:
            pt = board["per_tenant"].get(t)
            if pt is None:
                return f"tenant {t} missing from scoreboard"
            if pt["p99_ttft_ms"] > bound_ms:
                return (
                    f"tenant {t} p99 TTFT {pt['p99_ttft_ms']:.1f}ms "
                    f"> {bound_ms}ms"
                )
        return None
    return check


def inv_cp_ring_engaged(min_prefills: int = 1) -> Invariant:
    """The context-parallel tier provably ran: at least ``min_prefills``
    long prompts prefilled through the ring schedule (the TTFT gate is
    vacuous if every document took the monolithic path)."""
    def check(board: dict) -> str | None:
        lc = board.get("long_context")
        if lc is None:
            return "scoreboard carries no long_context section"
        if lc["cp_ring_prefills"] < min_prefills:
            return f"cp_ring_prefills {lc['cp_ring_prefills']} < {min_prefills}"
        return None
    return check


def inv_kv_paged_out(min_tokens: int = 1) -> Invariant:
    """The decode-time pager provably spilled: at least ``min_tokens``
    of KV left HBM for the host tier — without this the kv_peak bound
    would hold trivially on a fleet whose contexts simply fit."""
    def check(board: dict) -> str | None:
        lc = board.get("long_context")
        if lc is None:
            return "scoreboard carries no long_context section"
        if lc["kv_paged_out_tokens"] < min_tokens:
            return (
                f"kv_paged_out_tokens {lc['kv_paged_out_tokens']} "
                f"< {min_tokens}"
            )
        return None
    return check


def inv_kv_peak_bounded(board: dict) -> str | None:
    """THE residency bar (long-context.md): no replica's resident KV
    ever exceeded its pool capacity — million-token documents hold
    window bytes, not context bytes."""
    lc = board.get("long_context")
    if lc is None:
        return "scoreboard carries no long_context section"
    if lc["peak_kv_tokens"] > lc["kv_capacity_tokens"]:
        return (
            f"peak resident KV {lc['peak_kv_tokens']:.0f} tokens > "
            f"capacity {lc['kv_capacity_tokens']}"
        )
    return None


def inv_faults_fired(site: str, at_least: int = 1) -> Invariant:
    def check(board: dict) -> str | None:
        n = board["faults_injected"].get(site, 0)
        if n < at_least:
            return f"fault {site} fired {n} < {at_least} times"
        return None
    return check


def inv_kills_recorded(at_least: int = 1) -> Invariant:
    """Replica kills driven OUTSIDE the FaultPlan (the router-soak's
    direct chaos task) still must provably have happened."""
    def check(board: dict) -> str | None:
        n = len(board["reroute"]["kills"])
        if n < at_least:
            return f"{n} replica kill(s) recorded < {at_least}"
        return None
    return check
