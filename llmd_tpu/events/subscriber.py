"""EPP-side KV-event subscriber (ZMQ SUB, pod-discovery mode).

Each EPP replica independently subscribes to every pod's event socket
(reference kv-indexer.md:59-87, active-active pod-discovery delivery,
precise-prefix-cache-routing.values.yaml kvEventsConfig.podDiscoveryConfig).
One SUB socket connects to all publishers; a poller thread applies batches
to the KVBlockIndex. Per-topic sequence gaps (missed batches under
slow-joiner or overload) resynchronize by clearing the pod's view — the
index converges from subsequent BlockStored traffic, trading brief
under-scoring for correctness (kv-indexer.md:98-101).
"""

from __future__ import annotations

import json
import logging
import struct
import threading

from llmd_tpu import faults
from llmd_tpu.events.index import KVBlockIndex

log = logging.getLogger(__name__)


class KVEventSubscriber:
    def __init__(self, index: KVBlockIndex, topic: str = "kv-events") -> None:
        import zmq

        self.index = index
        self._zmq = zmq
        self._ctx = zmq.Context.instance()
        self._topic = topic
        # endpoint zmq-address -> pod address (events attribute to pods)
        self._pods: dict[str, str] = {}  # llmd: guarded_by(_lock)
        # Poller-thread-owned (single writer/reader): no lock needed.
        self._seqs: dict[str, int] = {}
        self.batch_failures = 0  # batches whose apply raised (poller survives)
        self._lock = threading.Lock()
        # ZMQ sockets are NOT thread-safe: connect/disconnect are queued here
        # and executed by the poller thread, which exclusively owns the
        # socket (commands drain within one 100ms poll interval).
        self._cmds: list[tuple[str, str]] = []  # llmd: guarded_by(_lock)
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def add_pod(self, pod_address: str, event_endpoint: str) -> None:
        """Subscribe to a discovered pod's event socket."""
        with self._lock:
            if event_endpoint in self._pods:
                return
            self._pods[event_endpoint] = pod_address
            self._cmds.append(("connect", event_endpoint))
        log.info("kv-events: subscribing to %s (%s)", event_endpoint, pod_address)

    def remove_pod(self, pod_address: str) -> None:
        with self._lock:
            eps = [ep for ep, pod in self._pods.items() if pod == pod_address]
            for ep in eps:
                del self._pods[ep]
                self._cmds.append(("disconnect", ep))
        self.index.remove_pod(pod_address)

    # ------------------------------------------------------------------ #

    def _loop(self) -> None:
        sock = self._ctx.socket(self._zmq.SUB)
        sock.setsockopt(self._zmq.LINGER, 0)
        sock.setsockopt_string(self._zmq.SUBSCRIBE, self._topic)
        poller = self._zmq.Poller()
        poller.register(sock, self._zmq.POLLIN)
        try:
            while not self._stop.is_set():
                with self._lock:
                    cmds, self._cmds = self._cmds, []
                for op, ep in cmds:
                    try:
                        getattr(sock, op)(ep)
                    except self._zmq.ZMQError as e:
                        log.warning("kv-events %s %s failed: %s", op, ep, e)
                try:
                    if not dict(poller.poll(timeout=100)):
                        continue
                    parts = sock.recv_multipart(flags=self._zmq.NOBLOCK)
                except self._zmq.ZMQError:
                    continue
                try:
                    self._handle(parts)
                except Exception:
                    # A backend hiccup (e.g. Redis outage in the shared
                    # index) must not kill the poller thread — the index
                    # would go silently stale forever.
                    self.batch_failures += 1
                    log.exception("kv-event batch failed; poller continues")
        finally:
            sock.close(0)

    def _handle(self, parts) -> None:
        if len(parts) != 3:
            return
        _topic, seq_raw, payload = parts
        try:
            (seq,) = struct.unpack(">Q", seq_raw)
            batch = json.loads(payload)
        except (struct.error, json.JSONDecodeError):
            return
        # Publishers embed their advertised pod address in the payload
        # (SUB sockets don't expose the sender).
        pod = batch.get("pod")
        if not pod:
            return
        # Injection site: a dropped batch leaves _seqs untouched, so the
        # NEXT batch presents a sequence gap and the resync path below
        # (clear the pod's view, converge from subsequent BlockStored
        # traffic) is what gets exercised — the same degradation a real
        # lost ZMQ message produces.
        if faults.fires("events.drop", pod):
            return
        last = self._seqs.get(pod)
        if last is not None and seq != last + 1:
            log.warning(
                "kv-events: seq gap for %s (%d -> %d), resyncing", pod, last, seq
            )
            self.index.remove_pod(pod)
        self._seqs[pod] = seq
        self.index.apply(pod, batch.get("events", []))

    def close(self) -> None:
        self._stop.set()
        self._thread.join(timeout=2)
