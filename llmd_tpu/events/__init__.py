"""KV-event plane: engine-side ZMQ publisher + EPP-side subscriber/index.

Re-implements the reference's precise prefix-cache indexing pipeline
(docs/architecture/advanced/kv-management/kv-indexer.md:59-151): engines
publish BlockStored/BlockRemoved/AllBlocksCleared; each EPP replica
subscribes to every pod (pod-discovery, active-active convergent) and
maintains a chained block-hash -> pods index used by the
precise-prefix-cache scorer.
"""

from llmd_tpu.events.index import KVBlockIndex, TIER_WEIGHTS  # noqa: F401
from llmd_tpu.events.publisher import ZMQEventSink  # noqa: F401
from llmd_tpu.events.subscriber import KVEventSubscriber  # noqa: F401
