"""Engine-side KV-event publisher (ZMQ PUB).

Reference contract (kv-indexer.md:59-87): the engine emits `KVEvents` —
BlockStored / BlockRemoved / AllBlocksCleared — on a ZMQ socket
(kvEventsConfig socketPort 5556 in precise-prefix-cache-routing.values.yaml).
Events are batched and sequence-numbered per topic so subscribers can detect
gaps and resynchronize by dropping their view of the pod (convergence over
exactness, matching the reference's active-active design, kv-indexer.md:98-101).

Wire format: multipart [topic: utf8, seq: u64-be, payload: JSON]
payload = {"events": [{"type": "BlockStored", "hashes": [hex...],
                       "parent": hex|null, "tokens": [...], "medium": "gpu"},
                      {"type": "BlockRemoved", "hashes": [hex...]},
                      {"type": "AllBlocksCleared"}]}
"""

from __future__ import annotations

import json
import logging
import struct
import threading

from llmd_tpu.engine.kv_cache import KVEventSink

log = logging.getLogger(__name__)


class ZMQEventSink(KVEventSink):
    """Batched ZMQ publisher implementing the engine's KVEventSink."""

    def __init__(
        self,
        endpoint: str = "tcp://*:5556",
        topic: str = "kv-events",
        flush_interval_s: float = 0.05,
        max_batch: int = 256,
        medium: str = "gpu",
        pod: str = "",
    ) -> None:
        import zmq

        self.topic = topic.encode()
        self.medium = medium
        # The pod's advertised serving address; subscribers attribute events
        # to endpoints by this field (SUB sockets don't expose the sender).
        self.pod = pod
        self._ctx = zmq.Context.instance()
        self._sock = self._ctx.socket(zmq.PUB)
        # 0 linger: never block process shutdown on undelivered events.
        self._sock.setsockopt(zmq.LINGER, 0)
        if endpoint.endswith(":0"):
            port = self._sock.bind_to_random_port(endpoint[: endpoint.rfind(":")])
            self.endpoint = endpoint[: endpoint.rfind(":") + 1] + str(port)
        else:
            self._sock.bind(endpoint)
            self.endpoint = endpoint
        self._seq = 0  # llmd: guarded_by(_lock)
        self._buf: list[dict] = []  # llmd: guarded_by(_lock)
        # batches the PUB socket refused
        self.publish_failures = 0  # llmd: guarded_by(_lock)
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self.max_batch = max_batch
        self._flusher = threading.Thread(
            target=self._flush_loop, args=(flush_interval_s,), daemon=True
        )
        self._flusher.start()

    # -- KVEventSink interface (called from the engine thread) ---------- #

    def blocks_stored(self, hashes, parent, token_ids) -> None:
        self._append(
            {
                "type": "BlockStored",
                "hashes": [h.hex() for h in hashes],
                "parent": parent.hex() if parent else None,
                "tokens": list(token_ids),
                "medium": self.medium,
            }
        )

    def blocks_removed(self, hashes) -> None:
        self._append(
            {
                "type": "BlockRemoved",
                "hashes": [h.hex() for h in hashes],
                # medium matters only for store-tier withdrawals
                # (kv-federation.md): resident removals clear the pod's
                # entry regardless of tier.
                "medium": self.medium,
            }
        )

    def all_cleared(self) -> None:
        self._append({"type": "AllBlocksCleared"})

    # ------------------------------------------------------------------ #

    def _append(self, ev: dict) -> None:
        with self._lock:
            self._buf.append(ev)
            if len(self._buf) >= self.max_batch:
                self._publish_locked()

    def _publish_locked(self) -> None:
        if not self._buf:
            return
        batch, self._buf = self._buf, []
        payload = json.dumps({"pod": self.pod, "events": batch}).encode()
        seq = struct.pack(">Q", self._seq)
        self._seq += 1
        try:
            self._sock.send_multipart([self.topic, seq, payload], copy=False)
        except Exception as e:  # pragma: no cover - zmq failure is best-effort
            # Subscribers see the seq gap and resync; the counter is the
            # publisher-side trail that the gap was OUR send failing.
            self.publish_failures += 1
            log.warning("kv-event publish failed: %s", e)

    def _flush_loop(self, interval: float) -> None:
        while not self._stop.wait(interval):
            with self._lock:
                self._publish_locked()

    def flush(self) -> None:
        with self._lock:
            self._publish_locked()

    def close(self) -> None:
        self._stop.set()
        self._flusher.join(timeout=2)
        with self._lock:
            self._publish_locked()
        self._sock.close(0)
