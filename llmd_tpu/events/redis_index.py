"""Redis/Valkey-backed KV block index (the reference's third backend).

kv-indexer.md:59-151 names Redis/Valkey as the shared-index option:
every router replica reads/writes one external store, so replicas see a
consistent index without per-replica event fan-in. No redis client
library ships in this image, so this speaks RESP directly over a
socket — a complete implementation against any real Redis/Valkey (and
the in-process fake used by tests).

Schema:
  HSET kv:{hash} {pod} {tier}     BlockStored
  HDEL kv:{hash} {pod}            BlockRemoved
  SADD pod:{pod} {hash}           reverse index for AllBlocksCleared
Speculative entries stay process-local (they exist to co-route bursts
hitting THIS replica before events arrive; sharing them would defeat
their 2s-TTL semantics).

Scoring pipelines one HGETALL per prefix hash in a single round trip,
then walks the run locally — one network RTT per scheduling decision.
"""

from __future__ import annotations

import logging
import socket
import threading
import time

from llmd_tpu.events.index import (
    SPECULATIVE_TTL_S,
    STORE_POD,
    tier_weights_from_env,
)

log = logging.getLogger(__name__)


class RespClient:
    """Minimal RESP2 client: command pipelining over one socket.

    Calls are SYNCHRONOUS; the scoring path runs on the router event
    loop, so the timeout must stay short — an unreachable Redis costs at
    most ~2x timeout_s per decision (attempt + one reconnect), and the
    scorer degrades to zero scores rather than erroring (fail-open,
    matching router FailOpen semantics)."""

    def __init__(
        self,
        host: str,
        port: int,
        timeout_s: float = 0.25,
        down_cooldown_s: float = 5.0,
        slow_threshold_s: float = 0.1,
        slow_open_after: int = 3,
    ) -> None:
        self.addr = (host, port)
        self.timeout_s = timeout_s
        self.down_cooldown_s = down_cooldown_s
        # Latency breaker: a slow-but-alive Redis never raises, so the
        # error breaker alone would let every scheduling decision stall
        # the event loop for up to ~2x timeout_s. N consecutive calls over
        # the threshold open the circuit like an error does.
        self.slow_threshold_s = slow_threshold_s
        self.slow_open_after = slow_open_after
        self._slow_streak = 0  # llmd: guarded_by(_lock)
        self._down_until = 0.0  # llmd: guarded_by(_lock)
        self._sock: socket.socket | None = None  # llmd: guarded_by(_lock)
        self._buf = b""  # llmd: guarded_by(_lock)
        self._lock = threading.Lock()

    def _connect_locked(self) -> socket.socket:
        if self._sock is None:
            self._sock = socket.create_connection(self.addr, self.timeout_s)
            self._sock.settimeout(self.timeout_s)
            self._buf = b""
        return self._sock

    def _close_locked(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            finally:
                self._sock = None

    def close(self) -> None:
        with self._lock:
            self._close_locked()

    @staticmethod
    def _encode(args: tuple) -> bytes:
        out = [b"*%d\r\n" % len(args)]
        for a in args:
            b = a if isinstance(a, bytes) else str(a).encode()
            out.append(b"$%d\r\n%s\r\n" % (len(b), b))
        return b"".join(out)

    def _read_line_locked(self, sock: socket.socket) -> bytes:
        while b"\r\n" not in self._buf:
            chunk = sock.recv(65536)
            if not chunk:
                raise ConnectionError("redis connection closed")
            self._buf += chunk
        line, self._buf = self._buf.split(b"\r\n", 1)
        return line

    def _read_exact_locked(self, sock: socket.socket, n: int) -> bytes:
        while len(self._buf) < n + 2:
            chunk = sock.recv(65536)
            if not chunk:
                raise ConnectionError("redis connection closed")
            self._buf += chunk
        data, self._buf = self._buf[:n], self._buf[n + 2 :]
        return data

    def _read_reply_locked(self, sock: socket.socket):
        line = self._read_line_locked(sock)
        kind, rest = line[:1], line[1:]
        if kind == b"+":
            return rest.decode()
        if kind == b"-":
            raise RuntimeError(f"redis error: {rest.decode()}")
        if kind == b":":
            return int(rest)
        if kind == b"$":
            n = int(rest)
            return None if n == -1 else self._read_exact_locked(sock, n)
        if kind == b"*":
            n = int(rest)
            return None if n == -1 else [self._read_reply_locked(sock) for _ in range(n)]
        raise RuntimeError(f"unexpected RESP type {line!r}")

    def _read_all_locked(self, sock: socket.socket, n: int) -> list:
        """Read n replies keeping the stream in sync: an error REPLY
        (-ERR...) consumes its line and is re-raised only after all
        replies are drained; an I/O failure mid-read leaves unread
        replies on the wire, so the socket is closed (a reused socket
        would misattribute the leftovers to later commands)."""
        replies = []
        first_err: RuntimeError | None = None
        try:
            for _ in range(n):
                try:
                    replies.append(self._read_reply_locked(sock))
                except RuntimeError as e:
                    replies.append(None)
                    first_err = first_err or e
        except (OSError, ConnectionError):
            self._close_locked()
            raise
        if first_err is not None:
            raise first_err
        return replies

    def pipeline(self, commands: list[tuple]) -> list:
        """Send all commands in one write; read all replies."""
        if not commands:
            return []
        with self._lock:
            # Clock starts under the lock: waiting for a peer caller's
            # round trip is not Redis latency and must not trip the breaker.
            now = time.monotonic()
            if now < self._down_until:
                raise ConnectionError("redis marked down (circuit open)")
            payload = b"".join(self._encode(c) for c in commands)
            try:
                try:
                    sock = self._connect_locked()
                    sock.sendall(payload)
                except (OSError, ConnectionError):
                    # one reconnect attempt (server restart, idle timeout)
                    self._close_locked()
                    sock = self._connect_locked()
                    sock.sendall(payload)
                replies = self._read_all_locked(sock, len(commands))
            except (OSError, ConnectionError):
                # Circuit-break: the caller runs on the router event loop;
                # retrying the connect on every scheduling decision would
                # stall the whole process for ~2x timeout per request.
                self._close_locked()
                self._down_until = time.monotonic() + self.down_cooldown_s
                self._slow_streak = 0
                raise
            if time.monotonic() - now > self.slow_threshold_s:
                self._slow_streak += 1
                if self._slow_streak >= self.slow_open_after:
                    self._down_until = time.monotonic() + self.down_cooldown_s
                    self._slow_streak = 0
                    log.warning(
                        "redis slow (%d calls > %.0fms): circuit open %.1fs",
                        self.slow_open_after,
                        self.slow_threshold_s * 1e3,
                        self.down_cooldown_s,
                    )
            else:
                self._slow_streak = 0
            return replies

    def command(self, *args):
        return self.pipeline([args])[0]


class RedisKVBlockIndex:
    """KVBlockIndex-compatible interface over a shared Redis/Valkey."""

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 6379,
        speculative_ttl_s: float = SPECULATIVE_TTL_S,
        key_prefix: str = "llmd",
        entry_ttl_s: int = 1200,
        tier_weights: dict[str, float] | None = None,
    ) -> None:
        """entry_ttl_s: sliding expiry on every key touched by a store —
        the shared store's safety net against pods that die while no
        router observes it (their entries would otherwise advertise
        caches forever and misroute warm traffic; the in-memory backend
        has its per-pod capacity cap instead). Live pods keep refreshing
        their keys through ongoing BlockStored traffic."""
        self.client = RespClient(host, port)
        self.speculative_ttl_s = speculative_ttl_s
        self.prefix = key_prefix
        self.entry_ttl_s = int(entry_ttl_s)
        self.tier_weights = tier_weights_from_env()
        if tier_weights:
            self.tier_weights.update(tier_weights)
        self._lock = threading.Lock()
        self._spec: dict[str, dict[str, float]] = {}  # llmd: guarded_by(_lock)
        self.metrics_events = 0  # llmd: guarded_by(_lock)
        self.metrics_lookups = 0  # llmd: guarded_by(_lock)
        self.metrics_hits = 0  # llmd: guarded_by(_lock)

    def _bk(self, h: str) -> str:
        return f"{self.prefix}:kv:{h}"

    def _pk(self, pod: str) -> str:
        return f"{self.prefix}:pod:{pod}"

    # ---------------------------------------------------------- events

    def apply(self, pod: str, events: list[dict]) -> None:
        # The poller thread applies while scheduler threads score: the
        # counters share one lock with _spec (the in-memory backend
        # counts under its lock for the same reason — unlocked `+=`
        # loses updates between the read and the write-back).
        with self._lock:
            self.metrics_events += len(events)
        cmds: list[tuple] = []
        for ev in events:
            t = ev.get("type")
            if t == "BlockStored":
                tier = ev.get("medium", "gpu")
                # Fleet-global store copies book under the reserved
                # pseudo-pod (see events.index.STORE_POD): the
                # publication must not downgrade the publisher's own
                # resident-tier entry.
                holder = STORE_POD if tier == "store" else pod
                for h in ev.get("hashes", []):
                    cmds.append(("HSET", self._bk(h), holder, tier))
                    cmds.append(("EXPIRE", self._bk(h), self.entry_ttl_s))
                    cmds.append(("SADD", self._pk(holder), h))
                if ev.get("hashes"):
                    cmds.append(("EXPIRE", self._pk(holder), self.entry_ttl_s))
            elif t == "BlockRemoved":
                # store-tier removals withdraw the fleet-global copy
                holder = STORE_POD if ev.get("medium") == "store" else pod
                for h in ev.get("hashes", []):
                    cmds.append(("HDEL", self._bk(h), holder))
                    cmds.append(("SREM", self._pk(holder), h))
            elif t == "AllBlocksCleared":
                # Strict event order: stores queued BEFORE the clear must
                # land (and then be wiped) — flushing keeps a batch like
                # [BlockStored h1, AllBlocksCleared] ending empty, exactly
                # like the in-memory index.
                if cmds:
                    self.client.pipeline(cmds)
                    cmds = []
                self._clear_pod(pod)
        if cmds:
            self.client.pipeline(cmds)

    def _clear_pod(self, pod: str) -> None:
        hashes = self.client.command("SMEMBERS", self._pk(pod)) or []
        cmds: list[tuple] = [("DEL", self._pk(pod))]
        for h in hashes:
            hs = h.decode() if isinstance(h, bytes) else h
            cmds.append(("HDEL", self._bk(hs), pod))
        self.client.pipeline(cmds)
        with self._lock:
            self._spec.pop(pod, None)

    def remove_pod(self, pod: str) -> None:
        # Endpoint-store removal callback: a Redis outage here must not
        # break pool reconciliation; the entry TTL reclaims eventually.
        try:
            self._clear_pod(pod)
        except (OSError, ConnectionError, RuntimeError) as e:
            log.warning("redis index clear for pod %s failed: %s", pod, e)

    # ---------------------------------------------------------- speculative

    def insert_speculative(self, pod: str, hashes: list[str]) -> None:
        now = time.monotonic()
        deadline = now + self.speculative_ttl_s
        with self._lock:
            spec = self._spec.setdefault(pod, {})
            for h in list(spec):
                if spec[h] <= now:
                    del spec[h]
            for h in hashes:
                spec[h] = deadline

    # ---------------------------------------------------------- scoring

    def score(self, hashes: list[str], pods: list[str]) -> dict[str, float]:
        return {p: s for p, (s, _) in self.score_detailed(hashes, pods).items()}

    def score_detailed(
        self, hashes: list[str], pods: list[str]
    ) -> dict[str, tuple[float, int]]:
        with self._lock:
            self.metrics_lookups += 1
        now = time.monotonic()
        try:
            replies = self.client.pipeline(
                [("HGETALL", self._bk(h)) for h in hashes]
            )
        except (OSError, ConnectionError, RuntimeError) as e:
            log.warning("redis index lookup failed (%s): scoring 0", e)
            return {p: (0.0, 0) for p in pods}
        # flatten [k1, v1, k2, v2, ...] -> per-hash {pod: tier}
        holders: list[dict[str, str]] = []
        for r in replies:
            d: dict[str, str] = {}
            items = r or []
            for i in range(0, len(items), 2):
                k = items[i].decode() if isinstance(items[i], bytes) else items[i]
                v = (
                    items[i + 1].decode()
                    if isinstance(items[i + 1], bytes)
                    else items[i + 1]
                )
                d[k] = v
            holders.append(d)
        out: dict[str, tuple[float, int]] = {}
        hit = False
        with self._lock:
            for pod in pods:
                spec = self._spec.get(pod, {})
                s, n = 0.0, 0
                for h, held in zip(hashes, holders):
                    tier = held.get(pod)
                    if tier is None and spec.get(h, 0.0) > now:
                        tier = "gpu"
                    if tier is None and "store" in held.values():
                        # Fleet-wide store copy (kv-federation.md): one
                        # fetch away from every pod.
                        tier = "store"
                    if tier is None:
                        break
                    s += self.tier_weights.get(tier, 0.5)
                    n += 1
                if n:
                    hit = True
                out[pod] = (s, n)
            if hit:
                self.metrics_hits += 1
        return out

    def matched_pages(self, hashes: list[str], pod: str) -> int:
        return self.score_detailed(hashes, [pod])[pod][1]

    # ---------------------------------------------------------- misc

    @property
    def size(self) -> int:
        # DBSIZE counts pod sets too; good enough for the size gauge.
        try:
            return int(self.client.command("DBSIZE"))
        # llmd: allow(broad-except) -- size gauge probe: a down Redis reads as 0; apply() owns surfacing the outage
        except Exception:
            return 0

    def stats(self) -> dict[str, int]:
        blocks = self.size  # network probe: outside the lock
        with self._lock:
            return {
                "blocks": blocks,
                "events": self.metrics_events,
                "lookups": self.metrics_lookups,
                "hits": self.metrics_hits,
            }

    def close(self) -> None:
        self.client.close()
