"""KV block index: chained block-hash -> pods, with tiers and speculation.

Reference behavior (kv-indexer.md:91-143):
  * block key -> set of pods holding it, each with a medium/tier;
  * two-level in-memory LRU backend: a global hash map plus per-pod LRU
    ordering with a capacity cap (evict oldest per pod);
  * longest-consecutive-prefix scoring with tier weights (gpu=1.0, cpu=0.8,
    kv-indexer.md:133);
  * speculative indexing: after a routing decision the picked pod is
    presumed to hold the prompt's blocks for a short TTL (2s,
    kv-indexer.md:137-143) so bursts of identical prompts co-route before
    the first BlockStored arrives.

Federation extension (docs/architecture/kv-federation.md): a
``BlockStored(medium="store")`` event means the publishing pod placed
the block in the FLEET-WIDE store — one peer-to-peer fetch away from
EVERY pod. Scoring becomes tri-state: a pod that holds a block scores
its resident tier (gpu/cpu), a pod that does not scores the ``store``
weight when any pod published it, and only blocks in neither state
break the consecutive-prefix walk (they would be recomputed). The
weight table is configurable per deployment via
``LLMD_PREFIX_TIER_WEIGHTS`` (e.g. ``"cpu=0.7,store=0.4"``) or the
scorer's ``tier_weights`` parameter — store fetch cost relative to
recompute varies with interconnect and model size.

Thread-safety: one lock; subscriber threads write, scheduler reads.
"""

from __future__ import annotations

import collections
import logging
import os
import threading
import time

log = logging.getLogger(__name__)

# The default weight table: resident tiers from kv-indexer.md:133, the
# store tier between "resident on CPU" and "worthless" — a fetch beats a
# re-prefill but loses to a local copy.
DEFAULT_TIER_WEIGHTS = {
    "gpu": 1.0, "hbm": 1.0, "cpu": 0.8, "disk": 0.6, "store": 0.5,
}
# Back-compat alias (importers predating the configurable table).
TIER_WEIGHTS = DEFAULT_TIER_WEIGHTS

TIER_WEIGHTS_ENV = "LLMD_PREFIX_TIER_WEIGHTS"

# Reserved holder for fleet-global store copies: a BlockStored
# (medium="store") event books under this pseudo-pod rather than the
# publishing pod, so the publication never DOWNGRADES the publisher's
# own resident-tier entry (the publisher still holds the page in a
# host tier) and the store copy outlives the publisher's evictions.
STORE_POD = "!store"

SPECULATIVE_TTL_S = 2.0


def parse_tier_weights(raw: str) -> dict[str, float]:
    """Parse ``tier=weight,...`` overrides (the shared syntax of
    ``LLMD_PREFIX_TIER_WEIGHTS`` and the router's
    ``--prefix-tier-weights`` flag). Unparseable entries are logged and
    skipped — a typo must not zero the scorer."""
    weights: dict[str, float] = {}
    for item in raw.split(","):
        item = item.strip()
        if not item:
            continue
        tier, sep, value = item.partition("=")
        try:
            if not sep:
                raise ValueError("missing '='")
            weights[tier.strip()] = float(value)
        except ValueError as e:
            log.warning(
                "%s: ignoring entry %r (%s)", TIER_WEIGHTS_ENV, item, e
            )
    return weights


def tier_weights_from_env(raw: str | None = None) -> dict[str, float]:
    """The deployment's weight table: defaults overlaid with the
    ``LLMD_PREFIX_TIER_WEIGHTS`` env (``tier=weight,...``)."""
    weights = dict(DEFAULT_TIER_WEIGHTS)
    if raw is None:
        raw = os.environ.get(TIER_WEIGHTS_ENV, "")
    weights.update(parse_tier_weights(raw))
    return weights

_HALVE_TABLE = bytes(v >> 1 for v in range(256))


class KVBlockIndex:
    def __init__(
        self,
        max_blocks_per_pod: int = 131072,
        speculative_ttl_s: float = SPECULATIVE_TTL_S,
        tier_weights: dict[str, float] | None = None,
    ) -> None:
        self.max_blocks_per_pod = max_blocks_per_pod
        self.speculative_ttl_s = speculative_ttl_s
        self.tier_weights = tier_weights_from_env()
        if tier_weights:
            self.tier_weights.update(tier_weights)
        self._lock = threading.Lock()
        # hash -> {pod -> tier}
        self._blocks: dict[str, dict[str, str]] = {}  # llmd: guarded_by(_lock)
        # pod -> LRU of its hashes (right = newest)
        self._pod_lru: dict[str, collections.OrderedDict] = {}  # llmd: guarded_by(_lock)
        # (pod) -> list of (deadline, hashes) speculative entries
        self._spec: dict[str, dict[str, float]] = {}  # llmd: guarded_by(_lock)
        self.metrics_events = 0  # llmd: guarded_by(_lock)
        self.metrics_lookups = 0  # llmd: guarded_by(_lock)
        self.metrics_hits = 0  # llmd: guarded_by(_lock)

    # ------------------------------------------------------------------ #
    # event application (subscriber threads)

    def apply(self, pod: str, events: list[dict]) -> None:
        now = time.monotonic()
        with self._lock:
            for ev in events:
                self.metrics_events += 1
                t = ev.get("type")
                if t == "BlockStored":
                    tier = ev.get("medium", "gpu")
                    holder = STORE_POD if tier == "store" else pod
                    for h in ev.get("hashes", []):
                        self._store_locked(holder, h, tier)
                elif t == "BlockRemoved":
                    # A store-tier removal withdraws the fleet-global
                    # copy (master eviction reached the owner), not the
                    # emitting pod's resident entry.
                    holder = (
                        STORE_POD if ev.get("medium") == "store" else pod
                    )
                    for h in ev.get("hashes", []):
                        self._remove_locked(holder, h)
                elif t == "AllBlocksCleared":
                    self._clear_pod_locked(pod)
            # opportunistic speculative-entry expiry
            spec = self._spec.get(pod)
            if spec:
                dead = [h for h, dl in spec.items() if dl <= now]
                for h in dead:
                    del spec[h]

    def _pod_cap(self, pod: str) -> int:
        """The STORE_POD bucket aggregates the WHOLE fleet's
        publications, not one pod's cache — give it headroom over the
        per-pod cap so fleet-scale store inventories don't LRU out
        still-valid claims."""
        return self.max_blocks_per_pod * (8 if pod == STORE_POD else 1)

    def _store_locked(self, pod: str, h: str, tier: str) -> None:
        self._blocks.setdefault(h, {})[pod] = tier
        lru = self._pod_lru.setdefault(pod, collections.OrderedDict())
        lru[h] = None
        lru.move_to_end(h)
        if len(lru) > self._pod_cap(pod):
            self._evict_one_locked(pod, lru)

    def _evict_one_locked(self, pod: str, lru: collections.OrderedDict) -> None:
        """Eviction policy hook: base class evicts the LRU entry."""
        old, _ = lru.popitem(last=False)
        self._drop_locked(pod, old)

    def _remove_locked(self, pod: str, h: str) -> None:
        lru = self._pod_lru.get(pod)
        if lru is not None:
            lru.pop(h, None)
        self._drop_locked(pod, h)

    def _drop_locked(self, pod: str, h: str) -> None:
        pods = self._blocks.get(h)
        if pods is not None:
            pods.pop(pod, None)
            if not pods:
                del self._blocks[h]

    def _clear_pod_locked(self, pod: str) -> None:
        lru = self._pod_lru.pop(pod, None)
        if lru:
            for h in lru:
                self._drop_locked(pod, h)
        self._spec.pop(pod, None)

    def remove_pod(self, pod: str) -> None:
        """Endpoint left the pool: drop everything it held."""
        with self._lock:
            self._clear_pod_locked(pod)

    # ------------------------------------------------------------------ #
    # speculative entries (scheduler thread, after a pick)

    def insert_speculative(self, pod: str, hashes: list[str]) -> None:
        now = time.monotonic()
        deadline = now + self.speculative_ttl_s
        with self._lock:
            spec = self._spec.setdefault(pod, {})
            # Prune here too: pods that never publish events would otherwise
            # accumulate expired entries forever (apply() never runs for them).
            dead = [h for h, dl in spec.items() if dl <= now]
            for h in dead:
                del spec[h]
            for h in hashes:
                spec[h] = deadline

    # ------------------------------------------------------------------ #
    # scoring (scheduler thread)

    def _pod_has_locked(self, pod: str, h: str, now: float) -> str | None:
        """Tier if the pod holds block h (confirmed or speculative)."""
        pods = self._blocks.get(h)
        if pods is not None and pod in pods:
            return pods[pod]
        spec = self._spec.get(pod)
        if spec is not None:
            dl = spec.get(h)
            if dl is not None and dl > now:
                return "gpu"  # speculative entries presume the hot tier
        return None

    def _tier_for_locked(self, pod: str, h: str, now: float) -> str | None:
        """Tri-state tier (kv-federation.md): resident-on-pod beats
        speculative beats one-fetch-away-in-store; None = recompute."""
        tier = self._pod_has_locked(pod, h, now)
        if tier is not None:
            return tier
        pods = self._blocks.get(h)
        if pods is not None and "store" in pods.values():
            # Published to the fleet-wide store: any pod can pull it
            # peer-to-peer instead of re-prefilling.
            return "store"
        return None

    def score(self, hashes: list[str], pods: list[str]) -> dict[str, float]:
        """Weighted longest-consecutive-prefix per pod (kv-indexer.md:120-135)."""
        return {p: s for p, (s, _) in self.score_detailed(hashes, pods).items()}

    def score_detailed(
        self, hashes: list[str], pods: list[str]
    ) -> dict[str, tuple[float, int]]:
        """One walk per pod: (weighted score, matched page count).

        Score = sum of tier weights over the longest run of leading blocks
        the pod holds; count = that run's length.
        """
        now = time.monotonic()
        out: dict[str, tuple[float, int]] = {}
        with self._lock:
            self.metrics_lookups += 1
            hit = False
            for pod in pods:
                s, n = 0.0, 0
                for h in hashes:
                    tier = self._tier_for_locked(pod, h, now)
                    if tier is None:
                        break
                    s += self.tier_weights.get(tier, 0.5)
                    n += 1
                if n:
                    hit = True
                out[pod] = (s, n)
            if hit:
                self.metrics_hits += 1
        return out

    def matched_pages(self, hashes: list[str], pod: str) -> int:
        """Unweighted longest-consecutive-prefix length for one pod
        (store-fetchable blocks count: they land via fetch-on-miss, not
        recompute, so admission treats them like a cache hit)."""
        now = time.monotonic()
        n = 0
        with self._lock:
            for h in hashes:
                if self._tier_for_locked(pod, h, now) is None:
                    break
                n += 1
        return n

    # ------------------------------------------------------------------ #

    @property
    def size(self) -> int:
        with self._lock:
            return len(self._blocks)

    def close(self) -> None:
        pass

    def stats(self) -> dict[str, int]:
        with self._lock:
            return {
                "blocks": len(self._blocks),
                "pods": len(self._pod_lru),
                "events": self.metrics_events,
                "lookups": self.metrics_lookups,
                "hits": self.metrics_hits,
                "store_blocks": sum(
                    1 for holders in self._blocks.values()
                    if "store" in holders.values()
                ),
            }


class CostAwareKVBlockIndex(KVBlockIndex):
    """Cost-aware backend (the reference's Ristretto option,
    kv-indexer.md:59-151): a counting sketch estimates each block's
    lookup frequency, and eviction removes the LEAST-FREQUENT of a
    sample of the pod's oldest entries instead of the strict LRU head —
    long-lived shared prefixes (system prompts) survive bursts of
    one-shot traffic that would churn a pure LRU.

    The sketch is a 4-bit count-min with periodic halving (TinyLFU
    aging), so hot entries stay distinguishable without unbounded
    counters.
    """

    SKETCH_BITS = 16  # 2**16 counters per row
    ROWS = 4
    MAX_COUNT = 15
    SAMPLE = 8

    def __init__(self, *args, **kwargs) -> None:
        super().__init__(*args, **kwargs)
        import array

        self._sketch = [  # llmd: guarded_by(_lock)
            array.array("B", bytes(1 << self.SKETCH_BITS))
            for _ in range(self.ROWS)
        ]
        self._ops = 0  # llmd: guarded_by(_lock)
        # halve all counters every ~16x the per-pod capacity of touches
        self._reset_every = 16 * max(self.max_blocks_per_pod, 1)

    def _hashes_of(self, h: str) -> list[int]:
        v = hash(h) & 0xFFFFFFFFFFFFFFFF
        out = []
        for r in range(self.ROWS):
            out.append((v >> (r * self.SKETCH_BITS)) & ((1 << self.SKETCH_BITS) - 1))
        return out

    def _touch_locked(self, h: str) -> None:
        self._ops += 1
        for row, idx in zip(self._sketch, self._hashes_of(h)):
            if row[idx] < self.MAX_COUNT:
                row[idx] += 1
        if self._ops >= self._reset_every:
            self._ops = 0
            # bytes.translate halves all 65536 counters per row in C —
            # a Python loop here would stall scheduling under the lock.
            for row in self._sketch:
                row[:] = type(row)("B", bytes(row).translate(_HALVE_TABLE))

    def _freq_locked(self, h: str) -> int:
        return min(
            row[idx] for row, idx in zip(self._sketch, self._hashes_of(h))
        )

    def _store_locked(self, pod: str, h: str, tier: str) -> None:
        self._touch_locked(h)
        super()._store_locked(pod, h, tier)

    def _pod_has_locked(self, pod: str, h: str, now: float):
        tier = super()._pod_has_locked(pod, h, now)
        if tier is not None:
            self._touch_locked(h)  # lookup hits drive frequency
        return tier

    def _evict_one_locked(self, pod: str, lru: collections.OrderedDict) -> None:
        sample = []
        for h in lru:  # oldest first
            sample.append(h)
            if len(sample) >= self.SAMPLE:
                break
        victim = min(sample, key=self._freq_locked)
        lru.pop(victim, None)
        self._drop_locked(pod, victim)
