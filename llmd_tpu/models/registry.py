"""Named model configurations.

Covers the model families the reference's guides deploy (SURVEY.md section 6
/ BASELINE.json configs): Llama-3 (8B/70B), Qwen2/Qwen3-class dense,
Mixtral 8x7B/8x22B and DeepSeek-style wide-EP MoE. Exact hyperparameters
follow the public HF configs for each family.
"""

from __future__ import annotations

import dataclasses
from typing import Callable

from llmd_tpu.config import ModelConfig, tiny_model_config

_REGISTRY: dict[str, Callable[[], ModelConfig]] = {}


def register_model(name: str):
    def deco(fn: Callable[[], ModelConfig]):
        _REGISTRY[name] = fn
        return fn
    return deco


def get_model_config(name: str, **overrides) -> ModelConfig:
    if name not in _REGISTRY:
        raise KeyError(f"unknown model {name!r}; known: {sorted(_REGISTRY)}")
    cfg = _REGISTRY[name]()
    if not overrides:
        return cfg
    # Rebuild so derived fields (head_dim, moe_intermediate_size) are
    # re-derived when their bases change, unless they were explicitly set.
    kw = dataclasses.asdict(cfg)
    if cfg.head_dim == cfg.hidden_size // cfg.num_heads and "head_dim" not in overrides:
        kw["head_dim"] = None
    if (
        cfg.moe_intermediate_size == cfg.intermediate_size
        and "moe_intermediate_size" not in overrides
    ):
        kw["moe_intermediate_size"] = None
    kw.update(overrides)
    return ModelConfig(**kw)


def list_models() -> list[str]:
    return sorted(_REGISTRY)


@register_model("tiny")
def _tiny() -> ModelConfig:
    return tiny_model_config()


@register_model("tiny-moe")
def _tiny_moe() -> ModelConfig:
    return tiny_model_config(
        name="tiny-moe", num_experts=8, num_experts_per_tok=2,
        moe_intermediate_size=64,
    )


@register_model("tiny-swa")
def _tiny_swa() -> ModelConfig:
    """Alternating sliding/full layers in miniature (gpt-oss layout) —
    the serving-level fixture for --kv-swa-ring and hybrid-APC paths."""
    return tiny_model_config(
        name="tiny-swa", sliding_window=64,
        layer_types=("sliding_attention", "full_attention"),
    )


@register_model("tiny-mla")
def _tiny_mla() -> ModelConfig:
    """CPU-testable MLA+MoE shape (DeepSeek architecture in miniature)."""
    return tiny_model_config(
        name="tiny-mla", kv_lora_rank=32, q_lora_rank=24,
        qk_nope_head_dim=16, qk_rope_head_dim=8, v_head_dim=16,
        num_experts=4, num_experts_per_tok=2, moe_intermediate_size=32,
        shared_expert_intermediate_size=32, first_dense_layers=1,
        num_layers=3,
    )


@register_model("llama-3.2-3b")
def _llama32_3b() -> ModelConfig:
    return ModelConfig(
        name="llama-3.2-3b", vocab_size=128256, hidden_size=3072,
        intermediate_size=8192, num_layers=28, num_heads=24, num_kv_heads=8,
        head_dim=128, rope_theta=500000.0, max_model_len=8192,
        tie_word_embeddings=True,
    )


@register_model("llama-3-8b")
def _llama3_8b() -> ModelConfig:
    return ModelConfig(
        name="llama-3-8b", vocab_size=128256, hidden_size=4096,
        intermediate_size=14336, num_layers=32, num_heads=32, num_kv_heads=8,
        rope_theta=500000.0, max_model_len=8192,
    )


@register_model("llama-3-70b")
def _llama3_70b() -> ModelConfig:
    return ModelConfig(
        name="llama-3-70b", vocab_size=128256, hidden_size=8192,
        intermediate_size=28672, num_layers=80, num_heads=64, num_kv_heads=8,
        rope_theta=500000.0, max_model_len=8192,
    )


@register_model("qwen2-72b")
def _qwen2_72b() -> ModelConfig:
    return ModelConfig(
        name="qwen2-72b", vocab_size=152064, hidden_size=8192,
        intermediate_size=29568, num_layers=80, num_heads=64, num_kv_heads=8,
        rope_theta=1000000.0, max_model_len=32768, attention_bias=True,
        rms_norm_eps=1e-6,
    )


@register_model("qwen3-32b")
def _qwen3_32b() -> ModelConfig:
    """Qwen3-32B (HF Qwen/Qwen3-32B) — the reference's prefix-cache and
    tiered-offload benchmark model (SURVEY.md §6). QK-norm, no bias."""
    return ModelConfig(
        name="qwen3-32b", vocab_size=151936, hidden_size=5120,
        intermediate_size=25600, num_layers=64, num_heads=64, num_kv_heads=8,
        head_dim=128, rope_theta=1000000.0, max_model_len=40960,
        qk_norm=True,
    )


@register_model("qwen3-30b-a3b")
def _qwen3_30b_a3b() -> ModelConfig:
    """Qwen3-30B-A3B (MoE): 128 experts, top-8, QK-norm."""
    return ModelConfig(
        name="qwen3-30b-a3b", vocab_size=151936, hidden_size=2048,
        intermediate_size=6144, num_layers=48, num_heads=32, num_kv_heads=4,
        head_dim=128, rope_theta=1000000.0, max_model_len=40960,
        qk_norm=True,
        num_experts=128, num_experts_per_tok=8, moe_intermediate_size=768,
    )


@register_model("mixtral-8x7b")
def _mixtral_8x7b() -> ModelConfig:
    return ModelConfig(
        name="mixtral-8x7b", vocab_size=32000, hidden_size=4096,
        intermediate_size=14336, num_layers=32, num_heads=32, num_kv_heads=8,
        rope_theta=1000000.0, max_model_len=32768,
        num_experts=8, num_experts_per_tok=2, moe_intermediate_size=14336,
    )


@register_model("mixtral-8x22b")
def _mixtral_8x22b() -> ModelConfig:
    return ModelConfig(
        name="mixtral-8x22b", vocab_size=32768, hidden_size=6144,
        intermediate_size=16384, num_layers=56, num_heads=48, num_kv_heads=8,
        rope_theta=1000000.0, max_model_len=65536,
        num_experts=8, num_experts_per_tok=2, moe_intermediate_size=16384,
    )


@register_model("deepseek-moe-wide")
def _deepseek_wide() -> ModelConfig:
    """DeepSeek-R1-class wide-EP shape (GQA stand-in for MLA; 256 experts,
    top-8, shared expert) -- the BASELINE.json config-3 target geometry."""
    return ModelConfig(
        name="deepseek-moe-wide", vocab_size=129280, hidden_size=7168,
        intermediate_size=18432, num_layers=61, num_heads=128, num_kv_heads=16,
        head_dim=64,
        rope_theta=10000.0, max_model_len=16384,
        num_experts=256, num_experts_per_tok=8, moe_intermediate_size=2048,
        shared_expert_intermediate_size=2048,
    )


@register_model("gpt-oss-20b")
def _gpt_oss_20b() -> ModelConfig:
    """gpt-oss-20b (HF openai/gpt-oss-20b): alternating sliding/full
    attention with per-head sinks, 32 experts top-4 with clamped-swiglu
    biased experts, yarn rope — the reference's flagship P/D benchmark
    model (guides/pd-disaggregation/README.md:600-615)."""
    return ModelConfig(
        name="gpt-oss-20b", vocab_size=201088, hidden_size=2880,
        intermediate_size=2880, num_layers=24, num_heads=64,
        num_kv_heads=8, head_dim=64, rope_theta=150000.0,
        max_model_len=131072,
        sliding_window=128,
        layer_types=tuple(
            "sliding_attention" if i % 2 == 0 else "full_attention"
            for i in range(24)
        ),
        attention_bias=True, attention_out_bias=True, attention_sinks=True,
        num_experts=32, num_experts_per_tok=4, moe_intermediate_size=2880,
        moe_activation="swiglu_oss", router_logit_bias=True,
        norm_topk_prob=True,
        rope_scaling={
            "rope_type": "yarn", "factor": 32.0, "beta_fast": 32.0,
            "beta_slow": 1.0, "original_max_position_embeddings": 4096,
        },
    )


@register_model("deepseek-v2-lite")
def _deepseek_v2_lite() -> ModelConfig:
    """DeepSeek-V2-Lite (HF deepseek-ai/DeepSeek-V2-Lite): MLA without a
    query LoRA, 64 routed + 2 shared experts, first layer dense."""
    return ModelConfig(
        name="deepseek-v2-lite", vocab_size=102400, hidden_size=2048,
        intermediate_size=10944, num_layers=27, num_heads=16,
        num_kv_heads=16, rope_theta=10000.0, max_model_len=32768,
        kv_lora_rank=512, q_lora_rank=0,
        qk_nope_head_dim=128, qk_rope_head_dim=64, v_head_dim=128,
        num_experts=64, num_experts_per_tok=6, moe_intermediate_size=1408,
        shared_expert_intermediate_size=2816, first_dense_layers=1,
    )


@register_model("deepseek-r1")
def _deepseek_r1() -> ModelConfig:
    """DeepSeek-V3/R1 (HF deepseek-ai/DeepSeek-R1): full MLA (q LoRA 1536,
    kv latent 512+64), 256 routed + 1 shared expert, top-8, first 3 layers
    dense -- the reference wide-EP headline model (SURVEY.md §3.3)."""
    return ModelConfig(
        name="deepseek-r1", vocab_size=129280, hidden_size=7168,
        intermediate_size=18432, num_layers=61, num_heads=128,
        num_kv_heads=128, rope_theta=10000.0, max_model_len=163840,
        kv_lora_rank=512, q_lora_rank=1536,
        qk_nope_head_dim=128, qk_rope_head_dim=64, v_head_dim=128,
        num_experts=256, num_experts_per_tok=8, moe_intermediate_size=2048,
        shared_expert_intermediate_size=2048, first_dense_layers=3,
    )
