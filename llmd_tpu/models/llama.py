"""Llama-class decoder (covers Llama-2/3, Qwen2, Mixtral/MoE via config).

Functional, TPU-first: layer params are STACKED along a leading L axis and
the forward pass is one ``lax.scan`` over layers -- one XLA while-loop body
instead of L inlined layers, so compile time is O(1) in depth and the paged
KV cache ([L, pages, K, page, 2D], head-major pages) is scanned in lock-step.

Reference parity: this is the model-execution role the reference delegates
to vLLM (docs/architecture/core/model-servers.md:3-25); the MoE path is the
wide-EP target (docs/architecture/foundations/wide-expert-parallelism.md).
"""

from __future__ import annotations

import zlib

import jax
import jax.numpy as jnp

from llmd_tpu.config import ModelConfig
from llmd_tpu.models.common import (
    StepInput, apply_rope, param_dtype, pdot, rms_norm, rope_tables,
)
from llmd_tpu.models.moe import moe_block
from llmd_tpu.ops import (
    paged_attention_full,
    paged_attention_full_flat,
    write_kv_pages_full,
    write_kv_pages_full_flat,
)
from llmd_tpu.ops.ring_attention import ring_prefill_attention_full


def init_params(cfg: ModelConfig, key: jax.Array) -> dict:
    """Deterministic random init (used for tests/bench and as the template
    for weight loading)."""
    dt = param_dtype(cfg)
    H, D = cfg.hidden_size, cfg.head_dim
    Nq, K, L = cfg.num_heads, cfg.num_kv_heads, cfg.num_layers
    F, V = cfg.intermediate_size, cfg.vocab_size

    def mk(name: str, shape: tuple[int, ...], scale: float | None = None) -> jax.Array:
        # zlib.crc32 is stable across processes (Python's hash() is salted).
        k = jax.random.fold_in(key, zlib.crc32(name.encode()) % (2**31))
        if scale is None:
            scale = shape[-2] ** -0.5 if len(shape) >= 2 else 1.0
        return (jax.random.normal(k, shape, jnp.float32) * scale).astype(dt)

    def layer_stack(n: int, moe: bool, prefix: str = "") -> dict[str, jax.Array]:
        """n stacked layers: attention (MLA or GQA) + dense-MLP or MoE."""

        def mkp(name, shape, scale=None):
            return mk(prefix + name, shape, scale)

        layers: dict[str, jax.Array] = {
            "input_norm": jnp.ones((n, H), dt),
            "post_norm": jnp.ones((n, H), dt),
        }
        if cfg.is_mla:
            nope, rope, vd = (
                cfg.qk_nope_head_dim, cfg.qk_rope_head_dim, cfg.v_head_dim,
            )
            rank = cfg.kv_lora_rank
            layers["wkv_a"] = mkp("wkv_a", (n, H, rank + rope))
            layers["kv_norm"] = jnp.ones((n, rank), dt)
            layers["wkv_b"] = mkp("wkv_b", (n, rank, Nq * (nope + vd)))
            layers["wo"] = mkp("wo", (n, Nq * vd, H))
            if cfg.q_lora_rank > 0:
                layers["wq_a"] = mkp("wq_a", (n, H, cfg.q_lora_rank))
                layers["q_norm"] = jnp.ones((n, cfg.q_lora_rank), dt)
                layers["wq_b"] = mkp(
                    "wq_b", (n, cfg.q_lora_rank, Nq * (nope + rope))
                )
            else:
                layers["wq"] = mkp("wq", (n, H, Nq * (nope + rope)))
        else:
            layers["wq"] = mkp("wq", (n, H, Nq * D))
            layers["wk"] = mkp("wk", (n, H, K * D))
            layers["wv"] = mkp("wv", (n, H, K * D))
            layers["wo"] = mkp("wo", (n, Nq * D, H))
        if cfg.attention_bias:
            layers["bq"] = jnp.zeros((n, Nq * D), dt)
            layers["bk"] = jnp.zeros((n, K * D), dt)
            layers["bv"] = jnp.zeros((n, K * D), dt)
        if cfg.attention_out_bias:
            layers["bo"] = jnp.zeros((n, H), dt)
        if cfg.attention_sinks:
            layers["sinks"] = mk("sinks", (n, Nq), scale=1.0)
        if cfg.qk_norm:
            layers["attn_q_norm"] = jnp.ones((n, D), dt)
            layers["attn_k_norm"] = jnp.ones((n, D), dt)
        if cfg.num_lora_adapters and not cfg.is_mla:
            # Adapter slot 0 = base model (zeros); slots 1..A are live
            # adapters on the q and v projections (the classic target set).
            A1, r = cfg.num_lora_adapters + 1, cfg.lora_rank
            mask = (jnp.arange(A1) > 0).astype(dt)[None, :, None, None]
            layers["la_q"] = mk("la_q", (n, A1, H, r)) * mask
            layers["la_v"] = mk("la_v", (n, A1, H, r)) * mask
            # Standard LoRA init: B starts at zero so every adapter slot is
            # exactly the base model until real adapter weights are loaded
            # (random B would perturb outputs for adapter-named requests).
            layers["lb_q"] = jnp.zeros((n, A1, r, Nq * D), dt)
            layers["lb_v"] = jnp.zeros((n, A1, r, K * D), dt)
        if moe:
            E, Fm = cfg.num_experts, cfg.moe_intermediate_size
            layers["router"] = mkp("router", (n, H, E), scale=H**-0.5)
            if cfg.router_scoring == "sigmoid" or cfg.router_logit_bias:
                # V3-style selection-only correction bias (noaux_tc), or
                # gpt-oss's real logit bias — either way the leaf must
                # exist in the init tree (load_params' shape contract).
                layers["router_bias"] = jnp.zeros((n, E), jnp.float32)
            layers["we_gate"] = mkp("we_gate", (n, E, H, Fm))
            layers["we_up"] = mkp("we_up", (n, E, H, Fm))
            layers["we_down"] = mkp("we_down", (n, E, Fm, H))
            if cfg.moe_activation == "swiglu_oss":
                layers["we_gate_b"] = jnp.zeros((n, E, Fm), dt)
                layers["we_up_b"] = jnp.zeros((n, E, Fm), dt)
                layers["we_down_b"] = jnp.zeros((n, E, H), dt)
            if cfg.shared_expert_intermediate_size:
                Fs = cfg.shared_expert_intermediate_size
                layers["ws_gate"] = mkp("ws_gate", (n, H, Fs))
                layers["ws_up"] = mkp("ws_up", (n, H, Fs))
                layers["ws_down"] = mkp("ws_down", (n, Fs, H))
        else:
            layers["w_gate"] = mkp("w_gate", (n, H, F))
            layers["w_up"] = mkp("w_up", (n, H, F))
            layers["w_down"] = mkp("w_down", (n, F, H))
        return layers

    n_dense = cfg.first_dense_layers if cfg.is_moe else 0
    params: dict = {
        "embed": mk("embed", (V, H), scale=0.02),
        "layers": layer_stack(L - n_dense, moe=cfg.is_moe),
        "final_norm": jnp.ones((H,), dt),
    }
    if n_dense:
        params["dense_layers"] = layer_stack(n_dense, moe=False, prefix="dense_")
    if not cfg.tie_word_embeddings:
        params["lm_head"] = mk("lm_head", (H, V))
    if cfg.quantization == "int8":
        from llmd_tpu.ops.quant import quantize_param_tree

        # ONE jitted call with the bf16 tree donated: eager per-tensor
        # quantization leaves the device arena fragmented enough that the
        # first big prefill later OOMs (observed on v5e at 3B scale).
        params = jax.jit(quantize_param_tree, donate_argnums=0)(params)
    return params


def _mlp(h: jax.Array, lp: dict) -> jax.Array:
    if "w_gu" in lp:  # fused gate|up (runner._maybe_fuse; lossless)
        gu = pdot(h, lp, "w_gu")
        F = gu.shape[-1] // 2
        return pdot(jax.nn.silu(gu[..., :F]) * gu[..., F:], lp, "w_down")
    gate = jax.nn.silu(pdot(h, lp, "w_gate"))
    return pdot(gate * pdot(h, lp, "w_up"), lp, "w_down")


def _scan_period(kinds: tuple[int, ...]) -> int | None:
    """Smallest period c <= 4 of a layer-kind pattern (None if aperiodic).

    gpt-oss alternates sliding/full every layer (c=2); periodic patterns
    let the hybrid-pool scan run over CYCLES with the pool choice static
    per sub-layer — no lax.cond, so XLA keeps both pool carries in place.
    """
    n = len(kinds)
    for c in (2, 3, 4):
        if n % c == 0 and n > c and all(kinds[i] == kinds[i % c] for i in range(n)):
            return c
    return None


def forward_hidden(
    params: dict,
    kv_cache: jax.Array,  # [L_full, pages, K * kv_rep, page, 2D]
    inp: StepInput,
    cfg: ModelConfig,
    world_size: int = 1,
    mesh=None,
    moe_backend: str = "dense",
    ep_capacity_factor: float = 2.0,
    kv_rep: int = 1,
    dbo: bool = False,
    kv_swa: jax.Array | None = None,
    moe_overlap: int = 0,
    moe_placement: dict | None = None,
    moe_census: jax.Array | None = None,
    cp_prefill: int = 0,
):
    """Run the decoder stack; returns (hidden [B, Q, H], new kv_cache) —
    or (hidden, new kv_cache, new kv_swa) when ``kv_swa`` is given.
    When ``moe_census`` (the runner's [E+2] accumulator) is given, the
    updated census is appended to the return tuple.

    ``moe_overlap``/``moe_placement``/``moe_census`` plumb the wide-EP
    perf layers into ``moe_block_ep`` (parallel/moe_ep.py): microbatched
    overlapped dispatch, the EPLB physical-placement tables, and the
    per-expert routed-token / dropped-slot / dispatch-demand stats vector
    (merged across layers as a scan output: counts add, demand maxes).
    All three are no-ops unless ``moe_backend == "ep"``.

    ``kv_swa`` (CacheConfig.swa_ring) is a second, smaller pool holding
    ONLY the sliding-window layers; those layers index it through
    ``inp.swa_page_table``, the ring-view table whose entries repeat
    modulo the per-sequence ring length. The attention kernels are
    unchanged: their window-skip never reads logical pages older than the
    window, which are exactly the ring slots that have been overwritten.

    ``moe_backend="ep"`` routes MoE layers through the shard_map all-to-all
    dispatch/combine (wide-EP; requires ``mesh``). ``kv_rep`` > 1 stores
    each KV head ``kv_rep`` times consecutively so the pool's head axis
    divides tp when num_kv_heads alone does not (tp > K): per-chip KV is
    then pool/K instead of a full replicated pool. Attention grouping
    stays exact — q head h reads expanded head h // (Nq / (K*kv_rep)),
    which holds h's original kv head.

    ``dbo`` (dual-batch overlap — the reference's --enable-dbo for wide-EP
    decode, wide-ep decode.yaml:125-126): each layer writes KV for the
    FULL batch once, then runs the read-only attention + FFN pipeline as
    two independent half-batch chains. Half 1's attention carries no data
    dependency on half 0's MoE dispatch, so XLA's latency-hiding
    scheduler can overlap the EP all-to-all of one half with the other
    half's attention compute. Half-batch EP calls get a doubled
    capacity_factor so absolute per-expert capacity matches the full
    batch; numerics are then exact unless EP capacity binds (a half's
    routing demand is compared against full capacity separately, so DBO
    can only drop FEWER tokens, never different ones below capacity).
    Requires an even batch.

    ``cp_prefill`` > 1 (ParallelConfig.cp_prefill) runs each layer's
    attention as a context-parallel ring over the mesh "dp" axis
    (ops/ring_attention.py): the chunk's query rows and fresh K/V shard
    contiguously across dp, K/V blocks rotate via ppermute while every
    shard folds online-softmax partials, and the committed prefix is
    read from the post-write pool — tolerance-equal to the monolithic
    path. Only engaged for the bucketed non-DBO layout with Q divisible
    by cp (the runner compiles a dedicated prefill program for it)."""
    B, Q = inp.token_ids.shape
    D, Nq, K = cfg.head_dim, cfg.num_heads, cfg.num_kv_heads
    x = params["embed"][inp.token_ids]  # [B, Q, H]
    # one rope table for all layers (hoisted out of the scan); MLA rotates
    # only its rope sub-dim
    rope_dim = cfg.qk_rope_head_dim if cfg.is_mla else D
    cos, sin = rope_tables(inp.positions, rope_dim, cfg.rope_theta, cfg.rope_scaling)
    valid = inp.valid
    sm_scale = D**-0.5

    # DBO also requires the HALF batch to stay dp-divisible, or the split
    # would silently demote attention from the sharded Pallas kernel to
    # the pool-slicing XLA fallback (ops._mesh_plan's B % dp gate) —
    # slower and memory-hungrier, the opposite of the knob's intent.
    _dp = mesh.shape["dp"] if mesh is not None and "dp" in mesh.axis_names else 1
    # Flattened-token layout (inp.token_rows): the batch axis IS the
    # packed token stream; attention/writes route through the cu_q_lens
    # entry points below. DBO keeps the bucketed layout only (its
    # half-batch table slicing assumes per-row tables).
    flat = inp.token_rows is not None
    use_dbo = (
        bool(dbo) and not flat and B >= 2 and B % 2 == 0
        and (B // 2) % _dp == 0
    )
    half = B // 2
    cp_ring = (
        cp_prefill > 1 and mesh is not None and not flat and not use_dbo
        and not cfg.is_mla and Q % cp_prefill == 0
    )

    use_census = moe_census is not None and cfg.is_moe and moe_backend == "ep"

    def _census_merge(a, b):
        # Census layout (moe_ep): counts in [:-1] add, the max-demand
        # element in [-1] maxes.
        return jnp.concatenate([a[:-1] + b[:-1], jnp.maximum(a[-1:], b[-1:])])

    def _ffn(h2, lp, use_moe: bool, cap_scale: float = 1.0):
        """FFN/MoE of one slice; returns (y, census_delta | None)."""
        if use_moe:
            if moe_backend == "ep":
                from llmd_tpu.parallel.moe_ep import moe_block_ep

                out = moe_block_ep(
                    h2, lp, cfg, mesh,
                    capacity_factor=ep_capacity_factor * cap_scale,
                    overlap=moe_overlap, placement=moe_placement,
                    emit_census=use_census,
                )
                return out if use_census else (out, None)
            if moe_backend == "grouped" and world_size == 1:
                from llmd_tpu.models.moe import moe_block_grouped

                return moe_block_grouped(h2, lp, cfg), None
            # Sharded jit without the EP backend: the dense combine is
            # the only path GSPMD can partition (expert weights are
            # EP-sharded; the grouped kernel has no partitioning rule
            # — multi-device MoE should run moe_backend="ep", whose
            # shard_map body uses the grouped GEMM locally).
            return moe_block(h2, lp, cfg), None
        return _mlp(h2, lp), None

    def _tail(x_sl, attn_sl, lp, use_moe, cap_scale: float = 1.0):
        """Post-attention chain of one (micro)batch slice: residual +
        post-norm + FFN/MoE + residual. Returns (x, census_delta)."""
        x_sl = x_sl + attn_sl
        h2 = rms_norm(x_sl, lp["post_norm"], cfg.rms_norm_eps)
        y, cd = _ffn(h2, lp, use_moe, cap_scale)
        return x_sl + y, cd

    def _tails_dbo(pairs):
        """Concatenate DBO half-chain _tail results; merge census deltas."""
        xs, cds = zip(*pairs)
        cd = cds[0]
        for c in cds[1:]:
            cd = c if cd is None else _census_merge(cd, c)
        return jnp.concatenate(xs, axis=0), cd

    def layer_body(x, cache, lp, layer_idx, use_moe: bool, window=None,
                   table=None, run_phys=None):
        """One decoder layer; returns (x, cache, census_delta | None)."""
        if table is None:
            table = inp.page_table
        h = rms_norm(x, lp["input_norm"], cfg.rms_norm_eps)
        if cfg.is_mla:
            from llmd_tpu.models.mla import mla_attention, mla_read, mla_write

            if use_dbo:
                # DBO: one full-batch write, then two independent
                # read-only half chains (attention -> MoE).
                cache, q_eff = mla_write(
                    h, lp, cache, layer_idx, inp, cfg, cos, sin,
                    world_size=world_size, mesh=mesh,
                )
                outs = []
                for sl in (slice(0, half), slice(half, B)):
                    attn_sl = mla_read(
                        q_eff[sl], lp, cache, layer_idx,
                        inp.page_table[sl], inp.kv_lens[sl],
                        inp.positions[sl], cfg,
                        world_size=world_size, mesh=mesh,
                    )
                    outs.append(_tail(x[sl], attn_sl, lp, use_moe, 2.0))
                x2, cd = _tails_dbo(outs)
                return x2, cache, cd
            attn_out, cache = mla_attention(
                h, lp, cache, layer_idx, inp, cfg, cos, sin,
                world_size=world_size, mesh=mesh,
            )
            x = x + attn_out
        else:
            if "wqkv" in lp:  # fused q|k|v (runner._maybe_fuse; lossless)
                qkv = pdot(h, lp, "wqkv")
                q = qkv[..., : Nq * D]
                k = qkv[..., Nq * D : (Nq + K) * D]
                v = qkv[..., (Nq + K) * D :]
            else:
                q = pdot(h, lp, "wq")
                k = pdot(h, lp, "wk")
                v = pdot(h, lp, "wv")
            if cfg.attention_bias:
                q, k, v = q + lp["bq"], k + lp["bk"], v + lp["bv"]
            if cfg.num_lora_adapters and inp.lora_ids is not None:
                # Per-sequence adapters: gather each row's A/B and apply
                # x@A@B on q and v (batched einsum; slot 0 is zeros).
                la_q = lp["la_q"][inp.lora_ids]  # [B, H, r]
                lb_q = lp["lb_q"][inp.lora_ids]  # [B, r, Nq*D]
                la_v = lp["la_v"][inp.lora_ids]
                lb_v = lp["lb_v"][inp.lora_ids]
                q = q + jnp.einsum(
                    "bqr,brd->bqd", jnp.einsum("bqh,bhr->bqr", h, la_q), lb_q
                )
                v = v + jnp.einsum(
                    "bqr,brd->bqd", jnp.einsum("bqh,bhr->bqr", h, la_v), lb_v
                )
            q = q.reshape(B, Q, Nq, D)
            k = k.reshape(B, Q, K, D)
            if cfg.qk_norm:  # Qwen3: per-head RMS norm before RoPE
                q = rms_norm(q, lp["attn_q_norm"], cfg.rms_norm_eps)
                k = rms_norm(k, lp["attn_k_norm"], cfg.rms_norm_eps)
            q = apply_rope(q, cos, sin)
            k = apply_rope(k, cos, sin)
            v = v.reshape(B, Q, K, D)
            if kv_rep > 1:
                k = jnp.repeat(k, kv_rep, axis=2)
                v = jnp.repeat(v, kv_rep, axis=2)
            if flat:
                cache = write_kv_pages_full_flat(
                    cache, layer_idx, k, v, table, inp.token_rows,
                    inp.positions, valid,
                    (*inp.flat_runs[0], run_phys)
                    if inp.flat_runs is not None and run_phys is not None
                    else None,
                    world_size=world_size, mesh=mesh,
                )
            else:
                cache = write_kv_pages_full(
                    cache, layer_idx, k, v, table, inp.positions, valid,
                    world_size=world_size, mesh=mesh,
                )
            sinks = lp.get("sinks")

            def _project(attn_sl, n_rows):
                out = pdot(attn_sl.reshape(n_rows, Q, Nq * D), lp, "wo")
                if "bo" in lp:
                    out = out + lp["bo"]
                return out

            if use_dbo:
                outs = []
                for sl in (slice(0, half), slice(half, B)):
                    attn_sl = paged_attention_full(
                        q[sl], cache, layer_idx, table[sl],
                        inp.kv_lens[sl], inp.positions[sl], sm_scale,
                        world_size=world_size, mesh=mesh, window=window,
                        sinks=sinks,
                    )
                    outs.append(
                        _tail(x[sl], _project(attn_sl, half), lp, use_moe, 2.0)
                    )
                x2, cd = _tails_dbo(outs)
                return x2, cache, cd
            if flat:
                attn = paged_attention_full_flat(
                    q, cache, layer_idx, inp.token_rows, table,
                    inp.kv_lens, inp.positions, sm_scale,
                    world_size=world_size, mesh=mesh, window=window,
                    sinks=sinks,
                )
            elif cp_ring:
                attn = ring_prefill_attention_full(
                    q, cache, layer_idx, k, v, table, inp.kv_lens,
                    inp.positions, valid, sm_scale, mesh=mesh,
                    cp=cp_prefill, window=window, sinks=sinks,
                )
            else:
                attn = paged_attention_full(
                    q, cache, layer_idx, table, inp.kv_lens, inp.positions,
                    sm_scale, world_size=world_size, mesh=mesh, window=window,
                    sinks=sinks,
                )
            x = x + _project(attn, B)
        # attention residual already applied above; _tail adds 0
        x, cd = _tail(x, 0.0, lp, use_moe)
        return x, cache, cd

    # DeepSeek-style dense prefix: the first N layers (N static, 1-3)
    # run unrolled with their own dense-MLP weights; the homogeneous MoE
    # (or dense) remainder rides lax.scan with the cache(s) as CARRY —
    # the layer-indexed kernels write/read cache[plane] in place so no
    # pool-sized slice ever materializes.
    n_dense = cfg.first_dense_layers if cfg.is_moe else 0
    # Per-layer sliding windows (gpt-oss alternating / Qwen2 upper-layer /
    # Mistral uniform patterns); None for full-attention models keeps the
    # scan signature (and compile cache) unchanged.
    sliding = cfg.sliding_window > 0 and not cfg.is_mla
    win_static = cfg.layer_windows
    windows = jnp.asarray(win_static, jnp.int32) if sliding else None
    # Layer-group assignment. Without the ring every layer shares one pool
    # and its plane is the global layer id; with it, sliding layers index
    # their own pool (planes count within the group) via the ring table.
    ring = kv_swa is not None and sliding
    kinds = tuple(1 if (ring and w > 0) else 0 for w in win_static)
    plane, counts = [], [0, 0]
    for knd in kinds:
        plane.append(counts[knd])
        counts[knd] += 1
    caches = [kv_cache, kv_swa]
    tables = [inp.page_table, inp.swa_page_table]
    # Flattened layout: the run plan shares (src, off, cnt) across pools;
    # only the physical page per run differs (main table vs ring view).
    run_physes = [None, None]
    if flat and inp.flat_runs is not None:
        run_physes = [inp.flat_runs[1], inp.flat_runs[2]]

    census = moe_census if use_census else None

    for i in range(n_dense):
        lp_i = jax.tree.map(lambda a: a[i], params["dense_layers"])
        g = kinds[i]
        x, caches[g], _ = layer_body(
            x, caches[g], lp_i, jnp.int32(plane[i]), use_moe=False,
            window=None if windows is None else windows[i],
            table=tables[g], run_phys=run_physes[g],
        )

    n_scan = cfg.num_layers - n_dense
    scan_kinds = kinds[n_dense:]
    plane_arr = jnp.asarray(plane[n_dense:], jnp.int32)
    win_arr = windows[n_dense:] if windows is not None else None
    lp_all = params["layers"]

    def _reduce_census(stacked):
        """Reduce per-layer census deltas [n, E+2] into the accumulator:
        counts sum over layers; the demand element takes the max."""
        return jnp.concatenate([
            jnp.sum(stacked[:, :-1], axis=0),
            jnp.max(stacked[:, -1:], axis=0),
        ])

    def scan_group(x, cache, census, table, lp, plane_ids, wins,
                   run_phys=None):
        """One homogeneous run of layers sharing a pool/table. The census
        delta rides the scan as a per-layer OUTPUT (stacked then reduced)
        so the carry signature — and the compile cache — only changes
        when the census is actually armed."""

        def fn(carry, scanned):
            x, cache = carry
            if wins is None:
                lp_s, pid = scanned
                w = None
            else:
                lp_s, pid, w = scanned
            x, cache, cd = layer_body(
                x, cache, lp_s, pid, use_moe=cfg.is_moe, window=w,
                table=table, run_phys=run_phys,
            )
            return (x, cache), cd

        scanned = (lp, plane_ids) if wins is None else (lp, plane_ids, wins)
        (x, cache), cds = jax.lax.scan(fn, (x, cache), scanned)
        if census is not None and cds is not None:
            census = _census_merge(census, _reduce_census(cds))
        return x, cache, census

    if len(set(scan_kinds)) <= 1:
        g = scan_kinds[0] if scan_kinds else 0
        x, caches[g], census = scan_group(
            x, caches[g], census, tables[g], lp_all, plane_arr, win_arr,
            run_physes[g],
        )
    elif (c := _scan_period(scan_kinds)) is not None:
        # Hybrid periodic pattern (gpt-oss alternating): scan over CYCLES
        # of c layers; within a cycle the pool choice is static per
        # sub-layer, so both pool carries update in place every step.
        T = n_scan // c

        def resh(a):
            return a.reshape(T, c, *a.shape[1:])

        cyc_scanned = (
            jax.tree.map(resh, lp_all), resh(plane_arr), resh(win_arr)
        )

        def cyc(carry, scanned):
            x, cf, cs = carry
            cc = [cf, cs]
            lp_c, plane_c, win_c = scanned
            cd_cyc = None
            for j in range(c):
                lp_s = jax.tree.map(lambda a: a[j], lp_c)
                g = scan_kinds[j]  # periodic: same kind for every cycle
                x, cc[g], cd = layer_body(
                    x, cc[g], lp_s, plane_c[j], use_moe=cfg.is_moe,
                    window=win_c[j] if g else None, table=tables[g],
                    run_phys=run_physes[g],
                )
                if cd is not None:
                    cd_cyc = cd if cd_cyc is None else _census_merge(cd_cyc, cd)
            return (x, cc[0], cc[1]), cd_cyc

        (x, caches[0], caches[1]), cds = jax.lax.scan(
            cyc, (x, caches[0], caches[1]), cyc_scanned
        )
        if census is not None and cds is not None:
            census = _census_merge(census, _reduce_census(cds))
    else:
        # Aperiodic hybrid (e.g. Qwen2 upper-layer sliding): contiguous
        # homogeneous runs, one scan each.
        off = 0
        while off < n_scan:
            g = scan_kinds[off]
            ln = 1
            while off + ln < n_scan and scan_kinds[off + ln] == g:
                ln += 1
            sl = slice(off, off + ln)
            x, caches[g], census = scan_group(
                x, caches[g], census, tables[g],
                jax.tree.map(lambda a: a[sl], lp_all),
                plane_arr[sl], win_arr[sl] if g else None,
                run_physes[g],
            )
            off += ln

    hidden = rms_norm(x, params["final_norm"], cfg.rms_norm_eps)
    out = (hidden, caches[0]) if kv_swa is None else (
        hidden, caches[0], caches[1]
    )
    if moe_census is not None:
        # Non-EP/non-MoE callers that still pass an accumulator get it
        # back unchanged — the runner's plumbing stays uniform.
        out = (*out, census if use_census else moe_census)
    return out


def compute_logits(params: dict, hidden: jax.Array, cfg: ModelConfig) -> jax.Array:
    """Project hidden states [N, H] -> logits [N, V] (f32 for sampling)."""
    if cfg.tie_word_embeddings:
        return (hidden @ params["embed"].T).astype(jnp.float32)
    return pdot(hidden, params, "lm_head").astype(jnp.float32)
