"""Llama-class decoder (covers Llama-2/3, Qwen2, Mixtral/MoE via config).

Functional, TPU-first: layer params are STACKED along a leading L axis and
the forward pass is one ``lax.scan`` over layers -- one XLA while-loop body
instead of L inlined layers, so compile time is O(1) in depth and the paged
KV cache ([L, pages, K, page, 2D], head-major pages) is scanned in lock-step.

Reference parity: this is the model-execution role the reference delegates
to vLLM (docs/architecture/core/model-servers.md:3-25); the MoE path is the
wide-EP target (docs/architecture/foundations/wide-expert-parallelism.md).
"""

from __future__ import annotations

import zlib

import jax
import jax.numpy as jnp

from llmd_tpu.config import ModelConfig
from llmd_tpu.models.common import StepInput, apply_rope, param_dtype, rms_norm, rope_tables
from llmd_tpu.models.moe import moe_block
from llmd_tpu.ops import paged_attention_full, write_kv_pages_full


def init_params(cfg: ModelConfig, key: jax.Array) -> dict:
    """Deterministic random init (used for tests/bench and as the template
    for weight loading)."""
    dt = param_dtype(cfg)
    H, D = cfg.hidden_size, cfg.head_dim
    Nq, K, L = cfg.num_heads, cfg.num_kv_heads, cfg.num_layers
    F, V = cfg.intermediate_size, cfg.vocab_size

    def mk(name: str, shape: tuple[int, ...], scale: float | None = None) -> jax.Array:
        # zlib.crc32 is stable across processes (Python's hash() is salted).
        k = jax.random.fold_in(key, zlib.crc32(name.encode()) % (2**31))
        if scale is None:
            scale = shape[-2] ** -0.5 if len(shape) >= 2 else 1.0
        return (jax.random.normal(k, shape, jnp.float32) * scale).astype(dt)

    layers: dict[str, jax.Array] = {
        "input_norm": jnp.ones((L, H), dt),
        "post_norm": jnp.ones((L, H), dt),
        "wq": mk("wq", (L, H, Nq * D)),
        "wk": mk("wk", (L, H, K * D)),
        "wv": mk("wv", (L, H, K * D)),
        "wo": mk("wo", (L, Nq * D, H)),
    }
    if cfg.attention_bias:
        layers["bq"] = jnp.zeros((L, Nq * D), dt)
        layers["bk"] = jnp.zeros((L, K * D), dt)
        layers["bv"] = jnp.zeros((L, K * D), dt)
    if cfg.is_moe:
        E, Fm = cfg.num_experts, cfg.moe_intermediate_size
        layers["router"] = mk("router", (L, H, E), scale=H**-0.5)
        layers["we_gate"] = mk("we_gate", (L, E, H, Fm))
        layers["we_up"] = mk("we_up", (L, E, H, Fm))
        layers["we_down"] = mk("we_down", (L, E, Fm, H))
        if cfg.shared_expert_intermediate_size:
            Fs = cfg.shared_expert_intermediate_size
            layers["ws_gate"] = mk("ws_gate", (L, H, Fs))
            layers["ws_up"] = mk("ws_up", (L, H, Fs))
            layers["ws_down"] = mk("ws_down", (L, Fs, H))
    else:
        layers["w_gate"] = mk("w_gate", (L, H, F))
        layers["w_up"] = mk("w_up", (L, H, F))
        layers["w_down"] = mk("w_down", (L, F, H))

    params: dict = {
        "embed": mk("embed", (V, H), scale=0.02),
        "layers": layers,
        "final_norm": jnp.ones((H,), dt),
    }
    if not cfg.tie_word_embeddings:
        params["lm_head"] = mk("lm_head", (H, V))
    return params


def _mlp(h: jax.Array, lp: dict) -> jax.Array:
    gate = jax.nn.silu(h @ lp["w_gate"])
    return (gate * (h @ lp["w_up"])) @ lp["w_down"]


def forward_hidden(
    params: dict,
    kv_cache: jax.Array,  # [L, pages, K, page, 2D]
    inp: StepInput,
    cfg: ModelConfig,
    world_size: int = 1,
    mesh=None,
    moe_backend: str = "dense",
    ep_capacity_factor: float = 2.0,
) -> tuple[jax.Array, jax.Array]:
    """Run the decoder stack; returns (hidden [B, Q, H], new kv_cache).

    ``moe_backend="ep"`` routes MoE layers through the shard_map all-to-all
    dispatch/combine (wide-EP; requires ``mesh``)."""
    B, Q = inp.token_ids.shape
    D, Nq, K = cfg.head_dim, cfg.num_heads, cfg.num_kv_heads
    x = params["embed"][inp.token_ids]  # [B, Q, H]
    cos, sin = rope_tables(inp.positions, D, cfg.rope_theta)
    valid = inp.valid
    sm_scale = D**-0.5

    # The cache rides the scan CARRY (not xs/ys): the layer-indexed
    # kernels write/read cache[layer] in place, so no pool-sized slice
    # ever materializes (the xs/ys form copied the pool every layer).
    def layer_fn(carry, scanned):
        x, cache = carry
        lp, layer_idx = scanned
        h = rms_norm(x, lp["input_norm"], cfg.rms_norm_eps)
        q = h @ lp["wq"]
        k = h @ lp["wk"]
        v = h @ lp["wv"]
        if cfg.attention_bias:
            q, k, v = q + lp["bq"], k + lp["bk"], v + lp["bv"]
        q = apply_rope(q.reshape(B, Q, Nq, D), cos, sin)
        k = apply_rope(k.reshape(B, Q, K, D), cos, sin)
        v = v.reshape(B, Q, K, D)
        cache = write_kv_pages_full(
            cache, layer_idx, k, v, inp.page_table, inp.positions, valid,
            world_size=world_size,
        )
        attn = paged_attention_full(
            q, cache, layer_idx, inp.page_table, inp.kv_lens, inp.positions,
            sm_scale, world_size=world_size,
        )
        x = x + attn.reshape(B, Q, Nq * D) @ lp["wo"]
        h2 = rms_norm(x, lp["post_norm"], cfg.rms_norm_eps)
        if cfg.is_moe:
            if moe_backend == "ep":
                from llmd_tpu.parallel.moe_ep import moe_block_ep

                out = moe_block_ep(
                    h2, lp, cfg, mesh, capacity_factor=ep_capacity_factor
                )
            else:
                out = moe_block(h2, lp, cfg)
        else:
            out = _mlp(h2, lp)
        return (x + out, cache), None

    (hidden, new_cache), _ = jax.lax.scan(
        layer_fn,
        (x, kv_cache),
        (params["layers"], jnp.arange(cfg.num_layers, dtype=jnp.int32)),
    )
    hidden = rms_norm(hidden, params["final_norm"], cfg.rms_norm_eps)
    return hidden, new_cache


def compute_logits(params: dict, hidden: jax.Array, cfg: ModelConfig) -> jax.Array:
    """Project hidden states [N, H] -> logits [N, V] (f32 for sampling)."""
    head = params["embed"].T if cfg.tie_word_embeddings else params["lm_head"]
    return (hidden @ head).astype(jnp.float32)
