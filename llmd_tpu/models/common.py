"""Shared model building blocks (functional, jit-friendly)."""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from llmd_tpu.config import ModelConfig


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class StepInput:
    """Device inputs for one forward step (static shapes per bucket).

    token_ids:  [B, Q] input token ids (padded)
    positions:  [B, Q] absolute positions (padded rows repeat last valid)
    query_lens: [B] valid token count per row
    kv_lens:    [B] total valid kv length per seq AFTER this step's writes
    page_table: [B, max_pages] physical page ids
    """

    token_ids: jax.Array
    positions: jax.Array
    query_lens: jax.Array
    kv_lens: jax.Array
    page_table: jax.Array
    # Per-sequence LoRA adapter slot ([B] i32, 0 = base model); None when
    # the model has no adapters (keeps the pytree/compile cache stable
    # for non-LoRA configs).
    lora_ids: jax.Array | None = None

    @property
    def valid(self) -> jax.Array:  # [B, Q] bool
        B, Q = self.token_ids.shape
        return jnp.arange(Q)[None, :] < self.query_lens[:, None]


def rms_norm(x: jax.Array, weight: jax.Array, eps: float) -> jax.Array:
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    return (xf * jax.lax.rsqrt(var + eps)).astype(dtype) * weight


def rope_tables(positions: jax.Array, head_dim: int, theta: float) -> tuple[jax.Array, jax.Array]:
    """cos/sin tables for rotary embedding: [..., head_dim//2], f32."""
    half = head_dim // 2
    inv_freq = 1.0 / (theta ** (jnp.arange(half, dtype=jnp.float32) / half))
    angles = positions.astype(jnp.float32)[..., None] * inv_freq  # [..., half]
    return jnp.cos(angles), jnp.sin(angles)


def apply_rope(x: jax.Array, cos: jax.Array, sin: jax.Array) -> jax.Array:
    """Rotate [B, Q, N, D] with tables [B, Q, half] (HF 'split-half' layout)."""
    half = x.shape[-1] // 2
    x1, x2 = x[..., :half], x[..., half:]
    c = cos[..., None, :]  # broadcast over heads
    s = sin[..., None, :]
    xf1, xf2 = x1.astype(jnp.float32), x2.astype(jnp.float32)
    return jnp.concatenate(
        [xf1 * c - xf2 * s, xf2 * c + xf1 * s], axis=-1
    ).astype(x.dtype)


def param_dtype(cfg: ModelConfig):
    return jnp.dtype(cfg.dtype)
