"""Shared model building blocks (functional, jit-friendly)."""

from __future__ import annotations

import dataclasses
import math

import jax
import jax.numpy as jnp

from llmd_tpu.config import ModelConfig


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class StepInput:
    """Device inputs for one forward step (static shapes per bucket).

    token_ids:  [B, Q] input token ids (padded)
    positions:  [B, Q] absolute positions (padded rows repeat last valid)
    query_lens: [B] valid token count per row
    kv_lens:    [B] total valid kv length per seq AFTER this step's writes
    page_table: [B, max_pages] physical page ids
    """

    token_ids: jax.Array
    positions: jax.Array
    query_lens: jax.Array
    kv_lens: jax.Array
    page_table: jax.Array
    # Per-sequence LoRA adapter slot ([B] i32, 0 = base model); None when
    # the model has no adapters (keeps the pytree/compile cache stable
    # for non-LoRA configs).
    lora_ids: jax.Array | None = None
    # Ring-view page table for sliding-window layers ([B, max_pages] i32,
    # entries repeat modulo the per-sequence ring length); None unless the
    # engine runs with CacheConfig.swa_ring.
    swa_page_table: jax.Array | None = None
    # Flattened-token layout (`--ragged-qlens`): when set, the "batch"
    # axis is a packed token stream — token_ids/positions are [T, 1],
    # query_lens/kv_lens are per TOKEN (kv_len = position + 1, which IS
    # the causal mask derived from cu_q_lens), and page_table stays the
    # COMPACT per-row table [R, max_pages] indexed through this [T] i32
    # token -> row map. None keeps the bucketed [B, Q] layout.
    token_rows: jax.Array | None = None
    # Run-addressed KV-write plan for the flattened layout:
    # ((src, off, cnt), phys_main, phys_swa) where each run writes
    # ``cnt`` consecutive stream tokens into one physical page at slots
    # [off, off+cnt) — the same-page-safe addressing the Pallas write
    # kernel needs (per-token decode writes would violate its
    # distinct-pages pipeline precondition). ``src`` indexes the padded
    # [K, T + 2*page, 2D] token slab (src = page + t0 - off, so slab row
    # off+j holds token t0+j). phys_swa is None without a SWA ring.
    flat_runs: tuple | None = None

    @property
    def valid(self) -> jax.Array:  # [B, Q] bool
        B, Q = self.token_ids.shape
        return jnp.arange(Q)[None, :] < self.query_lens[:, None]


def rms_norm(x: jax.Array, weight: jax.Array, eps: float) -> jax.Array:
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    return (xf * jax.lax.rsqrt(var + eps)).astype(dtype) * weight


SUPPORTED_ROPE_TYPES = ("default", "linear", "llama3", "yarn")


def rope_type(scaling: dict | None) -> str:
    if not scaling:
        return "default"
    return scaling.get("rope_type") or scaling.get("type") or "default"


def _yarn_mscale(scale: float, mscale: float = 1.0) -> float:
    if scale <= 1.0:
        return 1.0
    return 0.1 * mscale * math.log(scale) + 1.0


def yarn_sm_scale_mult(scaling: dict | None) -> float:
    """DeepSeek-style yarn splits the attention temperature correction:
    with mscale_all_dim set, cos/sin stay (nearly) unscaled and the
    softmax scale is multiplied by mscale^2 instead (HF DeepseekV3
    Attention.__init__). 1.0 for every other rope config."""
    if rope_type(scaling) != "yarn":
        return 1.0
    m_all = float(scaling.get("mscale_all_dim") or 0.0)
    if not m_all:
        return 1.0
    m = _yarn_mscale(float(scaling["factor"]), m_all)
    return m * m


def _inv_freq_and_factor(
    head_dim: int, theta: float, scaling: dict | None
) -> tuple[jax.Array, float]:
    """Inverse frequencies + cos/sin post-factor per HF rope_scaling.

    llama3 (Llama-3.1+): low-frequency bands divided by `factor`, high
    kept, smooth interpolation between (_compute_llama3_parameters).
    yarn (DeepSeek V2/V3, long-context Qwen): NTK-by-parts interpolation
    with linear ramp between beta_fast/beta_slow correction dims plus an
    attention factor on cos/sin (_compute_yarn_parameters)."""
    half = head_dim // 2
    inv_freq = 1.0 / (theta ** (jnp.arange(half, dtype=jnp.float32) / half))
    rt = rope_type(scaling)
    if rt == "default":
        return inv_freq, 1.0
    factor = float(scaling["factor"])
    if rt == "linear":
        return inv_freq / factor, 1.0
    if rt == "llama3":
        low = float(scaling["low_freq_factor"])
        high = float(scaling["high_freq_factor"])
        orig = float(scaling["original_max_position_embeddings"])
        wavelen = 2.0 * jnp.pi / inv_freq
        scaled = jnp.where(wavelen > orig / low, inv_freq / factor, inv_freq)
        smooth = (orig / wavelen - low) / (high - low)
        smoothed = (1.0 - smooth) / factor * inv_freq + smooth * inv_freq
        is_medium = (wavelen >= orig / high) & (wavelen <= orig / low)
        return jnp.where(is_medium, smoothed, scaled), 1.0
    if rt == "yarn":
        orig = float(
            scaling.get("original_max_position_embeddings") or 0.0
        ) or None
        if orig is None:
            raise ValueError("yarn rope_scaling needs original_max_position_embeddings")
        attention_factor = scaling.get("attention_factor")
        if attention_factor is None:
            mscale = scaling.get("mscale")
            m_all = scaling.get("mscale_all_dim")
            if mscale and m_all:
                attention_factor = _yarn_mscale(factor, float(mscale)) / _yarn_mscale(
                    factor, float(m_all)
                )
            else:
                attention_factor = _yarn_mscale(factor)
        beta_fast = float(scaling.get("beta_fast") or 32)
        beta_slow = float(scaling.get("beta_slow") or 1)

        def correction_dim(rot: float) -> float:
            return (head_dim * math.log(orig / (rot * 2 * math.pi))) / (
                2 * math.log(theta)
            )

        low = max(math.floor(correction_dim(beta_fast)), 0)
        high = min(math.ceil(correction_dim(beta_slow)), head_dim - 1)
        ramp = jnp.clip(
            (jnp.arange(half, dtype=jnp.float32) - low) / max(high - low, 1e-3),
            0.0,
            1.0,
        )
        extrapolation_factor = 1.0 - ramp
        inv_freq = (
            inv_freq / factor * ramp + inv_freq * extrapolation_factor
        )
        return inv_freq, float(attention_factor)
    raise NotImplementedError(f"rope_scaling type {rt!r} not supported")


def rope_tables(
    positions: jax.Array, head_dim: int, theta: float,
    scaling: dict | None = None,
) -> tuple[jax.Array, jax.Array]:
    """cos/sin tables for rotary embedding: [..., head_dim//2], f32."""
    inv_freq, factor = _inv_freq_and_factor(head_dim, theta, scaling)
    angles = positions.astype(jnp.float32)[..., None] * inv_freq  # [..., half]
    return jnp.cos(angles) * factor, jnp.sin(angles) * factor


def apply_rope(x: jax.Array, cos: jax.Array, sin: jax.Array) -> jax.Array:
    """Rotate [B, Q, N, D] with tables [B, Q, half] (HF 'split-half' layout)."""
    half = x.shape[-1] // 2
    x1, x2 = x[..., :half], x[..., half:]
    c = cos[..., None, :]  # broadcast over heads
    s = sin[..., None, :]
    xf1, xf2 = x1.astype(jnp.float32), x2.astype(jnp.float32)
    return jnp.concatenate(
        [xf1 * c - xf2 * s, xf2 * c + xf1 * s], axis=-1
    ).astype(x.dtype)


def param_dtype(cfg: ModelConfig):
    return jnp.dtype(cfg.dtype)


def pdot(x: jax.Array, lp: dict, name: str) -> jax.Array:
    """``x @ lp[name]``, transparently taking the int8 path when the param
    tree carries a ``<name>_scale`` (see llmd_tpu.ops.quant): the weight
    streams from HBM as int8 and multiplies on the MXU natively."""
    scale = lp.get(name + "_scale")
    if scale is None:
        return x @ lp[name]
    from llmd_tpu.ops.quant import qdot

    return qdot(x, lp[name], scale)
