"""Multi-head latent attention (DeepSeek V2/V3/R1 family).

The attention half of the DeepSeek architecture the reference's wide-EP
guides deploy (SURVEY.md §2.4: DeepSeek-R1 on 16P+16D; wide-EP MoE
lives in llmd_tpu/parallel/moe_ep.py — MLA is what makes its decode
batches fit by caching one compressed latent per token).

Projections (HF naming in comments):
  q:  x -> [q_lora_rank] -> norm -> heads x (nope + rope)   (q_a/q_b)
      or dense x -> heads x (nope + rope) when q_lora_rank == 0
  kv: x -> [kv_lora_rank + rope]                            (kv_a)
      latent = [rmsnorm(c_kv), rope(k_pe)]   <- THE CACHED ROW
      kv_b: [kv_lora_rank] -> heads x (nope + v)
Decode uses weight absorption: fold kv_b's key half into the query
(q_eff = [q_nope @ W_uk, q_pe]) and its value half into the output
(out = attn_latent @ W_uv), so attention itself never materializes
per-head K/V — it runs against the latent cache directly.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from llmd_tpu.config import ModelConfig
from llmd_tpu.models.common import (
    StepInput, apply_rope, pdot, rms_norm, rope_tables, yarn_sm_scale_mult,
)
from llmd_tpu.ops import mla_paged_attention_full, write_kv_pages_full


def mla_write(
    h: jax.Array,          # [B, Q, H] (already input-normed)
    lp: dict,              # this layer's params
    cache: jax.Array,      # FULL [L, pages, 1, page, Dl]
    layer_idx: jax.Array,  # scalar i32
    inp: StepInput,
    cfg: ModelConfig,
    cos: jax.Array,
    sin: jax.Array,
    world_size: int = 1,
    mesh=None,
) -> tuple[jax.Array, jax.Array]:
    """Write phase: project + cache this step's latents; returns
    (updated cache, absorbed effective queries q_eff [B, Q, nh, Dl]).

    Split from the read phase so dual-batch-overlap can write the FULL
    batch once and then run read-only attention per microbatch."""
    B, Q, _ = h.shape
    nh = cfg.num_heads
    nope, rope = cfg.qk_nope_head_dim, cfg.qk_rope_head_dim
    rank = cfg.kv_lora_rank
    Dl = cfg.kv_cache_entry_dim

    # ---- queries
    if cfg.q_lora_rank > 0:
        q = pdot(
            rms_norm(pdot(h, lp, "wq_a"), lp["q_norm"], cfg.rms_norm_eps),
            lp, "wq_b",
        )
    else:
        q = pdot(h, lp, "wq")
    q = q.reshape(B, Q, nh, nope + rope)
    q_nope, q_pe = q[..., :nope], q[..., nope:]
    q_pe = apply_rope(q_pe, cos, sin)

    # ---- latent (the cached row)
    kv_a = pdot(h, lp, "wkv_a")  # [B, Q, rank + rope]
    c_kv = rms_norm(kv_a[..., :rank], lp["kv_norm"], cfg.rms_norm_eps)
    k_pe = apply_rope(kv_a[..., None, rank:], cos, sin)[:, :, 0]  # shared head
    latent = jnp.concatenate([c_kv, k_pe], axis=-1)
    if Dl > rank + rope:
        latent = jnp.pad(latent, ((0, 0), (0, 0), (0, Dl - rank - rope)))
    # Write through the generic page writer: split the row into two
    # halves posing as K/V — the writer just concatenates them back.
    half = Dl // 2
    lat4 = latent[:, :, None, :]  # [B, Q, 1, Dl]
    cache = write_kv_pages_full(
        cache, layer_idx, lat4[..., :half], lat4[..., half:],
        inp.page_table, inp.positions, inp.valid, world_size=world_size,
        mesh=mesh,
    )

    # ---- absorption (query half): W_uk [nh, rank, nope]
    wkv_b = lp["wkv_b"].reshape(rank, nh, nope + cfg.v_head_dim)
    w_uk = wkv_b[..., :nope].transpose(1, 0, 2)  # [nh, rank, nope]
    q_eff_nope = jnp.einsum("bqhn,hrn->bqhr", q_nope, w_uk)
    q_eff = jnp.concatenate([q_eff_nope, q_pe], axis=-1)  # [B, Q, nh, rank+rope]
    if Dl > rank + rope:
        q_eff = jnp.pad(q_eff, ((0, 0), (0, 0), (0, 0), (0, Dl - rank - rope)))
    return cache, q_eff


def mla_read(
    q_eff: jax.Array,      # [B, Q, nh, Dl]
    lp: dict,
    cache: jax.Array,
    layer_idx: jax.Array,
    page_table: jax.Array,  # [B, max_pages]
    kv_lens: jax.Array,     # [B]
    positions: jax.Array,   # [B, Q]
    cfg: ModelConfig,
    world_size: int = 1,
    mesh=None,
) -> jax.Array:
    """Read phase: latent attention against cache[layer] + value
    absorption + output projection. Read-only on the cache — microbatches
    of the same step run independently (the DBO property)."""
    B, Q = q_eff.shape[:2]
    nh = cfg.num_heads
    nope, rope, vd = cfg.qk_nope_head_dim, cfg.qk_rope_head_dim, cfg.v_head_dim
    rank = cfg.kv_lora_rank
    # MLA scales by the FULL qk head dim (nope + rope), not the latent;
    # DeepSeek yarn folds its mscale^2 temperature correction in here.
    sm_scale = (nope + rope) ** -0.5 * yarn_sm_scale_mult(cfg.rope_scaling)
    wkv_b = lp["wkv_b"].reshape(rank, nh, nope + vd)
    w_uv = wkv_b[..., nope:].transpose(1, 0, 2)  # [nh, rank, vd]
    # ---- latent attention (Pallas on TPU decode: streams live pages;
    # never slices the pool)
    out_lat = mla_paged_attention_full(
        q_eff, cache, layer_idx, page_table, kv_lens, positions,
        rank=rank, sm_scale=sm_scale, world_size=world_size, mesh=mesh,
    )  # [B, Q, nh, rank]
    out = jnp.einsum("bqhr,hrv->bqhv", out_lat, w_uv)  # [B, Q, nh, vd]
    return pdot(out.reshape(B, Q, nh * vd), lp, "wo")


def mla_attention(
    h: jax.Array,          # [B, Q, H] (already input-normed)
    lp: dict,              # this layer's params
    cache: jax.Array,      # FULL [L, pages, 1, page, Dl]
    layer_idx: jax.Array,  # scalar i32
    inp: StepInput,
    cfg: ModelConfig,
    cos: jax.Array | None = None,  # rope tables for qk_rope_head_dim,
    sin: jax.Array | None = None,  # hoisted out of the layer scan
    world_size: int = 1,
    mesh=None,
) -> tuple[jax.Array, jax.Array]:
    """Returns (attn output [B, Q, H_hidden], updated cache)."""
    if cos is None or sin is None:
        cos, sin = rope_tables(
            inp.positions, cfg.qk_rope_head_dim, cfg.rope_theta,
            cfg.rope_scaling,
        )
    cache, q_eff = mla_write(
        h, lp, cache, layer_idx, inp, cfg, cos, sin,
        world_size=world_size, mesh=mesh,
    )
    out = mla_read(
        q_eff, lp, cache, layer_idx, inp.page_table, inp.kv_lens,
        inp.positions, cfg, world_size=world_size, mesh=mesh,
    )
    return out, cache


def mla_reference_attention(
    h: jax.Array,
    lp: dict,
    inp: StepInput,
    cfg: ModelConfig,
    context_latent: jax.Array,  # [B, S, rank+rope] unnormalized? no: cached latents
) -> jax.Array:
    """Numerical oracle WITHOUT absorption: materialize per-head K/V from
    the context latents and run standard masked attention. Used by tests
    to validate the absorbed/paged path."""
    B, Q, _ = h.shape
    nh = cfg.num_heads
    nope, rope, vd = cfg.qk_nope_head_dim, cfg.qk_rope_head_dim, cfg.v_head_dim
    rank = cfg.kv_lora_rank
    sm_scale = (nope + rope) ** -0.5 * yarn_sm_scale_mult(cfg.rope_scaling)
    cos, sin = rope_tables(inp.positions, rope, cfg.rope_theta, cfg.rope_scaling)

    if cfg.q_lora_rank > 0:
        q = rms_norm(h @ lp["wq_a"], lp["q_norm"], cfg.rms_norm_eps) @ lp["wq_b"]
    else:
        q = h @ lp["wq"]
    q = q.reshape(B, Q, nh, nope + rope)
    q_nope, q_pe = q[..., :nope], q[..., nope:]
    q_pe = apply_rope(q_pe, cos, sin)

    S = context_latent.shape[1]
    c_kv = context_latent[..., :rank]          # already normed when cached
    k_pe = context_latent[..., rank : rank + rope]
    wkv_b = lp["wkv_b"].reshape(rank, nh, nope + vd)
    k_nope = jnp.einsum("bsr,rhn->bshn", c_kv, wkv_b[..., :nope])
    v = jnp.einsum("bsr,rhv->bshv", c_kv, wkv_b[..., nope:])
    scores = (
        jnp.einsum("bqhn,bshn->bhqs", q_nope, k_nope, preferred_element_type=jnp.float32)
        + jnp.einsum("bqhr,bsr->bhqs", q_pe, k_pe, preferred_element_type=jnp.float32)
    ) * sm_scale
    key_pos = jnp.arange(S)[None, None, :]
    mask = (key_pos <= inp.positions[:, :, None])[:, None, :, :]
    scores = jnp.where(mask, scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1).astype(v.dtype)
    out = jnp.einsum("bhqs,bshv->bqhv", probs, v)
    return out.reshape(B, Q, nh * vd) @ lp["wo"]
