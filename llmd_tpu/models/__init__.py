"""Model families: dense Llama-class (Llama-2/3, Qwen2) and MoE
(Mixtral, DeepSeek-style wide-EP)."""

from llmd_tpu.models.registry import get_model_config, register_model  # noqa: F401
