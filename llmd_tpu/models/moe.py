"""Mixture-of-experts block: router + expert FFNs.

Wide-EP target (reference docs/architecture/foundations/
wide-expert-parallelism.md:5-30): experts sharded over the flattened
(dp, tp) mesh axes, dispatch/combine as all-to-all over ICI replacing the
reference's DeepEP/NVSHMEM kernels.

Two paths behind ``moe_block``:

- dense combine (default inside jit): every token's hidden state is
  contracted against ALL experts with a top-k one-hot combine weight. With
  experts sharded over (dp, tp) XLA turns this into an all-gather of the
  token batch onto the expert shards plus local GEMMs -- the
  "high-throughput" shape of the reference's deepep_high_throughput mode.
  Numerically exact; compute cost E/topk over-work, acceptable at small E
  or big batches (prefill).
- ``moe_block_ep`` (llmd_tpu.parallel.moe_ep): explicit shard_map
  dispatch/combine with lax.all_to_all and per-expert grouped GEMM -- the
  deepep_low_latency analogue for decode. Used when the caller runs inside
  shard_map (wide-EP engine mode).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from llmd_tpu.config import ModelConfig


def router_topk(
    h: jax.Array,
    w_router: jax.Array,
    top_k: int,
    cfg: ModelConfig | None = None,
    bias: jax.Array | None = None,
) -> tuple[jax.Array, jax.Array]:
    """Top-k expert routing covering the deployed MoE families.

    Default (cfg None): softmax-then-topk, renormalized (Mixtral-style).
    With cfg: scoring (softmax | sigmoid+bias-corrected selection),
    group-limited selection (DeepSeek V2 max-per-group / V3 top-2-sum),
    optional renormalization and routed scaling — mirroring HF
    DeepseekV2MoEGate / DeepseekV3TopkRouter semantics.

    h: [T, H]; returns (weights [T, k] f32, expert_ids [T, k] i32).
    """
    logits = (h.astype(jnp.float32) @ w_router.astype(jnp.float32))  # [T, E]
    if cfg is not None and cfg.router_logit_bias and bias is not None:
        # gpt-oss: the bias is part of the LOGITS — selection by
        # logits+bias AND weights from the (softmaxed) biased logits.
        # Softmax-topk-renormalize below is exactly softmax over the
        # selected biased logits, so fold it in and clear it.
        logits = logits + bias.astype(jnp.float32)
        bias = None
    if cfg is None:
        probs = jax.nn.softmax(logits, axis=-1)
        weights, ids = jax.lax.top_k(probs, top_k)
        return weights / jnp.sum(weights, axis=-1, keepdims=True), ids

    T, E = logits.shape
    if cfg.router_scoring == "sigmoid":
        scores = jax.nn.sigmoid(logits)
    else:
        scores = jax.nn.softmax(logits, axis=-1)
    # Selection scores may differ from combine weights (V3's correction
    # bias steers selection only; gathered weights stay uncorrected).
    choice = scores if bias is None else scores + bias.astype(jnp.float32)
    if cfg.topk_method in ("group_max", "group_top2") and cfg.n_group > 1:
        g = cfg.n_group
        grouped = choice.reshape(T, g, E // g)
        if cfg.topk_method == "group_max":
            group_scores = jnp.max(grouped, axis=-1)
        else:  # top-2 sum per group (V3 noaux_tc)
            group_scores = jnp.sum(jax.lax.top_k(grouped, 2)[0], axis=-1)
        _, group_idx = jax.lax.top_k(group_scores, cfg.topk_group)
        group_mask = jnp.zeros((T, g), bool).at[
            jnp.arange(T)[:, None], group_idx
        ].set(True)
        mask = jnp.repeat(group_mask, E // g, axis=-1)
        choice = jnp.where(mask, choice, 0.0 if cfg.router_scoring == "sigmoid" else -jnp.inf)
    _, ids = jax.lax.top_k(choice, top_k)
    weights = jnp.take_along_axis(scores, ids, axis=-1)
    if cfg.norm_topk_prob:
        weights = weights / (jnp.sum(weights, axis=-1, keepdims=True) + 1e-20)
    return weights * cfg.routed_scaling_factor, ids


def shared_expert_ffn(ht: jax.Array, lp: dict) -> jax.Array:
    """DeepSeek/Qwen2-MoE always-on shared expert (one place, three
    backends: dense / grouped / EP)."""
    from llmd_tpu.models.common import pdot

    g = jax.nn.silu(pdot(ht, lp, "ws_gate"))
    return pdot(g * pdot(ht, lp, "ws_up"), lp, "ws_down")


def _expert_scales(lp: dict) -> tuple | None:
    """(gate, up, down) channel scales when the experts are int8."""
    if "we_gate_scale" not in lp:
        return None
    return (lp["we_gate_scale"], lp["we_up_scale"], lp["we_down_scale"])


def _expert_biases(lp: dict) -> tuple | None:
    """(gate, up, down) per-expert biases (gpt-oss experts carry them)."""
    if "we_gate_b" not in lp:
        return None
    return (lp["we_gate_b"], lp["we_up_b"], lp["we_down_b"])


def expert_glu(gate: jax.Array, up: jax.Array, cfg: ModelConfig) -> jax.Array:
    """The gated-unit nonlinearity per MoE family (pre-down-projection).

    silu: silu(gate) * up (Mixtral/Qwen/DeepSeek). swiglu_oss (gpt-oss
    GptOssExperts): gate clamped above, up clamped both sides,
    glu = gate * sigmoid(1.702 * gate), combined as (up + 1) * glu.
    """
    if cfg.moe_activation == "swiglu_oss":
        gate = jnp.minimum(gate, cfg.swiglu_limit)
        up = jnp.clip(up, -cfg.swiglu_limit, cfg.swiglu_limit)
        glu = gate * jax.nn.sigmoid(1.702 * gate)
        return (up + 1.0) * glu
    return jax.nn.silu(gate) * up


def moe_block_grouped(h: jax.Array, lp: dict, cfg: ModelConfig) -> jax.Array:
    """MoE FFN via grouped GEMM (DeepGEMM role): tokens sorted by expert,
    each expert multiplies only its routed rows. Numerically equivalent to
    the dense combine (same f32 weighted sum) at top_k/E of the FLOPs."""
    from llmd_tpu.ops.grouped_gemm import moe_apply_grouped

    B, Q, H = h.shape
    T = B * Q
    ht = h.reshape(T, H)
    weights, ids = router_topk(
        ht, lp["router"], cfg.num_experts_per_tok, cfg, lp.get("router_bias")
    )
    out = moe_apply_grouped(
        ht, weights, ids, lp["we_gate"], lp["we_up"], lp["we_down"],
        scales=_expert_scales(lp), biases=_expert_biases(lp), cfg=cfg,
    ).astype(h.dtype)
    if cfg.shared_expert_intermediate_size:
        out = out + shared_expert_ffn(ht, lp)
    return out.reshape(B, Q, H)


def moe_block(h: jax.Array, lp: dict, cfg: ModelConfig) -> jax.Array:
    """MoE FFN on [B, Q, H] -> [B, Q, H] (dense-combine path)."""
    B, Q, H = h.shape
    T = B * Q
    E, k = cfg.num_experts, cfg.num_experts_per_tok
    ht = h.reshape(T, H)
    weights, ids = router_topk(ht, lp["router"], k, cfg, lp.get("router_bias"))
    # combine[t, e] = sum_j weights[t, j] * (ids[t, j] == e)
    combine = jnp.zeros((T, E), jnp.float32)
    combine = combine.at[jnp.arange(T)[:, None], ids].add(weights)

    # All experts on all tokens, the combine folded into the down
    # projection: weighting gate*up by combine[t, e] BEFORE contracting is
    # linearly equivalent to weighting per-expert outputs after, but
    # collapses combine+down-proj into ONE dot contracting {e, f}. With
    # experts EP-sharded over (dp, tp), GSPMD partitions that as a local
    # GEMM + psum over the expert axis; the old [E, T, H] per-expert
    # intermediate instead forced an involuntary full rematerialization
    # (all-gather of expert activations) every MoE layer.
    we_gate, we_up, we_down = lp["we_gate"], lp["we_up"], lp["we_down"]
    if "we_gate_scale" in lp:
        # Dense combine is the numerics oracle / GSPMD-fallback path:
        # dequantize in place (the serving int8 paths are grouped/EP).
        from llmd_tpu.ops.quant import dequantize

        we_gate = dequantize(we_gate, lp["we_gate_scale"], dtype=ht.dtype)
        we_up = dequantize(we_up, lp["we_up_scale"], dtype=ht.dtype)
        we_down = dequantize(we_down, lp["we_down_scale"], dtype=ht.dtype)
    gate = jnp.einsum("th,ehf->etf", ht, we_gate)
    up = jnp.einsum("th,ehf->etf", ht, we_up)
    biases = _expert_biases(lp)
    if biases is not None:
        gate = gate + biases[0][:, None, :]
        up = up + biases[1][:, None, :]
    act = expert_glu(gate, up, cfg) * combine.T[:, :, None].astype(gate.dtype)
    out = jnp.einsum(
        "etf,efh->th", act, we_down,
        preferred_element_type=jnp.float32,
    )
    if biases is not None:
        # Per-expert down bias, weighted by each token's combine weight.
        out = out + combine @ biases[2].astype(jnp.float32)
    out = out.astype(h.dtype)

    if cfg.shared_expert_intermediate_size:
        out = out + shared_expert_ffn(ht, lp)
    return out.reshape(B, Q, H)
