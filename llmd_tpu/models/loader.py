"""HF checkpoint loading: config.json -> ModelConfig, safetensors -> params.

The serving framework must load trained checkpoints in the format the
reference's deployment flow assumes (HF model directories; reference
docs/architecture/core/model-servers.md:3-25, HF_TOKEN download flow in
guides/pd-disaggregation/README.md:94-103). This module maps HF names and
layouts onto this framework's stacked-layer param tree:

  - HF linear weights are [out, in] and applied as x @ W.T; ours are
    [in, out] applied as x @ W -> every projection transposes on load.
  - HF stores one tensor per layer (model.layers.{i}.*); ours are stacked
    along a leading L axis for the lax.scan over layers -> np.stack.
  - DeepSeek-family checkpoints store rope dims interleaved (HF permutes
    them at runtime, modeling_deepseek's q/k view(d//2, 2) transpose);
    we bake the permutation into the loaded projections so the runtime
    split-half `apply_rope` matches.

Supported architectures: LlamaForCausalLM, Qwen2ForCausalLM,
Qwen3ForCausalLM, MixtralForCausalLM, Qwen3MoeForCausalLM,
DeepseekV2ForCausalLM, DeepseekV3ForCausalLM.
"""

from __future__ import annotations

import json
import logging
import math
import pathlib

import numpy as np

import jax.numpy as jnp

from llmd_tpu.config import ModelConfig

log = logging.getLogger(__name__)

_DENSE_ARCHS = {
    "LlamaForCausalLM",
    "MistralForCausalLM",
    "Qwen2ForCausalLM",
    "Qwen3ForCausalLM",
}
_MOE_ARCHS = {"MixtralForCausalLM", "Qwen3MoeForCausalLM", "GptOssForCausalLM"}
_MLA_ARCHS = {"DeepseekV2ForCausalLM", "DeepseekV3ForCausalLM"}
SUPPORTED_ARCHS = _DENSE_ARCHS | _MOE_ARCHS | _MLA_ARCHS


def is_model_dir(path: str) -> bool:
    p = pathlib.Path(path)
    return p.is_dir() and (p / "config.json").is_file()


def config_from_hf(model_dir: str, **overrides) -> ModelConfig:
    """Build a ModelConfig from an HF model directory's config.json."""
    p = pathlib.Path(model_dir)
    with open(p / "config.json") as f:
        hf = json.load(f)
    archs = hf.get("architectures") or []
    arch = archs[0] if archs else ""
    if arch not in SUPPORTED_ARCHS:
        raise ValueError(
            f"unsupported architecture {arch!r} in {model_dir}; "
            f"supported: {sorted(SUPPORTED_ARCHS)}"
        )
    from llmd_tpu.models.common import SUPPORTED_ROPE_TYPES, rope_type

    rope_scaling = hf.get("rope_scaling")
    if rope_type(rope_scaling) not in SUPPORTED_ROPE_TYPES:
        raise ValueError(
            f"rope_scaling type {rope_type(rope_scaling)!r} "
            f"not supported (have: {SUPPORTED_ROPE_TYPES})"
        )
    if rope_type(rope_scaling) == "yarn":
        # HF's _compute_yarn_parameters falls back to the model's
        # max_position_embeddings when the original length is absent.
        rope_scaling = dict(rope_scaling)
        rope_scaling.setdefault(
            "original_max_position_embeddings",
            hf.get("max_position_embeddings", 8192),
        )
    kw: dict = dict(
        name=p.name or str(p),
        vocab_size=hf["vocab_size"],
        hidden_size=hf["hidden_size"],
        intermediate_size=hf["intermediate_size"],
        num_layers=hf["num_hidden_layers"],
        num_heads=hf["num_attention_heads"],
        num_kv_heads=hf.get("num_key_value_heads", hf["num_attention_heads"]),
        head_dim=hf.get("head_dim"),
        rope_theta=float(hf.get("rope_theta", 10000.0)),
        rope_scaling=rope_scaling,
        rms_norm_eps=float(hf.get("rms_norm_eps", 1e-5)),
        max_model_len=int(hf.get("max_position_embeddings", 8192)),
        tie_word_embeddings=bool(hf.get("tie_word_embeddings", False)),
        # fp16 checkpoints run in bf16 on TPU (same exponent range as fp32;
        # fp16's narrower range under/overflows in softmax/logits). Newer
        # transformers writes the key as "dtype", older as "torch_dtype".
        dtype={
            "float32": "float32", "bfloat16": "bfloat16",
        }.get(str(hf.get("dtype") or hf.get("torch_dtype")), "bfloat16"),
    )
    # Sliding-window attention, in the HF conventions: Mistral-style
    # uniform windows, Qwen2's use_sliding_window + max_window_layers
    # (layers >= max_window_layers slide), and gpt-oss-style per-layer
    # layer_types ("sliding_attention"/"full_attention").
    if hf.get("sliding_window") and hf.get("use_sliding_window", True):
        kw["sliding_window"] = int(hf["sliding_window"])
        if hf.get("layer_types"):
            kw["layer_types"] = tuple(hf["layer_types"])
        elif "use_sliding_window" in hf:
            # Qwen2-style config: layers >= max_window_layers slide. A
            # checkpoint that omits the key inherits HF's class default
            # (Qwen2Config: 28) — falling through to uniform windows here
            # would silently slide layers the trained model didn't.
            kw["max_window_layers"] = int(hf.get("max_window_layers", 28))
    if arch == "Qwen2ForCausalLM":
        # Qwen2 uses bias on the QKV projections (no config flag).
        kw["attention_bias"] = True
    else:
        kw["attention_bias"] = bool(hf.get("attention_bias", False))
    if arch in ("Qwen3ForCausalLM", "Qwen3MoeForCausalLM"):
        kw["qk_norm"] = True
    if arch == "MixtralForCausalLM":
        kw.update(
            num_experts=hf["num_local_experts"],
            num_experts_per_tok=hf["num_experts_per_tok"],
            moe_intermediate_size=hf["intermediate_size"],
        )
    elif arch == "Qwen3MoeForCausalLM":
        kw.update(
            num_experts=hf["num_experts"],
            num_experts_per_tok=hf["num_experts_per_tok"],
            moe_intermediate_size=hf["moe_intermediate_size"],
            norm_topk_prob=bool(hf.get("norm_topk_prob", True)),
        )
    elif arch == "GptOssForCausalLM":
        kw.update(
            # HF GptOssConfig defaults attention_bias to TRUE (unlike the
            # shared path's False default): pin the same default for both
            # the qkv and o biases so a config.json omitting the key
            # doesn't silently drop the qkv biases.
            attention_bias=bool(hf.get("attention_bias", True)),
            num_experts=hf["num_local_experts"],
            num_experts_per_tok=hf["num_experts_per_tok"],
            moe_intermediate_size=hf["intermediate_size"],
            moe_activation="swiglu_oss",
            swiglu_limit=float(hf.get("swiglu_limit") or 7.0),
            router_logit_bias=True,
            norm_topk_prob=True,  # softmax over the selected logits
            attention_out_bias=bool(hf.get("attention_bias", True)),
            attention_sinks=True,
        )
    elif arch in _MLA_ARCHS:
        if arch == "DeepseekV3ForCausalLM":
            router_scoring, topk_method = "sigmoid", "group_top2"
        else:
            router_scoring = "softmax"
            topk_method = {
                "greedy": "greedy",
                "group_limited_greedy": "group_max",
            }[hf.get("topk_method", "greedy")]
        kw.update(
            kv_lora_rank=hf["kv_lora_rank"],
            q_lora_rank=hf.get("q_lora_rank") or 0,
            qk_nope_head_dim=hf["qk_nope_head_dim"],
            qk_rope_head_dim=hf["qk_rope_head_dim"],
            v_head_dim=hf["v_head_dim"],
            num_experts=hf.get("n_routed_experts") or 0,
            num_experts_per_tok=hf.get("num_experts_per_tok") or 2,
            moe_intermediate_size=hf.get("moe_intermediate_size"),
            first_dense_layers=hf.get("first_k_dense_replace", 0),
            shared_expert_intermediate_size=(
                (hf.get("n_shared_experts") or 0)
                * (hf.get("moe_intermediate_size") or 0)
            ),
            router_scoring=router_scoring,
            topk_method=topk_method,
            norm_topk_prob=bool(hf.get("norm_topk_prob", False)),
            routed_scaling_factor=float(hf.get("routed_scaling_factor", 1.0)),
            n_group=hf.get("n_group") or 1,
            topk_group=hf.get("topk_group") or 1,
        )
    kw.update(overrides)
    return ModelConfig(**kw)


class _Checkpoint:
    """Name-indexed view over a directory of .safetensors shards."""

    def __init__(self, model_dir: str) -> None:
        from safetensors import safe_open

        self.dir = pathlib.Path(model_dir)
        files = sorted(self.dir.glob("*.safetensors"))
        if not files:
            raise FileNotFoundError(f"no .safetensors files in {model_dir}")
        self._open = safe_open
        self._where: dict[str, pathlib.Path] = {}
        self._handles: dict[pathlib.Path, object] = {}
        for f in files:
            h = safe_open(str(f), framework="np")
            self._handles[f] = h
            for name in h.keys():
                self._where[name] = f
        self.used: set[str] = set()

    def names(self) -> set[str]:
        return set(self._where)

    def has(self, name: str) -> bool:
        return name in self._where

    def get(self, name: str) -> np.ndarray:
        f = self._where.get(name)
        if f is None:
            raise KeyError(f"checkpoint tensor {name!r} not found")
        self.used.add(name)
        # framework="np" maps bf16 to ml_dtypes.bfloat16 (a jax dep).
        return self._handles[f].get_tensor(name)


def _interleave_to_half(w: np.ndarray, rope_dim: int, axis: int = -1) -> np.ndarray:
    """Permute the trailing rope columns from interleaved (d0 d1 d0 d1 ...)
    to split-half (evens | odds) layout — HF DeepSeek's runtime q/k
    permutation, baked into the weights."""
    assert axis == -1
    head = w[..., : w.shape[-1] - rope_dim]
    tail = w[..., w.shape[-1] - rope_dim :]
    tail = np.concatenate([tail[..., 0::2], tail[..., 1::2]], axis=-1)
    return np.concatenate([head, tail], axis=-1)


def load_params(
    cfg: ModelConfig, model_dir: str, dtype: str | None = None
) -> dict:
    """Load an HF checkpoint into this framework's stacked param tree.

    Returns the same structure init_params produces (llmd_tpu/models/
    llama.py). LoRA adapter slots (serving-time state, not checkpoint
    weights) initialize empty: A random-free zeros => identity adapters.
    """
    ckpt = _Checkpoint(model_dir)
    dt = np.dtype(jnp.dtype(dtype or cfg.dtype))
    H, D = cfg.hidden_size, cfg.head_dim
    Nq, K, L = cfg.num_heads, cfg.num_kv_heads, cfg.num_layers

    def get(name: str, transpose: bool = False) -> np.ndarray:
        w = ckpt.get(name)
        if transpose:
            w = w.T
        return np.ascontiguousarray(w).astype(dt)

    def stack(names: list[str], transpose: bool = False) -> np.ndarray:
        return np.stack([get(n, transpose) for n in names])

    def proj(i: int, name: str) -> str:
        return f"model.layers.{i}.{name}"

    def layer_stack(layer_ids: list[int], moe: bool) -> dict[str, np.ndarray]:
        layers: dict[str, np.ndarray] = {
            "input_norm": stack([proj(i, "input_layernorm.weight") for i in layer_ids]),
            "post_norm": stack(
                [proj(i, "post_attention_layernorm.weight") for i in layer_ids]
            ),
        }
        if cfg.is_mla:
            rope = cfg.qk_rope_head_dim
            nope = cfg.qk_nope_head_dim

            def q_rows(w: np.ndarray) -> np.ndarray:
                # [H_in, Nq*(nope+rope)]: permute each head's rope tail.
                w = w.reshape(w.shape[0], Nq, nope + rope)
                w = _interleave_to_half(w, rope)
                return w.reshape(w.shape[0], Nq * (nope + rope))

            layers["wkv_a"] = np.stack(
                [
                    _interleave_to_half(
                        get(proj(i, "self_attn.kv_a_proj_with_mqa.weight"), True),
                        rope,
                    )
                    for i in layer_ids
                ]
            )
            layers["kv_norm"] = stack(
                [proj(i, "self_attn.kv_a_layernorm.weight") for i in layer_ids]
            )
            layers["wkv_b"] = stack(
                [proj(i, "self_attn.kv_b_proj.weight") for i in layer_ids], True
            )
            layers["wo"] = stack(
                [proj(i, "self_attn.o_proj.weight") for i in layer_ids], True
            )
            if cfg.q_lora_rank > 0:
                layers["wq_a"] = stack(
                    [proj(i, "self_attn.q_a_proj.weight") for i in layer_ids], True
                )
                layers["q_norm"] = stack(
                    [proj(i, "self_attn.q_a_layernorm.weight") for i in layer_ids]
                )
                layers["wq_b"] = np.stack(
                    [
                        q_rows(get(proj(i, "self_attn.q_b_proj.weight"), True))
                        for i in layer_ids
                    ]
                )
            else:
                layers["wq"] = np.stack(
                    [
                        q_rows(get(proj(i, "self_attn.q_proj.weight"), True))
                        for i in layer_ids
                    ]
                )
        else:
            layers["wq"] = stack(
                [proj(i, "self_attn.q_proj.weight") for i in layer_ids], True
            )
            layers["wk"] = stack(
                [proj(i, "self_attn.k_proj.weight") for i in layer_ids], True
            )
            layers["wv"] = stack(
                [proj(i, "self_attn.v_proj.weight") for i in layer_ids], True
            )
            layers["wo"] = stack(
                [proj(i, "self_attn.o_proj.weight") for i in layer_ids], True
            )
            if cfg.attention_bias:
                layers["bq"] = stack(
                    [proj(i, "self_attn.q_proj.bias") for i in layer_ids]
                )
                layers["bk"] = stack(
                    [proj(i, "self_attn.k_proj.bias") for i in layer_ids]
                )
                layers["bv"] = stack(
                    [proj(i, "self_attn.v_proj.bias") for i in layer_ids]
                )
            if cfg.attention_out_bias:
                layers["bo"] = stack(
                    [proj(i, "self_attn.o_proj.bias") for i in layer_ids]
                )
            if cfg.attention_sinks:
                layers["sinks"] = np.stack(
                    [ckpt.get(proj(i, "self_attn.sinks")) for i in layer_ids]
                ).astype(np.float32)
            if cfg.qk_norm:
                layers["attn_q_norm"] = stack(
                    [proj(i, "self_attn.q_norm.weight") for i in layer_ids]
                )
                layers["attn_k_norm"] = stack(
                    [proj(i, "self_attn.k_norm.weight") for i in layer_ids]
                )
        if cfg.num_lora_adapters and not cfg.is_mla:
            # Serving-time adapter slots, not checkpoint weights: zeros
            # everywhere => every slot is the base model until
            # set_lora_weights installs a real adapter.
            A1, r = cfg.num_lora_adapters + 1, cfg.lora_rank
            n = len(layer_ids)
            layers["la_q"] = np.zeros((n, A1, H, r), dt)
            layers["la_v"] = np.zeros((n, A1, H, r), dt)
            layers["lb_q"] = np.zeros((n, A1, r, Nq * D), dt)
            layers["lb_v"] = np.zeros((n, A1, r, K * D), dt)
        if moe and ckpt.has(proj(layer_ids[0], "mlp.router.weight")):
            # gpt-oss: the router is mlp.router (weight [E, H] + bias) and
            # experts are FUSED per-layer parameter tensors (not Linear
            # modules): gate_up_proj [E, H, 2F] with gate/up INTERLEAVED
            # on the last axis (HF GptOssExperts: gate = [..., ::2]),
            # plus per-expert biases, and down_proj [E, F, H] — already
            # [in, out], so no transpose.
            layers["router"] = stack(
                [proj(i, "mlp.router.weight") for i in layer_ids], True
            )
            layers["router_bias"] = np.stack(
                [ckpt.get(proj(i, "mlp.router.bias")) for i in layer_ids]
            ).astype(np.float32)
            gu = np.stack(
                [ckpt.get(proj(i, "mlp.experts.gate_up_proj")) for i in layer_ids]
            )  # [L, E, H, 2F]
            gub = np.stack(
                [ckpt.get(proj(i, "mlp.experts.gate_up_proj_bias"))
                 for i in layer_ids]
            )  # [L, E, 2F]
            layers["we_gate"] = np.ascontiguousarray(gu[..., 0::2]).astype(dt)
            layers["we_up"] = np.ascontiguousarray(gu[..., 1::2]).astype(dt)
            layers["we_gate_b"] = np.ascontiguousarray(gub[..., 0::2]).astype(dt)
            layers["we_up_b"] = np.ascontiguousarray(gub[..., 1::2]).astype(dt)
            layers["we_down"] = np.stack(
                [ckpt.get(proj(i, "mlp.experts.down_proj")) for i in layer_ids]
            ).astype(dt)
            layers["we_down_b"] = np.stack(
                [ckpt.get(proj(i, "mlp.experts.down_proj_bias"))
                 for i in layer_ids]
            ).astype(dt)
        elif moe:
            E = cfg.num_experts
            if ckpt.has(proj(layer_ids[0], "block_sparse_moe.gate.weight")):
                # Mixtral naming: w1=gate, w3=up, w2=down
                gate_name = "block_sparse_moe.gate.weight"
                expert = "block_sparse_moe.experts.{e}.w{w}.weight"
                enames = {"gate": "1", "up": "3", "down": "2"}

                def ename(i, e, which):
                    return proj(i, expert.format(e=e, w=enames[which]))
            else:
                gate_name = "mlp.gate.weight"

                def ename(i, e, which):
                    return proj(i, f"mlp.experts.{e}.{which}_proj.weight")

            layers["router"] = stack(
                [proj(i, gate_name) for i in layer_ids], True
            )
            bias_name = "mlp.gate.e_score_correction_bias"
            if ckpt.has(proj(layer_ids[0], bias_name)):
                layers["router_bias"] = np.stack(
                    [ckpt.get(proj(i, bias_name)) for i in layer_ids]
                ).astype(np.float32)
            elif cfg.router_scoring == "sigmoid":
                layers["router_bias"] = np.zeros(
                    (len(layer_ids), cfg.num_experts), np.float32
                )
            for which, key in (("gate", "we_gate"), ("up", "we_up"), ("down", "we_down")):
                layers[key] = np.stack(
                    [
                        np.stack([get(ename(i, e, which), True) for e in range(E)])
                        for i in layer_ids
                    ]
                )
            if cfg.shared_expert_intermediate_size:
                for which, key in (
                    ("gate", "ws_gate"), ("up", "ws_up"), ("down", "ws_down"),
                ):
                    layers[key] = stack(
                        [
                            proj(i, f"mlp.shared_experts.{which}_proj.weight")
                            for i in layer_ids
                        ],
                        True,
                    )
        else:
            layers["w_gate"] = stack(
                [proj(i, "mlp.gate_proj.weight") for i in layer_ids], True
            )
            layers["w_up"] = stack(
                [proj(i, "mlp.up_proj.weight") for i in layer_ids], True
            )
            layers["w_down"] = stack(
                [proj(i, "mlp.down_proj.weight") for i in layer_ids], True
            )
        return layers

    n_dense = cfg.first_dense_layers if cfg.is_moe else 0
    params: dict = {
        "embed": get("model.embed_tokens.weight"),
        "layers": layer_stack(list(range(n_dense, L)), moe=cfg.is_moe),
        "final_norm": get("model.norm.weight"),
    }
    if n_dense:
        params["dense_layers"] = layer_stack(list(range(n_dense)), moe=False)
    if not cfg.tie_word_embeddings:
        params["lm_head"] = get("lm_head.weight", transpose=True)

    unused = {
        n for n in ckpt.names() - ckpt.used
        if not n.endswith((".inv_freq", "rotary_emb.inv_freq"))
    }
    if unused:
        log.warning(
            "checkpoint tensors not mapped (%d): %s%s",
            len(unused), sorted(unused)[:8], " ..." if len(unused) > 8 else "",
        )
    if cfg.quantization == "int8":
        # Post-load quantization (the reference ships pre-quantized FP8
        # checkpoints; TPU INT8 quantizes the bf16 checkpoint at load).
        # Host-side numpy: the bf16 tree must never be materialized on one
        # device — big models only fit AFTER tp-sharding the int8 leaves.
        from llmd_tpu.ops.quant import quantize_param_tree_host

        params = quantize_param_tree_host(params)
    return params


def load_lora_adapter(cfg: ModelConfig, adapter_dir: str) -> dict:
    """Load an HF PEFT LoRA adapter directory into set_lora_weights form.

    Reads adapter_config.json + adapter_model.safetensors and returns
    {la_q, lb_q, la_v, lb_v} stacked [num_layers, ...], with the PEFT
    alpha/r scaling folded into B and ranks zero-padded up to the slot
    rank (zero columns are exact no-ops). Only q_proj/v_proj targets are
    servable (the slot layout); anything else raises rather than silently
    serving a partial adapter.
    """
    p = pathlib.Path(adapter_dir)
    with open(p / "adapter_config.json") as f:
        acfg = json.load(f)
    raw_targets = acfg.get("target_modules") or []
    if isinstance(raw_targets, str):  # PEFT accepts a bare string/regex
        raw_targets = [raw_targets]
    targets = set(raw_targets)
    unsupported = targets - {"q_proj", "v_proj"}
    if unsupported:
        raise ValueError(
            f"adapter targets unsupported modules {sorted(unsupported)}; "
            "servable slots cover q_proj and v_proj"
        )
    if acfg.get("bias", "none") != "none":
        raise ValueError(
            f"adapter bias={acfg['bias']!r} is not servable (slots carry "
            "A/B factors only); trained biases would silently drop"
        )
    # Anything that changes the math beyond plain scaled A/B must fail
    # loudly rather than serve approximately-the-adapter.
    for feature in ("use_dora", "modules_to_save", "alpha_pattern", "rank_pattern"):
        if acfg.get(feature):
            raise ValueError(
                f"adapter uses {feature}={acfg[feature]!r}, which the slot "
                "layout cannot represent; the adapter would serve wrong"
            )
    r = int(acfg["r"])
    if r > cfg.lora_rank:
        raise ValueError(
            f"adapter rank {r} > slot rank {cfg.lora_rank}; raise --lora-rank"
        )
    alpha = float(acfg.get("lora_alpha", r))
    # rsLoRA stores alpha/sqrt(r) scaling semantics (PEFT use_rslora).
    scale = alpha / math.sqrt(r) if acfg.get("use_rslora") else alpha / r
    ckpt = _Checkpoint(str(p))
    names = ckpt.names()

    def find(layer: int, proj: str, half: str) -> str | None:
        # PEFT names vary by wrapper depth; match on the stable suffix.
        suffix = f"layers.{layer}.self_attn.{proj}.{half}.weight"
        for n in names:
            if n.endswith(suffix):
                return n
        return None

    H, D = cfg.hidden_size, cfg.head_dim
    Nq, K, L = cfg.num_heads, cfg.num_kv_heads, cfg.num_layers
    dt = np.dtype(jnp.dtype(cfg.dtype))
    shapes = {
        "la_q": (H, cfg.lora_rank), "lb_q": (cfg.lora_rank, Nq * D),
        "la_v": (H, cfg.lora_rank), "lb_v": (cfg.lora_rank, K * D),
    }
    out = {k: np.zeros((L, *shape), dt) for k, shape in shapes.items()}
    for layer in range(L):
        for proj, a_key, b_key in (
            ("q_proj", "la_q", "lb_q"), ("v_proj", "la_v", "lb_v"),
        ):
            if proj not in targets:
                continue
            a_name = find(layer, proj, "lora_A")
            b_name = find(layer, proj, "lora_B")
            if a_name is None or b_name is None:
                raise KeyError(
                    f"adapter missing lora_A/lora_B for layer {layer} {proj}"
                )
            a = ckpt.get(a_name)  # [r, H]
            b = ckpt.get(b_name)  # [out, r]
            out[a_key][layer, :, :r] = a.T.astype(dt)
            out[b_key][layer, :r, :] = (b.T * scale).astype(dt)
    return out
