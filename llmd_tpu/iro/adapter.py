"""EngineAdapter: the swappable engine-specific surface.

Reference: proposals/inference-resilience-operator.md — "All
engine-specific logic is encapsulated in swappable EngineAdapter
implementations." The llmd adapter drives the engine's /admin
pause/resume/drain endpoints (llmd_tpu/serve/api.py).
"""

from __future__ import annotations

import asyncio
import logging
import os

import aiohttp

log = logging.getLogger(__name__)


class EngineAdapter:
    """One adapter instance coordinates ALL engines of a serving group;
    methods take the target engine's address."""

    async def pause(self, address: str) -> bool:
        raise NotImplementedError

    async def resume(self, address: str) -> bool:
        raise NotImplementedError

    async def drain(self, address: str, timeout_s: float = 60.0) -> bool:
        raise NotImplementedError

    async def close(self) -> None:
        pass


class HttpEngineAdapter(EngineAdapter):
    """Adapter for this framework's engine (and any engine exposing the
    same /admin surface)."""

    def __init__(self, timeout_s: float = 120.0) -> None:
        self.timeout_s = timeout_s
        self._session: aiohttp.ClientSession | None = None

    async def _s(self) -> aiohttp.ClientSession:
        if self._session is None or self._session.closed:
            self._session = aiohttp.ClientSession(
                timeout=aiohttp.ClientTimeout(total=self.timeout_s, sock_connect=5)
            )
        return self._session

    async def _post(self, address: str, path: str) -> bool:
        try:
            session = await self._s()
            # Engines deployed with LLMD_ADMIN_TOKEN reject unauthenticated
            # admin calls; the operator mounts the same secret.
            token = os.environ.get("LLMD_ADMIN_TOKEN", "")
            headers = {"x-admin-token": token} if token else None
            async with session.post(
                f"http://{address}{path}", headers=headers
            ) as resp:
                return resp.status < 300
        except (aiohttp.ClientError, asyncio.TimeoutError) as e:
            log.warning("engine %s %s failed: %s", address, path, e)
            return False

    async def pause(self, address: str) -> bool:
        return await self._post(address, "/admin/pause")

    async def resume(self, address: str) -> bool:
        return await self._post(address, "/admin/resume")

    async def drain(self, address: str, timeout_s: float = 60.0) -> bool:
        try:
            session = await self._s()
            async with session.post(
                f"http://{address}/admin/drain?timeout={timeout_s}",
                timeout=aiohttp.ClientTimeout(total=timeout_s + 10),
            ) as resp:
                return resp.status == 200
        except (aiohttp.ClientError, TimeoutError) as e:
            log.warning("engine %s drain failed: %s", address, e)
            return False

    async def close(self) -> None:
        if self._session is not None and not self._session.closed:
            await self._session.close()
