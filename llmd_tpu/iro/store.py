"""FileRecoveryStore: the no-Kubernetes RecoveryRequest channel.

The infrastructure recovery controller appends request objects to a
JSON file (`{"requests": [...]}`) and advances `status.phase` as it
executes the action; IRO reads the file and writes back only
`status.engineState`. On Kubernetes the same reconciler would sit on a
CRD watch instead — the store is the swapped layer.
"""

from __future__ import annotations

import json
import logging
import os

from llmd_tpu.iro.types import RecoveryRequest

log = logging.getLogger(__name__)


class FileRecoveryStore:
    def __init__(self, path: str) -> None:
        self.path = path

    def _read_raw(self) -> dict:
        try:
            with open(self.path) as f:
                return json.load(f)
        except FileNotFoundError:
            return {"requests": []}
        except json.JSONDecodeError as e:
            log.warning("recovery file %s unparseable: %s", self.path, e)
            return {"requests": []}

    def list(self) -> list[RecoveryRequest]:
        out = []
        for d in self._read_raw().get("requests", []):
            try:
                out.append(RecoveryRequest.from_dict(d))
            except (ValueError, KeyError) as e:
                log.warning("skipping malformed RecoveryRequest %r: %s", d, e)
        return out

    def update_engine_state(self, name: str, engine_state) -> None:
        """Read-modify-write of OUR status field only (phase belongs to
        the infrastructure controller and is preserved as-is)."""
        raw = self._read_raw()
        for d in raw.get("requests", []):
            if str(d.get("name") or d.get("metadata", {}).get("name", "")) == name:
                d.setdefault("status", {})["engineState"] = (
                    engine_state.value
                    if hasattr(engine_state, "value")
                    else str(engine_state)
                )
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(raw, f, indent=2)
        os.replace(tmp, self.path)
