"""FileRecoveryStore: the no-Kubernetes RecoveryRequest channel.

The infrastructure recovery controller appends request objects to a
JSON file (`{"requests": [...]}`) and advances `status.phase` as it
executes the action; IRO reads the file and writes back only
`status.engineState`. On Kubernetes the same reconciler would sit on a
CRD watch instead — the store is the swapped layer.
"""

from __future__ import annotations

import contextlib
import fcntl
import json
import logging
import os

from llmd_tpu.iro.types import RecoveryRequest

log = logging.getLogger(__name__)


class FileRecoveryStore:
    """All access goes through an flock on a sibling .lock file; the
    infrastructure controller must take the same lock for its writes or
    concurrent read-modify-write cycles lose each other's fields."""

    def __init__(self, path: str) -> None:
        self.path = path
        self._lock_path = path + ".lock"

    @contextlib.contextmanager
    def _locked(self):
        with open(self._lock_path, "a+") as lock_f:
            fcntl.flock(lock_f, fcntl.LOCK_EX)
            try:
                yield
            finally:
                fcntl.flock(lock_f, fcntl.LOCK_UN)

    def _read_raw(self) -> dict:
        try:
            with open(self.path) as f:
                return json.load(f)
        except FileNotFoundError:
            return {"requests": []}
        except json.JSONDecodeError as e:
            log.warning("recovery file %s unparseable: %s", self.path, e)
            return {"requests": []}

    def list(self) -> list[RecoveryRequest]:
        out = []
        with self._locked():
            raw = self._read_raw()
        for d in raw.get("requests", []):
            try:
                out.append(RecoveryRequest.from_dict(d))
            except (ValueError, KeyError) as e:
                log.warning("skipping malformed RecoveryRequest %r: %s", d, e)
        return out

    def update_engine_state(
        self, name: str, engine_state, extra_status: dict | None = None
    ) -> None:
        """Read-modify-write of OUR status fields only (phase belongs to
        the infrastructure controller and is preserved as-is).
        extra_status persists IRO bookkeeping that must survive restarts
        (e.g. the Track C removed-endpoints restore set)."""
        with self._locked():
            raw = self._read_raw()
            for d in raw.get("requests", []):
                if str(d.get("name") or d.get("metadata", {}).get("name", "")) == name:
                    status = d.setdefault("status", {})
                    status["engineState"] = (
                        engine_state.value
                        if hasattr(engine_state, "value")
                        else str(engine_state)
                    )
                    for k, v in (extra_status or {}).items():
                        status[k] = v
            tmp = self.path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(raw, f, indent=2)
            os.replace(tmp, self.path)
