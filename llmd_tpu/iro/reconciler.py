"""InferenceReconciler: the IRO state machine.

Sequencing contract (proposals/inference-resilience-operator.md Goals):
IRO acts on the engine BEFORE or in parallel with infrastructure
recovery, and resumes the engine only once recovery is confirmed
complete. Tracks by requested action:

  RESET_DEVICE  (A)  pause affected engines -> wait Completed -> resume
  REBOOT_NODE   (B)  same sequencing, longer horizon
  REPLACE_NODE  (C)  pause + remove the node's endpoints from the
                     serving pool (routers stop sending traffic; the
                     pool serves at reduced capacity) -> wait Completed
                     -> restore endpoints + resume

The rank topology map is the endpoints file: each endpoint's
`llm-d.ai/node` label names its node; IRO edits that file for Track C
(the no-K8s analogue of scaling the serving group).
"""

from __future__ import annotations

import asyncio
import json
import logging
import os

from llmd_tpu.iro.adapter import EngineAdapter
from llmd_tpu.iro.store import FileRecoveryStore
from llmd_tpu.iro.types import EngineState, Phase, RecoveryAction, RecoveryRequest

log = logging.getLogger(__name__)

NODE_LABEL = "llm-d.ai/node"


class InferenceReconciler:
    def __init__(
        self,
        store: FileRecoveryStore,
        adapter: EngineAdapter,
        endpoints_file: str,
        poll_s: float = 1.0,
        drain_before_pause: bool = False,
        drain_timeout_s: float = 30.0,
    ) -> None:
        self.store = store
        self.adapter = adapter
        self.endpoints_file = endpoints_file
        self.poll_s = poll_s
        self.drain_before_pause = drain_before_pause
        self.drain_timeout_s = drain_timeout_s
        # name -> engine_state we last acted on (in-memory FSM position)
        self._acted: dict[str, EngineState] = {}
        # name -> endpoint dicts removed from the pool (Track C restore set)
        self._removed: dict[str, list[dict]] = {}
        self._task: asyncio.Task | None = None
        self.cycles = 0

    # ---------------------------------------------------------- topology

    def _endpoints_raw(self) -> dict:
        try:
            with open(self.endpoints_file) as f:
                return json.load(f)
        except (FileNotFoundError, json.JSONDecodeError):
            return {"endpoints": []}

    def _write_endpoints(self, raw: dict) -> None:
        tmp = self.endpoints_file + ".tmp"
        with open(tmp, "w") as f:
            json.dump(raw, f, indent=2)
        os.replace(tmp, self.endpoints_file)

    def addresses_on_node(self, node: str) -> list[str]:
        return [
            e["address"]
            for e in self._endpoints_raw().get("endpoints", [])
            if e.get("labels", {}).get(NODE_LABEL) == node
        ]

    # ---------------------------------------------------------- actions

    async def _pause_node(self, req: RecoveryRequest) -> bool:
        """True when it is safe to report the node quiesced: no engines
        to pause, or at least one pause acknowledged. All-pauses-failed
        returns False — the caller retries rather than telling the infra
        controller the device is quiet while engines still step on it.
        (An engine the fault already killed cannot acknowledge; partial
        success therefore proceeds.)"""
        addrs = self.addresses_on_node(req.node_name)
        if not addrs:
            log.warning(
                "RecoveryRequest %s: no endpoints labeled %s=%s",
                req.name, NODE_LABEL, req.node_name,
            )
            return True

        async def quiesce(a: str) -> bool:
            if self.drain_before_pause:
                await self.adapter.drain(a, self.drain_timeout_s)
            return await self.adapter.pause(a)

        results = await asyncio.gather(*(quiesce(a) for a in addrs))
        return any(results)

    async def _resume_node(self, req: RecoveryRequest) -> bool:
        addrs = self.addresses_on_node(req.node_name)
        if not addrs:
            return True
        results = await asyncio.gather(
            *(self.adapter.resume(a) for a in addrs)
        )
        return all(results)

    def _scale_down_node(self, req: RecoveryRequest) -> list[dict]:
        """Returns the removed endpoint objects; the caller persists them
        in the request status so a restarted IRO can still restore them."""
        raw = self._endpoints_raw()
        keep, removed = [], []
        for e in raw.get("endpoints", []):
            if e.get("labels", {}).get(NODE_LABEL) == req.node_name:
                removed.append(e)
            else:
                keep.append(e)
        if removed:
            raw["endpoints"] = keep
            self._write_endpoints(raw)
            log.info(
                "RecoveryRequest %s: removed %d endpoints on node %s from pool",
                req.name, len(removed), req.node_name,
            )
        return removed

    def _scale_up_node(self, req: RecoveryRequest) -> None:
        removed = self._removed.pop(req.name, None)
        if removed is None:
            removed = req.removed_endpoints  # restart: persisted set
        if not removed:
            return
        raw = self._endpoints_raw()
        present = {e.get("address") for e in raw.get("endpoints", [])}
        raw.setdefault("endpoints", []).extend(
            e for e in removed if e.get("address") not in present
        )
        self._write_endpoints(raw)
        log.info(
            "RecoveryRequest %s: restored %d endpoints on node %s",
            req.name, len(removed), req.node_name,
        )

    # ---------------------------------------------------------- FSM

    async def reconcile_once(self) -> None:
        self.cycles += 1
        for req in self.store.list():
            state = self._acted.get(req.name, req.engine_state or EngineState.NONE)
            try:
                await self._advance(req, state)
            except Exception:
                log.exception("RecoveryRequest %s reconcile failed", req.name)

    async def _advance(self, req: RecoveryRequest, state: EngineState) -> None:
        terminal = {EngineState.RESUMED, EngineState.FAILED}
        if state in terminal:
            return
        if state is EngineState.NONE and req.phase in (
            Phase.PENDING, Phase.IN_PROGRESS
        ):
            # Engine-before-infrastructure: quiesce as soon as the request
            # exists, regardless of whether infra already started.
            if not await self._pause_node(req):
                # No engine acknowledged: do NOT report quiesced (the
                # infra controller would start resetting a live device);
                # stay in NONE and retry next cycle.
                log.warning(
                    "RecoveryRequest %s: pause not acknowledged, retrying",
                    req.name,
                )
                return
            if req.requested_action is RecoveryAction.REPLACE_NODE:
                removed = self._scale_down_node(req)
                self._removed[req.name] = removed
                self._set(
                    req, EngineState.SCALED_DOWN,
                    extra_status={"removedEndpoints": removed},
                )
            else:
                self._set(req, EngineState.PAUSED)
            return
        if state in (EngineState.PAUSED, EngineState.SCALED_DOWN):
            if req.phase is Phase.COMPLETED:
                if state is EngineState.SCALED_DOWN:
                    self._scale_up_node(req)
                await self._resume_node(req)
                self._set(req, EngineState.RESUMED)
            elif req.phase is Phase.FAILED:
                # Infra recovery failed: resume whatever still responds so
                # the group serves at reduced capacity; Track C endpoints
                # stay out of the pool (the node never came back).
                if state is EngineState.PAUSED:
                    await self._resume_node(req)
                self._set(req, EngineState.FAILED)

    def _set(
        self,
        req: RecoveryRequest,
        state: EngineState,
        extra_status: dict | None = None,
    ) -> None:
        self._acted[req.name] = state
        self.store.update_engine_state(req.name, state, extra_status)
        log.info("RecoveryRequest %s: engineState -> %s", req.name, state.value)

    # ---------------------------------------------------------- loop

    async def run(self) -> None:
        while True:
            try:
                await self.reconcile_once()
            except Exception:
                log.exception("IRO reconcile cycle failed")
            await asyncio.sleep(self.poll_s)

    def start(self) -> None:
        self._task = asyncio.get_event_loop().create_task(self.run())

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
        await self.adapter.close()
