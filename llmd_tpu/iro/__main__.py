"""`python -m llmd_tpu.iro` — the resilience operator process.

    python -m llmd_tpu.iro \
        --recovery-file /var/run/llmd/recovery.json \
        --endpoints-file /var/run/llmd/endpoints.json

The infrastructure recovery controller writes RecoveryRequests into
--recovery-file and advances status.phase; this process sequences the
engine side and edits --endpoints-file for REPLACE_NODE capacity
changes (routers watching the file pick the change up immediately).
"""

from __future__ import annotations

import argparse
import asyncio
import logging


def main(argv=None) -> None:
    logging.basicConfig(level=logging.INFO)
    p = argparse.ArgumentParser("llmd-tpu iro")
    p.add_argument("--recovery-file", required=True)
    p.add_argument("--endpoints-file", required=True)
    p.add_argument("--poll-interval", type=float, default=1.0)
    p.add_argument(
        "--drain-before-pause", action="store_true",
        help="drain in-flight requests before pausing (graceful variant)",
    )
    p.add_argument("--drain-timeout", type=float, default=30.0)
    args = p.parse_args(argv)

    from llmd_tpu.iro.adapter import HttpEngineAdapter
    from llmd_tpu.iro.reconciler import InferenceReconciler
    from llmd_tpu.iro.store import FileRecoveryStore

    rec = InferenceReconciler(
        store=FileRecoveryStore(args.recovery_file),
        adapter=HttpEngineAdapter(),
        endpoints_file=args.endpoints_file,
        poll_s=args.poll_interval,
        drain_before_pause=args.drain_before_pause,
        drain_timeout_s=args.drain_timeout,
    )

    async def _run() -> None:
        try:
            await rec.run()
        finally:
            await rec.stop()

    asyncio.run(_run())


if __name__ == "__main__":
    main()
