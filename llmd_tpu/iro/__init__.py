"""IRO: Inference Resilience Operator — hardware-fault/engine coordination.

Reference: proposals/inference-resilience-operator.md — an infrastructure
recovery controller resolves hardware faults into RecoveryRequests
(RESET_DEVICE | REBOOT_NODE | REPLACE_NODE); IRO sequences the
engine-side response (pause/drain before or parallel with infra
recovery, resume only after recovery is confirmed complete) and
restores serving capacity. No-Kubernetes deployments use a watched
JSON file in place of the CRD; the same reconciler drives both.
"""

from llmd_tpu.iro.types import Phase, RecoveryAction, RecoveryRequest
from llmd_tpu.iro.adapter import EngineAdapter, HttpEngineAdapter
from llmd_tpu.iro.reconciler import InferenceReconciler
from llmd_tpu.iro.store import FileRecoveryStore

__all__ = [
    "Phase",
    "RecoveryAction",
    "RecoveryRequest",
    "EngineAdapter",
    "HttpEngineAdapter",
    "InferenceReconciler",
    "FileRecoveryStore",
]
