"""IRO datatypes: the RecoveryRequest contract.

Field names follow the proposed CRD
(proposals/inference-resilience-operator.md "Design Details"):
nodeName, deviceID, errorCode, requestedAction, status.phase. IRO
writes only its own engine-side state (engineState) — the
infrastructure recovery controller owns `phase`.
"""

from __future__ import annotations

import dataclasses
import enum


class RecoveryAction(str, enum.Enum):
    RESET_DEVICE = "RESET_DEVICE"    # Track A: pause, reset, resume (seconds)
    REBOOT_NODE = "REBOOT_NODE"      # Track B: pause, reboot, resume (minutes)
    REPLACE_NODE = "REPLACE_NODE"    # Track C: pause, scale down, replace,
    #                                  scale up (reduced capacity meanwhile)


class Phase(str, enum.Enum):
    PENDING = "Pending"
    IN_PROGRESS = "InProgress"
    COMPLETED = "Completed"
    FAILED = "Failed"


class EngineState(str, enum.Enum):
    """IRO-owned status: where the engine-side sequencing stands."""

    NONE = ""
    PAUSED = "Paused"
    SCALED_DOWN = "ScaledDown"
    RESUMED = "Resumed"
    FAILED = "Failed"


@dataclasses.dataclass
class RecoveryRequest:
    name: str
    node_name: str
    requested_action: RecoveryAction
    device_id: str = ""
    error_code: str = ""      # observability only; IRO does not interpret it
    phase: Phase = Phase.PENDING
    engine_state: EngineState = EngineState.NONE
    # Track C bookkeeping persisted in status: the endpoints IRO removed
    # from the pool, so a restarted IRO can still restore them.
    removed_endpoints: list = dataclasses.field(default_factory=list)

    @classmethod
    def from_dict(cls, d: dict) -> "RecoveryRequest":
        status = d.get("status", {})
        return cls(
            name=str(d.get("name") or d.get("metadata", {}).get("name", "")),
            node_name=str(d.get("nodeName", "")),
            requested_action=RecoveryAction(d.get("requestedAction", "RESET_DEVICE")),
            device_id=str(d.get("deviceID", "")),
            error_code=str(d.get("errorCode", "")),
            phase=Phase(status.get("phase", "Pending")),
            engine_state=EngineState(status.get("engineState", "")),
            removed_endpoints=list(status.get("removedEndpoints", [])),
        )

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "nodeName": self.node_name,
            "deviceID": self.device_id,
            "errorCode": self.error_code,
            "requestedAction": self.requested_action.value,
            "status": {
                "phase": self.phase.value,
                "engineState": self.engine_state.value,
                "removedEndpoints": self.removed_endpoints,
            },
        }
