"""IPP — Inference Payload Processor: pool-level routing + payload plugins.

Re-implements the reference IPP
(docs/architecture/advanced/inference-payload-processing/README.md):
a pluggable framework that inspects/mutates request and response payloads
*before and after* routing decisions, sitting above the per-pool EPP:

    IPP  — pool-level:     which InferencePool?
    EPP  — endpoint-level: which pod within the pool?

Pipeline order (README.md "Plugin Architecture"):
    PreProcessing -> ProfilePicker -> profile request plugins
        -> [pool router] -> profile response plugins -> PostProcessing

The reference integrates with Envoy via ext-proc; this framework's proxy
tier is the standalone aiohttp reverse proxy (like the EPP Router), so the
IPP is an aiohttp front proxy that applies mutations and forwards to the
selected pool's Router URL — same decision surface, one fewer process hop.
Multi-model routing (guides/multi-model-routing/README.md): the
`model-extractor` plugin reads the model from the body and sets
`x-llm-d-model`; pool selection matches that header.
"""

from llmd_tpu.ipp.plugins import (
    IPPContext,
    IPPPlugin,
    ipp_plugin,
    build_ipp_plugin,
)
from llmd_tpu.ipp.server import IPPServer, PoolRoute, build_ipp_app

__all__ = [
    "IPPContext",
    "IPPPlugin",
    "ipp_plugin",
    "build_ipp_plugin",
    "IPPServer",
    "PoolRoute",
    "build_ipp_app",
]
