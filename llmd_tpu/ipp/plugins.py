"""IPP plugin framework: context, base class, registry, built-ins.

Plugins are modular units performing one processing task
(ipp README.md "Plugin Architecture"); profiles chain them; a profile
picker selects the chain per request. Mutations accumulate on the
IPPContext and the proxy applies them when forwarding.

Built-ins:
  model-extractor   read `model` from the JSON body -> x-llm-d-model header
                    (the multi-model-routing use case)
  model-rewrite     rename models (InferenceModelRewrite analogue,
                    docs/api-reference/inferencemodelrewrite.md): header +
                    body are both rewritten so the pool's engine sees the
                    served name
  header-setter     static header mutations
  defaults-injector fill missing body fields (e.g. max_tokens cap)
  guardrail         deny-pattern content filter -> immediate 403 response
  usage-recorder    response plugin: accumulate token usage per model
"""

from __future__ import annotations

import json
import logging
import re
import time
from dataclasses import dataclass, field

log = logging.getLogger(__name__)

_REGISTRY: dict[str, type] = {}


def ipp_plugin(type_name: str):
    def deco(cls):
        cls.type_name = type_name
        _REGISTRY[type_name] = cls
        return cls

    return deco


def build_ipp_plugin(type_name: str, params: dict | None = None):
    try:
        cls = _REGISTRY[type_name]
    except KeyError:
        raise KeyError(
            f"unknown IPP plugin {type_name!r}; known: {sorted(_REGISTRY)}"
        ) from None
    return cls(**(params or {}))


@dataclass
class IPPContext:
    """Mutable request/response state threaded through the pipeline."""

    path: str
    headers: dict[str, str]            # request headers (mutable)
    body: dict | None                  # parsed JSON body, None if not JSON
    body_mutated: bool = False
    # Early response short-circuit (guardrails): (status, payload).
    reject: tuple[int, dict] | None = None
    # Response side (filled before response plugins run).
    response_status: int = 0
    response_headers: dict[str, str] = field(default_factory=dict)
    response_body: dict | None = None
    response_body_mutated: bool = False
    # Plugin execution latency for /metrics (README.md "Monitoring").
    plugin_latency_s: dict[str, float] = field(default_factory=dict)

    @property
    def model(self) -> str:
        # str() guards non-string JSON values ({"model": 123}) from
        # reaching fnmatch / header forwarding.
        v = self.headers.get("x-llm-d-model", "") or (
            (self.body or {}).get("model", "") if self.body else ""
        )
        return str(v) if v is not None else ""

    def set_body(self, body: dict) -> None:
        self.body = body
        self.body_mutated = True


class IPPPlugin:
    """Base: override either hook; return nothing, mutate ctx."""

    type_name = "base"

    def process_request(self, ctx: IPPContext) -> None:  # pragma: no cover
        return None

    def process_response(self, ctx: IPPContext) -> None:  # pragma: no cover
        return None


def run_request_plugins(plugins: list[IPPPlugin], ctx: IPPContext) -> None:
    for p in plugins:
        if ctx.reject is not None:
            return
        t0 = time.monotonic()
        try:
            p.process_request(ctx)
        except Exception:
            log.exception("IPP request plugin %s failed", p.type_name)
        ctx.plugin_latency_s[p.type_name] = time.monotonic() - t0


def run_response_plugins(plugins: list[IPPPlugin], ctx: IPPContext) -> None:
    for p in plugins:
        t0 = time.monotonic()
        try:
            p.process_response(ctx)
        except Exception:
            log.exception("IPP response plugin %s failed", p.type_name)
        ctx.plugin_latency_s["resp:" + p.type_name] = time.monotonic() - t0


# ---- built-ins ----


@ipp_plugin("model-extractor")
class ModelExtractor(IPPPlugin):
    """Body `model` field -> x-llm-d-model header (+ optional default)."""

    def __init__(self, default_model: str = "") -> None:
        self.default_model = default_model

    def process_request(self, ctx: IPPContext) -> None:
        model = (ctx.body or {}).get("model") or self.default_model
        if model:
            ctx.headers["x-llm-d-model"] = str(model)


@ipp_plugin("model-rewrite")
class ModelRewrite(IPPPlugin):
    """Alias -> served-model mapping, rewriting header AND body."""

    def __init__(self, rules: dict[str, str] | None = None) -> None:
        self.rules = rules or {}

    def process_request(self, ctx: IPPContext) -> None:
        model = ctx.model
        target = self.rules.get(model)
        if target is None:
            return
        ctx.headers["x-llm-d-model"] = target
        ctx.headers["x-llm-d-original-model"] = model
        if ctx.body is not None and ctx.body.get("model") == model:
            ctx.body["model"] = target
            ctx.body_mutated = True

    def process_response(self, ctx: IPPContext) -> None:
        # Restore the client-facing name in the response body.
        orig = ctx.headers.get("x-llm-d-original-model")
        if orig and ctx.response_body and "model" in ctx.response_body:
            ctx.response_body["model"] = orig
            ctx.response_body_mutated = True


@ipp_plugin("header-setter")
class HeaderSetter(IPPPlugin):
    def __init__(self, set: dict[str, str] | None = None,
                 remove: list[str] | None = None) -> None:
        self.set = set or {}
        self.remove = [h.lower() for h in (remove or [])]

    def process_request(self, ctx: IPPContext) -> None:
        for h in self.remove:
            ctx.headers.pop(h, None)
        ctx.headers.update(self.set)


@ipp_plugin("defaults-injector")
class DefaultsInjector(IPPPlugin):
    """Fill absent body fields; cap max_tokens if configured."""

    def __init__(self, defaults: dict | None = None,
                 max_tokens_cap: int | None = None) -> None:
        self.defaults = defaults or {}
        self.max_tokens_cap = max_tokens_cap

    def process_request(self, ctx: IPPContext) -> None:
        if ctx.body is None:
            return
        for k, v in self.defaults.items():
            if k not in ctx.body:
                ctx.body[k] = v
                ctx.body_mutated = True
        if self.max_tokens_cap is not None:
            mt = ctx.body.get("max_tokens")
            if mt is None or mt > self.max_tokens_cap:
                ctx.body["max_tokens"] = self.max_tokens_cap
                ctx.body_mutated = True


@ipp_plugin("guardrail")
class Guardrail(IPPPlugin):
    """Deny-pattern filter over prompt/messages text -> 403 short-circuit.

    FAIL-CLOSED: any error while scanning (malformed messages, unexpected
    shapes) rejects the request — a security filter must not be crashable
    into an open position.
    """

    def __init__(self, deny_patterns: list[str] | None = None) -> None:
        self.patterns = [re.compile(p, re.I) for p in (deny_patterns or [])]

    @staticmethod
    def _texts(body: dict | None):
        if not body:
            return
        prompt = body.get("prompt")
        if isinstance(prompt, str):
            yield prompt
        elif isinstance(prompt, list):
            yield from (p for p in prompt if isinstance(p, str))
        messages = body.get("messages") or []
        if not isinstance(messages, list):
            raise ValueError("messages is not a list")
        for m in messages:
            if not isinstance(m, dict):
                raise ValueError("message entry is not an object")
            c = m.get("content")
            if isinstance(c, str):
                yield c
            elif isinstance(c, list):
                # OpenAI content-parts form: [{"type":"text","text":...},...]
                for part in c:
                    if not isinstance(part, dict):
                        raise ValueError("content part is not an object")
                    t = part.get("text")
                    if isinstance(t, str):
                        yield t

    def process_request(self, ctx: IPPContext) -> None:
        try:
            for text in self._texts(ctx.body):
                for pat in self.patterns:
                    if pat.search(text):
                        ctx.reject = (
                            403,
                            {"error": {
                                "message": "request blocked by guardrail",
                                "type": "guardrail_violation"}},
                        )
                        return
        except Exception:
            log.exception("guardrail scan failed; failing closed")
            ctx.reject = (
                400,
                {"error": {"message": "request could not be scanned",
                           "type": "guardrail_error"}},
            )


@ipp_plugin("usage-recorder")
class UsageRecorder(IPPPlugin):
    """Accumulates response `usage` per model (observability hook)."""

    def __init__(self) -> None:
        self.totals: dict[str, dict[str, int]] = {}

    def process_response(self, ctx: IPPContext) -> None:
        usage = (ctx.response_body or {}).get("usage")
        if not isinstance(usage, dict):
            return
        t = self.totals.setdefault(
            ctx.model, {"prompt_tokens": 0, "completion_tokens": 0}
        )
        for k in t:
            t[k] += int(usage.get(k, 0) or 0)


def _parse_body(raw: bytes) -> dict | None:
    try:
        obj = json.loads(raw)
        return obj if isinstance(obj, dict) else None
    except (json.JSONDecodeError, UnicodeDecodeError):
        return None
