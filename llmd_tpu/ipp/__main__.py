"""Run the IPP front proxy.

    python -m llmd_tpu.ipp --config ipp.yaml --port 8100

Config: see llmd_tpu/ipp/server.py docstring. Minimal zero-config mode:
`--pool URL` routes everything to one pool with model extraction only.
"""

from __future__ import annotations

import argparse
import json
import logging
from pathlib import Path

from aiohttp import web

from llmd_tpu.ipp.server import IPPServer, PoolRoute


def load_config(path: str) -> dict:
    text = Path(path).read_text()
    try:
        import yaml

        return yaml.safe_load(text)
    except ImportError:
        return json.loads(text)


def main() -> None:
    p = argparse.ArgumentParser(description="llmd-tpu IPP front proxy")
    p.add_argument("--config", help="YAML/JSON pipeline + pool config")
    p.add_argument("--pool", help="single-pool shortcut: route all to URL")
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=8100)
    p.add_argument("-v", "--verbose", action="store_true")
    args = p.parse_args()
    logging.basicConfig(level=logging.DEBUG if args.verbose else logging.INFO)
    if args.config:
        server = IPPServer.from_config(load_config(args.config))
    elif args.pool:
        server = IPPServer([PoolRoute("*", args.pool)])
    else:
        p.error("need --config or --pool")
    web.run_app(server.build_app(), host=args.host, port=args.port)


if __name__ == "__main__":
    main()
