"""IPP front proxy: profile-picked plugin pipelines + pool selection.

Request flow (ipp README.md "Request Flow"): client -> IPP -> pipeline
mutations -> pool Router (EPP) -> response plugins -> client. Pools are
matched on the `x-llm-d-model` header set by the pipeline (multi-model
routing: HTTPRoute header matching, guides/multi-model-routing — here a
glob table since the proxy is in-process).

Config shape (YAML):
    profiles:
      default:
        request: [{type: model-extractor}, {type: guardrail, parameters: {...}}]
        response: [{type: usage-recorder}]
    profile_rules:            # ProfilePicker: first match wins
      - {path_prefix: /v1/chat, profile: default}
    pools:                    # first glob match on model wins
      - {match: "qwen*", url: "http://qwen-pool:8000"}
      - {match: "*", url: "http://default-pool:8000"}
"""

from __future__ import annotations

import asyncio
import fnmatch
import json
import logging
from dataclasses import dataclass

import aiohttp
from aiohttp import web

from llmd_tpu.ipp.plugins import (
    IPPContext,
    UsageRecorder,
    _parse_body,
    build_ipp_plugin,
    run_request_plugins,
    run_response_plugins,
)

log = logging.getLogger(__name__)

HOP_HEADERS = frozenset(
    {"host", "content-length", "transfer-encoding", "connection", "keep-alive"}
)


@dataclass
class PoolRoute:
    match: str  # fnmatch glob over the model name
    url: str    # pool Router base URL

    def matches(self, model: str) -> bool:
        return fnmatch.fnmatch(model, self.match)


@dataclass
class Profile:
    name: str
    request_plugins: list
    response_plugins: list


class IPPServer:
    def __init__(
        self,
        pools: list[PoolRoute],
        profiles: dict[str, Profile] | None = None,
        profile_rules: list[dict] | None = None,
        request_timeout_s: float = 600.0,
    ) -> None:
        self.pools = pools
        self.profiles = profiles or {
            "default": Profile("default",
                               [build_ipp_plugin("model-extractor")], [])
        }
        self.profile_rules = profile_rules or []
        self.request_timeout_s = request_timeout_s
        self._session: aiohttp.ClientSession | None = None
        self.stats = {"requests": 0, "rejected": 0, "no_pool": 0,
                      "proxy_errors": 0}
        self.plugin_latency_sum: dict[str, float] = {}
        self.plugin_latency_count: dict[str, int] = {}

    @classmethod
    def from_config(cls, cfg: dict) -> "IPPServer":
        profiles = {}
        for name, spec in (cfg.get("profiles") or {}).items():
            profiles[name] = Profile(
                name,
                [build_ipp_plugin(p["type"], p.get("parameters"))
                 for p in spec.get("request", [])],
                [build_ipp_plugin(p["type"], p.get("parameters"))
                 for p in spec.get("response", [])],
            )
        pools = [PoolRoute(p["match"], p["url"]) for p in cfg.get("pools", [])]
        return cls(pools, profiles or None, cfg.get("profile_rules"))

    # ---- pipeline stages ----

    def pick_profile(self, ctx: IPPContext) -> Profile:
        """ProfilePicker: first matching rule, else 'default'."""
        for rule in self.profile_rules:
            prefix = rule.get("path_prefix")
            header = rule.get("header")
            if prefix and not ctx.path.startswith(prefix):
                continue
            if header:
                name, _, want = header.partition("=")
                if ctx.headers.get(name.lower(), "") != want:
                    continue
            prof = self.profiles.get(rule.get("profile", "default"))
            if prof is not None:
                return prof
        return self.profiles.get("default") or next(iter(self.profiles.values()))

    def pick_pool(self, model: str) -> PoolRoute | None:
        for pool in self.pools:
            if pool.matches(model):
                return pool
        return None

    def _note_latency(self, ctx: IPPContext, response_only: bool = False) -> None:
        # ctx.plugin_latency_s is cumulative across the request; the
        # response-phase call must not re-count request-plugin entries.
        for k, v in ctx.plugin_latency_s.items():
            if response_only != k.startswith("resp:"):
                continue
            self.plugin_latency_sum[k] = self.plugin_latency_sum.get(k, 0.0) + v
            self.plugin_latency_count[k] = self.plugin_latency_count.get(k, 0) + 1

    # ---- handlers ----

    async def _client(self) -> aiohttp.ClientSession:
        if self._session is None or self._session.closed:
            self._session = aiohttp.ClientSession(
                timeout=aiohttp.ClientTimeout(
                    total=self.request_timeout_s, sock_connect=5
                )
            )
        return self._session

    async def handle(self, request: web.Request) -> web.StreamResponse:
        self.stats["requests"] += 1
        raw = await request.read()
        headers = {
            k.lower(): v for k, v in request.headers.items()
            if k.lower() not in HOP_HEADERS
        }
        ctx = IPPContext(path=request.path, headers=headers,
                         body=_parse_body(raw))
        profile = self.pick_profile(ctx)
        run_request_plugins(profile.request_plugins, ctx)
        self._note_latency(ctx)
        if ctx.reject is not None:
            self.stats["rejected"] += 1
            status, payload = ctx.reject
            return web.json_response(payload, status=status)

        pool = self.pick_pool(ctx.model)
        if pool is None:
            self.stats["no_pool"] += 1
            return web.json_response(
                {"error": {"message": f"no pool serves model {ctx.model!r}",
                           "type": "model_not_found"}},
                status=404,
            )
        body_bytes = (
            json.dumps(ctx.body).encode() if ctx.body_mutated and ctx.body
            else raw
        )

        session = await self._client()
        url = pool.url.rstrip("/") + request.path
        try:
            async with session.request(
                request.method, url,
                data=body_bytes if request.method not in ("GET", "HEAD") else None,
                headers=ctx.headers,
            ) as upstream:
                is_stream = "text/event-stream" in upstream.headers.get(
                    "content-type", ""
                )
                if is_stream or not profile.response_plugins:
                    # Streamed (or plugin-free) responses pass through
                    # untouched — body plugins need the full payload.
                    resp = web.StreamResponse(status=upstream.status)
                    for k, v in upstream.headers.items():
                        if k.lower() not in HOP_HEADERS:
                            resp.headers[k] = v
                    await resp.prepare(request)
                    try:
                        async for chunk in upstream.content.iter_any():
                            await resp.write(chunk)
                    except (aiohttp.ClientError, asyncio.TimeoutError) as e:
                        # Mid-stream upstream death: the response is already
                        # prepared, so a shaped error body is impossible —
                        # truncate cleanly instead of erroring twice.
                        self.stats["proxy_errors"] += 1
                        log.warning("IPP stream from %s died: %s", url, e)
                    await resp.write_eof()
                    return resp
                resp_raw = await upstream.read()
                ctx.response_status = upstream.status
                ctx.response_headers = dict(upstream.headers)
                ctx.response_body = _parse_body(resp_raw)
                run_response_plugins(profile.response_plugins, ctx)
                self._note_latency(ctx, response_only=True)
                out = (
                    json.dumps(ctx.response_body).encode()
                    if ctx.response_body_mutated and ctx.response_body
                    else resp_raw
                )
                return web.Response(
                    body=out, status=upstream.status,
                    content_type="application/json",
                )
        except (aiohttp.ClientError, asyncio.TimeoutError) as e:
            self.stats["proxy_errors"] += 1
            log.warning("IPP proxy to %s failed: %s", url, e)
            return web.json_response(
                {"error": {"message": "upstream pool unreachable",
                           "type": "pool_unreachable"}},
                status=503,
            )

    async def handle_metrics(self, request: web.Request) -> web.Response:
        lines = [f"llmd_ipp_{k}_total {v}" for k, v in self.stats.items()]
        for k, total in self.plugin_latency_sum.items():
            n = self.plugin_latency_count.get(k, 1)
            safe = k.replace("-", "_").replace(":", "_")
            lines.append(f'llmd_ipp_plugin_latency_seconds_sum{{plugin="{safe}"}} {total:.6f}')
            lines.append(f'llmd_ipp_plugin_latency_seconds_count{{plugin="{safe}"}} {n}')
        for name, prof in self.profiles.items():
            for p in prof.response_plugins:
                if isinstance(p, UsageRecorder):
                    for model, t in p.totals.items():
                        for kind, v in t.items():
                            lines.append(
                                f'llmd_ipp_usage_tokens_total{{model="{model}",kind="{kind}"}} {v}'
                            )
        return web.Response(text="\n".join(lines) + "\n")

    async def handle_health(self, request: web.Request) -> web.Response:
        return web.json_response({"status": "ok", "pools": len(self.pools)})

    def build_app(self) -> web.Application:
        app = web.Application()
        app.router.add_get("/health", self.handle_health)
        app.router.add_get("/metrics", self.handle_metrics)
        app.router.add_route("*", "/{tail:.*}", self.handle)

        async def _cleanup(app):
            if self._session and not self._session.closed:
                await self._session.close()

        app.on_cleanup.append(_cleanup)
        return app


def build_ipp_app(cfg: dict) -> web.Application:
    return IPPServer.from_config(cfg).build_app()
