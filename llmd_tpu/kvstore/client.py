"""Cross-slice KV store client: embedded segment owner + reader.

The MooncakeStoreConnector/Client roles (reference kv-offloader.md:
160-205) on this framework's transfer plane:

  * every participating engine host owns a SEGMENT — object bytes
    registered with its local kvship server (the Transfer-Engine role;
    native C++ server when built) and announced to the master;
  * readers ask the master where a key lives, then pull the bytes
    peer-to-peer from the owning host's kvship server — the master never
    touches data;
  * the master's heartbeat reply carries eviction instructions
    (watermark-driven LRU), which the owner applies to its local server.

Synchronous HTTP (urllib) by design: callers are the offload pump
threads, never the serving event loop. Store failures degrade to misses
— the store is a cache tier, not a source of truth.
"""

from __future__ import annotations

import json
import logging
import os
import queue
import threading
import time
import urllib.error
import urllib.request
import uuid

from llmd_tpu import faults
from llmd_tpu.kvtransfer import shipper as shipper_mod

log = logging.getLogger(__name__)

# Objects are master-managed; the local kvship lease is just a safety net
# against a dead master never evicting.
_OBJECT_LEASE_MS = 24 * 3600 * 1000


class CrossSliceStoreClient:
    """Embedded-mode store participant (owner + reader in one)."""

    def __init__(
        self,
        master_url: str,
        advertised_host: str = "127.0.0.1",
        data_port: int = 0,
        segment_bytes: int = 1 << 30,
        segment_id: str | None = None,
        heartbeat_s: float = 2.0,
        timeout_s: float = 5.0,
    ) -> None:
        self.master_url = master_url.rstrip("/")
        self.segment_id = segment_id or f"seg-{uuid.uuid4().hex[:12]}"
        self.segment_bytes = segment_bytes
        self.timeout_s = timeout_s
        self.server = shipper_mod.ShipperServer(port=data_port)
        self.address = f"{advertised_host}:{self.server.port}"
        self.puts = 0
        self.pulls = 0
        self.pull_failures = 0
        self.misses = 0
        self.rejected_puts = 0
        self.dropped_publishes = 0
        self.locate_calls = 0  # master round-trips (batched reads = 1/run)
        # Publish-budget pacing (kv-federation.md): a bytes/s cap on the
        # publisher thread so publish-on-evict bursts — which land
        # exactly when the engine is under memory pressure — cannot
        # starve the transfer NIC the P/D + store-fetch legs ride.
        # Token bucket with a one-second burst allowance; 0 = unpaced.
        self.publish_bytes_per_s = float(
            os.environ.get("LLMD_KV_PUBLISH_BYTES_PER_S", "0") or 0
        )
        self.paced_publish_bytes = 0  # bytes the pacer delayed
        self._pace_tokens = self.publish_bytes_per_s
        self._pace_t = time.monotonic()
        # Federation hooks (llmd_tpu/federation/core.py). on_published:
        # called (from the publisher thread) with the key of every
        # publication the master ACCEPTED. on_publish_failed: the
        # publication did NOT land (master down, queue overflow) — the
        # federation unmarks the key so a later save/evict retries;
        # rejected puts (another segment won) are terminal, not
        # failures. on_evicted: the master's watermark eviction reached
        # this owner — the store copy is gone, withdraw its
        # advertisement.
        self.on_published = None
        self.on_publish_failed = None
        self.on_evicted = None
        self._local_keys: set[str] = set()
        self._registered = False
        self._stop = threading.Event()
        # Read breaker: a slow/hung master or peer must not stall the
        # engine thread's restore path on every prompt.
        self._read_down_until = 0.0
        self._read_cooldown_s = 10.0
        self._hb = threading.Thread(
            target=self._heartbeat_loop, args=(heartbeat_s,), daemon=True
        )
        # Publications are fire-and-forget off the engine thread: a
        # bounded queue feeds one publisher thread; overflow drops the
        # publish (the store is a cache, the local tiers still hold it).
        # items: (key, bytes | zero-arg loader) — see put_async
        self._pub_queue: "queue.Queue[tuple[str, object] | None]" = queue.Queue(
            maxsize=256
        )
        self._pub = threading.Thread(target=self._publish_loop, daemon=True)
        self._register()
        self._hb.start()
        self._pub.start()

    # ----------------------------------------------------------- http

    def _call(self, path: str, body: dict | None = None, method: str = "POST"):
        # Injection site: a hung/slow master degrades every caller to its
        # documented fallback (reads -> miss, puts -> dropped publish,
        # heartbeat -> deregistered), never an exception escaping.
        if faults.fires("kvstore.get.timeout", path):
            raise TimeoutError(f"injected kvstore.get.timeout at {path}")
        req = urllib.request.Request(
            f"{self.master_url}{path}",
            data=json.dumps(body).encode() if body is not None else None,
            headers={"content-type": "application/json"},
            method=method,
        )
        with urllib.request.urlopen(req, timeout=self.timeout_s) as resp:
            return json.loads(resp.read() or b"{}")

    def _register(self) -> None:
        try:
            self._call("/v1/segments/register", {
                "segment_id": self.segment_id,
                "address": self.address,
                "capacity_bytes": self.segment_bytes,
            })
            self._registered = True
        except (urllib.error.URLError, OSError, TimeoutError) as e:
            log.warning("kvstore master unreachable at register: %s", e)

    def _heartbeat_loop(self, interval_s: float) -> None:
        while not self._stop.wait(interval_s):
            try:
                if not self._registered:
                    self._register()
                    continue
                reply = self._call(
                    "/v1/segments/heartbeat", {"segment_id": self.segment_id}
                )
                if reply.get("unknown_segment"):
                    # Master restarted (or reaped us): the master's view
                    # of this segment is EMPTY, so withdraw the local
                    # shipper entries too — keeping them would pin
                    # unlocatable bytes in DRAM for the object-lease TTL
                    # and let the master overcommit an apparently-empty
                    # segment. Fresh publications repopulate both sides.
                    log.warning(
                        "kvstore master no longer knows segment %s; "
                        "dropping local objects and re-registering",
                        self.segment_id,
                    )
                    keys, self._local_keys = list(self._local_keys), set()
                    for key in keys:
                        self.server.unregister(key)
                    self._registered = False
                    continue
                for key in reply.get("evict", []):
                    self.server.unregister(key)
                    self._local_keys.discard(key)
                    if self.on_evicted is not None:
                        self.on_evicted(key)
            except (urllib.error.URLError, OSError, TimeoutError) as e:
                log.debug("kvstore heartbeat failed: %s", e)
                self._registered = False

    def _publish_loop(self) -> None:
        while True:
            item = self._pub_queue.get()
            try:
                if item is None:
                    return
                self.put(*item)
            finally:
                self._pub_queue.task_done()

    def flush_publishes(self, timeout_s: float = 10.0) -> None:
        """Block until queued publications have been attempted (tests,
        graceful shutdown)."""
        deadline = time.monotonic() + timeout_s
        while not self._pub_queue.empty() and time.monotonic() < deadline:
            time.sleep(0.01)

    # ------------------------------------------------------------ api

    def _publish_failed(self, key: str) -> None:
        if self.on_publish_failed is not None:
            self.on_publish_failed(key)

    def put_async(self, key: str, data) -> None:
        """Queue a publication without blocking the caller (the engine
        thread's offload flush). ``data`` is the object bytes, or a
        zero-arg callable the publisher thread invokes to materialize
        them (the evict-path publish defers its FS load + serialization
        here). Overflow drops the publish."""
        try:
            self._pub_queue.put_nowait((key, data))
        except queue.Full:
            self.dropped_publishes += 1
            self._publish_failed(key)

    def _pace_publish(self, nbytes: int) -> None:
        """Publisher-thread token bucket: block until the publish budget
        (LLMD_KV_PUBLISH_BYTES_PER_S) covers ``nbytes``. Runs ONLY on
        the publisher thread — the engine thread's put_async never
        blocks; overflow still just drops (the queue bounds memory, the
        pacer bounds NIC share)."""
        rate = self.publish_bytes_per_s
        if rate <= 0 or nbytes <= 0:
            return
        now = time.monotonic()
        self._pace_tokens = min(
            rate, self._pace_tokens + (now - self._pace_t) * rate
        )
        self._pace_t = now
        self._pace_tokens -= nbytes
        if self._pace_tokens < 0:
            self.paced_publish_bytes += nbytes
            time.sleep(-self._pace_tokens / rate)

    def put(self, key: str, data) -> bool:
        """Publish an object: bytes into the local kvship server, metadata
        to the master. First copy wins cluster-wide; redundant copies are
        dropped locally."""
        if callable(data):
            data = data()  # deferred materialization (publisher thread)
            if data is None:
                # The page left every local tier before the publish ran.
                self._publish_failed(key)
                return False
        if not self._registered:
            self._publish_failed(key)
            return False
        self._pace_publish(len(data))
        try:
            self.server.register(key, data, lease_ms=_OBJECT_LEASE_MS)
            reply = self._call("/v1/objects/put", {
                "segment_id": self.segment_id,
                "key": key,
                "nbytes": len(data),
            })
            if not reply.get("accepted"):
                self.server.unregister(key)
                self.rejected_puts += 1
                return False
            self.puts += 1
            self._local_keys.add(key)
            if self.on_published is not None:
                self.on_published(key)
            return True
        except (urllib.error.URLError, OSError, TimeoutError) as e:
            log.debug("kvstore put failed: %s", e)
            self.server.unregister(key)
            self._publish_failed(key)
            return False

    def locate(self, keys: list[str]) -> dict[str, dict]:
        self.locate_calls += 1
        try:
            return self._call("/v1/objects/locate", {"keys": keys})["found"]
        except (urllib.error.URLError, OSError, TimeoutError) as e:
            log.debug("kvstore locate failed: %s", e)
            return {}

    def get_many(self, keys: list[str]) -> dict[str, bytes]:
        """Batched read: ONE master locate for every key, then one
        pipelined kvship pull per owning segment (shipper.pull_many) —
        a whole prefix run's store fetch costs one locate + one
        connection per owner instead of a locate + connect per page.
        Absent/failed keys are simply missing from the result (the
        caller's recompute policy is the degradation, as ever)."""
        out: dict[str, bytes] = {}
        if not keys:
            return out
        now = time.monotonic()
        if now < self._read_down_until:
            self.misses += len(keys)
            return out
        t0 = now
        loc = self.locate(keys)
        by_owner: dict[str, list[str]] = {}
        for key in keys:
            entry = loc.get(key)
            if entry is None:
                self.misses += 1
                continue
            by_owner.setdefault(entry["address"], []).append(key)
        if not by_owner:
            if time.monotonic() - t0 > self.timeout_s / 2:
                self._read_down_until = (
                    time.monotonic() + self._read_cooldown_s
                )
            return out
        for addr, owner_keys in by_owner.items():
            host, _, port = addr.rpartition(":")
            try:
                got = shipper_mod.pull_many(host, int(port), owner_keys)
            except (shipper_mod.PullError, OSError) as e:
                self.pull_failures += len(owner_keys)
                self._read_down_until = (
                    time.monotonic() + self._read_cooldown_s
                )
                log.debug(
                    "kvstore batched pull from %s failed: %s", addr, e
                )
                continue
            self.pulls += len(got)
            self.misses += len(owner_keys) - len(got)
            out.update(got)
        return out

    def get(self, key: str) -> bytes | None:
        """Pull one object's bytes from whichever segment holds it.

        Runs on the engine thread's restore path, so a misbehaving store
        opens a read breaker instead of stalling every prompt."""
        now = time.monotonic()
        if now < self._read_down_until:
            self.misses += 1
            return None
        t0 = now
        loc = self.locate([key]).get(key)
        if loc is None:
            self.misses += 1
            if time.monotonic() - t0 > self.timeout_s / 2:
                self._read_down_until = time.monotonic() + self._read_cooldown_s
            return None
        host, _, port = loc["address"].rpartition(":")
        try:
            data = shipper_mod.pull(host, int(port), key)
            self.pulls += 1
            return data
        except (shipper_mod.PullError, OSError) as e:
            self.pull_failures += 1
            self._read_down_until = time.monotonic() + self._read_cooldown_s
            # Stale placement (owner restarted): the lease expiry on the
            # master reclaims it.
            log.debug("kvstore pull %s from %s failed: %s", key, loc, e)
            return None

    def clear_local(self) -> None:
        """Withdraw every object this segment published (weight rollout:
        cached KV no longer matches; content hashes do not encode weight
        versions, so each participant must clear its own contribution)."""
        keys, self._local_keys = list(self._local_keys), set()
        for key in keys:
            self.server.unregister(key)
        if keys and self._registered:
            try:
                self._call("/v1/objects/remove", {
                    "segment_id": self.segment_id, "keys": keys,
                })
            except (urllib.error.URLError, OSError, TimeoutError) as e:
                log.debug("kvstore clear_local failed: %s", e)

    def stats(self) -> dict:
        return {
            "segment_id": self.segment_id,
            "registered": self._registered,
            "local_objects": self.server.registered_count,
            "local_bytes": self.server.registered_bytes,
            "puts": self.puts,
            "pulls": self.pulls,
            "pull_failures": self.pull_failures,
            "misses": self.misses,
            "rejected_puts": self.rejected_puts,
            "dropped_publishes": self.dropped_publishes,
            "locate_calls": self.locate_calls,
            "paced_publish_bytes": self.paced_publish_bytes,
        }

    def close(self) -> None:
        self._stop.set()
        self._pub_queue.put(None)
        self._pub.join(timeout=5.0)
        self._hb.join(timeout=2.0)
        try:
            self._call(f"/v1/segments/{self.segment_id}", method="DELETE")
        except (urllib.error.URLError, OSError, TimeoutError):
            pass
        self.server.close()
