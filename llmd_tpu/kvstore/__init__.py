"""Cross-slice shared KV cache store (the Mooncake-Store role).

Master (metadata/leases/eviction/snapshots) + embedded segment clients
whose bytes ride the kvship transfer plane. See master.py / client.py.
"""

from llmd_tpu.kvstore.client import CrossSliceStoreClient
from llmd_tpu.kvstore.master import MasterState, build_app

__all__ = ["CrossSliceStoreClient", "MasterState", "build_app"]
