"""Cross-slice KV store master: metadata, placement, leases, eviction.

The Mooncake-Master role (reference kv-offloader.md:140-259): a
centralized service that pools the hosts' DRAM/FS segments into ONE
shared cache tier across slices. It tracks keyed objects and their owning
segments, grants read leases, coordinates watermark-driven eviction, and
snapshots its metadata for recovery. It is unaware of KV-cache block
semantics — keys are opaque content addresses.

Division of labor mirrors the reference: the master moves NO bytes. Data
rides the kvship transfer plane (llmd_tpu/kvtransfer/shipper.py — the
Transfer-Engine role): owners register object bytes with their local
kvship server; readers pull peer-to-peer from the owner's address.

Content addressing note: keys derive from the engine's deterministic
blake2b page-hash chain (engine/kv_cache.py), so instances share objects
without the PYTHONHASHSEED pinning the reference's Python-hash()-based
chunk keys require (kv-offloader.md:232-241).

Protocol (HTTP JSON):
  POST /v1/segments/register   {segment_id, address, capacity_bytes}
  POST /v1/segments/heartbeat  {segment_id} -> {evict: [keys]}
  DELETE /v1/segments/{id}     owner shutdown: drop its objects
  POST /v1/objects/put         {segment_id, key, nbytes} -> {accepted}
  POST /v1/objects/locate      {keys: [...]} -> {found: {key: {address,
                               nbytes}}}; touches LRU + read lease
  POST /v1/objects/remove      {segment_id, keys} (eviction ack)
  GET  /healthz, /metrics
"""

from __future__ import annotations

import asyncio
import collections
import dataclasses
import json
import logging
import pathlib
import time

from aiohttp import web

log = logging.getLogger(__name__)


@dataclasses.dataclass
class Segment:
    segment_id: str
    address: str  # kvship host:port serving this segment's bytes
    capacity_bytes: int
    used_bytes: int = 0
    last_heartbeat: float = 0.0
    pending_evictions: list[str] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class StoredObject:
    key: str
    segment_id: str
    nbytes: int
    stored_at: float
    lease_until: float = 0.0
    soft_pin_until: float = 0.0


class MasterState:
    """Metadata + eviction policy (single-threaded under the event loop)."""

    def __init__(
        self,
        eviction_high_watermark_ratio: float = 0.95,
        eviction_ratio: float = 0.05,
        default_kv_lease_ttl_ms: int = 5_000,
        default_kv_soft_pin_ttl_ms: int = 1_800_000,
        segment_dead_after_s: float = 30.0,
        snapshot_path: str | None = None,
    ) -> None:
        self.high_watermark = eviction_high_watermark_ratio
        self.eviction_ratio = eviction_ratio
        self.lease_ttl_s = default_kv_lease_ttl_ms / 1e3
        self.soft_pin_ttl_s = default_kv_soft_pin_ttl_ms / 1e3
        self.segment_dead_after_s = segment_dead_after_s
        self.snapshot_path = (
            pathlib.Path(snapshot_path) if snapshot_path else None
        )
        self.segments: dict[str, Segment] = {}
        # LRU order: oldest-touched first (move_to_end on locate)
        self.objects: collections.OrderedDict[str, StoredObject] = (
            collections.OrderedDict()
        )
        self.hits = 0
        self.misses = 0
        self.evicted = 0
        if self.snapshot_path is not None and self.snapshot_path.exists():
            self._load_snapshot()

    # ------------------------------------------------------------ pool

    @property
    def capacity(self) -> int:
        return sum(s.capacity_bytes for s in self.segments.values())

    @property
    def used(self) -> int:
        return sum(s.used_bytes for s in self.segments.values())

    def register_segment(
        self, segment_id: str, address: str, capacity_bytes: int
    ) -> None:
        seg = self.segments.get(segment_id)
        if seg is None:
            self.segments[segment_id] = Segment(
                segment_id, address, capacity_bytes,
                last_heartbeat=time.monotonic(),
            )
            return
        # Re-registration after owner restart: its DRAM is empty again,
        # so every object it held is gone.
        seg.address = address
        seg.capacity_bytes = capacity_bytes
        seg.last_heartbeat = time.monotonic()
        self._drop_segment_objects(segment_id)

    def remove_segment(self, segment_id: str) -> None:
        self._drop_segment_objects(segment_id)
        self.segments.pop(segment_id, None)

    def _drop_segment_objects(self, segment_id: str) -> None:
        gone = [k for k, o in self.objects.items() if o.segment_id == segment_id]
        for k in gone:
            del self.objects[k]
        seg = self.segments.get(segment_id)
        if seg is not None:
            seg.used_bytes = 0
            seg.pending_evictions.clear()

    def heartbeat(self, segment_id: str) -> list[str] | None:
        """Returns the pending-eviction list, or None for an UNKNOWN
        segment — the signal a cold-restarted master (or a reaped
        registration) sends so the client re-registers instead of
        heartbeating into the void forever."""
        seg = self.segments.get(segment_id)
        if seg is None:
            return None
        seg.last_heartbeat = time.monotonic()
        evict, seg.pending_evictions = seg.pending_evictions, []
        return evict

    def reap_dead_segments(self) -> None:
        now = time.monotonic()
        for sid in list(self.segments):
            if now - self.segments[sid].last_heartbeat > self.segment_dead_after_s:
                log.warning("segment %s missed heartbeats; dropping", sid)
                self.remove_segment(sid)

    # --------------------------------------------------------- objects

    def put(self, segment_id: str, key: str, nbytes: int, soft_pin: bool = False) -> bool:
        seg = self.segments.get(segment_id)
        if seg is None:
            return False
        prev = self.objects.get(key)
        if prev is not None:
            if prev.segment_id == segment_id:
                # Idempotent re-put from the owning segment (page
                # re-offloaded after local eviction while the registration
                # outlived it): accepting keeps the caller from dropping
                # the only live copy the master still points readers at.
                # Treat it as a fresh store: MRU position + soft-pin
                # refresh, or the just-rewritten copy would be the top
                # eviction candidate.
                now = time.monotonic()
                prev.nbytes = nbytes
                prev.stored_at = now
                if soft_pin:
                    prev.soft_pin_until = now + self.soft_pin_ttl_s
                self.objects.move_to_end(key)
                return True
            # First copy wins (content-addressed: replicas are identical);
            # the new copy is redundant, tell the caller to drop it.
            return False
        now = time.monotonic()
        self.objects[key] = StoredObject(
            key, segment_id, nbytes, stored_at=now,
            soft_pin_until=now + self.soft_pin_ttl_s if soft_pin else 0.0,
        )
        seg.used_bytes += nbytes
        self.maybe_evict()
        return True

    def locate(self, keys: list[str]) -> dict[str, dict]:
        now = time.monotonic()
        found: dict[str, dict] = {}
        for key in keys:
            obj = self.objects.get(key)
            if obj is None:
                self.misses += 1
                continue
            seg = self.segments.get(obj.segment_id)
            if seg is None:
                continue
            self.hits += 1
            obj.lease_until = now + self.lease_ttl_s
            self.objects.move_to_end(key)
            found[key] = {"address": seg.address, "nbytes": obj.nbytes}
        return found

    def remove(self, segment_id: str, keys: list[str]) -> None:
        for key in keys:
            obj = self.objects.get(key)
            if obj is not None and obj.segment_id == segment_id:
                del self.objects[key]
                seg = self.segments.get(segment_id)
                if seg is not None:
                    seg.used_bytes = max(0, seg.used_bytes - obj.nbytes)

    def maybe_evict(self) -> int:
        """Watermark-driven LRU eviction (reference configmap defaults:
        trigger at 95% full, evict 5% of capacity per cycle). Leased and
        soft-pinned objects are skipped; owners learn their eviction list
        on the next heartbeat."""
        cap = self.capacity
        if cap <= 0 or self.used < self.high_watermark * cap:
            return 0
        target = int(self.eviction_ratio * cap)
        now = time.monotonic()
        freed = 0
        for key in list(self.objects):  # LRU order
            if freed >= target:
                break
            obj = self.objects[key]
            if obj.lease_until > now or obj.soft_pin_until > now:
                continue
            seg = self.segments.get(obj.segment_id)
            del self.objects[key]
            if seg is not None:
                seg.used_bytes = max(0, seg.used_bytes - obj.nbytes)
                seg.pending_evictions.append(key)
            freed += obj.nbytes
            self.evicted += 1
        return freed

    # ------------------------------------------------------- snapshots

    def snapshot(self) -> None:
        if self.snapshot_path is None:
            return
        data = {
            "segments": [
                {
                    "segment_id": s.segment_id,
                    "address": s.address,
                    "capacity_bytes": s.capacity_bytes,
                    "used_bytes": s.used_bytes,
                }
                for s in self.segments.values()
            ],
            "objects": [
                {
                    "key": o.key,
                    "segment_id": o.segment_id,
                    "nbytes": o.nbytes,
                }
                for o in self.objects.values()
            ],
        }
        tmp = self.snapshot_path.with_suffix(".tmp")
        tmp.write_text(json.dumps(data))
        tmp.replace(self.snapshot_path)

    def _load_snapshot(self) -> None:
        try:
            data = json.loads(self.snapshot_path.read_text())
        except (OSError, json.JSONDecodeError) as e:
            log.warning("snapshot load failed: %s", e)
            return
        now = time.monotonic()
        for s in data.get("segments", []):
            self.segments[s["segment_id"]] = Segment(
                s["segment_id"], s["address"], s["capacity_bytes"],
                used_bytes=s.get("used_bytes", 0),
                # Recovered segments must re-announce within the grace
                # window or their objects drop with them.
                last_heartbeat=now,
            )
        for o in data.get("objects", []):
            self.objects[o["key"]] = StoredObject(
                o["key"], o["segment_id"], o["nbytes"], stored_at=now,
            )

    def stats(self) -> dict:
        return {
            "segments": len(self.segments),
            "objects": len(self.objects),
            "capacity_bytes": self.capacity,
            "used_bytes": self.used,
            "hits": self.hits,
            "misses": self.misses,
            "evicted": self.evicted,
        }


def build_app(
    state: MasterState, snapshot_interval_s: float = 60.0
) -> web.Application:
    async def register(request: web.Request) -> web.Response:
        b = await request.json()
        state.register_segment(
            str(b["segment_id"]), str(b["address"]), int(b["capacity_bytes"])
        )
        return web.json_response({"ok": True})

    async def heartbeat(request: web.Request) -> web.Response:
        b = await request.json()
        evict = state.heartbeat(str(b["segment_id"]))
        if evict is None:
            return web.json_response({"unknown_segment": True, "evict": []})
        return web.json_response({"evict": evict})

    async def unregister(request: web.Request) -> web.Response:
        state.remove_segment(request.match_info["sid"])
        return web.json_response({"ok": True})

    async def put(request: web.Request) -> web.Response:
        b = await request.json()
        accepted = state.put(
            str(b["segment_id"]), str(b["key"]), int(b["nbytes"]),
            soft_pin=bool(b.get("soft_pin", False)),
        )
        return web.json_response({"accepted": accepted})

    async def locate(request: web.Request) -> web.Response:
        b = await request.json()
        return web.json_response({"found": state.locate(list(b["keys"]))})

    async def remove(request: web.Request) -> web.Response:
        b = await request.json()
        state.remove(str(b["segment_id"]), list(b["keys"]))
        return web.json_response({"ok": True})

    async def healthz(request: web.Request) -> web.Response:
        return web.json_response({"status": "ok", **state.stats()})

    async def metrics(request: web.Request) -> web.Response:
        st = state.stats()
        lines = []
        for name, val in st.items():
            metric = f"llm_d_kvstore_{name}"
            kind = "counter" if name in ("hits", "misses", "evicted") else "gauge"
            lines.append(f"# TYPE {metric} {kind}")
            lines.append(f"{metric} {val}")
        return web.Response(text="\n".join(lines) + "\n")

    async def background(app: web.Application):
        async def loop():
            while True:
                await asyncio.sleep(min(snapshot_interval_s, 5.0))
                state.reap_dead_segments()
                state.maybe_evict()
                if time.monotonic() - loop.last_snap >= snapshot_interval_s:
                    state.snapshot()
                    loop.last_snap = time.monotonic()

        loop.last_snap = time.monotonic()
        task = asyncio.create_task(loop())
        yield
        task.cancel()

    app = web.Application()
    app.cleanup_ctx.append(background)
    app.add_routes([
        web.post("/v1/segments/register", register),
        web.post("/v1/segments/heartbeat", heartbeat),
        web.delete("/v1/segments/{sid}", unregister),
        web.post("/v1/objects/put", put),
        web.post("/v1/objects/locate", locate),
        web.post("/v1/objects/remove", remove),
        web.get("/healthz", healthz),
        web.get("/metrics", metrics),
    ])
    return app
