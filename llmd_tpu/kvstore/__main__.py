"""`python -m llmd_tpu.kvstore` — the cross-slice KV store master.

Flag names mirror the reference Mooncake master configmap
(helpers/mooncake-master-store/base/configmap.yaml)."""

from __future__ import annotations

import argparse
import logging

from aiohttp import web

from llmd_tpu.kvstore.master import MasterState, build_app


def main(argv=None) -> None:
    logging.basicConfig(level=logging.INFO)
    p = argparse.ArgumentParser("llmd-tpu kvstore master")
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=50051)
    p.add_argument("--eviction-high-watermark-ratio", type=float, default=0.95)
    p.add_argument("--eviction-ratio", type=float, default=0.05)
    p.add_argument("--default-kv-lease-ttl", type=int, default=5000,
                   help="read lease TTL in ms")
    p.add_argument("--default-kv-soft-pin-ttl", type=int, default=1_800_000)
    p.add_argument("--enable-snapshot", action="store_true")
    p.add_argument("--snapshot-path", default="/data/kvstore-snapshot.json")
    p.add_argument("--snapshot-interval-seconds", type=float, default=60.0)
    args = p.parse_args(argv)

    state = MasterState(
        eviction_high_watermark_ratio=args.eviction_high_watermark_ratio,
        eviction_ratio=args.eviction_ratio,
        default_kv_lease_ttl_ms=args.default_kv_lease_ttl,
        default_kv_soft_pin_ttl_ms=args.default_kv_soft_pin_ttl,
        snapshot_path=args.snapshot_path if args.enable_snapshot else None,
    )
    app = build_app(state, snapshot_interval_s=args.snapshot_interval_seconds)
    web.run_app(app, host=args.host, port=args.port)


if __name__ == "__main__":
    main()
