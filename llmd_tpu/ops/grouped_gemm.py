"""Grouped GEMM for MoE expert compute — the DeepGEMM role.

The reference's wide-EP decode path routes MoE through DeepGEMM's masked
grouped GEMMs (`--moe-backend deep_gemm`, guides/wide-ep-lws/modelserver/
gpu/vllm/base/decode.yaml:128) so each expert multiplies ONLY its routed
tokens. The TPU-native equivalent: tokens sorted by expert id feed a
ragged/grouped matmul — jax's Pallas megablocks kernel (`megablox.gmm`)
on TPU, `lax.ragged_dot` elsewhere — instead of the one-hot masked
contraction that burns E/top_k redundant FLOPs.

FLOPs per token: 3 * k * H * F (exactly the routed work) vs the dense
combine's 3 * E * H * F.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp


def _use_megablox(H: int, F: int) -> bool:
    """megablox wants lane-tiled contraction/output dims; anything else
    (tiny models) takes the XLA ragged_dot, which is correct everywhere.
    LLMD_PALLAS=interpret forces the kernel in interpret mode so CPU CI
    parity-tests the same glue (tiling, padding, sorting) TPUs run."""
    mode = os.environ.get("LLMD_PALLAS", "auto")
    if mode == "off":
        return False
    if H % 128 or F % 128:
        return False
    if mode == "interpret":
        return True
    try:
        platform = jax.devices()[0].platform
    except Exception:
        return False
    return platform in ("tpu", "axon")


def grouped_matmul(
    x: jax.Array,            # [T, K_dim] tokens sorted by group
    w: jax.Array,            # [G, K_dim, N]
    group_sizes: jax.Array,  # [G] i32, sums to T
) -> jax.Array:              # [T, N]
    T, K_dim = x.shape
    G, _, N = w.shape
    if _use_megablox(K_dim, N):
        from jax.experimental.pallas.ops.tpu.megablox.gmm import gmm

        # gmm requires m % tile_m == 0 and a sublane-aligned tile: pad rows
        # up to the (8-aligned) tile. Pad rows are zero and land in the
        # LAST group (group_sizes must sum to m); their zero outputs are
        # sliced off below.
        tm = min(128, -(-max(T, 1) // 8) * 8)
        pad = (-T) % tm
        if pad:
            x = jnp.concatenate([x, jnp.zeros((pad, K_dim), x.dtype)], axis=0)
            group_sizes = group_sizes.at[-1].add(pad)
        out = gmm(
            x, w, group_sizes.astype(jnp.int32),
            preferred_element_type=jnp.float32,
            tiling=(tm, 128, 128),
            interpret=os.environ.get("LLMD_PALLAS") == "interpret",
        )
        return out[:T].astype(x.dtype)
    return jax.lax.ragged_dot(
        x, w, group_sizes.astype(jnp.int32),
        preferred_element_type=jnp.float32,
    ).astype(x.dtype)


def expert_mlp_grouped(
    x_sorted: jax.Array,     # [T', H] rows sorted by expert
    group_sizes: jax.Array,  # [E]
    we_gate: jax.Array,      # [E, H, F] (bf16, or int8 with scales)
    we_up: jax.Array,        # [E, H, F]
    we_down: jax.Array,      # [E, F, H]
    scales: tuple | None = None,  # int8 experts: (s_gate [E,F], s_up [E,F], s_down [E,H])
    biases: tuple | None = None,  # gpt-oss experts: (b_gate [E,F], b_up [E,F], b_down [E,H])
    cfg=None,                # ModelConfig for the activation family
) -> jax.Array:              # [T', H]
    from llmd_tpu.models.moe import expert_glu

    T = x_sorted.shape[0]
    E = we_gate.shape[0]
    if scales is not None:
        from llmd_tpu.ops.quant import grouped_matmul_q

        mm = lambda x, w, s: grouped_matmul_q(x, w, s, group_sizes)  # noqa: E731
    else:
        mm = lambda x, w, s: grouped_matmul(x, w, group_sizes)  # noqa: E731
    s_gate, s_up, s_down = scales if scales is not None else (None,) * 3
    gate = mm(x_sorted, we_gate, s_gate)
    up = mm(x_sorted, we_up, s_up)
    gid = None
    if biases is not None:
        gid = jnp.repeat(
            jnp.arange(E, dtype=jnp.int32), group_sizes, total_repeat_length=T
        )
        gate = gate + biases[0][gid]
        up = up + biases[1][gid]
    act = (
        expert_glu(gate, up, cfg) if cfg is not None
        else jax.nn.silu(gate) * up  # bare-array callers (tests)
    )
    out = mm(act.astype(x_sorted.dtype), we_down, s_down)
    if biases is not None:
        out = out + biases[2][gid].astype(out.dtype)
    return out


def moe_apply_grouped(
    ht: jax.Array,       # [T, H]
    weights: jax.Array,  # [T, k] f32 combine weights (scaled/normalized)
    ids: jax.Array,      # [T, k] i32 expert ids
    we_gate: jax.Array,
    we_up: jax.Array,
    we_down: jax.Array,
    scales: tuple | None = None,
    biases: tuple | None = None,
    cfg=None,
) -> jax.Array:          # [T, H] f32
    """Route -> sort-by-expert -> grouped MLP -> weighted unsort-combine."""
    T, H = ht.shape
    k = ids.shape[1]
    E = we_gate.shape[0]
    flat_ids = ids.reshape(-1)                       # [T*k]
    # Explicitly stable: equal expert ids keep token order, so the sorted
    # row layout — and the f32 scatter-add accumulation order below — is
    # deterministic across backends (XLA's default sort is NOT guaranteed
    # stable everywhere; tests/test_wide_ep.py pins this).
    order = jnp.argsort(flat_ids, stable=True)
    tok = order // k                                 # source token per slot
    xs = ht[tok]                                     # [T*k, H]
    group_sizes = jnp.bincount(flat_ids, length=E)
    ys = expert_mlp_grouped(
        xs, group_sizes, we_gate, we_up, we_down, scales=scales,
        biases=biases, cfg=cfg,
    )
    w_sorted = weights.reshape(-1)[order]
    return (
        jnp.zeros((T, H), jnp.float32)
        .at[tok]
        .add(ys.astype(jnp.float32) * w_sorted[:, None])
    )
