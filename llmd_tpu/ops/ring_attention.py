"""Context-parallel ring attention for chunked prefill.

The monolithic chunked-prefill attention computes every query row of a
chunk on every device (the batch axis is 1 for a long prompt, so the dp
axis idles). This op shards the CHUNK's query axis across the mesh "dp"
axis instead — each shard holds Q/cp query rows plus the matching slice
of the chunk's fresh K/V — and computes attention as a ring (Liu et al.,
Ring Attention):

  * every shard first accumulates online-softmax partials (flash-style
    m/l/acc) of its queries against the COMMITTED prefix in the paged
    pool (keys strictly below the chunk start — earlier chunks' pages),
    reading the same post-write cache the monolithic path reads so no
    pool copy materializes;
  * the chunk's fresh K/V blocks then rotate around the ring via
    ``jax.lax.ppermute`` (CollectivePermute over ICI) while each shard
    folds the visiting block into its partials;
  * blocks that originate on a HIGHER shard than the queries hold only
    future positions (the query axis is split contiguously), so the
    fold is skipped entirely — causal block skipping, ~half the ring
    work. The ppermute stays OUTSIDE the skip so every shard runs the
    identical collective sequence.

Numerics match the monolithic path to floating-point tolerance (the same
online-softmax recurrence over a different key partition); routing and
sampling downstream are byte-identical in practice. The fresh K/V
operands still CONTAIN pad rows (the pool write drops them via its OOB
scatter; here they are masked explicitly via ``valid``), and int8 pools
dequantize gathered prefix pages exactly like the blocked XLA fallback.

Geometry contract (validated by ParallelConfig): cp == mesh dp size,
Q % cp == 0, q heads divide tp, kv heads divide tp (or K == 1).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from llmd_tpu.compat import shard_map
from llmd_tpu.ops.paged_attention import _dequant_gathered, _window_mask

_NEG_INF = -1e30


def _online_update(m, l, acc, s, mask, v):
    """One flash-style block fold: s [B, Qs, K, G, S] masked scores,
    v [B, S, K, D] values; carry shapes match paged_attention_xla_blocked."""
    s = jnp.where(mask, s, _NEG_INF)
    m_new = jnp.maximum(m, jnp.max(s, axis=-1))
    alpha = jnp.exp(m - m_new)
    p = jnp.exp(s - m_new[..., None])
    p = jnp.where(mask, p, 0.0)
    l_new = l * alpha + jnp.sum(p, axis=-1)
    pv = jnp.einsum(
        "bqkgs,bskd->bqkgd", p.astype(v.dtype), v,
        preferred_element_type=jnp.float32,
    )
    acc_new = acc * alpha[..., None] + pv
    return m_new, l_new, acc_new


def _prefix_partials(
    qg, kv_slice, scales, page_table, kv_lens, positions, chunk_start,
    sm_scale, window, block_pages,
):
    """Online-softmax partials of the local queries against the COMMITTED
    prefix (pool keys strictly below the chunk start). Blocked scan over
    page blocks — the same recurrence as ``paged_attention_xla_blocked``
    but returning the raw (m, l, acc) carry for the ring to extend."""
    B, Qs, K, G, D = qg.shape
    num_pages, Kc, page, D2 = kv_slice.shape
    max_pages = page_table.shape[1]
    if max_pages % block_pages:
        pad = block_pages - max_pages % block_pages
        page_table = jnp.concatenate(
            [page_table, jnp.repeat(page_table[:, -1:], pad, axis=1)], axis=1
        )
        max_pages += pad
    n_blocks = max_pages // block_pages
    Sb = block_pages * page

    def body(carry, blk):
        m, l, acc = carry
        pt_blk = jax.lax.dynamic_slice_in_dim(
            page_table, blk * block_pages, block_pages, axis=1
        )
        kv = kv_slice[pt_blk]  # [B, bp, K, page, 2D]
        if scales is not None:
            k, v = _dequant_gathered(kv, scales, pt_blk, D, qg.dtype)
        else:
            kv = kv.transpose(0, 1, 3, 2, 4).reshape(B, Sb, K, D2)
            k = kv[..., :D]
            v = kv[..., D:]
        s = (
            jnp.einsum(
                "bqkgd,bskd->bqkgs", qg, k,
                preferred_element_type=jnp.float32,
            )
            * sm_scale
        )
        key_pos = blk * Sb + jnp.arange(Sb)[None, None, :]
        # Prefix keys only: strictly below the chunk start (this step's
        # fresh writes live at key_pos >= chunk_start and arrive via the
        # ring instead — reading them here would double-count).
        prefix = key_pos < chunk_start[:, None, None]
        in_ctx = key_pos < kv_lens[:, None, None]
        mask = (
            prefix & in_ctx & _window_mask(key_pos, positions, window)
        )[:, :, None, None, :]
        return _online_update(m, l, acc, s, mask, v), None

    m0 = jnp.full((B, Qs, K, G), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, Qs, K, G), jnp.float32)
    acc0 = jnp.zeros((B, Qs, K, G, D), jnp.float32)
    (m, l, acc), _ = jax.lax.scan(
        body, (m0, l0, acc0), jnp.arange(n_blocks, dtype=jnp.int32)
    )
    return m, l, acc


def ring_prefill_attention_full(
    q: jax.Array,        # [B, Q, H, D] post-RoPE queries
    kv_cache_full,       # [L, P, K, page, 2D] POST-write pool (or int8 tuple)
    layer,               # i32 scalar layer index
    k: jax.Array,        # [B, Q, K, D] this chunk's fresh keys (post-RoPE/rep)
    v: jax.Array,        # [B, Q, K, D] this chunk's fresh values
    page_table: jax.Array,  # [B, max_pages]
    kv_lens: jax.Array,  # [B] context end AFTER this chunk's writes
    positions: jax.Array,  # [B, Q]
    valid: jax.Array,    # [B, Q] bool — fresh rows include pad tokens
    sm_scale: float | None = None,
    mesh=None,
    cp: int = 1,
    window=None,         # i32 scalar (0/None = full attention)
    sinks=None,          # [H] per-q-head virtual-key logits
    block_pages: int = 32,
) -> jax.Array:
    """Ring-parallel chunked-prefill attention on the FULL [L, ...] cache.

    Reads the post-write pool for the committed prefix (masked to keys
    below the chunk start) and the rotating fresh K/V blocks for the
    chunk itself; the union covers exactly the monolithic path's
    ``key_pos <= position`` read set.
    """
    B, Q, H, D = q.shape
    if sm_scale is None:
        sm_scale = D**-0.5
    if cp <= 1 or mesh is None or Q % cp:
        # Degenerate geometry: the monolithic path IS the reference.
        from llmd_tpu.ops import paged_attention_full

        return paged_attention_full(
            q, kv_cache_full, layer, page_table, kv_lens, positions,
            sm_scale, world_size=1, mesh=None, window=window, sinks=sinks,
        )
    if isinstance(kv_cache_full, tuple):
        kv_cache_full, kv_scales = kv_cache_full
    else:
        kv_scales = None
    Kc = kv_cache_full.shape[2]
    sl = jax.lax.dynamic_index_in_dim(kv_cache_full, layer, 0, keepdims=False)
    ssl = (
        None if kv_scales is None
        else jax.lax.dynamic_index_in_dim(kv_scales, layer, 0, keepdims=False)
    )
    # Chunk start per row: the first query position. Computed on the
    # unsharded array — shard s > 0 never holds column 0.
    chunk_start = positions[:, 0]

    tp = mesh.shape["tp"]
    tp_k = "tp" if tp > 1 and Kc > 1 and Kc % tp == 0 else None
    win = jnp.zeros((), jnp.int32) if window is None else jnp.asarray(window, jnp.int32)
    use_win = window is not None
    sk = jnp.zeros((H,), jnp.float32) if sinks is None else sinks
    use_sinks = sinks is not None
    scale_spec = (P(None, tp_k, None, None),) if ssl is not None else ()
    scale_arg = (ssl,) if ssl is not None else ()
    perm = [(i, (i + 1) % cp) for i in range(cp)]

    def local(q, k, v, pos, val, sl, pt, kl, cs, win, sk, *sc):
        Bq, Qs, Hl, _ = q.shape
        Kl = k.shape[2]
        G = Hl // Kl
        qg = q.reshape(Bq, Qs, Kl, G, D)
        scales = sc[0] if sc else None
        my = jax.lax.axis_index("dp")

        # Prefix partials against the committed pool pages (overlappable
        # with the ring steps: no data dependency between the two).
        m, l, acc = _prefix_partials(
            qg, sl, scales, pt, kl, pos, cs, sm_scale,
            win if use_win else None, block_pages,
        )

        kb, vb, pb, ab = k, v, pos, val
        for t in range(cp):
            src = (my - t) % cp

            def attend(carry, kb=kb, vb=vb, pb=pb, ab=ab):
                m, l, acc = carry
                s = (
                    jnp.einsum(
                        "bqkgd,bskd->bqkgs", qg, kb,
                        preferred_element_type=jnp.float32,
                    )
                    * sm_scale
                )
                key_pos = pb[:, None, :]  # [B, 1, Qs]
                mask = (
                    (key_pos <= pos[:, :, None])
                    & ab[:, None, :]
                    & _window_mask(key_pos, pos, win if use_win else None)
                )[:, :, None, None, :]
                return _online_update(m, l, acc, s, mask, vb)

            # Causal block skipping: blocks from a higher-origin shard
            # hold only future positions (contiguous query split) — the
            # whole fold is skipped, ~halving the ring's work. The
            # rotation below stays OUTSIDE the cond: every shard must
            # run the identical collective sequence.
            m, l, acc = jax.lax.cond(
                src <= my, attend, lambda c: c, (m, l, acc)
            )
            if t < cp - 1:
                kb = jax.lax.ppermute(kb, "dp", perm)
                vb = jax.lax.ppermute(vb, "dp", perm)
                pb = jax.lax.ppermute(pb, "dp", perm)
                ab = jax.lax.ppermute(ab, "dp", perm)

        if use_sinks:
            skg = sk.astype(jnp.float32).reshape(Kl, G)[None, None, :, :]
            m2 = jnp.maximum(m, skg)
            l = l * jnp.exp(m - m2) + jnp.exp(skg - m2)
            acc = acc * jnp.exp(m - m2)[..., None]
        l = jnp.where(l == 0.0, 1.0, l)
        out = acc / l[..., None]
        return out.reshape(Bq, Qs, Hl, D).astype(q.dtype)

    return shard_map(
        local, mesh=mesh,
        in_specs=(
            P(None, "dp", "tp", None),   # q: chunk rows over dp, heads over tp
            P(None, "dp", tp_k, None),   # fresh k
            P(None, "dp", tp_k, None),   # fresh v
            P(None, "dp"),               # positions
            P(None, "dp"),               # valid
            P(None, tp_k, None, None),   # pool layer slice (dp-replicated)
            P(None, None),               # page table
            P(None),                     # kv_lens
            P(None),                     # chunk_start
            P(),                         # window
            P("tp"),                     # sinks (per-q-head)
            *scale_spec,
        ),
        out_specs=P(None, "dp", "tp", None),
        check_vma=False,
    )(q, k, v, positions, valid, sl, page_table, kv_lens, chunk_start,
      win, sk, *scale_arg)
