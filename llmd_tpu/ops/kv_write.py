"""Pallas in-place KV cache write (decode path).

The XLA scatter in ``write_kv_pages`` is not in-place under ``lax.scan``:
every decode step copies the ENTIRE per-layer KV pool (read+write), which
measured ~12ms/step for a 2048-page llama-3B pool on v5e — about 40% of
the decode step. This kernel aliases the cache HBM buffer into the
output (``input_output_aliases``) and issues one small DMA per token
(the [K, 1, 2D] slab at its page/offset), so per-step traffic is the
actual KV bytes (~1MB) instead of the pool size (GBs).

Used for Q==1 (decode); prefill keeps the XLA scatter, whose pool copy
amortizes over thousands of tokens per dispatch.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from llmd_tpu.compat import pallas_tpu_compiler_params


def _write_kernel(
    # scalar prefetch
    layer_ref,   # [1] i32 layer index (full-cache variant; [0] otherwise)
    phys_ref,    # [T] i32 physical page per token
    offset_ref,  # [T] i32 in-page slot per token
    valid_ref,   # [T] i32 (0/1)
    # blocks
    kv_new_ref,  # [1, K, 1, 2D] VMEM (this token's K/V slab)
    kv_hbm_ref,  # [(L,) num_pages, K, page, 2D] ANY (aliased into out)
    out_ref,     # same buffer as kv_hbm_ref
    # scratch (scratch_shapes buffers persist across grid steps — the
    # documented substrate for cross-step software pipelines)
    page_buf,    # [2, K, page, 2D] VMEM double buffer
    sem_in,      # [2] DMA
    sem_out,     # scalar DMA (stores complete in-step; no second slot)
):
    """Read-modify-write of the token's page: a direct single-row DMA into
    HBM violates the (8,128) sublane tiling, so the whole [K, page, 2D]
    slab (~64KB) rides through VMEM. Precondition: tokens in one grid
    launch target distinct pages (decode: one token per sequence, and the
    allocator never shares a page across sequences)."""
    t = pl.program_id(0)
    T = pl.num_programs(0)
    is_full = len(kv_hbm_ref.shape) == 5
    src = kv_hbm_ref.at[layer_ref[0]] if is_full else kv_hbm_ref
    dst = out_ref.at[layer_ref[0]] if is_full else out_ref

    # Software pipeline across grid steps (TPU grids run sequentially and
    # scratch persists): step t waits on the load it started at t-1,
    # modifies, stores, while t+1's load is already in flight. Each
    # index's start and wait are gated on the SAME predicate
    # (valid_ref[i]), so the semaphore protocol stays balanced while pad
    # rows skip their page DMA entirely (a 64-row bucket with 2 live
    # sequences would otherwise stream ~4MB/layer/step of discarded
    # pages).
    def load(i):
        slot_i = jax.lax.rem(i, 2)
        return pltpu.make_async_copy(
            src.at[phys_ref[i]], page_buf.at[slot_i], sem_in.at[slot_i]
        )

    @pl.when((t == 0) & (valid_ref[0] != 0))
    def _warmup():
        load(0).start()

    @pl.when((t + 1 < T) & (valid_ref[jnp.minimum(t + 1, T - 1)] != 0))
    def _prefetch():
        load(t + 1).start()

    slot = jax.lax.rem(t, 2)

    @pl.when(valid_ref[t] != 0)
    def _write():
        load(t).wait()
        # Masked select instead of a dynamic-index store: Mosaic cannot
        # prove sublane alignment for a runtime page offset.
        buf = page_buf.at[slot]
        rows = jax.lax.broadcasted_iota(jnp.int32, buf.shape, 1)
        buf[:] = jnp.where(rows == offset_ref[t], kv_new_ref[0], buf[:])
        store = pltpu.make_async_copy(buf, dst.at[phys_ref[t]], sem_out)
        store.start()
        # The slot's next LOAD starts at t+1 (other slot) and t+2 (this
        # slot); waiting here still overlaps this store with t+1's
        # in-flight load.
        store.wait()


def _flat_write_kernel(
    # scalar prefetch
    layer_ref,  # [1] i32 layer index (full-cache variant; [0] otherwise)
    src_ref,    # [R] i32 slab row of the run's first token (pre-shifted:
                #     src = page + t0 - off, so slab row off+j = token t0+j)
    phys_ref,   # [R] i32 physical page per run
    off_ref,    # [R] i32 first in-page slot per run
    cnt_ref,    # [R] i32 token count per run (0 = pad run, fully skipped)
    # blocks
    kv_new_ref,  # [K, Tp, 2D] ANY (whole step's token slab, page-padded)
    kv_hbm_ref,  # [(L,) num_pages, K, page, 2D] ANY (aliased into out)
    out_ref,     # same buffer as kv_hbm_ref
    # scratch
    page_buf,   # [2, K, page, 2D] VMEM double buffer (the target pages)
    slab_buf,   # [2, K, page, 2D] VMEM double buffer (the token slabs)
    sem_page,   # [2] DMA
    sem_slab,   # [2] DMA
    sem_out,    # scalar DMA
):
    """Flattened-token KV write, one RUN per grid step: a run is a
    maximal span of consecutive stream tokens landing in one physical
    page, so runs target DISTINCT pages by construction (the allocator
    never shares a page across sequences, and within a row the run
    covers every token the page receives) — which is what keeps the
    cross-step software pipeline's prefetch safe where the per-token
    decode kernel's same-page read-modify-writes would race it. The
    token slab arrives page-padded and pre-shifted ([K, T + 2*page,
    2D], run slab start = page + t0 - off), so the fixed-size slab DMA
    lands token t0+j exactly at page row off+j with no in-kernel
    gather."""
    r = pl.program_id(0)
    R = pl.num_programs(0)
    page = page_buf.shape[2]
    is_full = len(kv_hbm_ref.shape) == 5
    src = kv_hbm_ref.at[layer_ref[0]] if is_full else kv_hbm_ref
    dst = out_ref.at[layer_ref[0]] if is_full else out_ref

    def load(i):
        slot_i = jax.lax.rem(i, 2)
        return (
            pltpu.make_async_copy(
                src.at[phys_ref[i]], page_buf.at[slot_i], sem_page.at[slot_i]
            ),
            pltpu.make_async_copy(
                kv_new_ref.at[:, pl.ds(src_ref[i], page), :],
                slab_buf.at[slot_i],
                sem_slab.at[slot_i],
            ),
        )

    @pl.when((r == 0) & (cnt_ref[0] != 0))
    def _warmup():
        for c in load(0):
            c.start()

    @pl.when((r + 1 < R) & (cnt_ref[jnp.minimum(r + 1, R - 1)] != 0))
    def _prefetch():
        for c in load(r + 1):
            c.start()

    slot = jax.lax.rem(r, 2)

    @pl.when(cnt_ref[r] != 0)
    def _write():
        for c in load(r):
            c.wait()
        buf = page_buf.at[slot]
        rows = jax.lax.broadcasted_iota(jnp.int32, buf.shape, 1)
        hit = (rows >= off_ref[r]) & (rows < off_ref[r] + cnt_ref[r])
        buf[:] = jnp.where(hit, slab_buf[slot], buf[:])
        store = pltpu.make_async_copy(buf, dst.at[phys_ref[r]], sem_out)
        store.start()
        store.wait()


def _flat_write_call(kv_cache, kv_new_t, layer, src, phys, offset, cnt, interpret):
    K = kv_new_t.shape[0]
    page, D2 = kv_cache.shape[-2], kv_cache.shape[-1]
    R = src.shape[0]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=5,
        grid=(R,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.ANY),
            pl.BlockSpec(memory_space=pltpu.ANY),
        ],
        out_specs=pl.BlockSpec(memory_space=pltpu.ANY),
        scratch_shapes=[
            pltpu.VMEM((2, K, page, D2), kv_cache.dtype),
            pltpu.VMEM((2, K, page, D2), kv_cache.dtype),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA,
        ],
    )
    kernel = pl.pallas_call(
        _flat_write_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(kv_cache.shape, kv_cache.dtype),
        # operand index counts scalar-prefetch args first: 5 scalars,
        # kv_new_t, then kv_cache at index 6 -> aliased to output 0.
        input_output_aliases={6: 0},
        compiler_params=pallas_tpu_compiler_params(
            dimension_semantics=("arbitrary",),
        ),
        interpret=interpret,
    )
    return kernel(
        layer.astype(jnp.int32).reshape(1),
        src.astype(jnp.int32),
        phys.astype(jnp.int32),
        offset.astype(jnp.int32),
        cnt.astype(jnp.int32),
        kv_new_t,
        kv_cache,
    )


def write_kv_pages_flat_full(
    kv_cache: jax.Array,  # [L, num_pages, K, page, 2D] (whole model)
    kv_new: jax.Array,    # [T, K, 2D] packed token stream (K|V halves)
    layer: jax.Array,     # scalar i32
    src: jax.Array,       # [R] i32 slab start row (page + t0 - off)
    phys: jax.Array,      # [R] i32 physical page per run
    offset: jax.Array,    # [R] i32 first in-page slot per run
    cnt: jax.Array,       # [R] i32 token count per run (0 = pad)
    interpret: bool = False,
) -> jax.Array:
    """Layer-indexed flattened-token write: the whole step's packed token
    stream lands through run-addressed page read-modify-writes (see
    ``_flat_write_kernel``). The caller owns donation of the full cache
    (called under the engine's jitted flat step program)."""
    T, K, D2 = kv_new.shape
    L, num_pages, Kc, page, D2c = kv_cache.shape
    assert (K, D2) == (Kc, D2c), (kv_new.shape, kv_cache.shape)
    # Head-major slab, padded one page on both ends so every pre-shifted
    # run slice (src in [1, page + T]) stays in range.
    kv_new_t = jnp.pad(
        kv_new.transpose(1, 0, 2).astype(kv_cache.dtype),
        ((0, 0), (page, page), (0, 0)),
    )
    return _flat_write_call(
        kv_cache, kv_new_t, layer, src, phys, offset, cnt, interpret
    )


def _write_call(kv_cache, kv_new4, layer, phys, offset, valid, interpret):
    T, K = kv_new4.shape[0], kv_new4.shape[1]
    page, D2 = kv_cache.shape[-2], kv_cache.shape[-1]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(T,),
        in_specs=[
            pl.BlockSpec((1, K, 1, D2), lambda t, l, p, o, v: (t, 0, 0, 0)),
            pl.BlockSpec(memory_space=pltpu.ANY),
        ],
        out_specs=pl.BlockSpec(memory_space=pltpu.ANY),
        scratch_shapes=[
            pltpu.VMEM((2, K, page, D2), kv_cache.dtype),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA,
        ],
    )
    kernel = pl.pallas_call(
        _write_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(kv_cache.shape, kv_cache.dtype),
        # operand index counts scalar-prefetch args first: 4 scalars,
        # kv_new, then kv_cache at index 5 -> aliased to output 0.
        input_output_aliases={5: 0},
        compiler_params=pallas_tpu_compiler_params(
            dimension_semantics=("arbitrary",),
        ),
        interpret=interpret,
    )
    return kernel(
        layer.astype(jnp.int32).reshape(1),
        phys.astype(jnp.int32),
        offset.astype(jnp.int32),
        valid.astype(jnp.int32),
        kv_new4,
        kv_cache,
    )


@functools.partial(jax.jit, static_argnames=("interpret",), donate_argnums=(0,))
def write_kv_pages_decode(
    kv_cache: jax.Array,  # [num_pages, K, page, 2D]
    kv_new: jax.Array,    # [T, K, 2D] (K then V halves on the last axis)
    phys: jax.Array,      # [T] i32
    offset: jax.Array,    # [T] i32
    valid: jax.Array,     # [T] bool/i32
    interpret: bool = False,
) -> jax.Array:
    T, K, D2 = kv_new.shape
    num_pages, Kc, page, D2c = kv_cache.shape
    assert (K, D2) == (Kc, D2c), (kv_new.shape, kv_cache.shape)
    kv_new4 = kv_new.reshape(T, K, 1, D2).astype(kv_cache.dtype)
    return _write_call(
        kv_cache, kv_new4, jnp.zeros((1,), jnp.int32), phys, offset, valid,
        interpret,
    )


def write_kv_pages_decode_full(
    kv_cache: jax.Array,  # [L, num_pages, K, page, 2D] (whole model)
    kv_new: jax.Array,    # [T, K, 2D]
    layer: jax.Array,     # scalar i32
    phys: jax.Array,      # [T] i32
    offset: jax.Array,    # [T] i32
    valid: jax.Array,     # [T] bool/i32
    interpret: bool = False,
) -> jax.Array:
    """Layer-indexed variant: writes into cache[layer] with the FULL cache
    aliased in place, so a scan over layers never slices (and never
    copies) the pool. Called under an enclosing jit (the engine's step
    programs); the caller owns donation of the full cache."""
    T, K, D2 = kv_new.shape
    L, num_pages, Kc, page, D2c = kv_cache.shape
    assert (K, D2) == (Kc, D2c), (kv_new.shape, kv_cache.shape)
    kv_new4 = kv_new.reshape(T, K, 1, D2).astype(kv_cache.dtype)
    return _write_call(kv_cache, kv_new4, layer, phys, offset, valid, interpret)
