"""Pallas TPU paged attention (decode path).

TPU-native replacement for the reference's FlashInfer decode kernels
(SURVEY.md N8; reference docker/Dockerfile.cuda:71-72). The XLA fallback in
``paged_attention.py`` materializes the full padded context per layer; this
kernel streams only the LIVE context pages HBM->VMEM (double-buffered manual
DMAs, dynamic trip count = cdiv(kv_len, page)) and keeps a flash-style
online-softmax accumulator in VMEM. pages_per_block=16 measured ~2% faster
than 8 at short contexts (fewer loop trips) and keeps the per-slot VMEM
buffer around 1MB for GQA geometries.

Layout: kv_cache [num_pages, K, page, 2D] -- one page is a contiguous
[K, page, 2D] slab, fetched in a single DMA per loop iteration. Grid is
(B,): each program handles one sequence, looping its pages while the next
page's DMA is in flight; all KV heads are processed per iteration as a
K-batched MXU matmul.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from llmd_tpu.compat import pallas_tpu_compiler_params

NEG_INF = -2.0**30


def _decode_kernel(
    # scalar prefetch
    layer_ref,  # [1] i32 layer index (full-cache variant; [0] otherwise)
    # [rows_ref [T] i32 when row_lookup: the flattened-token layout's
    # token -> page-table-row map — the row-lookup prologue that lets
    # the grid iterate TOKENS against a compact [R, max_pages] table]
    *refs,
    page_size: int,
    head_dim: int,
    sm_scale: float,
    pages_per_block: int,
    has_sinks: bool,
    quant: bool,
    row_lookup: bool = False,
):
    # remaining scalar prefetch:
    #   page_table_ref  [B|R, max_pages] i32
    #   kv_lens_ref     [B] i32 (per token when row_lookup: position + 1,
    #                   the causal mask derived from cu_q_lens)
    #   win_starts_ref  [B] i32 first attended position (sliding; 0=full)
    # blocks: q_ref, sinks_ref, kv_hbm_full_ref, [ks_ref, vs_ref when
    # quant: [1, K, S_max] f16 per-row scales, gathered into lane-aligned
    # form by XLA in _decode_call — Mosaic manual DMA requires a
    # 128-aligned minor dim, which a page's [K, page, 2] scale slab (2
    # lanes) can never satisfy, so the scales cannot ride per-page DMAs
    # like the data], out_ref — see _decode_call
    if row_lookup:
        rows_ref, *refs = refs
    page_table_ref, kv_lens_ref, win_starts_ref, *refs = refs
    if quant:
        (q_ref, sinks_ref, kv_hbm_full_ref, ks_ref, vs_ref, out_ref,
         m_ref, l_ref, acc_ref) = refs
    else:
        q_ref, sinks_ref, kv_hbm_full_ref, out_ref, m_ref, l_ref, acc_ref = refs
    b = pl.program_id(0)
    # Row-lookup prologue: program b handles TOKEN b; its pages live in
    # the compact table's row rows_ref[b]. kv_lens/win_starts stay
    # per-program (per token).
    tr = rows_ref[b] if row_lookup else b
    kv_hbm_ref = (
        kv_hbm_full_ref.at[layer_ref[0]]
        if len(kv_hbm_full_ref.shape) == 5
        else kv_hbm_full_ref
    )
    D = head_dim
    K = q_ref.shape[1]
    ppb = pages_per_block
    S = ppb * page_size  # tokens per compute block
    kv_len = kv_lens_ref[b]
    win_start = win_starts_ref[b]  # first position this query may attend
    n_blocks = (kv_len + S - 1) // S
    blk_lo = win_start // S  # blocks fully before the window are skipped

    m_ref[:] = jnp.full_like(m_ref, NEG_INF)
    l_ref[:] = jnp.zeros_like(l_ref)
    acc_ref[:] = jnp.zeros_like(acc_ref)

    n_live_pages = (kv_len + page_size - 1) // page_size
    first_live_page = win_start // page_size

    def body(buf, sem):
        # buf: [2, K, S, 2D]; one DMA per page, ppb in flight per block.
        # Pages past the live context (tail block) — or wholly before the
        # sliding window — are never fetched.
        def _dma(slot, i, j):
            return pltpu.make_async_copy(
                kv_hbm_ref.at[page_table_ref[tr, i * ppb + j]],
                buf.at[slot, :, pl.ds(j * page_size, page_size), :],
                sem.at[slot, j],
            )

        def _page_live(i, j):
            p = i * ppb + j
            return jnp.logical_and(p < n_live_pages, p >= first_live_page)

        def start_block(slot, i):
            for j in range(ppb):  # static unroll

                @pl.when(_page_live(i, j))
                def _start():
                    _dma(slot, i, j).start()

        def wait_block(slot, i):
            for j in range(ppb):

                @pl.when(_page_live(i, j))
                def _wait():
                    _dma(slot, i, j).wait()

        @pl.when(n_blocks > blk_lo)
        def _warmup():
            start_block(jax.lax.rem(blk_lo, 2), blk_lo)

        def loop(i, _):
            slot = jax.lax.rem(i, 2)

            @pl.when(i + 1 < n_blocks)
            def _prefetch():
                start_block(jax.lax.rem(i + 1, 2), i + 1)

            wait_block(slot, i)
            kv = buf[slot]  # [K, S, 2D]
            k = kv[:, :, :D]
            v = kv[:, :, D:].astype(jnp.float32)
            q = q_ref[0]  # [K, G, D]
            ks = vs = None
            if quant:
                # Scales ride as f16 (they live on the f16 grid — see
                # pool_scales_to_wire) and upcast here: HALF the
                # per-block scale-plane bytes of the old f32 relayout,
                # bit-identical math (f16 -> f32 widening is exact).
                ks = ks_ref[0, :, pl.ds(i * S, S)].astype(jnp.float32)
                vs = vs_ref[0, :, pl.ds(i * S, S)].astype(jnp.float32)
                k = k.astype(q.dtype)  # i8 -> exact in bf16/f32
            # Unfetched positions (tail past kv_len, or pages before the
            # window) hold uninitialized VMEM; zero them so a stray NaN
            # can't poison the (0-prob x v) accumulation.
            pos_v = i * S + jax.lax.broadcasted_iota(jnp.int32, v.shape, 1)
            live_v = jnp.logical_and(pos_v < kv_len, pos_v >= win_start)
            v = jnp.where(live_v, v, 0.0)
            # K-batched (G, D) x (D, S) -> [K, G, S], f32 accumulate.
            s = jax.lax.dot_general(
                q, k, (((2,), (2,)), ((0,), (0,))),
                preferred_element_type=jnp.float32,
            ) * sm_scale
            if quant:
                # Row dequantization, factored around the matmuls on the
                # small [K, G, S] plane: (q . k_i8) * ks == q . (k_i8 *
                # ks); (probs * vs) . v_i8 == probs . (v_i8 * vs) — the
                # [K, S, D] value plane is never touched by scales.
                # Dead-column scale values die in the live mask below
                # (jnp.where does not propagate the unselected arm).
                s = s * ks[:, None, :]
            pos = i * S + jax.lax.broadcasted_iota(jnp.int32, s.shape, 2)
            live = jnp.logical_and(pos < kv_len, pos >= win_start)
            s = jnp.where(live, s, NEG_INF)

            m_prev = m_ref[:, :, :1]  # [K, G, 1]
            m_new = jnp.maximum(m_prev, jnp.max(s, axis=2, keepdims=True))
            alpha = jnp.exp(m_prev - m_new)
            probs = jnp.exp(s - m_new)  # [K, G, S]
            probs = jnp.where(live, probs, 0.0)
            l_ref[:, :, :1] = l_ref[:, :, :1] * alpha + jnp.sum(
                probs, axis=2, keepdims=True
            )
            m_ref[:, :, :1] = m_new
            # Dead-column vs values are DEFINED (the scale operand is a
            # fully-copied XLA gather, not a manual DMA) but may be a
            # pathological f16-overflow inf — 0-prob x inf = NaN, so
            # re-mask after the multiply.
            pv_probs = (
                probs if not quant
                else jnp.where(live, probs * vs[:, None, :], 0.0)
            )
            pv = jax.lax.dot_general(
                pv_probs, v, (((2,), (1,)), ((0,), (0,))),
                preferred_element_type=jnp.float32,
            )  # [K, G, D]
            acc_ref[:] = acc_ref[:] * alpha + pv
            return 0

        jax.lax.fori_loop(blk_lo, n_blocks, loop, 0)

    pl.run_scoped(
        body,
        buf=pltpu.VMEM(
            (2, K, ppb * page_size, kv_hbm_ref.shape[-1]), kv_hbm_ref.dtype
        ),
        sem=pltpu.SemaphoreType.DMA((2, ppb)),
    )

    l = l_ref[:, :, :1]
    if has_sinks:
        # gpt-oss attention sink: one extra value-less key — fold
        # exp(sink) into the denominator, rescaled into the running-max
        # frame (exact concat-then-drop semantics).
        m = m_ref[:, :, :1]
        sk = sinks_ref[...][:, :, None]  # read the block, then broadcast
        m2 = jnp.maximum(m, sk)
        l = l * jnp.exp(m - m2) + jnp.exp(sk - m2)
        acc_ref[:] = acc_ref[:] * jnp.exp(m - m2)
    l = jnp.where(l == 0.0, 1.0, l)
    out_ref[0] = (acc_ref[:] / l).astype(out_ref.dtype)


def _decode_call(
    q, kv_cache, layer, page_table, kv_lens, sm_scale, interpret,
    pages_per_block, window=None, sinks=None, scales=None,
):
    B, Q, H, D = q.shape
    assert Q == 1, "decode kernel handles Q=1"
    K, page, D2 = kv_cache.shape[-3], kv_cache.shape[-2], kv_cache.shape[-1]
    assert D2 == 2 * D
    G = H // K
    if sm_scale is None:
        sm_scale = D**-0.5
    max_pages = page_table.shape[1]
    if max_pages % pages_per_block:
        # pad the table so block index arithmetic never reads out of bounds
        pad = pages_per_block - max_pages % pages_per_block
        page_table = jnp.pad(page_table, ((0, 0), (0, pad)))

    qk = q.reshape(B, K, G, D)
    # Sliding window: the decode query sits at kv_len-1, so the first
    # attended position is max(0, kv_len - window). window may be a traced
    # per-layer scalar; window<=0 (or None) degrades to full attention.
    if window is None:
        win_starts = jnp.zeros_like(kv_lens)
    else:
        window = jnp.asarray(window, jnp.int32)
        win_starts = jnp.where(
            window > 0, jnp.maximum(kv_lens - window, 0), 0
        ).astype(jnp.int32)

    if sinks is None:
        sinks2d = jnp.zeros((K, G), jnp.float32)
    else:
        # q head h maps to (h // G, h % G) — same grouping as qk above.
        sinks2d = sinks.astype(jnp.float32).reshape(K, G)

    in_specs = [
        pl.BlockSpec((1, K, G, D), lambda b, l, pt, kl, ws: (b, 0, 0, 0)),
        pl.BlockSpec((K, G), lambda b, l, pt, kl, ws: (0, 0)),
        pl.BlockSpec(memory_space=pltpu.ANY),  # stays in HBM; manual DMA
    ]
    operands = [qk, sinks2d, kv_cache]
    if scales is not None:
        # Per-row scales, gathered + relayouted to lane-aligned
        # [B, K, S_max] by XLA. A per-page scale DMA inside the kernel
        # (like the data pages) is structurally impossible: Mosaic
        # requires a 128-aligned minor dim on manual copies and a page's
        # scale slab is 2 lanes wide in every scatter-friendly layout —
        # measured anyway via a const-scales probe: this gather is NOT
        # the int8 decode cost (within noise of zero).
        lidx = jnp.asarray(layer, jnp.int32).reshape(-1)[0]
        sl = (
            jax.lax.dynamic_index_in_dim(scales, lidx, 0, keepdims=False)
            if scales.ndim == 5 else scales
        )  # [P, K, page, 2]
        mp = page_table.shape[1]
        # Cast BEFORE the gather: pool scales are f32 values ON the f16
        # grid (quant_kv layout contract), so the f16 gather+relayout
        # moves half the bytes of the old f32 form losslessly — this
        # plane scales with max_pages, not the live context, which made
        # it the widest int8-only HBM stream in the decode step.
        g = sl.astype(jnp.float16)[page_table]  # [B, mp, K, page, 2]
        ksvs = g.transpose(0, 2, 4, 1, 3).reshape(B, K, 2, mp * page)
        sspec = pl.BlockSpec(
            (1, K, mp * page), lambda b, l, pt, kl, ws: (b, 0, 0)
        )
        in_specs.extend([sspec, sspec])
        operands.extend([ksvs[:, :, 0], ksvs[:, :, 1]])
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(B,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec(
            (1, K, G, D), lambda b, l, pt, kl, ws: (b, 0, 0, 0)
        ),
        scratch_shapes=[
            pltpu.VMEM((K, G, 128), jnp.float32),
            pltpu.VMEM((K, G, 128), jnp.float32),
            pltpu.VMEM((K, G, D), jnp.float32),
        ],
    )
    kernel = pl.pallas_call(
        functools.partial(
            _decode_kernel,
            page_size=page,
            head_dim=D,
            sm_scale=sm_scale,
            pages_per_block=pages_per_block,
            has_sinks=sinks is not None,
            quant=scales is not None,
        ),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, K, G, D), q.dtype),
        compiler_params=pallas_tpu_compiler_params(
            dimension_semantics=("arbitrary",),
        ),
        interpret=interpret,
    )
    out = kernel(
        layer.astype(jnp.int32).reshape(1), page_table, kv_lens, win_starts,
        *operands,
    )
    return out.reshape(B, 1, H, D)


@functools.partial(
    jax.jit, static_argnames=("sm_scale", "interpret", "pages_per_block")
)
def decode_paged_attention(
    q: jax.Array,  # [B, 1, H, D]
    kv_cache: jax.Array,  # [num_pages, K, page, 2D]
    page_table: jax.Array,  # [B, max_pages] i32
    kv_lens: jax.Array,  # [B] i32
    sm_scale: float | None = None,
    interpret: bool = False,
    pages_per_block: int = 16,
    window: jax.Array | None = None,
    sinks: jax.Array | None = None,
    scales: jax.Array | None = None,  # [num_pages, K, page, 2]
) -> jax.Array:
    return _decode_call(
        q, kv_cache, jnp.zeros((1,), jnp.int32), page_table, kv_lens,
        sm_scale, interpret, pages_per_block, window=window, sinks=sinks,
        scales=scales,
    )


def flat_paged_attention_full(
    q: jax.Array,  # [T, 1, H, D] packed token-query stream
    kv_cache: jax.Array,  # [L, num_pages, K, page, 2D] (whole model)
    layer: jax.Array,  # scalar i32
    rows: jax.Array,  # [T] i32 token -> page-table row (cu_q_lens lookup)
    page_table: jax.Array,  # [R, max_pages] COMPACT per-row table
    kv_lens: jax.Array,  # [T] i32 per-token: position + 1 (causal-in-row)
    sm_scale: float | None = None,
    interpret: bool = False,
    pages_per_block: int = 16,
    window: jax.Array | None = None,
    sinks: jax.Array | None = None,
    scales: jax.Array | None = None,  # [L, num_pages, K, page, 2]
) -> jax.Array:
    """Flattened-token (``cu_q_lens``) attention: the grid iterates the
    packed TOKEN stream — program t streams exactly the pages token t's
    row holds up to its own position (kv_len = pos + 1 IS the causal
    mask within the row) — against the compact per-row table through a
    scalar-prefetched row-lookup prologue, so no [T, max_pages]
    per-token table is ever materialized for the data DMAs. Pure decode
    rows cost ONE program; prefill-chunk tokens each stream their live
    prefix (write-before-read per layer makes same-step earlier tokens'
    fresh KV visible)."""
    T, Q, H, D = q.shape
    assert Q == 1, "flat attention takes the packed [T, 1, H, D] stream"
    K, page, D2 = kv_cache.shape[-3], kv_cache.shape[-2], kv_cache.shape[-1]
    assert D2 == 2 * D
    G = H // K
    if sm_scale is None:
        sm_scale = D**-0.5
    max_pages = page_table.shape[1]
    if max_pages % pages_per_block:
        pad = pages_per_block - max_pages % pages_per_block
        page_table = jnp.pad(page_table, ((0, 0), (0, pad)))

    qk = q.reshape(T, K, G, D)
    if window is None:
        win_starts = jnp.zeros_like(kv_lens)
    else:
        window = jnp.asarray(window, jnp.int32)
        win_starts = jnp.where(
            window > 0, jnp.maximum(kv_lens - window, 0), 0
        ).astype(jnp.int32)
    if sinks is None:
        sinks2d = jnp.zeros((K, G), jnp.float32)
    else:
        sinks2d = sinks.astype(jnp.float32).reshape(K, G)

    # 5 scalar prefetch args: layer, rows, page_table, kv_lens, win_starts.
    in_specs = [
        pl.BlockSpec((1, K, G, D), lambda b, l, r, pt, kl, ws: (b, 0, 0, 0)),
        pl.BlockSpec((K, G), lambda b, l, r, pt, kl, ws: (0, 0)),
        pl.BlockSpec(memory_space=pltpu.ANY),  # stays in HBM; manual DMA
    ]
    operands = [qk, sinks2d, kv_cache]
    if scales is not None:
        # Per-ROW scale plane (scales cannot ride the page DMAs — see
        # _decode_call): gathered ONCE per row ([R, K, mp*page], f16 on
        # the wire, upcast in-kernel — lossless, half the bytes) and
        # indexed through the scalar-prefetched row map in the
        # BlockSpec, so a prefill chunk's tokens share one plane
        # instead of duplicating it chunk-length times into a
        # [T, max_pages, ...] intermediate.
        lidx = jnp.asarray(layer, jnp.int32).reshape(-1)[0]
        sl = (
            jax.lax.dynamic_index_in_dim(scales, lidx, 0, keepdims=False)
            if scales.ndim == 5 else scales
        )
        mp = page_table.shape[1]
        R = page_table.shape[0]
        g = sl.astype(jnp.float16)[page_table]  # [R, mp, K, page, 2]
        ksvs = g.transpose(0, 2, 4, 1, 3).reshape(R, K, 2, mp * page)
        sspec = pl.BlockSpec(
            (1, K, mp * page), lambda b, l, r, pt, kl, ws: (r[b], 0, 0)
        )
        in_specs.extend([sspec, sspec])
        operands.extend([ksvs[:, :, 0], ksvs[:, :, 1]])
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=5,
        grid=(T,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec(
            (1, K, G, D), lambda b, l, r, pt, kl, ws: (b, 0, 0, 0)
        ),
        scratch_shapes=[
            pltpu.VMEM((K, G, 128), jnp.float32),
            pltpu.VMEM((K, G, 128), jnp.float32),
            pltpu.VMEM((K, G, D), jnp.float32),
        ],
    )
    kernel = pl.pallas_call(
        functools.partial(
            _decode_kernel,
            page_size=page,
            head_dim=D,
            sm_scale=sm_scale,
            pages_per_block=pages_per_block,
            has_sinks=sinks is not None,
            quant=scales is not None,
            row_lookup=True,
        ),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((T, K, G, D), q.dtype),
        compiler_params=pallas_tpu_compiler_params(
            dimension_semantics=("arbitrary",),
        ),
        interpret=interpret,
    )
    out = kernel(
        jnp.asarray(layer, jnp.int32).reshape(1),
        rows.astype(jnp.int32),
        page_table,
        kv_lens,
        win_starts,
        *operands,
    )
    return out.reshape(T, 1, H, D)


def decode_paged_attention_full(
    q: jax.Array,  # [B, 1, H, D]
    kv_cache: jax.Array,  # [L, num_pages, K, page, 2D] (whole model)
    layer: jax.Array,  # scalar i32
    page_table: jax.Array,
    kv_lens: jax.Array,
    sm_scale: float | None = None,
    interpret: bool = False,
    pages_per_block: int = 16,
    window: jax.Array | None = None,
    sinks: jax.Array | None = None,
    scales: jax.Array | None = None,  # [L, num_pages, K, page, 2]
) -> jax.Array:
    """Layer-indexed variant: reads cache[layer] pages directly from the
    full-cache HBM ref — a scan over layers never materializes a
    pool-sized slice."""
    return _decode_call(
        q, kv_cache, layer, page_table, kv_lens, sm_scale, interpret,
        pages_per_block, window=window, sinks=sinks, scales=scales,
    )
