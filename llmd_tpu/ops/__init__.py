"""TPU compute kernels (Pallas) and their XLA reference fallbacks.

``paged_attention`` / ``write_kv_pages`` (and their layer-indexed
``*_full`` variants for the scan-carry cache layout) dispatch at trace
time: the Pallas decode kernels on TPU-class backends for Q=1 with
tile-compatible geometry, the XLA fallbacks otherwise. Env
LLMD_PALLAS=off disables the kernels; =interpret forces interpret mode
(CPU parity testing).

Sharded meshes (tp/dp > 1) run the SAME kernels per device under
shard_map — the role FlashInfer plays under vLLM TP in the reference
stack (docker/Dockerfile.cuda:71-72). Layout contract:

  - q/attention-output heads shard over tp (they arrive sharded: wq/wo
    are tp-sharded in PARAM_SPECS); the KV pool's kv-head axis shards
    over tp when tp divides num_kv_heads (kv_cache_spec).
  - the batch shards over dp for attention reads; KV WRITES replicate
    the (tiny) per-step K/V slabs across dp so every dp replica of the
    pool applies identical updates and replicas never diverge — the
    pool itself is never partitioned over dp (each dp group keeps a
    full copy, matching the engine's per-rank-pool design).
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from llmd_tpu.compat import shard_map

from llmd_tpu.ops.paged_attention import (
    paged_attention_xla,
    paged_attention_xla_blocked,
    scatter_kv_scales,
    scatter_kv_scales_flat,
)
from llmd_tpu.ops.paged_attention import write_kv_pages as write_kv_pages_xla
from llmd_tpu.ops.kv_write import (
    write_kv_pages_decode,
    write_kv_pages_decode_full,
    write_kv_pages_flat_full,
)
from llmd_tpu.ops.ragged_paged_attention import (
    decode_paged_attention,
    decode_paged_attention_full,
    flat_paged_attention_full,
)

_TPU_PLATFORMS = {"tpu", "axon"}


def _mode() -> str:
    return os.environ.get("LLMD_PALLAS", "auto")


def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform in _TPU_PLATFORMS
    except Exception:
        return False


def _interpret() -> bool:
    return _mode() == "interpret"


def _platform_ok() -> bool:
    return _mode() == "interpret" or _on_tpu()


def _mesh_dims(mesh) -> tuple[int, int] | None:
    if mesh is None or not ({"dp", "tp"} <= set(mesh.axis_names)):
        return None
    return mesh.shape["dp"], mesh.shape["tp"]


def _geometry_ok(Q, page, D, D2, need_lane_d: bool) -> bool:
    """Per-shard kernel geometry: decode shape (Q==1), sublane-tiled pages
    (page % 8), packed K/V halves (D2 == 2D). ``need_lane_d``: the
    ATTENTION kernel matmuls over D, so D itself must be lane-tiled
    (D % 128); the WRITE kernel only moves [.., D2] slabs, so D2 % 128
    suffices (head_dim-64 models keep the in-place write)."""
    if not (Q == 1 and page % 8 == 0 and D2 == 2 * D and D2 % 128 == 0):
        return False
    return not (need_lane_d and D % 128 != 0)


def _mesh_plan(world_size, mesh, B=None, H=None, K=None) -> str:
    """Shared tail of every dispatch decision once geometry/platform pass:
    "direct" (single device), "shard" (per-device kernels under
    shard_map), or "xla". Divisibility gates, each skipped when the axis
    is irrelevant to the caller (None): tp | H (q heads stay local),
    tp | K for K > 1 (the pool's kv-head axis is tp-sharded; K == 1 MLA
    latent pools replicate), dp | B (batch rows split evenly — writes
    replicate the batch instead and pass B=None)."""
    if world_size == 1:
        return "direct"
    dims = _mesh_dims(mesh)
    if dims is None:
        return "xla"
    dp, tp = dims
    if H is not None and H % tp:
        return "xla"
    if K is not None and K > 1 and K % tp:
        return "xla"
    if B is not None and B % dp:
        return "xla"
    return "shard"


def _plan(Q, page, D, D2, world_size, need_lane_d, mesh, B, H, K):
    """Dense-kernel dispatch: geometry/platform gate, then _mesh_plan."""
    if _mode() == "off" or not _geometry_ok(Q, page, D, D2, need_lane_d):
        return "xla"
    if not _platform_ok():
        return "xla"
    return _mesh_plan(world_size, mesh, B=B, H=H, K=K)


def _plan_write(Q, page, D, D2, world_size, mesh):
    """Write-kernel dispatch: no head/batch divisibility gates — the
    sharded write replicates the batch across dp and _kv_head_axis
    degrades to a replicated head axis when tp does not divide K."""
    if _mode() == "off" or not _geometry_ok(Q, page, D, D2, need_lane_d=False):
        return "xla"
    if not _platform_ok():
        return "xla"
    return _mesh_plan(world_size, mesh)


def _plan_mla(Q, page, Dl, rank, world_size, mesh, B, H):
    """MLA attention dispatch: latent-width tiling instead of D2 == 2D;
    the latent pool replicates over tp (K folds away)."""
    if _mode() == "off" or not (
        Q == 1 and page % 8 == 0 and Dl % 128 == 0 and rank % 128 == 0
    ):
        return "xla"
    if not _platform_ok():
        return "xla"
    return _mesh_plan(world_size, mesh, B=B, H=H)


# Above this context size the dense XLA attention's [B, Q, .., S] score
# tensor dominates memory (it grows as chunk x context); switch to the
# blocked online-softmax form.
_DENSE_XLA_MAX_S = 4096


def _split_cache(kv_cache):
    """(data, scales) view of a pool: int8 pools travel as a 2-tuple
    (data i8, scales f16 — ops/quant_kv.py layout); float pools as a
    bare array with scales None."""
    if isinstance(kv_cache, tuple):
        return kv_cache
    return kv_cache, None


def _attention_xla(q, kv_slice, page_table, kv_lens, positions, sm_scale,
                   window=None, sinks=None, scales=None):
    S = page_table.shape[1] * kv_slice.shape[-2]
    if S > _DENSE_XLA_MAX_S:
        # The blocked online-softmax path handles Q==1 too — long-context
        # DECODE through the XLA fallback (e.g. sink models) must not
        # gather the whole padded context per step.
        return paged_attention_xla_blocked(
            q, kv_slice, page_table, kv_lens, positions, sm_scale,
            window=window, sinks=sinks, scales=scales,
        )
    return paged_attention_xla(
        q, kv_slice, page_table, kv_lens, positions, sm_scale, window=window,
        sinks=sinks, scales=scales,
    )


def _decode_write_prep(k, v, page_table, positions, page):
    """[B,1,K,D] k/v -> (kv_new [B,K,2D], phys [B], offset [B])."""
    B, _, K, D = k.shape
    kv_new = jnp.concatenate([k, v], axis=-1).reshape(B, K, 2 * D)
    pos = positions[:, 0]
    phys = jnp.take_along_axis(page_table, (pos // page)[:, None], axis=1)[:, 0]
    return kv_new, phys, pos % page


def _kv_head_axis(K: int, tp: int) -> str | None:
    # K == 1 (MLA latent pool) and non-dividing K keep the head axis
    # replicated — matching kv_cache_spec's allocation-time policy.
    return "tp" if tp > 1 and K > 1 and K % tp == 0 else None


def _write_sharded(mesh, kv_cache, kv_new, layer, phys, offset, valid, full):
    """Per-device in-place writes with the batch REPLICATED across dp:
    the slabs are tiny (B x K x 2D), and identical writes on every dp
    replica keep the un-partitioned pool consistent."""
    K = kv_new.shape[1]
    tp_k = _kv_head_axis(K, mesh.shape["tp"])
    cache_spec = (
        P(None, None, tp_k, None, None) if full else P(None, tp_k, None, None)
    )
    interpret = _interpret()

    if full:

        def local(cache, kv_new, layer, phys, offset, valid):
            return write_kv_pages_decode_full(
                cache, kv_new, layer, phys, offset, valid, interpret=interpret
            )

        args = (kv_cache, kv_new, layer, phys, offset, valid)
        in_specs = (cache_spec, P(None, tp_k, None), P(), P(), P(), P())
    else:

        def local(cache, kv_new, phys, offset, valid):
            return write_kv_pages_decode(
                cache, kv_new, phys, offset, valid, interpret=interpret
            )

        args = (kv_cache, kv_new, phys, offset, valid)
        in_specs = (cache_spec, P(None, tp_k, None), P(), P(), P())

    return shard_map(
        local, mesh=mesh, in_specs=in_specs, out_specs=cache_spec,
        check_vma=False,
    )(*args)


def write_kv_pages(
    kv_cache, k, v, page_table, positions, valid, world_size=1, mesh=None
):
    """Scatter this step's K/V into the (single-layer) paged cache.

    Decode (Q==1) on TPU uses the Pallas in-place kernel — the XLA
    scatter copies the whole pool per step when the buffer is not
    donated; the kernel DMAs only the written slabs. Prefill and
    non-TPU paths keep the XLA scatter.
    """
    B, Q, K, D = k.shape
    num_pages, Kc, page, D2 = kv_cache.shape
    plan = _plan_write(Q, page, D, D2, world_size, mesh)
    if plan != "xla":
        kv_new, phys, offset = _decode_write_prep(k, v, page_table, positions, page)
        if plan == "direct":
            return write_kv_pages_decode(
                kv_cache, kv_new, phys, offset, valid[:, 0], interpret=_interpret()
            )
        return _write_sharded(
            mesh, kv_cache, kv_new, None, phys, offset, valid[:, 0], full=False
        )
    return write_kv_pages_xla(kv_cache, k, v, page_table, positions, valid)


def write_kv_pages_full(
    kv_cache_full, layer, k, v, page_table, positions, valid,
    world_size=1, mesh=None,
):
    """Layer-indexed write on the FULL [L, ...] cache (scan-carry layout).

    The whole point: a lax.scan over layers that slices the cache pays a
    pool-sized copy per layer (slice + update, or xs->ys buffers); the
    Pallas variant indexes [layer, page] inside the kernel so only the
    written slabs move. Fallback (CPU / prefill / non-divisible
    sharding): dynamic slice + XLA scatter + dynamic update — the
    carry-update pattern XLA optimizes in place where it can.

    Int8 pools (tuple cache): k/v rows quantize on device first; the
    int8 data rides the same dispatch below (the Pallas kernel moves
    HALF the bytes), and the tiny per-row scales scatter via XLA.
    """
    kv_cache_full, kv_scales = _split_cache(kv_cache_full)
    if kv_scales is not None:
        from llmd_tpu.ops.quant_kv import quantize_kv_rows

        k8, v8, srow = quantize_kv_rows(k, v)
        data = write_kv_pages_full(
            kv_cache_full, layer, k8, v8, page_table, positions, valid,
            world_size=world_size, mesh=mesh,
        )
        # Slice + scatter + update-slice on the layer's scale pool
        # ([P, K, page, 2]): the full-array layer-indexed scatter reads
        # cleaner but defeats XLA's in-place aliasing (the attention
        # read is a second consumer), copying the whole scale pool per
        # layer — measured 10x slower e2e. The slice form pays ~2
        # layer-slices per step (~1/128 of the data bytes).
        ssl = jax.lax.dynamic_index_in_dim(kv_scales, layer, 0, keepdims=False)
        ssl = scatter_kv_scales(ssl, srow, page_table, positions, valid)
        return (data, jax.lax.dynamic_update_index_in_dim(kv_scales, ssl, layer, 0))
    B, Q, K, D = k.shape
    L, num_pages, Kc, page, D2 = kv_cache_full.shape
    plan = _plan_write(Q, page, D, D2, world_size, mesh)
    if plan != "xla":
        kv_new, phys, offset = _decode_write_prep(k, v, page_table, positions, page)
        if plan == "direct":
            return write_kv_pages_decode_full(
                kv_cache_full, kv_new, layer, phys, offset, valid[:, 0],
                interpret=_interpret(),
            )
        return _write_sharded(
            mesh, kv_cache_full, kv_new, layer, phys, offset, valid[:, 0],
            full=True,
        )
    sl = jax.lax.dynamic_index_in_dim(kv_cache_full, layer, 0, keepdims=False)
    sl = write_kv_pages_xla(sl, k, v, page_table, positions, valid)
    return jax.lax.dynamic_update_index_in_dim(kv_cache_full, sl, layer, 0)


def write_kv_pages_full_flat(
    kv_cache_full, layer, k, v, page_table, rows, positions, valid, runs,
    world_size=1, mesh=None,
):
    """Flattened-token (``cu_q_lens``) layer-indexed KV write: k/v arrive
    as the packed ``[T, 1, K, D]`` token stream, ``page_table`` stays the
    COMPACT per-row table indexed through ``rows`` ([T] token -> row),
    and the TPU path lands the stream via run-addressed page
    read-modify-writes (``runs`` = (src, off, cnt) + this pool's phys —
    same-page-safe where the per-token decode kernel's pipeline is not).
    XLA fallback: gather the per-token table rows, then the plain
    scatter (distinct (page, slot) targets per live token).
    """
    kv_cache_full, kv_scales = _split_cache(kv_cache_full)
    if kv_scales is not None:
        from llmd_tpu.ops.quant_kv import quantize_kv_rows

        k8, v8, srow = quantize_kv_rows(k, v)
        data = write_kv_pages_full_flat(
            kv_cache_full, layer, k8, v8, page_table, rows, positions,
            valid, runs, world_size=world_size, mesh=mesh,
        )
        ssl = jax.lax.dynamic_index_in_dim(kv_scales, layer, 0, keepdims=False)
        # Per-token enumerated scatter: the decode-path dense-slab form
        # assumes one token per page, which the flattened stream breaks
        # (a prefill chunk's tokens share pages).
        ssl = scatter_kv_scales_flat(
            ssl, srow, page_table, rows, positions, valid
        )
        return (data, jax.lax.dynamic_update_index_in_dim(kv_scales, ssl, layer, 0))
    B, Q, K, D = k.shape
    L, num_pages, Kc, page, D2 = kv_cache_full.shape
    plan = _plan_write(Q, page, D, D2, world_size, mesh)
    if plan != "xla" and runs is not None:
        src, off, cnt, phys = runs
        kv_new = jnp.concatenate([k, v], axis=-1).reshape(B, K, 2 * D)
        if plan == "direct":
            return write_kv_pages_flat_full(
                kv_cache_full, kv_new, layer, src, phys, off, cnt,
                interpret=_interpret(),
            )
        tp_k = _kv_head_axis(K, mesh.shape["tp"])
        cache_spec = P(None, None, tp_k, None, None)
        interpret = _interpret()

        def local(cache, kv_new, layer, src, phys, off, cnt):
            return write_kv_pages_flat_full(
                cache, kv_new, layer, src, phys, off, cnt,
                interpret=interpret,
            )

        return shard_map(
            local, mesh=mesh,
            in_specs=(
                cache_spec, P(None, tp_k, None), P(), P(), P(), P(), P(),
            ),
            out_specs=cache_spec,
            check_vma=False,
        )(kv_cache_full, kv_new, layer, src, phys, off, cnt)
    pt_tok = page_table[rows]  # [T, max_pages]
    sl = jax.lax.dynamic_index_in_dim(kv_cache_full, layer, 0, keepdims=False)
    sl = write_kv_pages_xla(sl, k, v, pt_tok, positions, valid)
    return jax.lax.dynamic_update_index_in_dim(kv_cache_full, sl, layer, 0)


def paged_attention_full_flat(
    q, kv_cache_full, layer, rows, page_table, kv_lens, positions,
    sm_scale=None, world_size=1, mesh=None, window=None, sinks=None,
):
    """Flattened-token (``cu_q_lens``) layer-indexed attention: q is the
    packed ``[T, 1, H, D]`` stream, ``kv_lens`` is per TOKEN (position +
    1 — causality within a row derived from the packing), and the TPU
    kernel iterates tokens against the compact per-row table through
    its row-lookup prologue. XLA fallback gathers per-token table rows
    and reuses the bucketed reference path."""
    kv_cache_full, kv_scales = _split_cache(kv_cache_full)
    L, num_pages, K, page, D2 = kv_cache_full.shape
    T, Q, H, D = q.shape
    plan = _plan(Q, page, D, D2, world_size, True, mesh, T, H, K)
    if window is not None:
        window = jnp.asarray(window, jnp.int32)
    if plan == "direct":
        return flat_paged_attention_full(
            q, kv_cache_full, layer, rows, page_table, kv_lens,
            sm_scale=sm_scale, interpret=_interpret(), window=window,
            sinks=sinks, scales=kv_scales,
        )
    if plan == "shard":
        tp_k = _kv_head_axis(K, mesh.shape["tp"])
        interpret = _interpret()
        win = jnp.zeros((), jnp.int32) if window is None else window
        use_win = window is not None
        sk = jnp.zeros((H,), jnp.float32) if sinks is None else sinks
        use_sinks = sinks is not None
        scale_spec = (
            (P(None, None, tp_k, None, None),) if kv_scales is not None else ()
        )
        scale_arg = (kv_scales,) if kv_scales is not None else ()

        def local(q, cache, layer, rows, pt, kl, win, sk, *sc):
            return flat_paged_attention_full(
                q, cache, layer, rows, pt, kl, sm_scale=sm_scale,
                interpret=interpret, window=win if use_win else None,
                sinks=sk if use_sinks else None,
                scales=sc[0] if sc else None,
            )

        # The compact table stays REPLICATED: any token shard may
        # reference any row; tokens (q/rows/kv_lens) split over dp.
        return shard_map(
            local, mesh=mesh,
            in_specs=(
                P("dp", None, "tp", None), P(None, None, tp_k, None, None),
                P(), P("dp"), P(None, None), P("dp"), P(), P("tp"),
                *scale_spec,
            ),
            out_specs=P("dp", None, "tp", None),
            check_vma=False,
        )(q, kv_cache_full, layer, rows, page_table, kv_lens, win, sk,
          *scale_arg)
    pt_tok = page_table[rows]  # [T, max_pages]
    sl = jax.lax.dynamic_index_in_dim(kv_cache_full, layer, 0, keepdims=False)
    ssl = (
        None if kv_scales is None
        else jax.lax.dynamic_index_in_dim(kv_scales, layer, 0, keepdims=False)
    )
    return _attention_xla(
        q, sl, pt_tok, kv_lens, positions, sm_scale, window=window,
        sinks=sinks, scales=ssl,
    )


def paged_attention(
    q, kv_cache, page_table, kv_lens, positions, sm_scale=None,
    world_size=1, mesh=None,
):
    """Decode attention. Sharded meshes run the kernel per device under
    shard_map: q/output heads over tp, batch over dp, pool heads over tp
    (dp replicas of the pool read-only here)."""
    num_pages, K, page, D2 = kv_cache.shape
    B, Q, H, D = q.shape
    plan = _plan(Q, page, D, D2, world_size, True, mesh, B, H, K)
    if plan == "direct":
        return decode_paged_attention(
            q, kv_cache, page_table, kv_lens, sm_scale=sm_scale,
            interpret=_interpret(),
        )
    if plan == "shard":
        tp_k = _kv_head_axis(K, mesh.shape["tp"])
        interpret = _interpret()

        def local(q, cache, pt, kl):
            return decode_paged_attention(
                q, cache, pt, kl, sm_scale=sm_scale, interpret=interpret
            )

        return shard_map(
            local, mesh=mesh,
            in_specs=(
                P("dp", None, "tp", None), P(None, tp_k, None, None),
                P("dp", None), P("dp"),
            ),
            out_specs=P("dp", None, "tp", None),
            check_vma=False,
        )(q, kv_cache, page_table, kv_lens)
    return _attention_xla(q, kv_cache, page_table, kv_lens, positions, sm_scale)


def mla_paged_attention_full(
    q_eff, latent_cache_full, layer, page_table, kv_lens, positions,
    rank, sm_scale, world_size=1, mesh=None,
):
    """Layer-indexed MLA latent attention on the FULL [L, ...] cache.

    Pallas for decode (Q==1, lane-tiled latent width); sharded meshes
    split the query heads over tp and the batch over dp against the
    replicated latent pool (rows are a few hundred bytes; every head
    reads the same latent). XLA gather fallback otherwise.
    """
    from llmd_tpu.ops.mla_attention import mla_paged_attention_xla
    from llmd_tpu.ops.mla_decode import mla_decode_paged_attention_full

    L, num_pages, one, page, Dl = latent_cache_full.shape
    B, Q, H, _ = q_eff.shape
    plan = _plan_mla(Q, page, Dl, rank, world_size, mesh, B, H)
    if plan == "direct":
        return mla_decode_paged_attention_full(
            q_eff, latent_cache_full, layer, page_table, kv_lens,
            rank=rank, sm_scale=sm_scale, interpret=_interpret(),
        )
    if plan == "shard":
        interpret = _interpret()

        def local(q_eff, cache, layer, pt, kl):
            return mla_decode_paged_attention_full(
                q_eff, cache, layer, pt, kl, rank=rank,
                sm_scale=sm_scale, interpret=interpret,
            )

        return shard_map(
            local, mesh=mesh,
            in_specs=(
                P("dp", None, "tp", None),
                P(None, None, None, None, None),
                P(), P("dp", None), P("dp"),
            ),
            out_specs=P("dp", None, "tp", None),
            check_vma=False,
        )(q_eff, latent_cache_full, layer, page_table, kv_lens)
    sl = jax.lax.dynamic_index_in_dim(
        latent_cache_full, layer, 0, keepdims=False
    )
    return mla_paged_attention_xla(
        q_eff, sl, page_table, kv_lens, positions, rank=rank, sm_scale=sm_scale
    )


def paged_attention_full(
    q, kv_cache_full, layer, page_table, kv_lens, positions,
    sm_scale=None, world_size=1, mesh=None, window=None, sinks=None,
):
    """Layer-indexed attention on the FULL [L, ...] cache (see
    write_kv_pages_full). ``window`` is an optional i32 scalar sliding
    window (0/None = full attention; a traced per-layer value inside the
    layer scan). Int8 pools (tuple cache) dequantize per row at the
    read: the Pallas kernel DMAs half the HBM bytes and folds the scales
    around its matmuls; the XLA fallback dequantizes gathered pages."""
    kv_cache_full, kv_scales = _split_cache(kv_cache_full)
    L, num_pages, K, page, D2 = kv_cache_full.shape
    B, Q, H, D = q.shape
    plan = _plan(Q, page, D, D2, world_size, True, mesh, B, H, K)
    if window is not None:
        window = jnp.asarray(window, jnp.int32)
    if plan == "direct":
        return decode_paged_attention_full(
            q, kv_cache_full, layer, page_table, kv_lens, sm_scale=sm_scale,
            interpret=_interpret(), window=window, sinks=sinks,
            scales=kv_scales,
        )
    if plan == "shard":
        tp_k = _kv_head_axis(K, mesh.shape["tp"])
        interpret = _interpret()
        win = jnp.zeros((), jnp.int32) if window is None else window
        use_win = window is not None
        # Sinks are per-q-head: shard over tp with the q heads (zeros
        # placeholder keeps the shard_map arity fixed when absent).
        sk = jnp.zeros((H,), jnp.float32) if sinks is None else sinks
        use_sinks = sinks is not None
        if kv_scales is not None:
            # Scales shard with the pool's head axis.

            def local_q(q, cache, sc, layer, pt, kl, win, sk):
                return decode_paged_attention_full(
                    q, cache, layer, pt, kl, sm_scale=sm_scale,
                    interpret=interpret, window=win if use_win else None,
                    sinks=sk if use_sinks else None, scales=sc,
                )

            return shard_map(
                local_q, mesh=mesh,
                in_specs=(
                    P("dp", None, "tp", None), P(None, None, tp_k, None, None),
                    P(None, None, tp_k, None, None),
                    P(), P("dp", None), P("dp"), P(), P("tp"),
                ),
                out_specs=P("dp", None, "tp", None),
                check_vma=False,
            )(q, kv_cache_full, kv_scales, layer, page_table, kv_lens, win, sk)

        def local(q, cache, layer, pt, kl, win, sk):
            return decode_paged_attention_full(
                q, cache, layer, pt, kl, sm_scale=sm_scale,
                interpret=interpret, window=win if use_win else None,
                sinks=sk if use_sinks else None,
            )

        return shard_map(
            local, mesh=mesh,
            in_specs=(
                P("dp", None, "tp", None), P(None, None, tp_k, None, None),
                P(), P("dp", None), P("dp"), P(), P("tp"),
            ),
            out_specs=P("dp", None, "tp", None),
            check_vma=False,
        )(q, kv_cache_full, layer, page_table, kv_lens, win, sk)
    sl = jax.lax.dynamic_index_in_dim(kv_cache_full, layer, 0, keepdims=False)
    ssl = (
        None if kv_scales is None
        else jax.lax.dynamic_index_in_dim(kv_scales, layer, 0, keepdims=False)
    )
    return _attention_xla(
        q, sl, page_table, kv_lens, positions, sm_scale, window=window,
        sinks=sinks, scales=ssl,
    )
