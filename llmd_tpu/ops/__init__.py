"""TPU compute kernels (Pallas) and their XLA reference fallbacks.

``paged_attention`` / ``write_kv_pages`` (and their layer-indexed
``*_full`` variants for the scan-carry cache layout) dispatch at trace
time: the Pallas decode kernels on TPU-class backends for Q=1 with
tile-compatible geometry, the XLA fallbacks otherwise. Env
LLMD_PALLAS=off disables the kernels; =interpret forces interpret mode
(CPU parity testing).
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp

from llmd_tpu.ops.paged_attention import (
    paged_attention_xla,
    paged_attention_xla_blocked,
)
from llmd_tpu.ops.paged_attention import write_kv_pages as write_kv_pages_xla
from llmd_tpu.ops.kv_write import (
    write_kv_pages_decode,
    write_kv_pages_decode_full,
)
from llmd_tpu.ops.ragged_paged_attention import (
    decode_paged_attention,
    decode_paged_attention_full,
)

_TPU_PLATFORMS = {"tpu", "axon"}


def _mode() -> str:
    return os.environ.get("LLMD_PALLAS", "auto")


def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform in _TPU_PLATFORMS
    except Exception:
        return False


def _dispatch_kernel(Q, page, D, D2, world_size, need_lane_d: bool) -> bool:
    """Single source of truth for the kernel gates.

    Common constraints: decode shape (Q==1), sublane-tiled pages
    (page % 8), packed K/V halves (D2 == 2D), kernels enabled, and an
    unsharded mesh (no GSPMD rule for the kernels yet).
    ``need_lane_d``: the ATTENTION kernel matmuls over D, so D itself
    must be lane-tiled (D % 128); the WRITE kernel only moves [.., D2]
    slabs, so D2 % 128 suffices (head_dim-64 models keep the in-place
    write).
    """
    mode = _mode()
    if not (
        Q == 1
        and page % 8 == 0
        and D2 == 2 * D
        and D2 % 128 == 0
        and mode != "off"
        and world_size == 1
    ):
        return False
    if need_lane_d and D % 128 != 0:
        return False
    return mode == "interpret" or _on_tpu()


def _interpret() -> bool:
    return _mode() == "interpret"


# Above this context size the dense XLA attention's [B, Q, .., S] score
# tensor dominates memory (it grows as chunk x context); switch to the
# blocked online-softmax form.
_DENSE_XLA_MAX_S = 4096


def _attention_xla(q, kv_slice, page_table, kv_lens, positions, sm_scale):
    S = page_table.shape[1] * kv_slice.shape[-2]
    if q.shape[1] > 1 and S > _DENSE_XLA_MAX_S:
        return paged_attention_xla_blocked(
            q, kv_slice, page_table, kv_lens, positions, sm_scale
        )
    return paged_attention_xla(
        q, kv_slice, page_table, kv_lens, positions, sm_scale
    )


def _decode_write_prep(k, v, page_table, positions, page):
    """[B,1,K,D] k/v -> (kv_new [B,K,2D], phys [B], offset [B])."""
    B, _, K, D = k.shape
    kv_new = jnp.concatenate([k, v], axis=-1).reshape(B, K, 2 * D)
    pos = positions[:, 0]
    phys = jnp.take_along_axis(page_table, (pos // page)[:, None], axis=1)[:, 0]
    return kv_new, phys, pos % page


def write_kv_pages(kv_cache, k, v, page_table, positions, valid, world_size=1):
    """Scatter this step's K/V into the (single-layer) paged cache.

    Decode (Q==1) on TPU uses the Pallas in-place kernel — the XLA
    scatter copies the whole pool per step when the buffer is not
    donated; the kernel DMAs only the written slabs. Prefill and
    non-TPU paths keep the XLA scatter.
    """
    B, Q, K, D = k.shape
    num_pages, Kc, page, D2 = kv_cache.shape
    if _dispatch_kernel(Q, page, D, D2, world_size, need_lane_d=False):
        kv_new, phys, offset = _decode_write_prep(k, v, page_table, positions, page)
        return write_kv_pages_decode(
            kv_cache, kv_new, phys, offset, valid[:, 0], interpret=_interpret()
        )
    return write_kv_pages_xla(kv_cache, k, v, page_table, positions, valid)


def write_kv_pages_full(
    kv_cache_full, layer, k, v, page_table, positions, valid, world_size=1
):
    """Layer-indexed write on the FULL [L, ...] cache (scan-carry layout).

    The whole point: a lax.scan over layers that slices the cache pays a
    pool-sized copy per layer (slice + update, or xs->ys buffers); the
    Pallas variant indexes [layer, page] inside the kernel so only the
    written slabs move. Fallback (CPU / prefill / sharded): dynamic
    slice + XLA scatter + dynamic update — the carry-update pattern XLA
    optimizes in place where it can.
    """
    B, Q, K, D = k.shape
    L, num_pages, Kc, page, D2 = kv_cache_full.shape
    if _dispatch_kernel(Q, page, D, D2, world_size, need_lane_d=False):
        kv_new, phys, offset = _decode_write_prep(k, v, page_table, positions, page)
        return write_kv_pages_decode_full(
            kv_cache_full, kv_new, layer, phys, offset, valid[:, 0],
            interpret=_interpret(),
        )
    sl = jax.lax.dynamic_index_in_dim(kv_cache_full, layer, 0, keepdims=False)
    sl = write_kv_pages_xla(sl, k, v, page_table, positions, valid)
    return jax.lax.dynamic_update_index_in_dim(kv_cache_full, sl, layer, 0)


def paged_attention(
    q, kv_cache, page_table, kv_lens, positions, sm_scale=None, world_size=1
):
    """``world_size`` is the device count of the executing mesh. The Pallas
    kernel has no GSPMD partitioning rule yet, so it only dispatches for
    world_size == 1 (a sharded jit would otherwise all-gather the KV pool or
    fail to lower); the shard_map-wrapped kernel for tp>1 is future work."""
    num_pages, K, page, D2 = kv_cache.shape
    D = q.shape[-1]
    if _dispatch_kernel(q.shape[1], page, D, D2, world_size, need_lane_d=True):
        return decode_paged_attention(
            q, kv_cache, page_table, kv_lens, sm_scale=sm_scale,
            interpret=_interpret(),
        )
    return _attention_xla(q, kv_cache, page_table, kv_lens, positions, sm_scale)


def mla_paged_attention_full(
    q_eff, latent_cache_full, layer, page_table, kv_lens, positions,
    rank, sm_scale, world_size=1,
):
    """Layer-indexed MLA latent attention on the FULL [L, ...] cache.

    Pallas for decode (Q==1, lane-tiled latent width); XLA gather
    fallback otherwise (prefill, CPU, sharded). Returns [B, Q, H, rank].
    """
    from llmd_tpu.ops.mla_attention import mla_paged_attention_xla
    from llmd_tpu.ops.mla_decode import mla_decode_paged_attention_full

    L, num_pages, one, page, Dl = latent_cache_full.shape
    mode = _mode()
    kernel_ok = (
        q_eff.shape[1] == 1
        and page % 8 == 0
        and Dl % 128 == 0
        and rank % 128 == 0
        and mode != "off"
        and world_size == 1
    )
    if kernel_ok and (mode == "interpret" or _on_tpu()):
        return mla_decode_paged_attention_full(
            q_eff, latent_cache_full, layer, page_table, kv_lens,
            rank=rank, sm_scale=sm_scale, interpret=_interpret(),
        )
    sl = jax.lax.dynamic_index_in_dim(
        latent_cache_full, layer, 0, keepdims=False
    )
    return mla_paged_attention_xla(
        q_eff, sl, page_table, kv_lens, positions, rank=rank, sm_scale=sm_scale
    )


def paged_attention_full(
    q, kv_cache_full, layer, page_table, kv_lens, positions,
    sm_scale=None, world_size=1,
):
    """Layer-indexed attention on the FULL [L, ...] cache (see
    write_kv_pages_full)."""
    L, num_pages, K, page, D2 = kv_cache_full.shape
    D = q.shape[-1]
    if _dispatch_kernel(q.shape[1], page, D, D2, world_size, need_lane_d=True):
        return decode_paged_attention_full(
            q, kv_cache_full, layer, page_table, kv_lens, sm_scale=sm_scale,
            interpret=_interpret(),
        )
    sl = jax.lax.dynamic_index_in_dim(kv_cache_full, layer, 0, keepdims=False)
    return _attention_xla(q, sl, page_table, kv_lens, positions, sm_scale)
