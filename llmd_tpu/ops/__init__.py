"""TPU compute kernels (Pallas) and their XLA reference fallbacks."""
