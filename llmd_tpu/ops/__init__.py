"""TPU compute kernels (Pallas) and their XLA reference fallbacks.

``paged_attention`` dispatches at trace time: the Pallas decode kernel on
TPU-class backends for Q=1 with tile-compatible geometry, the XLA gather
fallback otherwise. Env LLMD_PALLAS=off disables the kernel; =interpret
forces interpret mode (CPU parity testing).
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp

from llmd_tpu.ops.paged_attention import paged_attention_xla
from llmd_tpu.ops.paged_attention import write_kv_pages as write_kv_pages_xla
from llmd_tpu.ops.kv_write import write_kv_pages_decode
from llmd_tpu.ops.ragged_paged_attention import decode_paged_attention

_TPU_PLATFORMS = {"tpu", "axon"}


def _mode() -> str:
    return os.environ.get("LLMD_PALLAS", "auto")


def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform in _TPU_PLATFORMS
    except Exception:
        return False


def write_kv_pages(kv_cache, k, v, page_table, positions, valid, world_size=1):
    """Scatter this step's K/V into the paged cache.

    Decode (Q==1) on TPU uses the Pallas in-place kernel — the XLA
    scatter copies the whole pool per step under lax.scan (~12ms/step
    for a 2048-page 3B pool); the kernel DMAs only the written slabs.
    Prefill and non-TPU paths keep the XLA scatter.
    """
    mode = _mode()
    B, Q, K, D = k.shape
    num_pages, Kc, page, D2 = kv_cache.shape
    kernel_ok = (
        Q == 1
        and D2 == 2 * D
        and D2 % 128 == 0
        and page % 8 == 0  # VMEM sublane tiling for the page-slab scratch
        and mode != "off"
        and world_size == 1
    )
    if kernel_ok and (mode == "interpret" or _on_tpu()):
        kv_new = jnp.concatenate([k, v], axis=-1).reshape(B, K, D2)
        pos = positions[:, 0]
        phys = jnp.take_along_axis(
            page_table, (pos // page)[:, None], axis=1
        )[:, 0]
        return write_kv_pages_decode(
            kv_cache, kv_new, phys, pos % page, valid[:, 0],
            interpret=(mode == "interpret"),
        )
    return write_kv_pages_xla(kv_cache, k, v, page_table, positions, valid)


def paged_attention(
    q, kv_cache, page_table, kv_lens, positions, sm_scale=None, world_size=1
):
    """``world_size`` is the device count of the executing mesh. The Pallas
    kernel has no GSPMD partitioning rule yet, so it only dispatches for
    world_size == 1 (a sharded jit would otherwise all-gather the KV pool or
    fail to lower); the shard_map-wrapped kernel for tp>1 is future work."""
    mode = _mode()
    num_pages, K, page, D2 = kv_cache.shape
    D = q.shape[-1]
    kernel_ok = (
        q.shape[1] == 1
        and D % 128 == 0
        and page % 8 == 0
        and D2 == 2 * D
        and mode != "off"
        and world_size == 1
    )
    if kernel_ok and mode == "interpret":
        return decode_paged_attention(
            q, kv_cache, page_table, kv_lens, sm_scale=sm_scale, interpret=True
        )
    if kernel_ok and _on_tpu():
        return decode_paged_attention(
            q, kv_cache, page_table, kv_lens, sm_scale=sm_scale
        )
    return paged_attention_xla(q, kv_cache, page_table, kv_lens, positions, sm_scale)
