"""Latent paged attention for MLA (DeepSeek V2/V3/R1-style).

With weight absorption, MLA decode attention runs entirely in the
compressed latent space: queries are projected to
q_eff = [q_nope @ W_uk, q_pe] (per head), keys ARE the cached latents
[c_kv, k_pe], and values are the first kv_lora_rank components of the
same latent. One cache row serves every head — MQA with a wide head —
so the pool stores latent_dim bytes/token instead of
2 * num_kv_heads * head_dim (e.g. DeepSeek-V3: 576 vs 32768 per token).

Layout matches the engine pool: latent_cache [num_pages, 1, page, Dl]
(Dl = latent width padded to lane tiling; padding columns are zero and
drop out of both the dot products and the value slice).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def mla_paged_attention_xla(
    q_eff: jax.Array,       # [B, Q, H, Dl] (zero-padded past latent_dim)
    latent_cache: jax.Array,  # [num_pages, 1, page, Dl]
    page_table: jax.Array,  # [B, max_pages]
    kv_lens: jax.Array,     # [B]
    positions: jax.Array,   # [B, Q]
    rank: int,              # kv_lora_rank: value = latent[..., :rank]
    sm_scale: float,
) -> jax.Array:
    """Reference implementation: gather the context latents, masked
    softmax, value contraction over the rank slice. Returns
    [B, Q, H, rank]."""
    B, Q, H, Dl = q_eff.shape
    num_pages, one, page, Dlc = latent_cache.shape
    assert Dl == Dlc, (Dl, Dlc)
    S = page_table.shape[1] * page

    lat = latent_cache[page_table]  # [B, max_pages, 1, page, Dl]
    lat = lat.reshape(B, S, Dl)
    scores = (
        jnp.einsum("bqhd,bsd->bhqs", q_eff, lat, preferred_element_type=jnp.float32)
        * sm_scale
    )
    key_pos = jnp.arange(S)[None, None, :]
    causal = key_pos <= positions[:, :, None]          # [B, Q, S]
    in_ctx = key_pos < kv_lens[:, None, None]          # [B, 1, S]
    mask = (causal & in_ctx)[:, None, :, :]            # [B, 1, Q, S]
    scores = jnp.where(mask, scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum(
        "bhqs,bsr->bqhr",
        probs.astype(lat.dtype),
        lat[..., :rank],
        preferred_element_type=jnp.float32,
    )
    return out.astype(q_eff.dtype)
