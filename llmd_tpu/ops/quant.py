"""INT8 weight quantization: per-channel scales, native int8 MXU matmuls.

The reference serves its flagship DeepSeek-R1 path FP8 end-to-end (DeepGEMM
`--moe-backend deep_gemm`, reference docker/Dockerfile.cuda:69-70; wide-ep
decode.yaml:128) — quantized weights are how it reaches its headline
tok/s/chip. TPU v5e/v6e have no FP8 MXU mode; the native low-precision
path is INT8 (2x bf16 MXU throughput, half the HBM bytes — decode is
weight-streaming bound, so bytes are the whole game).

Scheme (standard W8A8 dynamic quantization):

- weights: symmetric per-output-channel int8. ``w_q[..., i, o] =
  round(w / s_w[o])`` with ``s_w = max|w|/127`` reduced over the
  contraction axis. Scales live next to the weight in the param tree as
  ``<name>_scale`` (f32), sharded like the weight's output axis.
- activations: symmetric per-token (per-row) int8, quantized on the fly
  (amax over the feature axis — a cheap VPU reduction XLA fuses).
- matmul: ``int8 x int8 -> int32`` via ``lax.dot_general`` — one native
  MXU pass — then one fused rescale ``int32 * s_a * s_w -> bf16``.

Under tensor parallelism this is exact-by-construction: a row-parallel
contraction computes the GLOBAL amax first (psum-max over the sharded
feature axis, [*, 1] — negligible traffic), so every shard quantizes
against the same scale and the int32 partials add correctly.

Why there is NO hand-written Pallas W8A8 GEMM here (measured, r4): the
hypothesis that XLA leaves the quantize/rescale epilogues unfused was
tested with the LLMD_QDOT=w8a16 lever — bf16 activations x int8 weights
cast inside the dot (no activation-quant epilogue at all) measured
3,739 tok/s e2e vs 4,227 for this W8A8 path on the bench workload
(llama-3.2-3b-class, B=128). The full quantized path is 13% FASTER than
the epilogue-free alternative, i.e. XLA already fuses the epilogues and
exploits the int8 MXU mode; a custom GEMM kernel has no headroom to
reclaim from this seam. (The DeepGEMM gap the reference fills is a CUDA
codegen problem TPU/XLA does not share.)
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

_EPS = 1e-30


def quantize_weight(w: jax.Array, contract_axis: int = -2) -> tuple[jax.Array, jax.Array]:
    """Symmetric per-output-channel int8 quantization.

    ``w`` is ``[..., I, O]`` with the contraction (input) axis at
    ``contract_axis``; returns ``(q int8 same-shape, scale f32)`` where
    ``scale`` is ``w.shape`` minus the contraction axis.
    """
    wf = w.astype(jnp.float32)
    amax = jnp.max(jnp.abs(wf), axis=contract_axis)
    scale = jnp.maximum(amax, _EPS) / 127.0
    q = jnp.round(wf / jnp.expand_dims(scale, contract_axis))
    return jnp.clip(q, -127, 127).astype(jnp.int8), scale


# Param-tree leaves that quantize (all [..., I, O] matmul weights on the
# serving hot path). Excluded on purpose: embed (gather table), norms,
# router + bias (tiny, routing-accuracy sensitive), LoRA factors (tiny,
# per-adapter), and MLA's wkv_b (re-sliced into absorbed W_uk/W_uv
# einsums — per-channel scales don't survive the reshape).
QUANT_NAMES = frozenset({
    "wq", "wk", "wv", "wo",
    "w_gate", "w_up", "w_down",
    "ws_gate", "ws_up", "ws_down",
    "we_gate", "we_up", "we_down",
    "wq_a", "wq_b", "wkv_a",
    "lm_head",
})


def quantize_param_tree(params: dict) -> dict:
    """Quantize every QUANT_NAMES leaf in a model param tree, adding a
    sibling ``<name>_scale`` f32 leaf (the layout pdot/shard_params read)."""
    out: dict = {}
    for k, v in params.items():
        if isinstance(v, dict):
            out[k] = quantize_param_tree(v)
        elif k in QUANT_NAMES:
            q, s = quantize_weight(v)
            out[k] = q
            out[k + "_scale"] = s
        else:
            out[k] = v
    return out


def quantize_param_tree_host(params: dict) -> dict:
    """Numpy variant of quantize_param_tree for checkpoint loading: the
    bf16 tree never touches a device, so models that only fit when
    tp-sharded (the main audience for int8) quantize on host and then
    shard the int8 leaves directly."""
    import numpy as np

    out: dict = {}
    for k, v in params.items():
        if isinstance(v, dict):
            out[k] = quantize_param_tree_host(v)
        elif k in QUANT_NAMES:
            wf = np.asarray(v, dtype=np.float32)
            amax = np.max(np.abs(wf), axis=-2)
            scale = np.maximum(amax, _EPS) / 127.0
            q = np.clip(
                np.round(wf / np.expand_dims(scale, -2)), -127, 127
            ).astype(np.int8)
            out[k] = q
            out[k + "_scale"] = scale.astype(np.float32)
        else:
            out[k] = v
    return out


def quantize_activations(x: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Per-row (last-axis) dynamic int8: returns (x_q int8, scale [..., 1] f32)."""
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1, keepdims=True)
    scale = jnp.maximum(amax, _EPS) / 127.0
    xq = jnp.clip(jnp.round(xf / scale), -127, 127).astype(jnp.int8)
    return xq, scale


def qdot(x: jax.Array, w_q: jax.Array, w_scale: jax.Array) -> jax.Array:
    """``x @ dequant(w_q)`` without ever materializing the dequantized
    weight: dynamic-quantize ``x`` per row, int8 MXU matmul, fused rescale.

    x: [..., I] (any leading dims); w_q: int8 [I, O]; w_scale: f32 [O].
    Returns [..., O] in x.dtype (f32 accumulation throughout).

    LLMD_QDOT=w8a16 switches to bf16 activations x int8 weights cast in
    the dot (an A/B lever: isolates the activation-quantize epilogue
    cost from the weight-byte savings; weights still stream as int8 when
    XLA fuses the convert into the operand read).
    """
    import os

    if os.environ.get("LLMD_QDOT") == "w8a16":
        acc = jax.lax.dot_general(
            x.astype(jnp.bfloat16), w_q.astype(jnp.bfloat16),
            (((x.ndim - 1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return (acc * w_scale).astype(x.dtype)
    xq, a_scale = quantize_activations(x)
    acc = jax.lax.dot_general(
        xq, w_q,
        (((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )
    return (acc.astype(jnp.float32) * a_scale * w_scale).astype(x.dtype)


def dequantize(w_q: jax.Array, scale: jax.Array, contract_axis: int = -2,
               dtype=jnp.bfloat16) -> jax.Array:
    """Materialize the full-precision weight (oracle paths / tests only —
    the serving matmuls go through qdot and never pay these bytes)."""
    return (
        w_q.astype(jnp.float32) * jnp.expand_dims(scale, contract_axis)
    ).astype(dtype)


def grouped_matmul_q(
    x: jax.Array,            # [T, K_dim] rows sorted by group
    w_q: jax.Array,          # int8 [G, K_dim, N]
    w_scale: jax.Array,      # f32 [G, N]
    group_sizes: jax.Array,  # [G] i32, sums to T
) -> jax.Array:              # [T, N] in x.dtype
    """Quantized grouped GEMM (the DeepGEMM-FP8 role on TPU): each group's
    int8 expert weight multiplies only its routed rows via ragged_dot,
    rescaled per row by (activation scale x its group's channel scales)."""
    T = x.shape[0]
    G = w_q.shape[0]
    xq, a_scale = quantize_activations(x)
    acc = jax.lax.ragged_dot(
        xq, w_q, group_sizes.astype(jnp.int32),
        preferred_element_type=jnp.int32,
    )
    gid = jnp.repeat(
        jnp.arange(G, dtype=jnp.int32), group_sizes, total_repeat_length=T
    )
    out = acc.astype(jnp.float32) * a_scale * w_scale[gid]
    return out.astype(x.dtype)
