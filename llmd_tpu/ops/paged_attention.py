"""Paged attention over a block (page) table.

TPU equivalent of the reference's FlashInfer / ragged-paged-attention path
(SURVEY.md N8: reference ships FlashInfer CUDA kernels, and the TPU images
use Pallas ragged paged attention). Two implementations behind one
interface:

- ``paged_attention_xla``: pure-XLA reference implementation (gather pages,
  masked softmax). Correct everywhere (CPU test mesh included); used as the
  numerical oracle for the Pallas kernel and as the fallback path.
- ``paged_attention`` in ``llmd_tpu.ops.ragged_paged_attention``:
  the Pallas TPU kernel (flash-style online softmax over pages).

Layout conventions (TPU-first):
  kv_cache (one layer): [num_pages, num_kv_heads, page_size, 2*head_dim]
      (K in [..., :head_dim], V in [..., head_dim:]; head-major within a
      page so one (page, head) slab is a contiguous DMA)
  q:          [B, Q, num_q_heads, head_dim]
  page_table: [B, max_pages] int32
  kv_lens:    [B] int32, total valid kv tokens per seq AFTER this step's
              writes (so causality is enforced via per-token positions).
  positions:  [B, Q] int32 absolute position of each query token.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def write_kv_pages(
    kv_cache: jax.Array,  # [num_pages, K, page, 2D]
    k: jax.Array,  # [B, Q, K, D]
    v: jax.Array,  # [B, Q, K, D]
    page_table: jax.Array,  # [B, max_pages]
    positions: jax.Array,  # [B, Q]
    valid: jax.Array,  # [B, Q] bool
) -> jax.Array:
    """Scatter this step's K/V into their cache slots.

    Token (b, i) lands at [page_table[b, pos // page], :, pos % page, :].
    Invalid (padding) tokens scatter out-of-bounds and are dropped.
    """
    num_pages, K, page, D2 = kv_cache.shape
    kv = jnp.concatenate([k, v], axis=-1)  # [B, Q, K, 2D]
    page_idx = positions // page
    offset = positions % page
    phys = jnp.take_along_axis(page_table, page_idx, axis=1)  # [B, Q]
    phys = jnp.where(valid, phys, num_pages)  # OOB => dropped
    T = phys.size
    kv_flat = kv.reshape(T, K, D2).astype(kv_cache.dtype)
    return kv_cache.at[
        phys.reshape(T, 1), jnp.arange(K)[None, :], offset.reshape(T, 1), :
    ].set(kv_flat, mode="drop")


def scatter_kv_scales(
    scales: jax.Array,  # [num_pages, K, page, 2] f32 (one layer)
    srow: jax.Array,  # [B, Q, K, 2] per-row K/V-half scales
    page_table: jax.Array,  # [B, max_pages]
    positions: jax.Array,  # [B, Q]
    valid: jax.Array,  # [B, Q] bool
) -> jax.Array:
    """Scatter this step's per-row scales into one layer's scale pool
    (the tiny sibling of write_kv_pages; ~1/32 of the data bytes, so the
    plain XLA scatter is fine even on the Pallas write path). The
    half-pair is the trailing contiguous dim — one 8-byte write per
    (token, head)."""
    num_pages, K, page, two = scales.shape
    B, Q = positions.shape
    page_idx = positions // page
    offset = positions % page
    phys = jnp.take_along_axis(page_table, page_idx, axis=1)
    phys = jnp.where(valid, phys, num_pages)  # OOB => dropped
    T = phys.size
    if Q > 1:
        # Prefill: K stays a SLICE, not an enumerated index — T scatter
        # updates with a [K, 2] window each instead of T*K eight-byte
        # updates. Scatter cost is per-update; the enumerated form was
        # measured at ~1/5 of the whole int8 prefill step (B=128,
        # Q=384: 3.68s -> 3.16s, vs 3.07s with the write deleted).
        return scales.at[
            phys.reshape(T), :, offset.reshape(T), :
        ].set(srow.reshape(T, K, 2).astype(scales.dtype), mode="drop")
    # Decode (T = B rows): gather each row's page slab, update its
    # column densely, write back WHOLE [K, page, 2] slabs — contiguous
    # 1KB updates instead of T*K strided 8-byte ones. Safe: a writable
    # page belongs to exactly one sequence (prefix-shared pages are
    # read-only), so slab writes cannot race. (Measured per 64-step
    # window: enumerated scatter 5.5ms/step; [K,2] strided windows
    # worse; this form ~zero.)
    phys_f = phys.reshape(T)
    slabs = scales[jnp.minimum(phys_f, num_pages - 1)]  # [T, K, page, 2]
    col = (
        jax.lax.broadcasted_iota(jnp.int32, (T, 1, page, 1), 2)
        == offset.reshape(T, 1, 1, 1)
    )
    slabs = jnp.where(
        col, srow.reshape(T, K, 1, 2).astype(scales.dtype), slabs
    )
    return scales.at[phys_f].set(slabs, mode="drop")


def scatter_kv_scales_flat(
    scales: jax.Array,  # [num_pages, K, page, 2] f32 (one layer)
    srow: jax.Array,  # [T, 1, K, 2] per-token K/V-half scales
    page_table: jax.Array,  # [R, max_pages] COMPACT per-row table
    rows: jax.Array,  # [T] i32 token -> row
    positions: jax.Array,  # [T, 1]
    valid: jax.Array,  # [T, 1] bool
) -> jax.Array:
    """Flattened-token scale scatter: one enumerated (page, slot) write
    per live token. The decode path's dense-slab form is WRONG here —
    it assumes one token per page, and a gathered-slab update with
    duplicate page indices drops all but one of a prefill chunk's
    same-page tokens — while the enumerated targets are distinct by
    construction (distinct (page, slot) per live token)."""
    num_pages, K, page, two = scales.shape
    T = rows.shape[0]
    pos = positions[:, 0]
    phys = page_table[rows, pos // page]
    phys = jnp.where(valid[:, 0], phys, num_pages)  # OOB => dropped
    return scales.at[phys, :, pos % page, :].set(
        srow.reshape(T, K, 2).astype(scales.dtype), mode="drop"
    )


def _dequant_gathered(kv, scales, page_idx, D, dtype=jnp.bfloat16):
    """Gathered int8 pages [B, n, K, page, 2D] + one layer's scale pool
    [P, K, page, 2] with the same page indices [B, n] -> k, v
    [B, S, K, D] in ``dtype`` (S = n * page).

    ``dtype`` defaults to bf16, NOT f32: these feed the attention
    matmuls, and f32 operands push them onto the MXU's 1/8-rate f32
    path with 2x the VMEM bytes — measured as the entire int8-pool
    prefill regression vs bf16 pools (the decode kernel was within 5%
    all along). int8 values are exact in bf16; only the scale multiply
    rounds, bounded by the quantization error already accepted."""
    B, n, K, page, D2 = kv.shape
    S = n * page
    kv = kv.transpose(0, 1, 3, 2, 4).reshape(B, S, K, D2).astype(jnp.float32)
    g = scales[page_idx]  # [B, n, K, page, 2]
    s = g.transpose(0, 1, 3, 2, 4).reshape(B, S, K, 2).astype(jnp.float32)
    k = (kv[..., :D] * s[..., 0:1]).astype(dtype)
    v = (kv[..., D:] * s[..., 1:2]).astype(dtype)
    return k, v


def _window_mask(key_pos, positions, window):
    """Sliding-window lower bound: key_pos > q_pos - window (no-op when
    window <= 0). ``window`` may be a traced i32 scalar (per-layer value
    inside the layer scan)."""
    if window is None:
        return jnp.bool_(True)
    window = jnp.asarray(window, jnp.int32)
    return jnp.where(
        window > 0, key_pos > positions[:, :, None] - window, True
    )


def paged_attention_xla_blocked(
    q: jax.Array,  # [B, Q, H, D]
    kv_cache: jax.Array,  # [num_pages, K, page, 2D]
    page_table: jax.Array,  # [B, max_pages]
    kv_lens: jax.Array,  # [B]
    positions: jax.Array,  # [B, Q]
    sm_scale: float | None = None,
    block_pages: int = 32,
    window=None,  # i32 scalar (0/None = full attention)
    sinks=None,   # [H] per-q-head virtual-key logits (gpt-oss)
    scales=None,  # [num_pages, K, page, 2] f32: int8-pool row scales
) -> jax.Array:
    """Flash-style blocked paged attention in plain XLA.

    The dense path materializes [B, Q, K, G, S] scores — at 16k context
    with an 8k prefill chunk that is a ~100GB tensor. This version scans
    page blocks with an online-softmax carry (m, l, acc), so peak memory
    is O(B * Q * block) regardless of context length. Used for long
    contexts; the dense path remains the small-shape oracle.
    """
    B, Q, H, D = q.shape
    num_pages, K, page, D2 = kv_cache.shape
    max_pages = page_table.shape[1]
    if sm_scale is None:
        sm_scale = D**-0.5
    if max_pages % block_pages:
        pad = block_pages - max_pages % block_pages
        # repeat last page id: masked out by kv_lens anyway
        page_table = jnp.concatenate(
            [page_table, jnp.repeat(page_table[:, -1:], pad, axis=1)], axis=1
        )
        max_pages += pad
    n_blocks = max_pages // block_pages
    Sb = block_pages * page
    G = H // K
    qg = q.reshape(B, Q, K, G, D)

    def body(carry, blk):
        m, l, acc = carry
        pt_blk = jax.lax.dynamic_slice_in_dim(
            page_table, blk * block_pages, block_pages, axis=1
        )  # [B, bp]
        kv = kv_cache[pt_blk]  # [B, bp, K, page, 2D]
        if scales is not None:
            k, v = _dequant_gathered(kv, scales, pt_blk, D, q.dtype)
        else:
            kv = kv.transpose(0, 1, 3, 2, 4).reshape(B, Sb, K, D2)
            k = kv[..., :D]
            v = kv[..., D:]
        s = (
            jnp.einsum(
                "bqkgd,bskd->bqkgs", qg, k, preferred_element_type=jnp.float32
            )
            * sm_scale
        )  # [B, Q, K, G, Sb]
        key_pos = blk * Sb + jnp.arange(Sb)[None, None, :]
        causal = key_pos <= positions[:, :, None]
        in_ctx = key_pos < kv_lens[:, None, None]
        mask = (causal & in_ctx & _window_mask(key_pos, positions, window))[
            :, :, None, None, :
        ]
        s = jnp.where(mask, s, -1e30)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))  # [B, Q, K, G]
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[..., None])
        # fully-masked rows: m_new stays -1e30, p rows ~e^0=1 — zero them
        p = jnp.where(mask, p, 0.0)
        l_new = l * alpha + jnp.sum(p, axis=-1)
        pv = jnp.einsum(
            "bqkgs,bskd->bqkgd", p.astype(v.dtype), v,
            preferred_element_type=jnp.float32,
        )
        acc_new = acc * alpha[..., None] + pv
        return (m_new, l_new, acc_new), None

    m0 = jnp.full((B, Q, K, G), -1e30, jnp.float32)
    l0 = jnp.zeros((B, Q, K, G), jnp.float32)
    acc0 = jnp.zeros((B, Q, K, G, D), jnp.float32)
    (m, l, acc), _ = jax.lax.scan(
        body, (m0, l0, acc0), jnp.arange(n_blocks, dtype=jnp.int32)
    )
    if sinks is not None:
        # The sink is one more (value-less) key: fold exp(sink) into the
        # softmax denominator, rescaled into the online-softmax's running
        # max frame (exactly HF's concat-then-drop formulation).
        sk = sinks.astype(jnp.float32).reshape(K, G)[None, None, :, :]
        m2 = jnp.maximum(m, sk)
        l = l * jnp.exp(m - m2) + jnp.exp(sk - m2)
        acc = acc * jnp.exp(m - m2)[..., None]
    l = jnp.where(l == 0.0, 1.0, l)
    out = acc / l[..., None]
    return out.reshape(B, Q, H, D).astype(q.dtype)


def paged_attention_xla(
    q: jax.Array,  # [B, Q, H, D]
    kv_cache: jax.Array,  # [num_pages, K, page, 2D]
    page_table: jax.Array,  # [B, max_pages]
    kv_lens: jax.Array,  # [B]
    positions: jax.Array,  # [B, Q]
    sm_scale: float | None = None,
    window=None,  # i32 scalar (0/None = full attention)
    sinks=None,   # [H] per-q-head virtual-key logits (gpt-oss)
    scales=None,  # [num_pages, K, page, 2] f32: int8-pool row scales
) -> jax.Array:
    """Reference paged attention: gather the whole context, masked softmax."""
    B, Q, H, D = q.shape
    num_pages, K, page, D2 = kv_cache.shape
    max_pages = page_table.shape[1]
    S = max_pages * page
    if sm_scale is None:
        sm_scale = D ** -0.5

    kv = kv_cache[page_table]  # [B, max_pages, K, page, 2D]
    if scales is not None:
        k, v = _dequant_gathered(kv, scales, page_table, D, q.dtype)
    else:
        kv = kv.transpose(0, 1, 3, 2, 4).reshape(B, S, K, D2)
        k = kv[..., :D]
        v = kv[..., D:]

    group = H // K
    qg = q.reshape(B, Q, K, group, D)
    # Accumulate scores in f32 on the MXU while streaming bf16 operands.
    scores = (
        jnp.einsum("bqkgd,bskd->bqkgs", qg, k, preferred_element_type=jnp.float32)
        * sm_scale
    )

    key_pos = jnp.arange(S)[None, None, :]  # [1,1,S]
    causal = key_pos <= positions[:, :, None]  # [B,Q,S]
    in_ctx = key_pos < kv_lens[:, None, None]  # [B,1,S]
    mask = (causal & in_ctx & _window_mask(key_pos, positions, window))[
        :, :, None, None, :
    ]  # [B,Q,1,1,S]
    scores = jnp.where(mask, scores, -1e30)
    if sinks is not None:
        # gpt-oss attention sinks: append the per-head sink logit as an
        # extra (always-unmasked) column, softmax, then drop it — the
        # sink only absorbs probability mass (HF eager_attention_forward).
        sk = jnp.broadcast_to(
            sinks.astype(scores.dtype).reshape(K, group)[None, None, :, :, None],
            (B, Q, K, group, 1),
        )
        probs = jax.nn.softmax(
            jnp.concatenate([scores, sk], axis=-1), axis=-1
        )[..., :-1]
    else:
        probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum(
        "bqkgs,bskd->bqkgd",
        probs.astype(v.dtype),
        v,
        preferred_element_type=jnp.float32,
    )
    return out.reshape(B, Q, H, D).astype(q.dtype)
