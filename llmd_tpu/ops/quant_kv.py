"""INT8 KV-cache pool: quantization helpers and layout contract.

The reference's flagship path runs a quantized cache end-to-end (FP8 KV in
the deployed vLLM engine; FP8 DeepGEMM MoE — docker/Dockerfile.cuda:69-70).
TPU-native the pool is symmetric int8 with per-(token, head) row scales,
kept as a 2-tuple pytree alongside the data:

One layout everywhere:

  POOL/BUNDLE/WIRE scales: [(L,) num_pages, K, page, 2] — co-indexed
         with the data pool's page axis, head axis TP-sharded like the
         data's; page-in-sublane, K/V-half-in-lane. This is (a) the
         shape quantize_kv_rows emits natively, (b) a contiguous 8-byte
         pair per (token, head) for the step's scale scatter, and (c)
         DMA-able into the decode kernel with the exact access pattern
         of the data pages (sublane offset j*page), which is what lets
         the kernel fetch scales per page instead of XLA pre-gathering
         the whole context's scales each layer. Pool stores f32; the
         wire carries the same values as f16 (see below).
         (Historical: a [.., K, 2, page] pool needed a Mosaic-
         unsupported in-kernel relayout, forcing that pre-gather —
         which cost more than int8's halved KV bytes saved, BENCH_r04;
         and a page-axis-last "plane" layout was measured worse on the
         prefill scatter side: 2839 vs 3100 tok/s short-ctx.)

Scales are STORED f32 (Mosaic has no f16 type on TPU, and f32 scales are
only 8B next to each 256B int8 row) but their VALUES live on the f16
grid — quantization divides by the f16-rounded scale — so converting to
the f16 transfer-wire form is lossless.

Separate K/V half scales for the same reason as the transfer encoding
(kvtransfer/connector.py): RoPE'd keys run ~an order of magnitude hotter
than values; one shared amax would crush the value half to a few int8
levels. Scales are rounded through f16 BEFORE quantizing so dequant uses
the exact value quant divided by (no systematic rounding bias), which
also makes dequantize -> requantize a lossless round trip (same grid).

The fused weight-side W8A8 path lives in ops/quant.py; this module is the
KV (activation-cache) side.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

# Pool storage dtype (f32: Mosaic-compatible); values sit on the f16
# grid so the f16 wire encoding is a lossless cast.
KV_SCALES_DTYPE = jnp.float32


def quantize_kv_rows(k: jax.Array, v: jax.Array):
    """Per-row symmetric int8 for this step's K/V slabs.

    k, v: [..., D] float -> (k8 i8, v8 i8, scales [..., 2] f32 on the
    f16 grid).
    """

    def one(x):
        xf = x.astype(jnp.float32)
        amax = jnp.max(jnp.abs(xf), axis=-1, keepdims=True)
        scale = jnp.maximum(amax, 1e-30) / 127.0
        # Quantize against the f16-ROUNDED scale — the exact value any
        # f16 wire consumer will dequantize with. One reciprocal per ROW
        # then a multiply across D: a per-element divide was ~3% of the
        # whole int8 prefill step. The reciprocal's rounding only
        # perturbs which grid point a value lands on (<=0.5 ulp);
        # dequant still uses the exact f16 scale.
        scale = scale.astype(jnp.float16).astype(jnp.float32)
        q = jnp.clip(
            jnp.round(xf * jnp.reciprocal(scale)), -127, 127
        ).astype(jnp.int8)
        return q, scale[..., 0].astype(KV_SCALES_DTYPE)

    k8, ks = one(k)
    v8, vs = one(v)
    return k8, v8, jnp.stack([ks, vs], axis=-1)


def quantize_pages(pages: jax.Array):
    """Canonical float pages [..., K, page, 2D] -> (data i8 same shape,
    scales [..., K, page, 2] f32) in the shared layout."""
    *lead, K, page, D2 = pages.shape
    D = D2 // 2
    k8, v8, srow = quantize_kv_rows(pages[..., :D], pages[..., D:])
    data = jnp.concatenate([k8, v8], axis=-1)
    return data, srow  # srow is already [..., K, page, 2]


def dequantize_pages(data: jax.Array, scales: jax.Array, dtype) -> jax.Array:
    """(data, scales [..., K, page, 2]) -> float pages [..., K, page, 2D]."""
    D2 = data.shape[-1]
    D = D2 // 2
    srow = scales.astype(jnp.float32)  # [..., K, page, 2]
    k = data[..., :D].astype(jnp.float32) * srow[..., 0:1]
    v = data[..., D:].astype(jnp.float32) * srow[..., 1:2]
    return jnp.concatenate([k, v], axis=-1).astype(dtype)


def pool_scales_to_wire(scales: jax.Array) -> jax.Array:
    """Pool and wire share one layout ([..., K, page, 2]); the wire
    narrows to f16 at the call site. Kept as a named seam so a future
    layout split only touches this pair."""
    return scales


def wire_scales_to_pool(scales) -> jax.Array:
    """Wire -> pool: identity layout (values widen f16 -> f32 at the
    call site)."""
    return jnp.asarray(scales)
