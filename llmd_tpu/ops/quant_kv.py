"""INT8 KV-cache pool: quantization helpers and layout contract.

The reference's flagship path runs a quantized cache end-to-end (FP8 KV in
the deployed vLLM engine; FP8 DeepGEMM MoE — docker/Dockerfile.cuda:69-70).
TPU-native the pool is symmetric int8 with per-(token, head) row scales,
kept as a 2-tuple pytree alongside the data:

Two layouts, one value set:

  POOL/BUNDLE scales: [(L,) num_pages, K, 2, page] f32 — co-indexed
         with the data pool's page axis (axis 1), head axis TP-sharded
         like the data's. (A page-axis-last "plane" layout was tried
         for cheaper decode-time gathers and measured WORSE e2e — its
         strided per-token scatter dominates prefill: 2839 vs 3100
         tok/s short-ctx and 1039 vs 1524 at ISL=384.)
  WIRE   (transfer q8 encoding, kvtransfer/connector.py):
         scales [L, n, K, page, 2] f16

Scales are STORED f32 (Mosaic has no f16 type on TPU, and f32 scales are
only 8B next to each 256B int8 row) but their VALUES live on the f16
grid — quantization divides by the f16-rounded scale — so converting to
the f16 transfer-wire form is lossless.

Separate K/V half scales for the same reason as the transfer encoding
(kvtransfer/connector.py): RoPE'd keys run ~an order of magnitude hotter
than values; one shared amax would crush the value half to a few int8
levels. Scales are rounded through f16 BEFORE quantizing so dequant uses
the exact value quant divided by (no systematic rounding bias), which
also makes dequantize -> requantize a lossless round trip (same grid).

The fused weight-side W8A8 path lives in ops/quant.py; this module is the
KV (activation-cache) side.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

# Pool storage dtype (f32: Mosaic-compatible); values sit on the f16
# grid so the f16 wire encoding is a lossless cast.
KV_SCALES_DTYPE = jnp.float32


def quantize_kv_rows(k: jax.Array, v: jax.Array):
    """Per-row symmetric int8 for this step's K/V slabs.

    k, v: [..., D] float -> (k8 i8, v8 i8, scales [..., 2] f32 on the
    f16 grid).
    """

    def one(x):
        xf = x.astype(jnp.float32)
        amax = jnp.max(jnp.abs(xf), axis=-1, keepdims=True)
        scale = jnp.maximum(amax, 1e-30) / 127.0
        # Quantize against the f16-ROUNDED scale — the exact value any
        # f16 wire consumer will dequantize with.
        scale = scale.astype(jnp.float16).astype(jnp.float32)
        q = jnp.clip(jnp.round(xf / scale), -127, 127).astype(jnp.int8)
        return q, scale[..., 0].astype(KV_SCALES_DTYPE)

    k8, ks = one(k)
    v8, vs = one(v)
    return k8, v8, jnp.stack([ks, vs], axis=-1)


def quantize_pages(pages: jax.Array):
    """Canonical float pages [..., K, page, 2D] -> (data i8 same shape,
    scales [..., K, 2, page] f32) in the BUNDLE layout."""
    *lead, K, page, D2 = pages.shape
    D = D2 // 2
    k8, v8, srow = quantize_kv_rows(pages[..., :D], pages[..., D:])
    data = jnp.concatenate([k8, v8], axis=-1)
    # srow [..., K, page, 2] -> bundle layout [..., K, 2, page]
    scales = jnp.swapaxes(srow, -1, -2)
    return data, scales


def dequantize_pages(data: jax.Array, scales: jax.Array, dtype) -> jax.Array:
    """Bundle-layout (data, scales) -> float pages [..., K, page, 2D]."""
    D2 = data.shape[-1]
    D = D2 // 2
    srow = jnp.swapaxes(scales, -1, -2).astype(jnp.float32)  # [..., page, 2]
    k = data[..., :D].astype(jnp.float32) * srow[..., 0:1]
    v = data[..., D:].astype(jnp.float32) * srow[..., 1:2]
    return jnp.concatenate([k, v], axis=-1).astype(dtype)


def pool_scales_to_wire(scales: jax.Array) -> jax.Array:
    """Pool layout [..., K, 2, page] -> transfer-wire layout
    [..., K, page, 2] (kvtransfer bundle scales order)."""
    return jnp.swapaxes(scales, -1, -2)


def wire_scales_to_pool(scales) -> jax.Array:
    """Transfer-wire layout [..., K, page, 2] -> pool layout."""
    return jnp.swapaxes(jnp.asarray(scales), -1, -2)
