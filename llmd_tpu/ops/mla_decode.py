"""Pallas TPU latent paged attention (MLA decode path).

The MQA-shaped sibling of ragged_paged_attention._decode_kernel: one
latent "head" of width Dl serves every query head, scores contract over
the full latent row, values are its first `rank` components. Streams
only the LIVE context pages HBM->VMEM (double-buffered DMAs) with a
flash-style online-softmax accumulator — the XLA fallback gathers the
whole padded context per layer per step, which is exactly what makes
naive MLA decode slow at 160k context.

Layer-indexed like the other decode kernels: the FULL [L, pages, 1,
page, Dl] cache stays in HBM and the kernel reads cache[layer], so the
scan over layers never slices the pool.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from llmd_tpu.compat import pallas_tpu_compiler_params

NEG_INF = -2.0**30


def _mla_decode_kernel(
    # scalar prefetch
    layer_ref,       # [1] i32
    page_table_ref,  # [B, max_pages] i32
    kv_lens_ref,     # [B] i32
    # blocks
    q_ref,       # [1, H, Dl] VMEM
    lat_hbm_ref,  # [(L,) num_pages, 1, page, Dl] HBM (unblocked)
    out_ref,     # [1, H, rank] VMEM
    # scratch
    m_ref,    # [H, 128] f32
    l_ref,    # [H, 128] f32
    acc_ref,  # [H, rank] f32
    *,
    page_size: int,
    rank: int,
    sm_scale: float,
    pages_per_block: int,
):
    b = pl.program_id(0)
    hbm = (
        lat_hbm_ref.at[layer_ref[0]]
        if len(lat_hbm_ref.shape) == 5
        else lat_hbm_ref
    )
    ppb = pages_per_block
    S = ppb * page_size
    kv_len = kv_lens_ref[b]
    n_blocks = (kv_len + S - 1) // S
    n_live_pages = (kv_len + page_size - 1) // page_size

    m_ref[:] = jnp.full_like(m_ref, NEG_INF)
    l_ref[:] = jnp.zeros_like(l_ref)
    acc_ref[:] = jnp.zeros_like(acc_ref)

    def body(buf, sem):
        # buf: [2, 1, S, Dl]; one DMA per page.
        def _dma(slot, i, j):
            return pltpu.make_async_copy(
                hbm.at[page_table_ref[b, i * ppb + j]],
                buf.at[slot, :, pl.ds(j * page_size, page_size), :],
                sem.at[slot, j],
            )

        def start_block(slot, i):
            for j in range(ppb):

                @pl.when(i * ppb + j < n_live_pages)
                def _start():
                    _dma(slot, i, j).start()

        def wait_block(slot, i):
            for j in range(ppb):

                @pl.when(i * ppb + j < n_live_pages)
                def _wait():
                    _dma(slot, i, j).wait()

        @pl.when(n_blocks > 0)
        def _warmup():
            start_block(0, 0)

        def loop(i, _):
            slot = jax.lax.rem(i, 2)

            @pl.when(i + 1 < n_blocks)
            def _prefetch():
                start_block(jax.lax.rem(i + 1, 2), i + 1)

            wait_block(slot, i)
            lat = buf[slot, 0]  # [S, Dl]
            # zero unfetched tail rows so stray VMEM can't poison (0 x v)
            pos_l = i * S + jax.lax.broadcasted_iota(jnp.int32, lat.shape, 0)
            lat = jnp.where(pos_l < kv_len, lat, 0.0)
            q = q_ref[0]  # [H, Dl]
            s = jax.lax.dot_general(
                q, lat, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * sm_scale  # [H, S]
            pos = i * S + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(pos < kv_len, s, NEG_INF)

            m_prev = m_ref[:, :1]  # [H, 1]
            m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
            alpha = jnp.exp(m_prev - m_new)
            probs = jnp.exp(s - m_new)  # [H, S]
            l_ref[:, :1] = l_ref[:, :1] * alpha + jnp.sum(
                probs, axis=1, keepdims=True
            )
            m_ref[:, :1] = m_new
            pv = jax.lax.dot_general(
                probs.astype(lat.dtype), lat[:, :rank],
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )  # [H, rank]
            acc_ref[:] = acc_ref[:] * alpha + pv
            return 0

        jax.lax.fori_loop(0, n_blocks, loop, 0)

    pl.run_scoped(
        body,
        buf=pltpu.VMEM(
            (2, 1, ppb * page_size, lat_hbm_ref.shape[-1]), lat_hbm_ref.dtype
        ),
        sem=pltpu.SemaphoreType.DMA((2, ppb)),
    )

    l = l_ref[:, :1]
    l = jnp.where(l == 0.0, 1.0, l)
    out_ref[0] = (acc_ref[:] / l).astype(out_ref.dtype)


def mla_decode_paged_attention_full(
    q_eff: jax.Array,        # [B, 1, H, Dl]
    latent_cache: jax.Array,  # [L, num_pages, 1, page, Dl]
    layer: jax.Array,        # scalar i32
    page_table: jax.Array,   # [B, max_pages]
    kv_lens: jax.Array,      # [B]
    rank: int,
    sm_scale: float,
    interpret: bool = False,
    pages_per_block: int = 8,
) -> jax.Array:
    """Returns [B, 1, H, rank]."""
    B, Q, H, Dl = q_eff.shape
    assert Q == 1, "MLA decode kernel handles Q=1"
    page = latent_cache.shape[-2]
    max_pages = page_table.shape[1]
    if max_pages % pages_per_block:
        pad = pages_per_block - max_pages % pages_per_block
        page_table = jnp.pad(page_table, ((0, 0), (0, pad)))
    qh = q_eff.reshape(B, H, Dl)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(B,),
        in_specs=[
            pl.BlockSpec((1, H, Dl), lambda b, l, pt, kl: (b, 0, 0)),
            pl.BlockSpec(memory_space=pltpu.ANY),
        ],
        out_specs=pl.BlockSpec((1, H, rank), lambda b, l, pt, kl: (b, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((H, 128), jnp.float32),
            pltpu.VMEM((H, 128), jnp.float32),
            pltpu.VMEM((H, rank), jnp.float32),
        ],
    )
    kernel = pl.pallas_call(
        functools.partial(
            _mla_decode_kernel,
            page_size=page,
            rank=rank,
            sm_scale=sm_scale,
            pages_per_block=pages_per_block,
        ),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, H, rank), q_eff.dtype),
        compiler_params=pallas_tpu_compiler_params(
            dimension_semantics=("arbitrary",),
        ),
        interpret=interpret,
    )
    out = kernel(
        layer.astype(jnp.int32).reshape(1), page_table, kv_lens, qh, latent_cache
    )
    return out.reshape(B, 1, H, rank)
