// Concurrency self-test for the kvship transfer core.
//
// Exercises the producer/consumer paths the Python tests cover, but with
// genuine thread-level contention so TSAN/ASAN builds can catch data
// races and lifetime bugs (SURVEY.md §5.2: the reference documents its
// concurrency hazards instead of sanitizing them; this framework runs
// sanitizers over the native transfer layer in CI).
//
// Build & run:  make test        (plain)
//               make tsan        (ThreadSanitizer)
//               make asan        (AddressSanitizer)

#include <atomic>
#include <chrono>
#include <cassert>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

extern "C" {
void* kvship_server_create(uint16_t port);
int kvship_server_port(void* h);
void kvship_server_destroy(void* h);
int kvship_register(void* h, const char* key, const uint8_t* data,
                    uint64_t len, uint64_t lease_ms);
int kvship_unregister(void* h, const char* key);
uint64_t kvship_registered_bytes(void* h);
uint64_t kvship_registered_count(void* h);
int kvship_pull(const char* host, uint16_t port, const char* key,
                uint8_t** out, uint64_t* out_len);
void kvship_buf_free(uint8_t* buf);
int kvship_free_notify(const char* host, uint16_t port, const char* key);
int kvship_renew(const char* host, uint16_t port, const char* key,
                 uint64_t lease_ms);
}

int main() {
  void* srv = kvship_server_create(0);  // ephemeral port
  assert(srv != nullptr);
  const int port = kvship_server_port(srv);
  assert(port > 0);

  constexpr int kThreads = 8;
  constexpr int kKeysPerThread = 24;
  std::atomic<int> pulls_ok{0}, pulls_missing{0}, frees_ok{0};

  // Producer threads register/unregister; consumer threads pull, renew
  // and free-notify the same key space concurrently.
  std::vector<std::thread> threads;
  for (int t = 0; t < kThreads; ++t) {
    threads.emplace_back([&, t] {
      char key[64];
      std::vector<uint8_t> payload(4096, static_cast<uint8_t>(t));
      for (int i = 0; i < kKeysPerThread; ++i) {
        snprintf(key, sizeof(key), "k-%d-%d", t % 4, i);  // overlapping keys
        if (t < 4) {  // producers
          kvship_register(srv, key, payload.data(), payload.size(), 30000);
          if (i % 3 == 0) kvship_unregister(srv, key);
        } else {  // consumers
          uint8_t* buf = nullptr;
          uint64_t len = 0;
          int rc = kvship_pull("127.0.0.1", static_cast<uint16_t>(port), key,
                               &buf, &len);
          if (rc == 0) {
            assert(len == 4096);
            pulls_ok.fetch_add(1);
            kvship_buf_free(buf);
            kvship_renew("127.0.0.1", static_cast<uint16_t>(port), key, 10000);
            if (kvship_free_notify("127.0.0.1", static_cast<uint16_t>(port),
                                   key) == 0)
              frees_ok.fetch_add(1);
          } else {
            pulls_missing.fetch_add(1);
          }
        }
      }
    });
  }
  for (auto& th : threads) th.join();

  // Lease expiry path: a short-lease key must disappear on its own.
  const uint8_t tiny[8] = {1, 2, 3, 4, 5, 6, 7, 8};
  kvship_register(srv, "short-lease", tiny, sizeof(tiny), 50);
  std::this_thread::sleep_for(std::chrono::milliseconds(200));
  uint8_t* buf = nullptr;
  uint64_t len = 0;
  int rc = kvship_pull("127.0.0.1", static_cast<uint16_t>(port), "short-lease",
                       &buf, &len);
  assert(rc != 0 && "expired lease must not be pullable");

  std::printf(
      "kvship_test ok: pulls_ok=%d pulls_missing=%d frees_ok=%d "
      "registered_count=%llu\n",
      pulls_ok.load(), pulls_missing.load(), frees_ok.load(),
      static_cast<unsigned long long>(kvship_registered_count(srv)));
  kvship_server_destroy(srv);
  return 0;
}
