// kvship: TPU-native KV-cache block shipper (the NIXL-equivalent transfer
// core of the framework's P/D disaggregation path).
//
// Reference semantics being replicated (docs/architecture/advanced/
// disaggregation/operations-vllm.md:18-47,155-160 in /root/reference):
//   * pull model: the producer (prefill) registers KV bytes under a key and
//     parks them; the consumer (decode) pulls them one-sided over the
//     network whenever it is ready — the producer's engine loop is never
//     involved in the transfer;
//   * lease + free-notify: registered buffers carry a lease (default 30s);
//     the consumer extends it with RENEW heartbeats and releases it with
//     FREE when the pull landed; a reaper reclaims expired entries so a
//     crashed consumer cannot leak producer memory.
//
// On TPU there is no GPUDirect-RDMA equivalent exposed to user code, so the
// fast path is: JAX stages KV pages HBM->host (device_get), this library
// ships host bytes over TCP (same-host loopback, ICI-adjacent DCN, or
// cross-slice DCN), and JAX re-stages host->HBM (device_put) on the
// consumer. This is the TPUConnector/TPUConnectorHMA pattern the reference
// deploys on TPU (pd-disaggregation/modelserver/tpu/* patches,
// TPU_KV_TRANSFER_PORT=9100 / TPU_SIDE_CHANNEL_PORT=9600); side channel and
// data channel are folded into one length-prefixed protocol here.
//
// Exposed as a plain C ABI consumed from Python via ctypes (no pybind11 in
// the image).

#include <arpa/inet.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <sys/time.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <unordered_set>
#include <vector>

namespace {

constexpr uint32_t kMagic = 0x4B565348;  // "KVSH"

enum Op : uint8_t { OP_PULL = 1, OP_FREE = 2, OP_RENEW = 3, OP_STAT = 4 };
enum Status : uint8_t { ST_OK = 0, ST_NOT_FOUND = 1, ST_ERR = 2 };

using Clock = std::chrono::steady_clock;

struct Entry {
  std::vector<uint8_t> data;
  Clock::time_point deadline;
};

bool write_all(int fd, const void* buf, size_t len) {
  const uint8_t* p = static_cast<const uint8_t*>(buf);
  while (len > 0) {
    ssize_t n = ::send(fd, p, len, MSG_NOSIGNAL);
    if (n <= 0) return false;
    p += n;
    len -= static_cast<size_t>(n);
  }
  return true;
}

bool read_all(int fd, void* buf, size_t len) {
  uint8_t* p = static_cast<uint8_t*>(buf);
  while (len > 0) {
    ssize_t n = ::recv(fd, p, len, 0);
    if (n <= 0) return false;
    p += n;
    len -= static_cast<size_t>(n);
  }
  return true;
}

class Server {
 public:
  explicit Server(uint16_t port) {
    listen_fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
    if (listen_fd_ < 0) return;
    int one = 1;
    ::setsockopt(listen_fd_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_addr.s_addr = htonl(INADDR_ANY);
    addr.sin_port = htons(port);
    if (::bind(listen_fd_, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0 ||
        ::listen(listen_fd_, 128) != 0) {
      ::close(listen_fd_);
      listen_fd_ = -1;
      return;
    }
    socklen_t alen = sizeof(addr);
    ::getsockname(listen_fd_, reinterpret_cast<sockaddr*>(&addr), &alen);
    port_ = ntohs(addr.sin_port);
    accept_thread_ = std::thread([this] { AcceptLoop(); });
    reaper_thread_ = std::thread([this] { ReaperLoop(); });
  }

  ~Server() { Stop(); }

  bool ok() const { return listen_fd_ >= 0; }
  int port() const { return port_; }

  void Stop() {
    bool expected = false;
    if (!stopping_.compare_exchange_strong(expected, true)) return;
    ::shutdown(listen_fd_, SHUT_RDWR);
    ::close(listen_fd_);
    {
      std::lock_guard<std::mutex> lk(mu_);
      cv_.notify_all();
    }
    if (accept_thread_.joinable()) accept_thread_.join();
    if (reaper_thread_.joinable()) reaper_thread_.join();
    // Handler threads are detached. Force-shutdown every live connection so
    // a handler blocked in recv wakes immediately, then wait (no timeout:
    // post-shutdown the handlers exit promptly, and returning early would
    // let a live handler dereference a freed Server).
    {
      std::lock_guard<std::mutex> lk(workers_mu_);
      for (int cfd : client_fds_) ::shutdown(cfd, SHUT_RDWR);
    }
    std::unique_lock<std::mutex> lk(workers_mu_);
    workers_cv_.wait(lk, [this] { return active_workers_ == 0; });
  }

  // Two-part register (header + payload) so callers can hand a raw device
  // buffer without first concatenating it with its header on the Python side.
  int Register(const std::string& key, const uint8_t* hdr, uint64_t hdr_len,
               const uint8_t* data, uint64_t len, uint64_t lease_ms) {
    Entry e;
    e.data.reserve(hdr_len + len);
    e.data.insert(e.data.end(), hdr, hdr + hdr_len);
    e.data.insert(e.data.end(), data, data + len);
    e.deadline = Clock::now() + std::chrono::milliseconds(lease_ms);
    std::lock_guard<std::mutex> lk(mu_);
    bytes_ += hdr_len + len;
    auto it = entries_.find(key);
    if (it != entries_.end()) bytes_ -= it->second.data.size();
    entries_[key] = std::move(e);
    cv_.notify_all();
    return 0;
  }

  int Unregister(const std::string& key) {
    std::lock_guard<std::mutex> lk(mu_);
    auto it = entries_.find(key);
    if (it == entries_.end()) return 1;
    bytes_ -= it->second.data.size();
    entries_.erase(it);
    return 0;
  }

  uint64_t RegisteredBytes() {
    std::lock_guard<std::mutex> lk(mu_);
    return bytes_;
  }

  uint64_t RegisteredCount() {
    std::lock_guard<std::mutex> lk(mu_);
    return entries_.size();
  }

  uint64_t Expired() { return expired_.load(); }

 private:
  void AcceptLoop() {
    while (!stopping_.load()) {
      int fd = ::accept(listen_fd_, nullptr, nullptr);
      if (fd < 0) {
        if (stopping_.load()) break;
        continue;
      }
      int one = 1;
      ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      // Idle-connection bound so a silent peer can't pin a handler forever.
      timeval tv{60, 0};
      ::setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
      {
        std::lock_guard<std::mutex> lk(workers_mu_);
        ++active_workers_;
        client_fds_.insert(fd);
      }
      std::thread([this, fd] {
        Handle(fd);
        std::lock_guard<std::mutex> lk(workers_mu_);
        client_fds_.erase(fd);
        ::close(fd);
        --active_workers_;
        workers_cv_.notify_all();
      }).detach();
    }
  }

  void Handle(int fd) {
    for (;;) {
      uint32_t magic;
      uint8_t op;
      uint16_t keylen;
      if (!read_all(fd, &magic, 4) || magic != kMagic) return;
      if (!read_all(fd, &op, 1) || !read_all(fd, &keylen, 2)) return;
      std::string key(keylen, '\0');
      if (keylen && !read_all(fd, &key[0], keylen)) return;
      switch (op) {
        case OP_PULL: {
          std::vector<uint8_t> data;
          uint8_t st = ST_NOT_FOUND;
          {
            std::lock_guard<std::mutex> lk(mu_);
            auto it = entries_.find(key);
            // Expiry is enforced at access time, not just by the 500ms
            // reaper sweep: serving a pull after the lease lapsed would
            // break the reclamation contract (the producer may already
            // treat the pages as free).
            if (it != entries_.end() && it->second.deadline > Clock::now()) {
              data = it->second.data;  // copy out so the lock isn't held on send
              st = ST_OK;
            }
          }
          uint64_t len = data.size();
          if (!write_all(fd, &st, 1) || !write_all(fd, &len, 8)) return;
          if (st == ST_OK && len && !write_all(fd, data.data(), len)) return;
          break;
        }
        case OP_FREE: {
          uint8_t st = Unregister(key) == 0 ? ST_OK : ST_NOT_FOUND;
          uint64_t len = 0;
          if (!write_all(fd, &st, 1) || !write_all(fd, &len, 8)) return;
          break;
        }
        case OP_RENEW: {
          uint64_t lease_ms;
          if (!read_all(fd, &lease_ms, 8)) return;
          uint8_t st = ST_NOT_FOUND;
          {
            std::lock_guard<std::mutex> lk(mu_);
            auto it = entries_.find(key);
            // A lapsed lease cannot be resurrected: the producer may have
            // reclaimed the pages between expiry and this heartbeat.
            if (it != entries_.end() && it->second.deadline > Clock::now()) {
              it->second.deadline =
                  Clock::now() + std::chrono::milliseconds(lease_ms);
              st = ST_OK;
            }
          }
          uint64_t len = 0;
          if (!write_all(fd, &st, 1) || !write_all(fd, &len, 8)) return;
          break;
        }
        case OP_STAT: {
          uint8_t st = ST_OK;
          uint64_t len = 16;
          uint64_t stat[2] = {RegisteredCount(), RegisteredBytes()};
          if (!write_all(fd, &st, 1) || !write_all(fd, &len, 8) ||
              !write_all(fd, stat, 16))
            return;
          break;
        }
        default:
          return;
      }
    }
  }

  void ReaperLoop() {
    std::unique_lock<std::mutex> lk(mu_);
    while (!stopping_.load()) {
      cv_.wait_for(lk, std::chrono::milliseconds(500));
      if (stopping_.load()) break;
      auto now = Clock::now();
      for (auto it = entries_.begin(); it != entries_.end();) {
        if (it->second.deadline <= now) {
          bytes_ -= it->second.data.size();
          it = entries_.erase(it);
          expired_.fetch_add(1);
        } else {
          ++it;
        }
      }
    }
  }

  int listen_fd_ = -1;
  int port_ = 0;
  std::atomic<bool> stopping_{false};
  std::thread accept_thread_;
  std::thread reaper_thread_;
  std::mutex workers_mu_;
  std::condition_variable workers_cv_;
  int active_workers_ = 0;
  std::unordered_set<int> client_fds_;

  std::mutex mu_;
  std::condition_variable cv_;
  std::unordered_map<std::string, Entry> entries_;
  uint64_t bytes_ = 0;
  std::atomic<uint64_t> expired_{0};
};

int Connect(const char* host, uint16_t port) {
  // Resolve via getaddrinfo so k8s service DNS names and IPv6 literals work
  // (not just dotted-quad IPv4).
  addrinfo hints{};
  hints.ai_family = AF_UNSPEC;
  hints.ai_socktype = SOCK_STREAM;
  addrinfo* res = nullptr;
  char portbuf[8];
  std::snprintf(portbuf, sizeof(portbuf), "%u", port);
  if (::getaddrinfo(host, portbuf, &hints, &res) != 0 || !res) return -1;
  int fd = -1;
  for (addrinfo* ai = res; ai; ai = ai->ai_next) {
    fd = ::socket(ai->ai_family, ai->ai_socktype, ai->ai_protocol);
    if (fd < 0) continue;
    // Bound every client op (connect/send/recv) so a blackholed producer
    // can never hang the calling engine thread; matches the Python
    // fallback's 30s.
    timeval tv{30, 0};
    ::setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
    ::setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof(tv));
    if (::connect(fd, ai->ai_addr, ai->ai_addrlen) == 0) break;
    ::close(fd);
    fd = -1;
  }
  ::freeaddrinfo(res);
  if (fd < 0) return -1;
  int one = 1;
  ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  return fd;
}

// Issues one op and reads the response header (+payload for PULL/STAT).
int RoundTrip(const char* host, uint16_t port, uint8_t op, const char* key,
              uint64_t lease_ms, uint8_t** out, uint64_t* out_len) {
  int fd = Connect(host, port);
  if (fd < 0) return -1;
  uint16_t keylen = static_cast<uint16_t>(std::strlen(key));
  bool ok = write_all(fd, &kMagic, 4) && write_all(fd, &op, 1) &&
            write_all(fd, &keylen, 2) && write_all(fd, key, keylen);
  if (ok && op == OP_RENEW) ok = write_all(fd, &lease_ms, 8);
  uint8_t st = ST_ERR;
  uint64_t len = 0;
  ok = ok && read_all(fd, &st, 1) && read_all(fd, &len, 8);
  if (ok && len > 0) {
    uint8_t* buf = static_cast<uint8_t*>(::malloc(len));
    if (!buf || !read_all(fd, buf, len)) {
      ::free(buf);
      ok = false;
    } else if (out) {
      *out = buf;
      if (out_len) *out_len = len;
    } else {
      ::free(buf);
    }
  } else if (out) {
    *out = nullptr;
    if (out_len) *out_len = 0;
  }
  ::close(fd);
  if (!ok) return -1;
  return st;
}

}  // namespace

extern "C" {

void* kvship_server_create(uint16_t port) {
  Server* s = new Server(port);
  if (!s->ok()) {
    delete s;
    return nullptr;
  }
  return s;
}

int kvship_server_port(void* h) { return static_cast<Server*>(h)->port(); }

void kvship_server_destroy(void* h) { delete static_cast<Server*>(h); }

int kvship_register(void* h, const char* key, const uint8_t* data,
                    uint64_t len, uint64_t lease_ms) {
  return static_cast<Server*>(h)->Register(key, nullptr, 0, data, len, lease_ms);
}

int kvship_register2(void* h, const char* key, const uint8_t* hdr,
                     uint64_t hdr_len, const uint8_t* data, uint64_t len,
                     uint64_t lease_ms) {
  return static_cast<Server*>(h)->Register(key, hdr, hdr_len, data, len,
                                           lease_ms);
}

int kvship_unregister(void* h, const char* key) {
  return static_cast<Server*>(h)->Unregister(key);
}

uint64_t kvship_registered_bytes(void* h) {
  return static_cast<Server*>(h)->RegisteredBytes();
}

uint64_t kvship_registered_count(void* h) {
  return static_cast<Server*>(h)->RegisteredCount();
}

uint64_t kvship_expired_count(void* h) {
  return static_cast<Server*>(h)->Expired();
}

// Returns: 0 OK (out/out_len set), 1 not found, 2 server error, -1 I/O error.
int kvship_pull(const char* host, uint16_t port, const char* key,
                uint8_t** out, uint64_t* out_len) {
  return RoundTrip(host, port, OP_PULL, key, 0, out, out_len);
}

void kvship_buf_free(uint8_t* buf) { ::free(buf); }

int kvship_free_notify(const char* host, uint16_t port, const char* key) {
  return RoundTrip(host, port, OP_FREE, key, 0, nullptr, nullptr);
}

int kvship_renew(const char* host, uint16_t port, const char* key,
                 uint64_t lease_ms) {
  return RoundTrip(host, port, OP_RENEW, key, lease_ms, nullptr, nullptr);
}

// stat[0]=count stat[1]=bytes
int kvship_stat(const char* host, uint16_t port, uint64_t* stat2) {
  uint8_t* buf = nullptr;
  uint64_t len = 0;
  int st = RoundTrip(host, port, OP_STAT, "", 0, &buf, &len);
  if (st == 0 && len == 16) {
    std::memcpy(stat2, buf, 16);
  } else if (st == 0) {
    st = -1;
  }
  ::free(buf);
  return st;
}

}  // extern "C"
