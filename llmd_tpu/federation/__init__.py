"""Cross-replica KV federation (docs/architecture/kv-federation.md).

Glues the per-replica pieces — tiered offload, cross-slice kvstore,
KV-event prefix index, precise-prefix scorer — into one fleet-wide
prefix cache: publish-on-evict, store-aware tri-state routing,
fetch-on-miss.
"""

from llmd_tpu.federation.core import (  # noqa: F401
    PUBLISH_POLICIES,
    KVFederation,
)
from llmd_tpu.federation.wire import (  # noqa: F401
    PageDecodeError,
    decode_page,
    encode_page,
)
