"""Page framing for the federation store tier: CRC-guarded npy payloads.

Objects in the cross-slice store travel host→host over the kvship
transfer plane and may outlive the publishing process by hours (the
master's soft-pin TTL is 30 minutes). The local tiers get away with
trusting their own memory; a federated pull cannot — a corrupt page
committed into the prefix cache would silently poison every request
that hits it. So every published page rides a tiny header:

    magic "KVF1" | crc32(payload) u32-le | npy payload

Decode verifies the CRC before numpy ever parses the payload; a
mismatch (or a foreign/old-format blob) raises :class:`PageDecodeError`
and the caller degrades to the recompute policy — the same contract the
P/D connector's version-2 bundle CRC enforces on the transfer leg
(docs/architecture/fault-tolerance.md).
"""

from __future__ import annotations

import io
import struct
import zlib

import numpy as np

MAGIC = b"KVF1"
_HEADER = struct.Struct("<4sI")


class PageDecodeError(ValueError):
    """Blob failed the CRC or did not parse as a page."""


def encode_page(page: np.ndarray) -> bytes:
    """Frame one host-tier page for publication."""
    buf = io.BytesIO()
    np.save(buf, page, allow_pickle=False)
    payload = buf.getvalue()
    return _HEADER.pack(MAGIC, zlib.crc32(payload)) + payload


def decode_page(blob: bytes) -> np.ndarray:
    """Verify and parse a pulled page. Raises PageDecodeError on any
    corruption — callers degrade to recompute, never commit the page."""
    if len(blob) < _HEADER.size:
        raise PageDecodeError(f"short blob ({len(blob)}B)")
    magic, crc = _HEADER.unpack_from(blob)
    payload = blob[_HEADER.size:]
    if magic != MAGIC:
        raise PageDecodeError(f"bad magic {magic!r}")
    if zlib.crc32(payload) != crc:
        raise PageDecodeError("payload CRC mismatch")
    try:
        return np.load(io.BytesIO(payload), allow_pickle=False)
    except (OSError, ValueError) as e:
        raise PageDecodeError(f"npy parse failed: {e}") from e
