"""Cross-replica KV federation: the glue between four existing pieces.

The offload tier (:mod:`llmd_tpu.kvtransfer.offload`), the
Mooncake-class cross-slice store (:mod:`llmd_tpu.kvstore`), the KV-event
prefix index (:mod:`llmd_tpu.events.index`) and the precise-prefix
scorer (:mod:`llmd_tpu.epp.precise_prefix`) each work per-replica; this
module turns them into ONE fleet-wide prefix cache
(docs/architecture/kv-federation.md):

- **publish-on-evict** — when the device cache evicts a page the host
  tier still holds, a hotness gate (``publish_min_hits`` distinct uses
  of the page's hash chain) decides whether the page is worth a global
  copy; hot pages are CRC-framed (:mod:`llmd_tpu.federation.wire`),
  registered with the local kvship shipper and ``PUT`` to the master
  off-thread. Once the master ACCEPTS the copy, a
  ``BlockStored(medium="store")`` event teaches the prefix index the
  third tier. The eager ``save`` policy (publish every host save, the
  pre-federation behavior) remains available for small fleets where
  publish bandwidth is free.
- **fetch-on-miss** — the engine's restore-on-prefill path consults
  :meth:`KVFederation.fetch` for hash-chain pages that extend the local
  prefix run: locate at the master, pull peer-to-peer from the owning
  segment's shipper, CRC-verify, and hand the page to the ordinary
  cache-seeding commit. Every failure mode (master timeout, locate
  miss, ``PullError``, CRC reject, injected ``kv.pull.drop``) returns
  ``None`` — the caller's existing recompute policy is the degradation,
  never an exception up the admission path.

Counter surface (rendered on ``/metrics`` via
``EngineStats``/``serve/metrics.py``): ``kv_federation_published_total``
(master-accepted publications), ``kv_federation_hits_total`` (pages
pulled from the store), plus the store client's
``kvstore_pulls/pull_failures/misses``. The recompute-avoided token
count lives with the offload connector, which knows the page size and
whether fetched pages actually committed.
"""

from __future__ import annotations

import collections
import logging
import threading

import numpy as np

from llmd_tpu import faults
from llmd_tpu.federation.wire import PageDecodeError, decode_page, encode_page

log = logging.getLogger(__name__)

PUBLISH_POLICIES = ("save", "evict-hot", "off")


class KVFederation:
    """One engine's membership in the fleet-wide prefix cache.

    Owns the publish policy and the fetch path; the store client
    (:class:`llmd_tpu.kvstore.client.CrossSliceStoreClient`) owns the
    wire. ``event_sink`` is attached by the engine once the tiered sink
    exists — publications confirmed before that are counted but not
    advertised (the index converges from later traffic).

    Thread model: ``touch``/``fetch``/``publish`` run on the engine
    thread; ``_on_published`` runs on the client's publisher thread.
    The shared hotness/published books sit behind one lock; event
    emission happens outside it (the ZMQ sink has its own lock).
    """

    def __init__(
        self,
        client,
        publish_policy: str = "save",
        publish_min_hits: int = 2,
        hot_track_max: int = 65536,
    ) -> None:
        if publish_policy not in PUBLISH_POLICIES:
            raise ValueError(
                f"unknown publish policy {publish_policy!r} "
                f"(expected one of {PUBLISH_POLICIES})"
            )
        self.client = client
        self.publish_policy = publish_policy
        self.publish_min_hits = max(1, publish_min_hits)
        self.event_sink = None  # TieredEventSink, attached by the engine
        self._lock = threading.Lock()
        # hash -> distinct-use count, LRU-bounded (the hotness book).
        # llmd: guarded_by(_lock)
        self._touches: collections.OrderedDict[bytes, int] = (
            collections.OrderedDict()
        )
        self._hot_track_max = hot_track_max
        # Keys already handed to the publisher (bounded): the master
        # dedups anyway (first copy wins), this just keeps a hot page
        # that keeps getting device-evicted from re-serializing itself
        # into the publish queue every time.
        # llmd: guarded_by(_lock)
        self._enqueued: collections.OrderedDict[str, None] = (
            collections.OrderedDict()
        )
        # pages handed to the publisher
        self.publish_requests = 0  # llmd: guarded_by(_lock)
        # publications the master accepted
        self.published = 0  # llmd: guarded_by(_lock)
        # publications that did not land
        self.publish_failures = 0  # llmd: guarded_by(_lock)
        # pages fetched from the store
        self.hits = 0  # llmd: guarded_by(_lock)
        # pulled blobs rejected by the CRC
        self.crc_failures = 0  # llmd: guarded_by(_lock)
        client.on_published = self._on_published
        client.on_publish_failed = self._on_publish_failed
        client.on_evicted = self._on_store_evicted

    # ---------------------------------------------------------- hotness

    def touch(self, h: bytes) -> None:
        """Record one use of a page hash (host-tier save/hit or a
        device-cache prefix hit seen by the restore walk)."""
        with self._lock:
            n = self._touches.pop(h, 0)
            self._touches[h] = n + 1
            while len(self._touches) > self._hot_track_max:
                self._touches.popitem(last=False)

    def is_hot(self, h: bytes) -> bool:
        with self._lock:
            return self._touches.get(h, 0) >= self.publish_min_hits

    # ---------------------------------------------------------- publish

    def on_save(self, h: bytes, page: np.ndarray) -> None:
        """Host-tier save hook (save-on-fill). Eager ``save`` policy
        publishes everything; ``evict-hot`` waits for the eviction."""
        self.touch(h)
        if self.publish_policy == "save":
            self.publish(h, page)

    def wants_publish_on_evict(self, h: bytes) -> bool:
        """The hotness gate, checked BEFORE the caller pays to
        materialize the page bytes (possibly an FS load)."""
        if self.publish_policy != "evict-hot":
            return False
        with self._lock:
            if h.hex() in self._enqueued:
                return False
            return self._touches.get(h, 0) >= self.publish_min_hits

    def _mark_enqueued(self, key: str) -> bool:
        with self._lock:
            if key in self._enqueued:
                return False
            self._enqueued[key] = None
            while len(self._enqueued) > self._hot_track_max:
                self._enqueued.popitem(last=False)
            self.publish_requests += 1
            return True

    def publish(self, h: bytes, page: np.ndarray) -> None:
        """Hand one page to the store's publisher thread (never blocks
        the engine thread; queue overflow drops the publish)."""
        key = h.hex()
        if self._mark_enqueued(key):
            self.client.put_async(key, encode_page(page))

    def publish_deferred(self, h: bytes, loader) -> None:
        """Evict-path publish: ``loader`` (zero-arg, returns the page
        array or None) runs on the client's publisher thread, so the
        engine thread pays neither the possible FS load nor the
        serialization — eviction bursts land exactly when the engine is
        under memory pressure."""
        key = h.hex()
        if not self._mark_enqueued(key):
            return

        def blob():
            page = loader()
            return None if page is None else encode_page(page)

        self.client.put_async(key, blob)

    def _on_published(self, key: str) -> None:
        """Publisher-thread callback: the master accepted our copy —
        advertise the store tier to the prefix index."""
        with self._lock:
            self.published += 1
            sink = self.event_sink
        if sink is not None:
            try:
                sink.stored_with_medium([bytes.fromhex(key)], "store")
            # llmd: allow(broad-except) -- publisher thread must survive any sink failure
            except Exception as e:
                log.warning("store-tier event emit failed: %s", e)

    def _on_publish_failed(self, key: str) -> None:
        """The publication did not land (master down, queue overflow,
        page gone before the deferred load ran): forget the enqueued
        mark so a later save/evict retries once the store recovers.
        Rejected puts (another segment already owns the copy) do NOT
        come through here — for those the mark correctly suppresses
        re-serialization."""
        with self._lock:
            self.publish_failures += 1
            self._enqueued.pop(key, None)

    def _on_store_evicted(self, key: str) -> None:
        """Heartbeat-thread callback: the master's watermark eviction
        reclaimed our copy — withdraw the store-tier advertisement so
        routing stops scoring a copy that no longer exists, and unmark
        the key so a future hot eviction can re-publish it."""
        with self._lock:
            self._enqueued.pop(key, None)
            sink = self.event_sink
        if sink is not None:
            try:
                sink.removed_with_medium([bytes.fromhex(key)], "store")
            # llmd: allow(broad-except) -- heartbeat thread must survive any sink failure
            except Exception as e:
                log.warning("store-tier removal emit failed: %s", e)

    # ------------------------------------------------------------ fetch

    def fetch(self, h: bytes) -> np.ndarray | None:
        """Fetch-on-miss: one page from whichever segment holds it.

        Returns None on ANY failure — the caller recomputes. Counted
        here: successful store hits and CRC rejects; the client counts
        pulls / pull failures / locate misses."""
        key = h.hex()
        # The store leg of the kv.pull.drop site (fault-tolerance.md):
        # a dropped federated pull degrades to recompute exactly like a
        # dropped P/D pull.
        if faults.fires("kv.pull.drop", f"store|{key}"):
            return None
        blob = self.client.get(key)
        if blob is None:
            return None
        blob = faults.corrupt("kv.bundle.corrupt", blob, f"store|{key}")
        try:
            page = decode_page(blob)
        except PageDecodeError as e:
            with self._lock:
                self.crc_failures += 1
            log.warning("federated page %s rejected: %s", key[:16], e)
            return None
        with self._lock:
            self.hits += 1
        return page

    def fetch_many(self, hs: list[bytes]) -> dict[bytes, "np.ndarray"]:
        """Batched fetch-on-miss: every store-held page of a prefix run
        in ONE store round trip (one master locate + one pipelined
        kvship pull per owning segment — the group framing of the store
        leg). Per-page failures (drop, CRC reject, absent) just leave
        that page out of the result; the caller's chain walk stops at
        the first gap and recomputes from there."""
        out: dict[bytes, np.ndarray] = {}
        if not hs:
            return out
        keys = []
        for h in hs:
            key = h.hex()
            # Per-page drop site: a dropped federated pull degrades that
            # page to recompute exactly like the sequential path.
            if faults.fires("kv.pull.drop", f"store|{key}"):
                continue
            keys.append(key)
        getter = getattr(self.client, "get_many", None)
        if getter is None:  # minimal/store-stub clients
            blobs = {}
            for key in keys:
                blob = self.client.get(key)
                if blob is not None:
                    blobs[key] = blob
        else:
            blobs = getter(keys)
        for key, blob in blobs.items():
            if blob is None:
                continue
            blob = faults.corrupt("kv.bundle.corrupt", blob, f"store|{key}")
            try:
                page = decode_page(blob)
            except PageDecodeError as e:
                with self._lock:
                    self.crc_failures += 1
                log.warning("federated page %s rejected: %s", key[:16], e)
                continue
            with self._lock:
                self.hits += 1
            out[bytes.fromhex(key)] = page
        return out

    # ------------------------------------------------------------ misc

    def clear_local(self) -> None:
        """Weight rollout: withdraw this replica's store contribution
        and forget the hotness book (hashes no longer match)."""
        with self._lock:
            self._touches.clear()
            self._enqueued.clear()
        self.client.clear_local()

    def stats(self) -> dict:
        with self._lock:
            return {
                "publish_policy": self.publish_policy,
                "publish_requests": self.publish_requests,
                "published": self.published,
                "hits": self.hits,
                "crc_failures": self.crc_failures,
            }
