"""ctypes loader for the native kvship library, with lazy compilation.

Builds llmd_tpu/native/libkvship.so with g++ on first use if missing (the
image ships the toolchain; pybind11 is absent so the ABI is plain C).
Returns None if the toolchain is unavailable — callers fall back to the
pure-Python shipper, which speaks the identical wire protocol.
"""

from __future__ import annotations

import ctypes
import logging
import pathlib
import subprocess
import threading

log = logging.getLogger(__name__)

_NATIVE_DIR = pathlib.Path(__file__).resolve().parent.parent / "native"
_SO = _NATIVE_DIR / "libkvship.so"
_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_tried = False


def _build() -> bool:
    src = _NATIVE_DIR / "kvship.cpp"
    if not src.exists():
        return False
    try:
        subprocess.run(
            ["g++", "-O2", "-std=c++17", "-fPIC", "-Wall", "-shared",
             "-pthread", "-o", str(_SO), str(src)],
            check=True,
            capture_output=True,
            timeout=120,
        )
        return True
    except (subprocess.SubprocessError, FileNotFoundError) as e:
        log.warning("kvship native build failed, using Python fallback: %s", e)
        return False


def load() -> ctypes.CDLL | None:
    """Load (building if needed) the native library; None on failure."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        stale = (
            _SO.exists()
            and (_NATIVE_DIR / "kvship.cpp").exists()
            and _SO.stat().st_mtime < (_NATIVE_DIR / "kvship.cpp").stat().st_mtime
        )
        if (not _SO.exists() or stale) and not _build():
            return None
        try:
            lib = ctypes.CDLL(str(_SO))
        except OSError as e:
            log.warning("kvship load failed, using Python fallback: %s", e)
            return None

        lib.kvship_server_create.argtypes = [ctypes.c_uint16]
        lib.kvship_server_create.restype = ctypes.c_void_p
        lib.kvship_server_port.argtypes = [ctypes.c_void_p]
        lib.kvship_server_port.restype = ctypes.c_int
        lib.kvship_server_destroy.argtypes = [ctypes.c_void_p]
        lib.kvship_server_destroy.restype = None
        lib.kvship_register.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_uint64, ctypes.c_uint64,
        ]
        lib.kvship_register.restype = ctypes.c_int
        lib.kvship_register2.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_uint64, ctypes.c_uint64,
        ]
        lib.kvship_register2.restype = ctypes.c_int
        lib.kvship_unregister.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.kvship_unregister.restype = ctypes.c_int
        lib.kvship_registered_bytes.argtypes = [ctypes.c_void_p]
        lib.kvship_registered_bytes.restype = ctypes.c_uint64
        lib.kvship_registered_count.argtypes = [ctypes.c_void_p]
        lib.kvship_registered_count.restype = ctypes.c_uint64
        lib.kvship_expired_count.argtypes = [ctypes.c_void_p]
        lib.kvship_expired_count.restype = ctypes.c_uint64
        lib.kvship_pull.argtypes = [
            ctypes.c_char_p, ctypes.c_uint16, ctypes.c_char_p,
            ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
            ctypes.POINTER(ctypes.c_uint64),
        ]
        lib.kvship_pull.restype = ctypes.c_int
        lib.kvship_buf_free.argtypes = [ctypes.POINTER(ctypes.c_uint8)]
        lib.kvship_buf_free.restype = None
        lib.kvship_free_notify.argtypes = [
            ctypes.c_char_p, ctypes.c_uint16, ctypes.c_char_p,
        ]
        lib.kvship_free_notify.restype = ctypes.c_int
        lib.kvship_renew.argtypes = [
            ctypes.c_char_p, ctypes.c_uint16, ctypes.c_char_p, ctypes.c_uint64,
        ]
        lib.kvship_renew.restype = ctypes.c_int
        lib.kvship_stat.argtypes = [
            ctypes.c_char_p, ctypes.c_uint16,
            ctypes.POINTER(ctypes.c_uint64),
        ]
        lib.kvship_stat.restype = ctypes.c_int
        _lib = lib
        return _lib
