"""KV-cache transfer layer: the framework's NIXL equivalent.

Pull-model block shipping with lease + free-notify semantics
(reference operations-vllm.md:18-47,155-160), implemented as a C++ core
(llmd_tpu/native/kvship.cpp) with a pure-Python fallback speaking the same
wire protocol.
"""

from llmd_tpu.kvtransfer.shipper import (  # noqa: F401
    DEFAULT_LEASE_MS,
    PullError,
    ShipperServer,
    free_notify,
    pull,
    renew,
    stat,
)
from llmd_tpu.kvtransfer.connector import (  # noqa: F401
    KVTransferConfig,
    TPUConnector,
)
