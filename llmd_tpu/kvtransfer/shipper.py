"""Shipper API: register / pull / renew / free KV byte bundles.

Prefers the native C++ core (llmd_tpu/native/kvship.cpp); falls back to a
pure-Python server/client speaking the identical length-prefixed wire
protocol, so mixed deployments interoperate. Semantics follow the reference
transfer layer (operations-vllm.md:18-47,155-160): pull model, leases with
consumer heartbeats, free-notify, reaper-based reclamation.
"""

from __future__ import annotations

import ctypes
import socket
import socketserver
import struct
import threading
import time

from llmd_tpu.kvtransfer import native

MAGIC = 0x4B565348  # "KVSH"
OP_PULL, OP_FREE, OP_RENEW, OP_STAT = 1, 2, 3, 4
ST_OK, ST_NOT_FOUND, ST_ERR = 0, 1, 2

# Reference default: 30s initial lease, heartbeat at 2/3 of the lease
# (operations-vllm.md:155-160).
DEFAULT_LEASE_MS = 30_000


class PullError(RuntimeError):
    def __init__(self, msg: str, status: int = ST_ERR) -> None:
        super().__init__(msg)
        self.status = status


# --------------------------------------------------------------------------- #
# Server


class ShipperServer:
    """Producer-side registry + TCP server.

    One instance per engine process; serves both metadata and KV bytes (the
    reference's TPU_SIDE_CHANNEL_PORT / TPU_KV_TRANSFER_PORT pair folded
    into one port).
    """

    def __init__(self, port: int = 0) -> None:
        self._native = native.load()
        self._handle = None
        self._py = None
        if self._native is not None:
            self._handle = self._native.kvship_server_create(port)
        if self._handle:
            self.port = self._native.kvship_server_port(self._handle)
            self.backend = "native"
        else:
            self._py = _PyServer(port)
            self.port = self._py.port
            self.backend = "python"

    def register(
        self,
        key: str,
        data,
        lease_ms: int = DEFAULT_LEASE_MS,
        header: bytes = b"",
    ) -> None:
        """Register a bundle as header+payload.

        ``data`` is bytes or anything exposing a C-contiguous buffer (e.g. a
        numpy array); the buffer-protocol path hands the raw pointer to the
        native server, which makes the single owning copy — no Python-side
        concat or intermediate copy of a multi-hundred-MB KV payload.
        """
        if self._handle is None and self._py is None:
            # Closed/crashed shipper: a clean error for the staging thread
            # to log — NOT an AttributeError that could leak upward and
            # take the engine step loop down with it.
            raise RuntimeError("shipper server is closed")
        if self._handle:
            mv = memoryview(data).cast("B")
            n = len(mv)
            if mv.readonly:  # bytes path (tests / small payloads): copy
                buf = (ctypes.c_uint8 * n).from_buffer_copy(mv)
            else:  # numpy path: zero-copy view of the array's buffer
                buf = (ctypes.c_uint8 * n).from_buffer(mv)
            dptr = ctypes.cast(buf, ctypes.POINTER(ctypes.c_uint8))
            hbuf = (ctypes.c_uint8 * max(len(header), 1)).from_buffer_copy(
                header or b"\0"
            )
            hptr = ctypes.cast(hbuf, ctypes.POINTER(ctypes.c_uint8))
            self._native.kvship_register2(
                self._handle, key.encode(), hptr, len(header), dptr, n, lease_ms
            )
        else:
            self._py.register(key, header + bytes(data), lease_ms)

    def unregister(self, key: str) -> bool:
        if self._handle:
            return self._native.kvship_unregister(self._handle, key.encode()) == 0
        return self._py.unregister(key) if self._py else False

    @property
    def registered_bytes(self) -> int:
        if self._handle:
            return self._native.kvship_registered_bytes(self._handle)
        return self._py.registered_bytes if self._py else 0

    @property
    def registered_count(self) -> int:
        if self._handle:
            return self._native.kvship_registered_count(self._handle)
        return self._py.registered_count if self._py else 0

    @property
    def expired_count(self) -> int:
        if self._handle:
            return self._native.kvship_expired_count(self._handle)
        return self._py.expired_count if self._py else 0

    def close(self) -> None:
        if self._handle:
            self._native.kvship_server_destroy(self._handle)
            self._handle = None
        elif self._py:
            self._py.close()
            self._py = None

    def __del__(self) -> None:  # best-effort
        try:
            self.close()
        # llmd: allow(broad-except) -- __del__ during interpreter teardown; nothing to surface to
        except Exception:
            pass


class _PyServer:
    """Pure-Python registry + threaded TCP server (protocol-identical)."""

    def __init__(self, port: int) -> None:
        self._entries: dict[str, tuple[bytes, float]] = {}  # llmd: guarded_by(_lock)
        self._lock = threading.Lock()
        self.expired_count = 0  # llmd: guarded_by(_lock)
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self) -> None:
                sock = self.request
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                sock.settimeout(60.0)  # idle-connection bound
                try:
                    while True:
                        hdr = _recv_exact(sock, 7)
                        if hdr is None:
                            return
                        magic, op, keylen = struct.unpack("<IBH", hdr)
                        if magic != MAGIC:
                            return
                        key = b""
                        if keylen:
                            key = _recv_exact(sock, keylen)
                            if key is None:
                                return
                        outer._dispatch(sock, op, key.decode())
                except (ConnectionError, OSError, struct.error):
                    return

        class Srv(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._srv = Srv(("0.0.0.0", port), Handler)
        self.port = self._srv.server_address[1]
        self._thread = threading.Thread(target=self._srv.serve_forever, daemon=True)
        self._thread.start()
        self._stop = threading.Event()
        self._reaper = threading.Thread(target=self._reap_loop, daemon=True)
        self._reaper.start()

    def _dispatch(self, sock: socket.socket, op: int, key: str) -> None:
        if op == OP_PULL:
            with self._lock:
                entry = self._entries.get(key)
            if entry is None:
                sock.sendall(struct.pack("<BQ", ST_NOT_FOUND, 0))
            else:
                sock.sendall(struct.pack("<BQ", ST_OK, len(entry[0])))
                sock.sendall(entry[0])
        elif op == OP_FREE:
            ok = self.unregister(key)
            sock.sendall(struct.pack("<BQ", ST_OK if ok else ST_NOT_FOUND, 0))
        elif op == OP_RENEW:
            raw = _recv_exact(sock, 8)
            if raw is None:
                return
            (lease_ms,) = struct.unpack("<Q", raw)
            with self._lock:
                entry = self._entries.get(key)
                if entry is not None:
                    self._entries[key] = (entry[0], time.monotonic() + lease_ms / 1e3)
            st = ST_OK if entry is not None else ST_NOT_FOUND
            sock.sendall(struct.pack("<BQ", st, 0))
        elif op == OP_STAT:
            with self._lock:
                n = len(self._entries)
                b = sum(len(v[0]) for v in self._entries.values())
            sock.sendall(struct.pack("<BQQQ", ST_OK, 16, n, b))

    def register(self, key: str, data: bytes, lease_ms: int) -> None:
        with self._lock:
            self._entries[key] = (data, time.monotonic() + lease_ms / 1e3)

    def unregister(self, key: str) -> bool:
        with self._lock:
            return self._entries.pop(key, None) is not None

    @property
    def registered_bytes(self) -> int:
        with self._lock:
            return sum(len(v[0]) for v in self._entries.values())

    @property
    def registered_count(self) -> int:
        with self._lock:
            return len(self._entries)

    def _reap_loop(self) -> None:
        while not self._stop.wait(0.5):
            now = time.monotonic()
            with self._lock:
                dead = [k for k, (_, dl) in self._entries.items() if dl <= now]
                for k in dead:
                    del self._entries[k]
                    self.expired_count += 1

    def close(self) -> None:
        self._stop.set()
        self._srv.shutdown()
        self._srv.server_close()


def _recv_exact(sock: socket.socket, n: int) -> bytes | None:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf


# --------------------------------------------------------------------------- #
# Client ops (one connection per op, mirroring the native client)


def _py_roundtrip(
    host: str, port: int, op: int, key: str, lease_ms: int = 0
) -> tuple[int, bytes]:
    with socket.create_connection((host, port), timeout=30.0) as sock:
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        kb = key.encode()
        msg = struct.pack("<IBH", MAGIC, op, len(kb)) + kb
        if op == OP_RENEW:
            msg += struct.pack("<Q", lease_ms)
        sock.sendall(msg)
        hdr = _recv_exact(sock, 9)
        if hdr is None:
            raise PullError("connection closed mid-response")
        st, length = struct.unpack("<BQ", hdr)
        payload = b""
        if length:
            payload = _recv_exact(sock, length)
            if payload is None:
                raise PullError("connection closed mid-payload")
        return st, payload


def pull(host: str, port: int, key: str) -> bytes:
    """One-sided pull of a registered bundle. Raises PullError if absent."""
    lib = native.load()
    if lib is not None:
        out = ctypes.POINTER(ctypes.c_uint8)()
        out_len = ctypes.c_uint64()
        st = lib.kvship_pull(
            host.encode(), port, key.encode(),
            ctypes.byref(out), ctypes.byref(out_len),
        )
        if st != ST_OK:
            raise PullError(
                f"pull {key!r} from {host}:{port} -> status {st}", status=st
            )
        try:
            return ctypes.string_at(out, out_len.value)
        finally:
            lib.kvship_buf_free(out)
    st, payload = _py_roundtrip(host, port, OP_PULL, key)
    if st != ST_OK:
        raise PullError(
            f"pull {key!r} from {host}:{port} -> status {st}", status=st
        )
    return payload


def pull_many(host: str, port: int, keys: list[str]) -> dict[str, bytes]:
    """Pull several bundles over ONE connection (pipelined requests).

    The federation restore path fetches every store-held page of a
    prefix run in one shot: one TCP connect + N request/response rounds
    on the same socket instead of N fresh connections (the per-page GET
    was the dominant fixed cost of a multi-page store hit). Keys the
    server does not hold are simply absent from the result; transport
    errors raise PullError (the caller's miss/degrade policy decides).

    Speaks the standard per-request wire protocol, so it works against
    both the python and native servers (their handlers loop on the
    connection); if the peer closes between requests, the remaining keys
    fall back to one-shot pulls.
    """
    out: dict[str, bytes] = {}
    if not keys:
        return out
    remaining = list(keys)
    try:
        with socket.create_connection((host, port), timeout=30.0) as sock:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            while remaining:
                key = remaining[0]
                kb = key.encode()
                sock.sendall(
                    struct.pack("<IBH", MAGIC, OP_PULL, len(kb)) + kb
                )
                hdr = _recv_exact(sock, 9)
                if hdr is None:
                    raise ConnectionError("peer closed mid-batch")
                st, length = struct.unpack("<BQ", hdr)
                payload = b""
                if length:
                    payload = _recv_exact(sock, length)
                    if payload is None:
                        raise ConnectionError("peer closed mid-payload")
                if st == ST_OK:
                    out[key] = payload
                remaining.pop(0)
    except (ConnectionError, OSError):
        # Mixed/native deployments that close per request: finish the
        # remainder as ordinary one-shot pulls (absent keys stay absent).
        for key in remaining:
            try:
                out[key] = pull(host, port, key)
            except PullError as e:
                if e.status != ST_NOT_FOUND:
                    raise
    return out


def pull_wait(
    host: str, port: int, key: str, deadline: float, poll_s: float = 0.01
) -> bytes:
    """Pull, retrying while the key is NOT-YET-registered (a producer that
    streams chunks as it stages them registers each one when its download
    completes). Hard errors and the ``deadline`` (monotonic) abort."""
    while True:
        try:
            return pull(host, port, key)
        except PullError as e:
            if e.status != ST_NOT_FOUND or time.monotonic() >= deadline:
                raise
        time.sleep(poll_s)


def free_notify(host: str, port: int, key: str) -> bool:
    """Tell the producer the bundle landed; it may reclaim the memory."""
    lib = native.load()
    if lib is not None:
        return lib.kvship_free_notify(host.encode(), port, key.encode()) == ST_OK
    try:
        st, _ = _py_roundtrip(host, port, OP_FREE, key)
    except (OSError, PullError):
        return False
    return st == ST_OK


def renew(host: str, port: int, key: str, lease_ms: int = DEFAULT_LEASE_MS) -> bool:
    """Consumer heartbeat: extend the producer-side lease."""
    lib = native.load()
    if lib is not None:
        return lib.kvship_renew(host.encode(), port, key.encode(), lease_ms) == ST_OK
    try:
        st, _ = _py_roundtrip(host, port, OP_RENEW, key, lease_ms)
    except (OSError, PullError):
        return False
    return st == ST_OK


def stat(host: str, port: int) -> tuple[int, int]:
    """(registered_count, registered_bytes) of a remote shipper."""
    lib = native.load()
    if lib is not None:
        arr = (ctypes.c_uint64 * 2)()
        if lib.kvship_stat(host.encode(), port, arr) != ST_OK:
            raise PullError(f"stat {host}:{port} failed")
        return arr[0], arr[1]
    st, payload = _py_roundtrip(host, port, OP_STAT, "")
    if st != ST_OK or len(payload) != 16:
        raise PullError(f"stat {host}:{port} failed")
    n, b = struct.unpack("<QQ", payload)
    return n, b
