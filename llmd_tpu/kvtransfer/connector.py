"""TPUConnector: wires the KV shipper into the engine (P/D disaggregation).

Mirrors the reference's TPU connector family (tpu_inference TPUConnector /
TPUConnectorHMA, pd-disaggregation/modelserver/tpu/*/vllm/patch-decode.yaml;
transfer semantics per operations-vllm.md:18-47):

  producer (prefill engine): when a request tagged ``do_remote_decode``
  finishes, the KV pages covering its full prompt pages are staged
  HBM -> host (one device_get) and registered with the local ShipperServer
  under the request id; the response carries ``kv_transfer_params`` with the
  shipper's address.

  consumer (decode engine): a request arriving with ``kv_transfer_params``
  pulls the bundle, stages host -> HBM into freshly allocated pages, and
  commits each page's chained content hash into the local prefix cache —
  so the ordinary automatic-prefix-cache path "hits" the remote KV and only
  the partial last page is recomputed. Pull failure degrades per
  ``kv_load_failure_policy``: "recompute" (prefill locally, the reference's
  lenient mode) or "fail" (surface an error; recommended in the reference,
  operations-vllm.md:118-139).

This cache-seeding design is deliberately TPU-first: there is no one-sided
device RDMA into live HBM on TPU, so instead of emulating NIXL's
write-into-running-engine, transfers land as ordinary (idempotent) cache
inserts that never touch the jitted step.
"""

from __future__ import annotations

import dataclasses
import logging
import struct
import threading
import uuid
from typing import Any

import numpy as np

from llmd_tpu.engine.kv_cache import PageAllocator, page_hashes_for_tokens
from llmd_tpu.kvtransfer import shipper as shipper_mod
from llmd_tpu.kvtransfer.shipper import DEFAULT_LEASE_MS, PullError, ShipperServer

log = logging.getLogger(__name__)

_HDR = struct.Struct("<4sBHIIIII")  # magic, ver, dtype_len, L, n, K, page, inner
_MAGIC = b"KVPG"


@dataclasses.dataclass
class KVTransferConfig:
    role: str  # "kv_producer" | "kv_consumer" | "kv_both"
    host: str = "127.0.0.1"  # address advertised to consumers
    port: int = 9100  # TPU_KV_TRANSFER_PORT; 0 = ephemeral
    lease_ms: int = DEFAULT_LEASE_MS
    load_failure_policy: str = "recompute"  # "recompute" | "fail"

    @property
    def is_producer(self) -> bool:
        return self.role in ("kv_producer", "kv_both")

    @property
    def is_consumer(self) -> bool:
        return self.role in ("kv_consumer", "kv_both")


class KVLoadError(RuntimeError):
    """Remote KV pull failed and policy is 'fail'."""


@dataclasses.dataclass
class PulledBundle:
    """A fetched-and-validated KV bundle awaiting engine-thread apply."""

    pages: np.ndarray  # [L, n_full, K, page, 2D]
    hashes: list[bytes]  # chained content hashes, one per page
    nbytes: int
    host: str
    port: int
    key: str


def pack_header(pages: np.ndarray) -> bytes:
    """Bundle header for a [L, n, K, page, 2D] page array.

    The dtype travels by NAME ('bfloat16', 'float32', ...): extension
    dtypes like ml_dtypes.bfloat16 have an anonymous .str ('<V2') that
    does not round-trip through np.dtype(), while np.dtype(name) resolves
    both builtins and registered extension dtypes."""
    dt = pages.dtype.name.encode()
    L, n, K, page, inner = pages.shape
    return _HDR.pack(_MAGIC, 1, len(dt), L, n, K, page, inner) + dt


def pack_pages(pages: np.ndarray) -> bytes:
    """Full serialized bundle (tests / small payloads; the production path
    registers header + raw buffer separately to avoid the concat copy)."""
    return pack_header(pages) + pages.tobytes()


def unpack_pages(blob: bytes) -> np.ndarray:
    magic, ver, dlen, L, n, K, page, inner = _HDR.unpack_from(blob, 0)
    if magic != _MAGIC or ver != 1:
        raise PullError("bad KV bundle header")
    off = _HDR.size + dlen
    dt = np.dtype(blob[_HDR.size : off].decode())
    arr = np.frombuffer(blob, dtype=dt, offset=off)
    return arr.reshape(L, n, K, page, inner)


class TPUConnector:
    """Engine-side connector; one per engine process."""

    def __init__(self, cfg: KVTransferConfig, runner, allocator: PageAllocator) -> None:
        self.cfg = cfg
        self.runner = runner
        self.allocator = allocator
        if cfg.is_consumer and not allocator.enable_prefix_caching:
            # The import path lands remote KV as prefix-cache seeds; with
            # caching off every transfer would be paid for zero benefit.
            raise ValueError(
                "kv_consumer role requires enable_prefix_caching=True"
            )
        self.server: ShipperServer | None = None
        if cfg.is_producer:
            self.server = ShipperServer(cfg.port)
            log.info(
                "kvship producer listening on :%d (%s backend)",
                self.server.port,
                self.server.backend,
            )
        # transfer metrics
        self.exported_requests = 0
        self.exported_bytes = 0
        self.imported_requests = 0
        self.imported_bytes = 0
        self.import_failures = 0

    # ------------------------------------------------------------------ #
    # producer side

    def wants_export(self, req) -> bool:
        return bool(
            self.cfg.is_producer
            and self.server is not None
            and req.kv_transfer_params
            and req.kv_transfer_params.get("do_remote_decode")
        )

    def export_finished(self, req) -> dict[str, Any] | None:
        """Stage + register a finished producer request's prompt KV.

        Must run while ``req.block_ids`` is still live (the engine calls it
        from the scheduler's finish hook, before page release).
        """
        page = self.allocator.page_size
        n_full = req.num_prompt_tokens // page
        if (
            n_full == 0
            or len(req.block_ids) < n_full
            or req.num_computed_tokens < n_full * page
        ):
            return None
        # Server-unique key: never the raw (client-controllable) request id,
        # so colliding x-request-id headers can't cross-wire two exports.
        key = f"{req.request_id}:{uuid.uuid4().hex[:12]}"
        # The device_get runs on the engine thread by design: the pages must
        # be read before the allocator can reuse them. Everything after is a
        # single memcpy into the server's owning buffer (no Python-side
        # concat of the payload).
        pages = np.ascontiguousarray(self.runner.gather_pages(req.block_ids[:n_full]))
        header = pack_header(pages)
        # Extension dtypes (bfloat16: isbuiltin == 2, "registered user
        # type") don't expose the buffer protocol the zero-copy register
        # path needs; a same-memory uint8 view does.
        payload = pages if pages.dtype.isbuiltin == 1 else pages.view(np.uint8)
        self.server.register(key, payload, self.cfg.lease_ms, header=header)
        self.exported_requests += 1
        self.exported_bytes += len(header) + pages.nbytes
        return {
            "remote_host": self.cfg.host,
            "remote_port": self.server.port,
            "remote_key": key,
            "num_full_pages": n_full,
            "page_size": page,
        }

    # ------------------------------------------------------------------ #
    # consumer side

    def wants_import(self, params: dict | None) -> bool:
        return bool(self.cfg.is_consumer and params and params.get("remote_host"))

    def fetch_remote(self, prompt_token_ids: list[int], params: dict) -> PulledBundle:
        """Network half of an import: pull + validate the bundle.

        Thread-safe (touches no engine state) — the async serving layer runs
        it on an executor so a slow producer never head-of-line-blocks the
        engine step thread.
        """
        page = self.allocator.page_size
        if params.get("page_size") != page:
            raise ValueError(
                f"page_size mismatch: producer {params.get('page_size')} "
                f"vs consumer {page}"
            )
        n_full = int(params["num_full_pages"])
        hashes = page_hashes_for_tokens(prompt_token_ids, page)
        if len(hashes) < n_full:
            raise ValueError(
                f"producer sent {n_full} pages but prompt has only "
                f"{len(hashes)} full pages"
            )
        host, port, key = params["remote_host"], int(params["remote_port"]), params["remote_key"]
        blob = shipper_mod.pull(host, port, key)
        pages = unpack_pages(blob)
        if pages.shape[1] != n_full:
            raise ValueError(
                f"bundle holds {pages.shape[1]} pages, expected {n_full}"
            )
        want_dtype = np.dtype(self.runner.kv_cache.dtype)
        if pages.dtype != want_dtype:
            # Never silently cast transferred KV: the P/D invariance
            # guarantee is byte-exact numerics.
            raise ValueError(
                f"KV dtype mismatch: producer {pages.dtype} vs consumer {want_dtype}"
            )
        return PulledBundle(
            pages=pages, hashes=hashes[:n_full], nbytes=len(blob),
            host=host, port=port, key=key,
        )

    def fetch_remote_policy(
        self, prompt_token_ids: list[int], params: dict
    ) -> "PulledBundle | None":
        """fetch_remote with the load-failure policy applied.

        Returns None on policy='recompute' failure; raises KVLoadError on
        policy='fail' (operations-vllm.md:118-139).
        """
        try:
            return self.fetch_remote(prompt_token_ids, params)
        except (PullError, OSError, ValueError, KeyError, TypeError, struct.error) as e:
            # struct.error: truncated header; TypeError: garbage dtype string
            # -- a corrupt/foreign bundle must hit the policy, not escape.
            self.import_failures += 1
            if self.cfg.load_failure_policy == "fail":
                raise KVLoadError(str(e)) from e
            log.warning("remote KV load failed, recomputing locally: %s", e)
            return None

    def apply_bundle(
        self, prompt_token_ids: list[int], bundle: "PulledBundle"
    ) -> int:
        """Engine-thread half: seed the local prefix cache with the bundle.

        Allocator + device scatter only (fast); the free-notify to the
        producer is fired on a background thread. Failures (e.g. no free
        pages under pressure) degrade to local recompute.
        """
        from llmd_tpu.engine.kv_cache import NoFreePagesError

        page = self.allocator.page_size
        hashes = bundle.hashes
        n_full = len(hashes)
        # Skip a leading run already cached locally (idempotent re-imports,
        # shared prefixes). Only a prefix run is usable anyway.
        skip = 0
        while skip < n_full and self.allocator.has_cached(hashes[skip]):
            skip += 1
        adopted = 0
        if skip < n_full:
            want = bundle.pages[:, skip:]
            try:
                page_ids = self.allocator.allocate(want.shape[1])
            except NoFreePagesError as e:
                self.import_failures += 1
                log.warning("no free pages for KV import, recomputing: %s", e)
                self._notify_free_async(bundle)
                return 0
            self.runner.scatter_pages(page_ids, want)
            parent = None if skip == 0 else hashes[skip - 1]
            for i, pid in enumerate(page_ids):
                idx = skip + i
                chunk = prompt_token_ids[idx * page : (idx + 1) * page]
                self.allocator.commit_page(pid, hashes[idx], chunk, parent)
                parent = hashes[idx]
            # Drop our references: pages stay cached (ref 0) for the
            # prefix-cache hit when this request is scheduled.
            self.allocator.free(page_ids)
            adopted = len(page_ids)
        self.imported_requests += 1
        self.imported_bytes += bundle.nbytes
        self._notify_free_async(bundle)
        return adopted

    def import_for_prompt(self, prompt_token_ids: list[int], params: dict) -> int:
        """Synchronous fetch + apply (offline engine path and tests)."""
        bundle = self.fetch_remote_policy(prompt_token_ids, params)
        if bundle is None:
            return 0
        return self.apply_bundle(prompt_token_ids, bundle)

    @staticmethod
    def _notify_free_async(bundle: "PulledBundle") -> None:
        threading.Thread(
            target=shipper_mod.free_notify,
            args=(bundle.host, bundle.port, bundle.key),
            daemon=True,
        ).start()

    # ------------------------------------------------------------------ #

    def stats(self) -> dict[str, int]:
        out = {
            "exported_requests": self.exported_requests,
            "exported_bytes": self.exported_bytes,
            "imported_requests": self.imported_requests,
            "imported_bytes": self.imported_bytes,
            "import_failures": self.import_failures,
        }
        if self.server is not None:
            out["registered_count"] = self.server.registered_count
            out["registered_bytes"] = self.server.registered_bytes
            out["expired_count"] = self.server.expired_count
        return out

    def close(self) -> None:
        if self.server is not None:
            self.server.close()
            self.server = None
