"""TPUConnector: wires the KV shipper into the engine (P/D disaggregation).

Mirrors the reference's TPU connector family (tpu_inference TPUConnector /
TPUConnectorHMA, pd-disaggregation/modelserver/tpu/*/vllm/patch-decode.yaml;
transfer semantics per operations-vllm.md:18-47):

  producer (prefill engine): when a request tagged ``do_remote_decode``
  finishes, the KV pages covering its full prompt pages are staged
  HBM -> host (one device_get) and registered with the local ShipperServer
  under the request id; the response carries ``kv_transfer_params`` with the
  shipper's address.

  consumer (decode engine): a request arriving with ``kv_transfer_params``
  pulls the bundle, stages host -> HBM into freshly allocated pages, and
  commits each page's chained content hash into the local prefix cache —
  so the ordinary automatic-prefix-cache path "hits" the remote KV and only
  the partial last page is recomputed. Pull failure degrades per
  ``kv_load_failure_policy``: "recompute" (prefill locally, the reference's
  lenient mode) or "fail" (surface an error; recommended in the reference,
  operations-vllm.md:118-139).

This cache-seeding design is deliberately TPU-first: there is no one-sided
device RDMA into live HBM on TPU, so instead of emulating NIXL's
write-into-running-engine, transfers land as ordinary (idempotent) cache
inserts that never touch the jitted step.
"""

from __future__ import annotations

import collections
import dataclasses
import logging
import os
import struct
import threading
import time
import uuid
import weakref
import zlib
from typing import Any

import numpy as np

from llmd_tpu import faults
from llmd_tpu.engine.kv_cache import PageAllocator, page_hashes_for_tokens
from llmd_tpu.kvtransfer import shipper as shipper_mod
from llmd_tpu.kvtransfer.shipper import DEFAULT_LEASE_MS, PullError, ShipperServer

log = logging.getLogger(__name__)

_HDR = struct.Struct("<4sBHIIIII")  # magic, ver, dtype_len, L, n, K, page, inner
_MAGIC = b"KVPG"
# Version 2 appends a CRC32 of everything after the dtype name (scales +
# payload for q8, payload for exact) right after the name; version-1
# bundles (no CRC) still parse — header-versioned compatibility.
_CRC = struct.Struct("<I")
# Version 3 (the group-framed stream wire): between the dtype name and
# the CRC rides a group extension — (group_idx, num_groups, layer0) —
# and the header's L field carries the layers in THIS group only. The
# CRC coverage is unchanged (everything after it: scales + payload).
# Readers here accept v1/v2/v3; the LLMD_KV_STREAM_COMPAT_V2 pin keeps
# producers on the v2 monolithic-layer framing for reader-first rolling
# deploys (same discipline as LLMD_KV_BUNDLE_COMPAT_V1).
_GRP = struct.Struct("<HHH")


@dataclasses.dataclass
class KVTransferConfig:
    role: str  # "kv_producer" | "kv_consumer" | "kv_both"
    host: str = "127.0.0.1"  # address advertised to consumers
    port: int = 9100  # TPU_KV_TRANSFER_PORT; 0 = ephemeral
    lease_ms: int = DEFAULT_LEASE_MS
    load_failure_policy: str = "recompute"  # "recompute" | "fail"
    # Pages per transfer chunk. Exports are staged HBM -> host and
    # registered chunk-by-chunk on a background thread, so the producer's
    # response (and the consumer's pull+upload pipeline) starts after the
    # FIRST chunk instead of after the whole bundle; the consumer's
    # device uploads then overlap the producer's remaining downloads.
    chunk_pages: int = 8
    # Transfer encoding: "auto" keeps the pool dtype byte-exact (the P/D
    # invariance default); "int8" quantizes each (token, head) row to
    # int8 + an f16 scale ON DEVICE before staging — both staging legs
    # move half the bytes (the TTFT floor when staging-bandwidth-bound),
    # at ~0.4% per-row error. "adaptive" measures both encodings on THIS
    # link (per-chunk staging throughput in ORIGINAL bytes, EWMA, with
    # periodic re-probes) and picks the faster per export: whether int8's
    # halved bytes beat its quantize+scales overhead depends entirely on
    # the link (BENCH r3 vs r4 measured opposite winners). Producer-
    # driven; the consumer dequantizes into its pool dtype.
    transfer_dtype: str = "auto"  # "auto" | "int8" | "adaptive"
    # Single-host xPyD: consumers claim in-process producers' device
    # snapshots directly (no host staging, no wire bytes).
    local_fastpath: bool = True
    # With the fast path enabled, the staging thread grants an in-process
    # consumer this long to claim the device snapshots before starting
    # HBM->host downloads. A local claim lands within ~ms of the export;
    # without the grace the thread races ahead and the first chunk's
    # download (hundreds of ms of device-queue + host-link traffic)
    # contends with the consumer's decode steps for pure waste. Remote
    # consumers pay at most this delay on a multi-second staging path.
    local_claim_grace_ms: int = 100
    # Layer-streamed transfer (the v3 group-framed wire): exports split
    # into this many contiguous layer groups, staged and shipped
    # group-major so the consumer's import pipelines per group —
    # fetch -> CRC -> scatter of group g overlaps the wire transfer of
    # g+1, pages are batch-allocated once up front, and the decode-side
    # request becomes schedulable as soon as group 0 is resident
    # (docs/architecture/kv-cache.md "layer-streamed import"). Clamped
    # to the model's layer count; 1 (or the LLMD_KV_STREAM_COMPAT_V2 /
    # LLMD_KV_BUNDLE_COMPAT_V1 pins, or a multi-host runner — its
    # lockstep gather stays monolithic) disables grouping and restores
    # the v2 chunk framing byte-for-byte.
    stream_groups: int = 4

    @property
    def is_producer(self) -> bool:
        return self.role in ("kv_producer", "kv_both")

    @property
    def is_consumer(self) -> bool:
        return self.role in ("kv_consumer", "kv_both")


class KVLoadError(RuntimeError):
    """Remote KV pull failed and policy is 'fail'."""


class KVCorruptionError(PullError):
    """Bundle payload failed its CRC32 — corrupted in flight or at rest.

    A PullError subclass so every existing policy path (recompute/fail)
    treats it as a failed pull; the distinct type lets the connector
    count CRC rejections separately (kv_bundle_crc_failures_total)."""


def _pad_chunk_ids(ids: list[int], cp: int) -> list[int]:
    """Pad a chunk's page-id slice to ``cp`` by repeating the last real
    id: producers pad tail chunks by repeating the last real PAGE, so
    aiming the pad slots at the same id makes the duplicate write
    idempotent."""
    if len(ids) < cp:
        return ids + [ids[-1]] * (cp - len(ids))
    return ids


@dataclasses.dataclass
class PulledBundle:
    """A fetched-and-validated KV bundle awaiting engine-thread apply."""

    pages: np.ndarray | None  # [L, n_full, K, page, 2D]; None => chunked
    hashes: list[bytes]  # chained content hashes, one per page
    nbytes: int
    host: str
    port: int
    key: str
    keys: list[str] = dataclasses.field(default_factory=list)  # chunk keys
    # Pipelined import: chunks already uploaded to device scratch by the
    # fetch thread ([L, chunk_pages, K, page, 2D] each, canonical heads).
    device_chunks: list = dataclasses.field(default_factory=list)
    # Host-side chunk arrays (kept for the partial-overlap fallback; the
    # common pipelined apply reads only device_chunks).
    np_chunks: list = dataclasses.field(default_factory=list)
    chunk_pages: int = 0
    # Multi-host STREAMED import: pages pre-allocated by the fetch
    # thread and already lockstep-scattered chunk-by-chunk as pulls
    # landed (overlapping wire and broadcast legs); apply only commits
    # hashes. Covers pages [start_page, start_page + len(stream_ids)).
    # The bundle is the ownership root until apply_bundle/release_bundle
    # frees the refs (the leak sanitizer tracks fetched bundles too).
    stream_ids: list | None = None  # llmd: owns(pages)
    # Prompt-page index of the first page in the first PULLED chunk
    # (byte diet: producer-skipped pages + consumer-skipped chunks).
    start_page: int = 0
    # Sliding-layer section of a ring export (kv_swa_ring): the trailing
    # in-window ring pages [L_swa, swa_pages, K, page, 2D] and the logical
    # prompt page the section starts at. host array, device snapshot
    # (local fastpath), or None.
    swa_pages_np: np.ndarray | None = None
    swa_device: Any = None
    swa_start_page: int = 0
    swa_count: int = 0

    @staticmethod
    def _dequant_chunk(c) -> np.ndarray:
        if isinstance(c, np.ndarray):
            return c
        q8, scales = c
        *lead, d2 = q8.shape
        qf = q8.astype(np.float32).reshape(*lead, 2, d2 // 2)
        out = qf * scales[..., None].astype(np.float32)
        return out.reshape(*lead, d2)

    def host_pages(self, n_full: int) -> np.ndarray:
        """Materialize the [L, n_full - start_page, ...] host view of the
        PULLED pages (fallback path only — this concat is deliberately
        NOT done on the fetch critical path). int8-transferred chunks
        dequantize on host here."""
        if self.pages is not None:
            return self.pages
        chunks = [self._dequant_chunk(c) for c in self.np_chunks]
        return np.concatenate(chunks, axis=1)[:, : n_full - self.start_page]


def chunk_key(key: str, j: int) -> str:
    """Shipper key of one export chunk (the ONE place the scheme lives:
    producer registration, consumer pulls, free-notify, and the sidecar
    heartbeat all derive from here)."""
    return f"{key}:c{j}"


def group_key(key: str, g: int, j: int) -> str:
    """Shipper key of one (layer-group, page-chunk) CELL of a v3
    group-framed export. Group-major registration order (g0c0, g0c1, ...,
    g1c0, ...) is the streaming contract: the consumer pulls in the same
    order and becomes schedulable once group 0 is resident."""
    return f"{key}:g{g}:c{j}"


def layer_groups(num_layers: int, groups: int) -> list[tuple[int, int]]:
    """The (layer0, n_layers) split of ``num_layers`` into ``groups``
    contiguous groups — derived IDENTICALLY by producer and consumer
    from (L, num_groups) alone, so the wire never has to carry a layer
    map. Uneven splits front-load the remainder (first groups one layer
    larger), keeping group 0 — the admission gate — never the runt."""
    groups = max(1, min(groups, num_layers))
    base, rem = divmod(num_layers, groups)
    out, l0 = [], 0
    for g in range(groups):
        lg = base + (1 if g < rem else 0)
        out.append((l0, lg))
        l0 += lg
    return out


def swa_key(key: str) -> str:
    """Shipper key of a ring export's sliding-layer section (the trailing
    in-window ring pages a kv_swa_ring producer ships alongside the
    full-group chunks)."""
    return f"{key}:swa"


def transfer_keys(params: dict) -> list[str]:
    """Every shipper key a transfer's lease heartbeat must renew (chunked
    exports register one key per chunk; group-framed exports one per
    (layer-group, chunk) cell; legacy bundles just one; ring exports add
    the sliding-layer section)."""
    key = params.get("remote_key", "")
    n = int(params.get("num_chunks", 0) or 0)
    ng = int(params.get("num_groups", 0) or 0)
    if n <= 0:
        keys = [key]
    elif ng > 1:
        keys = [
            group_key(key, g, j) for g in range(ng) for j in range(n)
        ]
    else:
        keys = [chunk_key(key, j) for j in range(n)]
    if int(params.get("swa_pages", 0) or 0) > 0:
        keys.append(swa_key(key))
    return keys


def payload_crc(*parts) -> int:
    """CRC32 over the wire bytes after the dtype name (header-trailing
    scales block first for q8, then the payload). Parts are bytes or
    C-contiguous buffers (numpy arrays; bf16 callers pass a uint8 view,
    same as the register path)."""
    crc = 0
    for p in parts:
        crc = zlib.crc32(p, crc)
    return crc


def pack_header(
    pages: np.ndarray,
    crc: int | None = None,
    group: tuple[int, int, int] | None = None,
) -> bytes:
    """Bundle header for a [L, n, K, page, 2D] page array.

    The dtype travels by NAME ('bfloat16', 'float32', ...): extension
    dtypes like ml_dtypes.bfloat16 have an anonymous .str ('<V2') that
    does not round-trip through np.dtype(), while np.dtype(name) resolves
    both builtins and registered extension dtypes.

    With ``crc`` (CRC32 of the payload bytes) the header is version 2 and
    importers verify it; without, a version-1 header (legacy producers,
    or every producer under the ``LLMD_KV_BUNDLE_COMPAT_V1`` rollout
    pin — see ``_COMPAT_V1``). ``group=(g, num_groups, layer0)`` makes a
    version-3 group-framed header: the L dim is this group's layer count
    and the group extension rides between the name and the CRC."""
    dt = pages.dtype.name.encode()
    L, n, K, page, inner = pages.shape
    if group is not None:
        assert crc is not None, "group-framed headers always carry a CRC"
        return (
            _HDR.pack(_MAGIC, 3, len(dt), L, n, K, page, inner)
            + dt
            + _GRP.pack(*group)
            + _CRC.pack(crc)
        )
    if crc is None or _COMPAT_V1:
        return _HDR.pack(_MAGIC, 1, len(dt), L, n, K, page, inner) + dt
    return (
        _HDR.pack(_MAGIC, 2, len(dt), L, n, K, page, inner)
        + dt
        + _CRC.pack(crc)
    )


_Q8_PREFIX = "int8q:"

# Mixed-version rolling deploys: a not-yet-upgraded consumer rejects a
# version-2 header outright ("bad KV bundle header"), which would turn
# every P/D transfer into a recompute (or a hard failure under
# load_failure_policy='fail') while prefill and decode pods roll
# independently. Readers here accept both versions, so the safe order is
# reader-first: upgrade consumers, then producers, then drop this pin.
# Setting LLMD_KV_BUNDLE_COMPAT_V1=1 pins producers to the version-1
# wire format (no CRC) for the transition window.
_COMPAT_V1 = os.environ.get("LLMD_KV_BUNDLE_COMPAT_V1", "0") not in ("", "0")

# Same reader-first discipline for the v3 group-framed stream wire: a
# not-yet-upgraded consumer knows nothing of group keys and would time
# out pulling `key:c0` from a streaming producer (degrading every
# transfer to recompute). LLMD_KV_STREAM_COMPAT_V2=1 pins producers to
# the v2 monolithic-layer chunk framing until every consumer is
# upgraded; the v1 pin implies it (v1 has no CRC, v3 requires one).
_COMPAT_V2 = os.environ.get("LLMD_KV_STREAM_COMPAT_V2", "0") not in ("", "0")


def pack_header_q8(
    q8: np.ndarray,
    orig_dtype_name: str,
    crc: int | None = None,
    group: tuple[int, int, int] | None = None,
) -> bytes:
    """Header for an int8-quantized bundle: dtype travels as
    'int8q:<original>'; the f16 scales block follows the header (same
    register call), and its size is derivable from the dims. A version-2
    ``crc`` covers scales + payload (everything after the name); the
    ``LLMD_KV_BUNDLE_COMPAT_V1`` rollout pin downgrades to version 1.
    ``group`` makes a version-3 group-framed header (see
    :func:`pack_header`)."""
    dt = (_Q8_PREFIX + orig_dtype_name).encode()
    L, n, K, page, inner = q8.shape
    if group is not None:
        assert crc is not None, "group-framed headers always carry a CRC"
        return (
            _HDR.pack(_MAGIC, 3, len(dt), L, n, K, page, inner)
            + dt
            + _GRP.pack(*group)
            + _CRC.pack(crc)
        )
    if crc is None or _COMPAT_V1:
        return _HDR.pack(_MAGIC, 1, len(dt), L, n, K, page, inner) + dt
    return (
        _HDR.pack(_MAGIC, 2, len(dt), L, n, K, page, inner)
        + dt
        + _CRC.pack(crc)
    )


def _payload_offset(blob: bytes, ver: int, dlen: int) -> int:
    """Start of the post-name wire bytes; versions 2+ verify the CRC
    riding between the name (and, v3, the group extension) and the
    payload before anything decodes."""
    off = _HDR.size + dlen
    if ver < 2:
        return off
    if ver >= 3:
        off += _GRP.size
    (want,) = _CRC.unpack_from(blob, off)
    off += _CRC.size
    got = zlib.crc32(memoryview(blob)[off:])
    if got != want:
        raise KVCorruptionError(
            f"KV bundle CRC mismatch: header {want:#010x} vs payload "
            f"{got:#010x} ({len(blob)} wire bytes)"
        )
    return off


def bundle_group_info(blob: bytes) -> tuple[int, int, int]:
    """(group_idx, num_groups, layer0) of a wire blob — (0, 1, 0) for
    the pre-v3 monolithic-layer forms."""
    magic, ver, dlen, *_rest = _HDR.unpack_from(blob, 0)
    if magic != _MAGIC:
        raise PullError("bad KV bundle header")
    if ver < 3:
        return (0, 1, 0)
    return _GRP.unpack_from(blob, _HDR.size + dlen)


def unpack_pages_any(blob: bytes):
    """Decode either wire form. Returns ("exact", pages) or
    ("q8", q8, scales_f16, orig_dtype_name). v3 group-framed cells
    decode the same way (their L dim is the group's layer count; use
    :func:`bundle_group_info` for the framing)."""
    magic, ver, dlen, L, n, K, page, inner = _HDR.unpack_from(blob, 0)
    if magic != _MAGIC or ver not in (1, 2, 3):
        raise PullError("bad KV bundle header")
    name = blob[_HDR.size : _HDR.size + dlen].decode()
    if not name.startswith(_Q8_PREFIX):
        return ("exact", unpack_pages(blob))
    off = _payload_offset(blob, ver, dlen)
    orig = name[len(_Q8_PREFIX):]
    n_rows = L * n * K * page
    # 2 f16 scales per row: separate K-half and V-half quantization.
    scales = np.frombuffer(blob, dtype=np.float16, offset=off, count=n_rows * 2)
    scales = scales.reshape(L, n, K, page, 2)
    q8 = np.frombuffer(
        blob, dtype=np.int8, offset=off + n_rows * 4, count=n_rows * inner
    ).reshape(L, n, K, page, inner)
    return ("q8", q8, scales, orig)


def pack_pages(pages: np.ndarray) -> bytes:
    """Full serialized bundle (tests / small payloads; the production path
    registers header + raw buffer separately to avoid the concat copy)."""
    body = pages.tobytes()
    return pack_header(pages, crc=zlib.crc32(body)) + body


def unpack_pages(blob: bytes) -> np.ndarray:
    magic, ver, dlen, L, n, K, page, inner = _HDR.unpack_from(blob, 0)
    if magic != _MAGIC or ver not in (1, 2, 3):
        raise PullError("bad KV bundle header")
    off = _payload_offset(blob, ver, dlen)
    dt = np.dtype(blob[_HDR.size : _HDR.size + dlen].decode())
    arr = np.frombuffer(blob, dtype=dt, offset=off)
    return arr.reshape(L, n, K, page, inner)


def _faulty_pull(host: str, port: int, key: str, deadline: float | None = None):
    """Every consumer pull funnels through here: the kv.pull.* /
    kv.bundle.corrupt injection sites wrap the real wire call."""
    faults.delay("kv.pull.delay_ms", key)
    if faults.fires("kv.pull.drop", key):
        raise PullError(f"injected kv.pull.drop for {key!r}")
    if deadline is None:
        blob = shipper_mod.pull(host, port, key)
    else:
        blob = shipper_mod.pull_wait(host, port, key, deadline)
    return faults.corrupt("kv.bundle.corrupt", blob, key)


# In-process producer registry (single-host xPyD fast path): a consumer
# whose target (host, port) resolves to a producer connector in the SAME
# process claims its device snapshots directly — no HBM->host staging, no
# wire. The reference deploys single-host P/D as a first-class shape
# (guides/recipes/modelserver/base/single-host/pd/) where NIXL takes the
# same-node shortcut; TPU-first, the shortcut is a device-to-device copy
# (and on a real multi-chip host, an ICI copy).
_LOCAL_PRODUCERS: dict[int, "TPUConnector"] = {}
# Live in-process CONSUMER connectors (weak — a consumer dropped
# without close() must not pin the grace forever). Producers consult
# this before granting the local-claim grace: with no consumer in this
# process, no claim can ever arrive, and delaying staging would tax
# every remote pull for nothing.
_LOCAL_CONSUMERS: "weakref.WeakSet[TPUConnector]" = weakref.WeakSet()
_LOCAL_HOSTS = {"127.0.0.1", "localhost", "::1"}


def _lookup_local(host: str, port: int) -> "TPUConnector | None":
    conn = _LOCAL_PRODUCERS.get(port)
    if conn is None:
        return None
    if host in _LOCAL_HOSTS or host == conn.cfg.host:
        return conn
    return None


class KVStreamHandle:
    """Progress of one in-flight group-streamed import (consumer side).

    The serving layer submits the request to the engine as soon as
    :attr:`first_group` fires (the admission seam: a request whose KV is
    group-streaming is schedulable once its first layer group is
    resident); the engine parks it and finalizes — apply on success,
    recompute on failure — when :attr:`done` fires. Exactly one of
    take()/abandon() disposes of the fetched bundle: take() hands it to
    the engine's apply, abandon() (request aborted / serving layer died)
    releases it, whichever side loses the race.
    """

    def __init__(self, connector: "TPUConnector", params: dict) -> None:
        self.connector = connector
        self.params = params
        self.first_group = threading.Event()
        self.done = threading.Event()
        self._lock = threading.Lock()
        self._bundle: "PulledBundle | None" = None  # llmd: guarded_by(_lock)
        self._abandoned = False  # llmd: guarded_by(_lock)
        self.error: str | None = None
        self.t0 = time.monotonic()
        self.first_group_ms = 0.0
        # Optional admission signal for async serving layers: assigned
        # BEFORE the fetch is submitted (never mutated after), invoked
        # once from the fetch thread at first-group time — so the event
        # loop can await an asyncio.Event instead of parking an executor
        # thread on wait_admittable for the whole wire transfer.
        self.on_first_group = None

    def mark_first_group(self) -> None:
        if not self.first_group.is_set():
            self.first_group_ms = (time.monotonic() - self.t0) * 1e3
            self.first_group.set()
            cb = self.on_first_group
            if cb is not None:
                try:
                    cb()
                except RuntimeError:
                    pass  # event loop already closed (shutdown race)

    def resolve(self, bundle: "PulledBundle") -> None:
        """Fetch-thread success: publish the bundle (or release it if
        the request was abandoned while the stream was in flight)."""
        release = None
        with self._lock:
            if self._abandoned:
                release = bundle
            else:
                self._bundle = bundle
        self.mark_first_group()
        self.done.set()
        if release is not None:
            self.connector.release_bundle(release)

    def fail(self, error: str) -> None:
        """Fetch-thread failure: the parked request degrades to local
        recompute (policy='recompute') — waiters wake either way."""
        self.error = error
        self.mark_first_group()
        self.done.set()

    def take(self) -> "PulledBundle | None":
        with self._lock:
            bundle, self._bundle = self._bundle, None
            return bundle

    def abandon(self) -> None:
        with self._lock:
            self._abandoned = True
            bundle, self._bundle = self._bundle, None
        if bundle is not None:
            self.connector.release_bundle(bundle)

    def wait_admittable(self, timeout: float | None = None) -> bool:
        """Block (executor thread) until the import is admittable —
        first group resident, or resolved either way."""
        return self.first_group.wait(timeout)


# Bundle lifecycle (static-analysis.md): a fetched bundle stages pages
# (host chunks, device scratch, or stream-reserved pool pages) until
# exactly one of apply_bundle / apply_preload / release_bundle disposes
# of it — dropping a bundle on the floor strands the producer's lease
# and any stream-reserved pages. The leak sanitizer tracks outstanding
# bundles per connector with fetch backtraces.
# llmd: resource(bundles, recv=connector, acquire=fetch_remote|fetch_remote_policy, release=apply_bundle:arg2|apply_preload:arg2|release_bundle)
class TPUConnector:
    """Engine-side connector; one per engine process."""

    def __init__(self, cfg: KVTransferConfig, runner, allocator: PageAllocator) -> None:
        if cfg.transfer_dtype not in ("auto", "int8", "adaptive"):
            # A typo'd value would otherwise silently select the exact
            # path and the expected bandwidth halving never materializes.
            raise ValueError(
                f"kv transfer_dtype {cfg.transfer_dtype!r} not supported "
                "('auto', 'int8', or 'adaptive')"
            )
        if cfg.transfer_dtype == "adaptive" and runner.cfg.is_mla:
            # q8's K|V midpoint scale split is wrong for MLA latent rows
            # — same reason the explicit 'int8' below refuses. Adaptive
            # degrades to the exact encoding, LOUDLY (the operator asked
            # for link-measured convergence they will not get).
            log.warning(
                "transfer_dtype='adaptive' downgraded to 'auto' for an "
                "MLA model: the q8 wire form is unsafe for latent rows, "
                "so no encoding race will run"
            )
            cfg = dataclasses.replace(cfg, transfer_dtype="auto")
        if cfg.transfer_dtype == "int8" and runner.cfg.is_mla:
            # The K|V midpoint half-split is wrong for MLA latent rows
            # ([rank latent | rope] padded to 128 lanes): one shared amax
            # would crush the smaller sub-block — refuse rather than
            # silently degrade transferred-KV accuracy.
            raise ValueError(
                "kv transfer_dtype='int8' is not supported for MLA models "
                "(latent rows need their own scale layout); use 'auto'"
            )
        self.cfg = cfg
        self.runner = runner
        self.allocator = allocator
        if (
            cfg.is_consumer
            and not allocator.enable_prefix_caching
            and getattr(runner, "swa", None) is None
        ):
            # The import path lands remote KV as prefix-cache seeds; with
            # caching off every transfer would be paid for zero benefit.
            # (Ring engines are the exception: they import through the
            # PRELOAD path — pages handed straight to the request.)
            raise ValueError(
                "kv_consumer role requires enable_prefix_caching=True"
            )
        self.server: ShipperServer | None = None
        if cfg.is_producer:
            self.server = ShipperServer(cfg.port)
            log.info(
                "kvship producer listening on :%d (%s backend)",
                self.server.port,
                self.server.backend,
            )
        # Single-host xPyD fast path: pending device snapshots by key,
        # claimable by an in-process consumer (see _LOCAL_PRODUCERS).
        self._local_lock = threading.Lock()
        # Staging threads wait on this for the local-claim grace window;
        # claim_local notifies so a claim releases the wait immediately.
        self._local_cond = threading.Condition(self._local_lock)
        self._local_exports: dict[str, tuple] = {}  # llmd: guarded_by(_local_lock)
        self._local_claimed: set[str] = set()  # llmd: guarded_by(_local_lock)
        self._staging_active: set[str] = set()  # llmd: guarded_by(_local_lock)
        self._local_enabled = (
            cfg.local_fastpath
            and self.server is not None
            and not getattr(runner, "_multihost", False)
        )
        if self._local_enabled:
            _LOCAL_PRODUCERS[self.server.port] = self
        if cfg.is_consumer and cfg.local_fastpath:
            _LOCAL_CONSUMERS.add(self)
        # transfer metrics
        self.exported_requests = 0
        # Incremented from concurrent per-export staging threads.
        self.exported_bytes = 0  # llmd: guarded_by(_local_lock)
        self.imported_requests = 0
        self.imported_bytes = 0
        self.import_failures = 0
        self.local_imports = 0  # transfers served by the in-process path
        self.stream_imports = 0  # pipelined (streamed) imports
        # v3 group-framed stream: cells (layer-group x chunk) landed on
        # the consumer + the last import/export's first-group latency
        # (the admission-gate leg of the pipeline waterfall).
        self.stream_groups_total = 0  # llmd: guarded_by(_local_lock)
        self.last_first_group_ms = 0.0
        # Milestone timestamps (monotonic) of the LAST import — the
        # bench waterfall telescopes over these, so the per-stage splits
        # provably sum to the measured total.
        self.last_timeline: dict[str, float] = {}
        # Failure trails (the SLO layer's view of degradation): every
        # swallowed transfer failure lands in transfer_failures keyed by
        # (stage, policy applied); CRC rejections and recompute
        # fallbacks additionally count on their own so the dashboards
        # can alert on silent-corruption and degraded-throughput rates.
        self.crc_failures = 0
        self.recompute_fallbacks = 0
        self.transfer_failures: collections.Counter = collections.Counter()
        # Adaptive encoding: EWMA staging throughput per ORIGINAL byte
        # for each wire form, learned from per-chunk stage timings.
        # Concurrent staging threads (one per export) share these.
        self._enc_rate: dict[str, float | None] = {"exact": None, "q8": None}  # llmd: guarded_by(_local_lock)
        self._adaptive_exports = 0  # llmd: guarded_by(_local_lock)
        # last-transfer stage timings (ms) — the P/D TTFT budget, readable
        # from stats()/bench without instrumentation hooks
        self.last_stage_ms = 0.0   # producer: HBM->host downloads + register
        self.last_fetch_ms = 0.0   # consumer: pull-wait + device uploads
        self.last_apply_ms = 0.0   # consumer: device->pool scatters + commit

    # ------------------------------------------------------------------ #
    # layer-group plan (shared by both roles)

    @property
    def _pool_layers(self) -> int:
        """Layer count of the runner's FULL-ATTENTION pool (the unit the
        transfer moves; ring engines ship sliding layers separately)."""
        spec = getattr(self.runner, "swa", None)
        if spec is not None:
            return len(spec.full_layers)
        return self.runner.cfg.num_layers

    def _group_plan(self, n_groups: int | None = None) -> list[tuple[int, int]]:
        """The (layer0, n_layers) split this connector stages/imports.

        Producer: from its own config (the compat pins and multi-host —
        whose lockstep gather is monolithic — force a single group).
        Consumer: pass the producer-declared ``num_groups``; both sides
        derive the identical split from (L, num_groups) alone."""
        if n_groups is None:
            n_groups = self.cfg.stream_groups
            if (
                _COMPAT_V1
                or _COMPAT_V2
                or getattr(self.runner, "_multihost", False)
            ):
                n_groups = 1
        return layer_groups(self._pool_layers, max(1, n_groups))

    # ------------------------------------------------------------------ #
    # producer side

    def wants_export(self, req) -> bool:
        return bool(
            self.cfg.is_producer
            and self.server is not None
            and req.kv_transfer_params
            and req.kv_transfer_params.get("do_remote_decode")
        )

    def export_finished(self, req) -> dict[str, Any] | None:
        """Stage + register a finished producer request's prompt KV.

        Must run while ``req.block_ids`` is still live (the engine calls it
        from the scheduler's finish hook, before page release).

        The engine thread only ENQUEUES on-device page snapshots (async,
        independent buffers — the pool may be donated/reused right after);
        the slow HBM -> host downloads + registrations run chunk-by-chunk
        on a staging thread. The response therefore leaves after prefill
        COMPUTE, and the consumer's pull/upload pipeline overlaps the
        remaining downloads (pulls of not-yet-registered chunks wait).

        Prefix-cache-aware byte diet: ``skip_pages`` in the request's
        kv_transfer_params (set by the sidecar after probing the decode
        engine's prefix cache) drops the consumer's already-cached
        leading pages from the export — the reference's disagg decider
        asks the same "how much of the prompt is cached on D?" question
        (scheduling.md:113). A fully-cached prompt exports ZERO chunks
        (params still returned so the consumer accounts the transfer).
        """
        page = self.allocator.page_size
        n_full = req.num_prompt_tokens // page
        if (
            n_full == 0
            or len(req.block_ids) < n_full
            or req.num_computed_tokens < n_full * page
        ):
            return None
        skip = 0
        if req.kv_transfer_params:
            try:
                skip = min(
                    max(int(req.kv_transfer_params.get("skip_pages", 0) or 0), 0),
                    n_full,
                )
            except (TypeError, ValueError):
                # Client-controllable field reaching the scheduler finish
                # hook: malformed values degrade to a full export, never
                # crash the producer's step path.
                skip = 0
        # Server-unique key: never the raw (client-controllable) request id,
        # so colliding x-request-id headers can't cross-wire two exports.
        key = f"{req.request_id}:{uuid.uuid4().hex[:12]}"
        cp = max(1, self.cfg.chunk_pages)
        ids = list(req.block_ids[skip:n_full])
        n_chunks = -(-len(ids) // cp) if ids else 0
        # Int8 POOLS always ship the q8 wire form: the pool bytes go out
        # directly — lossless wrt the pool, half the staging bytes, no
        # quantize work. Float pools use it when opted in ("int8") or
        # when the adaptive picker has measured it faster on this link.
        # Adaptive single-host exports snapshot EXACT and decide the wire
        # encoding on the STAGING thread per chunk (on-device quantize of
        # the snapshot): a local claim then hands lossless device
        # snapshots to the fast path, while remote pulls keep the full
        # encoding race — per-request consumer locality is unknowable at
        # export time, so the decision is deferred to the leg where it
        # matters. Multi-host has no local fast path and no process-local
        # staging-thread dispatch, so it picks at export via the
        # lockstep q8 gather as before.
        adaptive_stage = (
            self.cfg.transfer_dtype == "adaptive"
            and not getattr(self.runner, "kv_quantized", False)
            and not getattr(self.runner, "_multihost", False)
        )
        use_q8 = (
            self.cfg.transfer_dtype == "int8"
            or getattr(self.runner, "kv_quantized", False)
            or (
                self.cfg.transfer_dtype == "adaptive"
                and not adaptive_stage
                and self._adaptive_pick_q8()
            )
        )
        snap_fn = (
            self.runner.snapshot_pages_device_q8
            if use_q8
            else self.runner.snapshot_pages_device
        )
        # v3 layer-group framing: one snapshot CELL per (group, chunk),
        # enqueued GROUP-MAJOR so the staging thread registers group 0
        # across all pages first — the consumer's admission gate. A
        # single-group plan degrades to the v2 chunk framing exactly.
        plan = self._group_plan()
        n_groups = len(plan)
        cells = [
            (
                g, l0, lg, j,
                snap_fn(
                    ids[j * cp : (j + 1) * cp], cp,
                    layers=(l0, lg) if n_groups > 1 else None,
                ),
            )
            for g, (l0, lg) in enumerate(plan)
            for j in range(n_chunks)
        ]
        # Ring engines (kv_swa_ring) ship a sliding-layer SECTION: the
        # trailing ring pages covering the window before the consumer's
        # continuation point. Both sides derive the same geometry from
        # (prompt_len, page, window): preload covers n_pre full pages
        # (never the whole prompt — the last token must be recomputed for
        # logits), and post-preload queries need sliding keys back to
        # n_pre*page - window.
        swa_snap, swa_s0, swa_n = None, 0, 0
        spec = getattr(self.runner, "swa", None)
        if spec is not None and req.swa_block_ids:
            n_pre, swa_s0, swa_n = spec.section(req.num_prompt_tokens, page)
            R = len(req.swa_block_ids)
            # Staleness guard: the ring kept advancing during DECODE, and
            # once any logical page >= s0 + R has been written, slot s0
            # holds newer-position KV — exporting it would label wrong
            # positions and the consumer would silently decode garbage.
            # Normal producer requests are max_tokens=1 (the sidecar
            # two-phase protocol) and never trip this; a client-driven
            # long-decode export just omits the section, and the ring
            # consumer's mixed-mode refusal degrades it to recompute.
            highest_page = max(0, req.num_computed_tokens - 1) // page
            if swa_n <= 0 or highest_page >= swa_s0 + R:
                swa_s0, swa_n = 0, 0
            else:
                ring_ids = [
                    req.swa_block_ids[l % R] for l in range(swa_s0, n_pre)
                ]
                swa_snap = self.runner.snapshot_swa_pages_device(
                    ring_ids, swa_n
                )
        if cells and self._local_enabled:
            # Short retention: a legit in-process claim follows the
            # prefill response within milliseconds; a CROSS-host consumer
            # never claims, so pinning device snapshots for the full
            # lease would be a real HBM tax per export.
            deadline = time.monotonic() + min(self.cfg.lease_ms / 1e3, 5.0)
            with self._local_lock:
                self._prune_local_locked()
                self._local_exports[key] = (
                    deadline, cells, swa_snap, n_groups
                )
        if cells or swa_snap is not None:
            threading.Thread(
                target=self._stage_chunks,
                args=(key, cells, swa_snap, adaptive_stage, n_groups),
                daemon=True,
            ).start()
        self.exported_requests += 1
        params_out = {
            "remote_host": self.cfg.host,
            "remote_port": self.server.port,
            "remote_key": key,
            "num_full_pages": n_full,
            "page_size": page,
            "chunk_pages": cp,
            "num_chunks": n_chunks,
            # First exported page (pages [0, start_page) were declared
            # cached on the consumer and are not staged).
            "start_page": skip,
            # Sliding-layer section geometry (0 pages = no section; a
            # ring consumer refuses params without one).
            "swa_pages": swa_n,
            "swa_start_page": swa_s0,
        }
        if n_groups > 1:
            # v3 group-framed stream: the consumer derives the identical
            # layer split from (its own L, num_groups) via layer_groups.
            params_out["num_groups"] = n_groups
        return params_out

    # Cross-host consumers never claim; cap retained pending exports so a
    # remote-only traffic burst bounds HBM at ~N snapshots until pruning.
    _MAX_LOCAL_PENDING = 16

    def _prune_local_locked(self) -> None:
        now = time.monotonic()
        for k in [
            k for k, entry in self._local_exports.items() if entry[0] < now
        ]:
            del self._local_exports[k]
        while len(self._local_exports) > self._MAX_LOCAL_PENDING:
            self._local_exports.pop(next(iter(self._local_exports)))

    def claim_local(self, key: str) -> tuple | None:
        """In-process consumer leg of the single-host fast path: take the
        pending device snapshots for ``key`` (stops any remaining host
        staging; already-registered chunks are freed by the consumer's
        ordinary free-notify). Returns (snapshot cells, swa snap or
        None, num_groups). Entries live until claimed, expiry (5s), or
        the pending cap evicts them."""
        with self._local_lock:
            self._prune_local_locked()
            entry = self._local_exports.pop(key, None)
            if entry is not None and key in self._staging_active:
                # Marker only matters while the staging thread runs (it
                # is the thread's early-exit signal); setting it for an
                # already-finished key would leak the entry forever.
                self._local_claimed.add(key)
            self._local_cond.notify_all()
        return None if entry is None else (entry[1], entry[2], entry[3])

    def _stage_chunks(
        self, key: str, cells: list, swa_snap=None,
        adaptive_stage: bool = False, n_groups: int = 1,
    ) -> None:
        """Staging thread: download each snapshot cell and register it.
        A failed download leaves later cells unregistered; the consumer's
        pull wait times out and its load-failure policy decides. The
        sliding-layer section (tiny: <= a window's worth of ring pages)
        registers FIRST so a ring consumer's final pull never waits on
        the big chunks.

        ``cells`` are (group, layer0, n_layers, chunk, snapshot) tuples
        in GROUP-MAJOR order; with ``n_groups > 1`` each registers under
        its group key with a v3 group-framed header (the consumer's
        import pipeline starts at group 0), otherwise under the legacy
        chunk key with the v2 frame — byte-identical to the pre-stream
        wire.

        ``adaptive_stage``: snapshots are exact; this leg decides the
        wire encoding per cell, quantizing ON DEVICE when the measured
        link favors q8 — so local claims stay lossless while remote
        pulls keep the adaptive race."""
        t0 = time.monotonic()
        with self._local_lock:
            self._staging_active.add(key)
            if (
                self._local_enabled
                and self.cfg.local_claim_grace_ms > 0
                and _LOCAL_CONSUMERS
            ):
                # Give an in-process consumer the grace window to claim
                # before any HBM->host bytes move; a claim (or the entry
                # disappearing via expiry/eviction) ends the wait early.
                deadline = (
                    time.monotonic() + self.cfg.local_claim_grace_ms / 1e3
                )
                while (
                    key not in self._local_claimed
                    and key in self._local_exports
                ):
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    self._local_cond.wait(remaining)
            swa_wanted = swa_snap is not None and (
                key not in self._local_claimed
            )
        try:
            # A claim_local landing AFTER this check (during the download/
            # register below) leaves the section blob registered; that is
            # a benign leak bounded by the lease — free-notify or expiry
            # reclaims it.
            if swa_wanted:
                pages = self.runner.download_pages(swa_snap)
                payload = (
                    pages if pages.dtype.isbuiltin == 1
                    else pages.view(np.uint8)
                )
                self.server.register(
                    swa_key(key), payload, self.cfg.lease_ms,
                    header=pack_header(pages, crc=payload_crc(payload)),
                )
                with self._local_lock:
                    self.exported_bytes += payload.nbytes
            staging_itemsize = np.dtype(self.runner.staging_dtype).itemsize
            for g, _l0, _lg, j, snap in cells:
                # llmd: allow(concurrency) -- intentional lock-free peek: a claim landing mid-check only costs one extra chunk download (benign, bounded by the lease); taking the lock per chunk would serialize staging against the claim path
                if key in self._local_claimed:
                    # An in-process consumer took the device path; the
                    # remaining HBM->host downloads would be pure waste.
                    break
                t_chunk = time.monotonic()
                if adaptive_stage and not isinstance(snap, tuple):
                    if self._adaptive_pick_q8():
                        # On-device row quantize of the exact snapshot
                        # (same math as the q8 snapshot path), then the
                        # halved download. Timed within the chunk so the
                        # rate estimator prices the quantize in.
                        from llmd_tpu.engine.runner import _quantize_rows_q8

                        snap = _quantize_rows_q8(snap)
                is_q8 = isinstance(snap, tuple)
                grp = (g, n_groups, _l0) if n_groups > 1 else None
                if is_q8:  # int8 transfer: (q8, scales)
                    q8, scales = (self.runner.download_pages(s) for s in snap)
                    orig = self.runner.staging_dtype_name
                    # Scales ride in the header blob: one owning copy in
                    # the shipper, no concat of the big int8 payload.
                    scales_b = scales.tobytes()
                    header = (
                        pack_header_q8(
                            q8, orig, crc=payload_crc(scales_b, q8),
                            group=grp,
                        )
                        + scales_b
                    )
                    payload = q8
                    orig_bytes = q8.nbytes * staging_itemsize
                else:
                    pages = self.runner.download_pages(snap)
                    # Extension dtypes (bfloat16: isbuiltin == 2) don't
                    # expose the buffer protocol the zero-copy register
                    # path needs; a same-memory uint8 view does.
                    payload = (
                        pages if pages.dtype.isbuiltin == 1
                        else pages.view(np.uint8)
                    )
                    header = pack_header(
                        pages, crc=payload_crc(payload), group=grp
                    )
                    orig_bytes = payload.nbytes
                cell_key = (
                    group_key(key, g, j) if n_groups > 1
                    else chunk_key(key, j)
                )
                self.server.register(
                    cell_key, payload, self.cfg.lease_ms, header=header
                )
                if g == 0 and j == (len(cells) // n_groups) - 1:
                    # Group 0 fully shipped: the consumer's admission
                    # gate opens here — the producer-side half of the
                    # first-group latency.
                    self.last_first_group_ms = (
                        (time.monotonic() - t0) * 1e3
                    )
                self._observe_encoding(
                    is_q8, orig_bytes, time.monotonic() - t_chunk
                )
                with self._local_lock:
                    self.exported_bytes += len(header) + payload.nbytes
        except Exception:
            # Abandoned export: the consumer's pull wait times out and
            # ITS load-failure policy decides — but the producer-side
            # failure must leave a metric trail, not just a log line.
            # Same-key increments race between concurrent staging
            # threads (engine-thread sites touch disjoint keys).
            with self._local_lock:
                self.transfer_failures[("export-staging", "abandon")] += 1
            log.exception("KV export staging failed for %s", key)
        finally:
            self.last_stage_ms = (time.monotonic() - t0) * 1e3
            with self._local_lock:
                # The claim marker is only needed while this thread runs;
                # the pending-export entry itself lives until claimed,
                # expiry, or cap eviction (claim_local prunes).
                self._staging_active.discard(key)
                self._local_claimed.discard(key)

    # ------------------------------------------------------------------ #
    # consumer side

    def wants_import(self, params: dict | None) -> bool:
        return bool(self.cfg.is_consumer and params and params.get("remote_host"))

    def fetch_remote(
        self,
        prompt_token_ids: list[int],
        params: dict,
        handle: "KVStreamHandle | None" = None,
    ) -> PulledBundle:
        """Network half of an import: pull + validate + land on device.

        Thread-safe (device writes ride the runner's dispatch lock,
        independent arrays otherwise) — the async serving layer runs it
        on an executor so a slow producer never head-of-line-blocks the
        engine step thread. Chunked exports pipeline: chunk j's (async)
        device upload overlaps the pull of chunk j+1 AND the producer's
        remaining HBM -> host downloads (pull_wait blocks until the
        producer registers each).

        v3 group-framed exports (params["num_groups"] > 1) STREAM:
        pool pages are batch-allocated once up front, each
        (layer-group, chunk) cell is pulled, CRC-checked, and scattered
        straight into the pool on THIS thread while later cells are
        still on the wire, and ``handle`` (when given) is signalled as
        soon as group 0 is resident — the engine's admission gate. The
        returned bundle then only commits hashes at apply. Allocation
        pressure (or a ring/multi-host consumer) degrades to the
        buffered path: cells are reassembled into full-layer chunks and
        applied exactly like a v2 import.
        """
        page = self.allocator.page_size
        if params.get("page_size") != page:
            raise ValueError(
                f"page_size mismatch: producer {params.get('page_size')} "
                f"vs consumer {page}"
            )
        n_full = int(params["num_full_pages"])
        hashes = page_hashes_for_tokens(prompt_token_ids, page)
        if len(hashes) < n_full:
            raise ValueError(
                f"producer sent {n_full} pages but prompt has only "
                f"{len(hashes)} full pages"
            )
        ring_mode = getattr(self.runner, "swa", None) is not None
        n_swa = int(params.get("swa_pages", 0) or 0)
        swa_sp = int(params.get("swa_start_page", 0) or 0)
        if ring_mode and n_swa <= 0:
            # A ring consumer cannot decode from full-group pages alone:
            # the sliding layers' in-window KV must arrive too. Mixed-mode
            # pairings (ring-off producer) are not supported — the policy
            # decides (recompute/fail), never a silent wrong answer.
            raise ValueError(
                "kv_swa_ring consumer requires a sliding-layer section in "
                "the export (pair it with a kv_swa_ring producer)"
            )
        if ring_mode and int(params.get("start_page", 0) or 0) > 0:
            # Ring consumers have no prefix cache, so they never probe and
            # never request a partial export; a nonzero skip (stale or
            # hostile kv_transfer_params) would leave pages [0, skip)
            # uninitialized while marked computed — refuse into the policy
            # rather than silently decode garbage.
            raise ValueError(
                "kv_swa_ring consumer cannot use a partial export "
                "(start_page > 0)"
            )
        host, port, key = params["remote_host"], int(params["remote_port"]), params["remote_key"]
        want_dtype = np.dtype(self.runner.staging_dtype)
        # Int8 pools re-quantize whatever arrives (the pool itself is the
        # lossy step), so the byte-exact-dtype invariant only binds for
        # float pools.
        pool_quant = getattr(self.runner, "kv_quantized", False)
        n_chunks = int(params.get("num_chunks", 0) or 0)
        sp = int(params.get("start_page", 0) or 0)
        if sp > n_full:
            raise ValueError(f"start_page {sp} > num_full_pages {n_full}")
        if n_chunks <= 0 and "start_page" in params:
            # Byte-diet empty export: everything up to n_full was declared
            # cached here; nothing to pull.
            return PulledBundle(
                pages=None, hashes=hashes[:n_full], nbytes=0,
                host=host, port=port, key=key, start_page=n_full,
            )
        if n_chunks <= 0:
            # Legacy single-bundle producer.
            blob = _faulty_pull(host, port, key)
            pages = unpack_pages(blob)
            if pages.shape[1] != n_full:
                raise ValueError(
                    f"bundle holds {pages.shape[1]} pages, expected {n_full}"
                )
            if pages.dtype != want_dtype and not pool_quant:
                # Never silently cast transferred KV: the P/D invariance
                # guarantee is byte-exact numerics.
                raise ValueError(
                    f"KV dtype mismatch: producer {pages.dtype} "
                    f"vs consumer {want_dtype}"
                )
            return PulledBundle(
                pages=pages, hashes=hashes[:n_full], nbytes=len(blob),
                host=host, port=port, key=key,
            )
        cp = int(params["chunk_pages"])
        if cp <= 0 or -(-(n_full - sp) // cp) != n_chunks:
            raise ValueError(
                f"chunk geometry mismatch: {n_full - sp} pages / {cp} per "
                f"chunk != {n_chunks} chunks"
            )
        n_groups = int(params.get("num_groups", 1) or 1)
        grouped = n_groups > 1
        multihost = getattr(self.runner, "_multihost", False)
        self.last_timeline = {"fetch_start": time.monotonic()}
        # Single-host xPyD fast path: an in-process producer's device
        # snapshots are claimed directly — no host staging, no wire
        # bytes (production shape: reference single-host/pd recipes; on
        # a multi-chip host this is the ICI copy).
        all_keys = transfer_keys(params)
        if self.cfg.local_fastpath and not multihost:
            producer = _lookup_local(host, port)
            if producer is not None:
                claimed = producer.claim_local(key)
                if claimed is not None:
                    cells, swa_snap, _ng = claimed
                    if ring_mode and swa_snap is None:
                        raise ValueError(
                            "local claim carried no sliding-layer snapshot"
                        )
                    self.local_imports += 1
                    if grouped and not ring_mode:
                        # Group-streamed local claim: scatter every cell
                        # into batch-allocated pool pages NOW (device-to-
                        # device copies on this thread); apply is just
                        # the hash-chain commit. Allocation pressure
                        # degrades to the apply-side scatter below.
                        bundle = self._claim_streamed(
                            cells, hashes, n_full, sp, cp,
                            host, port, key, all_keys, handle,
                        )
                        if bundle is not None:
                            return bundle
                    dev_cells = (
                        [(j, l0, lg, snap) for _g, l0, lg, j, snap in cells]
                        if grouped
                        else [snap for _g, _l0, _lg, _j, snap in cells]
                    )
                    return PulledBundle(
                        pages=None, hashes=hashes[:n_full], nbytes=0,
                        host=host, port=port, key=key,
                        keys=all_keys,
                        device_chunks=dev_cells, np_chunks=[],
                        chunk_pages=cp,
                        start_page=sp,
                        swa_device=swa_snap if ring_mode else None,
                        swa_start_page=swa_sp, swa_count=n_swa,
                    )
        # Consumer-side byte diet: skip whole chunks the local prefix
        # cache already holds (the producer may have exported more than
        # needed — e.g. no probe ran, or the cache grew since).
        skip0 = 0
        while skip0 < n_full and self.allocator.has_cached(hashes[skip0]):
            skip0 += 1
        j0 = max(0, (skip0 - sp) // cp) if skip0 > sp else 0
        start_page = sp + j0 * cp
        if grouped:
            # v3 group-framed wire: per-cell pull -> CRC -> scatter
            # pipeline (single-host streams into batch-allocated pages;
            # ring/multi-host consumers reassemble full-layer chunks).
            return self._fetch_grouped_wire(
                params, hashes, n_full, sp, cp, n_chunks, j0, n_groups,
                host, port, key, all_keys, ring_mode, n_swa, swa_sp,
                want_dtype, pool_quant, handle,
            )
        # Multi-host consumer: process-local device-scratch uploads
        # cannot feed the lockstep global-mesh scatter, so the
        # device_chunks pipeline stays single-host. The multi-host
        # analog STREAMS instead: pages are allocated up front (the
        # allocator is thread-safe) and each chunk broadcast-scatters as
        # its pull lands — the runner's dispatch lock interleaves these
        # ops safely with the engine's steps, so the wire pulls overlap
        # the DCN broadcast + device scatter legs chunk by chunk.
        pipelined = not multihost
        stream_ids: list[int] | None = None
        if multihost and not ring_mode:
            from llmd_tpu.engine.kv_cache import NoFreePagesError

            # Streaming reserves the pages for the WHOLE wire transfer
            # (up to minutes on a slow link) — only do it with decode
            # headroom left over, or the reservation starves the
            # scheduler into preempting live requests to feed a
            # not-yet-usable import. Check + allocate are one atomic
            # allocator call (concurrent fetch threads must not jointly
            # reserve past the floor), and a single import may pin at
            # most a quarter of the pool: larger transfers take the
            # buffered path, whose allocation lives only for the
            # microseconds of apply.
            need = n_full - start_page
            headroom = max(self.allocator.num_pages // 8, 16)
            if need <= self.allocator.num_pages // 4:
                try:
                    stream_ids = self.allocator.allocate_with_floor(
                        need, headroom
                    )
                except NoFreePagesError:
                    stream_ids = None  # buffered fallback under pressure
        # Per-CHUNK deadline, reset on progress: a shared whole-bundle
        # budget would let a large multi-chunk transfer over a slow link
        # exhaust itself on later chunks and spuriously fall back to
        # recompute even though the producer is healthy and advancing.
        # Still bounded overall (2s/chunk of slack past the first wait) so
        # a trickling producer can't hold the executor thread for
        # n_chunks x 20s before the failure policy kicks in.
        per_chunk_s = min(self.cfg.lease_ms / 1e3, 20.0)
        hard_deadline = time.monotonic() + per_chunk_s + 2.0 * (n_chunks + 1)
        np_chunks, dev_chunks, nbytes = [], [], 0
        swa_np = None
        # ONE protected region from here: every raise between the
        # stream-page reservation above and the bundle handoff below
        # must refund the reserved pages (the lifecycle checker pins
        # this — a leaked reservation permanently shrinks the decode
        # pool by up to a quarter).
        try:
            if ring_mode and n_swa:
                # The sliding-layer section first: it registers first
                # and is tiny, so a missing/expired export fails fast.
                blob = _faulty_pull(
                    host, port, swa_key(key),
                    min(time.monotonic() + per_chunk_s, hard_deadline),
                )
                swa_np = unpack_pages(blob)
                if swa_np.shape[1] != n_swa:
                    raise ValueError(
                        f"sliding section holds {swa_np.shape[1]} pages, "
                        f"expected {n_swa}"
                    )
                if swa_np.dtype != want_dtype and not pool_quant:
                    raise ValueError(
                        f"sliding-section KV dtype mismatch: "
                        f"{swa_np.dtype} vs consumer {want_dtype}"
                    )
                nbytes += len(blob)
            for j in range(j0, n_chunks):
                blob = _faulty_pull(
                    host, port, chunk_key(key, j),
                    min(time.monotonic() + per_chunk_s, hard_deadline),
                )
                decoded = unpack_pages_any(blob)
                payload = decoded[1]
                if payload.shape[1] != cp:
                    raise ValueError(
                        f"chunk {j} holds {payload.shape[1]} pages, "
                        f"expected {cp}"
                    )
                if decoded[0] == "q8":
                    # Already lossy, and dequantization targets the
                    # CONSUMER pool dtype — no producer-pool-dtype match
                    # required (heterogeneous-pool pairings are fine).
                    _, q8, scales, _orig = decoded
                    chunk_entry = (q8, scales)
                    if pipelined:
                        dev_chunks.append(
                            self.runner.upload_pages_device_q8(q8, scales)
                        )
                else:
                    if payload.dtype != want_dtype and not pool_quant:
                        # The EXACT path's guarantee is byte-identical
                        # numerics; silent casts would break it. (Int8
                        # pools re-quantize on scatter — any float dtype
                        # works.)
                        raise ValueError(
                            f"KV dtype mismatch: producer {payload.dtype} "
                            f"vs consumer {want_dtype}"
                        )
                    chunk_entry = payload
                    if pipelined:
                        dev_chunks.append(
                            self.runner.upload_pages_device(payload)
                        )
                if stream_ids is not None:
                    # Streamed multi-host leg: broadcast-scatter this
                    # chunk now, while later chunks are still on the
                    # wire, and do NOT retain a host copy (the streamed
                    # apply never reads np_chunks; holding the whole
                    # transfer in RAM would cost a bundle-sized buffer
                    # for nothing). Pad slots repeat the last real id
                    # (idempotent duplicate write). q8 wire chunks ride
                    # the symmetric _OP_KV_SCATTER_Q8 broadcast — half
                    # the DCN bytes per page, dequant (or direct int8
                    # write) on every process's device; exact chunks
                    # keep the staging-dtype broadcast.
                    o0 = sp + j * cp - start_page
                    ids_j = _pad_chunk_ids(stream_ids[o0 : o0 + cp], cp)
                    if isinstance(chunk_entry, tuple):
                        self.runner.scatter_pages_q8(
                            ids_j, chunk_entry[0], chunk_entry[1]
                        )
                    else:
                        self.runner.scatter_pages(ids_j, chunk_entry)
                else:
                    np_chunks.append(chunk_entry)
                nbytes += len(blob)
        except Exception:
            if stream_ids is not None:
                self.allocator.free(stream_ids)
            raise
        return PulledBundle(
            pages=None, hashes=hashes[:n_full], nbytes=nbytes,
            host=host, port=port, key=key,
            keys=all_keys,
            device_chunks=dev_chunks, np_chunks=np_chunks, chunk_pages=cp,
            start_page=start_page, stream_ids=stream_ids,
            swa_pages_np=swa_np, swa_start_page=swa_sp, swa_count=n_swa,
        )

    def _note_first_group(self, handle: "KVStreamHandle | None") -> None:
        """Group 0 is resident: stamp the admission-gate milestone and
        wake the serving layer's admittable-waiter."""
        now = time.monotonic()
        self.last_timeline.setdefault("first_group", now)
        t0 = self.last_timeline.get("fetch_start", now)
        self.last_first_group_ms = (now - t0) * 1e3
        if handle is not None:
            handle.mark_first_group()

    # llmd: transfers(pages)
    def _stream_alloc(self, need: int) -> list[int] | None:
        """Batch page allocation for a streamed import — ONCE up front,
        never per chunk. Reserved for the whole wire transfer, so only
        with decode headroom left over (floor) and never more than a
        quarter of the pool; None = take the buffered path instead.
        Callers own the returned ids (they ride into the bundle's
        stream_ids, whose apply/release frees them)."""
        from llmd_tpu.engine.kv_cache import NoFreePagesError

        if need <= 0:
            return []
        headroom = max(self.allocator.num_pages // 8, 16)
        if need > self.allocator.num_pages // 4:
            return None
        try:
            return self.allocator.allocate_with_floor(need, headroom)
        except NoFreePagesError:
            return None  # buffered fallback under pressure

    def _claim_streamed(
        self, cells, hashes, n_full, sp, cp,
        host, port, key, all_keys, handle,
    ) -> "PulledBundle | None":
        """Group-streamed LOCAL claim: scatter every claimed device cell
        into batch-allocated pool pages on the fetch thread (device-to-
        device copies under the dispatch lock), so apply is just the
        hash-chain commit. None = allocation pressure; the caller falls
        back to apply-side scatters."""
        stream_ids = self._stream_alloc(n_full - sp)
        if stream_ids is None:
            return None
        n_chunks = (
            max(j for _g, _l0, _lg, j, _s in cells) + 1 if cells else 0
        )
        try:
            for g, l0, lg, j, snap in cells:
                o0 = j * cp
                ids_j = _pad_chunk_ids(stream_ids[o0 : o0 + cp], cp)
                self.runner.scatter_pages_from_device(
                    ids_j, snap, layers=(l0, lg)
                )
                with self._local_lock:
                    self.stream_groups_total += 1
                if g == 0 and j == n_chunks - 1:
                    self._note_first_group(handle)
        except Exception:
            self.allocator.free(stream_ids)
            raise
        self.last_timeline["fetch_done"] = time.monotonic()
        return PulledBundle(
            pages=None, hashes=hashes[:n_full], nbytes=0,
            host=host, port=port, key=key, keys=all_keys,
            chunk_pages=cp, start_page=sp, stream_ids=stream_ids,
        )

    def _fetch_grouped_wire(
        self, params, hashes, n_full, sp, cp, n_chunks, j0, n_groups,
        host, port, key, all_keys, ring_mode, n_swa, swa_sp,
        want_dtype, pool_quant, handle,
    ) -> "PulledBundle":
        """Wire leg of a v3 group-framed import.

        Single-host (non-ring): pages batch-allocated once up front,
        then every (group, chunk) cell pulls, CRC-verifies, and scatters
        its layer slice straight into the pool while later cells are
        still on the wire — group 0's completion opens the admission
        gate. Ring / multi-host consumers (and allocation pressure)
        reassemble full-layer chunks instead and apply exactly like a
        v2 import."""
        plan = self._group_plan(n_groups)
        multihost = getattr(self.runner, "_multihost", False)
        start_page = sp + j0 * cp
        streamed = not ring_mode and not multihost
        stream_ids = (
            self._stream_alloc(n_full - start_page) if streamed else None
        )
        # Per-CELL deadline, reset on progress (same contract as the v2
        # chunk loop), bounded overall by 2s of slack per cell.
        per_chunk_s = min(self.cfg.lease_ms / 1e3, 20.0)
        n_cells = n_groups * max(n_chunks - j0, 0)
        hard_deadline = time.monotonic() + per_chunk_s + 2.0 * (n_cells + 1)
        np_bufs: dict[int, np.ndarray] = {}
        nbytes = 0
        swa_np = None
        # ONE protected region: every raise between the stream-page
        # reservation above and the bundle handoff below must refund the
        # reserved pages (a leaked reservation permanently shrinks the
        # decode pool by up to a quarter).
        try:
            if ring_mode and n_swa:
                # The sliding-layer section first: it registers first
                # and is tiny, so a missing/expired export fails fast.
                blob = _faulty_pull(
                    host, port, swa_key(key),
                    min(time.monotonic() + per_chunk_s, hard_deadline),
                )
                swa_np = unpack_pages(blob)
                if swa_np.shape[1] != n_swa:
                    raise ValueError(
                        f"sliding section holds {swa_np.shape[1]} pages, "
                        f"expected {n_swa}"
                    )
                if swa_np.dtype != want_dtype and not pool_quant:
                    raise ValueError(
                        f"sliding-section KV dtype mismatch: "
                        f"{swa_np.dtype} vs consumer {want_dtype}"
                    )
                nbytes += len(blob)
            for g, (l0, lg) in enumerate(plan):
                for j in range(j0, n_chunks):
                    blob = _faulty_pull(
                        host, port, group_key(key, g, j),
                        min(time.monotonic() + per_chunk_s, hard_deadline),
                    )
                    decoded = unpack_pages_any(blob)
                    payload = decoded[1]
                    gi, gn, gl0 = bundle_group_info(blob)
                    if (gi, gn, gl0) != (g, n_groups, l0):
                        raise ValueError(
                            f"group frame mismatch at cell g{g}c{j}: wire "
                            f"says (group {gi}/{gn}, layer0 {gl0}), "
                            f"expected (group {g}/{n_groups}, layer0 {l0})"
                        )
                    if payload.shape[0] != lg or payload.shape[1] != cp:
                        raise ValueError(
                            f"cell g{g}c{j} holds {payload.shape[0]}x"
                            f"{payload.shape[1]} layers x pages, expected "
                            f"{lg}x{cp}"
                        )
                    direct_q8 = decoded[0] == "q8" and pool_quant
                    if decoded[0] == "q8" and not direct_q8:
                        # Already lossy; dequantization targets the
                        # consumer pool dtype (heterogeneous pairings OK).
                        vals = PulledBundle._dequant_chunk(
                            (decoded[1], decoded[2])
                        )
                    elif decoded[0] == "q8":
                        vals = None  # int8 pool: wire pair goes direct
                    else:
                        if payload.dtype != want_dtype and not pool_quant:
                            raise ValueError(
                                f"KV dtype mismatch: producer "
                                f"{payload.dtype} vs consumer {want_dtype}"
                            )
                        vals = payload
                    if stream_ids is not None:
                        o0 = sp + j * cp - start_page
                        ids_j = _pad_chunk_ids(stream_ids[o0 : o0 + cp], cp)
                        if direct_q8:
                            # Int8 pool + q8 wire: the pool bytes ship
                            # and land DIRECTLY — a dequant/requant
                            # round trip would cost a rounding flip and
                            # break the lossless-wrt-pool contract.
                            self.runner.scatter_pages_from_device(
                                ids_j, (decoded[1], decoded[2]),
                                layers=(l0, lg),
                            )
                        else:
                            self.runner.scatter_pages(
                                ids_j, vals, layers=(l0, lg)
                            )
                    else:
                        if vals is None:
                            # Buffered reassembly has no layer-sliced
                            # direct write; dequant like the legacy
                            # host path (requant at scatter — same
                            # behavior as a v2 buffered import).
                            vals = PulledBundle._dequant_chunk(
                                (decoded[1], decoded[2])
                            )
                        buf = np_bufs.get(j)
                        if buf is None:
                            # Full-layer reassembly buffer. float32 holds
                            # every staging dtype exactly (bf16/f16 are
                            # strict subsets), so the scatter's cast back
                            # to the pool dtype stays byte-identical.
                            _, _, K, pg, inner = payload.shape
                            buf = np.empty(
                                (self._pool_layers, cp, K, pg, inner),
                                dtype=np.float32,
                            )
                            np_bufs[j] = buf
                        buf[l0 : l0 + lg] = np.asarray(vals).astype(
                            np.float32, copy=False
                        )
                    with self._local_lock:
                        self.stream_groups_total += 1
                    nbytes += len(blob)
                if g == 0:
                    self._note_first_group(handle)
        except Exception:
            if stream_ids is not None:
                self.allocator.free(stream_ids)
            raise
        self.last_timeline["fetch_done"] = time.monotonic()
        np_chunks = [np_bufs[j] for j in sorted(np_bufs)]
        return PulledBundle(
            pages=None, hashes=hashes[:n_full], nbytes=nbytes,
            host=host, port=port, key=key, keys=all_keys,
            np_chunks=np_chunks, chunk_pages=cp,
            start_page=start_page, stream_ids=stream_ids,
            swa_pages_np=swa_np, swa_start_page=swa_sp, swa_count=n_swa,
        )

    def streaming_import(self, params: dict | None) -> bool:
        """True when ``params`` describe a v3 group-framed import THIS
        consumer can admit early (first-group admission seam): grouped
        wire, cache-seeding (non-ring) single-host consumer, recompute
        policy (policy='fail' keeps the synchronous surface so the
        serving layer can still 500 the request)."""
        return bool(
            self.wants_import(params)
            and int(params.get("num_groups", 1) or 1) > 1
            and getattr(self.runner, "swa", None) is None
            and not getattr(self.runner, "_multihost", False)
            and self.cfg.load_failure_policy == "recompute"
        )

    def make_stream_handle(self, params: dict) -> "KVStreamHandle":
        return KVStreamHandle(self, params)

    def fetch_remote_policy(
        self,
        prompt_token_ids: list[int],
        params: dict,
        handle: "KVStreamHandle | None" = None,
    ) -> "PulledBundle | None":
        """fetch_remote with the load-failure policy applied.

        Returns None on policy='recompute' failure; raises KVLoadError on
        policy='fail' (operations-vllm.md:118-139). With ``handle`` the
        outcome is ALSO published through it — success hands the bundle
        to whoever wins the take()/abandon() race, failure wakes the
        parked request into local recompute."""
        t0 = time.monotonic()
        try:
            bundle = self.fetch_remote(prompt_token_ids, params, handle)
            if handle is not None:
                handle.resolve(bundle)
            return bundle
        except (PullError, OSError, ValueError, KeyError, TypeError, struct.error) as e:
            # struct.error: truncated header; TypeError: garbage dtype string
            # -- a corrupt/foreign bundle must hit the policy, not escape.
            self.import_failures += 1
            if isinstance(e, KVCorruptionError):
                self.crc_failures += 1
            policy = self.cfg.load_failure_policy
            self.transfer_failures[("fetch", policy)] += 1
            if handle is not None:
                handle.fail(str(e))
            if policy == "fail":
                raise KVLoadError(str(e)) from e
            self.recompute_fallbacks += 1
            log.warning("remote KV load failed, recomputing locally: %s", e)
            return None
        finally:
            self.last_fetch_ms = (time.monotonic() - t0) * 1e3
            self.last_timeline.setdefault("fetch_start", t0)
            self.last_timeline["fetch_done"] = time.monotonic()

    def _adaptive_pick_q8(self) -> bool:
        """Per-export encoding choice from measured link behavior.

        Cold start alternates the two forms; once both have EWMA rates
        (original bytes staged per second, so the q8 form's halved
        payload and its quantize/scales overhead are both priced in),
        the faster wins, with every 8th export re-probing the loser so
        a drifting link can flip the decision.

        Concurrent staging threads share the estimator state, so both
        the pick and the observe run under the local lock (off the
        engine thread; the lock covers dict reads, never the staging
        I/O itself)."""
        with self._local_lock:
            self._adaptive_exports += 1
            exact, q8 = self._enc_rate["exact"], self._enc_rate["q8"]
            if exact is None or q8 is None:
                return self._adaptive_exports % 2 == 0
            best_q8 = q8 > exact
            if self._adaptive_exports % 8 == 0:
                return not best_q8  # re-probe the loser
            return best_q8

    def _observe_encoding(self, q8: bool, orig_bytes: int, dt_s: float) -> None:
        if dt_s <= 0 or orig_bytes <= 0:
            return
        key = "q8" if q8 else "exact"
        rate = orig_bytes / dt_s
        with self._local_lock:
            prev = self._enc_rate[key]
            self._enc_rate[key] = (
                rate if prev is None else 0.7 * prev + 0.3 * rate
            )

    def _enc_rate_snapshot(self, key: str) -> float | None:
        with self._local_lock:
            return self._enc_rate[key]

    def release_bundle(self, bundle: "PulledBundle") -> None:
        """Dispose of a fetched bundle that will never be applied: free
        any stream-allocated pages and fire the producer free-notify."""
        if bundle.stream_ids is not None:
            self.allocator.free(bundle.stream_ids)
            bundle.stream_ids = None
        self._notify_free_async(bundle)

    def apply_bundle(
        self, prompt_token_ids: list[int], bundle: "PulledBundle"
    ) -> int:
        """Engine-thread half: seed the local prefix cache with the bundle.

        Allocator + device scatter only (fast); the free-notify to the
        producer is fired on a background thread. Failures (e.g. no free
        pages under pressure) degrade to local recompute.
        """
        from llmd_tpu.engine.kv_cache import NoFreePagesError

        t_apply = time.monotonic()
        page = self.allocator.page_size
        hashes = bundle.hashes
        n_full = len(hashes)
        # Skip a leading run already cached locally (idempotent re-imports,
        # shared prefixes). Only a prefix run is usable anyway. Pages
        # before start_page were never pulled (byte diet): if the cache
        # evicted some of them since the probe, the import still lands
        # correct content from start_page on (the chain below the missing
        # page simply isn't reachable until recomputed — same degradation
        # as any partial-prefix state).
        skip = 0
        while skip < n_full and self.allocator.has_cached(hashes[skip]):
            skip += 1
        skip = max(skip, bundle.start_page)
        if bundle.stream_ids is not None:
            # Streamed multi-host import: content already scattered by
            # the fetch thread — commit the hash chain and release refs.
            # Pages whose hash got cached since the fetch decision are
            # duplicates; commit_page dedups onto the existing page and
            # the spare frees with the rest.
            parent = None if skip == 0 else hashes[skip - 1]
            adopted = 0
            for i, pid in enumerate(bundle.stream_ids):
                idx = bundle.start_page + i
                if idx >= n_full or idx < skip:
                    continue
                chunk = prompt_token_ids[idx * page : (idx + 1) * page]
                self.allocator.commit_page(pid, hashes[idx], chunk, parent)
                parent = hashes[idx]
                adopted += 1
            self.allocator.free(bundle.stream_ids)
            bundle.stream_ids = None  # release_bundle stays idempotent
            self.stream_imports += 1
            self.imported_requests += 1
            self.imported_bytes += bundle.nbytes
            self._notify_free_async(bundle)
            self.last_apply_ms = (time.monotonic() - t_apply) * 1e3
            self.last_timeline["apply_done"] = time.monotonic()
            return adopted
        if bundle.device_chunks and not bundle.np_chunks:
            # Local-fastpath bundles keep no host chunks for the
            # partial-overlap fallback; re-importing from start_page is
            # correct regardless (duplicate hashes dedup at commit and
            # the spare pages free right after).
            skip = bundle.start_page
        adopted = 0
        if skip < n_full:
            try:
                page_ids = self.allocator.allocate(n_full - skip)
            except NoFreePagesError as e:
                self.import_failures += 1
                self.transfer_failures[("apply", "recompute")] += 1
                self.recompute_fallbacks += 1
                log.warning("no free pages for KV import, recomputing: %s", e)
                self._notify_free_async(bundle)
                return 0
            try:
                if bundle.device_chunks:
                    # Pipelined path: chunks are already on device
                    # (uploaded by the fetch thread) — only fast
                    # device->pool scatters here. Grouped claim cells
                    # ride as (chunk, layer0, n_layers, dev) tuples and
                    # scatter their layer slice; legacy entries are
                    # whole-layer chunks keyed by position.
                    cp = bundle.chunk_pages
                    for idx, entry in enumerate(bundle.device_chunks):
                        if isinstance(entry, tuple) and len(entry) == 4:
                            j, l0, lg, dev = entry
                            layers = (l0, lg)
                        else:
                            j, dev, layers = idx, entry, None
                        p0 = bundle.start_page + j * cp
                        if p0 + cp <= skip:
                            continue  # wholly cached since the fetch
                        if p0 >= skip:
                            ids_j = _pad_chunk_ids(
                                page_ids[p0 - skip : p0 - skip + cp], cp
                            )
                            self.runner.scatter_pages_from_device(
                                ids_j, dev, layers=layers
                            )
                        else:
                            # Partial overlap (cache grew between fetch
                            # and apply): host-path scatter of the
                            # uncached tail.
                            want = PulledBundle._dequant_chunk(
                                bundle.np_chunks[j]
                            )[:, skip - p0 :]
                            take = min(p0 + cp, n_full) - skip
                            self.runner.scatter_pages(
                                page_ids[:take], want[:, :take]
                            )
                elif skip < n_full and (
                    bundle.pages is not None or bundle.np_chunks
                ):
                    want = bundle.host_pages(n_full)[
                        :, skip - bundle.start_page :
                    ]
                    self.runner.scatter_pages(page_ids, want)
                parent = None if skip == 0 else hashes[skip - 1]
                for i, pid in enumerate(page_ids):
                    idx = skip + i
                    chunk = prompt_token_ids[idx * page : (idx + 1) * page]
                    self.allocator.commit_page(
                        pid, hashes[idx], chunk, parent
                    )
                    parent = hashes[idx]
            finally:
                # Drop our references: pages stay cached (ref 0) for the
                # prefix-cache hit when this request is scheduled — and
                # a mid-scatter failure must refund them rather than
                # bleed the pool one failed import at a time.
                self.allocator.free(page_ids)
            adopted = len(page_ids)
        self.imported_requests += 1
        self.imported_bytes += bundle.nbytes
        self._notify_free_async(bundle)
        self.last_apply_ms = (time.monotonic() - t_apply) * 1e3
        self.last_timeline["apply_done"] = time.monotonic()
        return adopted

    # llmd: transfers(pages)
    def apply_preload(
        self,
        prompt_token_ids: list[int],
        bundle: "PulledBundle",
        swa_allocator: PageAllocator,
        ring_pages: int,
    ) -> dict[str, Any] | None:
        """Engine-thread half of a RING-mode import (kv_swa_ring).

        With the ring on there is no prefix cache to seed, so the
        transferred KV is handed straight to the request instead:
        full-group pages land in freshly allocated (ref-held) main-pool
        pages, the sliding-layer section lands in a freshly allocated
        ring at the matching ring slots, and the caller constructs the
        Request with these pages and num_computed_tokens pre-set — the
        scheduler then prefills only the recompute tail. All-or-nothing:
        any failure frees everything and returns None (local recompute),
        mirroring apply_bundle's degradation.
        """
        from llmd_tpu.engine.kv_cache import NoFreePagesError

        t_apply = time.monotonic()
        page = self.allocator.page_size
        n_full = len(bundle.hashes)
        spec = self.runner.swa
        # Shared geometry (SwaRingSpec.section): producer and consumer
        # MUST derive the identical (n_pre, s0) from the prompt alone.
        # n_pre/s0 are NOT clamped to the producer-declared page count —
        # clamping would shift the consumer's window start and let a
        # tampered num_full_pages slide a non-covering section past the
        # geometry guard below (the guard instead refuses n_full < n_pre).
        n_pre, s0, _cnt = spec.section(len(prompt_token_ids), page)
        if (
            n_pre <= 0
            or bundle.swa_count <= 0
            or bundle.start_page != 0  # partial exports rejected at fetch;
            # defense in depth for hand-built bundles
            or not (
                bundle.swa_pages_np is not None
                or bundle.swa_device is not None
            )
        ):
            self._notify_free_async(bundle)
            return None
        page_ids: list[int] = []
        ring_ids: list[int] = []
        try:
            # The section must MATCH the consumer-derived geometry, not
            # merely overlap [0, n_pre): a stale/hostile swa_start_page
            # != s0 or short swa_count would leave in-window ring slots
            # zero-initialized (or alias two logical pages onto one ring
            # slot when the span exceeds the ring) while
            # num_computed_tokens says they're valid — silent garbage
            # decode (same defense-in-depth as the start_page guard).
            # Honest producers derive (s0, cnt) from the identical
            # spec.section, so equality is the honest case, checked
            # BEFORE any allocation/scatter work is spent.
            if (
                n_full < n_pre
                or bundle.swa_start_page != s0
                or bundle.swa_start_page + bundle.swa_count < n_pre
                or n_pre - s0 <= 0
                or n_pre - s0 > ring_pages
            ):
                raise ValueError(
                    f"sliding section [{bundle.swa_start_page}, "
                    f"+{bundle.swa_count}) over {n_full} pages does not "
                    f"match the required window [{s0}, {n_pre}) "
                    f"(ring {ring_pages} pages)"
                )
            # Land ALL exported pages, then hand the request only the
            # first n_pre: chunk writes beyond the preload boundary (the
            # producer may have exported one more page than we keep, plus
            # its pad columns) land in real scratch slots instead of
            # clobbering a kept page, and the spares free right after.
            # llmd: allow(release-on-all-paths) -- every raise through the scatters refunds via the except arm; past it the tail is counter bumps + the free-notify daemon-thread spawn, and ownership then passes to the caller in the returned preload dict (this def is a transfers(pages) boundary)
            page_ids = self.allocator.allocate(n_full)
            # llmd: allow(release-on-all-paths) -- same contract as page_ids one line up: except-arm refund, then ownership rides the returned preload dict
            ring_ids = swa_allocator.allocate(ring_pages)
            # Full-group content into the main pool (grouped claim
            # cells carry their layer slice; legacy entries are
            # whole-layer chunks keyed by position).
            if bundle.device_chunks:
                cp = bundle.chunk_pages
                for idx, entry in enumerate(bundle.device_chunks):
                    if isinstance(entry, tuple) and len(entry) == 4:
                        j, l0, lg, dev = entry
                        layers = (l0, lg)
                    else:
                        j, dev, layers = idx, entry, None
                    p0 = bundle.start_page + j * cp
                    ids_j = _pad_chunk_ids(page_ids[p0 : p0 + cp], cp)
                    self.runner.scatter_pages_from_device(
                        ids_j, dev, layers=layers
                    )
            elif bundle.pages is not None or bundle.np_chunks:
                want = bundle.host_pages(n_full)
                self.runner.scatter_pages(page_ids, want[:, : n_full])
            else:
                raise ValueError("preload bundle carries no full-group data")
            # Sliding-layer section into the ring at matching slots:
            # logical prompt page l lives at ring[l % R] — the same
            # mapping the engine's ring-view table uses from here on.
            n_swa = min(bundle.swa_count, n_pre - bundle.swa_start_page)
            swa_ids = [
                ring_ids[(bundle.swa_start_page + i) % ring_pages]
                for i in range(n_swa)
            ]
            if bundle.swa_device is not None:
                self.runner.scatter_pages_from_device(
                    swa_ids, bundle.swa_device, swa=True
                )
            else:
                self.runner.scatter_pages(
                    swa_ids, bundle.swa_pages_np[:, :n_swa], swa=True
                )
        except (NoFreePagesError, ValueError, KeyError, TypeError) as e:
            self.import_failures += 1
            self.transfer_failures[("preload", "recompute")] += 1
            self.recompute_fallbacks += 1
            log.warning("KV ring preload failed, recomputing locally: %s", e)
            if page_ids:
                self.allocator.free(page_ids)
            if ring_ids:
                swa_allocator.free(ring_ids)
            self._notify_free_async(bundle)
            return None
        if len(page_ids) > n_pre:
            self.allocator.free(page_ids[n_pre:])
            page_ids = page_ids[:n_pre]
        self.imported_requests += 1
        self.imported_bytes += bundle.nbytes
        self._notify_free_async(bundle)
        self.last_apply_ms = (time.monotonic() - t_apply) * 1e3
        self.last_timeline["apply_done"] = time.monotonic()
        return {
            "block_ids": page_ids,
            "swa_block_ids": ring_ids,
            "tokens": n_pre * page,
        }

    def import_for_prompt(self, prompt_token_ids: list[int], params: dict) -> int:
        """Synchronous fetch + apply (offline engine path and tests).

        Cache-seeding engines only: a ring engine (kv_swa_ring) has no
        prefix cache, so apply_bundle would scatter-and-free unreachable
        content while dropping the sliding section — refuse loudly and
        point at the preload path instead of silently wasting a transfer.
        """
        if getattr(self.runner, "swa", None) is not None:
            raise RuntimeError(
                "ring engines (kv_swa_ring) import via "
                "LLMEngine.add_request's preload path (apply_preload needs "
                "the engine's ring allocator); import_for_prompt only "
                "serves cache-seeding engines"
            )
        bundle = self.fetch_remote_policy(prompt_token_ids, params)
        if bundle is None:
            return 0
        return self.apply_bundle(prompt_token_ids, bundle)

    @staticmethod
    def _notify_free_async(bundle: "PulledBundle") -> None:
        keys = bundle.keys or [bundle.key]

        def notify() -> None:
            for k in keys:
                shipper_mod.free_notify(bundle.host, bundle.port, k)

        threading.Thread(target=notify, daemon=True).start()

    # ------------------------------------------------------------------ #

    def stats(self) -> dict[str, int]:
        with self._local_lock:
            exported_bytes = self.exported_bytes
            stream_groups_total = self.stream_groups_total
        out = {
            "stream_groups_total": stream_groups_total,
            "last_first_group_ms": round(self.last_first_group_ms, 2),
            "exported_requests": self.exported_requests,
            "exported_bytes": exported_bytes,
            "imported_requests": self.imported_requests,
            "imported_bytes": self.imported_bytes,
            "import_failures": self.import_failures,
            "crc_failures": self.crc_failures,
            "recompute_fallbacks": self.recompute_fallbacks,
            "transfer_failures": dict(self.transfer_failures),
            "local_imports": self.local_imports,
            "stream_imports": self.stream_imports,
            "enc_rate_exact_mbps": round(
                (self._enc_rate_snapshot("exact") or 0.0) / 2**20, 2
            ),
            "enc_rate_q8_mbps": round(
                (self._enc_rate_snapshot("q8") or 0.0) / 2**20, 2
            ),
            "last_stage_ms": round(self.last_stage_ms, 1),
            "last_fetch_ms": round(self.last_fetch_ms, 1),
            "last_apply_ms": round(self.last_apply_ms, 1),
        }
        if self.server is not None:
            out["registered_count"] = self.server.registered_count
            out["registered_bytes"] = self.server.registered_bytes
            out["expired_count"] = self.server.expired_count
        return out

    def close(self) -> None:
        _LOCAL_CONSUMERS.discard(self)
        if self.server is not None:
            if _LOCAL_PRODUCERS.get(self.server.port) is self:
                del _LOCAL_PRODUCERS[self.server.port]
            self.server.close()
            self.server = None
        with self._local_lock:
            self._local_exports.clear()


# Runtime twin of the `# llmd: resource(bundles, ...)` annotation
# (static-analysis.md): LLMD_LEAKSAN=1 tracks each fetched bundle from
# fetch_remote until exactly one of apply_bundle / apply_preload /
# release_bundle disposes of it (idempotent re-release is quiet by
# design — release_bundle nulls stream_ids).
from llmd_tpu.analysis import sanitize as _sanitize

_sanitize.leaksan_register(
    TPUConnector, "bundles", mode="set",
    acquire={
        "fetch_remote": lambda self, a, k, r: (
            [id(r)] if r is not None else []
        ),
    },
    release={
        "apply_bundle": lambda self, a, k, r: [id(a[1])],
        "apply_preload": lambda self, a, k, r: [id(a[1])],
        "release_bundle": lambda self, a, k, r: [id(a[0])],
    },
)
