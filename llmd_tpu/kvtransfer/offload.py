"""Tiered KV offload: HBM -> host-DRAM -> filesystem page cache.

Re-implements the reference's offloading-connector / TPUOffloadConnector
tiering (docs/architecture/advanced/kv-management/kv-offloader.md:15-21,
70-134; TPU deployment shape tiered-prefix-cache/modelserver/tpu/base/
vllm/patch-vllm.yaml:43,56-59 — HBM staging + 25000 CPU chunks):

  * save-on-fill: every page committed to the device prefix cache is also
    staged HBM -> host (one bucketed gather per engine step) and inserted
    into a capacity-capped host LRU keyed by the page's chained content
    hash;
  * restore-on-prefill: before a request is scheduled, host-cached pages
    extending the device cache's prefix run are staged host -> HBM and
    committed, so the ordinary prefix-cache hit path picks them up (the
    same cache-seeding move the P/D consumer uses);
  * optional FS tier: host-evicted pages spill to files, reloaded on miss
    (kv-offloader.md FS-backend persistence across restarts);
  * tier-honest events: a wrapping KVEventSink downgrades device evictions
    of host-held pages to BlockStored(medium="cpu") instead of removal, so
    the precise prefix indexer scores the CPU tier at weight 0.8
    (kv-indexer.md:133) rather than forgetting the pod;
  * federation tier (docs/architecture/kv-federation.md): behind DRAM/FS
    sits the fleet-wide store — ``KVFederation`` decides which pages earn
    a global copy (publish-on-evict hotness gate, or the eager save
    policy) and serves fetch-on-miss for hash-chain pages no local tier
    holds; the device eviction hook below is the publish trigger.
"""

from __future__ import annotations

import collections
import logging
import pathlib
import threading

import numpy as np

from llmd_tpu.engine.kv_cache import KVEventSink, page_hashes_for_tokens

log = logging.getLogger(__name__)


class HostKVCache:
    """Host-DRAM page store: content hash -> [L, K, page, 2D] ndarray.

    LRU with a page-count cap (the reference's CPU chunk budget). Evictions
    spill to the FS tier when configured. Thread-safe (engine thread saves,
    lookups on engine thread; FS writes on a background thread).
    """

    def __init__(
        self,
        max_pages: int = 25_000,
        fs_dir: str | None = None,
        fs_max_pages: int = 100_000,
        federation=None,  # KVFederation: fleet-wide tier behind DRAM/FS
    ) -> None:
        self.max_pages = max_pages
        self.fs_dir = pathlib.Path(fs_dir) if fs_dir else None
        self.fs_max_pages = fs_max_pages
        self.federation = federation
        self.remote_hits = 0  # llmd: guarded_by(_lock)
        self._lock = threading.Lock()
        self._pages: collections.OrderedDict[bytes, np.ndarray] = collections.OrderedDict()  # llmd: guarded_by(_lock)
        self._fs_lru: collections.OrderedDict[bytes, None] = collections.OrderedDict()  # llmd: guarded_by(_lock)
        if self.fs_dir is not None:
            self.fs_dir.mkdir(parents=True, exist_ok=True)
            for f in sorted(self.fs_dir.glob("*.npy")):
                try:
                    self._fs_lru[bytes.fromhex(f.stem)] = None
                except ValueError:
                    continue
        self.saves = 0  # llmd: guarded_by(_lock)
        self.restores = 0  # llmd: guarded_by(_lock)
        self.fs_spills = 0  # llmd: guarded_by(_lock)
        self.fs_loads = 0  # llmd: guarded_by(_lock)

    def __len__(self) -> int:
        with self._lock:
            return len(self._pages)

    def has(self, h: bytes) -> bool:
        with self._lock:
            return h in self._pages or h in self._fs_lru

    def put(self, h: bytes, page: np.ndarray, publish: bool = True) -> None:
        with self._lock:
            if h in self._pages:
                self._pages.move_to_end(h)
                re_save = True
            else:
                self._pages[h] = page
                self.saves += 1
                re_save = False
            spill: list[tuple[bytes, np.ndarray]] = []
            while len(self._pages) > self.max_pages:
                old_h, old_p = self._pages.popitem(last=False)
                spill.append((old_h, old_p))
        for old_h, old_p in spill:
            self._spill_fs(old_h, old_p)
        if self.federation is not None:
            if re_save:
                # Same content re-saved: a reuse signal for the
                # publish-on-evict hotness gate, not a new copy.
                self.federation.touch(h)
            elif publish:
                self.federation.on_save(h, page)

    def get(self, h: bytes) -> np.ndarray | None:
        page, _ = self.get_tagged(h)
        return page

    def get_tagged(
        self, h: bytes, store: bool = True
    ) -> tuple[np.ndarray | None, str | None]:
        """Fetch a page plus the tier that served it (``dram`` | ``fs``
        | ``store`` | None) — the restore path scores store-served
        pages as recompute avoided (kv-federation.md). ``store=False``
        stops at the local tiers (the batched restore walk fetches the
        store leg in one shot via :meth:`fetch_store_many` instead of a
        round trip per page)."""
        with self._lock:
            page = self._pages.get(h)
            if page is not None:
                self._pages.move_to_end(h)
                self.restores += 1
                if self.federation is not None:
                    self.federation.touch(h)
                return page, "dram"
        page = self._load_fs(h)
        if page is not None:
            with self._lock:
                self.restores += 1
            if self.federation is not None:
                self.federation.touch(h)
            return page, "fs"
        if not store:
            return None, None
        page = self._load_remote(h)
        if page is not None:
            with self._lock:
                self.restores += 1
            return page, "store"
        return None, None

    def fetch_store_many(self, hs: list[bytes]) -> dict[bytes, np.ndarray]:
        """Batched store leg of a restore walk: ONE federation round
        trip for every candidate hash (PR 9 follow-up — each store
        block used to be its own locate + GET). Fetched pages promote
        into the DRAM tier; ``restores`` counts only pages the caller
        actually consumes (see :meth:`note_store_restore`)."""
        if self.federation is None or not hs:
            return {}
        pages = self.federation.fetch_many(list(hs))
        for h, page in pages.items():
            with self._lock:
                self.remote_hits += 1
            self.put(h, page, publish=False)
        return pages

    def note_store_restore(self) -> None:
        """Count one batched-store page actually restored to device."""
        with self._lock:
            self.restores += 1

    def note_use(self, h: bytes) -> None:
        """Device-cache prefix hit observed by the restore walk: feed
        the federation hotness book (the device tier never calls
        get())."""
        if self.federation is not None:
            self.federation.touch(h)

    def publish_evicted(self, h: bytes) -> None:
        """Publish-on-evict hook (TieredEventSink.blocks_removed): the
        device cache just evicted a page this host still holds. The
        hotness gate runs here on the engine thread; the page bytes are
        materialized (possibly an FS load) and serialized on the
        store's publisher thread (publish_deferred), so an eviction
        burst — which lands exactly when the engine is under memory
        pressure — costs the engine thread nothing per page."""
        fed = self.federation
        if fed is None or not fed.wants_publish_on_evict(h):
            return

        def loader():
            with self._lock:
                page = self._pages.get(h)
            return page if page is not None else self._load_fs(h)

        fed.publish_deferred(h, loader)

    # ------------------------------------------------------------------ #
    # FS tier

    def _path(self, h: bytes) -> pathlib.Path:
        return self.fs_dir / f"{h.hex()}.npy"

    def _spill_fs(self, h: bytes, page: np.ndarray) -> None:
        if self.fs_dir is None:
            return
        try:
            np.save(self._path(h), page)
        except OSError as e:
            log.warning("FS spill failed: %s", e)
            return
        with self._lock:
            self._fs_lru[h] = None
            self.fs_spills += 1
            while len(self._fs_lru) > self.fs_max_pages:
                old, _ = self._fs_lru.popitem(last=False)
                try:
                    self._path(old).unlink(missing_ok=True)
                except OSError:
                    pass

    # ------------------------------------------------------------------ #
    # Federation tier (fleet-wide store; llmd_tpu/federation)

    def _load_remote(self, h: bytes) -> np.ndarray | None:
        if self.federation is None:
            return None
        page = self.federation.fetch(h)
        if page is None:
            return None
        with self._lock:
            self.remote_hits += 1
        # Promote into the local DRAM tier for subsequent hits.
        self.put(h, page, publish=False)
        return page

    def _load_fs(self, h: bytes) -> np.ndarray | None:
        if self.fs_dir is None:
            return None
        with self._lock:
            if h not in self._fs_lru:
                return None
        try:
            page = np.load(self._path(h))
        except (OSError, ValueError):
            with self._lock:
                self._fs_lru.pop(h, None)
            return None
        with self._lock:
            self.fs_loads += 1
        return page

    def drop(self, h: bytes) -> None:
        with self._lock:
            self._pages.pop(h, None)
            had_fs = self._fs_lru.pop(h, None) is not None
        if had_fs:
            try:
                self._path(h).unlink(missing_ok=True)
            except OSError:
                pass

    def clear(self) -> None:
        """Drop every tier (weight rollout: cached KV no longer matches).
        The cross-slice tier drops this host's contribution; other
        participants clear their own on their rollout."""
        with self._lock:
            self._pages.clear()
            fs = list(self._fs_lru)
            self._fs_lru.clear()
        for h in fs:
            try:
                self._path(h).unlink(missing_ok=True)
            except OSError:
                pass
        if self.federation is not None:
            self.federation.clear_local()

    def stats(self) -> dict[str, int]:
        with self._lock:
            return {
                "pages": len(self._pages),
                "fs_pages": len(self._fs_lru),
                "saves": self.saves,
                "restores": self.restores,
                "fs_spills": self.fs_spills,
                "fs_loads": self.fs_loads,
            }


class TieredEventSink(KVEventSink):
    """Wraps the engine's event sink with tier-honest semantics.

    Device eviction of a page the host tier still holds becomes
    BlockStored(medium="cpu") — the pod can still serve it (at host-load
    cost) so the indexer should score it at the cpu weight, not forget it.
    """

    def __init__(self, inner: KVEventSink, host: HostKVCache) -> None:
        self.inner = inner
        self.host = host
        # Serializes the inner sink's medium juggle: the engine thread
        # (device evictions -> cpu) and the federation publisher thread
        # (accepted publications -> store) both re-label through it.
        self._medium_lock = threading.Lock()

    def blocks_stored(self, hashes, parent, token_ids) -> None:
        # Under the medium lock: the federation's publisher thread swaps
        # inner.medium mid-emit (stored_with_medium); an unlocked pass
        # here could label fresh device commits with the swapped tier.
        with self._medium_lock:
            self.inner.blocks_stored(hashes, parent, token_ids)

    def _with_medium(self, medium: str, emit) -> None:
        with self._medium_lock:
            if hasattr(self.inner, "medium"):
                prev, self.inner.medium = self.inner.medium, medium
                try:
                    emit()
                finally:
                    self.inner.medium = prev
            else:
                emit()

    def stored_with_medium(self, hashes, medium: str) -> None:
        """Emit BlockStored under an explicit tier label (cpu for
        downgraded device evictions, store for accepted federation
        publications). Thread-safe."""
        self._with_medium(
            medium, lambda: self.inner.blocks_stored(hashes, None, [])
        )

    def removed_with_medium(self, hashes, medium: str) -> None:
        """Emit BlockRemoved under an explicit tier label — the
        federation's withdrawal of a store copy the master evicted
        (kv-federation.md staleness bound). Thread-safe."""
        self._with_medium(
            medium, lambda: self.inner.blocks_removed(hashes)
        )

    def blocks_removed(self, hashes) -> None:
        gone: list = []
        kept: list = []
        for h in hashes:
            (kept if self.host.has(h) else gone).append(h)
        if gone:
            self.inner.blocks_removed(gone)
        if kept:
            # Publish-on-evict trigger (kv-federation.md): the page
            # just left HBM but survives in a host tier — the hotness
            # gate decides whether it earns a fleet-wide copy.
            for h in kept:
                self.host.publish_evicted(h)
            self.stored_with_medium(kept, "cpu")

    def all_cleared(self) -> None:
        # Device cleared; host tier survives. Without per-block diffs the
        # honest summary is: pod still (partially) holds content. Clear only
        # if the host tier is empty.
        if len(self.host) == 0:
            self.inner.all_cleared()


class OffloadConnector:
    """Engine-side tiering pump: save committed pages, restore on prefill."""

    def __init__(
        self,
        runner,
        allocator,
        host: HostKVCache,
    ) -> None:
        self.runner = runner
        self.allocator = allocator
        self.host = host
        # (content_hash, page_id) committed this step, pending offload.
        self._pending: list[tuple[bytes, int]] = []
        # Federation accounting (kv-federation.md): prompt tokens whose
        # prefill was served by pages pulled from the fleet-wide store
        # and committed — the recompute the federation avoided.
        self.recompute_avoided_tokens = 0
        self.store_pages_committed = 0

    # -- save path (engine thread) -------------------------------------- #

    def on_commit(self, page_id: int, content_hash: bytes) -> None:
        if not self.host.has(content_hash):
            self._pending.append((content_hash, page_id))

    def flush(self) -> None:
        """One bucketed gather for all pages committed this step."""
        if not self._pending:
            return
        pending, self._pending = self._pending, []
        pages = self.runner.gather_pages([pid for _, pid in pending])
        for i, (h, _) in enumerate(pending):
            self.host.put(h, np.ascontiguousarray(pages[:, i]))

    # -- restore path (engine thread, before scheduling) ----------------- #

    def restore_for_prompt(self, prompt_token_ids: list[int]) -> int:
        """Seed the device prefix cache from the host tier.

        Finds the longest run of leading full pages where device misses are
        host hits, restores exactly the missing ones, commits them, and
        releases the refs (cache-seeding). Returns pages restored.
        """
        page = self.allocator.page_size
        hashes = page_hashes_for_tokens(prompt_token_ids, page)
        if not hashes:
            return 0
        restore: list[tuple[int, bytes, np.ndarray]] = []  # (idx, hash, data)
        store_pages = 0
        # Batched store leg (PR 9 follow-up): the first local-tier miss
        # fetches the REST of the chain from the federation in one
        # round trip (one locate + one pipelined pull per owner)
        # instead of a GET per page; the walk then consumes fetched
        # pages until the first real gap.
        store_batch: dict[bytes, np.ndarray] = {}
        store_batched = False
        for idx, h in enumerate(hashes):
            if self.allocator.has_cached(h):
                # Device-resident prefix hit: a reuse signal for the
                # publish-on-evict hotness gate.
                self.host.note_use(h)
                continue
            if store_batched and h in store_batch:
                # Batch-fetched pages count as store-served even though
                # fetch_store_many already promoted them to DRAM — the
                # promotion is an artifact of THIS walk, not a prior hit.
                data, tier = store_batch[h], "store"
                self.host.note_store_restore()
            else:
                data, tier = self.host.get_tagged(h, store=False)
            if data is None:
                if not store_batched:
                    # Only hashes no LOCAL tier holds go in the batch —
                    # fetching locally-resident pages would waste store
                    # bandwidth and mislabel their tier.
                    store_batched = True
                    store_batch = self.host.fetch_store_many([
                        h2 for h2 in hashes[idx:]
                        if not self.allocator.has_cached(h2)
                        and not self.host.has(h2)
                    ])
                    data = store_batch.get(h)
                    if data is not None:
                        tier = "store"
                        self.host.note_store_restore()
                if data is None:
                    break  # chain broken: nothing past here is usable
            if tier == "store":
                store_pages += 1
            restore.append((idx, h, data))
        if not restore:
            return 0
        from llmd_tpu.engine.kv_cache import NoFreePagesError

        try:
            page_ids = self.allocator.allocate(len(restore))
        except NoFreePagesError:
            return 0  # under pressure: recompute instead of thrashing
        try:
            stacked = np.stack([d for _, _, d in restore], axis=1)
            self.runner.scatter_pages(page_ids, stacked)
            for pid, (idx, h, _) in zip(page_ids, restore):
                chunk = prompt_token_ids[idx * page : (idx + 1) * page]
                parent = hashes[idx - 1] if idx > 0 else None
                self.allocator.commit_page(pid, h, chunk, parent)
        finally:
            # Drop our references even when the scatter/commit raises:
            # a failed restore must degrade to recompute, not bleed the
            # decode pool one restore attempt at a time.
            self.allocator.free(page_ids)
        if store_pages:
            # Counted only after the commit actually landed: these
            # tokens' prefill now rides the prefix cache instead of a
            # fleet-wide re-prefill.
            self.store_pages_committed += store_pages
            self.recompute_avoided_tokens += store_pages * page
        return len(page_ids)
