"""Tiered KV offload: HBM -> host-DRAM -> filesystem page cache.

Re-implements the reference's offloading-connector / TPUOffloadConnector
tiering (docs/architecture/advanced/kv-management/kv-offloader.md:15-21,
70-134; TPU deployment shape tiered-prefix-cache/modelserver/tpu/base/
vllm/patch-vllm.yaml:43,56-59 — HBM staging + 25000 CPU chunks):

  * save-on-fill: every page committed to the device prefix cache is also
    staged HBM -> host (one bucketed gather per engine step) and inserted
    into a capacity-capped host LRU keyed by the page's chained content
    hash;
  * restore-on-prefill: before a request is scheduled, host-cached pages
    extending the device cache's prefix run are staged host -> HBM and
    committed, so the ordinary prefix-cache hit path picks them up (the
    same cache-seeding move the P/D consumer uses);
  * optional FS tier: host-evicted pages spill to files, reloaded on miss
    (kv-offloader.md FS-backend persistence across restarts);
  * tier-honest events: a wrapping KVEventSink downgrades device evictions
    of host-held pages to BlockStored(medium="cpu") instead of removal, so
    the precise prefix indexer scores the CPU tier at weight 0.8
    (kv-indexer.md:133) rather than forgetting the pod.
"""

from __future__ import annotations

import collections
import io
import logging
import pathlib
import threading

import numpy as np

from llmd_tpu.engine.kv_cache import KVEventSink, page_hashes_for_tokens

log = logging.getLogger(__name__)


class HostKVCache:
    """Host-DRAM page store: content hash -> [L, K, page, 2D] ndarray.

    LRU with a page-count cap (the reference's CPU chunk budget). Evictions
    spill to the FS tier when configured. Thread-safe (engine thread saves,
    lookups on engine thread; FS writes on a background thread).
    """

    def __init__(
        self,
        max_pages: int = 25_000,
        fs_dir: str | None = None,
        fs_max_pages: int = 100_000,
        remote=None,  # CrossSliceStoreClient: shared tier behind DRAM/FS
    ) -> None:
        self.max_pages = max_pages
        self.fs_dir = pathlib.Path(fs_dir) if fs_dir else None
        self.fs_max_pages = fs_max_pages
        self.remote = remote
        self.remote_hits = 0
        self._lock = threading.Lock()
        self._pages: collections.OrderedDict[bytes, np.ndarray] = collections.OrderedDict()
        self._fs_lru: collections.OrderedDict[bytes, None] = collections.OrderedDict()
        if self.fs_dir is not None:
            self.fs_dir.mkdir(parents=True, exist_ok=True)
            for f in sorted(self.fs_dir.glob("*.npy")):
                try:
                    self._fs_lru[bytes.fromhex(f.stem)] = None
                except ValueError:
                    continue
        self.saves = 0
        self.restores = 0
        self.fs_spills = 0
        self.fs_loads = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._pages)

    def has(self, h: bytes) -> bool:
        with self._lock:
            return h in self._pages or h in self._fs_lru

    def put(self, h: bytes, page: np.ndarray, publish: bool = True) -> None:
        with self._lock:
            if h in self._pages:
                self._pages.move_to_end(h)
                return
            self._pages[h] = page
            self.saves += 1
            spill: list[tuple[bytes, np.ndarray]] = []
            while len(self._pages) > self.max_pages:
                old_h, old_p = self._pages.popitem(last=False)
                spill.append((old_h, old_p))
        for old_h, old_p in spill:
            self._spill_fs(old_h, old_p)
        if publish:
            self._publish_remote(h, page)

    def get(self, h: bytes) -> np.ndarray | None:
        with self._lock:
            page = self._pages.get(h)
            if page is not None:
                self._pages.move_to_end(h)
                self.restores += 1
                return page
        page = self._load_fs(h)
        if page is None:
            page = self._load_remote(h)
        if page is not None:
            self.restores += 1
        return page

    # ------------------------------------------------------------------ #
    # FS tier

    def _path(self, h: bytes) -> pathlib.Path:
        return self.fs_dir / f"{h.hex()}.npy"

    def _spill_fs(self, h: bytes, page: np.ndarray) -> None:
        if self.fs_dir is None:
            return
        try:
            np.save(self._path(h), page)
        except OSError as e:
            log.warning("FS spill failed: %s", e)
            return
        with self._lock:
            self._fs_lru[h] = None
            self.fs_spills += 1
            while len(self._fs_lru) > self.fs_max_pages:
                old, _ = self._fs_lru.popitem(last=False)
                try:
                    self._path(old).unlink(missing_ok=True)
                except OSError:
                    pass

    # ------------------------------------------------------------------ #
    # Cross-slice shared tier (Mooncake-store role; llmd_tpu/kvstore)

    def _load_remote(self, h: bytes) -> np.ndarray | None:
        if self.remote is None:
            return None
        blob = self.remote.get(h.hex())
        if blob is None:
            return None
        try:
            page = np.load(io.BytesIO(blob), allow_pickle=False)
        except (OSError, ValueError):
            return None
        with self._lock:
            self.remote_hits += 1
        # Promote into the local DRAM tier for subsequent hits.
        self.put(h, page, publish=False)
        return page

    def _publish_remote(self, h: bytes, page: np.ndarray) -> None:
        if self.remote is None:
            return
        buf = io.BytesIO()
        np.save(buf, page, allow_pickle=False)
        # Fire-and-forget: the caller is the engine thread's offload
        # flush; the client's publisher thread does the HTTP.
        self.remote.put_async(h.hex(), buf.getvalue())

    def _load_fs(self, h: bytes) -> np.ndarray | None:
        if self.fs_dir is None:
            return None
        with self._lock:
            if h not in self._fs_lru:
                return None
        try:
            page = np.load(self._path(h))
        except (OSError, ValueError):
            with self._lock:
                self._fs_lru.pop(h, None)
            return None
        with self._lock:
            self.fs_loads += 1
        return page

    def drop(self, h: bytes) -> None:
        with self._lock:
            self._pages.pop(h, None)
            had_fs = self._fs_lru.pop(h, None) is not None
        if had_fs:
            try:
                self._path(h).unlink(missing_ok=True)
            except OSError:
                pass

    def clear(self) -> None:
        """Drop every tier (weight rollout: cached KV no longer matches).
        The cross-slice tier drops this host's contribution; other
        participants clear their own on their rollout."""
        with self._lock:
            self._pages.clear()
            fs = list(self._fs_lru)
            self._fs_lru.clear()
        for h in fs:
            try:
                self._path(h).unlink(missing_ok=True)
            except OSError:
                pass
        if self.remote is not None:
            self.remote.clear_local()

    def stats(self) -> dict[str, int]:
        with self._lock:
            return {
                "pages": len(self._pages),
                "fs_pages": len(self._fs_lru),
                "saves": self.saves,
                "restores": self.restores,
                "fs_spills": self.fs_spills,
                "fs_loads": self.fs_loads,
            }


class TieredEventSink(KVEventSink):
    """Wraps the engine's event sink with tier-honest semantics.

    Device eviction of a page the host tier still holds becomes
    BlockStored(medium="cpu") — the pod can still serve it (at host-load
    cost) so the indexer should score it at the cpu weight, not forget it.
    """

    def __init__(self, inner: KVEventSink, host: HostKVCache) -> None:
        self.inner = inner
        self.host = host

    def blocks_stored(self, hashes, parent, token_ids) -> None:
        self.inner.blocks_stored(hashes, parent, token_ids)

    def blocks_removed(self, hashes) -> None:
        gone: list = []
        kept: list = []
        for h in hashes:
            (kept if self.host.has(h) else gone).append(h)
        if gone:
            self.inner.blocks_removed(gone)
        if kept and hasattr(self.inner, "medium"):
            prev, self.inner.medium = self.inner.medium, "cpu"
            try:
                self.inner.blocks_stored(kept, None, [])
            finally:
                self.inner.medium = prev
        elif kept:
            self.inner.blocks_stored(kept, None, [])

    def all_cleared(self) -> None:
        # Device cleared; host tier survives. Without per-block diffs the
        # honest summary is: pod still (partially) holds content. Clear only
        # if the host tier is empty.
        if len(self.host) == 0:
            self.inner.all_cleared()


class OffloadConnector:
    """Engine-side tiering pump: save committed pages, restore on prefill."""

    def __init__(
        self,
        runner,
        allocator,
        host: HostKVCache,
    ) -> None:
        self.runner = runner
        self.allocator = allocator
        self.host = host
        # (content_hash, page_id) committed this step, pending offload.
        self._pending: list[tuple[bytes, int]] = []

    # -- save path (engine thread) -------------------------------------- #

    def on_commit(self, page_id: int, content_hash: bytes) -> None:
        if not self.host.has(content_hash):
            self._pending.append((content_hash, page_id))

    def flush(self) -> None:
        """One bucketed gather for all pages committed this step."""
        if not self._pending:
            return
        pending, self._pending = self._pending, []
        pages = self.runner.gather_pages([pid for _, pid in pending])
        for i, (h, _) in enumerate(pending):
            self.host.put(h, np.ascontiguousarray(pages[:, i]))

    # -- restore path (engine thread, before scheduling) ----------------- #

    def restore_for_prompt(self, prompt_token_ids: list[int]) -> int:
        """Seed the device prefix cache from the host tier.

        Finds the longest run of leading full pages where device misses are
        host hits, restores exactly the missing ones, commits them, and
        releases the refs (cache-seeding). Returns pages restored.
        """
        page = self.allocator.page_size
        hashes = page_hashes_for_tokens(prompt_token_ids, page)
        if not hashes:
            return 0
        restore: list[tuple[int, bytes, np.ndarray]] = []  # (idx, hash, data)
        for idx, h in enumerate(hashes):
            if self.allocator.has_cached(h):
                continue
            data = self.host.get(h)
            if data is None:
                break  # chain broken: nothing past this point is usable
            restore.append((idx, h, data))
        if not restore:
            return 0
        from llmd_tpu.engine.kv_cache import NoFreePagesError

        try:
            page_ids = self.allocator.allocate(len(restore))
        except NoFreePagesError:
            return 0  # under pressure: recompute instead of thrashing
        stacked = np.stack([d for _, _, d in restore], axis=1)
        self.runner.scatter_pages(page_ids, stacked)
        for pid, (idx, h, _) in zip(page_ids, restore):
            chunk = prompt_token_ids[idx * page : (idx + 1) * page]
            parent = hashes[idx - 1] if idx > 0 else None
            self.allocator.commit_page(pid, h, chunk, parent)
        self.allocator.free(page_ids)
        return len(page_ids)
