"""RL rollout integration (the reference's verl integration).

Reference: guides/rl/verl-integration.md:9-36 — replace the RL
framework's least-requests rollout routing with this framework's
scheduler engine, reused out-of-cluster: an `InferenceAgentLoopManager`
routes every rollout request through the Filter/Score/Pick pipeline,
and an `InflightStore` tracks per-worker load in real time to augment
the slower polled metrics. Weight rollouts invalidate prefix-cache
affinity (the reference's AllBlocksCleared on weight sync,
kv-indexer.md:63).
"""

from llmd_tpu.rl.inflight import InflightStore
from llmd_tpu.rl.agent_loop import InferenceAgentLoopManager, RolloutResult

__all__ = ["InflightStore", "InferenceAgentLoopManager", "RolloutResult"]
