"""InferenceAgentLoopManager: scheduler-engine routing for RL rollouts.

The reference's `PyInferenceAgentLoopManager` (verl-integration.md:9-36)
replaces verl's least-requests load balancer: every rollout generation
request runs through the production Filter/Score/Pick pipeline against
the current worker set, with InflightStore supplying real-time load.
This module is framework-agnostic: an RL trainer hands it worker
addresses and calls `generate()` (or `acquire`/`release` for engines
that stream through their own client); verl's AgentLoopManager hook
would wrap these calls.

Weight-sync handling: `notify_weights_updated()` clears prefix-cache
affinity state, the analogue of the engines' `AllBlocksCleared` KV
event on RL weight rollout (reference kv-indexer.md:63) — stale
affinity would otherwise route for caches that no longer exist.
"""

from __future__ import annotations

import dataclasses
import logging
import time
import uuid

import aiohttp

from llmd_tpu.epp.config import DEFAULT_CONFIG, build_scheduler
from llmd_tpu.epp.datalayer import EndpointStore, MetricsCollector
from llmd_tpu.epp.scheduler import NoEndpointsError
from llmd_tpu.epp.types import Endpoint, LLMRequest
from llmd_tpu.rl.inflight import InflightStore

log = logging.getLogger(__name__)


@dataclasses.dataclass
class RolloutResult:
    request_id: str
    worker: str
    token_ids: list[int]
    text: str
    finish_reason: str | None
    latency_s: float


class InferenceAgentLoopManager:
    """Routes rollout requests through the scheduler engine.

    config: an EndpointPickerConfig dict (defaults to the
    optimized-baseline plugin set). Workers register via `add_worker`
    (address of an OpenAI-compatible engine).
    """

    def __init__(
        self,
        config: dict | None = None,
        scrape_interval_s: float = 2.0,
        request_timeout_s: float = 600.0,
    ) -> None:
        self.store = EndpointStore()
        self.scheduler = build_scheduler(config or DEFAULT_CONFIG)
        self.inflight = InflightStore()
        self.collector = MetricsCollector(self.store, interval_s=scrape_interval_s)
        self.request_timeout_s = request_timeout_s
        self._session: aiohttp.ClientSession | None = None
        self._started = False
        self.weight_epoch = 0

    # ------------------------------------------------------------ workers

    def add_worker(self, address: str, labels: dict | None = None) -> None:
        self.store.upsert(Endpoint(address=address, labels=labels or {}))

    def remove_worker(self, address: str) -> None:
        self.store.remove(address)
        self.inflight.drop_worker(address)
        self.scheduler.notify_endpoint_removed(address)

    def workers(self) -> list[str]:
        return [p.address for p in self.store.list()]

    async def start(self) -> None:
        if self._started:
            return
        self._session = aiohttp.ClientSession(
            timeout=aiohttp.ClientTimeout(
                total=self.request_timeout_s, sock_connect=10
            )
        )
        await self.collector.scrape_once()
        self.collector.start()
        self._started = True

    async def close(self) -> None:
        if not self._started:
            return
        await self.collector.stop()
        if self._session is not None:
            await self._session.close()
        self._started = False

    # ------------------------------------------------------------ routing

    def _request_for(self, prompt, prompt_token_ids, request_id) -> LLMRequest:
        text = prompt or ""
        if not text and prompt_token_ids:
            # Prefix-affinity scoring hashes prompt_text; token-only
            # rollouts need a stable text key or shared-prefix batches
            # spread instead of landing on the cached worker.
            text = " ".join(map(str, prompt_token_ids))
        return LLMRequest(
            request_id=request_id,
            prompt_text=text,
            prompt_token_ids=prompt_token_ids,
            path="/v1/completions",
        )

    def acquire_server(
        self,
        prompt: str | None = None,
        prompt_token_ids: list[int] | None = None,
        request_id: str | None = None,
    ) -> tuple[str, str]:
        """Pick a worker for one rollout (the verl `_acquire_server`
        analogue). Returns (worker_address, request_id); the caller MUST
        pair it with `release_server` when the rollout finishes."""
        rid = request_id or f"rollout-{uuid.uuid4().hex}"
        req = self._request_for(prompt, prompt_token_ids, rid)
        pods = self.store.list()
        # Real-time inflight view: overlay onto endpoint state so scoring
        # sees the rollout burst, not the last metrics poll.
        for p in pods:
            p.inflight = self.inflight.requests(p.address)
            p.inflight_tokens = self.inflight.tokens(p.address)
        result = self.scheduler.schedule(req, pods)
        addr = result.primary.address
        self.inflight.begin(addr, rid, req.approx_prompt_tokens)
        return addr, rid

    def release_server(self, address: str, request_id: str) -> None:
        self.inflight.end(address, request_id)

    # ------------------------------------------------------------ rollout

    async def generate(
        self,
        prompt: str | None = None,
        prompt_token_ids: list[int] | None = None,
        sampling_params: dict | None = None,
    ) -> RolloutResult:
        """One rollout generation, scheduler-routed. Token-in/token-out
        when `prompt_token_ids` is given (uses the engine's gRPC-transcoded
        Generate surface); text completion otherwise."""
        if not self._started:
            await self.start()
        sp = dict(sampling_params or {})
        addr, rid = self.acquire_server(prompt, prompt_token_ids)
        t0 = time.monotonic()
        try:
            if prompt_token_ids is not None:
                payload = {
                    "prompt_token_ids": prompt_token_ids,
                    "sampling_params": sp,
                }
                url = f"http://{addr}/vllm.Generation/Generate"
            else:
                payload = {"prompt": prompt, **sp}
                url = f"http://{addr}/v1/completions"
            async with self._session.post(
                url, json=payload, headers={"x-request-id": rid}
            ) as resp:
                if resp.status != 200:
                    raise RuntimeError(
                        f"worker {addr} returned {resp.status}: "
                        f"{(await resp.text())[:200]}"
                    )
                data = await resp.json()
        finally:
            self.release_server(addr, rid)
        if prompt_token_ids is not None:
            return RolloutResult(
                request_id=rid,
                worker=addr,
                token_ids=list(data.get("token_ids", [])),
                text="",
                finish_reason=data.get("finish_reason"),
                latency_s=time.monotonic() - t0,
            )
        choice = (data.get("choices") or [{}])[0]
        return RolloutResult(
            request_id=rid,
            worker=addr,
            token_ids=[],
            text=choice.get("text", ""),
            finish_reason=choice.get("finish_reason"),
            latency_s=time.monotonic() - t0,
        )

    async def generate_batch(
        self,
        prompts: list | None = None,
        prompt_token_ids: list[list[int]] | None = None,
        sampling_params: dict | None = None,
    ) -> list[RolloutResult]:
        """Fan a rollout batch across the worker pool concurrently —
        the shape of one verl `generate_sequences` step."""
        import asyncio

        if prompt_token_ids is not None:
            coros = [
                self.generate(prompt_token_ids=ids, sampling_params=sampling_params)
                for ids in prompt_token_ids
            ]
        else:
            coros = [
                self.generate(prompt=p, sampling_params=sampling_params)
                for p in (prompts or [])
            ]
        return list(await asyncio.gather(*coros))

    # ------------------------------------------------------------ weights

    def notify_weights_updated(self) -> None:
        """Weight rollout: all engine caches are invalid; clear prefix
        affinity so routing doesn't chase dead caches (the reference
        emits AllBlocksCleared from the engines, kv-indexer.md:63)."""
        self.weight_epoch += 1
        for p in self.store.list():
            self.scheduler.notify_endpoint_removed(p.address)
        log.info("weight epoch %d: prefix affinity cleared", self.weight_epoch)
