"""InflightStore: real-time per-worker request/token tracking.

Reference verl-integration.md:11 — "An InflightStore tracks active
requests per worker in real time, augmenting the slower
Prometheus-based metrics to give the scheduler an accurate view of
cluster load during rollout generation." Rollout bursts (hundreds of
requests dispatched within one training step) would otherwise all land
on whichever worker looked idle at the last metrics poll.
"""

from __future__ import annotations

import threading
import time


class InflightStore:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        # address -> {"requests": n, "tokens": n, "started": {rid: t0}}
        self._by_worker: dict[str, dict] = {}  # llmd: guarded_by(_lock)
        self.completed_total = 0  # llmd: guarded_by(_lock)

    def _w_locked(self, address: str) -> dict:
        return self._by_worker.setdefault(
            address, {"requests": 0, "tokens": 0, "started": {}}
        )

    def begin(self, address: str, request_id: str, tokens: int) -> None:
        with self._lock:
            w = self._w_locked(address)
            w["requests"] += 1
            w["tokens"] += tokens
            w["started"][request_id] = (time.monotonic(), tokens)

    def end(self, address: str, request_id: str) -> float | None:
        """Returns the request's wall time, or None if unknown."""
        with self._lock:
            w = self._by_worker.get(address)
            if w is None or request_id not in w["started"]:
                return None
            t0, tokens = w["started"].pop(request_id)
            w["requests"] = max(0, w["requests"] - 1)
            w["tokens"] = max(0, w["tokens"] - tokens)
            self.completed_total += 1
            return time.monotonic() - t0

    def requests(self, address: str) -> int:
        with self._lock:
            w = self._by_worker.get(address)
            return w["requests"] if w else 0

    def tokens(self, address: str) -> int:
        with self._lock:
            w = self._by_worker.get(address)
            return w["tokens"] if w else 0

    def snapshot(self) -> dict[str, dict[str, int]]:
        with self._lock:
            return {
                a: {"requests": w["requests"], "tokens": w["tokens"]}
                for a, w in self._by_worker.items()
            }

    def drop_worker(self, address: str) -> None:
        with self._lock:
            self._by_worker.pop(address, None)
