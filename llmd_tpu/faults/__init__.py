"""Deterministic fault injection for the serving stack.

The stack's degradation paths (recompute on pull failure, miss on store
timeout, re-pick on refused endpoints, resync on dropped KV events, the
engine watchdog) are only real if something exercises them. This module
makes failure a first-class, seedable input: a process-global
:class:`FaultPlan` of scoped :class:`FaultSpec` entries, armed either
programmatically (``faults.arm(plan)`` — the test-fixture path) or via
the ``LLMD_FAULT_PLAN`` environment variable (JSON, read at import —
the bench/CLI path). Injection sites threaded through the connector,
kvstore client, event subscriber, EPP datalayer/router, sidecar proxy
and engine step loop consult the plan through three tiny helpers:

- :func:`fires` — boolean gate; the SITE raises its native exception
  type (PullError, TimeoutError, ClientConnectionError, ...) so the
  degradation under test is exactly the one production would take.
- :func:`delay` — sleep ``delay_ms`` (stall/latency sites).
- :func:`corrupt` — deterministically flip payload bytes (wire sites).

Unarmed, every helper is a single module-global ``None`` check — no
allocation, no lock, no branch on the plan contents — so the hot path
pays nothing when no plan is armed (the default everywhere outside
chaos tests and the ``fault_degrade`` bench part).

Determinism: trigger selection is count-based (``after``/``times``)
and, when probabilistic (``p < 1``), drawn from a ``random.Random``
seeded from ``(plan.seed, site, match)`` — the same plan over the same
call sequence injects the same faults, which is what lets the chaos
matrix pin byte-identical degraded streams.

Known sites (the catalog; docs/architecture/fault-tolerance.md carries
the degradation contract per site):

==========================  =================================================
site                        effect at the injection point
==========================  =================================================
``kv.pull.drop``            connector chunk pull raises ``PullError``
``kv.pull.delay_ms``        connector chunk pull sleeps ``delay_ms``
``kv.bundle.corrupt``       pulled bundle bytes corrupted before decode
``engine.step.stall``       ``LLMEngine.step`` sleeps ``delay_ms`` (wedge)
``epp.scrape.fail``         EPP metrics scrape of one endpoint errors
``epp.endpoint.refuse``     EPP proxy leg raises connection-refused
``events.drop``             one KV-event batch is dropped (forces seq gap)
``kvstore.get.timeout``     kvstore client HTTP call raises ``TimeoutError``
``lockstep.sync.stall``     lockstep collective hangs past the bounded wait
``sidecar.prefill.fail``    sidecar phase-1 prefill POST raises
``serve.stream.cut``        engine SSE stream's transport severed mid-flight
``replica.crash``           fleet-sim replica dies (in-flight streams cut)
``replica.brownout``        fleet-sim replica serves ``delay_ms`` slower
``lora.load.fail``          adapter weight fetch raises ``AdapterFetchError``
``lora.fetch.delay_ms``     adapter weight fetch sleeps ``delay_ms``
==========================  =================================================

The two ``replica.*`` sites are FLEET-scoped: they are consulted by the
fleet simulator's engine stubs (:mod:`llmd_tpu.fleetsim`), keyed by the
replica address, so one seeded plan describes a whole-fleet chaos
scenario (kill replica N mid-stream, brown out replica M per-request)
alongside the per-component sites the production stack consults.
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
import zlib

SITES = frozenset({
    "kv.pull.drop",
    "kv.pull.delay_ms",
    "kv.bundle.corrupt",
    "engine.step.stall",
    "epp.scrape.fail",
    "epp.endpoint.refuse",
    "events.drop",
    "kvstore.get.timeout",
    "lockstep.sync.stall",
    "sidecar.prefill.fail",
    "serve.stream.cut",
    "replica.crash",
    "replica.brownout",
    "lora.load.fail",
    "lora.fetch.delay_ms",
})


@dataclasses.dataclass
class FaultSpec:
    """One scoped fault: site + selector + trigger window.

    ``match`` is a substring selector against the site's context key
    (request id, endpoint address, shipper key, ...); empty matches
    every call. The spec fires on matching hits ``after < n <=
    after + times`` (``times=None`` = unbounded), each firing gated by
    ``p`` (seeded)."""

    site: str
    match: str = ""
    times: int | None = 1
    after: int = 0
    p: float = 1.0
    delay_ms: float = 0.0

    def __post_init__(self) -> None:
        if self.site not in SITES:
            raise ValueError(
                f"unknown fault site {self.site!r} (known: {sorted(SITES)})"
            )


class FaultPlan:
    """An armed set of fault specs with per-spec trigger accounting."""

    def __init__(self, specs: list[FaultSpec], seed: int = 0) -> None:
        self.specs = list(specs)
        self.seed = seed
        self.injected: dict[str, int] = {}  # llmd: guarded_by(_lock)
        self._lock = threading.Lock()
        self._hits = [0] * len(self.specs)  # llmd: guarded_by(_lock)
        self._fired = [0] * len(self.specs)  # llmd: guarded_by(_lock)
        # One seeded stream per spec, keyed by (seed, site, match) so a
        # plan reordering does not reshuffle an unrelated spec's draws.
        import random

        self._rng = [
            random.Random(
                (seed << 1) ^ zlib.crc32(f"{s.site}|{s.match}".encode())
            )
            for s in self.specs
        ]
        # Sites with no spec never scan the spec list.
        self._sites = frozenset(s.site for s in self.specs)

    @classmethod
    def from_json(cls, text: str) -> "FaultPlan":
        """``{"seed": 0, "faults": [{"site": ..., "match": ...,
        "times": ..., "after": ..., "p": ..., "delay_ms": ...}]}``"""
        data = json.loads(text)
        if not isinstance(data, dict):
            raise ValueError("fault plan must be a JSON object")
        specs = [FaultSpec(**f) for f in data.get("faults", [])]
        return cls(specs, seed=int(data.get("seed", 0)))

    def should_fire(self, site: str, key: str) -> FaultSpec | None:
        if site not in self._sites:
            return None
        with self._lock:
            for i, s in enumerate(self.specs):
                if s.site != site or (s.match and s.match not in key):
                    continue
                self._hits[i] += 1
                if self._hits[i] <= s.after:
                    continue
                if s.times is not None and self._fired[i] >= s.times:
                    continue
                if s.p < 1.0 and self._rng[i].random() >= s.p:
                    continue
                self._fired[i] += 1
                self.injected[site] = self.injected.get(site, 0) + 1
                return s
        return None


# The process-global plan. None (the default) is the zero-overhead
# unarmed state: every helper below returns on one global read.
_PLAN: FaultPlan | None = None


def arm(plan: FaultPlan) -> FaultPlan:
    """Install ``plan`` process-wide (test fixtures / bench legs)."""
    global _PLAN
    _PLAN = plan
    return plan


def disarm() -> None:
    global _PLAN
    _PLAN = None


def active() -> FaultPlan | None:
    return _PLAN


def injected_counts() -> dict[str, int]:
    """{site: injections so far}; empty when unarmed (metrics surface)."""
    plan = _PLAN
    return dict(plan.injected) if plan is not None else {}


# ------------------------------------------------------------------ #
# site helpers


def fires(site: str, key: str = "") -> bool:
    """True when an armed spec fires for (site, key). The call site
    raises its native exception type so the production degradation path
    is the one exercised."""
    plan = _PLAN
    if plan is None:
        return False
    return plan.should_fire(site, key) is not None


def delay_s(site: str, key: str = "") -> float:
    """The firing spec's delay in SECONDS, without sleeping (0.0 when
    nothing fires). Simulated-time callers (the fleet simulator's
    replica stubs) advance their virtual clock by this instead of
    blocking a real thread."""
    plan = _PLAN
    if plan is None:
        return 0.0
    spec = plan.should_fire(site, key)
    if spec is not None and spec.delay_ms > 0:
        return spec.delay_ms / 1e3
    return 0.0


def delay(site: str, key: str = "") -> None:
    """Sleep the firing spec's ``delay_ms`` (stall/latency sites)."""
    dt = delay_s(site, key)
    if dt > 0:
        time.sleep(dt)


def corrupt(site: str, data: bytes, key: str = "") -> bytes:
    """Deterministically corrupt ``data`` when the spec fires: XOR the
    middle byte (header-preserving for KV bundles, so the corruption is
    exactly what a payload CRC must catch — not what a magic check
    already would)."""
    plan = _PLAN
    if plan is None:
        return data
    if plan.should_fire(site, key) is None or not data:
        return data
    b = bytearray(data)
    b[len(b) // 2] ^= 0xFF
    return bytes(b)


# Bench/CLI arming: a JSON plan in the environment is read once at
# import. Tests use arm()/disarm() directly.
_env_plan = os.environ.get("LLMD_FAULT_PLAN")
if _env_plan:
    arm(FaultPlan.from_json(_env_plan))
del _env_plan
