"""Workload profiles: declarative load + data generation specs.

Mirrors the reference inference-perf profile fields
(guides/pd-disaggregation/benchmark-templates/tpu.yaml: load.type
constant with rate/duration stages; agentic guide.yaml: load.type
concurrent with num_requests/concurrency_level stages, lognormal token
distributions, shared system prompts).
"""

from __future__ import annotations

import dataclasses
import math
import random


@dataclasses.dataclass
class Distribution:
    """Token-count distribution: constant, uniform or lognormal."""

    type: str = "constant"  # constant | uniform | lognormal
    mean: float = 256.0
    min: float = 1.0
    max: float = 1_000_000.0
    std_dev: float = 0.0

    def sample(self, rng: random.Random) -> int:
        if self.type == "constant":
            v = self.mean
        elif self.type == "uniform":
            v = rng.uniform(self.min, self.max)
        elif self.type == "lognormal" and self.std_dev <= 0:
            v = self.mean
        elif self.type == "lognormal":
            # Parameterized by arithmetic mean/std of the underlying value
            # (the reference profiles specify mean/std_dev in token units).
            m, s = max(self.mean, 1e-9), max(self.std_dev, 1e-9)
            sigma2 = math.log(1.0 + (s * s) / (m * m))
            mu = math.log(m) - sigma2 / 2.0
            v = rng.lognormvariate(mu, math.sqrt(sigma2))
        else:
            raise ValueError(f"unknown distribution type {self.type!r}")
        return int(max(self.min, min(self.max, round(v))))


@dataclasses.dataclass
class Stage:
    """One load stage.

    Open-loop (reference load.type=constant): `rate` req/s for `duration`
    seconds (Poisson arrivals). Closed-loop (load.type=concurrent):
    `num_requests` total at `concurrency` in flight.
    """

    rate: float | None = None
    duration_s: float | None = None
    num_requests: int | None = None
    concurrency: int | None = None

    @property
    def open_loop(self) -> bool:
        return self.rate is not None


@dataclasses.dataclass
class WorkloadSpec:
    name: str = "custom"
    stages: list[Stage] = dataclasses.field(default_factory=list)
    # data generation
    data_type: str = "random"  # random | shared_prefix | conversation
    input_tokens: Distribution = dataclasses.field(default_factory=Distribution)
    output_tokens: Distribution = dataclasses.field(
        default_factory=lambda: Distribution(mean=128)
    )
    # shared_prefix: `num_groups` distinct prefixes of `prefix_tokens`,
    # each question continues one group's prefix (tiered/precise guides).
    num_groups: int = 8
    prefix_tokens: int = 1024
    # conversation: multi-turn sessions re-sending accumulated context
    # (agentic guide) — `turns` per conversation, shared system prompt.
    turns: Distribution = dataclasses.field(
        default_factory=lambda: Distribution(mean=4, min=1, max=64)
    )
    system_prompt_tokens: int = 512
    # History cap (tokens): long conversations keep the system prompt and
    # slide the rest, like real agent frameworks (the reference profile's
    # max_model_len knob). The cap converts to characters via
    # chars_per_token: ~4 for BPE tokenizers; set ~1 (and/or a smaller
    # cap) for byte-level tokenizers or the trimmed prompt still exceeds
    # the server's max_model_len.
    max_context_tokens: int = 8000
    chars_per_token: float = 4.0
    streaming: bool = True
    api: str = "completion"  # completion | chat
    ignore_eos: bool = True
    seed: int = 7

    def total_planned_requests(self) -> int | None:
        n = 0
        for s in self.stages:
            if s.num_requests is not None:
                n += s.num_requests
            elif s.rate is not None and s.duration_s is not None:
                n += int(s.rate * s.duration_s)
            else:
                return None
        return n


# ---------------------------------------------------------------- prompts

_WORDS = (
    "alpha bravo charlie delta echo foxtrot golf hotel india juliet kilo "
    "lima mike november oscar papa quebec romeo sierra tango uniform victor "
    "whiskey xray yankee zulu".split()
)


def synth_text(rng: random.Random, n_tokens: int) -> str:
    """~1 word ≈ 1 token for whitespace tokenizers; for BPE tokenizers the
    EPP-side char-ratio heuristic (4 chars/token) also roughly holds."""
    return " ".join(rng.choice(_WORDS) for _ in range(max(1, n_tokens)))


class PromptSource:
    """Stateful prompt generator for one workload run."""

    def __init__(self, spec: WorkloadSpec) -> None:
        self.spec = spec
        self.rng = random.Random(spec.seed)
        self._prefixes = [
            synth_text(self.rng, spec.prefix_tokens)
            for _ in range(max(1, spec.num_groups))
        ]
        self._system = synth_text(self.rng, spec.system_prompt_tokens)
        # live conversations: list of (history_text, turns_left)
        self._conversations: list[list] = []

    def next_request(self) -> tuple[str, int]:
        """Returns (prompt_text, max_tokens)."""
        spec = self.spec
        out_toks = spec.output_tokens.sample(self.rng)
        isl = spec.input_tokens.sample(self.rng)
        if spec.data_type == "random":
            return synth_text(self.rng, isl), out_toks
        if spec.data_type == "shared_prefix":
            prefix = self.rng.choice(self._prefixes)
            return prefix + " " + synth_text(self.rng, isl), out_toks
        if spec.data_type == "conversation":
            if not self._conversations or (
                len(self._conversations) < 64 and self.rng.random() < 0.3
            ):
                turns = spec.turns.sample(self.rng)
                self._conversations.append([self._system, turns])
            conv = self.rng.choice(self._conversations)
            conv[0] = conv[0] + " " + synth_text(self.rng, isl)
            conv[1] -= 1
            # sliding window: keep the shared system prompt + recent tail
            max_chars = int(spec.max_context_tokens * spec.chars_per_token)
            if len(conv[0]) > max_chars:
                keep = max_chars - len(self._system)
                # NB: [-keep:] with keep==0 would be [0:] — the WHOLE string
                tail = conv[0][-keep:] if keep > 0 else ""
                conv[0] = self._system + tail
            prompt = conv[0]
            if conv[1] <= 0:
                self._conversations.remove(conv)
            return prompt, out_toks
        raise ValueError(f"unknown data_type {spec.data_type!r}")


# ---------------------------------------------------------------- profiles

PROFILES: dict[str, WorkloadSpec] = {
    # Smoke-level check (the reference "sanity" workload).
    "sanity": WorkloadSpec(
        name="sanity",
        stages=[Stage(num_requests=8, concurrency=2)],
        input_tokens=Distribution(mean=64, min=16, max=128),
        output_tokens=Distribution(mean=32, min=8, max=64),
    ),
    # random_1k_1k_isl_osl (pd-disaggregation TPU template).
    "random_1k_1k": WorkloadSpec(
        name="random_1k_1k",
        stages=[Stage(rate=1.0, duration_s=120.0)],
        input_tokens=Distribution(mean=1024),
        output_tokens=Distribution(mean=1024),
    ),
    # shared_prefix_synthetic (tiered/precise prefix-cache guides).
    "shared_prefix_synthetic": WorkloadSpec(
        name="shared_prefix_synthetic",
        data_type="shared_prefix",
        stages=[Stage(num_requests=64, concurrency=8)],
        num_groups=8,
        prefix_tokens=2048,
        input_tokens=Distribution(mean=128, min=32, max=512),
        output_tokens=Distribution(mean=128, min=16, max=256),
    ),
    # Agentic multi-turn sessions (agentic-serving guide, scaled down).
    "agentic": WorkloadSpec(
        name="agentic",
        data_type="conversation",
        stages=[Stage(num_requests=64, concurrency=8)],
        system_prompt_tokens=1024,
        turns=Distribution(type="lognormal", mean=6, std_dev=4, min=1, max=64),
        input_tokens=Distribution(
            type="lognormal", mean=256, std_dev=192, min=32, max=2048
        ),
        output_tokens=Distribution(
            type="lognormal", mean=128, std_dev=96, min=16, max=1024
        ),
    ),
    # Rate ladder (precise-prefix benchmark: rate 3 -> 60).
    "rate_ladder": WorkloadSpec(
        name="rate_ladder",
        stages=[
            Stage(rate=r, duration_s=30.0) for r in (1.0, 2.0, 4.0, 8.0)
        ],
        input_tokens=Distribution(mean=512),
        output_tokens=Distribution(mean=128),
    ),
}


def get_profile(name: str, **overrides) -> WorkloadSpec:
    """Profile by name with per-run field overrides (the CLI
    `--overrides key=value` mechanism). Structured fields given as JSON
    (stages, token distributions) are rebuilt into their dataclasses."""
    spec = dataclasses.replace(PROFILES[name])
    for k, v in overrides.items():
        if not hasattr(spec, k):
            raise KeyError(f"unknown workload field {k!r}")
        if k == "stages" and isinstance(v, list):
            v = [s if isinstance(s, Stage) else Stage(**s) for s in v]
        elif isinstance(getattr(spec, k), Distribution) and isinstance(v, dict):
            v = Distribution(**v)
        setattr(spec, k, v)
    return spec
