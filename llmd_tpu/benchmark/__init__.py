"""Benchmark harness (the reference's `llmdbenchmark` / inference-perf).

Drives an OpenAI-compatible endpoint (engine or router) with declarative
workload profiles — constant-rate open-loop stages or concurrency-bound
closed-loop stages over random / shared-prefix / multi-turn-agentic data
generators — records per-request lifecycle (TTFT, TPOT, E2E, tokens),
and produces summary + per-stage reports (JSON and markdown).

Reference shape: helpers/benchmark.md:25-90 and the
guides/*/benchmark-templates/*.yaml workload profiles (load.type
constant|concurrent, data.type random|shared_prefix|conversation_replay,
report.request_lifecycle summary/per_stage/per_request).
"""

from llmd_tpu.benchmark.workload import (
    Distribution,
    Stage,
    WorkloadSpec,
    get_profile,
    PROFILES,
)
from llmd_tpu.benchmark.loadgen import LoadGenerator, RequestRecord
from llmd_tpu.benchmark.analysis import analyze, render_markdown

__all__ = [
    "Distribution",
    "Stage",
    "WorkloadSpec",
    "get_profile",
    "PROFILES",
    "LoadGenerator",
    "RequestRecord",
    "analyze",
    "render_markdown",
]
