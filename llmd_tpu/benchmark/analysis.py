"""Result analysis: lifecycle summaries + per-stage breakdowns.

Produces the metric set the reference guides publish in their
benchmark-results tables (e.g. pd-disaggregation/README.md:600-615:
mean/P50/P90/P95/P99 TTFT, TPOT/ITL, E2E, output tok/s, req/s,
success/failure counts).
"""

from __future__ import annotations

import statistics
from typing import Any

from llmd_tpu.benchmark.loadgen import RequestRecord


def _pct(sorted_vals: list[float], p: float) -> float:
    if not sorted_vals:
        return 0.0
    k = min(len(sorted_vals) - 1, max(0, int(round(p / 100.0 * (len(sorted_vals) - 1)))))
    return sorted_vals[k]


def _dist(vals: list[float]) -> dict[str, float]:
    if not vals:
        return {"mean": 0.0, "p50": 0.0, "p90": 0.0, "p95": 0.0, "p99": 0.0}
    s = sorted(vals)
    return {
        "mean": statistics.fmean(s),
        "p50": _pct(s, 50),
        "p90": _pct(s, 90),
        "p95": _pct(s, 95),
        "p99": _pct(s, 99),
    }


def analyze(records: list[RequestRecord]) -> dict[str, Any]:
    ok = [r for r in records if r.ok]
    failed = [r for r in records if not r.ok]
    if records:
        t0 = min(r.start_s for r in records)
        t1 = max(
            (r.start_s + (r.e2e_s or 0.0)) for r in records
        )
        wall = max(t1 - t0, 1e-9)
    else:
        wall = 1e-9
    out_tokens = sum(r.output_tokens for r in ok)
    summary = {
        "requests": len(records),
        "succeeded": len(ok),
        "failed": len(failed),
        "wall_s": wall,
        "request_throughput_rps": len(ok) / wall,
        "output_tokens": out_tokens,
        "output_tok_per_s": out_tokens / wall,
        "ttft_s": _dist([r.ttft_s for r in ok if r.ttft_s is not None]),
        "tpot_s": _dist([r.tpot_s for r in ok if r.tpot_s is not None]),
        "e2e_s": _dist([r.e2e_s for r in ok if r.e2e_s is not None]),
    }
    per_stage: dict[str, Any] = {}
    for idx in sorted({r.stage for r in records}):
        srecs = [r for r in ok if r.stage == idx]
        if not srecs:
            per_stage[str(idx)] = {"succeeded": 0}
            continue
        st0 = min(r.start_s for r in srecs)
        st1 = max(r.start_s + (r.e2e_s or 0.0) for r in srecs)
        sw = max(st1 - st0, 1e-9)
        per_stage[str(idx)] = {
            "succeeded": len(srecs),
            "output_tok_per_s": sum(r.output_tokens for r in srecs) / sw,
            "ttft_s": _dist([r.ttft_s for r in srecs if r.ttft_s is not None]),
            "e2e_s": _dist([r.e2e_s for r in srecs if r.e2e_s is not None]),
        }
    errors: dict[str, int] = {}
    for r in failed:
        key = r.error or f"http_{r.status}"
        errors[key] = errors.get(key, 0) + 1
    return {"summary": summary, "per_stage": per_stage, "errors": errors}


def render_markdown(report: dict[str, Any], title: str = "benchmark") -> str:
    s = report["summary"]

    def row(name: str, d: dict[str, float], scale: float = 1.0, unit: str = "s") -> str:
        return (
            f"| {name} | {d['mean']*scale:.3f} | {d['p50']*scale:.3f} | "
            f"{d['p90']*scale:.3f} | {d['p95']*scale:.3f} | {d['p99']*scale:.3f} | {unit} |"
        )

    lines = [
        f"# {title}",
        "",
        f"- requests: {s['requests']} (ok {s['succeeded']}, failed {s['failed']})",
        f"- wall: {s['wall_s']:.1f}s",
        f"- request throughput: {s['request_throughput_rps']:.2f} req/s",
        f"- output token throughput: {s['output_tok_per_s']:.1f} tok/s",
        "",
        "| metric | mean | p50 | p90 | p95 | p99 | unit |",
        "|---|---|---|---|---|---|---|",
        row("TTFT", s["ttft_s"]),
        row("TPOT", s["tpot_s"], 1000.0, "ms"),
        row("E2E", s["e2e_s"]),
    ]
    if report.get("errors"):
        lines += ["", "## Errors", ""]
        for k, v in sorted(report["errors"].items()):
            lines.append(f"- {k}: {v}")
    if len(report.get("per_stage", {})) > 1:
        lines += ["", "## Per stage", "", "| stage | ok | tok/s | TTFT p50 | TTFT p99 |", "|---|---|---|---|---|"]
        for idx, st in report["per_stage"].items():
            if st.get("succeeded"):
                lines.append(
                    f"| {idx} | {st['succeeded']} | {st['output_tok_per_s']:.1f} "
                    f"| {st['ttft_s']['p50']:.3f} | {st['ttft_s']['p99']:.3f} |"
                )
            else:
                lines.append(f"| {idx} | 0 | - | - | - |")
    return "\n".join(lines) + "\n"
