"""`python -m llmd_tpu.benchmark` — the benchmark CLI.

The no-cluster analogue of the reference `llmdbenchmark run`
(helpers/benchmark.md:66-90): point at an endpoint, pick a workload
profile, get a JSON report (+ optional markdown analysis).

Examples:
    python -m llmd_tpu.benchmark --url http://localhost:8800 \
        --model llama-3-8b --workload sanity
    python -m llmd_tpu.benchmark --url http://localhost:8800 \
        --model llama-3-8b --workload shared_prefix_synthetic \
        --overrides prefix_tokens=4096 seed=13 --analyze -o results.json
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys


def _parse_overrides(pairs: list[str]) -> dict:
    out = {}
    for pair in pairs:
        k, _, v = pair.partition("=")
        if not _:
            raise SystemExit(f"--overrides entries must be key=value, got {pair!r}")
        try:
            out[k] = json.loads(v)
        except json.JSONDecodeError:
            out[k] = v
    return out


def main(argv=None) -> None:
    from llmd_tpu.benchmark.analysis import analyze, render_markdown
    from llmd_tpu.benchmark.loadgen import LoadGenerator
    from llmd_tpu.benchmark.workload import PROFILES, get_profile

    p = argparse.ArgumentParser("llmd-tpu benchmark")
    p.add_argument("--url", required=True, help="endpoint base URL")
    p.add_argument("--model", required=True)
    p.add_argument("--workload", default="sanity", choices=sorted(PROFILES))
    p.add_argument(
        "--overrides", nargs="*", default=[],
        help="workload field overrides, key=value (JSON values accepted)",
    )
    p.add_argument("--request-timeout", type=float, default=600.0)
    p.add_argument("-o", "--output", default=None, help="write JSON report here")
    p.add_argument("--analyze", action="store_true", help="print markdown report")
    args = p.parse_args(argv)

    spec = get_profile(args.workload, **_parse_overrides(args.overrides))
    gen = LoadGenerator(args.url, args.model, spec, args.request_timeout)
    records = asyncio.run(gen.run())
    report = analyze(records)
    report["workload"] = spec.name
    report["endpoint"] = args.url
    if args.output:
        with open(args.output, "w") as f:
            json.dump(report, f, indent=2)
    if args.analyze:
        print(render_markdown(report, title=f"{spec.name} @ {args.url}"))
    else:
        s = report["summary"]
        print(json.dumps({
            "workload": spec.name,
            "requests": s["requests"],
            "failed": s["failed"],
            "req_per_s": round(s["request_throughput_rps"], 3),
            "output_tok_per_s": round(s["output_tok_per_s"], 1),
            "ttft_p50_s": round(s["ttft_s"]["p50"], 4),
            "ttft_p99_s": round(s["ttft_s"]["p99"], 4),
            "tpot_p50_ms": round(s["tpot_s"]["p50"] * 1e3, 2),
        }))
    if report["summary"]["failed"] and not report["summary"]["succeeded"]:
        sys.exit(1)


if __name__ == "__main__":
    main()
