"""Async load generator: open-loop (Poisson) and closed-loop stages.

Per-request lifecycle recording matches the reference report fields
(report.request_lifecycle per_request: start, TTFT, TPOT, E2E, token
counts, status). Streamed completions count SSE frames for TTFT/ITL the
same way the router does.
"""

from __future__ import annotations

import asyncio
import dataclasses
import json
import random
import time

import aiohttp

from llmd_tpu.benchmark.workload import PromptSource, Stage, WorkloadSpec


@dataclasses.dataclass
class RequestRecord:
    stage: int
    start_s: float
    ttft_s: float | None = None
    e2e_s: float | None = None
    prompt_tokens: int = 0
    output_tokens: int = 0
    status: int = 0
    error: str = ""

    @property
    def ok(self) -> bool:
        return 200 <= self.status < 300 and not self.error

    @property
    def tpot_s(self) -> float | None:
        if (
            self.ttft_s is None
            or self.e2e_s is None
            or self.output_tokens <= 1
        ):
            return None
        return (self.e2e_s - self.ttft_s) / (self.output_tokens - 1)


class LoadGenerator:
    def __init__(
        self,
        base_url: str,
        model: str,
        spec: WorkloadSpec,
        request_timeout_s: float = 600.0,
    ) -> None:
        self.base_url = base_url.rstrip("/")
        self.model = model
        self.spec = spec
        self.timeout_s = request_timeout_s
        self.records: list[RequestRecord] = []
        self._prompts = PromptSource(spec)
        self._rng = random.Random(spec.seed ^ 0x5EED)

    # ------------------------------------------------------------ request

    async def _one(
        self, session: aiohttp.ClientSession, stage_idx: int
    ) -> RequestRecord:
        prompt, max_tokens = self._prompts.next_request()
        rec = RequestRecord(
            stage=stage_idx,
            start_s=time.monotonic(),
            prompt_tokens=max(1, len(prompt) // 4),
        )
        if self.spec.api == "chat":
            path = "/v1/chat/completions"
            body = {
                "model": self.model,
                "messages": [{"role": "user", "content": prompt}],
                "max_tokens": max_tokens,
                "stream": self.spec.streaming,
                "ignore_eos": self.spec.ignore_eos,
            }
        else:
            path = "/v1/completions"
            body = {
                "model": self.model,
                "prompt": prompt,
                "max_tokens": max_tokens,
                "stream": self.spec.streaming,
                "ignore_eos": self.spec.ignore_eos,
            }
        t0 = rec.start_s
        try:
            async with session.post(self.base_url + path, json=body) as resp:
                rec.status = resp.status
                if resp.status != 200:
                    rec.error = (await resp.text())[:200]
                    rec.e2e_s = time.monotonic() - t0
                    return rec
                if self.spec.streaming:
                    # Chat streams open with a role-priming frame emitted
                    # before any token is generated; TTFT must anchor on
                    # the first CONTENT frame, and the role frame must not
                    # count as an output token.
                    n_frames = 0
                    usage_tokens = None
                    carry = b""
                    async for chunk in resp.content.iter_any():
                        lines = (carry + chunk).split(b"\n")
                        carry = lines.pop()
                        for ln in lines:
                            if not ln.startswith(b"data:") or b"[DONE]" in ln:
                                continue
                            # The engine fuses multiple tokens per SSE
                            # frame — up to decode_window for plain
                            # fused windows, and up to window x (1 + k)
                            # when speculative fused verify windows
                            # accept a full draft — so frames undercount
                            # tokens: trust the stream's usage frame and
                            # fall back to frame counting only when
                            # usage is absent.
                            if b'"usage"' in ln:
                                try:
                                    u = json.loads(ln[5:]).get("usage") or {}
                                    if "completion_tokens" in u:
                                        usage_tokens = u["completion_tokens"]
                                except (json.JSONDecodeError, AttributeError):
                                    pass
                            if (
                                self.spec.api == "chat"
                                and b'"content"' not in ln
                                and n_frames == 0
                            ):
                                continue  # role-priming frame
                            n_frames += 1
                            if rec.ttft_s is None:
                                rec.ttft_s = time.monotonic() - t0
                    rec.output_tokens = (
                        usage_tokens
                        if usage_tokens is not None
                        else max(0, n_frames - 1)  # final frame = usage
                    )
                else:
                    data = await resp.json()
                    rec.ttft_s = time.monotonic() - t0
                    rec.output_tokens = (
                        data.get("usage", {}).get("completion_tokens", 0)
                    )
                rec.e2e_s = time.monotonic() - t0
        except (aiohttp.ClientError, asyncio.TimeoutError) as e:
            rec.error = type(e).__name__
            rec.e2e_s = time.monotonic() - t0
        return rec

    # ------------------------------------------------------------ stages

    async def _run_closed_loop(
        self, session: aiohttp.ClientSession, stage: Stage, stage_idx: int
    ) -> None:
        assert stage.num_requests is not None
        sem = asyncio.Semaphore(stage.concurrency or 1)
        remaining = stage.num_requests

        async def worker():
            async with sem:
                rec = await self._one(session, stage_idx)
                self.records.append(rec)

        await asyncio.gather(*(worker() for _ in range(remaining)))

    async def run(self) -> list[RequestRecord]:
        timeout = aiohttp.ClientTimeout(total=self.timeout_s, sock_connect=10)
        async with aiohttp.ClientSession(timeout=timeout) as session:
            for i, stage in enumerate(self.spec.stages):
                if stage.open_loop:
                    await self._run_open_loop(session, stage, i)
                else:
                    await self._run_closed_loop(session, stage, i)
        return self.records

    async def _run_open_loop(
        self, session: aiohttp.ClientSession, stage: Stage, stage_idx: int
    ) -> None:
        """Poisson arrivals at `rate` for `duration_s`, no concurrency cap
        (open loop measures the system, not the client); optional
        num_requests cap ends the stage early."""
        assert stage.rate is not None and stage.duration_s is not None
        end = time.monotonic() + stage.duration_s
        tasks: list[asyncio.Task] = []
        while time.monotonic() < end:
            if stage.num_requests is not None and len(tasks) >= stage.num_requests:
                break
            tasks.append(asyncio.ensure_future(self._one(session, stage_idx)))
            await asyncio.sleep(self._rng.expovariate(stage.rate))
        for rec in await asyncio.gather(*tasks):
            self.records.append(rec)
