"""Plugin framework: Filter → Scorer → Picker → ProfileHandler.

The reference's scheduler runs per-profile plugin chains with weighted score
summation (docs/architecture/core/router/epp/scheduling.md:44-118); plugins
are declared by type/name/parameters in EndpointPickerConfig
(docs/api-reference/endpointpickerconfig.md:11-75). Same model here: a
registry keyed by plugin type name, instantiated from config dicts.
"""

from __future__ import annotations

import random
from typing import Any, Callable

from llmd_tpu.epp.types import Endpoint, LLMRequest, ProfileResult

_REGISTRY: dict[str, Callable[..., Any]] = {}


def register(type_name: str):
    def deco(cls):
        _REGISTRY[type_name] = cls
        cls.plugin_type = type_name
        return cls

    return deco


def create_plugin(type_name: str, **parameters):
    if type_name not in _REGISTRY:
        raise KeyError(f"unknown plugin type {type_name!r}; known: {sorted(_REGISTRY)}")
    return _REGISTRY[type_name](**parameters)


def registered_plugins() -> list[str]:
    return sorted(_REGISTRY)


class Filter:
    """Drops endpoints that cannot serve the request."""

    def filter(self, req: LLMRequest, pods: list[Endpoint]) -> list[Endpoint]:
        raise NotImplementedError

    def on_routed(self, req: LLMRequest, pod: Endpoint) -> None:
        """Hook after the pick lands on ``pod`` (state-tracking filters,
        e.g. prefix-cache-affinity's own index)."""

    def on_endpoint_removed(self, address: str) -> None:
        """Hook when an endpoint leaves the pool (index cleanup)."""


class Scorer:
    """Scores each endpoint in [0, 1] (higher = better)."""

    def score(self, req: LLMRequest, pods: list[Endpoint]) -> dict[str, float]:
        raise NotImplementedError

    def on_routed(self, req: LLMRequest, pod: Endpoint) -> None:
        """Hook after the pick lands on ``pod`` (state-updating scorers)."""

    def on_complete(self, req: LLMRequest, pod: Endpoint) -> None:
        """Hook when the request finishes on ``pod``."""

    def on_endpoint_removed(self, address: str) -> None:
        """Hook when an endpoint leaves the pool (index cleanup)."""


class Picker:
    """Chooses one endpoint from the scored set."""

    def pick(
        self, req: LLMRequest, scored: dict[str, float], pods: list[Endpoint]
    ) -> Endpoint | None:
        raise NotImplementedError


class SchedulingProfile:
    """One filter→score→pick chain (scheduling.md:60-68)."""

    def __init__(
        self,
        name: str,
        filters: list[Filter] | None = None,
        scorers: list[tuple[Scorer, float]] | None = None,
        picker: Picker | None = None,
    ) -> None:
        self.name = name
        self.filters = filters or []
        self.scorers = scorers or []
        self.picker = picker or MaxScorePicker()

    def run(self, req: LLMRequest, pods: list[Endpoint]) -> ProfileResult:
        for f in self.filters:
            pods = f.filter(req, pods)
            if not pods:
                return ProfileResult(self.name, None)
        totals: dict[str, float] = {p.address: 0.0 for p in pods}
        for scorer, weight in self.scorers:
            part = scorer.score(req, pods)
            for addr in totals:
                totals[addr] += weight * part.get(addr, 0.0)
        chosen = self.picker.pick(req, totals, pods)
        return ProfileResult(self.name, chosen, totals)

    def notify_routed(self, req: LLMRequest, pod: Endpoint) -> None:
        for f in self.filters:
            f.on_routed(req, pod)
        for scorer, _ in self.scorers:
            scorer.on_routed(req, pod)

    def notify_complete(self, req: LLMRequest, pod: Endpoint) -> None:
        for scorer, _ in self.scorers:
            scorer.on_complete(req, pod)


# --------------------------------------------------------------------- #
# Pickers (scheduling.md:104-108)


@register("max-score-picker")
class MaxScorePicker(Picker):
    """Highest total score; ties broken randomly (the default picker)."""

    def __init__(self, seed: int | None = None) -> None:
        self._rng = random.Random(seed)

    def pick(self, req, scored, pods):
        if not pods:
            return None
        best = max(scored.get(p.address, 0.0) for p in pods)
        top = [p for p in pods if scored.get(p.address, 0.0) >= best - 1e-12]
        return self._rng.choice(top)


@register("random-picker")
class RandomPicker(Picker):
    def __init__(self, seed: int | None = None) -> None:
        self._rng = random.Random(seed)

    def pick(self, req, scored, pods):
        return self._rng.choice(pods) if pods else None


@register("weighted-random-picker")
class WeightedRandomPicker(Picker):
    """Probability proportional to score (exploration-friendly)."""

    def __init__(self, seed: int | None = None) -> None:
        self._rng = random.Random(seed)

    def pick(self, req, scored, pods):
        if not pods:
            return None
        weights = [max(scored.get(p.address, 0.0), 0.0) + 1e-9 for p in pods]
        return self._rng.choices(pods, weights=weights, k=1)[0]
