"""Precise prefix-cache routing: token producer + event-indexed scorer.

Reference pipeline (kv-indexer.md:104-135; SURVEY.md §3.5): on each request
the `token-producer` tokenizes the prompt via an engine render endpoint
(/tokenize here, matching vLLM's /v1/completions/render role), computes the
chained block hashes — the SAME chain the engines commit pages under
(llmd_tpu.engine.kv_cache.hash_page) — and the `precise-prefix-cache-scorer`
scores endpoints by the KV-event index's weighted longest-consecutive-prefix
(gpu=1.0 / cpu=0.8 tiers). After a pick, speculative entries with a 2s TTL
co-route identical-prompt bursts (kv-indexer.md:137-143).

With KV federation (docs/architecture/kv-federation.md) the scorecard is
TRI-STATE: blocks published to the fleet-wide store score the `store`
weight (default 0.5, `LLMD_PREFIX_TIER_WEIGHTS`/`tier_weights`) on EVERY
endpoint, so the scheduler can prefer a cold-but-idle replica plus a
store fetch over queueing behind the one replica holding the prefix —
the fetch-on-miss leg then pulls the pages instead of re-prefilling.
"""

from __future__ import annotations

import collections
import logging

import aiohttp

from llmd_tpu.engine.kv_cache import page_hashes_for_tokens
from llmd_tpu.epp.plugins import Scorer, register
from llmd_tpu.epp.types import BLOCK_SIZE, Endpoint, LLMRequest
from llmd_tpu.events.index import KVBlockIndex
from llmd_tpu.events.subscriber import KVEventSubscriber

log = logging.getLogger(__name__)

# Pod label carrying the ZMQ event endpoint port (pod-discovery mode,
# reference precise-prefix-cache-routing.values.yaml socketPort: 5556).
KV_EVENTS_PORT_LABEL = "llm-d.ai/kv-events-port"
DEFAULT_EVENTS_PORT = 5556

SCRATCH_BLOCK_HASHES = "block_hashes"


class TokenProducer:
    """Async data producer: prompt text -> token ids -> block hashes.

    Calls an engine's /tokenize endpoint (any healthy pod — the shared
    render-service pattern, kv-indexer.md:104-113) with a small LRU so
    bursts of identical prompts tokenize once.
    """

    def __init__(
        self,
        default_page_size: int = 16,
        max_prefix_tokens: int = 262144,  # agentic ceiling (predicted-latency.values.yaml:24-33)
        cache_size: int = 512,
    ) -> None:
        self.default_page_size = default_page_size
        self.max_prefix_tokens = max_prefix_tokens
        self._cache: collections.OrderedDict[tuple, list[str]] = collections.OrderedDict()
        self.cache_size = cache_size
        self._session: aiohttp.ClientSession | None = None

    async def _client(self) -> aiohttp.ClientSession:
        if self._session is None or self._session.closed:
            # Tokenization is in the admission hot path: keep the bound tight
            # so one wedged pod cannot stall scheduling (fall back to
            # approximate scoring instead).
            self._session = aiohttp.ClientSession(
                timeout=aiohttp.ClientTimeout(total=1.0, sock_connect=0.5)
            )
        return self._session

    def _page_size(self, pods: list[Endpoint]) -> int:
        for p in pods:
            bs = p.attr(BLOCK_SIZE)
            if bs:
                return int(bs)
        return self.default_page_size

    async def produce(self, req: LLMRequest, pods: list[Endpoint]) -> None:
        if SCRATCH_BLOCK_HASHES in req.scratch or not pods:
            return
        page = self._page_size(pods)
        # LoRA key folding (reference kv-indexer.md:145-151): engines salt
        # adapter pages with `lora:<name>`; fold the same salt when the
        # requested model id is a registered adapter on any pod, or
        # unsalted hashes would (mis)match base-model pages.
        extra = b""
        if req.model:
            for p in pods:
                if req.model in (p.attrs.get("AvailableAdapters") or ()):
                    extra = f"lora:{req.model}".encode()
                    break
        token_ids = req.prompt_token_ids
        if token_ids is None:
            key = (hash(req.prompt_text), page, extra)
            cached = self._cache.get(key)
            if cached is not None:
                self._cache.move_to_end(key)
                req.scratch[SCRATCH_BLOCK_HASHES] = cached
                return
            token_ids = await self._tokenize(req, pods)
            if token_ids is None:
                return  # no render endpoint reachable; precise scoring skipped
        token_ids = token_ids[: self.max_prefix_tokens]
        hashes = [
            h.hex() for h in page_hashes_for_tokens(token_ids, page, extra)
        ]
        req.scratch[SCRATCH_BLOCK_HASHES] = hashes
        if req.prompt_token_ids is None:
            self._cache[(hash(req.prompt_text), page, extra)] = hashes
            while len(self._cache) > self.cache_size:
                self._cache.popitem(last=False)

    async def _tokenize(
        self, req: LLMRequest, pods: list[Endpoint]
    ) -> list[int] | None:
        session = await self._client()
        healthy = [p for p in pods if p.healthy] or pods
        # Chat requests must tokenize through the chat template (the engine
        # commits pages under templated ids); /tokenize handles both forms.
        if "messages" in req.body:
            payload = {"messages": req.body["messages"], "model": req.model}
        else:
            payload = {"prompt": req.prompt_text, "model": req.model}
        for pod in healthy[:2]:  # try at most two endpoints
            try:
                async with session.post(
                    f"{pod.url}/tokenize", json=payload,
                ) as resp:
                    if resp.status != 200:
                        continue
                    data = await resp.json()
                    return list(data.get("tokens", []))
            except (aiohttp.ClientError, TimeoutError, ValueError) as e:
                log.debug("tokenize via %s failed: %s", pod.address, e)
        return None

    async def close(self) -> None:
        if self._session is not None and not self._session.closed:
            await self._session.close()


@register("precise-prefix-cache-scorer")
class PrecisePrefixCacheScorer(Scorer):
    """Scores endpoints from the KV-event block index.

    Score = weighted longest consecutive prefix / total prompt blocks, so a
    full hot-tier hit scores 1.0. Also publishes per-pod match fractions to
    scratch['prefix_match_frac'] for the disagg decider (scheduler.py).
    """

    def __init__(
        self,
        index: KVBlockIndex | None = None,
        max_blocks_per_pod: int = 131072,
        speculative_ttl_s: float = 2.0,
        backend: str = "lru",
        redis_host: str = "127.0.0.1",
        redis_port: int = 6379,
        tier_weights: dict | None = None,
    ) -> None:
        """backend: the reference's three indexer backends
        (kv-indexer.md:59-151) — `lru` (in-memory two-level), `cost-aware`
        (frequency-sketch eviction), `redis` (shared Redis/Valkey).

        tier_weights: per-deployment overrides of the tri-state weight
        table (kv-federation.md), e.g. ``{"store": 0.4}`` — layered over
        the defaults and the ``LLMD_PREFIX_TIER_WEIGHTS`` env
        (EndpointPickerConfig: ``"parameters": {"tier_weights": ...}``)."""
        if index is None:
            if backend == "redis":
                from llmd_tpu.events.redis_index import RedisKVBlockIndex

                index = RedisKVBlockIndex(
                    host=redis_host, port=redis_port,
                    speculative_ttl_s=speculative_ttl_s,
                    tier_weights=tier_weights,
                )
            elif backend == "cost-aware":
                from llmd_tpu.events.index import CostAwareKVBlockIndex

                index = CostAwareKVBlockIndex(
                    max_blocks_per_pod=max_blocks_per_pod,
                    speculative_ttl_s=speculative_ttl_s,
                    tier_weights=tier_weights,
                )
            elif backend == "lru":
                index = KVBlockIndex(
                    max_blocks_per_pod=max_blocks_per_pod,
                    speculative_ttl_s=speculative_ttl_s,
                    tier_weights=tier_weights,
                )
            else:
                raise ValueError(
                    f"unknown index backend {backend!r} "
                    "(expected lru | cost-aware | redis)"
                )
        self.index = index

    def score(self, req: LLMRequest, pods: list[Endpoint]) -> dict[str, float]:
        hashes = req.scratch.get(SCRATCH_BLOCK_HASHES)
        if not hashes:
            return {p.address: 0.0 for p in pods}
        # The predicted-latency producer may have walked THIS index for
        # the same request already (store-aware admission); reuse its
        # result instead of paying the O(pods x hashes) walk twice per
        # scheduling pass. Keyed by index identity so a second scorer
        # over a different index never reuses the wrong walk.
        cached = req.scratch.get(f"prefix_detailed:{id(self.index)}")
        if cached is not None and all(p.address in cached for p in pods):
            detailed = {p.address: cached[p.address] for p in pods}
        else:
            detailed = self.index.score_detailed(
                hashes, [p.address for p in pods]
            )
        n = len(hashes)
        fracs = req.scratch.setdefault("prefix_match_frac", {})
        weighted = req.scratch.setdefault("prefix_weighted_frac", {})
        out: dict[str, float] = {}
        for addr, (s, matched) in detailed.items():
            out[addr] = s / n
            fracs[addr] = max(fracs.get(addr, 0.0), matched / n)
            # Store-aware admission (kv-federation.md): the WEIGHTED
            # fraction charges a store-fetchable prefix at its tier
            # weight (default 0.5) — less than a recompute (0), more
            # than resident (1) — and is what the latency predictor's
            # prefix feature should see instead of the flat match count.
            weighted[addr] = max(weighted.get(addr, 0.0), s / n)
        return out

    def on_routed(self, req: LLMRequest, pod: Endpoint) -> None:
        hashes = req.scratch.get(SCRATCH_BLOCK_HASHES)
        if hashes:
            self.index.insert_speculative(pod.address, hashes)

    def on_endpoint_removed(self, address: str) -> None:
        self.index.remove_pod(address)


def attach_precise_routing(
    router,
    default_events_port: int = DEFAULT_EVENTS_PORT,
    tier_weights: str | None = None,
):
    """Wire token-producer + KV-event subscription onto a built Router.

    Finds the precise scorer instance(s) in the router's scheduler, attaches
    a TokenProducer to the producer phase and a KVEventsSource to the pool.
    Returns the KVEventsSource (caller owns close()) or None if the config
    has no precise scorer.

    ``tier_weights``: raw ``tier=w,...`` overrides from the router's
    ``--prefix-tier-weights`` flag, layered OVER whatever the index was
    constructed with (defaults < env < scorer config < flag).
    """
    from llmd_tpu.epp.config import find_plugins
    from llmd_tpu.events.index import parse_tier_weights

    scorers = find_plugins(router.scheduler, PrecisePrefixCacheScorer)
    if not scorers:
        return None
    if tier_weights:
        for scorer in scorers:
            scorer.index.tier_weights.update(parse_tier_weights(tier_weights))
    router.producers.append(TokenProducer())
    source = KVEventsSource(
        router.store, scorers[0].index, default_port=default_events_port
    )
    router.closables.append(source)
    router.closables.append(scorers[0].index)  # redis backend owns a socket

    # Prefix-indexer self-metrics (reference scheduling.md:161-191:
    # indexer size / hit ratio).
    index = scorers[0].index

    def render_index_metrics() -> str:
        st = index.stats()
        lines = [
            "# TYPE llm_d_epp_prefix_index_blocks gauge",
            f"llm_d_epp_prefix_index_blocks {st.get('blocks', 0)}",
            "# TYPE llm_d_epp_prefix_index_events_total counter",
            f"llm_d_epp_prefix_index_events_total {st.get('events', 0)}",
            "# TYPE llm_d_epp_prefix_index_lookups_total counter",
            f"llm_d_epp_prefix_index_lookups_total {st.get('lookups', 0)}",
            "# TYPE llm_d_epp_prefix_index_hits_total counter",
            f"llm_d_epp_prefix_index_hits_total {st.get('hits', 0)}",
            # Federation visibility: blocks the index knows to be one
            # fetch away in the fleet-wide store (kv-federation.md).
            "# TYPE llm_d_epp_prefix_index_store_blocks gauge",
            f"llm_d_epp_prefix_index_store_blocks {st.get('store_blocks', 0)}",
        ]
        return "\n".join(lines)

    router.metric_extras.append(render_index_metrics)
    return source


class KVEventsSource:
    """Data-layer source wiring pool membership to the event subscriber.

    The `endpoint-notification-source` of the reference data layer
    (datalayer.md:49-91) in pod-discovery mode: on pod add, subscribe to its
    event socket; on remove, drop its index entries.
    """

    def __init__(
        self,
        store,
        index: KVBlockIndex,
        default_port: int = DEFAULT_EVENTS_PORT,
    ) -> None:
        self.subscriber = KVEventSubscriber(index)
        self.default_port = default_port
        store.on_add(self._added)
        store.on_remove(self.subscriber.remove_pod)
        for ep in store.list():
            self._added(ep)

    def _added(self, ep: Endpoint) -> None:
        endpoint = ep.labels.get("llm-d.ai/kv-events-endpoint")
        if not endpoint:
            host = ep.address.rsplit(":", 1)[0]
            port = ep.labels.get(KV_EVENTS_PORT_LABEL, self.default_port)
            endpoint = f"tcp://{host}:{port}"
        self.subscriber.add_pod(ep.address, endpoint)

    def close(self) -> None:
        self.subscriber.close()
