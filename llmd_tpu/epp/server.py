"""The router process: aiohttp reverse proxy wired to the EPP pipeline.

Request path (SURVEY.md §3.1 call stack): parse (openai-parser) →
admitters → flow control EnqueueAndWait → data producers → scheduler
(filter/score/pick) → proxy to the picked endpoint (streaming passthrough)
→ response processors (latency sampling, inflight accounting, prefix-index
update via scorer hooks). The reference splits proxy (Envoy) from picker
(EPP ext-proc); standalone mode fuses them in one process, matching the
no-Kubernetes deployment shape (guides/no-kubernetes-deployment/README.md).
"""

from __future__ import annotations

import asyncio
import collections
import json
import logging
import os
import random

import aiohttp
from aiohttp import web

from llmd_tpu import clock, faults
from llmd_tpu.epp import filters as filters_mod
from llmd_tpu.epp.breaker import EndpointCircuitBreaker
from llmd_tpu.epp.datalayer import EndpointStore, FileDiscoverySource, MetricsCollector
from llmd_tpu.epp.flow_control import OUTCOME_HTTP, FlowControl, Outcome
from llmd_tpu.epp.handler import (
    GENERATE_PATHS,
    VLLMGRPC_PATHS,
    Admitter,
    ParseError,
    parse_request,
)
from llmd_tpu.epp.scheduler import NoEndpointsError, Scheduler
from llmd_tpu.obs.tracing import get_tracer
from llmd_tpu.epp.types import (
    HDR_DROP_REASON,
    HDR_ENCODER,
    HDR_PREFILLER,
    HDR_RESUME,
    HDR_STREAM_TOKENS,
    KV_CACHE_USAGE,
    ROLE_ENCODE,
    WAITING_QUEUE_SIZE,
    Endpoint,
    LLMRequest,
)

log = logging.getLogger(__name__)

HOP_HEADERS = {
    "connection",
    "keep-alive",
    "transfer-encoding",
    "te",
    "upgrade",
    "proxy-authorization",
    "proxy-authenticate",
    "host",
    "content-length",
}


class UpstreamServerError(RuntimeError):
    """Picked endpoint answered 5xx: retryable on another replica."""

    def __init__(self, status: int, body: str = "") -> None:
        super().__init__(f"upstream returned {status}: {body}")
        self.status = status


class MidStreamFailure(RuntimeError):
    """The upstream died AFTER its stream was committed to the client
    (connection reset / truncated payload / timeout past first byte).
    The bytes already forwarded cannot be replayed on a plain re-pick —
    recovery is the stream-continuation protocol
    (docs/architecture/fault-tolerance.md): re-pick excluding the dead
    endpoint and resume with the accumulated token history."""

    def __init__(self, cause: BaseException) -> None:
        super().__init__(
            f"mid-stream upstream failure: {str(cause) or type(cause).__name__}"
        )
        self.cause = cause


class ClientDisconnected(RuntimeError):
    """The CLIENT went away mid-stream (its write side reset). Not an
    upstream failure: it must neither feed the breaker nor trigger a
    resume — there is nobody left to stream to."""


class ResumeRejected(RuntimeError):
    """A resume leg was refused by the upstream with a non-retryable
    status: the terminal error is surfaced to the client faithfully."""

    def __init__(self, status: int, body: str = "") -> None:
        super().__init__(f"resume rejected with {status}: {body}")
        self.status = status


class _StreamState:
    """Client-stream continuity across upstream attempts.

    Holds the ONE prepared client response a streaming request writes
    through (legs after the first graft onto it), the line-reassembly
    carry, and — when resume is armed (``accumulate``) — the token
    history parsed out of the frames' ``token_ids``. On the OpenAI
    surface the field is a router-requested ANNOTATION
    (:data:`~llmd_tpu.epp.types.HDR_STREAM_TOKENS`) and is stripped
    before bytes reach the client (``strip=True``); on the vllmgrpc
    surface ``token_ids`` IS the stream payload — it is read but
    forwarded untouched. Only COMPLETE SSE lines are forwarded: a frame
    truncated by a crash never reaches the client, so the delivered
    history is exactly ``tokens``."""

    def __init__(self, accumulate: bool, strip: bool = True) -> None:
        self.accumulate = accumulate
        self.strip = strip
        self.resp: web.StreamResponse | None = None
        self.tokens: list[int] = []
        self.carry = b""
        self.frames = 0  # complete data frames forwarded (all legs)
        self.done_sent = False  # [DONE] forwarded: the stream is whole
        # True once a replay leg has been issued: every subsequent
        # upstream request carries HDR_RESUME so the engine grafts onto
        # the open client stream (no re-emitted preambles) even when the
        # accumulated history is still empty.
        self.resuming = False

    @property
    def streamed(self) -> bool:
        """Bytes are committed to the client (prepared + written)."""
        return self.resp is not None

    def ingest(self, chunk: bytes) -> tuple[bytes, int]:
        """Split ``chunk`` into complete lines, strip ``token_ids`` from
        data frames (accumulating them as the resume history), and
        return (bytes to forward, complete data frames seen)."""
        lines = (self.carry + chunk).split(b"\n")
        self.carry = lines.pop()
        if not lines:
            return b"", 0
        n_data = 0
        out: list[bytes] = []
        for ln in lines:
            if ln.startswith(b"data:"):
                # Exact-match terminator: generated TEXT may legally
                # contain the substring "[DONE]" inside a JSON frame —
                # only the bare sentinel line ends the stream.
                if ln.strip() == b"data: [DONE]":
                    self.done_sent = True
                else:
                    n_data += 1
                    if self.accumulate and b"token_ids" in ln:
                        ln = self._strip_tokens(ln)
            out.append(ln)
        self.frames += n_data
        return b"\n".join(out) + b"\n", n_data

    def _strip_tokens(self, line: bytes) -> bytes:
        try:
            obj = json.loads(line[5:])
        except ValueError:
            return line
        if not isinstance(obj, dict) or "token_ids" not in obj:
            return line
        toks = obj.pop("token_ids")
        if isinstance(toks, list):
            self.tokens.extend(int(t) for t in toks)
        if not self.strip:
            # vllmgrpc: token_ids is the payload, not an annotation —
            # the client must receive the original bytes.
            return line
        # The engine emits frames with compact separators; re-dumping
        # with the same separators keeps the client bytes identical to
        # a never-annotated stream.
        return b"data: " + json.dumps(obj, separators=(",", ":")).encode()

    def flush(self) -> bytes:
        """Trailing partial line at clean stream end (non-SSE bodies
        routed through a streaming request, bodies without a final
        newline): forward it verbatim."""
        tail, self.carry = self.carry, b""
        return tail


def _env_max_resumes() -> int:
    try:
        return int(os.environ.get("LLMD_EPP_MAX_RESUMES", "2"))
    except ValueError:
        return 2


def _env_backoff_s() -> float:
    return float(os.environ.get("LLMD_EPP_RETRY_BACKOFF_S", "0.05"))


def _env_backoff_cap_s() -> float:
    return float(os.environ.get("LLMD_EPP_RETRY_BACKOFF_CAP_S", "1.0"))


def backoff_delay(
    prev_s: float, base_s: float, cap_s: float, rng: random.Random
) -> float:
    """Decorrelated-jitter retry backoff: ``min(cap, U(base, prev*3))``.

    Capped exponential backoff with no jitter SYNCHRONIZES re-pick
    storms: every request that failed against a dead replica in the
    same instant sleeps the same deterministic series and lands on the
    next pick together — the herd just moves. Decorrelated jitter keeps
    the exponential envelope (the upper bound triples per attempt, so a
    persistently-failing pool still backs off hard) while spreading each
    retry uniformly over the window, so concurrent failures de-cohere
    after one round. Pass the PREVIOUS returned delay back in as
    ``prev_s`` (seed it with ``base_s`` before the first retry).

    Shared by the router's retry loop and the fleet simulator's
    transport driver — the soak exercises this exact function.
    """
    return min(cap_s, rng.uniform(base_s, max(prev_s * 3.0, base_s)))


def eligible_pods(pods, tried: set, breaker: EndpointCircuitBreaker):
    """Retry-attempt candidate set: drop already-tried endpoints, then
    skip open-circuit ones — unless that empties the pool: stale breaker
    state must degrade to trying, never turn a routable pool into a
    manufactured 503 while replicas idle. (Shared with the fleet
    simulator so the soak drives the identical schedule-time gate.)"""
    pods = [p for p in pods if p.address not in tried]
    closed = [p for p in pods if not breaker.is_open(p.address)]
    return closed or pods


class RouterMetrics:
    """EPP self-metrics (reference scheduling.md:161-191)."""

    def __init__(self) -> None:
        self.requests_total = 0
        self.scheduling_attempts = 0
        self.scheduling_errors = 0
        self.proxy_errors = 0
        self.request_retries = 0
        # Mid-stream failover (the stream-continuation contract,
        # docs/architecture/fault-tolerance.md): upstream failures after
        # first byte, successful resume re-picks, delivered tokens
        # replayed as resume history, and streams that exhausted the
        # resume budget (the client saw a terminal error frame).
        self.mid_stream_failures = 0
        self.stream_resumes = 0
        self.resume_replayed_tokens = 0
        self.stream_resume_failures = 0
        self.ttft_sum = 0.0
        self.ttft_count = 0
        self.e2e_sum = 0.0
        self.outcome_counts: collections.Counter = collections.Counter()

    def render(
        self,
        store: EndpointStore,
        flow: FlowControl,
        breaker: EndpointCircuitBreaker | None = None,
    ) -> str:
        pods = store.list()
        ready = sum(1 for p in pods if p.healthy)
        avg_kv = sum(p.attr(KV_CACHE_USAGE) for p in pods) / max(len(pods), 1)
        avg_q = sum(p.attr(WAITING_QUEUE_SIZE) for p in pods) / max(len(pods), 1)
        lines = [
            "# TYPE llm_d_epp_ready_endpoints gauge",
            f"llm_d_epp_ready_endpoints {ready}",
            "# TYPE llm_d_epp_pool_avg_kv_cache_utilization gauge",
            f"llm_d_epp_pool_avg_kv_cache_utilization {avg_kv:.6f}",
            "# TYPE llm_d_epp_pool_avg_queue_size gauge",
            f"llm_d_epp_pool_avg_queue_size {avg_q:.6f}",
            "# TYPE llm_d_epp_flow_control_queue_size gauge",
            f"llm_d_epp_flow_control_queue_size {flow.queue_depth()}",
            "# TYPE llm_d_epp_requests_total counter",
            f"llm_d_epp_requests_total {self.requests_total}",
            "# TYPE llm_d_epp_scheduling_attempts_total counter",
            f"llm_d_epp_scheduling_attempts_total {self.scheduling_attempts}",
            "# TYPE llm_d_epp_scheduling_errors_total counter",
            f"llm_d_epp_scheduling_errors_total {self.scheduling_errors}",
            "# TYPE llm_d_epp_proxy_errors_total counter",
            f"llm_d_epp_proxy_errors_total {self.proxy_errors}",
            "# TYPE llm_d_epp_request_retries_total counter",
            f"llm_d_epp_request_retries_total {self.request_retries}",
            "# TYPE llm_d_epp_mid_stream_failures_total counter",
            f"llm_d_epp_mid_stream_failures_total {self.mid_stream_failures}",
            "# TYPE llm_d_epp_stream_resumes_total counter",
            f"llm_d_epp_stream_resumes_total {self.stream_resumes}",
            "# TYPE llm_d_epp_resume_replayed_tokens_total counter",
            f"llm_d_epp_resume_replayed_tokens_total {self.resume_replayed_tokens}",
            "# TYPE llm_d_epp_stream_resume_failures_total counter",
            f"llm_d_epp_stream_resume_failures_total {self.stream_resume_failures}",
            "# TYPE llm_d_epp_fail_open_total counter",
            f"llm_d_epp_fail_open_total {filters_mod.fail_open_total()}",
        ]
        if breaker is not None:
            lines.append("# TYPE llm_d_epp_circuit_open gauge")
            for addr in breaker.open_endpoints():
                lines.append(f'llm_d_epp_circuit_open{{endpoint="{addr}"}} 1')
            lines.append("# TYPE llm_d_epp_circuit_trips_total counter")
            lines.append(
                f"llm_d_epp_circuit_trips_total {breaker.trips_total}"
            )
        for oc, n in {**flow.outcomes, **self.outcome_counts}.items():
            name = oc.value if isinstance(oc, Outcome) else str(oc)
            lines.append(
                f'llm_d_epp_flow_control_outcomes_total{{outcome="{name}"}} {n}'
            )
        if self.ttft_count:
            lines += [
                "# TYPE llm_d_epp_ttft_seconds_mean gauge",
                f"llm_d_epp_ttft_seconds_mean {self.ttft_sum / self.ttft_count:.6f}",
                "# TYPE llm_d_epp_e2e_seconds_mean gauge",
                f"llm_d_epp_e2e_seconds_mean {self.e2e_sum / self.ttft_count:.6f}",
            ]
        return "\n".join(lines) + "\n"


class Router:
    def __init__(
        self,
        store: EndpointStore,
        scheduler: Scheduler,
        flow_control: FlowControl | None = None,
        collector: MetricsCollector | None = None,
        discovery: FileDiscoverySource | None = None,
        admitters: list[Admitter] | None = None,
        producers: list | None = None,
        request_timeout_s: float = 600.0,
        max_schedule_attempts: int = 3,
        default_parser: str = "openai-parser",
        breaker: EndpointCircuitBreaker | None = None,
        retry_backoff_s: float | None = None,
        retry_backoff_cap_s: float | None = None,
        retry_rng: random.Random | None = None,
        max_resumes: int | None = None,
    ) -> None:
        self.store = store
        self.scheduler = scheduler
        self.flow = flow_control or FlowControl()
        self.collector = collector
        self.discovery = discovery
        self.admitters = admitters or []
        # Async DataProducers (request-handling.md:26-52): run after flow
        # dispatch, before scheduling (token-producer, latency predictor...).
        self.producers = producers or []
        # Attached resources with close()/async close() (KV-event sources,
        # predictor clients...); closed on app cleanup.
        self.closables: list = []
        self.metrics = RouterMetrics()
        # Extra /metrics sections from attached subsystems (prefix index,
        # predictors...): callables returning Prometheus text.
        self.metric_extras: list = []
        self.request_timeout_s = request_timeout_s
        self.max_schedule_attempts = max_schedule_attempts
        # Request-outcome circuit breaker (trips faster than the 3-scrape
        # health window) + decorrelated-jitter backoff between re-picks
        # (base/cap env-tunable: LLMD_EPP_RETRY_BACKOFF_S /
        # LLMD_EPP_RETRY_BACKOFF_CAP_S; the rng is injectable so the
        # fleet soak replays byte-identically).
        self.breaker = breaker or EndpointCircuitBreaker()
        self.retry_backoff_s = (
            _env_backoff_s() if retry_backoff_s is None else retry_backoff_s
        )
        self.retry_backoff_cap_s = (
            _env_backoff_cap_s()
            if retry_backoff_cap_s is None
            else retry_backoff_cap_s
        )
        self._retry_rng = retry_rng or random.Random()
        # Mid-stream failover budget: how many times ONE request's cut
        # stream may be resumed on a fresh replica before the failure is
        # surfaced to the client (LLMD_EPP_MAX_RESUMES; 0 disables
        # resume — mid-stream failures then terminate the stream with a
        # faithful error frame but still feed the breaker).
        self.max_resumes = (
            _env_max_resumes() if max_resumes is None else max_resumes
        )
        # Readiness: flipped off FIRST on graceful shutdown so the
        # gateway stops routing before flow control starts evicting.
        self.ready = True
        # Parser for paths outside the OpenAI/vllm-gRPC sets
        # ("passthrough-parser" routes opaque payloads through the
        # scheduler instead of the unscored passthrough handler).
        self.default_parser = default_parser
        self._session: aiohttp.ClientSession | None = None
        # Async callbacks (req, pod, ttft_ms|None, tpot_ms|None) fired after
        # each proxied request — the latency-predictor training feedback
        # (reference latency-predictor.md: observed TTFT/TPOT per request).
        self.completion_observers: list = []
        # Strong refs to in-flight observer tasks (GC safety).
        self._observer_tasks: set[asyncio.Task] = set()

    async def _run_observers(self, req, pod, ttft_ms, tpot_ms) -> None:
        for obs in self.completion_observers:
            try:
                await obs(req, pod, ttft_ms, tpot_ms)
            # llmd: allow(broad-except) -- observers are fire-and-forget telemetry; the response is already written
            except Exception:
                log.exception("completion observer failed")

    # ------------------------------------------------------------------ #

    @staticmethod
    async def _error_body(upstream) -> str:
        """Best-effort snippet of an upstream error body: a connection
        cut mid-read of a 5xx body must not crash the retry path — the
        status alone is enough to act on."""
        try:
            body = await upstream.read()
        except (aiohttp.ClientError, asyncio.TimeoutError):
            return "<body unavailable: connection cut>"
        return body[:200].decode("utf-8", "replace")

    @staticmethod
    async def _client_write(resp: web.StreamResponse, data: bytes) -> None:
        """Write to the CLIENT side of the proxy, converting transport
        failures to :class:`ClientDisconnected` so they can never be
        mistaken for upstream death (aiohttp >= 3.10 raises
        `ClientConnectionResetError` — a ClientError — for writes to a
        closed client transport, which would otherwise match the
        upstream-failure handlers and feed a healthy pod's breaker)."""
        try:
            await resp.write(data)
        except (ConnectionResetError, aiohttp.ClientConnectionError) as e:
            raise ClientDisconnected(str(e)) from e

    async def _client(self) -> aiohttp.ClientSession:
        if self._session is None:
            self._session = aiohttp.ClientSession(
                timeout=aiohttp.ClientTimeout(total=self.request_timeout_s, sock_connect=5)
            )
        return self._session

    def _pool_stats(self) -> tuple[float, float]:
        pods = self.store.list()
        if not pods:
            return 1.0, float("inf")  # empty pool counts as saturated
        kv = sum(p.attr(KV_CACHE_USAGE) for p in pods) / len(pods)
        q = sum(p.attr(WAITING_QUEUE_SIZE) for p in pods) / len(pods)
        return kv, q

    # ------------------------------------------------------------------ #
    # HTTP handlers

    async def handle_generate(self, request: web.Request) -> web.StreamResponse:
        self.metrics.requests_total += 1
        raw = await request.read()
        try:
            req = parse_request(
                request.path, dict(request.headers), raw, self.default_parser
            )
        except ParseError as e:
            return web.json_response(
                {"error": {"message": str(e), "type": "invalid_request_error"}},
                status=400,
            )
        # Root/continued span for the whole routed request (reference
        # tracing.md: the EPP continues the proxy's traceparent; sampling
        # is parent-based). The span travels in req.scratch so scheduling
        # and proxying annotate it (P/D decision intelligence).
        span = get_tracer().start_span(
            "router.request",
            traceparent=req.headers.get("traceparent"),
            kind="SPAN_KIND_SERVER",
        )
        span.set("gen_ai.request.model", req.model)
        span.set("http.route", req.path)
        span.set("llm_d.request.priority", req.priority)
        span.set("llm_d.request.prompt_tokens", req.approx_prompt_tokens)
        req.scratch["span"] = span
        try:
            return await self._handle_generate_traced(request, req, raw, span)
        except BaseException as e:
            span.error(str(e))
            raise
        finally:
            span.end()

    async def _handle_generate_traced(
        self, request: web.Request, req: LLMRequest, raw: bytes, span
    ) -> web.StreamResponse:
        # Cheap admitters reject before the request can occupy queue
        # capacity or a dispatch slot; producer-dependent admitters run
        # after dispatch (below).
        for adm in self.admitters:
            if not adm.needs_producers:
                reason = adm.admit(req)
                if reason is not None:
                    return web.json_response(
                        {"error": {"message": reason, "type": "rejected"}},
                        status=429,
                        headers={HDR_DROP_REASON: reason},
                    )
        t_enq = clock.monotonic()
        outcome = await self.flow.enqueue_and_wait(req, nbytes=len(raw))
        span.set("llm_d.flow_control.wait_s", clock.monotonic() - t_enq)
        span.set("llm_d.flow_control.outcome", str(outcome.value))
        if outcome is not Outcome.DISPATCHED:
            status, reason = OUTCOME_HTTP[outcome]
            return web.json_response(
                {"error": {"message": reason, "type": "flow-control"}},
                status=status,
                headers={HDR_DROP_REASON: reason, "retry-after": "1"},
            )
        try:
            # DataProducers run before Admitters (reference
            # request-handling.md:26-52 / SURVEY.md §3.1 step 4) so admission
            # decisions see prefix hashes and latency predictions.
            for producer in self.producers:
                try:
                    await producer.produce(req, self.store.list())
                # llmd: allow(broad-except) -- producers enrich scheduling data; scoring degrades without it rather than failing the request
                except Exception:
                    log.exception("data producer %s failed", type(producer).__name__)
            for adm in self.admitters:
                if not adm.needs_producers:
                    continue
                reason = adm.admit(req)
                if reason is not None:
                    return web.json_response(
                        {"error": {"message": reason, "type": "rejected"}},
                        status=429,
                        headers={HDR_DROP_REASON: reason},
                    )
            return await self._route_and_proxy(request, req, raw)
        finally:
            self.flow.release()

    def _resume_armed(self, req: LLMRequest) -> bool:
        """Resume applies to streaming generate requests the router can
        REPLAY: a parsed JSON body (the openai/vllmgrpc surfaces — the
        passthrough parser carries opaque bytes) with a single choice
        (n > 1 interleaves choices the router cannot attribute)."""
        if self.max_resumes <= 0 or not req.streaming:
            return False
        if not isinstance(req.body, dict) or not req.body:
            return False
        if req.path not in GENERATE_PATHS | VLLMGRPC_PATHS:
            return False
        try:
            if int(req.body.get("n") or 1) != 1:
                return False
        except (TypeError, ValueError):
            return False
        return True

    def _request_deadline(self, request: web.Request) -> float | None:
        """Monotonic deadline from `x-request-deadline-s` (the same
        header the engine enforces): the resume loop must not keep a
        client past its own budget."""
        try:
            v = float(request.headers.get("x-request-deadline-s", ""))
        except ValueError:
            return None
        return clock.monotonic() + v if v > 0 else None

    async def _fail_stream(
        self, state: _StreamState, message: str, code: int
    ) -> web.StreamResponse:
        """Terminal error frame on an already-committed client stream:
        the contract when recovery is exhausted — the client sees the
        failure faithfully, as a frame, never a silent truncation."""
        self.metrics.stream_resume_failures += 1
        assert state.resp is not None
        try:
            await state.resp.write(
                b"data: "
                + json.dumps(
                    {"error": {"message": message, "type": "upstream_error",
                               "code": code}},
                    separators=(",", ":"),
                ).encode()
                + b"\n\n"
            )
            await state.resp.write(b"data: [DONE]\n\n")
            await state.resp.write_eof()
        except (ConnectionResetError, RuntimeError,
                aiohttp.ClientConnectionError):
            pass  # the client went away too; nothing left to tell it
        return state.resp

    def _resume_body(self, req: LLMRequest, state: _StreamState) -> bytes:
        """The replay request: the original parsed body plus the
        delivered history — admitted downstream as prefill of committed
        prefix, continuing at the exact next output position."""
        return json.dumps(
            {**req.body, "resume_token_ids": list(state.tokens)}
        ).encode()

    async def _route_and_proxy(
        self, request: web.Request, req: LLMRequest, raw: bytes
    ) -> web.StreamResponse:
        tried: set[str] = set()
        prev_backoff = self.retry_backoff_s
        state: _StreamState | None = None
        if req.streaming:
            # OpenAI frames need the HDR_STREAM_TOKENS annotation
            # (stripped before the client); vllmgrpc frames carry
            # token_ids natively and must reach the client untouched.
            state = _StreamState(
                self._resume_armed(req),
                strip=req.path not in VLLMGRPC_PATHS,
            )
            if state.accumulate:
                # A client-initiated resume already carries history: the
                # next replay must extend it, not restart from it.
                prior = req.body.get("resume_token_ids") or []
                if isinstance(prior, list) and all(
                    isinstance(t, int) for t in prior
                ):
                    state.tokens.extend(prior)
        deadline = self._request_deadline(request)
        pre_failures = 0  # pre-stream failures (connect / 5xx before bytes)
        resumes = 0  # mid-stream continuations used
        while True:
            self.metrics.scheduling_attempts += 1
            pods = eligible_pods(self.store.list(), tried, self.breaker)
            try:
                result = self.scheduler.schedule(req, pods)
            except NoEndpointsError as e:
                self.metrics.scheduling_errors += 1
                if state is not None and state.streamed:
                    return await self._fail_stream(state, str(e), 503)
                return web.json_response(
                    {"error": {"message": str(e), "type": "no-endpoints"}},
                    status=503,
                    headers={HDR_DROP_REASON: "no-endpoints"},
                )
            pod = result.primary
            tried.add(pod.address)
            # llmd: allow(release-on-all-paths) -- the claimed grant resolves inside _proxy: record_success on the response path, record_failure on 5xx/refusal (the except arm here covers the transport-error edge)
            if not self.breaker.take_probe(pod.address):
                # Half-open endpoint whose single probe is already in
                # flight: losing the grant race is not an upstream
                # failure — re-pick at once, no backoff, no breaker
                # count.
                continue
            span = req.scratch.get("span")
            if span is not None:
                span.set("llm_d.decision.endpoint", pod.address)
                span.set(
                    "llm_d.decision.prefill",
                    result.prefill.address if result.prefill else "",
                )
                for pname, pres in result.profiles.items():
                    if pres.endpoint is not None and pres.scores:
                        span.set(
                            f"llm_d.score.{pname}",
                            round(pres.scores.get(pres.endpoint.address, 0.0), 4),
                        )
            extra_headers = {}
            if result.encode is not None:
                extra_headers[HDR_ENCODER] = result.encode.address
                if span is not None:
                    span.set("llm_d.decision.encode", result.encode.address)
            prefill_pod = result.prefill
            if prefill_pod is not None:
                extra_headers[HDR_PREFILLER] = prefill_pod.address
                # Prefill load rides for the duration of the proxied request
                # (its prefill phase happens within it); released below.
                prefill_pod.inflight_tokens += req.approx_prompt_tokens
            try:
                return await self._proxy(
                    request, req, raw, pod, extra_headers,
                    retry_5xx=pre_failures + 1 < self.max_schedule_attempts,
                    state=state,
                )
            except (
                aiohttp.ClientConnectionError,
                asyncio.TimeoutError,
                UpstreamServerError,
            ) as e:
                self.metrics.proxy_errors += 1
                self.breaker.record_failure(pod.address)
                if not isinstance(e, UpstreamServerError):
                    # The endpoint answered nothing at all — treat like a
                    # failed scrape; a 5xx responder stays scrape-governed.
                    pod.healthy = False
                log.warning(
                    "proxy to %s failed (attempt %d): %s",
                    pod.address, pre_failures + 1, str(e) or type(e).__name__,
                )
                pre_failures += 1
                if pre_failures >= self.max_schedule_attempts:
                    break
                self.metrics.request_retries += 1
                # Decorrelated-jitter backoff before the re-pick: a
                # refusing pool must not see a synchronized retry
                # storm land on the next replica in lockstep.
                prev_backoff = backoff_delay(
                    prev_backoff,
                    self.retry_backoff_s,
                    self.retry_backoff_cap_s,
                    self._retry_rng,
                )
                await asyncio.sleep(prev_backoff)
                continue
            except ResumeRejected as e:
                # The upstream refused the REPLAY itself (4xx): another
                # replica would refuse the same body — surface it.
                self.metrics.proxy_errors += 1
                assert state is not None
                return await self._fail_stream(state, str(e), e.status)
            except MidStreamFailure as e:
                # Bytes already reached the client. The dead endpoint
                # feeds the breaker EVEN when resume is off — a replica
                # that dies mid-stream on every request must trip the
                # circuit, not hide behind its streamed-200 status line.
                self.metrics.proxy_errors += 1
                self.metrics.mid_stream_failures += 1
                self.breaker.record_failure(pod.address)
                pod.healthy = False
                if state is None:
                    # Non-streaming body cut mid-transfer: nothing can be
                    # replayed onto a half-written JSON body — the
                    # breaker is fed (above) and the truncation surfaces
                    # as an aborted transfer.
                    raise e.cause from e
                log.warning(
                    "mid-stream failure on %s after %d frames: %s",
                    pod.address, state.frames, str(e.cause) or repr(e.cause),
                )
                if not state.accumulate or resumes >= self.max_resumes:
                    return await self._fail_stream(
                        state,
                        f"upstream stream failed and resume budget "
                        f"exhausted ({resumes}/{self.max_resumes}): "
                        f"{e.cause!r}",
                        502,
                    )
                if deadline is not None and clock.monotonic() >= deadline:
                    return await self._fail_stream(
                        state,
                        "request deadline exceeded during stream resume",
                        504,
                    )
                resumes += 1
                self.metrics.stream_resumes += 1
                self.metrics.resume_replayed_tokens += len(state.tokens)
                if span is not None:
                    span.set("llm_d.resume.count", resumes)
                    span.set("llm_d.resume.tokens", len(state.tokens))
                # Replay with the accumulated history: re-pick from the
                # WHOLE pool minus the dead endpoint (endpoints tried
                # pre-stream served nothing and remain candidates). The
                # dead leg's partial line is dropped — it was never
                # forwarded, and it must not prefix the next leg's bytes.
                state.carry = b""
                state.resuming = True
                raw = self._resume_body(req, state)
                tried = {pod.address}
                prev_backoff = backoff_delay(
                    prev_backoff,
                    self.retry_backoff_s,
                    self.retry_backoff_cap_s,
                    self._retry_rng,
                )
                await asyncio.sleep(prev_backoff)
                continue
            finally:
                if prefill_pod is not None:
                    prefill_pod.inflight_tokens = max(
                        0, prefill_pod.inflight_tokens - req.approx_prompt_tokens
                    )
        if state is not None and state.streamed:
            return await self._fail_stream(state, "all endpoints failed", 502)
        return web.json_response(
            {"error": {"message": "all endpoints failed", "type": "proxy-error"}},
            status=502,
        )

    async def _proxy(
        self,
        request: web.Request,
        req: LLMRequest,
        raw: bytes,
        pod: Endpoint,
        extra_headers: dict[str, str],
        retry_5xx: bool = False,
        state: _StreamState | None = None,
    ) -> web.StreamResponse:
        session = await self._client()
        # Injection site: the picked endpoint refuses the connection even
        # though its scrape health looks fine — the re-pick + breaker
        # path above is the degradation under test.
        if faults.fires("epp.endpoint.refuse", pod.address):
            raise aiohttp.ClientConnectionError(
                f"injected epp.endpoint.refuse for {pod.address}"
            )
        # Router-internal protocol headers: client copies are stripped
        # (the HDR_EC_HOST precedent) — a client asking the engine for
        # token annotations through the router would otherwise receive
        # internal frames the router only strips when resume is armed.
        # (Case-insensitive: aiohttp preserves the client's casing.)
        dropped = HOP_HEADERS | {HDR_STREAM_TOKENS, HDR_RESUME}
        headers = {
            k: v for k, v in request.headers.items()
            if k.lower() not in dropped
        }
        headers["x-request-id"] = req.request_id
        headers.update(extra_headers)
        if state is not None and state.accumulate and state.strip:
            # OpenAI surface: ask the engine to annotate delta frames
            # with raw token ids (stripped below) — the resume history a
            # replica death makes the router replay. vllmgrpc frames
            # carry token_ids natively; no annotation needed.
            headers[HDR_STREAM_TOKENS] = "1"
        if state is not None and state.resuming:
            # Replay leg: the client stream is already open — the engine
            # must graft (no re-emitted chat role preamble), even when
            # the accumulated history is still empty (death between the
            # preamble and the first token frame).
            headers[HDR_RESUME] = "1"
        span = req.scratch.get("span")
        if span is not None and span.sampled:
            headers["traceparent"] = span.traceparent
        pod.inflight += 1
        pod.inflight_tokens += req.approx_prompt_tokens
        t0 = clock.monotonic()
        first_byte: float | None = None
        last_byte: float | None = None
        stream_tokens = 0
        status = 0
        try:
            async with session.request(
                request.method, pod.url + request.path_qs, data=raw, headers=headers
            ) as upstream:
                status = upstream.status
                if state is not None and state.streamed:
                    # Resume leg grafting onto the committed client
                    # stream: there is no fresh response to carry an
                    # upstream error, so a 5xx re-picks (the caller's
                    # pre-stream budget) and any other non-200 surfaces
                    # as the terminal frame.
                    if status >= 500:
                        raise UpstreamServerError(
                            status, await self._error_body(upstream)
                        )
                    if status != 200:
                        raise ResumeRejected(
                            status, await self._error_body(upstream)
                        )
                    self.breaker.record_success(pod.address)
                    resp = state.resp
                else:
                    if status >= 500 and retry_5xx:
                        # Nothing streamed to the client yet: surface the
                        # 5xx to the retry loop so another replica gets
                        # the request instead of the client eating this
                        # one's failure. The LAST attempt streams the 5xx
                        # through.
                        raise UpstreamServerError(
                            status, await self._error_body(upstream)
                        )
                    if status < 500:
                        self.breaker.record_success(pod.address)
                    else:
                        # Last attempt (retry_5xx=False) streams the 5xx
                        # through to the client, but the breaker still
                        # counts it — a replica 500ing on every request
                        # must trip the circuit even when retries are
                        # disabled (scrape health stays green for a
                        # reachable-but-failing pod).
                        self.metrics.proxy_errors += 1
                        self.breaker.record_failure(pod.address)
                    resp = web.StreamResponse(status=upstream.status)
                    for k, v in upstream.headers.items():
                        if k.lower() not in HOP_HEADERS:
                            resp.headers[k] = v
                    resp.headers["x-llm-d-endpoint"] = pod.address
                    await resp.prepare(request)
                    if state is not None:
                        state.resp = resp
                # The upstream READ is the only leg whose failures mean
                # "the replica died" — the CLIENT-side writes sit outside
                # the guard, wrapped as ClientDisconnected, so a client
                # closing its tab mid-stream never feeds the breaker or
                # triggers replay generations nobody will read.
                aiter = upstream.content.iter_any().__aiter__()
                while True:
                    try:
                        chunk = await aiter.__anext__()
                    except StopAsyncIteration:
                        break
                    except (aiohttp.ClientError, asyncio.TimeoutError) as e:
                        # The upstream died after committing the stream.
                        # A whole stream ([DONE] forwarded) torn down
                        # uncleanly is complete, and a cut NON-200 body
                        # (e.g. a last-attempt 5xx streamed through,
                        # already breaker-counted above) is delivered
                        # truncated — grafting resume frames onto an
                        # error response would corrupt it. Only a cut
                        # 200 stream missing its terminator is a
                        # failure the continuation protocol handles.
                        if status == 200 and (
                            state is None or not state.done_sent
                        ):
                            raise MidStreamFailure(e) from e
                        break
                    if first_byte is None:
                        first_byte = clock.monotonic()
                    last_byte = clock.monotonic()
                    if state is not None:
                        # Complete-line forwarding: data frames are
                        # counted (one frame ~ one sampled token
                        # batch), token annotations accumulate into
                        # the resume history, and a frame truncated
                        # by a crash never reaches the client.
                        out, n_data = state.ingest(chunk)
                        stream_tokens += n_data
                        if out:
                            await self._client_write(resp, out)
                    else:
                        await self._client_write(resp, chunk)
                tail = state.flush() if state is not None else b""
                if tail:
                    if (
                        tail.startswith(b"data:")
                        and tail.strip() != b"data: [DONE]"
                    ):
                        stream_tokens += 1
                    await self._client_write(resp, tail)
                try:
                    await resp.write_eof()
                except (ConnectionResetError, aiohttp.ClientConnectionError) as e:
                    raise ClientDisconnected(str(e)) from e
                return resp
        finally:
            pod.inflight = max(0, pod.inflight - 1)
            pod.inflight_tokens = max(
                0, pod.inflight_tokens - req.approx_prompt_tokens
            )
            now = clock.monotonic()
            ttft_ms: float | None = None
            tpot_ms: float | None = None
            # Only successful responses produce latency observations: a pod
            # fast-failing with 500s must not train/score as "fastest".
            if span is not None and first_byte is not None:
                span.set("llm_d.ttft_s", first_byte - t0)
                span.set("http.status_code", status)
            if first_byte is not None and 200 <= status < 400:
                self.metrics.ttft_count += 1
                self.metrics.ttft_sum += first_byte - t0
                self.metrics.e2e_sum += now - t0
                # per-endpoint latency attrs for latency-aware scoring
                pod.attrs["LastTTFT"] = first_byte - t0
                pod.attrs["LastE2E"] = now - t0
                ttft_ms = (first_byte - t0) * 1000.0
                if last_byte is not None and stream_tokens > 1:
                    tpot_ms = (last_byte - first_byte) * 1000.0 / (stream_tokens - 1)
                    # Feeds the WVA SLO analyzer's ITL observations.
                    pod.attrs["LastTPOT"] = tpot_ms / 1000.0
            self.scheduler.notify_complete(req, pod)
            if ttft_ms is not None and self.completion_observers:
                # Fire-and-forget: the response is already written; a slow
                # trainer sidecar must not hold the flow-control slot.
                t = asyncio.ensure_future(
                    self._run_observers(req, pod, ttft_ms, tpot_ms)
                )
                self._observer_tasks.add(t)
                t.add_done_callback(self._observer_tasks.discard)

    async def handle_passthrough(self, request: web.Request) -> web.StreamResponse:
        """Non-generate paths (/v1/models, ...) go to any healthy endpoint.

        Encode workers serve a different surface (/v1/encode, EC pulls) —
        they cannot answer /v1/models and are skipped.
        """
        pods = [
            p for p in self.store.list()
            if p.healthy and p.role != ROLE_ENCODE
        ]
        if not pods:
            return web.json_response(
                {"error": {"message": "no endpoints", "type": "no-endpoints"}},
                status=503,
            )
        session = await self._client()
        raw = await request.read()
        headers = {
            k: v for k, v in request.headers.items() if k.lower() not in HOP_HEADERS
        }
        try:
            async with session.request(
                request.method, pods[0].url + request.path_qs, data=raw, headers=headers
            ) as upstream:
                body = await upstream.read()
                resp = web.Response(status=upstream.status, body=body)
                for k, v in upstream.headers.items():
                    if k.lower() not in HOP_HEADERS:
                        resp.headers[k] = v
                return resp
        except (aiohttp.ClientConnectionError, asyncio.TimeoutError):
            return web.json_response(
                {"error": {"message": "upstream unreachable", "type": "proxy-error"}},
                status=502,
            )

    async def handle_health(self, request: web.Request) -> web.Response:
        return web.json_response(
            {"status": "ok", "endpoints": len(self.store.list())}
        )

    async def handle_ready(self, request: web.Request) -> web.Response:
        """Readiness (distinct from /healthz liveness): flips to 503 the
        moment graceful shutdown begins, BEFORE flow control evicts, so
        the gateway stops routing before the retryable 503s start."""
        if not self.ready:
            return web.json_response(
                {"status": "draining"}, status=503
            )
        return web.json_response(
            {"status": "ready", "endpoints": len(self.store.list())}
        )

    def begin_shutdown(self) -> None:
        """Graceful-shutdown phase 1: unready first, evict second."""
        self.ready = False

    async def handle_metrics(self, request: web.Request) -> web.Response:
        parts = [self.metrics.render(self.store, self.flow, self.breaker)]
        for extra in self.metric_extras:
            try:
                parts.append(extra())
            # llmd: allow(broad-except) -- a broken metrics section must not take down the whole scrape page
            except Exception:
                log.exception("extra metrics renderer failed")
        return web.Response(
            text="\n".join(p.strip("\n") for p in parts) + "\n",
            content_type="text/plain",
        )

    async def handle_endpoints(self, request: web.Request) -> web.Response:
        return web.json_response(
            {
                "endpoints": [
                    {
                        "address": p.address,
                        "labels": p.labels,
                        "healthy": p.healthy,
                        "inflight": p.inflight,
                        "attrs": {k: v for k, v in p.attrs.items()},
                    }
                    for p in self.store.list()
                ]
            }
        )

    # ------------------------------------------------------------------ #

    def build_app(self) -> web.Application:
        app = web.Application()
        routes = [
            web.get("/healthz", self.handle_health),
            web.get("/readyz", self.handle_ready),
            web.get("/metrics", self.handle_metrics),
            web.get("/endpoints", self.handle_endpoints),
        ]
        for path in sorted(GENERATE_PATHS | VLLMGRPC_PATHS):
            routes.append(web.post(path, self.handle_generate))
        if self.default_parser == "passthrough-parser":
            # Opaque payloads still get scheduled (headers-only routing).
            routes.append(web.post("/{tail:.*}", self.handle_generate))
        routes.append(web.route("*", "/{tail:.*}", self.handle_passthrough))
        app.add_routes(routes)

        async def _lifecycle(app: web.Application):
            # Endpoint removal must purge scorer state (prefix index entries
            # for a recycled host:port would fake cache affinity on a cold pod).
            self.store.on_remove(self.scheduler.notify_endpoint_removed)
            # A recycled host:port must not inherit breaker state.
            self.store.on_remove(self.breaker.forget)
            if self.discovery is not None:
                try:
                    self.discovery.load_once()
                except FileNotFoundError:
                    log.warning("endpoints file missing at startup")
                self.discovery.start()
            if self.collector is not None:
                await self.collector.scrape_once()
                self.collector.start()
            if self.flow.saturation.pool_stats is None:
                self.flow.saturation.pool_stats = self._pool_stats
            self.flow.start()
            yield
            # Readiness drops BEFORE eviction. In a real deployment the
            # SIGTERM handler (`__main__._serve`) already flipped this
            # while the listen socket was still serving — by the time
            # cleanup_ctx teardown runs, aiohttp has closed the socket —
            # so this idempotent call is the fallback for embedded/test
            # runners that tear the app down without the signal path.
            self.begin_shutdown()
            await self.flow.drain()
            if self.collector is not None:
                await self.collector.stop()
            if self.discovery is not None:
                self.discovery.stop()
            if self._session is not None:
                await self._session.close()
            for res in self.producers + self.closables:
                closer = getattr(res, "close", None)
                if closer is None:
                    continue
                out = closer()
                if asyncio.iscoroutine(out):
                    await out

        app.cleanup_ctx.append(_lifecycle)
        return app
