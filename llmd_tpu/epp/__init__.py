"""EPP (Endpoint Picker) — the llm-d routing brain, TPU-stack edition.

Re-implements the reference's EPP architecture (reference
docs/architecture/core/router/epp/README.md:33-101): Request Handler →
Flow Control → Scheduler (Filter → Score → Pick) backed by a Data Layer of
per-endpoint attributes. The reference runs this as an Envoy ext-proc gRPC
server; here the same pipeline fronts an aiohttp reverse proxy (the
standalone/no-Kubernetes deployment shape, guides/no-kubernetes-deployment/
README.md:1-50), so one process is both L7 proxy and picker.
"""

from llmd_tpu.epp.types import Endpoint, LLMRequest, SchedulingResult

__all__ = ["Endpoint", "LLMRequest", "SchedulingResult"]
