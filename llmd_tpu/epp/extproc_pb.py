"""Minimal protobuf codec for the Envoy ext-proc v3 protocol.

The EPP's primary deployment shape is an Envoy external-processor gRPC
plugin (reference docs/architecture/core/router/epp/README.md:11-18); the
wire messages are `envoy.service.ext_proc.v3.ProcessingRequest/Response`.
Envoy's proto tree is not vendored here, so this module hand-encodes the
small field subset the endpoint-picking exchange uses. Field numbers
follow the public proto (api/envoy/service/ext_proc/v3/
external_processor.proto and envoy/config/core/v3/base.proto):

ProcessingRequest:  request_headers=2, response_headers=3, request_body=4,
                    response_body=5, request_trailers=6, response_trailers=7
HttpHeaders:        headers(HeaderMap)=1, end_of_stream=3
HeaderMap:          headers(repeated HeaderValue)=1
HeaderValue:        key=1, value=2, raw_value=3
HttpBody:           body=1, end_of_stream=2
ProcessingResponse: request_headers(HeadersResponse)=1,
                    response_headers=2, request_body(BodyResponse)=3,
                    response_body=4, request_trailers=5,
                    response_trailers=6, immediate_response=7
HeadersResponse / BodyResponse: response(CommonResponse)=1
CommonResponse:     status=1 (0=CONTINUE), header_mutation=2,
                    clear_route_cache=5
HeaderMutation:     set_headers(repeated HeaderValueOption)=1,
                    remove_headers(repeated string)=2
BodyMutation:       body=1, clear_body=2, streamed_response=3
StreamedBodyResponse: body=1, end_of_stream=2
HeaderValueOption:  header(HeaderValue)=1, append_action=3
                    (2=OVERWRITE_IF_EXISTS_OR_ADD; 1 is ADD_IF_ABSENT,
                    which would let a client-supplied routing header win
                    over the EPP's pick — never use it for mutations)
ImmediateResponse:  status(HttpStatus{code=1})=1, headers=2, body=3,
                    details=5
"""

from __future__ import annotations

import dataclasses


# --------------------------------------------------------------- wire


def _varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _read_varint(buf: bytes, pos: int) -> tuple[int, int]:
    result = shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift > 63:
            raise ValueError("varint too long")


def _tag(field: int, wire: int) -> bytes:
    return _varint((field << 3) | wire)


def _len_field(field: int, payload: bytes) -> bytes:
    return _tag(field, 2) + _varint(len(payload)) + payload


def _varint_field(field: int, value: int) -> bytes:
    if not value:
        return b""
    return _tag(field, 0) + _varint(value)


def iter_fields(buf: bytes):
    """Yield (field_number, wire_type, value) — value is bytes for
    length-delimited fields, int for varints; fixed fields are skipped."""
    pos = 0
    while pos < len(buf):
        key, pos = _read_varint(buf, pos)
        field, wire = key >> 3, key & 7
        if wire == 0:
            v, pos = _read_varint(buf, pos)
            yield field, wire, v
        elif wire == 2:
            n, pos = _read_varint(buf, pos)
            yield field, wire, buf[pos : pos + n]
            pos += n
        elif wire == 5:
            pos += 4
        elif wire == 1:
            pos += 8
        else:
            raise ValueError(f"unsupported wire type {wire}")


# --------------------------------------------------------------- decode


def _parse_header_value(buf: bytes) -> tuple[str, str]:
    key = value = ""
    raw = b""
    for field, _, v in iter_fields(buf):
        if field == 1:
            key = v.decode("utf-8", "replace")
        elif field == 2:
            value = v.decode("utf-8", "replace")
        elif field == 3:
            raw = v
    return key, value or raw.decode("utf-8", "replace")


def _parse_header_map(buf: bytes) -> dict[str, str]:
    out: dict[str, str] = {}
    for field, _, v in iter_fields(buf):
        if field == 1:
            k, val = _parse_header_value(v)
            out[k.lower()] = val
    return out


@dataclasses.dataclass
class ProcessingRequest:
    kind: str  # request_headers | response_headers | request_body |
    #            response_body | request_trailers | response_trailers
    headers: dict[str, str] = dataclasses.field(default_factory=dict)
    body: bytes = b""
    end_of_stream: bool = False


_REQ_KINDS = {
    2: "request_headers",
    3: "response_headers",
    4: "request_body",
    5: "response_body",
    6: "request_trailers",
    7: "response_trailers",
}


def parse_processing_request(buf: bytes) -> ProcessingRequest | None:
    for field, _, v in iter_fields(buf):
        kind = _REQ_KINDS.get(field)
        if kind is None:
            continue
        msg = ProcessingRequest(kind=kind)
        if kind.endswith("headers") or kind.endswith("trailers"):
            for f2, _, v2 in iter_fields(v):
                if f2 == 1:
                    msg.headers = _parse_header_map(v2)
                elif f2 == 3:
                    msg.end_of_stream = bool(v2)
        else:  # body
            for f2, _, v2 in iter_fields(v):
                if f2 == 1:
                    msg.body = v2
                elif f2 == 2:
                    msg.end_of_stream = bool(v2)
        return msg
    return None


# --------------------------------------------------------------- encode


def _header_value(key: str, value: str) -> bytes:
    # Envoy requires raw_value for mutations (value is for display).
    return _len_field(1, key.encode()) + _len_field(3, value.encode())


def _header_mutation(set_headers: dict[str, str], remove: list[str]) -> bytes:
    out = b""
    for k, v in set_headers.items():
        # append_action=2 (OVERWRITE_IF_EXISTS_OR_ADD): the EPP's routing
        # headers (x-gateway-destination-endpoint, x-request-id, P/D pairing)
        # must replace any client-sent value, or a client could steer the
        # request to an arbitrary host:port on the original_dst cluster.
        opt = _len_field(1, _header_value(k, v)) + _varint_field(3, 2)
        out += _len_field(1, opt)
    for k in remove:
        out += _len_field(2, k.encode())
    return out


_RESP_FIELD = {
    "request_headers": 1,
    "response_headers": 2,
    "request_body": 3,
    "response_body": 4,
    "request_trailers": 5,
    "response_trailers": 6,
}


def encode_common_response(
    kind: str,
    set_headers: dict[str, str] | None = None,
    remove_headers: list[str] | None = None,
    clear_route_cache: bool = False,
) -> bytes:
    """ProcessingResponse{<kind>: {response: CommonResponse{CONTINUE,...}}}"""
    common = b""
    if set_headers or remove_headers:
        common += _len_field(
            2, _header_mutation(set_headers or {}, remove_headers or [])
        )
    if clear_route_cache:
        common += _varint_field(5, 1)
    inner = _len_field(1, common)
    return _len_field(_RESP_FIELD[kind], inner)


def encode_streamed_body_response(
    kind: str, body: bytes, end_of_stream: bool
) -> bytes:
    """FULL_DUPLEX_STREAMED chunk hand-back: the processor received a
    streamed body chunk and returns it (possibly delayed until a routing
    decision) via BodyMutation.streamed_response."""
    streamed = _len_field(1, body) + _varint_field(2, int(end_of_stream))
    common = _len_field(3, _len_field(3, streamed))  # body_mutation.streamed
    inner = _len_field(1, common)
    return _len_field(_RESP_FIELD[kind], inner)


def encode_immediate_response(
    status_code: int,
    headers: dict[str, str] | None = None,
    body: bytes = b"",
    details: str = "",
) -> bytes:
    msg = _len_field(1, _varint_field(1, status_code) or _tag(1, 0) + b"\x00")
    if headers:
        msg += _len_field(2, _header_mutation(headers, []))
    if body:
        msg += _len_field(3, body)
    if details:
        msg += _len_field(5, details.encode())
    return _len_field(7, msg)


# ----------------------------------------------------- client-side helpers
# (tests / the no-Envoy smoke client encode ProcessingRequests and decode
# ProcessingResponses with these)


def encode_request_headers(headers: dict[str, str], end_of_stream: bool = False) -> bytes:
    hm = b"".join(_len_field(1, _header_value(k, v)) for k, v in headers.items())
    inner = _len_field(1, hm) + _varint_field(3, int(end_of_stream))
    return _len_field(2, inner)


def encode_request_body(body: bytes, end_of_stream: bool = True) -> bytes:
    inner = _len_field(1, body) + _varint_field(2, int(end_of_stream))
    return _len_field(4, inner)


def encode_response_headers(headers: dict[str, str]) -> bytes:
    hm = b"".join(_len_field(1, _header_value(k, v)) for k, v in headers.items())
    return _len_field(3, _len_field(1, hm))


def encode_response_body(body: bytes, end_of_stream: bool = False) -> bytes:
    inner = _len_field(1, body) + _varint_field(2, int(end_of_stream))
    return _len_field(5, inner)


def encode_request_trailers() -> bytes:
    return _len_field(6, b"")


def encode_response_trailers() -> bytes:
    return _len_field(7, b"")


@dataclasses.dataclass
class ProcessingResponse:
    kind: str
    set_headers: dict[str, str] = dataclasses.field(default_factory=dict)
    remove_headers: list[str] = dataclasses.field(default_factory=list)
    immediate_status: int = 0
    immediate_body: bytes = b""
    immediate_details: str = ""
    # FULL_DUPLEX_STREAMED: a handed-back body chunk.
    body: bytes = b""
    body_eos: bool = False


def parse_processing_response(buf: bytes) -> ProcessingResponse | None:
    kinds = {v: k for k, v in _RESP_FIELD.items()}
    for field, _, v in iter_fields(buf):
        if field in kinds:
            msg = ProcessingResponse(kind=kinds[field])
            for f2, _, v2 in iter_fields(v):  # CommonResponse wrapper
                if f2 != 1:
                    continue
                for f3, _, v3 in iter_fields(v2):
                    if f3 == 2:  # header_mutation
                        for f4, _, v4 in iter_fields(v3):
                            if f4 == 1:  # HeaderValueOption
                                for f5, _, v5 in iter_fields(v4):
                                    if f5 == 1:
                                        k, val = _parse_header_value(v5)
                                        msg.set_headers[k] = val
                            elif f4 == 2:
                                msg.remove_headers.append(v4.decode())
                    elif f3 == 3:  # body_mutation
                        for f4, _, v4 in iter_fields(v3):
                            if f4 == 3:  # streamed_response
                                for f5, _, v5 in iter_fields(v4):
                                    if f5 == 1:
                                        msg.body = v5
                                    elif f5 == 2:
                                        msg.body_eos = bool(v5)
            return msg
        if field == 7:  # immediate_response
            msg = ProcessingResponse(kind="immediate_response")
            for f2, _, v2 in iter_fields(v):
                if f2 == 1:
                    for f3, _, v3 in iter_fields(v2):
                        if f3 == 1:
                            msg.immediate_status = v3
                elif f2 == 2:
                    for f4, _, v4 in iter_fields(v2):
                        if f4 == 1:
                            for f5, _, v5 in iter_fields(v4):
                                if f5 == 1:
                                    k, val = _parse_header_value(v5)
                                    msg.set_headers[k] = val
                elif f2 == 3:
                    msg.immediate_body = v2
                elif f2 == 5:
                    msg.immediate_details = v2.decode()
            return msg
    return None
