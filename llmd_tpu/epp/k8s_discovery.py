"""Kubernetes pod discovery for the data layer.

The reference EPP's `k8s-notification-source` (datalayer.md:49-91)
watches the InferencePool selector and joins pods on status Running
(inferencepool.md:26-31, operations-vllm.md:49-53 — "no central
bootstrap"). The kubernetes client package is not part of this image,
so this source speaks to the API server directly over HTTPS using the
in-cluster service-account credentials, polling the pod list with a
label selector. Each Running+Ready pod becomes an Endpoint at
`podIP:port`, carrying its labels (role, engine-type, node) into the
scheduler's view.
"""

from __future__ import annotations

import asyncio
import json
import logging
import ssl
import urllib.parse

import aiohttp

from llmd_tpu.epp.types import Endpoint

log = logging.getLogger(__name__)

SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"


class K8sPodDiscoverySource:
    def __init__(
        self,
        store,
        label_selector: str,
        namespace: str | None = None,
        target_port: int = 8000,
        api_server: str | None = None,
        token_path: str = f"{SA_DIR}/token",
        ca_path: str = f"{SA_DIR}/ca.crt",
        namespace_path: str = f"{SA_DIR}/namespace",
        poll_s: float = 2.0,
        node_label: str = "llm-d.ai/node",
    ) -> None:
        self.store = store
        self.label_selector = label_selector
        self.target_port = target_port
        self.api_server = api_server or "https://kubernetes.default.svc"
        self.token_path = token_path
        self.ca_path = ca_path
        self.poll_s = poll_s
        self.node_label = node_label
        if namespace is None:
            try:
                with open(namespace_path) as f:
                    namespace = f.read().strip()
            except OSError:
                namespace = "default"
        self.namespace = namespace
        self._session: aiohttp.ClientSession | None = None
        self._task: asyncio.Task | None = None

    def _token(self) -> str:
        # Re-read per request: projected SA tokens rotate.
        with open(self.token_path) as f:
            return f.read().strip()

    async def _client(self) -> aiohttp.ClientSession:
        if self._session is None or self._session.closed:
            try:
                ctx = ssl.create_default_context(cafile=self.ca_path)
            except (OSError, ssl.SSLError):
                ctx = ssl.create_default_context()
            self._session = aiohttp.ClientSession(
                connector=aiohttp.TCPConnector(ssl=ctx),
                timeout=aiohttp.ClientTimeout(total=15),
            )
        return self._session

    @staticmethod
    def _pod_ready(pod: dict) -> bool:
        status = pod.get("status", {})
        if status.get("phase") != "Running" or not status.get("podIP"):
            return False
        if pod.get("metadata", {}).get("deletionTimestamp"):
            return False  # terminating: stop routing immediately
        for cond in status.get("conditions", []):
            if cond.get("type") == "Ready":
                return cond.get("status") == "True"
        return False

    def _endpoints_for(self, pod: dict) -> list[Endpoint]:
        meta = pod.get("metadata", {})
        labels = dict(meta.get("labels", {}))
        node = pod.get("spec", {}).get("nodeName")
        if node and self.node_label not in labels:
            labels[self.node_label] = node
        # Slice identity for topology-aware scoring: explicit llm-d.ai/slice
        # wins; multi-host LWS pods derive it from their replica group
        # (same group == same TPU slice, docs/infrastructure/multi-node.md).
        if "llm-d.ai/slice" not in labels:
            lws_name = labels.get("leaderworkerset.sigs.k8s.io/name")
            group = labels.get("leaderworkerset.sigs.k8s.io/group-index")
            if lws_name and group is not None:
                labels["llm-d.ai/slice"] = f"{lws_name}-{group}"
        annotations = meta.get("annotations", {})
        port = self.target_port
        # honor a per-pod port annotation (DP external-LB rank ports)
        ann = annotations.get("llm-d.ai/port")
        if ann:
            try:
                port = int(ann)
            except ValueError:
                pass
        # DP multi-port external LB (reference wide-ep-lws.values.yaml:
        # 41-52 lists every rank port in targetPorts): a pod annotated
        # llm-d.ai/dp-size=N exposes N rank listeners on [port, port+N)
        # and each becomes its OWN endpoint so the scheduler keeps a
        # rank-level load view.
        dp = 1
        ann = annotations.get("llm-d.ai/dp-size")
        if ann:
            try:
                dp = max(1, int(ann))
            except ValueError:
                pass
        ip = pod["status"]["podIP"]
        out = []
        for rank in range(dp):
            rank_labels = labels if dp == 1 else {
                **labels, "llm-d.ai/dp-rank": str(rank),
            }
            out.append(
                Endpoint(address=f"{ip}:{port + rank}", labels=rank_labels)
            )
        return out

    async def poll_once(self) -> list[Endpoint]:
        session = await self._client()
        qs = urllib.parse.urlencode({"labelSelector": self.label_selector})
        url = f"{self.api_server}/api/v1/namespaces/{self.namespace}/pods?{qs}"
        async with session.get(
            url, headers={"authorization": f"Bearer {self._token()}"}
        ) as resp:
            resp.raise_for_status()
            body = json.loads(await resp.text())
        eps = [
            ep
            for p in body.get("items", [])
            if self._pod_ready(p)
            for ep in self._endpoints_for(p)
        ]
        self.store.reconcile(eps)
        return eps

    async def run(self) -> None:
        while True:
            try:
                await self.poll_once()
            except Exception as e:
                log.warning("k8s pod discovery poll failed: %s", e)
            await asyncio.sleep(self.poll_s)

    def start(self) -> None:
        self._task = asyncio.get_event_loop().create_task(self.run())

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()

    async def close(self) -> None:
        self.stop()
        if self._session is not None and not self._session.closed:
            await self._session.close()
