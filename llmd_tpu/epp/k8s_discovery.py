"""Kubernetes pod discovery for the data layer.

The reference EPP's `k8s-notification-source` (datalayer.md:49-91)
watches the InferencePool selector and joins pods on status Running
(inferencepool.md:26-31, operations-vllm.md:49-53 — "no central
bootstrap"). The kubernetes client package is not part of this image,
so this source speaks to the API server directly over HTTPS using the
in-cluster service-account credentials. Each Running+Ready pod becomes
an Endpoint at `podIP:port`, carrying its labels (role, engine-type,
node) into the scheduler's view.

Default mode is a WATCH stream (the reference's notification semantics):
one initial LIST seeds the store and captures its resourceVersion, then
a chunked watch delivers ADDED/MODIFIED/DELETED events with sub-second
endpoint-join latency and O(changes) API load. The stream resumes from
the last seen resourceVersion after disconnects; a 410 Gone (expired
version) falls back to a fresh LIST. ``mode="poll"`` keeps the simple
list-polling behavior.

The selector/port can come from an ``InferencePool`` object
(inferencepool.md:26-37): ``resolve_inference_pool`` reads the CRD's
``spec.selector`` + ``spec.targetPortNumber`` so the EPP binds to the
pool resource a Gateway's HTTPRoute backendRef names
(deploy/recipes/router/inferencepool-crd.yaml ships the CRD + example).
"""

from __future__ import annotations

import asyncio
import json
import logging
import ssl
import urllib.parse

import aiohttp

from llmd_tpu.epp.types import Endpoint

log = logging.getLogger(__name__)

SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"
INFERENCE_POOL_API = "apis/inference.networking.x-k8s.io/v1alpha2"


class _WatchExpired(Exception):
    """410 Gone: the watch resourceVersion left etcd's history window."""


async def resolve_inference_pool(
    source: "K8sPodDiscoverySource", name: str
) -> None:
    """Bind a discovery source to an InferencePool object: read the CRD's
    spec.selector (matchLabels) + spec.targetPortNumber and install them
    as the source's label selector / target port (inferencepool.md:26-37).
    """
    session = await source._client()
    url = (
        f"{source.api_server}/{INFERENCE_POOL_API}/namespaces/"
        f"{source.namespace}/inferencepools/{name}"
    )
    async with session.get(
        url, headers={"authorization": f"Bearer {source._token()}"}
    ) as resp:
        resp.raise_for_status()
        pool = json.loads(await resp.text())
    spec = pool.get("spec", {})
    selector = spec.get("selector") or {}
    match = selector.get("matchLabels") or selector  # both CRD shapes
    if not match:
        raise ValueError(f"InferencePool {name!r} has no selector")
    source.label_selector = ",".join(f"{k}={v}" for k, v in sorted(match.items()))
    port = spec.get("targetPortNumber") or spec.get("targetPort")
    if port:
        source.target_port = int(port)
    log.info(
        "bound to InferencePool %s: selector=%r port=%d",
        name, source.label_selector, source.target_port,
    )


class K8sPodDiscoverySource:
    def __init__(
        self,
        store,
        label_selector: str,
        namespace: str | None = None,
        target_port: int = 8000,
        api_server: str | None = None,
        token_path: str = f"{SA_DIR}/token",
        ca_path: str = f"{SA_DIR}/ca.crt",
        namespace_path: str = f"{SA_DIR}/namespace",
        poll_s: float = 2.0,
        node_label: str = "llm-d.ai/node",
        mode: str = "watch",
    ) -> None:
        if mode not in ("watch", "poll"):
            raise ValueError(f"unknown discovery mode {mode!r}")
        self.store = store
        self.label_selector = label_selector
        self.target_port = target_port
        self.api_server = api_server or "https://kubernetes.default.svc"
        self.token_path = token_path
        self.ca_path = ca_path
        self.poll_s = poll_s
        self.node_label = node_label
        self.mode = mode
        # watch state: pod name -> endpoints, and the resume version
        self._pods: dict[str, list[Endpoint]] = {}
        self._resource_version: str | None = None
        if namespace is None:
            try:
                with open(namespace_path) as f:
                    namespace = f.read().strip()
            except OSError:
                namespace = "default"
        self.namespace = namespace
        self._session: aiohttp.ClientSession | None = None
        self._task: asyncio.Task | None = None

    def _token(self) -> str:
        # Re-read per request: projected SA tokens rotate.
        with open(self.token_path) as f:
            return f.read().strip()

    async def _client(self) -> aiohttp.ClientSession:
        if self._session is None or self._session.closed:
            try:
                ctx = ssl.create_default_context(cafile=self.ca_path)
            except (OSError, ssl.SSLError):
                ctx = ssl.create_default_context()
            self._session = aiohttp.ClientSession(
                connector=aiohttp.TCPConnector(ssl=ctx),
                timeout=aiohttp.ClientTimeout(total=15),
            )
        return self._session

    @staticmethod
    def _pod_ready(pod: dict) -> bool:
        status = pod.get("status", {})
        if status.get("phase") != "Running" or not status.get("podIP"):
            return False
        if pod.get("metadata", {}).get("deletionTimestamp"):
            return False  # terminating: stop routing immediately
        for cond in status.get("conditions", []):
            if cond.get("type") == "Ready":
                return cond.get("status") == "True"
        return False

    def _endpoints_for(self, pod: dict) -> list[Endpoint]:
        meta = pod.get("metadata", {})
        labels = dict(meta.get("labels", {}))
        node = pod.get("spec", {}).get("nodeName")
        if node and self.node_label not in labels:
            labels[self.node_label] = node
        # Slice identity for topology-aware scoring: explicit llm-d.ai/slice
        # wins; multi-host LWS pods derive it from their replica group
        # (same group == same TPU slice, docs/infrastructure/multi-node.md).
        if "llm-d.ai/slice" not in labels:
            lws_name = labels.get("leaderworkerset.sigs.k8s.io/name")
            group = labels.get("leaderworkerset.sigs.k8s.io/group-index")
            if lws_name and group is not None:
                labels["llm-d.ai/slice"] = f"{lws_name}-{group}"
        annotations = meta.get("annotations", {})
        port = self.target_port
        # honor a per-pod port annotation (DP external-LB rank ports)
        ann = annotations.get("llm-d.ai/port")
        if ann:
            try:
                port = int(ann)
            except ValueError:
                pass
        # DP multi-port external LB (reference wide-ep-lws.values.yaml:
        # 41-52 lists every rank port in targetPorts): a pod annotated
        # llm-d.ai/dp-size=N exposes N rank listeners on [port, port+N)
        # and each becomes its OWN endpoint so the scheduler keeps a
        # rank-level load view.
        dp = 1
        ann = annotations.get("llm-d.ai/dp-size")
        if ann:
            try:
                dp = max(1, int(ann))
            except ValueError:
                pass
        ip = pod["status"]["podIP"]
        out = []
        for rank in range(dp):
            rank_labels = labels if dp == 1 else {
                **labels, "llm-d.ai/dp-rank": str(rank),
            }
            out.append(
                Endpoint(address=f"{ip}:{port + rank}", labels=rank_labels)
            )
        return out

    async def poll_once(self) -> list[Endpoint]:
        session = await self._client()
        qs = urllib.parse.urlencode({"labelSelector": self.label_selector})
        url = f"{self.api_server}/api/v1/namespaces/{self.namespace}/pods?{qs}"
        async with session.get(
            url, headers={"authorization": f"Bearer {self._token()}"}
        ) as resp:
            resp.raise_for_status()
            body = json.loads(await resp.text())
        eps = [
            ep
            for p in body.get("items", [])
            if self._pod_ready(p)
            for ep in self._endpoints_for(p)
        ]
        self.store.reconcile(eps)
        return eps

    # ------------------------------------------------------------- watch

    async def list_once(self) -> None:
        """Seed the store with a full LIST; remember its resourceVersion
        as the watch resume point."""
        session = await self._client()
        qs = urllib.parse.urlencode({"labelSelector": self.label_selector})
        url = f"{self.api_server}/api/v1/namespaces/{self.namespace}/pods?{qs}"
        async with session.get(
            url, headers={"authorization": f"Bearer {self._token()}"}
        ) as resp:
            resp.raise_for_status()
            body = json.loads(await resp.text())
        self._resource_version = body.get("metadata", {}).get("resourceVersion")
        self._pods = {
            p["metadata"]["name"]: (
                self._endpoints_for(p) if self._pod_ready(p) else []
            )
            for p in body.get("items", [])
        }
        self._reconcile()

    def _reconcile(self) -> None:
        self.store.reconcile([ep for eps in self._pods.values() for ep in eps])

    def _apply_event(self, event: dict) -> None:
        etype = event.get("type")
        obj = event.get("object") or {}
        rv = obj.get("metadata", {}).get("resourceVersion")
        if rv:
            self._resource_version = rv
        if etype == "BOOKMARK":
            return
        name = obj.get("metadata", {}).get("name")
        if not name:
            return
        if etype == "DELETED":
            self._pods.pop(name, None)
        elif etype in ("ADDED", "MODIFIED"):
            self._pods[name] = (
                self._endpoints_for(obj) if self._pod_ready(obj) else []
            )
        else:
            return
        self._reconcile()

    async def watch_once(self) -> None:
        """One watch stream: apply events until the server closes it.

        Raises _WatchExpired on 410 Gone (the resume version fell out of
        etcd's window) so the caller re-lists.
        """
        session = await self._client()
        params = {
            "labelSelector": self.label_selector,
            "watch": "1",
            "allowWatchBookmarks": "true",
        }
        if self._resource_version:
            params["resourceVersion"] = self._resource_version
        qs = urllib.parse.urlencode(params)
        url = f"{self.api_server}/api/v1/namespaces/{self.namespace}/pods?{qs}"
        async with session.get(
            url,
            headers={"authorization": f"Bearer {self._token()}"},
            timeout=aiohttp.ClientTimeout(total=None, sock_read=330),
        ) as resp:
            resp.raise_for_status()
            # Manual line framing: StreamReader's line iterator enforces a
            # ~64KB line limit, and one pod event (managedFields, volumes)
            # can exceed it — which would demote every future watch into a
            # 1s full-LIST loop. iter_any + a buffer has no such limit.
            buf = b""
            async for data in resp.content.iter_any():
                buf += data
                while b"\n" in buf:
                    line, buf = buf.split(b"\n", 1)
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        event = json.loads(line)
                    except json.JSONDecodeError:
                        continue
                    if event.get("type") == "ERROR":
                        code = (event.get("object") or {}).get("code")
                        if code == 410:
                            raise _WatchExpired()
                        raise RuntimeError(f"watch error event: {event}")
                    self._apply_event(event)

    async def run(self) -> None:
        if self.mode == "poll":
            while True:
                try:
                    await self.poll_once()
                # llmd: allow(broad-except) -- discovery loop guard: retries next poll with the last-good pool intact
                except Exception as e:
                    log.warning("k8s pod discovery poll failed: %s", e)
                await asyncio.sleep(self.poll_s)
        from llmd_tpu import clock

        while True:
            t0 = clock.monotonic()
            try:
                if self._resource_version is None:
                    await self.list_once()
                await self.watch_once()
                # Clean server-side close: resume from the last version.
                # Guard against proxies that terminate streaming GETs
                # instantly — back-to-back re-watches would storm the
                # apiserver while everything looks healthy.
                if clock.monotonic() - t0 < 1.0:
                    await asyncio.sleep(min(self.poll_s, 1.0))
            except _WatchExpired:
                log.info("watch resourceVersion expired; re-listing")
                self._resource_version = None
            # llmd: allow(broad-except) -- watch loop guard: degrades to a full re-LIST after backoff
            except Exception as e:
                log.warning("k8s pod watch failed (%s); re-listing", e)
                self._resource_version = None
                # Full poll_s backoff: each retry re-LISTs, and a 1 Hz
                # LIST herd is worst exactly when the apiserver is sick.
                await asyncio.sleep(self.poll_s)

    def start(self) -> None:
        self._task = asyncio.get_event_loop().create_task(self.run())

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()

    async def close(self) -> None:
        self.stop()
        if self._session is not None and not self._session.closed:
            await self._session.close()
