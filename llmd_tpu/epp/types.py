"""Core EPP datatypes: endpoints, parsed requests, scheduling results.

Attribute names follow the reference's standardized data-layer attributes
(docs/architecture/core/router/epp/datalayer.md:49-91 — e.g.
KVCacheUsagePercent, WaitingQueueSize) and the `x-llm-d-*` request header
contract (docs/api-reference/epp-http-headers.md:10-44).
"""

from __future__ import annotations

import dataclasses
from typing import Any

from llmd_tpu import clock

# Standard attribute keys (datalayer core-metrics-extractor output).
KV_CACHE_USAGE = "KVCacheUsagePercent"
WAITING_QUEUE_SIZE = "WaitingQueueSize"
RUNNING_REQUESTS = "RunningRequests"
PREFIX_HIT_RATIO = "PrefixCacheHitRatio"
BLOCK_SIZE = "BlockSize"
NUM_BLOCKS = "NumBlocks"
TOKENS_IN_FLIGHT = "TokensInFlight"

# Pod role labels (reference disaggregation/README.md:95-99; encode tier
# from multimodal-serving/e-disaggregation).
ROLE_LABEL = "llm-d.ai/role"
ROLE_PREFILL = "prefill"
ROLE_DECODE = "decode"
ROLE_BOTH = "prefill-decode"
ROLE_ENCODE = "encode"

# Request headers (reference docs/api-reference/epp-http-headers.md:10-25).
HDR_OBJECTIVE = "x-llm-d-objective"
HDR_FAIRNESS_ID = "x-llm-d-fairness-id"
HDR_TTFT_SLO = "x-llm-d-slo-ttft-ms"
HDR_TPOT_SLO = "x-llm-d-slo-tpot-ms"
HDR_PREFILLER = "x-prefiller-host-port"
HDR_ENCODER = "x-encoder-host-port"
# Sidecar -> engine only: the encoder host whose ec_embedding parts the
# sidecar itself injected. The engine pulls EC handles from this host
# alone; the sidecar strips any client-supplied copy of the header.
HDR_EC_HOST = "x-llm-d-ec-host"
HDR_DROP_REASON = "x-llm-d-request-dropped-reason"
# Mid-stream failover (docs/architecture/fault-tolerance.md): the router
# sets this on proxied streaming requests so the engine annotates every
# SSE delta frame with its raw token ids ("token_ids") — the accumulated
# history the router replays as `resume_token_ids` when the upstream
# dies mid-stream. The router strips the field before frames reach the
# client.
HDR_STREAM_TOKENS = "x-llmd-stream-tokens"
# Marks a router-issued REPLAY leg of a cut stream (set alongside the
# resume_token_ids body field, including when the history is still
# empty — e.g. the upstream died after the chat role preamble but
# before the first token): the engine grafts onto the already-open
# client stream and must not re-emit stream preambles.
HDR_RESUME = "x-llmd-resume"
# Batch serving tier (docs/architecture/batch-processing.md): the batch
# processor marks offline work with this header; parsers clamp such
# requests to the backfill band.
HDR_PRIORITY = "x-llmd-priority"
# The backfill band's priority ceiling. Kept numerically identical to
# llmd_tpu.engine.request.PriorityClass.BATCH (pinned by test) but
# duplicated here so the EPP stays importable without the engine
# package: requests at or below this ride the batch band — a dedicated
# flow-control band below every interactive priority, the EPP's
# batch-saturation-filter, and the engine scheduler's backfill-only
# discipline.
BATCH_PRIORITY = -100


@dataclasses.dataclass
class Endpoint:
    """One model-server endpoint (pod:port in the reference)."""

    address: str  # "host:port"
    labels: dict[str, str] = dataclasses.field(default_factory=dict)
    model: str | None = None
    # Data-layer attributes, refreshed by collectors (metrics poll, KV index).
    attrs: dict[str, Any] = dataclasses.field(default_factory=dict)
    last_seen: float = dataclasses.field(default_factory=clock.monotonic)
    healthy: bool = True
    # Requests routed here that have not yet completed (EPP-side view,
    # fresher than the polled metrics — the inflight-load-producer).
    inflight: int = 0
    # Tokens routed here recently (token-load scoring).
    inflight_tokens: int = 0

    @property
    def role(self) -> str:
        return self.labels.get(ROLE_LABEL, ROLE_BOTH)

    @property
    def url(self) -> str:
        return f"http://{self.address}"

    def attr(self, key: str, default: float = 0.0) -> float:
        v = self.attrs.get(key)
        return default if v is None else float(v)


@dataclasses.dataclass
class LLMRequest:
    """A parsed inference request flowing through the EPP pipeline."""

    request_id: str
    model: str = ""
    prompt_text: str = ""
    prompt_token_ids: list[int] | None = None
    headers: dict[str, str] = dataclasses.field(default_factory=dict)
    body: dict[str, Any] = dataclasses.field(default_factory=dict)
    path: str = "/v1/completions"
    streaming: bool = False
    arrival_time: float = dataclasses.field(default_factory=clock.monotonic)
    # flow-control key parts
    priority: int = 0
    fairness_id: str = ""
    # SLO objectives (ms) if provided
    ttft_slo_ms: float | None = None
    tpot_slo_ms: float | None = None
    # predicted output length (latency predictor / heuristics)
    predicted_output_tokens: int | None = None
    # Multimodal items (images) found in the request: each entry carries a
    # content `ref` (digest of the inline data/URL) and optional
    # width/height for token estimation (reference token-producer
    # `estimate`, e-p-d-disaggregation.values.yaml:31-40).
    mm_items: list[dict] = dataclasses.field(default_factory=list)
    # Visual-token estimate summed over mm_items (set by the parser).
    mm_token_estimate: int = 0
    # Scratch space for DataProducers (prefix hashes, predictions, ...).
    scratch: dict[str, Any] = dataclasses.field(default_factory=dict)

    @property
    def num_prompt_chars(self) -> int:
        return len(self.prompt_text)

    @property
    def approx_prompt_tokens(self) -> int:
        if self.prompt_token_ids is not None:
            return len(self.prompt_token_ids) + self.mm_token_estimate
        # Char-ratio approximation (reference
        # prefix-cache-aware-routing.md:18-21): ~4 chars/token.
        return max(1, len(self.prompt_text) // 4) + self.mm_token_estimate


@dataclasses.dataclass
class ProfileResult:
    """Outcome of one scheduling profile run."""

    profile: str
    endpoint: Endpoint | None
    scores: dict[str, float] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class SchedulingResult:
    """The destination(s) picked for a request.

    ``primary`` receives the request; ``prefill`` (if set) is advertised via
    the x-prefiller-host-port header for the P/D sidecar two-phase flow
    (reference disaggregation/README.md:57-99).
    """

    primary: Endpoint
    prefill: Endpoint | None = None
    # Encode worker for E/P/D multimodal disaggregation, advertised via
    # x-encoder-host-port (multimodal-serving/README.md:41-46).
    encode: Endpoint | None = None
    profiles: dict[str, ProfileResult] = dataclasses.field(default_factory=dict)
