"""Per-endpoint request-failure circuit breaker.

The scrape-health window (MetricsCollector, 3 consecutive failed scrapes
at the poll interval) takes seconds to mark a dead endpoint unhealthy —
seconds during which the picker keeps sending real requests into
connection-refused. Request outcomes are a faster signal: the proxy leg
feeds every connect-refused/5xx into this breaker, which OPENS the
endpoint after ``failure_threshold`` consecutive failures (default 2 —
strictly faster than the 3-scrape window even if every scrape also
fails) and releases it after ``cooldown_s`` into a half-open probe: the
next request may try it, one more failure re-opens it immediately (the
consecutive count survives the cooldown), one success resets it fully.

State is address-keyed and time-based only — no background task, safe
on the router's single event loop.
"""

from __future__ import annotations

import time


class EndpointCircuitBreaker:
    def __init__(
        self, failure_threshold: int = 2, cooldown_s: float = 10.0
    ) -> None:
        self.failure_threshold = failure_threshold
        self.cooldown_s = cooldown_s
        self._consecutive: dict[str, int] = {}
        self._open_until: dict[str, float] = {}
        self.trips_total = 0

    def record_failure(self, address: str) -> None:
        n = self._consecutive.get(address, 0) + 1
        self._consecutive[address] = n
        # Open only on the closed->open TRANSITION: several in-flight
        # requests failing against one endpoint are ONE outage — extra
        # failures must neither inflate trips_total (an alerting
        # signal) nor keep pushing the cooldown window out.
        if n >= self.failure_threshold and address not in self._open_until:
            self._open_until[address] = time.monotonic() + self.cooldown_s
            self.trips_total += 1

    def record_success(self, address: str) -> None:
        self._consecutive.pop(address, None)
        self._open_until.pop(address, None)

    def is_open(self, address: str) -> bool:
        until = self._open_until.get(address)
        if until is None:
            return False
        if time.monotonic() >= until:
            # Cooldown elapsed: half-open. The consecutive count is left
            # at/above threshold, so one probe failure re-opens at once.
            self._open_until.pop(address, None)
            return False
        return True

    def open_endpoints(self) -> list[str]:
        now = time.monotonic()
        return sorted(a for a, t in self._open_until.items() if t > now)

    def forget(self, address: str) -> None:
        """Endpoint left the pool: a recycled host:port must start clean."""
        self._consecutive.pop(address, None)
        self._open_until.pop(address, None)
