"""Per-endpoint request-failure circuit breaker.

The scrape-health window (MetricsCollector, 3 consecutive failed scrapes
at the poll interval) takes seconds to mark a dead endpoint unhealthy —
seconds during which the picker keeps sending real requests into
connection-refused. Request outcomes are a faster signal: the proxy leg
feeds every connect-refused/5xx into this breaker, which OPENS the
endpoint after ``failure_threshold`` consecutive failures (default 2 —
strictly faster than the 3-scrape window even if every scrape also
fails) and releases it after ``cooldown_s`` into a half-open probe.

Half-open admits exactly ONE probe, and the grant is claimed at
DISPATCH time, not filter time: schedule-time ``is_open()`` is
non-consuming (a half-open endpoint reads as a candidate until someone
actually routes to it — filtering a candidate in and then scoring the
request onto a different pod must not burn the probe and lock the
endpoint out for another cooldown), while ``take_probe()`` — called by
the proxy leg for the pod it is about to send to — claims the grant.
The first ``take_probe()`` after the cooldown elapses wins; every
other caller loses the race, and ``is_open()`` reads True for everyone
while that probe is in flight — so a burst of concurrent requests
arriving at cooldown expiry cannot stampede a recovering replica, and
two concurrent probes can neither double-close nor double-trip the
circuit. A probe failure re-opens immediately (the consecutive count
survives the cooldown); a success resets fully. A probe that never
resolves (its caller died) expires after another ``cooldown_s`` and
the next ``take_probe()`` wins a fresh grant — an unresolved grant
must not lock an endpoint out forever.

Thresholds default from the environment so a chaos soak can sweep them
without code changes: ``LLMD_EPP_BREAKER_THRESHOLD`` (consecutive
failures to open, default 2) and ``LLMD_EPP_BREAKER_COOLDOWN_S``
(open→half-open cooldown seconds, default 10).

State is address-keyed and time-based only — no background task, safe
on the router's single event loop. Time comes from an injectable
``clock`` (default :func:`llmd_tpu.clock.monotonic`) so the fleet
simulator can drive cooldowns in virtual time.
"""

from __future__ import annotations

import os
from typing import Callable

from llmd_tpu import clock as _clock


def _env_threshold() -> int:
    return int(os.environ.get("LLMD_EPP_BREAKER_THRESHOLD", "2"))


def _env_cooldown_s() -> float:
    return float(os.environ.get("LLMD_EPP_BREAKER_COOLDOWN_S", "10.0"))


# Probe-grant lifecycle (static-analysis.md): a half-open grant claimed
# by take_probe must resolve through record_success / record_failure /
# forget — an unresolved grant locks the endpoint out for a full extra
# cooldown (the PR 8 bug class). Expiry after cooldown_s is the
# designed backstop, not a release path callers may lean on.
# llmd: resource(probes, recv=breaker, acquire=take_probe:arg, release=record_success|record_failure|forget)
class EndpointCircuitBreaker:
    def __init__(
        self,
        failure_threshold: int | None = None,
        cooldown_s: float | None = None,
        clock: Callable[[], float] | None = None,
    ) -> None:
        self.failure_threshold = (
            _env_threshold() if failure_threshold is None else failure_threshold
        )
        self.cooldown_s = _env_cooldown_s() if cooldown_s is None else cooldown_s
        self._clock = clock or _clock.monotonic
        self._consecutive: dict[str, int] = {}
        self._open_until: dict[str, float] = {}
        # address -> sim/real time the outstanding half-open probe was
        # granted; present while exactly one probe is in flight.
        self._probe_granted: dict[str, float] = {}
        self.trips_total = 0

    def record_failure(self, address: str) -> None:
        n = self._consecutive.get(address, 0) + 1
        self._consecutive[address] = n
        # A failure resolves any outstanding half-open probe.
        self._probe_granted.pop(address, None)
        now = self._clock()
        until = self._open_until.get(address)
        if until is not None and now >= until:
            # Half-open and the probe (or a straggler from before the
            # trip) failed: re-open at once. This IS a transition
            # (open -> half-open -> open), so it counts a trip.
            self._open_until[address] = now + self.cooldown_s
            self.trips_total += 1
            return
        # Open only on the closed->open TRANSITION: several in-flight
        # requests failing against one endpoint are ONE outage — extra
        # failures must neither inflate trips_total (an alerting
        # signal) nor keep pushing the cooldown window out.
        if n >= self.failure_threshold and until is None:
            self._open_until[address] = now + self.cooldown_s
            self.trips_total += 1

    def record_success(self, address: str) -> None:
        self._consecutive.pop(address, None)
        self._open_until.pop(address, None)
        self._probe_granted.pop(address, None)

    def is_open(self, address: str) -> bool:
        """Schedule-time filter: True while the endpoint must be held
        out of the candidate set. Non-consuming — a half-open endpoint
        stays a candidate (False) until some request claims the probe
        via :meth:`take_probe` at dispatch, then reads True for
        everyone else until that probe resolves or its grant expires."""
        until = self._open_until.get(address)
        if until is None:
            return False
        now = self._clock()
        if now < until:
            return True
        granted = self._probe_granted.get(address)
        return granted is not None and now - granted < self.cooldown_s

    def take_probe(self, address: str) -> bool:
        """Dispatch-time gate: claim the half-open single-probe grant
        for the pod the caller is about to send to. True = send
        (circuit closed, or this caller won the probe, or the circuit
        is fully open — the open case is reachable only through the
        fail-open filter branch when EVERY pool member is open, and
        the breaker must degrade to trying, never manufacture a 503).
        False = another probe is already in flight on this half-open
        endpoint; skip the pod and re-pick."""
        until = self._open_until.get(address)
        if until is None:
            return True
        now = self._clock()
        if now < until:
            return True
        # Cooldown elapsed: half-open. Grant exactly one probe; the
        # grant resolves via record_success (closes) / record_failure
        # (re-opens — the consecutive count is still at/above
        # threshold) or expires after another cooldown.
        granted = self._probe_granted.get(address)
        if granted is None or now - granted >= self.cooldown_s:
            self._probe_granted[address] = now
            return True
        return False

    def open_endpoints(self) -> list[str]:
        now = self._clock()
        return sorted(a for a, t in self._open_until.items() if t > now)

    def forget(self, address: str) -> None:
        """Endpoint left the pool: a recycled host:port must start clean."""
        self._consecutive.pop(address, None)
        self._open_until.pop(address, None)
        self._probe_granted.pop(address, None)


# Runtime twin of the `# llmd: resource(probes, ...)` annotation
# (static-analysis.md): LLMD_LEAKSAN=1 tracks each claimed half-open
# grant until it resolves; `live` honors the designed cooldown expiry
# so an expired grant is a released one, not a leak.
from llmd_tpu.analysis import sanitize as _sanitize

_sanitize.leaksan_register(
    EndpointCircuitBreaker, "probes", mode="set",
    acquire={
        "take_probe": lambda self, a, k, r: (
            [a[0]] if (r and a[0] in self._probe_granted) else []
        ),
    },
    # A resolution method only releases the grant it actually cleared:
    # checking post-state (not the method's name) means a breaker that
    # CLAIMS to resolve but leaves the grant behind is caught as a leak
    # — the exact PR 8 regression the mutation pin re-introduces.
    release={
        "record_success": lambda self, a, k, r: (
            [a[0]] if a[0] not in self._probe_granted else []
        ),
        "record_failure": lambda self, a, k, r: (
            [a[0]] if a[0] not in self._probe_granted else []
        ),
        "forget": lambda self, a, k, r: (
            [a[0]] if a[0] not in self._probe_granted else []
        ),
    },
    live=lambda self, h: (
        h in self._probe_granted
        and (self._clock() - self._probe_granted[h]) < self.cooldown_s
    ),
)
