"""Flow Control: fairness-aware, saturation-gated admission queues.

Reference: docs/architecture/core/router/epp/flow-control.md —
FlowKey=(FairnessID, Priority) queues grouped into priority bands
(:27-41); a 3-tier dispatch cycle (strict priority band order → fairness
policy across flows in the band → ordering policy within the flow,
:197-254); a saturation-gated dispatch loop (:260-295); global + per-band
capacity limits and TTL eviction (:293-359); and the outcome → HTTP mapping
(429/503 + x-llm-d-request-dropped-reason, :369-409).

Policies: fairness `round-robin` | `strict` (first flow always wins);
ordering `fcfs` | `edf` (earliest deadline = arrival + TTFT SLO first).
"""

from __future__ import annotations

import asyncio
import collections
import enum
import logging
from dataclasses import dataclass, field
from typing import Callable

from llmd_tpu import clock
from llmd_tpu.epp.types import LLMRequest

log = logging.getLogger(__name__)


class Outcome(enum.Enum):
    DISPATCHED = "dispatched"
    REJECTED_CAPACITY = "rejected-capacity"  # 429
    EVICTED_TTL = "evicted-ttl"  # 503 retryable
    EVICTED_SHUTDOWN = "evicted-shutdown"  # 503 retryable
    REJECTED_OTHER = "rejected-other"  # 500


# outcome -> (HTTP status, x-llm-d-request-dropped-reason)
OUTCOME_HTTP = {
    Outcome.REJECTED_CAPACITY: (429, "queue-full"),
    Outcome.EVICTED_TTL: (503, "ttl-expired"),
    Outcome.EVICTED_SHUTDOWN: (503, "shutting-down"),
    Outcome.REJECTED_OTHER: (500, "internal"),
}


@dataclass
class BandConfig:
    """Capacity limits for one priority band (flow-control.md:293-312)."""

    priority: int
    max_requests: int = 1024
    max_bytes: int = 1 << 30
    ttl_s: float = 60.0


# Default EDF budget for requests that carry NO TTFT SLO. An infinite
# deadline starves no-SLO traffic whenever SLO-carrying flows keep queue
# depth (they would sort first forever); a finite default keeps EDF's
# urgency ordering while guaranteeing no-SLO requests age toward the
# front (reference keeps fcfs/edf/slo-deadline distinct orderings —
# flow-control.md; this matches slo-deadline's fallback behavior).
DEFAULT_EDF_BUDGET_S = 30.0


@dataclass
class _Item:
    req: LLMRequest
    bytes: int
    future: asyncio.Future
    enqueue_time: float = field(default_factory=clock.monotonic)

    @property
    def deadline(self) -> float:
        # EDF deadline: arrival + TTFT SLO (flow-control.md ordering edf);
        # no-SLO requests get a finite default budget so they cannot be
        # starved behind a continuous SLO-carrying stream.
        if self.req.ttft_slo_ms is not None:
            return self.req.arrival_time + self.req.ttft_slo_ms / 1000.0
        return self.req.arrival_time + DEFAULT_EDF_BUDGET_S


class SaturationDetector:
    """Decides whether the backend pool can absorb another dispatch.

    `concurrency` mode: global inflight cap. `utilization` mode: average
    backend KV utilization / queue depth thresholds (flow-control.md
    saturation detectors)."""

    def __init__(
        self,
        max_inflight: int | None = None,
        max_kv_usage: float | None = None,
        max_queue_depth: float | None = None,
        pool_stats: Callable[[], tuple[float, float]] | None = None,
    ) -> None:
        self.max_inflight = max_inflight
        self.max_kv_usage = max_kv_usage
        self.max_queue_depth = max_queue_depth
        self.pool_stats = pool_stats  # () -> (avg_kv_usage, avg_queue_depth)
        self.inflight = 0

    def saturated(self) -> bool:
        if self.max_inflight is not None and self.inflight >= self.max_inflight:
            return True
        if self.pool_stats is not None and (
            self.max_kv_usage is not None or self.max_queue_depth is not None
        ):
            kv, depth = self.pool_stats()
            if self.max_kv_usage is not None and kv >= self.max_kv_usage:
                return True
            if self.max_queue_depth is not None and depth >= self.max_queue_depth:
                return True
        return False


class FlowControl:
    """EnqueueAndWait + background dispatch loop (flow-control.md:260-295)."""

    def __init__(
        self,
        bands: list[BandConfig] | None = None,
        fairness: str = "round-robin",
        ordering: str = "fcfs",
        saturation: SaturationDetector | None = None,
        max_total_requests: int = 4096,
        poll_interval_s: float = 0.005,
        enabled: bool = True,
    ) -> None:
        self.enabled = enabled
        self.bands = {b.priority: b for b in (bands or [BandConfig(priority=0)])}
        if fairness not in ("round-robin", "strict"):
            raise ValueError(f"unknown fairness policy {fairness!r}")
        if ordering not in ("fcfs", "edf"):
            raise ValueError(f"unknown ordering policy {ordering!r}")
        self.fairness = fairness
        self.ordering = ordering
        self.saturation = saturation or SaturationDetector()
        self.max_total_requests = max_total_requests
        self.poll_interval_s = poll_interval_s
        # band priority -> flow id -> deque[_Item]
        self._queues: dict[int, dict[str, collections.deque[_Item]]] = {}
        # round-robin cursor per band
        self._rr: dict[int, collections.deque[str]] = {}
        self._total = 0
        self._bytes: dict[int, int] = collections.defaultdict(int)
        self._counts: dict[int, int] = collections.defaultdict(int)
        self._event = asyncio.Event()
        self._task: asyncio.Task | None = None
        self._draining = False
        self.outcomes: collections.Counter = collections.Counter()

    # ------------------------------------------------------------------ #

    def band_for(self, priority: int) -> BandConfig:
        """Unconfigured priorities get a default-capacity band AT their own
        priority — never demoted below configured bands."""
        band = self.bands.get(priority)
        if band is None:
            band = self.bands[priority] = BandConfig(priority=priority)
        return band

    def queue_depth(self) -> int:
        return self._total

    async def enqueue_and_wait(self, req: LLMRequest, nbytes: int = 0) -> Outcome:
        """Park the caller until dispatched or dropped; returns the outcome."""
        if not self.enabled:
            return Outcome.DISPATCHED
        if self._draining:
            self.outcomes[Outcome.EVICTED_SHUTDOWN] += 1
            return Outcome.EVICTED_SHUTDOWN
        band = self.band_for(req.priority)
        if (
            self._total >= self.max_total_requests
            or self._counts[band.priority] >= band.max_requests
            or self._bytes[band.priority] + nbytes > band.max_bytes
        ):
            self.outcomes[Outcome.REJECTED_CAPACITY] += 1
            return Outcome.REJECTED_CAPACITY
        item = _Item(req, nbytes, asyncio.get_event_loop().create_future())
        flows = self._queues.setdefault(band.priority, {})
        flow = flows.get(req.fairness_id)
        if flow is None:
            flow = collections.deque()
            flows[req.fairness_id] = flow
            self._rr.setdefault(band.priority, collections.deque()).append(
                req.fairness_id
            )
        flow.append(item)
        self._total += 1
        self._counts[band.priority] += 1
        self._bytes[band.priority] += nbytes
        self._event.set()
        try:
            outcome = await item.future
        except asyncio.CancelledError:
            # If the dispatcher already granted the slot, give it back —
            # the caller will never reach its release().
            if (
                item.future.done()
                and not item.future.cancelled()
                and item.future.result() is Outcome.DISPATCHED
            ):
                self.release()
            else:
                item.future = None  # type: ignore  # mark dead; dispatch skips it
            raise
        self.outcomes[outcome] += 1
        return outcome

    def _grant(self, item: _Item) -> None:
        """Hand a parked caller its admission token: the request owns
        one unit of inflight concurrency from here until release().
        The explicit method is the leak-sanitizer seam — LLMD_LEAKSAN=1
        counts grants against releases per FlowControl instance."""
        self.saturation.inflight += 1
        item.future.set_result(Outcome.DISPATCHED)

    def release(self) -> None:
        """A dispatched request completed (frees inflight concurrency)."""
        if not self.enabled:
            return
        self.saturation.inflight = max(0, self.saturation.inflight - 1)
        self._event.set()

    # ------------------------------------------------------------------ #
    # dispatch cycle: strict band priority -> fairness -> ordering

    def _next_item(self) -> _Item | None:
        for prio in sorted(self._queues, reverse=True):  # higher = first
            flows = self._queues[prio]
            order = self._rr.get(prio, collections.deque())
            if self.fairness == "strict":
                candidates = sorted(order)
            else:
                candidates = list(order)
            for flow_id in candidates:
                flow = flows.get(flow_id)
                if not flow:
                    continue
                if self.ordering == "edf":
                    item = min(flow, key=lambda it: (it.deadline, it.enqueue_time))
                    flow.remove(item)
                else:
                    item = flow.popleft()
                self._pop_accounting(prio, item)
                if self.fairness == "round-robin":
                    order.rotate(-(candidates.index(flow_id) + 1))
                return item
        return None

    def _pop_accounting(self, prio: int, item: _Item) -> None:
        self._total -= 1
        self._counts[prio] -= 1
        self._bytes[prio] -= item.bytes
        flows = self._queues[prio]
        if not flows.get(item.req.fairness_id):
            flows.pop(item.req.fairness_id, None)
            try:
                self._rr[prio].remove(item.req.fairness_id)
            except ValueError:
                pass

    def _expire_ttls(self) -> None:
        now = clock.monotonic()
        for prio, flows in list(self._queues.items()):
            ttl = self.band_for(prio).ttl_s
            for flow_id, flow in list(flows.items()):
                while flow and now - flow[0].enqueue_time > ttl:
                    item = flow.popleft()
                    self._pop_accounting(prio, item)
                    if item.future is not None and not item.future.done():
                        item.future.set_result(Outcome.EVICTED_TTL)

    async def _dispatch_loop(self) -> None:
        while True:
            self._expire_ttls()
            if self._total == 0:
                self._event.clear()
                await self._event.wait()
                continue
            if self.saturation.saturated():
                # Saturated: hold dispatch, poll (the reference's
                # saturation-gated worker loop, flow-control.md:260-295).
                await asyncio.sleep(self.poll_interval_s)
                continue
            item = self._next_item()
            if item is None:
                await asyncio.sleep(self.poll_interval_s)
                continue
            if item.future is None or item.future.done():
                continue  # caller went away
            self._grant(item)

    def start(self) -> None:
        """Start the dispatch worker (idempotent: the fused HTTP app and
        the ext-proc gRPC server may both run in one process)."""
        if self._task is None or self._task.done():
            self._task = asyncio.get_event_loop().create_task(
                self._dispatch_loop()
            )

    async def drain(self) -> None:
        """Graceful shutdown: evict queued requests with retryable 503
        (flow-control.md:312,350)."""
        self._draining = True
        for prio, flows in list(self._queues.items()):
            for flow in list(flows.values()):
                while flow:
                    item = flow.popleft()
                    self._pop_accounting(prio, item)
                    if item.future is not None and not item.future.done():
                        item.future.set_result(Outcome.EVICTED_SHUTDOWN)
        if self._task:
            self._task.cancel()


# Leak-sanitizer registration (static-analysis.md): admission tokens
# are anonymous — the dispatcher's _grant pushes one, the caller's
# release() pops one — so LLMD_LEAKSAN counts them LIFO per instance;
# a release with no grant outstanding is a violation, and grants still
# outstanding at test teardown carry the granting backtrace.
from llmd_tpu.analysis import sanitize as _sanitize

_sanitize.leaksan_register(
    FlowControl, "tokens", mode="anon",
    acquire={"_grant": lambda self, a, k, r: [None]},
    release={
        "release": lambda self, a, k, r: [None] if self.enabled else [],
    },
)
