"""Predicted-latency routing: producer, scorer, SLO filter, SLO admitter.

Reference behavior (docs/architecture/advanced/latency-predictor.md and
guides/predicted-latency-routing): a `predicted-latency-producer` annotates
every candidate endpoint with model-predicted TTFT/TPOT before scheduling;
a latency scorer prefers low predicted latency; the `slo-headroom-tier`
filter keeps endpoints whose predicted latency leaves headroom under the
request's SLO (x-llm-d-slo-ttft-ms / x-llm-d-slo-tpot-ms headers); the
`latency-slo-admitter` sheds low-priority requests whose SLO no endpoint
can meet. Completed requests feed observed TTFT/TPOT back to the trainer —
the continuous-retrain loop.

The predictor itself may be in-process (a LatencyPredictor instance — the
dev/no-K8s mode) or remote sidecars (llmd_tpu.predictor.server); both are
behind PredictorClient.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Sequence

import aiohttp

from llmd_tpu.epp.handler import Admitter
from llmd_tpu.epp.plugins import Filter, Scorer, register
from llmd_tpu.epp.types import (
    KV_CACHE_USAGE,
    PREFIX_HIT_RATIO,
    RUNNING_REQUESTS,
    TOKENS_IN_FLIGHT,
    WAITING_QUEUE_SIZE,
    Endpoint,
    LLMRequest,
)
from llmd_tpu.predictor.model import (
    LatencyPredictor,
    ttft_features,
    tpot_features,
)

log = logging.getLogger("llmd.epp.latency")

SCRATCH_TTFT = "predicted_ttft_ms"  # {addr: ms}
SCRATCH_TPOT = "predicted_tpot_ms"  # {addr: ms}
SCRATCH_FEATURES = "latency_features"  # {addr: (ttft_f, tpot_f)}


def endpoint_features(
    req: LLMRequest, pod: Endpoint
) -> tuple[list[float], list[float]]:
    """Feature vectors for scheduling ``req`` on ``pod`` right now.

    The prefix feature prefers the TIER-WEIGHTED fraction
    (prefix_weighted_frac — store-fetchable blocks charged at the store
    tier weight, kv-federation.md) over the flat match count, so the
    latency estimate charges a store-fetchable prefix less than a
    recompute but more than a resident hit; it falls back to
    prefix_match_frac, then to the polled PrefixCacheHitRatio attribute.
    """
    prefix = req.scratch.get("prefix_weighted_frac", {}).get(
        pod.address,
        req.scratch.get("prefix_match_frac", {}).get(
            pod.address, pod.attr(PREFIX_HIT_RATIO)
        ),
    )
    tf = ttft_features(
        kv_usage=pod.attr(KV_CACHE_USAGE),
        waiting_queue=pod.attr(WAITING_QUEUE_SIZE),
        running=pod.attr(RUNNING_REQUESTS) + pod.inflight,
        input_tokens=req.approx_prompt_tokens,
        prefix_hit_ratio=prefix,
        tokens_in_flight=pod.attr(TOKENS_IN_FLIGHT, pod.inflight_tokens),
    )
    pf = tpot_features(
        kv_usage=pod.attr(KV_CACHE_USAGE),
        running=pod.attr(RUNNING_REQUESTS) + pod.inflight,
        input_tokens=req.approx_prompt_tokens,
        tokens_in_flight=pod.attr(TOKENS_IN_FLIGHT, pod.inflight_tokens),
    )
    return tf, pf


class PredictorClient:
    """In-process predictor, optionally backed by remote sidecars."""

    def __init__(
        self,
        predictor: LatencyPredictor | None = None,
        predict_url: str | None = None,
        train_url: str | None = None,
        timeout_s: float = 0.2,
    ) -> None:
        self.predictor = predictor or LatencyPredictor()
        self.predict_url = predict_url
        self.train_url = train_url
        self.timeout_s = timeout_s
        self._session: aiohttp.ClientSession | None = None

    async def _client(self) -> aiohttp.ClientSession:
        if self._session is None:
            self._session = aiohttp.ClientSession(
                timeout=aiohttp.ClientTimeout(total=self.timeout_s)
            )
        return self._session

    async def predict(
        self, ttft_f: Sequence[float], tpot_f: Sequence[float]
    ) -> tuple[float, float]:
        if self.predict_url:
            try:
                session = await self._client()
                async with session.post(
                    self.predict_url + "/v1/predict",
                    json={"ttft_features": list(ttft_f), "tpot_features": list(tpot_f)},
                ) as r:
                    d = await r.json()
                    return float(d["ttft_ms"]), float(d["tpot_ms"])
            # llmd: allow(broad-except) -- degrades to the in-process predictor below; scoring never fails a request
            except Exception:
                log.debug("remote predict failed; using local fallback")
        return (
            self.predictor.predict_ttft(ttft_f)[0],
            self.predictor.predict_tpot(tpot_f)[0],
        )

    async def observe(
        self,
        ttft_f: Sequence[float],
        ttft_ms: float | None,
        tpot_f: Sequence[float],
        tpot_ms: float | None,
    ) -> None:
        if ttft_ms is not None:
            self.predictor.observe_ttft(ttft_f, ttft_ms)
        if tpot_ms is not None:
            self.predictor.observe_tpot(tpot_f, tpot_ms)
        payload: dict = {}
        if ttft_ms is not None:
            payload["ttft"] = [{"features": list(ttft_f), "ms": ttft_ms}]
        if tpot_ms is not None:
            payload["tpot"] = [{"features": list(tpot_f), "ms": tpot_ms}]
        if self.train_url and payload:
            try:
                session = await self._client()
                async with session.post(
                    self.train_url + "/v1/samples", json=payload
                ) as r:
                    await r.read()
            # llmd: allow(broad-except) -- training feedback is best-effort; a lost sample costs model freshness only
            except Exception:
                log.debug("trainer sample post failed")

    async def close(self) -> None:
        if self._session is not None:
            await self._session.close()
            self._session = None


class PredictedLatencyProducer:
    """DataProducer: annotate req.scratch with per-endpoint predictions.

    ``prefix_index``: the precise-prefix KV-event index, when the
    deployment runs one. The producer scores the tri-state
    (resident / store-fetchable / recompute) weighted prefix fraction
    BEFORE predicting, so the SLO admitter — which runs off these
    predictions ahead of the scorer phase — charges a store-fetchable
    prefix less than a recompute (kv-federation.md store-aware
    admission)."""

    def __init__(
        self,
        client: PredictorClient | None = None,
        prefix_index=None,
    ) -> None:
        self.client = client or PredictorClient()
        self.prefix_index = prefix_index

    def _seed_weighted_prefix(
        self, req: LLMRequest, pods: list[Endpoint]
    ) -> None:
        from llmd_tpu.epp.precise_prefix import SCRATCH_BLOCK_HASHES

        hashes = req.scratch.get(SCRATCH_BLOCK_HASHES)
        if not hashes or self.prefix_index is None:
            return
        weighted = req.scratch.setdefault("prefix_weighted_frac", {})
        fracs = req.scratch.setdefault("prefix_match_frac", {})
        detailed = self.prefix_index.score_detailed(
            hashes, [p.address for p in pods]
        )
        # Stash the raw walk for the precise scorer (same index, same
        # request): the scheduling pass pays the O(pods x hashes) index
        # walk ONCE, not once per plugin.
        req.scratch[f"prefix_detailed:{id(self.prefix_index)}"] = detailed
        n = len(hashes)
        for addr, (s, matched) in detailed.items():
            weighted[addr] = max(weighted.get(addr, 0.0), s / n)
            fracs[addr] = max(fracs.get(addr, 0.0), matched / n)

    async def produce(self, req: LLMRequest, pods: list[Endpoint]) -> None:
        self._seed_weighted_prefix(req, pods)
        feats = {p.address: endpoint_features(req, p) for p in pods}
        # One concurrent round trip regardless of pool size (a degraded
        # prediction sidecar must not add N x timeout to the critical path).
        results = await asyncio.gather(
            *(self.client.predict(tf, pf) for tf, pf in feats.values())
        )
        req.scratch[SCRATCH_TTFT] = {
            a: t for a, (t, _) in zip(feats, results)
        }
        req.scratch[SCRATCH_TPOT] = {
            a: p for a, (_, p) in zip(feats, results)
        }
        req.scratch[SCRATCH_FEATURES] = feats

    async def on_complete(
        self,
        req: LLMRequest,
        pod: Endpoint,
        ttft_ms: float | None,
        tpot_ms: float | None,
    ) -> None:
        """Completion observer: feed observed latencies back to training."""
        feats = req.scratch.get(SCRATCH_FEATURES, {}).get(pod.address)
        if feats is None:
            tf, pf = endpoint_features(req, pod)
        else:
            tf, pf = feats
        await self.client.observe(tf, ttft_ms, pf, tpot_ms)


def _predicted(req: LLMRequest, pod: Endpoint) -> tuple[float, float]:
    """Predicted (ttft_ms, tpot_ms), heuristic-computed if no producer ran."""
    ttft = req.scratch.get(SCRATCH_TTFT, {}).get(pod.address)
    tpot = req.scratch.get(SCRATCH_TPOT, {}).get(pod.address)
    if ttft is None or tpot is None:
        from llmd_tpu.predictor.model import heuristic_tpot_ms, heuristic_ttft_ms

        tf, pf = endpoint_features(req, pod)
        ttft = ttft if ttft is not None else heuristic_ttft_ms(tf)
        tpot = tpot if tpot is not None else heuristic_tpot_ms(pf)
    return float(ttft), float(tpot)


@register("latency-scorer")
class LatencyScorer(Scorer):
    """Lower predicted latency -> higher score (normalized per request).

    ttft_weight/tpot_weight blend the two objectives; streaming chat cares
    about both, embeddings only about TTFT.
    """

    def __init__(self, ttft_weight: float = 1.0, tpot_weight: float = 1.0) -> None:
        self.ttft_weight = ttft_weight
        self.tpot_weight = tpot_weight

    def score(self, req: LLMRequest, pods: list[Endpoint]) -> dict[str, float]:
        costs: dict[str, float] = {}
        for pod in pods:
            ttft, tpot = _predicted(req, pod)
            costs[pod.address] = self.ttft_weight * ttft + self.tpot_weight * tpot
        worst = max(costs.values(), default=0.0)
        if worst <= 0:
            return {a: 1.0 for a in costs}
        return {a: 1.0 - c / worst for a, c in costs.items()}


@register("slo-headroom-tier-filter")
class SloHeadroomTierFilter(Filter):
    """Keep the best headroom tier among endpoints meeting the SLO.

    Headroom = slo - predicted. Tiers of ``tier_ms`` width let load spread
    within a tier instead of always dog-piling the single best endpoint
    (reference scheduling.md:77-83 `slo-headroom-tier`). Requests without
    SLO headers pass through unfiltered. If nobody meets the SLO the least
    violating endpoint is kept (the admitter decides whether to shed).
    """

    def __init__(self, tier_ms: float = 50.0) -> None:
        self.tier_ms = tier_ms

    def filter(self, req: LLMRequest, pods: list[Endpoint]) -> list[Endpoint]:
        if req.ttft_slo_ms is None and req.tpot_slo_ms is None:
            return pods
        headrooms: dict[str, float] = {}
        for pod in pods:
            ttft, tpot = _predicted(req, pod)
            h = float("inf")
            if req.ttft_slo_ms is not None:
                h = min(h, req.ttft_slo_ms - ttft)
            if req.tpot_slo_ms is not None:
                h = min(h, req.tpot_slo_ms - tpot)
            headrooms[pod.address] = h
        meeting = [p for p in pods if headrooms[p.address] >= 0]
        if not meeting:
            best = max(pods, key=lambda p: headrooms[p.address], default=None)
            return [best] if best else []
        top = max(headrooms[p.address] for p in meeting)
        return [p for p in meeting if headrooms[p.address] >= top - self.tier_ms]


def maybe_attach_predicted_latency(
    router, predict_url: str | None = None, train_url: str | None = None
) -> PredictedLatencyProducer | None:
    """attach_predicted_latency iff the scheduler config uses the feature."""
    from llmd_tpu.epp.config import find_plugins

    used = find_plugins(router.scheduler, LatencyScorer) + find_plugins(
        router.scheduler, SloHeadroomTierFilter
    )
    if not used:
        if predict_url or train_url:
            log.warning(
                "--predictor-url/--trainer-url given but the scheduler "
                "config has no latency-scorer or slo-headroom-tier-filter "
                "plugin; predicted-latency routing is NOT active"
            )
        return None
    return attach_predicted_latency(router, predict_url, train_url)


def attach_predicted_latency(
    router,
    predict_url: str | None = None,
    train_url: str | None = None,
    slack: float = 1.5,
) -> PredictedLatencyProducer:
    """Wire the predicted-latency plane onto a built Router.

    Adds the PredictedLatencyProducer to the producer phase, its training
    feedback to the completion observers, and a LatencySloAdmitter in front
    of flow control. Returns the producer (its .client owns the predictor).

    When the scheduler also runs a precise-prefix scorer, its KV-event
    index is handed to the producer so the admitter's latency estimate
    is tri-state-aware (store-aware admission, kv-federation.md).
    """
    from llmd_tpu.epp.config import find_plugins
    from llmd_tpu.epp.precise_prefix import PrecisePrefixCacheScorer

    precise = find_plugins(router.scheduler, PrecisePrefixCacheScorer)
    client = PredictorClient(predict_url=predict_url, train_url=train_url)
    producer = PredictedLatencyProducer(
        client, prefix_index=precise[0].index if precise else None
    )
    router.producers.append(producer)
    router.completion_observers.append(producer.on_complete)
    router.admitters.append(LatencySloAdmitter(router.store, slack=slack))
    router.closables.append(client)
    return producer


class LatencySloAdmitter(Admitter):
    """Shed sheddable requests whose SLO no endpoint is predicted to meet.

    Reads predicted-latency producer output, so it must run post-dispatch
    (needs_producers=True).

    Priority >= ``protected_priority`` is never shed (the reference admits
    critical traffic regardless and lets flow-control arbitrate).
    """

    needs_producers = True

    def __init__(
        self,
        store,
        slack: float = 1.5,
        protected_priority: int = 1,
    ) -> None:
        self.store = store
        self.slack = slack
        self.protected_priority = protected_priority

    def admit(self, req: LLMRequest) -> str | None:
        if req.priority >= self.protected_priority:
            return None
        if req.ttft_slo_ms is None and req.tpot_slo_ms is None:
            return None
        pods = [p for p in self.store.list() if p.healthy]
        if not pods:
            return None  # let the scheduler produce the 503
        for pod in pods:
            ttft, tpot = _predicted(req, pod)
            ok = True
            if req.ttft_slo_ms is not None and ttft > req.ttft_slo_ms * self.slack:
                ok = False
            if req.tpot_slo_ms is not None and tpot > req.tpot_slo_ms * self.slack:
                ok = False
            if ok:
                return None
        return "slo-unattainable"
