"""Data Layer: endpoint discovery + per-endpoint attribute collection.

Reference architecture (docs/architecture/core/router/epp/datalayer.md:49-91):
Source → Extract → Attribute. Sources here:
  - StaticSource / FileDiscoverySource (the no-Kubernetes `file-discovery`
    plugin, guides/no-kubernetes-deployment/README.md:1-50) — watches an
    endpoints file and reconciles the pool;
  - MetricsCollector — polls each endpoint's /metrics on an interval
    (hot loop #4 in SURVEY.md §3.1) and runs the core-metrics-extractor
    name mapping (model-servers.md:38-52) into standard attributes.
"""

from __future__ import annotations

import asyncio
import json
import logging
import re
import pathlib
from typing import Callable

import aiohttp

from llmd_tpu import clock, faults
from llmd_tpu.epp.types import (
    BLOCK_SIZE,
    KV_CACHE_USAGE,
    NUM_BLOCKS,
    PREFIX_HIT_RATIO,
    RUNNING_REQUESTS,
    WAITING_QUEUE_SIZE,
    Endpoint,
)
from llmd_tpu.serve.metrics import parse_prometheus

log = logging.getLogger(__name__)

# Per-engine metric-name mapping (reference model-servers.md:38-52 requires a
# mapping table per engine family, selected by the llm-d.ai/engine-type
# label). Each entry: standard attr -> candidate metric names, first found wins.
METRIC_MAPPINGS: dict[str, dict[str, list[str]]] = {
    "vllm": {
        WAITING_QUEUE_SIZE: ["vllm:num_requests_waiting"],
        RUNNING_REQUESTS: ["vllm:num_requests_running"],
        KV_CACHE_USAGE: ["vllm:gpu_cache_usage_perc", "vllm:kv_cache_usage_perc"],
        PREFIX_HIT_RATIO: ["vllm:prefix_cache_hit_rate"],
    },
    "llmd": {
        WAITING_QUEUE_SIZE: ["llmd:num_requests_waiting"],
        RUNNING_REQUESTS: ["llmd:num_requests_running"],
        KV_CACHE_USAGE: ["llmd:gpu_cache_usage_perc"],
        PREFIX_HIT_RATIO: ["llmd:prefix_cache_hit_rate"],
    },
    "sglang": {
        WAITING_QUEUE_SIZE: ["sglang:num_queue_reqs"],
        RUNNING_REQUESTS: ["sglang:num_running_reqs"],
        KV_CACHE_USAGE: ["sglang:token_usage"],
    },
}


def extract_attrs(text: str, engine_type: str = "vllm") -> dict[str, float]:
    """core-metrics-extractor: raw Prometheus page -> standard attrs."""
    parsed = parse_prometheus(text)
    mapping = METRIC_MAPPINGS.get(engine_type, METRIC_MAPPINGS["vllm"])
    out: dict[str, float] = {}
    for attr, names in mapping.items():
        for n in names:
            if n in parsed:
                out[attr] = parsed[n]
                break
    # lora_requests_info labels carry adapter state (reference
    # model-servers.md:78-89); feeds the lora-affinity scorer.
    if "vllm:lora_requests_info" in parsed:
        for line in text.splitlines():
            if line.startswith("vllm:lora_requests_info{"):
                m = re.search(r'running_lora_adapters="([^"]*)"', line)
                if m:
                    out["LoadedAdapters"] = [
                        a.strip() for a in m.group(1).split(",") if a.strip()
                    ]
                m = re.search(r'waiting_lora_adapters="([^"]*)"', line)
                if m:
                    out["WaitingAdapters"] = [
                        a.strip() for a in m.group(1).split(",") if a.strip()
                    ]
                m = re.search(r'available_lora_adapters="([^"]*)"', line)
                if m:
                    out["AvailableAdapters"] = [
                        a.strip() for a in m.group(1).split(",") if a.strip()
                    ]
                # Paged-pool residency (multi-tenant-lora.md): the HBM
                # working set the tri-state lora-affinity scorer routes
                # on; static engines emit their full slot map here.
                m = re.search(r'resident_lora_adapters="([^"]*)"', line)
                if m:
                    out["ResidentAdapters"] = [
                        a.strip() for a in m.group(1).split(",") if a.strip()
                    ]
                break
    # cache_config_info labels carry block geometry; parse_prometheus drops
    # labels, so read them directly if present.
    for fam in ("vllm", "llmd"):
        key = f"{fam}:cache_config_info"
        if key in parsed:
            for line in text.splitlines():
                if line.startswith(key + "{"):
                    m = re.search(r'block_size="(\d+)"', line)
                    if m:
                        out[BLOCK_SIZE] = float(m.group(1))
                    m = re.search(r'num_gpu_blocks="(\d+)"', line)
                    if m:
                        out[NUM_BLOCKS] = float(m.group(1))
                    break
            break
    return out


class EndpointStore:
    """The EPP's pool view: address -> Endpoint. Single event loop, no locks."""

    def __init__(self) -> None:
        self._pods: dict[str, Endpoint] = {}
        self._on_remove: list[Callable[[str], None]] = []
        self._on_add: list[Callable[[Endpoint], None]] = []

    def on_remove(self, cb: Callable[[str], None]) -> None:
        self._on_remove.append(cb)

    def on_add(self, cb: Callable[[Endpoint], None]) -> None:
        self._on_add.append(cb)

    def upsert(self, ep: Endpoint) -> Endpoint:
        existing = self._pods.get(ep.address)
        if existing is None:
            self._pods[ep.address] = ep
            for cb in self._on_add:
                cb(ep)
            return ep
        existing.labels = ep.labels or existing.labels
        existing.model = ep.model or existing.model
        existing.last_seen = clock.monotonic()
        return existing

    def remove(self, address: str) -> None:
        if self._pods.pop(address, None) is not None:
            for cb in self._on_remove:
                cb(address)

    def get(self, address: str) -> Endpoint | None:
        return self._pods.get(address)

    def list(self) -> list[Endpoint]:
        return list(self._pods.values())

    def reconcile(self, endpoints: list[Endpoint]) -> None:
        want = {e.address for e in endpoints}
        for addr in list(self._pods):
            if addr not in want:
                self.remove(addr)
        for e in endpoints:
            self.upsert(e)


def parse_endpoints_config(data: dict) -> list[Endpoint]:
    """Endpoints file schema: {"endpoints": [{"address": "...", "labels": {...},
    "model": "..."}, ...]} (the file-discovery no-K8s analogue)."""
    out = []
    for item in data.get("endpoints", []):
        if isinstance(item, str):
            out.append(Endpoint(address=item))
        else:
            out.append(
                Endpoint(
                    address=item["address"],
                    labels=dict(item.get("labels", {})),
                    model=item.get("model"),
                )
            )
    return out


class FileDiscoverySource:
    """Watch a JSON endpoints file; reconcile the store on mtime change."""

    def __init__(self, store: EndpointStore, path: str, poll_s: float = 2.0) -> None:
        self.store = store
        self.path = pathlib.Path(path)
        self.poll_s = poll_s
        self._mtime = 0.0
        self._task: asyncio.Task | None = None

    def load_once(self) -> None:
        data = json.loads(self.path.read_text())
        self.store.reconcile(parse_endpoints_config(data))
        self._mtime = self.path.stat().st_mtime

    async def run(self) -> None:
        while True:
            try:
                mtime = self.path.stat().st_mtime
                if mtime != self._mtime:
                    self.load_once()
                    log.info("endpoints file reloaded: %d pods", len(self.store.list()))
            except FileNotFoundError:
                pass
            # llmd: allow(broad-except) -- discovery loop guard: the pool keeps its last-good view until the next poll
            except Exception:
                log.exception("endpoints file reload failed")
            await asyncio.sleep(self.poll_s)

    def start(self) -> None:
        self._task = asyncio.get_event_loop().create_task(self.run())

    def stop(self) -> None:
        if self._task:
            self._task.cancel()


class MetricsCollector:
    """Polls every endpoint's /metrics; updates attrs + health.

    An endpoint that fails ``unhealthy_after`` consecutive scrapes is marked
    unhealthy (filtered out by healthy-filter) but kept in the pool — the
    discovery source decides membership, the collector decides health.
    """

    def __init__(
        self,
        store: EndpointStore,
        interval_s: float = 1.0,
        timeout_s: float = 2.0,
        unhealthy_after: int = 3,
        engine_type_default: str = "vllm",
    ) -> None:
        self.store = store
        self.interval_s = interval_s
        self.timeout_s = timeout_s
        self.unhealthy_after = unhealthy_after
        self.engine_type_default = engine_type_default
        self._fail_counts: dict[str, int] = {}
        self._task: asyncio.Task | None = None
        self._session: aiohttp.ClientSession | None = None

    async def scrape_once(self) -> None:
        pods = self.store.list()
        await asyncio.gather(*(self._scrape(p) for p in pods), return_exceptions=True)

    async def _fetch(self, pod: Endpoint) -> str:
        """The HTTP leg of one scrape, isolated so a virtual transport
        (the fleet simulator's in-memory replicas) can substitute it
        while the health-window accounting in _scrape stays the real
        production code under test."""
        if self._session is None:
            self._session = aiohttp.ClientSession(
                timeout=aiohttp.ClientTimeout(total=self.timeout_s)
            )
        async with self._session.get(pod.url + "/metrics") as resp:
            text = await resp.text()
            if resp.status != 200:
                raise RuntimeError(f"scrape {resp.status}")
            return text

    async def _scrape(self, pod: Endpoint) -> None:
        try:
            # Injection site: a failing scrape feeds the consecutive-
            # failure counter exactly like an unreachable endpoint.
            if faults.fires("epp.scrape.fail", pod.address):
                raise RuntimeError("injected epp.scrape.fail")
            text = await self._fetch(pod)
        except Exception:
            n = self._fail_counts.get(pod.address, 0) + 1
            self._fail_counts[pod.address] = n
            if n >= self.unhealthy_after:
                pod.healthy = False
            return
        self._fail_counts[pod.address] = 0
        pod.healthy = True
        engine_type = pod.labels.get("llm-d.ai/engine-type", self.engine_type_default)
        pod.attrs.update(extract_attrs(text, engine_type))
        pod.last_seen = clock.monotonic()

    async def run(self) -> None:
        while True:
            try:
                await self.scrape_once()
            # llmd: allow(broad-except) -- scrape loop guard: per-endpoint failures feed _fail_counts in _scrape; this only catches cycle-level bugs
            except Exception:
                log.exception("metrics scrape cycle failed")
            await asyncio.sleep(self.interval_s)

    def start(self) -> None:
        self._task = asyncio.get_event_loop().create_task(self.run())

    async def stop(self) -> None:
        if self._task:
            self._task.cancel()
        if self._session:
            await self._session.close()
            self._session = None
