"""Approximate prefix-cache index (EPP-side, no engine events needed).

The reference's approximate prefix cache plugin
(docs/architecture/advanced/kv-management/prefix-cache-aware-routing.md:18-29):
prompts are chunked into fixed-size blocks hashed with a rolling chain; the
EPP remembers which endpoint each block hash was last routed to (updated on
its OWN routing decisions, not engine events) in an LRU, and scores
endpoints by longest consecutive matched prefix. Works unmodified for chat
payloads because the serialized prompt text is hashed, not token ids.
"""

from __future__ import annotations

import collections
import hashlib


def text_block_hashes(text: str, block_chars: int) -> list[bytes]:
    """Chained hashes of fixed-char blocks of the prompt text."""
    out: list[bytes] = []
    parent = b"llmd-prefix-root"
    for start in range(0, len(text) - block_chars + 1, block_chars):
        h = hashlib.blake2b(digest_size=16)
        h.update(parent)
        h.update(text[start : start + block_chars].encode("utf-8", "replace"))
        parent = h.digest()
        out.append(parent)
    return out


def prompt_block_hashes(req, index: "ApproxPrefixIndex") -> list[bytes]:
    """Per-request memoized prompt block hashes, keyed by the FULL hash
    geometry (block size AND prefix cap — hashes() truncates to the cap,
    so two plugins only share when both match). Same-geometry scorer +
    filter hash the prompt once."""
    key = f"prefix_hashes:{index.block_chars}:{index.max_prefix_blocks}"
    hashes = req.scratch.get(key)
    if hashes is None:
        hashes = index.hashes(req.prompt_text)
        req.scratch[key] = hashes
    return hashes


class ApproxPrefixIndex:
    """LRU of block hash → {endpoint addresses that likely hold it}."""

    def __init__(
        self,
        block_chars: int = 256,
        max_entries: int = 500_000,
        max_prefix_blocks: int = 1024,
    ) -> None:
        self.block_chars = block_chars
        self.max_entries = max_entries
        self.max_prefix_blocks = max_prefix_blocks
        self._lru: collections.OrderedDict[bytes, set[str]] = collections.OrderedDict()

    def __len__(self) -> int:
        return len(self._lru)

    def hashes(self, text: str) -> list[bytes]:
        return text_block_hashes(text, self.block_chars)[: self.max_prefix_blocks]

    def record_routed(self, hashes: list[bytes], address: str) -> None:
        """Remember that this prompt's blocks now live on ``address``."""
        for h in hashes:
            entry = self._lru.get(h)
            if entry is None:
                entry = set()
                self._lru[h] = entry
            entry.add(address)
            self._lru.move_to_end(h)
        while len(self._lru) > self.max_entries:
            self._lru.popitem(last=False)

    def match_lengths(self, hashes: list[bytes]) -> dict[str, int]:
        """Longest consecutive matched block count per endpoint address."""
        out: dict[str, int] = {}
        live: set[str] | None = None
        for i, h in enumerate(hashes):
            holders = self._lru.get(h)
            if not holders:
                break
            self._lru.move_to_end(h)
            live = set(holders) if live is None else live & holders
            if not live:
                break
            for addr in live:
                out[addr] = i + 1
        return out

    def evict_endpoint(self, address: str) -> None:
        """Forget an endpoint (it left the pool or cleared its cache)."""
        dead = []
        for h, holders in self._lru.items():
            holders.discard(address)
            if not holders:
                dead.append(h)
        for h in dead:
            del self._lru[h]
