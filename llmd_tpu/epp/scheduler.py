"""Request Scheduler: ProfileHandler → per-profile Filter/Score/Pick.

Reference: docs/architecture/core/router/epp/scheduling.md:44-118. The
ProfileHandler decides WHICH profiles run (single vs disagg prefill+decode);
each SchedulingProfile runs its chain; ProcessResults assembles the
SchedulingResult (primary destination + optional prefill endpoint for the
P/D sidecar header).
"""

from __future__ import annotations

import logging

from llmd_tpu.epp.plugins import SchedulingProfile
from llmd_tpu.epp.types import (
    Endpoint,
    LLMRequest,
    ProfileResult,
    SchedulingResult,
)

log = logging.getLogger(__name__)


class NoEndpointsError(RuntimeError):
    """No endpoint survived filtering — maps to 503 at the HTTP edge."""


class ProfileHandler:
    """Picks profiles to run and assembles the result."""

    def profiles_for(
        self, req: LLMRequest, profiles: dict[str, SchedulingProfile]
    ) -> list[str]:
        raise NotImplementedError

    def assemble(
        self, req: LLMRequest, results: dict[str, ProfileResult]
    ) -> SchedulingResult:
        raise NotImplementedError


class SingleProfileHandler(ProfileHandler):
    """Default: run the sole profile; its pick is the destination
    (scheduling.md:110-112)."""

    def __init__(self, profile_name: str = "default") -> None:
        self.profile_name = profile_name

    def profiles_for(self, req, profiles):
        return [self.profile_name]

    def assemble(self, req, results):
        r = results[self.profile_name]
        if r.endpoint is None:
            raise NoEndpointsError("no endpoint available")
        return SchedulingResult(primary=r.endpoint, profiles=results)


class DisaggProfileHandler(ProfileHandler):
    """P/D disaggregation (scheduling.md:113-118 + disaggregation/README.md).

    Runs the decode profile first, then the decider asks "is a separate
    prefill worth it?" — long uncached prefills go to a prefill pod; short
    or well-cached ones decode-only. The decode pick is always the primary
    destination; the prefill pick rides the x-prefiller-host-port header.
    """

    def __init__(
        self,
        decode_profile: str = "decode",
        prefill_profile: str = "prefill",
        threshold_tokens: int = 256,
    ) -> None:
        self.decode_profile = decode_profile
        self.prefill_profile = prefill_profile
        self.threshold_tokens = threshold_tokens

    def _wants_prefill(self, req: LLMRequest, decode: ProfileResult) -> bool:
        # Decider: how much of the prompt is NOT already cached on the decode
        # pod? (disaggregation/README.md:57-99). The decode profile's prefix
        # match fraction lives in scratch (set by the prefix scorer).
        uncached = req.approx_prompt_tokens
        if decode.endpoint is not None:
            frac = req.scratch.get("prefix_match_frac", {}).get(
                decode.endpoint.address, 0.0
            )
            uncached = int(uncached * (1.0 - frac))
        return uncached >= self.threshold_tokens

    def profiles_for(self, req, profiles):
        return [self.decode_profile, self.prefill_profile]

    def assemble(self, req, results):
        decode = results[self.decode_profile]
        if decode.endpoint is None:
            raise NoEndpointsError("no decode endpoint available")
        prefill = results.get(self.prefill_profile)
        prefill_ep: Endpoint | None = None
        if (
            prefill is not None
            and prefill.endpoint is not None
            and prefill.endpoint.address != decode.endpoint.address
            and self._wants_prefill(req, decode)
        ):
            prefill_ep = prefill.endpoint
        return SchedulingResult(
            primary=decode.endpoint, prefill=prefill_ep, profiles=results
        )


class EpdProfileHandler(DisaggProfileHandler):
    """E/P/D multimodal disaggregation (multimodal-serving/README.md:33-50
    + e-p-d-disaggregation.values.yaml).

    Adds an encode profile ahead of the P/D pair. The decider is the
    reference's `always-disagg-multimodal-decider`: any request carrying
    media always gets a dedicated encode worker (when one exists); the
    pick rides the x-encoder-host-port header so the sidecar can ship
    images to the E tier and forward embedding handles downstream.
    Text-only requests degrade to plain P/D behavior.
    """

    def __init__(
        self,
        encode_profile: str = "encode",
        decode_profile: str = "decode",
        prefill_profile: str = "prefill",
        threshold_tokens: int = 256,
    ) -> None:
        super().__init__(decode_profile, prefill_profile, threshold_tokens)
        self.encode_profile = encode_profile

    def profiles_for(self, req, profiles):
        names = list(super().profiles_for(req, profiles))
        if req.mm_items:  # always-disagg-multimodal-decider
            names.insert(0, self.encode_profile)
        return names

    def assemble(self, req, results):
        result = super().assemble(req, results)
        enc = results.get(self.encode_profile)
        if req.mm_items and enc is not None and enc.endpoint is not None:
            result.encode = enc.endpoint
        return result


class Scheduler:
    """Runs the configured profiles over the current pod set."""

    def __init__(
        self,
        profiles: dict[str, SchedulingProfile],
        handler: ProfileHandler | None = None,
    ) -> None:
        self.profiles = profiles
        self.handler = handler or SingleProfileHandler(next(iter(profiles)))

    def schedule(self, req: LLMRequest, pods: list[Endpoint]) -> SchedulingResult:
        if not pods:
            raise NoEndpointsError("endpoint pool is empty")
        results: dict[str, ProfileResult] = {}
        for name in self.handler.profiles_for(req, self.profiles):
            profile = self.profiles.get(name)
            if profile is None:
                continue
            results[name] = profile.run(req, list(pods))
            # Later profiles see earlier picks (DisaggProfileHandler runs
            # decode first): the topology-affinity scorer anchors the
            # prefill pick to the decode pod's slice/host so the P->D KV
            # transfer rides ICI, not DCN.
            req.scratch.setdefault("profile_picks", {})[name] = results[
                name
            ].endpoint
        result = self.handler.assemble(req, results)
        # notify state-updating scorers on the winning profile(s)
        for name, pr in results.items():
            if pr.endpoint is not None and (
                pr.endpoint is result.primary
                or pr.endpoint is result.prefill
                or pr.endpoint is result.encode
            ):
                self.profiles[name].notify_routed(req, pr.endpoint)
        return result

    def notify_complete(self, req: LLMRequest, pod: Endpoint) -> None:
        for profile in self.profiles.values():
            profile.notify_complete(req, pod)

    def notify_endpoint_removed(self, address: str) -> None:
        seen: set[int] = set()
        for profile in self.profiles.values():
            for plugin in (
                *(s for s, _ in profile.scorers), *profile.filters,
            ):
                if id(plugin) not in seen:
                    seen.add(id(plugin))
                    plugin.on_endpoint_removed(address)
