"""EndpointPickerConfig: declarative router assembly.

Mirrors the reference's `EndpointPickerConfig` YAML
(docs/api-reference/endpointpickerconfig.md:11-75): `plugins` declare
type/name/parameters, `schedulingProfiles` reference plugins with weights,
`flowControl` declares bands + policies. Read once at startup. JSON/dict
here (YAML loads to the same shape).

Example:
    {
      "plugins": [
        {"type": "queue-scorer", "name": "q"},
        {"type": "prefix-cache-scorer", "name": "prefix",
         "parameters": {"block_chars": 256}},
        {"type": "max-score-picker", "name": "picker"}
      ],
      "schedulingProfiles": [
        {"name": "default",
         "plugins": [{"pluginRef": "q", "weight": 2},
                     {"pluginRef": "prefix", "weight": 3},
                     {"pluginRef": "picker"}]}
      ],
      "profileHandler": {"type": "single", "profile": "default"},
      "flowControl": {"enabled": true, "fairness": "round-robin",
                      "ordering": "fcfs", "maxInflight": 256,
                      "bands": [{"priority": 0, "maxRequests": 1024,
                                 "ttlSeconds": 60}]}
    }
"""

from __future__ import annotations

from typing import Any

# Importing these modules registers their plugins.
import llmd_tpu.epp.filters  # noqa: F401
import llmd_tpu.epp.precise_prefix  # noqa: F401
import llmd_tpu.epp.predicted_latency  # noqa: F401
import llmd_tpu.epp.scorers  # noqa: F401
from llmd_tpu.epp.flow_control import BandConfig, FlowControl, SaturationDetector
from llmd_tpu.epp.plugins import (
    Filter,
    Picker,
    SchedulingProfile,
    Scorer,
    create_plugin,
)
from llmd_tpu.epp.scheduler import (
    DisaggProfileHandler,
    EpdProfileHandler,
    ProfileHandler,
    Scheduler,
    SingleProfileHandler,
)

DEFAULT_CONFIG: dict[str, Any] = {
    # The optimized-baseline plugin set (reference
    # guides/optimized-baseline/router/optimized-baseline.values.yaml:14-32).
    "plugins": [
        {"type": "healthy-filter", "name": "healthy"},
        # Batch tier watermark admission: batch-band requests
        # (x-llmd-priority: batch) are admitted only on replicas with
        # real headroom; interactive requests pass through untouched
        # (docs/architecture/batch-processing.md).
        {"type": "batch-saturation-filter", "name": "batch-gate"},
        {"type": "queue-scorer", "name": "queue"},
        {"type": "kv-cache-utilization-scorer", "name": "kv"},
        {"type": "prefix-cache-scorer", "name": "prefix"},
        {"type": "no-hit-lru-scorer", "name": "no-hit-lru"},
        {"type": "max-score-picker", "name": "picker"},
    ],
    "schedulingProfiles": [
        {
            "name": "default",
            "plugins": [
                {"pluginRef": "healthy"},
                {"pluginRef": "batch-gate"},
                {"pluginRef": "queue", "weight": 1.0},
                {"pluginRef": "kv", "weight": 1.0},
                {"pluginRef": "prefix", "weight": 3.0},
                {"pluginRef": "no-hit-lru", "weight": 0.5},
                {"pluginRef": "picker"},
            ],
        }
    ],
    "profileHandler": {"type": "single", "profile": "default"},
    "flowControl": {"enabled": True, "maxInflight": 512},
}

# The P/D plugin config (reference
# guides/pd-disaggregation/router/pd-disaggregation.values.yaml:11-42).
PD_CONFIG: dict[str, Any] = {
    "plugins": [
        {"type": "healthy-filter", "name": "healthy"},
        {"type": "decode-filter", "name": "decode"},
        {"type": "prefill-filter", "name": "prefill"},
        {"type": "queue-scorer", "name": "queue"},
        {"type": "kv-cache-utilization-scorer", "name": "kv"},
        {"type": "prefix-cache-scorer", "name": "prefix"},
        {"type": "topology-affinity-scorer", "name": "topology"},
        {"type": "max-score-picker", "name": "picker"},
    ],
    "schedulingProfiles": [
        {
            "name": "decode",
            "plugins": [
                {"pluginRef": "healthy"},
                {"pluginRef": "decode"},
                {"pluginRef": "queue", "weight": 1.0},
                {"pluginRef": "kv", "weight": 1.0},
                {"pluginRef": "prefix", "weight": 3.0},
                {"pluginRef": "picker"},
            ],
        },
        {
            "name": "prefill",
            "plugins": [
                {"pluginRef": "healthy"},
                {"pluginRef": "prefill"},
                {"pluginRef": "queue", "weight": 2.0},
                {"pluginRef": "kv", "weight": 1.0},
                # Same-slice/host P->D pairing: KV rides ICI, not DCN.
                {"pluginRef": "topology", "weight": 2.0},
                {"pluginRef": "picker"},
            ],
        },
    ],
    "profileHandler": {
        "type": "disagg",
        "decodeProfile": "decode",
        "prefillProfile": "prefill",
        "thresholdTokens": 256,
    },
    "flowControl": {"enabled": True, "maxInflight": 512},
}


# Precise prefix-cache routing plugin config (reference
# guides/precise-prefix-cache-routing/router/*.values.yaml): the approximate
# prefix scorer is replaced by the KV-event-indexed one; requires the
# token-producer and a KVEventsSource wired to the pool (see
# llmd_tpu.epp.precise_prefix.attach_precise_routing).
PRECISE_CONFIG: dict[str, Any] = {
    "plugins": [
        {"type": "healthy-filter", "name": "healthy"},
        {"type": "queue-scorer", "name": "queue"},
        {"type": "kv-cache-utilization-scorer", "name": "kv"},
        {"type": "precise-prefix-cache-scorer", "name": "precise-prefix"},
        {"type": "max-score-picker", "name": "picker"},
    ],
    "schedulingProfiles": [
        {
            "name": "default",
            "plugins": [
                {"pluginRef": "healthy"},
                {"pluginRef": "queue", "weight": 1.0},
                {"pluginRef": "kv", "weight": 1.0},
                {"pluginRef": "precise-prefix", "weight": 3.0},
                {"pluginRef": "picker"},
            ],
        }
    ],
    "profileHandler": {"type": "single", "profile": "default"},
    "flowControl": {"enabled": True, "maxInflight": 512},
}


# E/P/D multimodal encode disaggregation (reference
# guides/multimodal-serving/e-disaggregation/router/
# e-p-d-disaggregation.values.yaml:13-60): an encode profile picks a
# dedicated vision-encode worker by queue depth; prefill/decode profiles
# as in P/D. Requests without media degrade to plain P/D.
EPD_CONFIG: dict[str, Any] = {
    "plugins": [
        {"type": "healthy-filter", "name": "healthy"},
        {"type": "encode-filter", "name": "encode-f"},
        {"type": "decode-filter", "name": "decode-f"},
        {"type": "prefill-filter", "name": "prefill-f"},
        {"type": "queue-scorer", "name": "queue"},
        {"type": "kv-cache-utilization-scorer", "name": "kv"},
        {"type": "prefix-cache-scorer", "name": "prefix"},
        {"type": "no-hit-lru-scorer", "name": "no-hit-lru"},
        {"type": "max-score-picker", "name": "picker"},
    ],
    "schedulingProfiles": [
        {
            "name": "encode",
            "plugins": [
                {"pluginRef": "healthy"},
                {"pluginRef": "encode-f"},
                {"pluginRef": "queue", "weight": 2.0},
                {"pluginRef": "picker"},
            ],
        },
        {
            "name": "decode",
            "plugins": [
                {"pluginRef": "healthy"},
                {"pluginRef": "decode-f"},
                {"pluginRef": "prefix", "weight": 3.0},
                {"pluginRef": "queue", "weight": 2.0},
                {"pluginRef": "kv", "weight": 2.0},
                {"pluginRef": "no-hit-lru", "weight": 0.5},
                {"pluginRef": "picker"},
            ],
        },
        {
            "name": "prefill",
            "plugins": [
                {"pluginRef": "healthy"},
                {"pluginRef": "prefill-f"},
                {"pluginRef": "prefix", "weight": 3.0},
                {"pluginRef": "queue", "weight": 2.0},
                {"pluginRef": "kv", "weight": 2.0},
                {"pluginRef": "picker"},
            ],
        },
    ],
    "profileHandler": {
        "type": "epd",
        "encodeProfile": "encode",
        "decodeProfile": "decode",
        "prefillProfile": "prefill",
        "thresholdTokens": 256,
    },
    "flowControl": {"enabled": True, "maxInflight": 512},
}


# Predicted-latency routing plugin config (reference
# guides/predicted-latency-routing/router/predicted-latency.values.yaml):
# the latency scorer dominates, with the SLO headroom filter ahead of it;
# wire a PredictedLatencyProducer + LatencySloAdmitter on the Router
# (see llmd_tpu.epp.predicted_latency.attach_predicted_latency).
PREDICTED_LATENCY_CONFIG: dict[str, Any] = {
    "plugins": [
        {"type": "healthy-filter", "name": "healthy"},
        {"type": "slo-headroom-tier-filter", "name": "slo-tier"},
        {"type": "latency-scorer", "name": "latency"},
        {"type": "queue-scorer", "name": "queue"},
        # maxPrefixTokensToMatch 262144 in the reference values; our index
        # works in 256-char blocks -> 4096 blocks covers 262144 tokens.
        {"type": "prefix-cache-scorer", "name": "prefix",
         "parameters": {"max_prefix_blocks": 4096}},
        {"type": "max-score-picker", "name": "picker"},
    ],
    "schedulingProfiles": [
        {
            "name": "default",
            "plugins": [
                {"pluginRef": "healthy"},
                {"pluginRef": "slo-tier"},
                {"pluginRef": "latency", "weight": 3.0},
                {"pluginRef": "queue", "weight": 1.0},
                {"pluginRef": "prefix", "weight": 2.0},
                {"pluginRef": "picker"},
            ],
        }
    ],
    "profileHandler": {"type": "single", "profile": "default"},
    "flowControl": {"enabled": True, "maxInflight": 512},
}


def find_plugins(scheduler: Scheduler, cls: type) -> list[Any]:
    """All plugin instances of a type across profiles (deduplicated)."""
    seen: dict[int, Any] = {}
    for profile in scheduler.profiles.values():
        for f in profile.filters:
            if isinstance(f, cls):
                seen[id(f)] = f
        for s, _ in profile.scorers:
            if isinstance(s, cls):
                seen[id(s)] = s
        if isinstance(profile.picker, cls):
            seen[id(profile.picker)] = profile.picker
    return list(seen.values())


def build_scheduler(config: dict[str, Any]) -> Scheduler:
    instances: dict[str, Any] = {}
    for spec in config.get("plugins", []):
        name = spec.get("name") or spec["type"]
        instances[name] = create_plugin(spec["type"], **spec.get("parameters", {}))

    profiles: dict[str, SchedulingProfile] = {}
    for pspec in config.get("schedulingProfiles", []):
        filters: list[Filter] = []
        scorers: list[tuple[Scorer, float]] = []
        picker: Picker | None = None
        for ref in pspec.get("plugins", []):
            plugin = instances[ref["pluginRef"]]
            if isinstance(plugin, Filter):
                filters.append(plugin)
            elif isinstance(plugin, Scorer):
                scorers.append((plugin, float(ref.get("weight", 1.0))))
            elif isinstance(plugin, Picker):
                picker = plugin
            else:
                raise TypeError(f"plugin {ref['pluginRef']} has unknown role")
        profiles[pspec["name"]] = SchedulingProfile(
            pspec["name"], filters, scorers, picker
        )

    hspec = config.get("profileHandler", {"type": "single"})
    handler: ProfileHandler
    if hspec.get("type") == "epd":
        handler = EpdProfileHandler(
            encode_profile=hspec.get("encodeProfile", "encode"),
            decode_profile=hspec.get("decodeProfile", "decode"),
            prefill_profile=hspec.get("prefillProfile", "prefill"),
            threshold_tokens=int(hspec.get("thresholdTokens", 256)),
        )
    elif hspec.get("type") == "disagg":
        handler = DisaggProfileHandler(
            decode_profile=hspec.get("decodeProfile", "decode"),
            prefill_profile=hspec.get("prefillProfile", "prefill"),
            threshold_tokens=int(hspec.get("thresholdTokens", 256)),
        )
    else:
        handler = SingleProfileHandler(
            hspec.get("profile") or next(iter(profiles), "default")
        )
    return Scheduler(profiles, handler)


def build_flow_control(config: dict[str, Any]) -> FlowControl:
    fc = config.get("flowControl", {})
    bands = [
        BandConfig(
            priority=int(b.get("priority", 0)),
            max_requests=int(b.get("maxRequests", 1024)),
            max_bytes=int(b.get("maxBytes", 1 << 30)),
            ttl_s=float(b.get("ttlSeconds", 60.0)),
        )
        for b in fc.get("bands", [])
    ] or None
    saturation = SaturationDetector(
        max_inflight=fc.get("maxInflight"),
        max_kv_usage=fc.get("maxKvUsage"),
        max_queue_depth=fc.get("maxQueueDepth"),
    )
    return FlowControl(
        bands=bands,
        fairness=fc.get("fairness", "round-robin"),
        ordering=fc.get("ordering", "fcfs"),
        saturation=saturation,
        max_total_requests=int(fc.get("maxTotalRequests", 4096)),
        enabled=bool(fc.get("enabled", True)),
    )
